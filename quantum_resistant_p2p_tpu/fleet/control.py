"""Fleet control-plane protocol: router <-> gateway, client -> router.

Reuses net/p2p_node.py's wire format (magic ``QP`` | version | flags |
u32 length | JSON payload) so fleet frames and peer frames share one
parser discipline, but control messages are small and NEVER chunked —
a chunk flag on a control frame is a protocol error.

Message types (all prefixed ``__gw_``/``__route`` so they can never
collide with application message types):

* ``__gw_hello__``     gateway -> router: registration (gateway id, the
                       P2P listen port peers dial, pid, and — when live
                       telemetry is armed — the gateway's own HTTP
                       telemetry port, so the router's ``/fleet`` view
                       and ``tools/qrtop.py`` can find every scrape).
* ``__gw_heartbeat__`` gateway -> router: liveness + the cross-process
                       SLO aggregation feed (cumulative probe totals,
                       device/fallback trip counters, admission stats,
                       the device-cost ledger totals the router sums
                       fleet-wide, and the telemetry port again).
* ``__gw_probe__``     router -> gateway: the HALF-OPEN canary.  A
                       gateway that missed heartbeats is a breaker-open
                       shard at fleet scope; one probe round-trip is the
                       evidence that lets it take ring ownership back.
* ``__gw_probe_ok__``  gateway -> router: probe reply (echoes ``n``).
* ``__gw_stek__``      router -> gateway: the fleet's session-ticket-
                       encryption keys (current + previous — the dual-key
                       rotation window), pushed on registration and on
                       every rotation.  ONE ring per fleet is what lets a
                       ticket minted by gw1 resume on gw2 after a handoff,
                       and on the respawned gw1 after a rolling restart.
                       The control link is the fleet's trusted channel
                       (localhost/pod-internal by construction — see
                       docs/fleet.md); key material never rides any
                       peer-facing or observability surface.
* ``__gw_drain__``     router -> gateway: GRACEFUL drain (also wired to
                       SIGTERM in the gateway): stop admitting (/readyz
                       goes 503 draining), flush outboxes, nudge peers to
                       resume on their ring successor (``ke_rehome``),
                       write the slo report, send ``__gw_bye__``, exit 0.
                       The planned half of a rolling restart — vs
                       ``__gw_stop__``, the fast teardown.
* ``__gw_stop__``      router -> gateway: drain and exit; the gateway
                       writes its per-node ``slo_report.json`` first.
* ``__gw_bye__``       gateway -> router: final stats before exit.
* ``__rt_lease__``     router -> router: leader-lease claim/renewal
                       (holder id, monotonic lease epoch, RELATIVE ttl —
                       each replica arms the deadline on its OWN clock,
                       so bounded skew shifts the window but never
                       inverts it).  Epochs only move forward; a frame
                       below the receiver's epoch is fenced as stale.
* ``__rt_sync__``      leader router -> follower routers: full authority
                       state replication on every change — the STEK ring
                       export (current + previous key, same dual-key
                       window the gateways hold), membership roster, and
                       the lease epoch that authorizes the frame.  This
                       is what lets ANY follower assume the lease without
                       losing the ticket accept window.  Router links are
                       the same trusted channel as the gateway control
                       link (localhost/pod-internal by construction).
* ``__rt_reject__``    router -> router: stale-lease fence.  Reply to an
                       authority frame whose epoch is below the
                       receiver's: carries the receiver's epoch so the
                       stale sender has PROOF a newer lease exists and
                       demotes loudly instead of split-braining.
* ``__route__``        client -> router: "which gateway serves peer X"
                       (``exclude`` lists gateways the client just
                       watched die — the router may already know).
* ``__route_ok__``     router -> client: gateway id + dial address.
* ``__busy__``         router -> client: fleet admission budget
                       exhausted — the SAME typed shed frame a gateway's
                       connection budget uses (net/p2p_node.py), so
                       clients treat both scopes with one retry policy.
* ``__no_route__``     router -> client: no non-quarantined gateway.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any

from ..net.p2p_node import _HEADER, _MAGIC, _VERSION, MAX_FRAME

GW_HELLO = "__gw_hello__"
GW_HEARTBEAT = "__gw_heartbeat__"
GW_PROBE = "__gw_probe__"
GW_PROBE_OK = "__gw_probe_ok__"
GW_TICKET_KEYS = "__gw_stek__"
GW_DRAIN = "__gw_drain__"
GW_STOP = "__gw_stop__"
GW_BYE = "__gw_bye__"
RT_LEASE = "__rt_lease__"
RT_SYNC = "__rt_sync__"
RT_REJECT = "__rt_reject__"
ROUTE = "__route__"
ROUTE_OK = "__route_ok__"
ROUTE_DONE = "__route_done__"
BUSY = "__busy__"
NO_ROUTE = "__no_route__"


async def send_ctrl(writer: asyncio.StreamWriter, message: dict) -> None:
    """Frame and send one control message (single frame, no chunking)."""
    body = json.dumps(message, separators=(",", ":")).encode()
    writer.write(_HEADER.pack(_MAGIC, _VERSION, 0, len(body)) + body)
    await writer.drain()


async def read_ctrl(reader: asyncio.StreamReader) -> dict:
    """Read one control frame; raises on malformed/chunked/oversized."""
    header = await reader.readexactly(_HEADER.size)
    magic, version, flags, length = _HEADER.unpack(header)
    if magic != _MAGIC or version != _VERSION or flags:
        raise ValueError(f"bad control frame header {header!r}")
    if length > MAX_FRAME:
        raise ValueError(f"oversized control frame ({length} bytes)")
    return json.loads(await reader.readexactly(length))


async def route_query(router_host: str, router_port: int, peer_id: str,
                      exclude: list[str] | None = None,
                      timeout: float = 5.0) -> dict[str, Any]:
    """One client-side route query: open, ask, read, close.

    Returns the reply dict (``type`` one of ROUTE_OK / BUSY / NO_ROUTE).
    Transport failures surface as exceptions — the storm harness's
    bounded retry loop owns the policy."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(router_host, router_port), timeout)
    try:
        await send_ctrl(writer, {"type": ROUTE, "peer_id": peer_id,
                                 "exclude": list(exclude or ())})
        return await asyncio.wait_for(read_ctrl(reader), timeout)
    finally:
        writer.close()


async def route_done(router_host: str, router_port: int, gateway: str,
                     timeout: float = 5.0) -> None:
    """Fire-and-forget session-end signal: releases the admission slot
    the matching route query claimed (best-effort — a lost done frame
    over-counts inflight only until the gateway's next heartbeat, whose
    reported connection count the router reconciles against)."""
    try:
        _reader, writer = await asyncio.wait_for(
            asyncio.open_connection(router_host, router_port), timeout)
    except (OSError, asyncio.TimeoutError):
        return
    try:
        await send_ctrl(writer, {"type": ROUTE_DONE, "gateway": gateway})
    except (ConnectionError, OSError):
        pass
    finally:
        writer.close()
