"""Multi-process fleet chaos storm: ``--storm --fleet N``.

Drives ``sessions`` live peers through a :class:`fleet.manager.GatewayFleet`
over real TCP — every session asks the router which gateway owns it
(consistent-hash assignment, typed ``__busy__`` shed at the fleet
admission budget), dials that gateway's OWN process, runs the full
authenticated handshake, and delivers its bulk messages.  Gateway death
is the measured case, not an abort: a session whose gateway dies —
mid-handshake or mid-session — re-routes to the ring successor, re-keys,
and resumes delivery from where it stopped (undelivered messages are
preserved client-side and re-sent under the NEW session key; nothing is
ever sent in plaintext because the engine refuses to send without a
shared key).  The acceptance currency (ISSUE 11 /
``bench_results/fleet_storm_r0N.json``):

* ``lost_established_sessions == 0`` — no session that completed a
  handshake failed to finish its workload;
* a BOUNDED handshake-failure burst (``handshake_failures`` counts
  failed attempts; the kill makes some inevitable, the ring handoff
  makes them finite);
* fleet ``device_served_fraction >= 0.9`` summed across every gateway
  process and the client plane.

Chaos rides the seeded fault plan's new ``process`` scope
(faults/plan.py): the fleet health loop polls
``process_control(gateway)`` per gateway per tick in sorted order, so
the ``injected`` log is byte-reproducible from the seed.
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import tempfile
import time
from collections import deque
from pathlib import Path
from typing import Any

from ..faults import FaultPlan
from ..native import wipe
from ..obs import slo as obs_slo
from . import control
from .manager import GatewayFleet
from .stormlib import (StormAEAD, prewarm_facades, register_storm_providers,
                       storm_env)

logger = logging.getLogger(__name__)


def default_kill_rules(gateway: str = "gw1", tick: int = 8) -> list:
    """The canonical mid-storm chaos: SIGKILL one gateway on its Nth
    health tick (~``tick * hb_interval`` seconds in)."""
    from ..faults import FaultRule

    return [FaultRule("process", "kill_gateway",
                      match={"gateway": gateway}, nth=tick)]


async def run_fleet_storm(
    sessions: int = 1000,
    gateways: int = 3,
    providers: str = "stdlib",
    seed: int = 0,
    arrival_rate: float = 0.0,
    concurrency: int = 256,
    msgs_per_session: int = 2,
    spawn: str = "process",
    per_gateway_max_peers: int = 0,
    handshake_budget: int = 0,
    max_batch: int = 4096,
    max_wait_ms: float = 3.0,
    autotune: bool = True,
    hb_interval: float = 0.25,
    ke_timeout: float = 120.0,
    session_attempts: int = 4,
    prewarm_cap: int = 256,
    fault_rules=None,
    report_dir: str | Path | None = None,
    telemetry: bool = False,
    scrape_cb=None,
    roll: bool = False,
    roll_delay_s: float = 3.0,
    drain_timeout: float = 30.0,
    msg_interval_s: float = 0.0,
) -> dict[str, Any]:
    """One seeded fleet storm; returns the JSON-ready report.

    ``telemetry=True`` arms the live HTTP endpoints (obs/http.py): the
    router serves ``/fleet`` and every gateway its own ephemeral scrape
    surface.  ``scrape_cb(endpoints)`` — e.g.
    ``tools.qrtop.snapshot_endpoints`` — is called WHILE the gateways
    are still alive (just before drain) with ``{gateway_id: "host:port"}``
    and its return value lands in the report as ``cost_snapshot`` (the
    committed ``fleet_storm_cost_snapshot.json`` artifact).

    ``roll=True`` runs a mid-storm ROLLING RESTART of every gateway
    (``GatewayFleet.rolling_restart`` — each drained, SIGTERM-style, then
    respawned) ``roll_delay_s`` after the first session launches.  A
    displaced session carries its resumption ticket to wherever the ring
    re-routes it, so post-restart reconnects are cheap 1-RTT resumes —
    the report splits them (``post_roll_resumed`` vs ``post_roll_full``)
    and the ``--roll`` ratchet gates on a >=90% resume rate (the
    committed ``fleet_roll_r0N.json`` artifact)."""
    register_storm_providers()
    from ..app.messaging import SecureMessaging
    from ..net.p2p_node import P2PNode
    from ..provider import get_kem, get_signature

    if providers == "stdlib":
        kem_name, sig_name = "STORM-KEM", "STORM-SIG"
    else:
        kem_name, sig_name = "ML-KEM-768", "ML-DSA-65"
    aead = StormAEAD()
    rng = random.Random(seed)
    tmp_reports = report_dir is None
    if tmp_reports:
        report_dir = Path(tempfile.mkdtemp(prefix="qrp2p_fleet_"))
    report_dir = Path(report_dir)

    fleet = GatewayFleet(
        gateways, spawn=spawn, providers=providers, seed=seed,
        hb_interval=hb_interval,
        per_gateway_max_peers=per_gateway_max_peers,
        handshake_budget=handshake_budget,
        report_dir=report_dir,
        telemetry_port=0 if telemetry else None,
        gateway_kw={
            "max_batch": max_batch, "max_wait_ms": max_wait_ms,
            "autotune": autotune, "ke_timeout": ke_timeout,
            # the ring does not split load perfectly: a gateway's share of
            # the concurrent window can exceed concurrency/N, so warm each
            # gateway up to the FULL concurrency (capped) — a cold bucket
            # silently degrades its whole share to the cpu fallback
            "prewarm_cap": min(prewarm_cap, max(1, concurrency)),
        },
    )

    clients: list[Any] = []
    established_sessions = 0
    completed = 0
    failures = 0
    lost_established = 0
    handoffs = 0
    handshake_failures = 0
    route_busy = 0
    msgs_delivered = 0
    first_lat: list[float] = []
    # resumption accounting: reconnects of ALREADY-established sessions,
    # split by whether the ticket resumed (vs a full re-handshake) and by
    # whether they happened after the rolling restart began — the >=90%
    # post-restart resume rate is the roll ratchet's acceptance currency
    resumed_reconnects = 0
    full_reconnects = 0
    post_roll_resumed = 0
    post_roll_full = 0
    reconnects_no_ticket = [0]
    roll_state: dict[str, Any] = {"t0": None, "report": None}

    proto = None
    with storm_env(ke_timeout, fd_need=4 * sessions + 128):
        # Everything below unwinds through the finally: the gateway
        # subprocesses are spawned start_new_session=True, so a raising
        # session task that skipped fleet.stop() would ORPHAN them (holding
        # their ports) and leak every client socket — the fleet-scope twin
        # of the storm_env restore guarantee.
        try:
            await fleet.start()
            # shared client-side batching plane (the storm-bench proto
            # pattern): every client coalesces into one set of queues
            proto = SecureMessaging(
                P2PNode(node_id="proto", host="127.0.0.1", port=0),
                kem=get_kem(kem_name, "tpu"), symmetric=aead,
                signature=get_signature(sig_name, "tpu"),
                use_batching=True, max_batch=max_batch,
                max_wait_ms=max_wait_ms, autotune=autotune,
            )
            await proto.wait_ready()
            if prewarm_cap and proto._bkem is not None:
                # the client plane sees the FULL concurrency (every initiator
                # coalesces into these queues): warm its reachable buckets
                await prewarm_facades(
                    (proto._bkem, proto._bsig, proto._bfused),
                    min(max_batch, max(concurrency, 1), prewarm_cap))
            kp_pks, kp_sks = proto.signature.generate_keypair_batch(sessions)
            sem = asyncio.Semaphore(concurrency)

            def make_client(i: int):
                node = P2PNode(node_id=f"peer{i:05d}", host="127.0.0.1", port=0)
                sm = SecureMessaging(
                    node, kem=proto.kem, symmetric=proto.symmetric,
                    signature=proto.signature,
                    sig_keypair=(bytes(kp_pks[i]), bytes(kp_sks[i])),
                    # fleet handoff replaces single-peer healing: a dead
                    # gateway must be LEFT dead and its arc re-routed, not
                    # redialed at its last known (now vacant) address
                    auto_heal=False,
                )
                sm._bkem, sm._bsig, sm._bfused = (proto._bkem, proto._bsig,
                                                  proto._bfused)
                sm.use_batching = True
                clients.append(sm)
                return sm

            async def route(peer_id: str, exclude: list[str]):
                """Bounded route-query retry: BUSY backs off (the typed fleet
                shed), transport errors retry, NO_ROUTE gives up."""
                nonlocal route_busy
                delay = 0.1
                for _ in range(6):
                    try:
                        reply = await control.route_query(
                            fleet.host, fleet.ctrl_port, peer_id, exclude)
                    except (OSError, asyncio.TimeoutError, ValueError):
                        await asyncio.sleep(delay)
                        delay *= 2
                        continue
                    rtype = reply.get("type")
                    if rtype == control.ROUTE_OK:
                        return reply
                    if rtype == control.BUSY:
                        route_busy += 1
                        if str(reply.get("scope") or "fleet") == "fleet":
                            # fleet-wide saturation: re-asking sooner cannot
                            # help, every member counts against one budget
                            await asyncio.sleep(delay)
                            delay *= 2
                        # a narrower shed scope re-asks immediately — the
                        # router can still route around a busy member
                        continue
                    if rtype == control.NO_ROUTE:
                        # NO_ROUTE is TRANSIENT during a rolling restart
                        # (one gateway draining + one freshly dead can
                        # empty the pool for a beat): back off and re-ask —
                        # only a fleet that stays unroutable through the
                        # retry budget gives up
                        await asyncio.sleep(delay)
                        delay *= 2
                        continue
                    # unknown reply verb (version skew): treat as transient
                    await asyncio.sleep(delay)
                    delay *= 2
                return None

            async def one_session(i: int, start_at: float, t_origin: float,
                                  srng: random.Random) -> None:
                nonlocal established_sessions, completed, failures
                nonlocal lost_established, handoffs, handshake_failures
                nonlocal msgs_delivered, resumed_reconnects, full_reconnects
                nonlocal post_roll_resumed, post_roll_full
                delay = start_at - (time.perf_counter() - t_origin)
                if delay > 0:
                    await asyncio.sleep(delay)
                async with sem:
                    peer_id = f"peer{i:05d}"
                    sm = make_client(i)
                    # bounded exclude (the most recent death only): under a
                    # ROLLING restart every gateway fails once — excluding
                    # more than the latest failure can transiently exclude
                    # every survivor and manufacture NO_ROUTE for itself
                    exclude: deque = deque(maxlen=1)
                    was_established = False
                    #: which gateway the held ticket is keyed under — NOT
                    #: simply the previous route target: an intermediate
                    #: failed handshake must not orphan the ticket minted
                    #: by the gateway before it
                    ticket_gid: str | None = None
                    delivered = 0
                    for attempt in range(session_attempts):
                        if attempt:
                            # seeded, bounded reroute jitter: N clients of
                            # one dead/drained gateway must not hammer the
                            # ring successor in the same tick
                            await asyncio.sleep(srng.uniform(0.0, 0.25))
                        reply = await route(peer_id, list(exclude))
                        if reply is None:
                            break
                        gid = reply["gateway"]
                        if await sm.node.connect_to_peer(
                                reply["host"], reply["port"], retries=2) != gid:
                            # dead/unreachable gateway the router has not
                            # noticed yet: exclude it and walk the ring
                            exclude.append(gid)
                            await control.route_done(fleet.host, fleet.ctrl_port,
                                                     gid)
                            continue
                        if ticket_gid is not None and ticket_gid != gid:
                            # fleet handoff: the ticket the dead/drained
                            # gateway minted resumes on the successor (one
                            # STEK ring per fleet)
                            sm.adopt_ticket(gid, sm.take_ticket(ticket_gid))
                            ticket_gid = gid
                        had_ticket = sm.ticket_for(gid) is not None
                        t0 = time.perf_counter()
                        r0 = sm._ctr_resumes_used.value
                        ok = await sm.initiate_key_exchange(gid)
                        resumed = sm._ctr_resumes_used.value > r0
                        if not ok:
                            handshake_failures += 1
                            await control.route_done(fleet.host, fleet.ctrl_port,
                                                     gid)
                            if not sm.node.is_connected(gid):
                                # the gateway died mid-handshake: the typed
                                # retry machinery already backed off; hand the
                                # arc to the ring successor
                                exclude.append(gid)
                            continue
                        if was_established:
                            # a reconnect of a live session: the resume-vs-
                            # full split is the roll ratchet's currency
                            after_roll = (roll_state["t0"] is not None
                                          and t0 >= roll_state["t0"])
                            if resumed:
                                resumed_reconnects += 1
                                post_roll_resumed += 1 if after_roll else 0
                            else:
                                full_reconnects += 1
                                post_roll_full += 1 if after_roll else 0
                                if not had_ticket:
                                    # diagnostic split: a full reconnect
                                    # WITH a ticket means a reject/timeout
                                    # (investigate); without one it is the
                                    # mint-delivery race at establishment
                                    reconnects_no_ticket[0] += 1
                        else:
                            first_lat.append(time.perf_counter() - t0)
                            established_sessions += 1
                            was_established = True
                        # the just-established gateway minted (or will
                        # refresh) this session's ticket
                        ticket_gid = gid
                        while delivered < msgs_per_session:
                            sent = await sm.send_message(
                                gid, b"fleet storm %d/%d" % (i, delivered))
                            if sent is None:
                                break
                            delivered += 1
                            msgs_delivered += 1
                            if msg_interval_s:
                                # paced traffic: sessions LIVE long enough
                                # to be displaced by a mid-storm restart —
                                # back-to-back sends finish in microseconds
                                # and prove nothing about displacement
                                await asyncio.sleep(msg_interval_s)
                        if delivered >= msgs_per_session:
                            completed += 1
                            await control.route_done(fleet.host, fleet.ctrl_port,
                                                     gid)
                            return
                        # mid-session death: preserve the undelivered tail and
                        # hand off to the ring successor (re-key, resume)
                        handoffs += 1
                        exclude.append(gid)
                        await control.route_done(fleet.host, fleet.ctrl_port, gid)
                    failures += 1
                    if was_established:
                        lost_established += 1

            offsets = []
            t = 0.0
            for _ in range(sessions):
                if arrival_rate > 0:
                    t += rng.uniform(0.0, 2.0 / arrival_rate)
                offsets.append(t)

            session_rngs = [random.Random(rng.getrandbits(64))
                            for _ in range(sessions)]
            plan = FaultPlan(seed, list(fault_rules)) if fault_rules else None
            ctx = plan.activate() if plan is not None else None
            if ctx is not None:
                ctx.__enter__()
            t_origin = time.perf_counter()
            roll_task = None
            if roll:
                async def _roll() -> None:
                    # mid-storm rolling restart: drain -> respawn -> re-
                    # register every gateway in turn while the sessions run
                    await asyncio.sleep(roll_delay_s)
                    roll_state["t0"] = time.perf_counter()
                    roll_state["report"] = await fleet.rolling_restart(
                        drain_timeout=drain_timeout)

                roll_task = asyncio.create_task(_roll())
            try:
                await asyncio.gather(*(
                    one_session(i, offsets[i], t_origin, session_rngs[i])
                    for i in range(sessions)))
                if roll_task is not None:
                    await roll_task
            finally:
                if roll_task is not None:
                    roll_task.cancel()
                if ctx is not None:
                    ctx.__exit__(None, None, None)
            elapsed = time.perf_counter() - t_origin

            fleet_slo = fleet.slo_status()
            fleet_stats = fleet.stats()
            proto_metrics = proto.metrics()
            # fleet-wide device-cost economics (obs/cost.py): the heartbeat
            # totals the router summed, plus the driver-side client plane
            fleet_cost = fleet.fleet_cost_totals()
            fleet_cost["client_plane"] = proto.cost.totals()
            telemetry_info = None
            cost_snapshot = None
            if telemetry:
                telemetry_info = {
                    "router_port": (fleet.telemetry.port
                                    if fleet.telemetry is not None else None),
                    "gateways": {m.gateway_id: m.telemetry_port
                                 for m in fleet._members_sorted()},
                }
                if scrape_cb is not None:
                    # scrape the LIVE per-gateway endpoints before drain —
                    # this is the qrtop --snapshot path run in-harness, so
                    # the committed artifact comes from the same code a
                    # human's dashboard uses.  A killed gateway's endpoint
                    # is gone; the scraper reports it unreachable.
                    endpoints = {
                        m.gateway_id: f"{fleet.host}:{m.telemetry_port}"
                        for m in fleet._members_sorted()
                        if m.telemetry_port
                    }
                    try:
                        cost_snapshot = await asyncio.get_running_loop(
                        ).run_in_executor(None, scrape_cb, endpoints)
                    except Exception:
                        logger.exception("telemetry scrape failed")
        finally:
            await fleet.stop()
            for sm in clients:
                try:
                    await sm.node.stop()
                except (ConnectionError, OSError, RuntimeError):
                    logger.exception("client node stop failed")
            if proto is not None:
                await proto.node.stop()

    # fleet-wide device-served: every gateway process's queue totals (the
    # final __gw_bye__ stats; heartbeat stats as fallback for a killed
    # gateway) plus the driver-side client plane
    total_ops = fb_ops = 0
    per_gateway: dict[str, Any] = {}
    for m in fleet._members_sorted():
        stats = m.final_stats or m.stats
        per_gateway[m.gateway_id] = stats
        total_ops += int(stats.get("ops") or 0)
        fb_ops += int(stats.get("fallback_ops") or 0)
    for fam in ("kem_queue", "sig_queue", "fused_queue"):
        for q in proto_metrics.get(fam, {}).values():
            total_ops += q["ops"]
            fb_ops += q["fallback_ops"]
    reports = fleet.collect_reports()
    merged = obs_slo.merge_reports(reports) if reports else None
    if tmp_reports:
        # scratch report dir (smoke / parity runs): reports are merged
        # above, so don't leak one /tmp/qrp2p_fleet_* per invocation
        import shutil

        shutil.rmtree(report_dir, ignore_errors=True)

    f_sorted = sorted(first_lat)

    def pct(p: float):
        if not f_sorted:
            return None
        return round(f_sorted[min(len(f_sorted) - 1,
                                  int(len(f_sorted) * p / 100.0))], 4)

    out: dict[str, Any] = {
        "workload": "fleet_storm",
        "sessions": sessions,
        "gateways": gateways,
        "spawn": spawn,
        "providers": ("stdlib-toy (serving-loop workload)"
                      if providers == "stdlib"
                      else f"{kem_name}+{sig_name}"),
        "seed": seed,
        "arrival_rate": arrival_rate,
        "concurrency": concurrency,
        "msgs_per_session": msgs_per_session,
        "elapsed_s": round(elapsed, 3),
        "established_sessions": established_sessions,
        "completed_sessions": completed,
        "failures": failures,
        "lost_established_sessions": lost_established,
        "handoffs": handoffs,
        "handshake_failures": handshake_failures,
        "route_busy": route_busy,
        "msgs_delivered": msgs_delivered,
        # reconnects of established sessions, split resume-vs-full (and by
        # whether they fell after the rolling restart began): the ticket
        # machinery's acceptance currency (docs/protocol.md "Session
        # resumption"; the --roll ratchet gates on the post-roll rate)
        "resumed_reconnects": resumed_reconnects,
        "full_handshake_reconnects": full_reconnects,
        "ticket_resume_rate": (
            round(resumed_reconnects / (resumed_reconnects + full_reconnects),
                  4) if (resumed_reconnects + full_reconnects) else None),
        "post_roll_resumed": post_roll_resumed,
        "post_roll_full": post_roll_full,
        "post_roll_resume_rate": (
            round(post_roll_resumed / (post_roll_resumed + post_roll_full), 4)
            if (post_roll_resumed + post_roll_full) else None),
        "full_reconnects_without_ticket": reconnects_no_ticket[0],
        "client_resumes_used": sum(
            sm._ctr_resumes_used.value for sm in clients),
        "client_resume_fallbacks": sum(
            sm._ctr_resume_fallbacks.value for sm in clients),
        "roll": roll_state["report"],
        # the engine refuses to send without a shared key (fail-closed,
        # tests/test_faults.py pins it) and this harness only sends
        # through send_message — plaintext on the wire is structurally
        # impossible; the field records the claim the chaos gate makes
        "plaintext_sends": 0,
        "handshakes_per_s": (round(established_sessions / elapsed, 2)
                             if elapsed else None),
        "p50_handshake_s": pct(50),
        "p99_handshake_s": pct(99),
        "device_served_fraction": (
            round((total_ops - fb_ops) / total_ops, 4) if total_ops else None),
        "fleet": fleet_stats,
        "per_gateway": per_gateway,
        "fleet_slo": fleet_slo,
        "fleet_slo_merged": merged,
        "fleet_cost": fleet_cost,
    }
    if telemetry_info is not None:
        out["telemetry"] = telemetry_info
    if cost_snapshot is not None:
        out["cost_snapshot"] = cost_snapshot
    wipe(kp_sks)  # every session adopted its own copy at construction
    if plan is not None:
        out["chaos"] = {
            "seed": plan.seed,
            "injected": len(plan.injected),
            "injected_log": plan.injected,
        }
    return out


def default_router_kill_rules(router: str = "rt0", tick: int = 8) -> list:
    """The canonical control-plane chaos: SIGKILL one ROUTER replica —
    by convention rt0, rank 0, the deterministic initial leaseholder —
    on its Nth chaos tick (~``tick * hb_interval`` seconds in)."""
    from ..faults import FaultRule

    return [FaultRule("process", "kill_router",
                      match={"router": router}, nth=tick)]


async def run_router_storm(
    sessions: int = 1000,
    gateways: int = 3,
    routers: int = 2,
    providers: str = "stdlib",
    seed: int = 0,
    arrival_rate: float = 0.0,
    concurrency: int = 256,
    msgs_per_session: int = 4,
    spawn: str = "process",
    per_gateway_max_peers: int = 0,
    handshake_budget: int = 0,
    max_batch: int = 4096,
    max_wait_ms: float = 3.0,
    autotune: bool = True,
    hb_interval: float = 0.25,
    ke_timeout: float = 120.0,
    session_attempts: int = 6,
    prewarm_cap: int = 256,
    fault_rules=None,
    report_dir: str | Path | None = None,
    roll: bool = True,
    roll_delay_s: float = 3.0,
    lease_ttl_s: float = 1.0,
    lease_stagger_s: float = 0.2,
    msg_interval_s: float = 0.0,
) -> dict[str, Any]:
    """The ROUTER-roll storm: same live data plane as
    :func:`run_fleet_storm`, but the control plane is N replicated
    routers (fleet/router.py) and the chaos targets THEM — a seeded
    mid-storm SIGKILL of the leader replica plus (``roll=True``) a
    rolling restart of every router.  The acceptance currency
    (``bench_results/router_roll_r0N.json``):

    * ``lost_established_sessions == 0`` — router death must be invisible
      to established sessions (the gateways keep serving; only routing
      and STEK authority move);
    * ``plaintext_sends == 0`` — structural, as in every storm;
    * ``post_failover_resume_rate >= 0.9`` — reconnects AFTER the leader
      died still redeem tickets minted under the dead leader's STEK
      (replicated dual-key window, docs/fleet.md "HA control plane").

    Every session deliberately drops its gateway connection mid-workload
    and reconnects: gateways survive this storm, so without the forced
    drop there would be nothing for the ticket machinery to prove.
    Clients walk the ROUTER ring (successors of their peer id) for route
    queries, failing over to the next replica on transport errors with
    the usual typed-busy/backoff + seeded-jitter discipline.
    """
    register_storm_providers()
    from ..app.messaging import SecureMessaging
    from ..net.p2p_node import P2PNode
    from ..provider import get_kem, get_signature
    from .router import RouterFleet

    if providers == "stdlib":
        kem_name, sig_name = "STORM-KEM", "STORM-SIG"
    else:
        kem_name, sig_name = "ML-KEM-768", "ML-DSA-65"
    aead = StormAEAD()
    rng = random.Random(seed)
    tmp_reports = report_dir is None
    if tmp_reports:
        report_dir = Path(tempfile.mkdtemp(prefix="qrp2p_rroll_"))
    report_dir = Path(report_dir)

    rf = RouterFleet(
        routers, gateways, spawn=spawn, providers=providers, seed=seed,
        hb_interval=hb_interval,
        per_gateway_max_peers=per_gateway_max_peers,
        handshake_budget=handshake_budget,
        report_dir=report_dir,
        lease_ttl_s=lease_ttl_s, lease_stagger_s=lease_stagger_s,
        gateway_kw={
            "max_batch": max_batch, "max_wait_ms": max_wait_ms,
            "autotune": autotune, "ke_timeout": ke_timeout,
            "prewarm_cap": min(prewarm_cap, max(1, concurrency)),
        },
    )

    clients: list[Any] = []
    established_sessions = 0
    completed = 0
    failures = 0
    lost_established = 0
    handoffs = 0
    handshake_failures = 0
    route_busy = 0
    router_failovers = 0
    msgs_delivered = 0
    forced_drops = 0
    first_lat: list[float] = []
    resumed_reconnects = 0
    full_reconnects = 0
    post_failover_resumed = 0
    post_failover_full = 0
    #: perf_counter stamp of the FIRST control-plane event (chaos kill or
    #: roll start) — reconnects at/after it count as post-failover
    failover_state: dict[str, Any] = {"t0": None, "kill_t0": None,
                                      "roll_t0": None, "report": None}

    def _mark_failover(key: str) -> None:
        now = time.perf_counter()
        failover_state[key] = now
        if failover_state["t0"] is None or now < failover_state["t0"]:
            failover_state["t0"] = now

    proto = None
    leader0: str | None = None
    final_router_stats: dict[str, Any] | None = None
    with storm_env(ke_timeout, fd_need=4 * sessions + 256):
        try:
            await rf.start()
            leader0 = await rf.leader_id()
            proto = SecureMessaging(
                P2PNode(node_id="proto", host="127.0.0.1", port=0),
                kem=get_kem(kem_name, "tpu"), symmetric=aead,
                signature=get_signature(sig_name, "tpu"),
                use_batching=True, max_batch=max_batch,
                max_wait_ms=max_wait_ms, autotune=autotune,
            )
            await proto.wait_ready()
            if prewarm_cap and proto._bkem is not None:
                await prewarm_facades(
                    (proto._bkem, proto._bsig, proto._bfused),
                    min(max_batch, max(concurrency, 1), prewarm_cap))
            kp_pks, kp_sks = proto.signature.generate_keypair_batch(sessions)
            sem = asyncio.Semaphore(concurrency)

            def make_client(i: int):
                node = P2PNode(node_id=f"peer{i:05d}", host="127.0.0.1",
                               port=0)
                sm = SecureMessaging(
                    node, kem=proto.kem, symmetric=proto.symmetric,
                    signature=proto.signature,
                    sig_keypair=(bytes(kp_pks[i]), bytes(kp_sks[i])),
                    auto_heal=False,
                )
                sm._bkem, sm._bsig, sm._bfused = (proto._bkem, proto._bsig,
                                                  proto._bfused)
                sm.use_batching = True
                clients.append(sm)
                return sm

            async def route(peer_id: str, exclude: list[str],
                            srng: random.Random):
                """Walk the ROUTER ring (per-peer successor order) with
                bounded retries: a dead replica is skipped — the client-
                visible face of the failover — BUSY backs off (typed
                fleet shed), NO_ROUTE is transient during re-registration
                after a respawn."""
                nonlocal route_busy, router_failovers
                delay = 0.1
                for _ in range(8):
                    for rid in rf.router_ring.successors(peer_id):
                        m = rf.routers[rid]
                        try:
                            reply = await control.route_query(
                                m.host, m.ctrl_port, peer_id, exclude)
                        except (OSError, asyncio.TimeoutError, ValueError):
                            # replica down/respawning: the next ring
                            # successor answers instead
                            router_failovers += 1
                            continue
                        rtype = reply.get("type")
                        if rtype == control.ROUTE_OK:
                            return reply, rid
                        if rtype == control.BUSY:
                            route_busy += 1
                            break  # one budget fleet-wide: back off
                        # NO_ROUTE / unknown verb: transient, back off
                        break
                    await asyncio.sleep(delay * (0.5 + srng.random()))
                    delay = min(delay * 2, 2.0)
                return None, None

            async def done(gid: str, rid_hint: str | None) -> None:
                """Advisory inflight release: any live replica will do."""
                order = list(rf.routers)
                if rid_hint in rf.routers:
                    order.remove(rid_hint)
                    order.insert(0, rid_hint)
                for rid in order:
                    m = rf.routers[rid]
                    try:
                        await control.route_done(m.host, m.ctrl_port, gid)
                        return
                    except (OSError, asyncio.TimeoutError, ValueError):
                        continue

            drop_at = max(1, msgs_per_session // 2)

            async def one_session(i: int, start_at: float, t_origin: float,
                                  srng: random.Random) -> None:
                nonlocal established_sessions, completed, failures
                nonlocal lost_established, handoffs, handshake_failures
                nonlocal msgs_delivered, resumed_reconnects, full_reconnects
                nonlocal post_failover_resumed, post_failover_full
                nonlocal forced_drops
                delay = start_at - (time.perf_counter() - t_origin)
                if delay > 0:
                    await asyncio.sleep(delay)
                async with sem:
                    peer_id = f"peer{i:05d}"
                    sm = make_client(i)
                    exclude: deque = deque(maxlen=1)
                    was_established = False
                    ticket_gid: str | None = None
                    delivered = 0
                    dropped = False
                    for attempt in range(session_attempts):
                        if attempt:
                            await asyncio.sleep(srng.uniform(0.0, 0.25))
                        reply, rid = await route(peer_id, list(exclude), srng)
                        if reply is None:
                            break
                        gid = reply["gateway"]
                        if await sm.node.connect_to_peer(
                                reply["host"], reply["port"],
                                retries=2) != gid:
                            exclude.append(gid)
                            await done(gid, rid)
                            continue
                        if ticket_gid is not None and ticket_gid != gid:
                            sm.adopt_ticket(gid, sm.take_ticket(ticket_gid))
                            ticket_gid = gid
                        t0 = time.perf_counter()
                        r0 = sm._ctr_resumes_used.value
                        ok = await sm.initiate_key_exchange(gid)
                        resumed = sm._ctr_resumes_used.value > r0
                        if not ok:
                            handshake_failures += 1
                            await done(gid, rid)
                            if not sm.node.is_connected(gid):
                                exclude.append(gid)
                            continue
                        if was_established:
                            after = (failover_state["t0"] is not None
                                     and t0 >= failover_state["t0"])
                            if resumed:
                                resumed_reconnects += 1
                                post_failover_resumed += 1 if after else 0
                            else:
                                full_reconnects += 1
                                post_failover_full += 1 if after else 0
                        else:
                            first_lat.append(time.perf_counter() - t0)
                            established_sessions += 1
                            was_established = True
                        ticket_gid = gid
                        intentional = False
                        while delivered < msgs_per_session:
                            sent = await sm.send_message(
                                gid, b"router storm %d/%d" % (i, delivered))
                            if sent is None:
                                break
                            delivered += 1
                            msgs_delivered += 1
                            if msg_interval_s:
                                await asyncio.sleep(msg_interval_s)
                            if not dropped and delivered == drop_at:
                                # the deliberate mid-workload drop: the
                                # gateways SURVIVE this storm, so without
                                # it no reconnect would ever exercise the
                                # replicated ticket window
                                dropped = True
                                intentional = True
                                forced_drops += 1
                                await sm.node.disconnect_from_peer(gid)
                                break
                        if delivered >= msgs_per_session:
                            completed += 1
                            await done(gid, rid)
                            return
                        if not intentional:
                            # a REAL loss (not our forced drop): hand the
                            # arc to the ring successor as usual
                            handoffs += 1
                            exclude.append(gid)
                        await done(gid, rid)
                    failures += 1
                    if was_established:
                        lost_established += 1

            offsets = []
            t = 0.0
            for _ in range(sessions):
                if arrival_rate > 0:
                    t += rng.uniform(0.0, 2.0 / arrival_rate)
                offsets.append(t)

            session_rngs = [random.Random(rng.getrandbits(64))
                            for _ in range(sessions)]
            plan = FaultPlan(seed, list(fault_rules)) if fault_rules else None
            ctx = plan.activate() if plan is not None else None
            if ctx is not None:
                ctx.__enter__()
            t_origin = time.perf_counter()

            async def _watch_kills() -> None:
                # stamp the moment the chaos kill lands so reconnects can
                # be split pre/post failover (the plan fires inside the
                # RouterFleet's chaos loop, not here)
                while failover_state["kill_t0"] is None:
                    if rf.router_kills > 0:
                        _mark_failover("kill_t0")
                        return
                    await asyncio.sleep(0.05)

            watch_task = asyncio.create_task(_watch_kills())
            roll_task = None
            if roll:
                async def _roll() -> None:
                    await asyncio.sleep(roll_delay_s)
                    _mark_failover("roll_t0")
                    failover_state["report"] = await rf.rolling_restart()

                roll_task = asyncio.create_task(_roll())
            try:
                await asyncio.gather(*(
                    one_session(i, offsets[i], t_origin, session_rngs[i])
                    for i in range(sessions)))
                if roll_task is not None:
                    await roll_task
            finally:
                watch_task.cancel()
                if roll_task is not None:
                    roll_task.cancel()
                if ctx is not None:
                    ctx.__exit__(None, None, None)
            elapsed = time.perf_counter() - t_origin
            final_router_stats = await rf.stats()
            proto_metrics = proto.metrics()
            client_cost = proto.cost.totals()
        finally:
            await rf.stop()
            for sm in clients:
                try:
                    await sm.node.stop()
                except (ConnectionError, OSError, RuntimeError):
                    logger.exception("client node stop failed")
            if proto is not None:
                await proto.node.stop()

    # device-served: the client plane's queue totals (the gateway-side
    # split lands in the merged per-node SLO reports below — no single
    # router holds an authoritative final-stats view in this storm)
    total_ops = fb_ops = 0
    for fam in ("kem_queue", "sig_queue", "fused_queue"):
        for q in proto_metrics.get(fam, {}).values():
            total_ops += q["ops"]
            fb_ops += q["fallback_ops"]
    reports = []
    _loop = asyncio.get_running_loop()
    for path in sorted(report_dir.glob("*_slo_report.json")):
        try:
            text = await _loop.run_in_executor(None, path.read_text)
            reports.append(json.loads(text))
        except (OSError, ValueError):
            logger.warning("unreadable slo report %s", path)
    merged = obs_slo.merge_reports(reports) if reports else None
    if tmp_reports:
        import shutil

        shutil.rmtree(report_dir, ignore_errors=True)

    f_sorted = sorted(first_lat)

    def pct(p: float):
        if not f_sorted:
            return None
        return round(f_sorted[min(len(f_sorted) - 1,
                                  int(len(f_sorted) * p / 100.0))], 4)

    post_total = post_failover_resumed + post_failover_full
    out: dict[str, Any] = {
        "workload": "router_roll_storm",
        "sessions": sessions,
        "gateways": gateways,
        "routers": routers,
        "spawn": spawn,
        "providers": ("stdlib-toy (serving-loop workload)"
                      if providers == "stdlib"
                      else f"{kem_name}+{sig_name}"),
        "seed": seed,
        "arrival_rate": arrival_rate,
        "concurrency": concurrency,
        "msgs_per_session": msgs_per_session,
        "elapsed_s": round(elapsed, 3),
        "initial_leader": leader0,
        "established_sessions": established_sessions,
        "completed_sessions": completed,
        "failures": failures,
        "lost_established_sessions": lost_established,
        "handoffs": handoffs,
        "handshake_failures": handshake_failures,
        "route_busy": route_busy,
        "router_failovers": router_failovers,
        "forced_drops": forced_drops,
        "msgs_delivered": msgs_delivered,
        "resumed_reconnects": resumed_reconnects,
        "full_handshake_reconnects": full_reconnects,
        "ticket_resume_rate": (
            round(resumed_reconnects / (resumed_reconnects + full_reconnects),
                  4) if (resumed_reconnects + full_reconnects) else None),
        # reconnects at/after the first control-plane event (leader kill
        # or roll start): tickets redeemed here were minted under a STEK
        # authority that no longer exists — the HA gate's currency
        "post_failover_resumed": post_failover_resumed,
        "post_failover_full": post_failover_full,
        "post_failover_resume_rate": (
            round(post_failover_resumed / post_total, 4)
            if post_total else None),
        "client_resumes_used": sum(
            sm._ctr_resumes_used.value for sm in clients),
        "client_resume_fallbacks": sum(
            sm._ctr_resume_fallbacks.value for sm in clients),
        "router_kills": rf.router_kills,
        "router_pauses": rf.router_pauses,
        "roll": failover_state["report"],
        "plaintext_sends": 0,
        "handshakes_per_s": (round(established_sessions / elapsed, 2)
                             if elapsed else None),
        "p50_handshake_s": pct(50),
        "p99_handshake_s": pct(99),
        "device_served_fraction": (
            round((total_ops - fb_ops) / total_ops, 4) if total_ops else None),
        "router_fleet": final_router_stats,
        "fleet_slo_merged": merged,
        "client_cost": client_cost,
    }
    wipe(kp_sks)  # every session adopted its own copy at construction
    if plan is not None:
        out["chaos"] = {
            "seed": plan.seed,
            "injected": len(plan.injected),
            "injected_log": plan.injected,
        }
    return out


def write_fleet_artifacts(out: dict[str, Any], out_dir: str | Path) -> None:
    """Write the merged fleet SLO report next to the storm artifacts
    (CI uploads both)."""
    d = Path(out_dir)
    d.mkdir(parents=True, exist_ok=True)
    if out.get("fleet_slo_merged") is not None:
        (d / "fleet_slo_report.json").write_text(
            json.dumps({"merged": out["fleet_slo_merged"],
                        "live": out.get("fleet_slo")}, indent=2) + "\n")
