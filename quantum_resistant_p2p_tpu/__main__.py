"""Entry point: ``python -m quantum_resistant_p2p_tpu``.

Reference analog: quantum_resistant_p2p/__main__.py:59-114 (argparse +
logging setup + event loop + graceful shutdown), with the Qt app replaced by
the asyncio CLI (cli.py).
"""

import sys

from .cli import main

if __name__ == "__main__":
    sys.exit(main())
