"""Pure-Python HQC (round-4 shaped) — clean-room reference.

Hamming Quasi-Cyclic KEM: syndrome decoding on a concatenated code —
an outer Reed-Solomon code over GF(2^8) and an inner duplicated
Reed-Muller RM(1,7) code — with quasi-cyclic products in
GF(2)[x]/(x^n - 1) (big-int carryless arithmetic here).

COMPATIBILITY NOTE: the vendored liboqs binary is stripped from the
reference checkout (.MISSING_LARGE_BLOBS), so no native HQC oracle exists in
this environment.  Randomness follows the official round-4 structure — a
SHAKE256 seedexpander (``seed || 0x02`` domain byte, one continuing stream
per context), ``vect_set_random_fixed_weight`` with multiplicative range
reduction ``i + (rand32 * (n-i)) >> 32`` and index-replacement dedup, G/K as
SHAKE256-512 with trailing domain bytes, keygen drawing y then x from one sk
stream, encrypt drawing r2, e, r1 from one theta stream.  The byte-level
call order is RECONSTRUCTED from the official round-4 reference with
corroborating evidence — serialized sizes match liboqs's published
2249/2305/4433 (128), 4522/4586/8978 (192), 7245/7317/14421 (256) exactly,
and official decaps re-deriving ONLY y from sk_seed forces the y-first
order — but remains unverified against official .rsp files; interop
confidence is moderate, and ``tools/verify_vectors.py`` carries a
divergence-diagnosis decision tree naming exactly which assumption a
failing official file refutes (docs/correctness.md §HQC seam).
Both backends (this oracle and the batched JAX implementation in ``kem.hqc``)
are bit-exact against each other, which is the property the application
protocol needs (reference behavior: crypto/key_exchange.py:189-309).

Determinism seam: keygen takes (sk_seed, sigma, pk_seed); encaps takes
(m, salt).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

RM_N = 128  # inner RM(1,7) length


@dataclass(frozen=True)
class HQCParams:
    name: str
    n: int
    n1: int  # RS length (bytes)
    k: int  # message bytes
    delta: int  # RS correction capability
    dup: int  # RM duplication (n2 = 128 * dup)
    w: int
    wr: int

    @property
    def n2(self) -> int:
        return RM_N * self.dup

    @property
    def n_bytes(self) -> int:
        return (self.n + 7) // 8

    @property
    def n1n2_bytes(self) -> int:
        return self.n1 * self.n2 // 8

    @property
    def pk_len(self) -> int:
        return 40 + self.n_bytes

    @property
    def sk_len(self) -> int:
        return 40 + self.k + self.pk_len

    @property
    def ct_len(self) -> int:
        return self.n_bytes + self.n1n2_bytes + 16

    @property
    def ss_len(self) -> int:
        return 64


HQC128 = HQCParams("HQC-128", n=17669, n1=46, k=16, delta=15, dup=3, w=66, wr=75)
HQC192 = HQCParams("HQC-192", n=35851, n1=56, k=24, delta=16, dup=5, w=100, wr=114)
HQC256 = HQCParams("HQC-256", n=57637, n1=90, k=32, delta=29, dup=5, w=131, wr=149)

PARAMS = {p.name: p for p in (HQC128, HQC192, HQC256)}


# -- GF(2^8) arithmetic (poly 0x11D, generator alpha = 2) --------------------


def _build_gf():
    exp = [0] * 512
    log = [0] * 256
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= 0x11D
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    return exp, log


_GF_EXP, _GF_LOG = _build_gf()


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return _GF_EXP[_GF_LOG[a] + _GF_LOG[b]]


def gf_inv(a: int) -> int:
    return _GF_EXP[255 - _GF_LOG[a]]


# -- Reed-Solomon [n1, k] over GF(2^8), corrects delta errors ----------------


def _rs_gen_poly(p: HQCParams) -> list[int]:
    """g(x) = prod_{i=1..2delta} (x - alpha^i); low-degree-first coeffs."""
    g = [1]
    for i in range(1, 2 * p.delta + 1):
        root = _GF_EXP[i]
        ng = [0] * (len(g) + 1)
        for j, c in enumerate(g):
            ng[j] ^= gf_mul(c, root)
            ng[j + 1] ^= c
        g = ng
    return g


def rs_encode(p: HQCParams, msg: bytes) -> bytes:
    """Systematic RS encode: msg (k bytes) -> codeword (n1 bytes).

    Codeword = [parity || msg] with parity = x^(2delta) * m(x) mod g(x).
    """
    g = _rs_gen_poly(p)
    red = 2 * p.delta
    assert p.n1 - p.k == red
    rem = [0] * red
    # long division of m(x)*x^red by g(x); msg[k-1] is the highest-degree coeff
    for byte in reversed(msg):
        coef = byte ^ rem[-1]
        rem = [0] + rem[:-1]
        if coef:
            for j in range(red):
                rem[j] ^= gf_mul(g[j], coef)
    return bytes(rem) + msg


def rs_decode(p: HQCParams, cw: bytes) -> bytes:
    """Syndrome decode (Berlekamp-Massey + Chien + Forney) -> k message bytes."""
    red = 2 * p.delta
    c = list(cw)
    # syndromes S_i = c(alpha^i), i = 1..2delta, with coefficient j at x^j
    synd = []
    for i in range(1, red + 1):
        s = 0
        for j, cj in enumerate(c):
            if cj:
                s ^= _GF_EXP[(_GF_LOG[cj] + i * j) % 255]
        synd.append(s)
    if not any(synd):
        return cw[red:]
    # Berlekamp-Massey
    sigma = [1]
    b = [1]
    L = 0
    m = 1
    bb = 1
    for n_it in range(red):
        d = synd[n_it]
        for i in range(1, L + 1):
            if i < len(sigma) and sigma[i] and synd[n_it - i]:
                d ^= gf_mul(sigma[i], synd[n_it - i])
        if d == 0:
            m += 1
        elif 2 * L <= n_it:
            t = list(sigma)
            coef = gf_mul(d, gf_inv(bb))
            shifted = [0] * m + b
            sigma = [
                (sigma[i] if i < len(sigma) else 0)
                ^ (gf_mul(coef, shifted[i]) if i < len(shifted) else 0)
                for i in range(max(len(sigma), len(shifted)))
            ]
            L = n_it + 1 - L
            b = t
            bb = d
            m = 1
        else:
            coef = gf_mul(d, gf_inv(bb))
            shifted = [0] * m + b
            sigma = [
                (sigma[i] if i < len(sigma) else 0)
                ^ (gf_mul(coef, shifted[i]) if i < len(shifted) else 0)
                for i in range(max(len(sigma), len(shifted)))
            ]
            m += 1
    # Chien search: roots alpha^{-j} <-> error at position j
    err_pos = []
    for j in range(p.n1):
        val = 0
        for i, s in enumerate(sigma):
            if s:
                val ^= _GF_EXP[(_GF_LOG[s] + i * ((255 - j) % 255)) % 255]
        if val == 0:
            err_pos.append(j)
    if len(err_pos) != max(0, len(sigma) - 1 - sigma.count(0)):
        pass  # best effort: proceed with found roots
    # Forney: error values via omega(x) = S(x) sigma(x) mod x^red
    s_poly = synd
    omega = [0] * red
    for i in range(len(sigma)):
        for j in range(len(s_poly)):
            if i + j < red and sigma[i] and s_poly[j]:
                omega[i + j] ^= gf_mul(sigma[i], s_poly[j])
    # sigma'(x): formal derivative (odd-degree terms)
    for j in err_pos:
        xinv = _GF_EXP[(255 - j) % 255]  # alpha^{-j}
        num = 0
        xp = 1
        for i in range(red):
            if omega[i]:
                num ^= gf_mul(omega[i], xp)
            xp = gf_mul(xp, xinv)
        den = 0
        xp = 1  # (alpha^{-j})^0
        for i in range(1, len(sigma), 2):
            if sigma[i]:
                den ^= gf_mul(sigma[i], xp)
            xp = gf_mul(xp, gf_mul(xinv, xinv))
        if den == 0:
            continue
        # error magnitude e_j = omega(alpha^-j) / sigma'(alpha^-j)
        # (no X_l factor: with S(x) = sum S_{i+1} x^i, omega(X^-1) = e*X*prod
        #  and sigma'(X^-1) = X*prod, so the X cancels in char 2)
        c[j] ^= gf_mul(num, gf_inv(den))
    return bytes(c[red:])


# -- duplicated Reed-Muller RM(1,7) ------------------------------------------


def rm_encode_byte(b: int) -> int:
    """byte -> 128-bit RM(1,7) codeword (int, bit j = position j)."""
    cw = 0
    for j in range(RM_N):
        bit = b & 1  # b0 on the all-ones basis vector
        for t in range(7):
            if (b >> (t + 1)) & 1 and (j >> t) & 1:
                bit ^= 1
        cw |= bit << j
    return cw


_RM_ENC_TABLE = [rm_encode_byte(b) for b in range(256)]


def rm_decode_block(p: HQCParams, bits: list[int]) -> int:
    """dup*128 received bits -> decoded byte via soft FHT correlation."""
    # soft-combine duplicates: counts in {-dup..dup}
    f = [0] * RM_N
    for j in range(RM_N):
        acc = 0
        for d in range(p.dup):
            acc += 1 - 2 * bits[d * RM_N + j]  # 0 -> +1, 1 -> -1
        f[j] = acc
    # fast Hadamard transform
    h = 1
    while h < RM_N:
        for i in range(0, RM_N, 2 * h):
            for j in range(i, i + h):
                a, b2 = f[j], f[j + h]
                f[j], f[j + h] = a + b2, a - b2
        h *= 2
    best = max(range(RM_N), key=lambda i: abs(f[i]))
    b0 = 1 if f[best] < 0 else 0
    return (best << 1) | b0


def code_encode(p: HQCParams, msg: bytes) -> int:
    """k message bytes -> n1*n2-bit codeword (as int)."""
    rs = rs_encode(p, msg)
    out = 0
    for i, byte in enumerate(rs):
        cw = _RM_ENC_TABLE[byte]
        for d in range(p.dup):
            out |= cw << (i * p.n2 + d * RM_N)
    return out


def code_decode(p: HQCParams, v: int) -> bytes:
    rs_bytes = []
    for i in range(p.n1):
        block = [(v >> (i * p.n2 + j)) & 1 for j in range(p.n2)]
        rs_bytes.append(rm_decode_block(p, block))
    return rs_decode(p, bytes(rs_bytes))


# -- fixed-weight sampling + cyclic arithmetic -------------------------------


SEEDEXPANDER_DOMAIN = 0x02  # HQC shake_prng.c seedexpander domain byte


class SeedExpander:
    """HQC seedexpander: SHAKE256 XOF over ``seed || 0x02``, squeezed
    incrementally — each read continues the same output stream, exactly the
    reference implementation's seedexpander state (shake_prng.c)."""

    def __init__(self, seed: bytes):
        self._xof = hashlib.shake_256(seed + bytes([SEEDEXPANDER_DOMAIN]))
        self._pos = 0

    def read(self, n: int) -> bytes:
        # hashlib has no incremental squeeze; an XOF's output is a
        # prefix-consistent stream, so re-digest and slice.
        out = self._xof.digest(self._pos + n)[self._pos :]
        self._pos += n
        return out


def sample_fixed_weight(p: HQCParams, ctx: SeedExpander, weight: int) -> int:
    """HQC vect_set_random_fixed_weight: one 4*weight-byte draw,
    multiplicative range reduction ``i + (rand32 * (n-i)) >> 32``, duplicate
    slots replaced by their index (reverse scan)."""
    buf = ctx.read(4 * weight)
    support = [0] * weight
    for i in range(weight):
        r = int.from_bytes(buf[4 * i : 4 * i + 4], "little")
        support[i] = i + ((r * (p.n - i)) >> 32)
    for i in range(weight - 2, -1, -1):
        if support[i] in support[i + 1 :]:
            support[i] = i
    v = 0
    for pos in support:
        v |= 1 << pos
    return v


def sample_random_vector(p: HQCParams, ctx: SeedExpander) -> int:
    v = int.from_bytes(ctx.read(p.n_bytes), "little")
    return v & ((1 << p.n) - 1)


def cyclic_mul(p: HQCParams, a: int, b_support_int: int) -> int:
    """a * b in GF(2)[x]/(x^n - 1); b given as bit-vector int (any weight)."""
    mask = (1 << p.n) - 1
    out = 0
    b = b_support_int
    while b:
        low = b & -b
        pos = low.bit_length() - 1
        out ^= a << pos
        b ^= low
    return (out & mask) ^ (out >> p.n)


def _hash_g(data: bytes) -> bytes:
    """G: SHAKE256-512 with trailing domain byte (HQC hash.c shake256_512_ds)."""
    return hashlib.shake_256(data + b"\x03").digest(64)


def _hash_k(data: bytes) -> bytes:
    """K: SHAKE256-512 with trailing domain byte."""
    return hashlib.shake_256(data + b"\x04").digest(64)


# -- KEM ---------------------------------------------------------------------


def keygen(p: HQCParams, sk_seed: bytes, sigma: bytes, pk_seed: bytes):
    """sk_seed (40), sigma (k), pk_seed (40) -> (pk, sk).

    Both secrets come from ONE sk seedexpander stream, y before x
    (reference hqc.c keygen order); h from the pk seedexpander.
    """
    sk_ctx = SeedExpander(sk_seed)
    y = sample_fixed_weight(p, sk_ctx, p.w)
    x = sample_fixed_weight(p, sk_ctx, p.w)
    h = sample_random_vector(p, SeedExpander(pk_seed))
    s = x ^ cyclic_mul(p, h, y)
    pk = pk_seed + s.to_bytes(p.n_bytes, "little")
    sk = sk_seed + sigma + pk
    return pk, sk


def _encrypt(p: HQCParams, pk: bytes, m: bytes, theta: bytes):
    """One theta seedexpander stream: r2, e, r1 (in that order)."""
    pk_seed = pk[:40]
    s = int.from_bytes(pk[40:], "little")
    h = sample_random_vector(p, SeedExpander(pk_seed))
    ctx = SeedExpander(theta)
    r2 = sample_fixed_weight(p, ctx, p.wr)
    e = sample_fixed_weight(p, ctx, p.wr)
    r1 = sample_fixed_weight(p, ctx, p.wr)
    u = r1 ^ cyclic_mul(p, h, r2)
    t = code_encode(p, m) ^ cyclic_mul(p, s, r2) ^ e
    v = t & ((1 << (p.n1 * p.n2)) - 1)  # truncate to the code length
    return u, v


def encaps(p: HQCParams, pk: bytes, m: bytes, salt: bytes):
    """pk, m (k bytes), salt (16) -> (ct, ss)."""
    theta = _hash_g(m + pk[:32] + salt)
    u, v = _encrypt(p, pk, m, theta)
    u_b = u.to_bytes(p.n_bytes, "little")
    v_b = v.to_bytes(p.n1n2_bytes, "little")
    ct = u_b + v_b + salt
    ss = _hash_k(m + u_b + v_b)
    return ct, ss


def decaps(p: HQCParams, sk: bytes, ct: bytes) -> bytes:
    sk_seed, sigma = sk[:40], sk[40 : 40 + p.k]
    pk = sk[40 + p.k :]
    u_b = ct[: p.n_bytes]
    v_b = ct[p.n_bytes : p.n_bytes + p.n1n2_bytes]
    salt = ct[p.n_bytes + p.n1n2_bytes :]
    u = int.from_bytes(u_b, "little")
    v = int.from_bytes(v_b, "little")
    y = sample_fixed_weight(p, SeedExpander(sk_seed), p.w)  # first draw = y
    uy = cyclic_mul(p, u, y)
    m_p = code_decode(p, v ^ (uy & ((1 << (p.n1 * p.n2)) - 1)))
    theta_p = _hash_g(m_p + pk[:32] + salt)
    u2, v2 = _encrypt(p, pk, m_p, theta_p)
    if u2 == u and v2 == v:
        return _hash_k(m_p + u_b + v_b)
    return _hash_k(sigma + u_b + v_b)
