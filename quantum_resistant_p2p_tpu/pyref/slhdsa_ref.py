"""Pure-Python SLH-DSA / SPHINCS+-SHA2 (FIPS 205) — clean-room reference.

Written directly from the FIPS 205 specification (SHA2 'simple'
instantiations, §11.2) with ``hashlib``/``hmac``.  Serves as the bit-exactness
oracle for the batched JAX implementation in ``sig.sphincs`` and as the CPU
provider backend (the role liboqs SPHINCS+ plays for the reference app's
crypto/signatures.py:191-315 SPHINCSSignature).

Determinism seam: keygen takes (sk_seed, sk_prf, pk_seed); signing takes
``addrnd`` (None = deterministic, addrnd = pk_seed per spec default).

Security-category hash split (FIPS 205 §11.2): F/PRF/PRF_msg-inner use
SHA-256 everywhere; H/T_l/H_msg use SHA-256 for the 128-bit sets and SHA-512
for the 192/256-bit sets.
"""

from __future__ import annotations

# qrlint: disable-file=cross-thread-state — ADRS address words are mutated freely per FIPS 205 idiom, but every ADRS instance is constructed inside the signing/verify call that mutates it (never stored on a shared object), so multi-domain callers each own a private instance

import hashlib
import hmac as hmac_mod
from dataclasses import dataclass

LG_W = 4
W = 16


@dataclass(frozen=True)
class SLHDSAParams:
    name: str
    n: int
    h: int
    d: int
    hp: int  # h' = h/d
    a: int
    k: int
    m: int

    @property
    def len1(self) -> int:
        return 2 * self.n

    @property
    def len2(self) -> int:
        return 3

    @property
    def wots_len(self) -> int:
        return self.len1 + self.len2

    @property
    def pk_len(self) -> int:
        return 2 * self.n

    @property
    def sk_len(self) -> int:
        return 4 * self.n

    @property
    def sig_len(self) -> int:
        # R + FORS(k*(1+a)*n) + HT(d*(wots_len+hp)*n)
        return self.n * (1 + self.k * (1 + self.a) + self.d * (self.wots_len + self.hp))

    @property
    def big_hash(self) -> bool:
        """True -> H/T/H_msg/PRF_msg use SHA-512 (security categories 3, 5)."""
        return self.n > 16


SLH128S = SLHDSAParams("SPHINCS+-SHA2-128s-simple", n=16, h=63, d=7, hp=9, a=12, k=14, m=30)
SLH128F = SLHDSAParams("SPHINCS+-SHA2-128f-simple", n=16, h=66, d=22, hp=3, a=6, k=33, m=34)
SLH192S = SLHDSAParams("SPHINCS+-SHA2-192s-simple", n=24, h=63, d=7, hp=9, a=14, k=17, m=39)
SLH192F = SLHDSAParams("SPHINCS+-SHA2-192f-simple", n=24, h=66, d=22, hp=3, a=8, k=33, m=42)
SLH256S = SLHDSAParams("SPHINCS+-SHA2-256s-simple", n=32, h=64, d=8, hp=8, a=14, k=22, m=47)
SLH256F = SLHDSAParams("SPHINCS+-SHA2-256f-simple", n=32, h=68, d=17, hp=4, a=9, k=35, m=49)

PARAMS = {p.name: p for p in (SLH128S, SLH128F, SLH192S, SLH192F, SLH256S, SLH256F)}

assert SLH128F.sig_len == 17088 and SLH128S.sig_len == 7856
assert SLH192F.sig_len == 35664 and SLH192S.sig_len == 16224
assert SLH256F.sig_len == 49856 and SLH256S.sig_len == 29792


# -- ADRS (FIPS 205 §4.2-4.3; compressed 22-byte form for SHA2, §11.2) -------

WOTS_HASH, WOTS_PK, TREE, FORS_TREE, FORS_ROOTS, WOTS_PRF, FORS_PRF = range(7)


class ADRS:
    __slots__ = ("layer", "tree", "type", "w1", "w2", "w3")

    def __init__(self):
        self.layer = 0
        self.tree = 0
        self.type = 0
        self.w1 = self.w2 = self.w3 = 0

    def copy(self) -> "ADRS":
        a = ADRS()
        a.layer, a.tree, a.type = self.layer, self.tree, self.type
        a.w1, a.w2, a.w3 = self.w1, self.w2, self.w3
        return a

    def set_type_and_clear(self, t: int) -> None:
        self.type = t
        self.w1 = self.w2 = self.w3 = 0

    def compressed(self) -> bytes:
        return (
            self.layer.to_bytes(1, "big")
            + self.tree.to_bytes(8, "big")
            + self.type.to_bytes(1, "big")
            + self.w1.to_bytes(4, "big")
            + self.w2.to_bytes(4, "big")
            + self.w3.to_bytes(4, "big")
        )


# -- hash functions (SHA2 'simple', FIPS 205 §11.2.1-11.2.2) -----------------


def _sha(big: bool, data: bytes) -> bytes:
    return (hashlib.sha512 if big else hashlib.sha256)(data).digest()


def _mgf1(big: bool, seed: bytes, length: int) -> bytes:
    hlen = 64 if big else 32
    out = b""
    for c in range((length + hlen - 1) // hlen):
        out += _sha(big, seed + c.to_bytes(4, "big"))
    return out[:length]


def f_hash(p: SLHDSAParams, pk_seed: bytes, adrs: ADRS, m: bytes) -> bytes:
    """F / PRF / T_l for the small-hash engine (always SHA-256)."""
    return hashlib.sha256(
        pk_seed + b"\0" * (64 - p.n) + adrs.compressed() + m
    ).digest()[: p.n]


def t_hash(p: SLHDSAParams, pk_seed: bytes, adrs: ADRS, m: bytes) -> bytes:
    """H / T_l — SHA-256 (cat 1) or SHA-512 (cats 3, 5) with block-pad seed."""
    if not p.big_hash:
        return f_hash(p, pk_seed, adrs, m)
    return hashlib.sha512(
        pk_seed + b"\0" * (128 - p.n) + adrs.compressed() + m
    ).digest()[: p.n]


def prf_msg(p: SLHDSAParams, sk_prf: bytes, opt_rand: bytes, msg: bytes) -> bytes:
    h = hashlib.sha512 if p.big_hash else hashlib.sha256
    return hmac_mod.new(sk_prf, opt_rand + msg, h).digest()[: p.n]


def h_msg(p: SLHDSAParams, r: bytes, pk_seed: bytes, pk_root: bytes, msg: bytes) -> bytes:
    inner = _sha(p.big_hash, r + pk_seed + pk_root + msg)
    return _mgf1(p.big_hash, r + pk_seed + inner, p.m)


# -- base-w / checksum (FIPS 205 §5, lg_w = 4: nibbles, big-endian) ----------


def _to_nibbles(b: bytes) -> list[int]:
    out = []
    for byte in b:
        out.append(byte >> 4)
        out.append(byte & 0xF)
    return out


def _wots_digits(p: SLHDSAParams, m: bytes) -> list[int]:
    msg = _to_nibbles(m)  # len1 digits
    csum = sum(W - 1 - d for d in msg)
    csum <<= 4  # (8 - ((len2 * LG_W) % 8)) % 8
    return msg + _to_nibbles(csum.to_bytes(2, "big"))[: p.len2]


# -- WOTS+ (FIPS 205 §5) -----------------------------------------------------


def _chain(p: SLHDSAParams, x: bytes, i: int, s: int, pk_seed: bytes, adrs: ADRS) -> bytes:
    for j in range(i, i + s):
        adrs.w3 = j
        x = f_hash(p, pk_seed, adrs, x)
    return x


def wots_pkgen(p: SLHDSAParams, sk_seed: bytes, pk_seed: bytes, adrs: ADRS) -> bytes:
    sk_adrs = adrs.copy()
    sk_adrs.set_type_and_clear(WOTS_PRF)
    sk_adrs.w1 = adrs.w1
    tmp = b""
    for i in range(p.wots_len):
        sk_adrs.w2 = i
        sk = f_hash(p, pk_seed, sk_adrs, sk_seed)
        adrs.w2 = i
        adrs.w3 = 0
        tmp += _chain(p, sk, 0, W - 1, pk_seed, adrs)
    pk_adrs = adrs.copy()
    pk_adrs.set_type_and_clear(WOTS_PK)
    pk_adrs.w1 = adrs.w1
    return t_hash(p, pk_seed, pk_adrs, tmp)


def wots_sign(p: SLHDSAParams, m: bytes, sk_seed: bytes, pk_seed: bytes, adrs: ADRS) -> bytes:
    digits = _wots_digits(p, m)
    sk_adrs = adrs.copy()
    sk_adrs.set_type_and_clear(WOTS_PRF)
    sk_adrs.w1 = adrs.w1
    sig = b""
    for i, d in enumerate(digits):
        sk_adrs.w2 = i
        sk = f_hash(p, pk_seed, sk_adrs, sk_seed)
        adrs.w2 = i
        adrs.w3 = 0
        sig += _chain(p, sk, 0, d, pk_seed, adrs)
    return sig


def wots_pk_from_sig(p: SLHDSAParams, sig: bytes, m: bytes, pk_seed: bytes, adrs: ADRS) -> bytes:
    digits = _wots_digits(p, m)
    tmp = b""
    for i, d in enumerate(digits):
        adrs.w2 = i
        tmp += _chain(p, sig[i * p.n : (i + 1) * p.n], d, W - 1 - d, pk_seed, adrs)
    pk_adrs = adrs.copy()
    pk_adrs.set_type_and_clear(WOTS_PK)
    pk_adrs.w1 = adrs.w1
    return t_hash(p, pk_seed, pk_adrs, tmp)


# -- XMSS (FIPS 205 §6) ------------------------------------------------------


def _xmss_node(p: SLHDSAParams, sk_seed: bytes, i: int, z: int, pk_seed: bytes, adrs: ADRS) -> bytes:
    if z == 0:
        adrs.set_type_and_clear(WOTS_HASH)
        adrs.w1 = i
        return wots_pkgen(p, sk_seed, pk_seed, adrs)
    lnode = _xmss_node(p, sk_seed, 2 * i, z - 1, pk_seed, adrs)
    rnode = _xmss_node(p, sk_seed, 2 * i + 1, z - 1, pk_seed, adrs)
    adrs.set_type_and_clear(TREE)
    adrs.w2 = z  # FIPS 205 §4.3: TREE uses (pad, height, index) in words 1-3
    adrs.w3 = i
    return t_hash(p, pk_seed, adrs, lnode + rnode)


def xmss_sign(p: SLHDSAParams, m: bytes, sk_seed: bytes, idx: int, pk_seed: bytes, adrs: ADRS) -> bytes:
    auth = b""
    for j in range(p.hp):
        k = (idx >> j) ^ 1
        auth += _xmss_node(p, sk_seed, k, j, pk_seed, adrs.copy())
    adrs.set_type_and_clear(WOTS_HASH)
    adrs.w1 = idx
    return wots_sign(p, m, sk_seed, pk_seed, adrs) + auth


def xmss_pk_from_sig(p: SLHDSAParams, idx: int, sig_xmss: bytes, m: bytes, pk_seed: bytes, adrs: ADRS) -> bytes:
    wots_sig = sig_xmss[: p.wots_len * p.n]
    auth = sig_xmss[p.wots_len * p.n :]
    adrs.set_type_and_clear(WOTS_HASH)
    adrs.w1 = idx
    node = wots_pk_from_sig(p, wots_sig, m, pk_seed, adrs)
    adrs.set_type_and_clear(TREE)
    adrs.w3 = idx
    for k in range(p.hp):
        adrs.w2 = k + 1
        sib = auth[k * p.n : (k + 1) * p.n]
        if (idx >> k) & 1:
            adrs.w3 = (adrs.w3 - 1) >> 1
            node = t_hash(p, pk_seed, adrs, sib + node)
        else:
            adrs.w3 = adrs.w3 >> 1
            node = t_hash(p, pk_seed, adrs, node + sib)
    return node


# -- Hypertree (FIPS 205 §7) -------------------------------------------------


def ht_sign(p: SLHDSAParams, m: bytes, sk_seed: bytes, pk_seed: bytes, idx_tree: int, idx_leaf: int) -> bytes:
    adrs = ADRS()
    adrs.tree = idx_tree
    sig = xmss_sign(p, m, sk_seed, idx_leaf, pk_seed, adrs)
    root = xmss_pk_from_sig(
        p, idx_leaf, sig, m, pk_seed, _adrs_for(idx_tree, 0)
    )
    for j in range(1, p.d):
        idx_leaf = idx_tree & ((1 << p.hp) - 1)
        idx_tree >>= p.hp
        adrs = _adrs_for(idx_tree, j)
        sig_j = xmss_sign(p, root, sk_seed, idx_leaf, pk_seed, adrs)
        sig += sig_j
        if j < p.d - 1:
            root = xmss_pk_from_sig(p, idx_leaf, sig_j, root, pk_seed, _adrs_for(idx_tree, j))
    return sig


def _adrs_for(tree: int, layer: int) -> ADRS:
    a = ADRS()
    a.tree = tree
    a.layer = layer
    return a


def ht_verify(p: SLHDSAParams, m: bytes, sig_ht: bytes, pk_seed: bytes, idx_tree: int, idx_leaf: int, pk_root: bytes) -> bool:
    per = (p.wots_len + p.hp) * p.n
    node = xmss_pk_from_sig(p, idx_leaf, sig_ht[:per], m, pk_seed, _adrs_for(idx_tree, 0))
    for j in range(1, p.d):
        idx_leaf = idx_tree & ((1 << p.hp) - 1)
        idx_tree >>= p.hp
        node = xmss_pk_from_sig(
            p, idx_leaf, sig_ht[j * per : (j + 1) * per], node, pk_seed, _adrs_for(idx_tree, j)
        )
    return node == pk_root


# -- FORS (FIPS 205 §8) ------------------------------------------------------


def _fors_sk(p: SLHDSAParams, sk_seed: bytes, pk_seed: bytes, adrs: ADRS, idx: int) -> bytes:
    sk_adrs = adrs.copy()
    sk_adrs.set_type_and_clear(FORS_PRF)
    sk_adrs.w1 = adrs.w1
    sk_adrs.w3 = idx
    return f_hash(p, pk_seed, sk_adrs, sk_seed)


def _fors_node(p: SLHDSAParams, sk_seed: bytes, i: int, z: int, pk_seed: bytes, adrs: ADRS) -> bytes:
    if z == 0:
        sk = _fors_sk(p, sk_seed, pk_seed, adrs, i)
        adrs.w2 = 0
        adrs.w3 = i
        return f_hash(p, pk_seed, adrs, sk)
    lnode = _fors_node(p, sk_seed, 2 * i, z - 1, pk_seed, adrs)
    rnode = _fors_node(p, sk_seed, 2 * i + 1, z - 1, pk_seed, adrs)
    adrs.w2 = z
    adrs.w3 = i
    return t_hash(p, pk_seed, adrs, lnode + rnode)


def _msg_indices(p: SLHDSAParams, md: bytes) -> list[int]:
    """base_2^a digits of the FORS message digest."""
    out = []
    bits = 0
    acc = 0
    pos = 0
    for _ in range(p.k):
        while bits < p.a:
            acc = (acc << 8) | md[pos]
            pos += 1
            bits += 8
        bits -= p.a
        out.append((acc >> bits) & ((1 << p.a) - 1))
        acc &= (1 << bits) - 1
    return out


def fors_sign(p: SLHDSAParams, md: bytes, sk_seed: bytes, pk_seed: bytes, adrs: ADRS) -> bytes:
    indices = _msg_indices(p, md)
    sig = b""
    for i, idx in enumerate(indices):
        sig += _fors_sk(p, sk_seed, pk_seed, adrs, (i << p.a) + idx)
        for j in range(p.a):
            s = (idx >> j) ^ 1
            sig += _fors_node(p, sk_seed, (i << (p.a - j)) + s, j, pk_seed, adrs.copy())
    return sig


def fors_pk_from_sig(p: SLHDSAParams, sig: bytes, md: bytes, pk_seed: bytes, adrs: ADRS) -> bytes:
    indices = _msg_indices(p, md)
    per = (1 + p.a) * p.n
    roots = b""
    for i, idx in enumerate(indices):
        sk = sig[i * per : i * per + p.n]
        auth = sig[i * per + p.n : (i + 1) * per]
        adrs.w2 = 0
        adrs.w3 = (i << p.a) + idx
        node = f_hash(p, pk_seed, adrs, sk)
        tree_idx = (i << p.a) + idx
        for j in range(p.a):
            sib = auth[j * p.n : (j + 1) * p.n]
            adrs.w2 = j + 1
            if (tree_idx >> j) & 1:
                adrs.w3 = ((i << (p.a - j)) + (idx >> j) - 1) >> 1
                node = t_hash(p, pk_seed, adrs, sib + node)
            else:
                adrs.w3 = ((i << (p.a - j)) + (idx >> j)) >> 1
                node = t_hash(p, pk_seed, adrs, node + sib)
        roots += node
    pk_adrs = adrs.copy()
    pk_adrs.set_type_and_clear(FORS_ROOTS)
    pk_adrs.w1 = adrs.w1  # keep the keypair address (FIPS 205 Alg 17 line 25)
    return t_hash(p, pk_seed, pk_adrs, roots)


# -- SLH-DSA top level (FIPS 205 §9-10, internal forms) ----------------------


def keygen(p: SLHDSAParams, sk_seed: bytes, sk_prf: bytes, pk_seed: bytes) -> tuple[bytes, bytes]:
    """Algorithm 18 slh_keygen_internal: three n-byte seeds -> (pk, sk)."""
    adrs = ADRS()
    adrs.layer = p.d - 1
    pk_root = _xmss_node(p, sk_seed, 0, p.hp, pk_seed, adrs)
    pk = pk_seed + pk_root
    return pk, sk_seed + sk_prf + pk


def _split_digest(p: SLHDSAParams, digest: bytes) -> tuple[bytes, int, int]:
    ka = (p.k * p.a + 7) // 8
    t = (p.h - p.hp + 7) // 8
    u = (p.hp + 7) // 8
    md = digest[:ka]
    idx_tree = int.from_bytes(digest[ka : ka + t], "big") & ((1 << (p.h - p.hp)) - 1)
    idx_leaf = int.from_bytes(digest[ka + t : ka + t + u], "big") & ((1 << p.hp) - 1)
    return md, idx_tree, idx_leaf


def sign_internal(p: SLHDSAParams, msg: bytes, sk: bytes, addrnd: bytes | None = None) -> bytes:
    """Algorithm 19 slh_sign_internal (addrnd=None -> deterministic variant)."""
    sk_seed, sk_prf, pk_seed, pk_root = (
        sk[: p.n], sk[p.n : 2 * p.n], sk[2 * p.n : 3 * p.n], sk[3 * p.n :]
    )
    opt_rand = pk_seed if addrnd is None else addrnd
    r = prf_msg(p, sk_prf, opt_rand, msg)
    digest = h_msg(p, r, pk_seed, pk_root, msg)
    md, idx_tree, idx_leaf = _split_digest(p, digest)
    adrs = ADRS()
    adrs.tree = idx_tree
    adrs.set_type_and_clear(FORS_TREE)
    adrs.w1 = idx_leaf
    sig_fors = fors_sign(p, md, sk_seed, pk_seed, adrs)
    pk_fors = fors_pk_from_sig(p, sig_fors, md, pk_seed, _fors_adrs(idx_tree, idx_leaf))
    sig_ht = ht_sign(p, pk_fors, sk_seed, pk_seed, idx_tree, idx_leaf)
    return r + sig_fors + sig_ht


def _fors_adrs(tree: int, leaf: int) -> ADRS:
    a = ADRS()
    a.tree = tree
    a.set_type_and_clear(FORS_TREE)
    a.w1 = leaf
    return a


def verify_internal(p: SLHDSAParams, msg: bytes, sig: bytes, pk: bytes) -> bool:
    """Algorithm 20 slh_verify_internal."""
    if len(sig) != p.sig_len or len(pk) != p.pk_len:
        return False
    pk_seed, pk_root = pk[: p.n], pk[p.n :]
    r = sig[: p.n]
    fors_len = p.k * (1 + p.a) * p.n
    sig_fors = sig[p.n : p.n + fors_len]
    sig_ht = sig[p.n + fors_len :]
    digest = h_msg(p, r, pk_seed, pk_root, msg)
    md, idx_tree, idx_leaf = _split_digest(p, digest)
    pk_fors = fors_pk_from_sig(p, sig_fors, md, pk_seed, _fors_adrs(idx_tree, idx_leaf))
    return ht_verify(p, pk_fors, sig_ht, pk_seed, idx_tree, idx_leaf, pk_root)


# -- external API (pure M' = M, matching liboqs SPHINCS+ usage) --------------


def sign(p: SLHDSAParams, sk: bytes, message: bytes, addrnd: bytes | None = None) -> bytes:
    return sign_internal(p, message, sk, addrnd)


def verify(p: SLHDSAParams, pk: bytes, message: bytes, sig: bytes) -> bool:
    try:
        return verify_internal(p, message, sig, pk)
    except Exception:  # qrlint: disable=broad-except  — FIPS 205 verify contract: any malformed signature/key decodes to False, never an exception
        return False
