"""Pure-Python FIPS reference implementations.

These serve two purposes:

1. **Bit-exactness oracle** for the JAX/TPU implementations: the vendored
   liboqs binary the reference app shipped (``vendor/lib/linux/liboqs.so``,
   stripped from this checkout) is not available and this environment has no
   network, so cross-validation is done against independent clean-room
   implementations of FIPS 203 (ML-KEM) / FIPS 204 (ML-DSA) / FIPS 205
   (SLH-DSA) written directly from the specifications, with ``hashlib``
   (OpenSSL) as the Keccak/SHA-2 oracle.

2. **CPU fallback backend** for the provider layer, filling the role liboqs
   plays in the reference app (reference: crypto/key_exchange.py:125-186
   constructs per-op liboqs objects via the ctypes wrapper vendor/oqs.py).
"""
