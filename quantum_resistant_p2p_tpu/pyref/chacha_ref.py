"""Pure-Python RFC 8439 ChaCha20-Poly1305 — the scalar reference twin.

Two jobs, mirroring the other pyref modules:

* the KAT oracle for the batched device AEAD (core/chacha_pallas.py): the
  device seal/open must be bit-exact against this implementation at every
  length bucket, masked tail, and AAD shape (tests/test_chacha_pallas.py
  pins the RFC 8439 §2.8.2 vector through BOTH paths);
* the wheel-less scalar fallback: ``provider/symmetric.py`` routes
  ChaCha20-Poly1305 here when the OpenSSL ``cryptography`` wheel is absent
  (minimal accelerator images), so the protocol engine's bulk path — and
  the batched queue's cpu fallback — works everywhere the PQC layers do.

Spec: RFC 8439 (ChaCha20 §2.3, Poly1305 §2.5, AEAD construction §2.8).
Performance is NOT a goal here — the whole point of the device path is
that this scalar twin is slow.
"""

from __future__ import annotations

import hmac as _hmac
import struct

_MASK32 = 0xFFFFFFFF
_CONSTS = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
#: Poly1305 prime 2^130 - 5
_P1305 = (1 << 130) - 5

KEY_SIZE = 32
NONCE_SIZE = 12
TAG_SIZE = 16


def _rotl32(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & _MASK32


def _quarter(state: list[int], a: int, b: int, c: int, d: int) -> None:
    state[a] = (state[a] + state[b]) & _MASK32
    state[d] = _rotl32(state[d] ^ state[a], 16)
    state[c] = (state[c] + state[d]) & _MASK32
    state[b] = _rotl32(state[b] ^ state[c], 12)
    state[a] = (state[a] + state[b]) & _MASK32
    state[d] = _rotl32(state[d] ^ state[a], 8)
    state[c] = (state[c] + state[d]) & _MASK32
    state[b] = _rotl32(state[b] ^ state[c], 7)


def chacha20_block(key: bytes, counter: int, nonce: bytes) -> bytes:
    """One 64-byte ChaCha20 block (RFC 8439 §2.3)."""
    if len(key) != KEY_SIZE:
        raise ValueError("ChaCha20 key must be 32 bytes")
    if len(nonce) != NONCE_SIZE:
        raise ValueError("ChaCha20 nonce must be 12 bytes")
    init = list(_CONSTS)
    init += list(struct.unpack("<8I", key))
    init.append(counter & _MASK32)
    init += list(struct.unpack("<3I", nonce))
    x = list(init)
    for _ in range(10):
        _quarter(x, 0, 4, 8, 12)
        _quarter(x, 1, 5, 9, 13)
        _quarter(x, 2, 6, 10, 14)
        _quarter(x, 3, 7, 11, 15)
        _quarter(x, 0, 5, 10, 15)
        _quarter(x, 1, 6, 11, 12)
        _quarter(x, 2, 7, 8, 13)
        _quarter(x, 3, 4, 9, 14)
    return struct.pack("<16I", *((x[i] + init[i]) & _MASK32 for i in range(16)))


def chacha20_xor(key: bytes, counter: int, nonce: bytes, data: bytes) -> bytes:
    """XOR ``data`` with the keystream starting at block ``counter``."""
    out = bytearray(len(data))
    for blk in range(-(-len(data) // 64)):
        ks = chacha20_block(key, counter + blk, nonce)
        lo = 64 * blk
        chunk = data[lo : lo + 64]
        out[lo : lo + len(chunk)] = bytes(a ^ b for a, b in zip(chunk, ks))
    return bytes(out)


def poly1305_mac(key: bytes, msg: bytes) -> bytes:
    """Poly1305 tag (RFC 8439 §2.5.1) over arbitrary-length ``msg``."""
    r = int.from_bytes(key[:16], "little")
    r &= 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key[16:32], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        n = int.from_bytes(block, "little") + (1 << (8 * len(block)))
        acc = ((acc + n) * r) % _P1305
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    rem = len(data) % 16
    return data if rem == 0 else data + bytes(16 - rem)


def _mac_data(aad: bytes, ct: bytes) -> bytes:
    """AEAD MAC input (RFC 8439 §2.8): padded AAD, padded ciphertext, lens."""
    return (_pad16(aad) + _pad16(ct)
            + struct.pack("<QQ", len(aad), len(ct)))


def seal(key: bytes, nonce: bytes, plaintext: bytes,
         aad: bytes = b"") -> bytes:
    """-> ciphertext || 16-byte tag (RFC 8439 §2.8.1)."""
    otk = chacha20_block(key, 0, nonce)[:32]
    ct = chacha20_xor(key, 1, nonce, plaintext)
    return ct + poly1305_mac(otk, _mac_data(aad, ct))


def open_(key: bytes, nonce: bytes, data: bytes, aad: bytes = b"") -> bytes:
    """Verify-then-decrypt ``ciphertext || tag``; ValueError on a bad tag."""
    if len(data) < TAG_SIZE:
        raise ValueError("ciphertext too short")
    ct, tag = data[:-TAG_SIZE], data[-TAG_SIZE:]
    otk = chacha20_block(key, 0, nonce)[:32]
    want = poly1305_mac(otk, _mac_data(aad, ct))
    if not _hmac.compare_digest(tag, want):
        raise ValueError("authentication failed")
    return chacha20_xor(key, 1, nonce, ct)
