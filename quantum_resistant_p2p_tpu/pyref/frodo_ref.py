"""Pure-Python FrodoKEM (round-3 / ISO spec) — clean-room reference.

Written from the FrodoKEM specification (frodokem.org round-3 submission):
LWE with dense n x n matrices, nbar = mbar = 8, q = 2^D.  Matrix A comes from
AES-128-ECB (the -AES variants) or SHAKE-128 (the -SHAKE variants) expansion.
``cryptography`` supplies AES; ``hashlib`` supplies SHAKE.

Serves as the bit-exactness oracle for the batched JAX implementation in
``kem.frodo`` and as the CPU provider backend (the role liboqs FrodoKEM plays
for the reference app's crypto/key_exchange.py:312-449 FrodoKEMKeyExchange).

Determinism seam: keygen takes (s, seedSE, z); encaps takes mu — the exact
random inputs the spec draws, so KAT-style seeds drive both implementations.

Self-check: parameter sets reproduce the published sizes
  pk 9616/15632/21520, sk 19888/31296/43088, ct 9720/15744/21632.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

try:
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
except ImportError:  # pragma: no cover - exercised only on minimal images
    # Gate, don't crash: only FrodoKEM's AES matrix expansion needs it;
    # importers using the SHAKE variant (or other pyref modules) work
    # without the wheel.
    Cipher = algorithms = modes = None

NBAR = 8


@dataclass(frozen=True)
class FrodoParams:
    name: str
    n: int
    d: int  # q = 2^d
    b: int  # extracted bits per coefficient
    len_sec: int  # bytes of s / seedSE / z / pkh / mu / ss
    cdf: tuple[int, ...]
    aes: bool  # True -> AES-128 matrix gen, False -> SHAKE-128

    @property
    def q(self) -> int:
        return 1 << self.d

    @property
    def pk_len(self) -> int:
        return 16 + self.n * NBAR * self.d // 8

    @property
    def sk_len(self) -> int:
        return self.len_sec + self.pk_len + 2 * self.n * NBAR + self.len_sec

    @property
    def ct_len(self) -> int:
        return (NBAR * self.n + NBAR * NBAR) * self.d // 8

    @property
    def shake(self):
        return hashlib.shake_128 if self.n == 640 else hashlib.shake_256


_CDF640 = (4643, 13363, 20579, 25843, 29227, 31145, 32103, 32525, 32689,
           32745, 32762, 32766, 32767)
_CDF976 = (5638, 15915, 23689, 28571, 31116, 32217, 32613, 32731, 32760,
           32766, 32767)
_CDF1344 = (9142, 23462, 30338, 32361, 32725, 32765, 32767)


def _mk(name, n, d, b, sec, cdf, aes):
    return FrodoParams(name, n, d, b, sec, cdf, aes)


FRODO640AES = _mk("FrodoKEM-640-AES", 640, 15, 2, 16, _CDF640, True)
FRODO640SHAKE = _mk("FrodoKEM-640-SHAKE", 640, 15, 2, 16, _CDF640, False)
FRODO976AES = _mk("FrodoKEM-976-AES", 976, 16, 3, 24, _CDF976, True)
FRODO976SHAKE = _mk("FrodoKEM-976-SHAKE", 976, 16, 3, 24, _CDF976, False)
FRODO1344AES = _mk("FrodoKEM-1344-AES", 1344, 16, 4, 32, _CDF1344, True)
FRODO1344SHAKE = _mk("FrodoKEM-1344-SHAKE", 1344, 16, 4, 32, _CDF1344, False)

PARAMS = {p.name: p for p in (
    FRODO640AES, FRODO640SHAKE, FRODO976AES, FRODO976SHAKE, FRODO1344AES, FRODO1344SHAKE
)}

assert FRODO640AES.pk_len == 9616 and FRODO640AES.sk_len == 19888 and FRODO640AES.ct_len == 9720
assert FRODO976AES.pk_len == 15632 and FRODO976AES.sk_len == 31296 and FRODO976AES.ct_len == 15744
assert FRODO1344AES.pk_len == 21520 and FRODO1344AES.sk_len == 43088 and FRODO1344AES.ct_len == 21632


def _shake(p: FrodoParams, data: bytes, out_len: int) -> bytes:
    return p.shake(data).digest(out_len)


# -- matrix A generation (spec Algorithms 7-8) -------------------------------


def gen_a(p: FrodoParams, seed_a: bytes) -> list[list[int]]:
    n = p.n
    mask = p.q - 1
    a = []
    if p.aes:
        if Cipher is None:
            raise RuntimeError(
                "FrodoKEM-AES matrix expansion needs the 'cryptography' package"
            )
        enc = Cipher(algorithms.AES(seed_a), modes.ECB()).encryptor()
        for i in range(n):
            row = []
            blocks = b"".join(
                i.to_bytes(2, "little") + j.to_bytes(2, "little") + b"\0" * 12
                for j in range(0, n, 8)
            )
            ct = enc.update(blocks)
            for k in range(0, len(ct), 2):
                row.append(int.from_bytes(ct[k : k + 2], "little") & mask)
            a.append(row)
    else:
        for i in range(n):
            buf = hashlib.shake_128(i.to_bytes(2, "little") + seed_a).digest(2 * n)
            a.append(
                [int.from_bytes(buf[2 * j : 2 * j + 2], "little") & mask for j in range(n)]
            )
    return a


# -- error sampling (spec Algorithm 5: inversion sampling on the CDF) --------


def sample(p: FrodoParams, r16: int) -> int:
    t = r16 >> 1
    e = 0
    for z in p.cdf[:-1]:
        if t > z:
            e += 1
    if r16 & 1:
        e = -e
    return e % p.q


def sample_matrix(p: FrodoParams, rbytes: bytes, n1: int, n2: int) -> list[list[int]]:
    vals = [
        sample(p, int.from_bytes(rbytes[2 * k : 2 * k + 2], "little"))
        for k in range(n1 * n2)
    ]
    return [vals[i * n2 : (i + 1) * n2] for i in range(n1)]


# -- packing / encoding (spec Algorithms 3-4 and 1-2) ------------------------


def pack(p: FrodoParams, m: list[list[int]]) -> bytes:
    """D-bit big-endian bit packing of the matrix in row-major order."""
    bits = 0
    acc = 0
    out = bytearray()
    for row in m:
        for v in row:
            acc = (acc << p.d) | (v & (p.q - 1))
            bits += p.d
            while bits >= 8:
                bits -= 8
                out.append((acc >> bits) & 0xFF)
    return bytes(out)


def unpack(p: FrodoParams, data: bytes, n1: int, n2: int) -> list[list[int]]:
    acc = 0
    bits = 0
    vals = []
    pos = 0
    for _ in range(n1 * n2):
        while bits < p.d:
            acc = (acc << 8) | data[pos]
            pos += 1
            bits += 8
        bits -= p.d
        vals.append((acc >> bits) & (p.q - 1))
        acc &= (1 << bits) - 1
    return [vals[i * n2 : (i + 1) * n2] for i in range(n1)]


def encode(p: FrodoParams, mu: bytes) -> list[list[int]]:
    """mu (len_sec bytes = nbar*nbar*B bits) -> nbar x nbar matrix."""
    step = p.q >> p.b
    vals = []
    for k in range(NBAR * NBAR):
        v = 0
        for l in range(p.b):
            bit_idx = k * p.b + l
            v |= ((mu[bit_idx >> 3] >> (bit_idx & 7)) & 1) << l
        vals.append(v * step)
    return [vals[i * NBAR : (i + 1) * NBAR] for i in range(NBAR)]


def decode(p: FrodoParams, m: list[list[int]]) -> bytes:
    out = bytearray(NBAR * NBAR * p.b // 8)
    k = 0
    for row in m:
        for v in row:
            val = ((v << p.b) + (p.q >> 1)) >> p.d  # round(v * 2^B / q)
            val &= (1 << p.b) - 1
            for l in range(p.b):
                bit_idx = k * p.b + l
                out[bit_idx >> 3] |= ((val >> l) & 1) << (bit_idx & 7)
            k += 1
    return bytes(out)


# -- matrix helpers ----------------------------------------------------------


def _matmul_as(p, a, s):
    """A (n x n) @ S (n x nbar) mod q."""
    q = p.q
    n = p.n
    return [
        [sum(a[i][k] * s[k][j] for k in range(n)) % q for j in range(NBAR)]
        for i in range(n)
    ]


def _matmul_sa(p, s, a):
    """S' (nbar x n) @ A (n x n) mod q."""
    q = p.q
    n = p.n
    return [
        [sum(s[i][k] * a[k][j] for k in range(n)) % q for j in range(n)]
        for i in range(NBAR)
    ]


def _matmul_sb(p, s, b):
    """S' (nbar x n) @ B (n x nbar) mod q."""
    q = p.q
    return [
        [sum(s[i][k] * b[k][j] for k in range(p.n)) % q for j in range(NBAR)]
        for i in range(NBAR)
    ]


def _add(p, x, y):
    return [[(a + b) % p.q for a, b in zip(rx, ry)] for rx, ry in zip(x, y)]


def _sub(p, x, y):
    return [[(a - b) % p.q for a, b in zip(rx, ry)] for rx, ry in zip(x, y)]


# -- KEM (spec Algorithms 12-14) ---------------------------------------------


def keygen(p: FrodoParams, s: bytes, seed_se: bytes, z: bytes) -> tuple[bytes, bytes]:
    """Deterministic KeyGen from the spec's three random inputs."""
    seed_a = _shake(p, z, 16)
    a = gen_a(p, seed_a)
    r = _shake(p, b"\x5f" + seed_se, 4 * p.n * NBAR)
    st = sample_matrix(p, r[: 2 * p.n * NBAR], NBAR, p.n)  # S^T
    e = sample_matrix(p, r[2 * p.n * NBAR :], p.n, NBAR)
    s_mat = [[st[j][i] for j in range(NBAR)] for i in range(p.n)]  # n x nbar
    b_mat = _add(p, _matmul_as(p, a, s_mat), e)
    b_packed = pack(p, b_mat)
    pk = seed_a + b_packed
    pkh = _shake(p, pk, p.len_sec)
    st_bytes = b"".join(
        (v if v < p.q // 2 else v - p.q).to_bytes(2, "little", signed=True)
        for row in st for v in row
    )
    sk = s + pk + st_bytes + pkh
    return pk, sk


def encaps(p: FrodoParams, pk: bytes, mu: bytes) -> tuple[bytes, bytes]:
    """Deterministic Encaps from the spec's random mu -> (ct, ss)."""
    seed_a, b_packed = pk[:16], pk[16:]
    pkh = _shake(p, pk, p.len_sec)
    se_k = _shake(p, pkh + mu, p.len_sec + p.len_sec)
    seed_se, k = se_k[: p.len_sec], se_k[p.len_sec :]
    r = _shake(p, b"\x96" + seed_se, (2 * NBAR * p.n + NBAR * NBAR) * 2)
    sp = sample_matrix(p, r[: 2 * NBAR * p.n], NBAR, p.n)
    ep = sample_matrix(p, r[2 * NBAR * p.n : 4 * NBAR * p.n], NBAR, p.n)
    epp = sample_matrix(p, r[4 * NBAR * p.n :], NBAR, NBAR)
    a = gen_a(p, seed_a)
    bp = _add(p, _matmul_sa(p, sp, a), ep)
    b_mat = unpack(p, b_packed, p.n, NBAR)
    v = _add(p, _matmul_sb(p, sp, b_mat), epp)
    c = _add(p, v, encode(p, mu))
    ct = pack(p, bp) + pack(p, c)
    ss = _shake(p, ct + k, p.len_sec)
    return ct, ss


def decaps(p: FrodoParams, sk: bytes, ct: bytes) -> bytes:
    n, q = p.n, p.q
    s = sk[: p.len_sec]
    pk = sk[p.len_sec : p.len_sec + p.pk_len]
    seed_a = pk[:16]
    b_packed = pk[16:]
    st_off = p.len_sec + p.pk_len
    st = [
        [
            int.from_bytes(sk[st_off + 2 * (i * n + j) : st_off + 2 * (i * n + j) + 2],
                           "little", signed=True) % q
            for j in range(n)
        ]
        for i in range(NBAR)
    ]
    pkh = sk[st_off + 2 * NBAR * n :]
    c1_len = NBAR * n * p.d // 8
    bp = unpack(p, ct[:c1_len], NBAR, n)
    c = unpack(p, ct[c1_len:], NBAR, NBAR)
    # M = C - B' * S  (S is n x nbar = transpose of stored S^T)
    bps = [
        [sum(bp[i][k] * st[j][k] for k in range(n)) % q for j in range(NBAR)]
        for i in range(NBAR)
    ]
    m = _sub(p, c, bps)
    mu_p = decode(p, m)
    se_k = _shake(p, pkh + mu_p, 2 * p.len_sec)
    seed_se, kp = se_k[: p.len_sec], se_k[p.len_sec :]
    r = _shake(p, b"\x96" + seed_se, (2 * NBAR * p.n + NBAR * NBAR) * 2)
    sp = sample_matrix(p, r[: 2 * NBAR * p.n], NBAR, p.n)
    ep = sample_matrix(p, r[2 * NBAR * p.n : 4 * NBAR * p.n], NBAR, p.n)
    epp = sample_matrix(p, r[4 * NBAR * p.n :], NBAR, NBAR)
    a = gen_a(p, seed_a)
    bpp = _add(p, _matmul_sa(p, sp, a), ep)
    b_mat = unpack(p, b_packed, p.n, NBAR)
    v = _add(p, _matmul_sb(p, sp, b_mat), epp)
    cp = _add(p, v, encode(p, mu_p))
    if bp == bpp and c == cp:
        return _shake(p, ct + kp, p.len_sec)
    return _shake(p, ct + s, p.len_sec)
