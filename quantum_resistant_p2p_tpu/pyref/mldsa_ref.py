"""Pure-Python ML-DSA (FIPS 204) — clean-room reference implementation.

Written directly from the FIPS 204 specification with ``hashlib`` supplying
SHAKE-128/256.  Serves as the bit-exactness oracle for the batched JAX
implementation in ``quantum_resistant_p2p_tpu.sig.mldsa`` and as the CPU
provider backend (the role liboqs ML-DSA plays for the reference app's
crypto/signatures.py:58-188 MLDSASignature).

Determinism seam: keygen takes the 32-byte seed ``xi``; signing takes the
32-byte ``rnd`` (all-zeros = the deterministic variant), matching the spec's
internal functions so KAT-style seeds drive both implementations.

Self-check: parameter sets reproduce the published sizes
  pk 1312/1952/2592, sk 2560/4032/4896, sig 2420/3309/4627  (44/65/87).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

Q = 8380417
N = 256
D = 13  # dropped bits in Power2Round
ZETA = 1753


@dataclass(frozen=True)
class MLDSAParams:
    name: str
    k: int
    l: int
    eta: int
    tau: int
    gamma1: int
    gamma2: int
    omega: int
    lambda_: int  # collision strength in bits; ctilde = lambda/4 bytes

    @property
    def beta(self) -> int:
        return self.tau * self.eta

    @property
    def ctilde_len(self) -> int:
        return self.lambda_ // 4

    @property
    def z_bits(self) -> int:
        return 1 + (self.gamma1 - 1).bit_length()  # 18 or 20

    @property
    def w1_bits(self) -> int:
        return ((Q - 1) // (2 * self.gamma2) - 1).bit_length()  # 6 or 4

    @property
    def s_bits(self) -> int:
        return (2 * self.eta).bit_length()  # 3 (eta=2) or 4 (eta=4)

    @property
    def pk_len(self) -> int:
        return 32 + 32 * (23 - D) * self.k

    @property
    def sk_len(self) -> int:
        return 128 + 32 * self.s_bits * (self.k + self.l) + 32 * D * self.k

    @property
    def sig_len(self) -> int:
        return self.ctilde_len + 32 * self.z_bits * self.l + self.omega + self.k


MLDSA44 = MLDSAParams("ML-DSA-44", k=4, l=4, eta=2, tau=39, gamma1=1 << 17,
                      gamma2=(Q - 1) // 88, omega=80, lambda_=128)
MLDSA65 = MLDSAParams("ML-DSA-65", k=6, l=5, eta=4, tau=49, gamma1=1 << 19,
                      gamma2=(Q - 1) // 32, omega=55, lambda_=192)
MLDSA87 = MLDSAParams("ML-DSA-87", k=8, l=7, eta=2, tau=60, gamma1=1 << 19,
                      gamma2=(Q - 1) // 32, omega=75, lambda_=256)

PARAMS = {p.name: p for p in (MLDSA44, MLDSA65, MLDSA87)}

assert MLDSA44.pk_len == 1312 and MLDSA44.sk_len == 2560 and MLDSA44.sig_len == 2420
assert MLDSA65.pk_len == 1952 and MLDSA65.sk_len == 4032 and MLDSA65.sig_len == 3309
assert MLDSA87.pk_len == 2592 and MLDSA87.sk_len == 4896 and MLDSA87.sig_len == 4627


def shake256(data: bytes, n: int) -> bytes:
    return hashlib.shake_256(data).digest(n)


def shake128(data: bytes, n: int) -> bytes:
    return hashlib.shake_128(data).digest(n)


# -- NTT (complete 256-point, FIPS 204 §7.5) --------------------------------

def _bitrev8(i: int) -> int:
    return int(f"{i:08b}"[::-1], 2)


ZETAS = [pow(ZETA, _bitrev8(i), Q) for i in range(256)]
_N_INV = pow(256, -1, Q)


def ntt(f: list[int]) -> list[int]:
    f = list(f)
    k = 0
    length = 128
    while length >= 1:
        for start in range(0, N, 2 * length):
            k += 1
            zeta = ZETAS[k]
            for j in range(start, start + length):
                t = (zeta * f[j + length]) % Q
                f[j + length] = (f[j] - t) % Q
                f[j] = (f[j] + t) % Q
        length //= 2
    return f


def ntt_inv(fh: list[int]) -> list[int]:
    f = list(fh)
    k = 256
    length = 1
    while length <= 128:
        for start in range(0, N, 2 * length):
            k -= 1
            zeta = ZETAS[k]
            for j in range(start, start + length):
                t = f[j]
                f[j] = (t + f[j + length]) % Q
                f[j + length] = (zeta * (f[j + length] - t)) % Q
        length *= 2
    return [(x * _N_INV) % Q for x in f]


def pw_mul(a: list[int], b: list[int]) -> list[int]:
    return [(x * y) % Q for x, y in zip(a, b)]


def poly_add(a, b):
    return [(x + y) % Q for x, y in zip(a, b)]


def poly_sub(a, b):
    return [(x - y) % Q for x, y in zip(a, b)]


def _center(x: int, m: int = Q) -> int:
    """mod± : representative in (-m/2, m/2]."""
    x %= m
    return x - m if x > m // 2 else x


def inf_norm(poly: list[int]) -> int:
    return max(abs(_center(c)) for c in poly)


# -- rounding (FIPS 204 §7.4) ----------------------------------------------

def power2round(r: int) -> tuple[int, int]:
    r %= Q
    r0 = _center(r, 1 << D)
    return (r - r0) >> D, r0


def decompose(p: MLDSAParams, r: int) -> tuple[int, int]:
    alpha = 2 * p.gamma2
    r %= Q
    r0 = _center(r, alpha)
    if r - r0 == Q - 1:
        return 0, r0 - 1
    return (r - r0) // alpha, r0


def high_bits(p: MLDSAParams, r: int) -> int:
    return decompose(p, r)[0]


def low_bits(p: MLDSAParams, r: int) -> int:
    return decompose(p, r)[1]


def make_hint(p: MLDSAParams, z: int, r: int) -> int:
    return int(high_bits(p, r + z) != high_bits(p, r))


def use_hint(p: MLDSAParams, h: int, r: int) -> int:
    m = (Q - 1) // (2 * p.gamma2)
    r1, r0 = decompose(p, r)
    if h:
        return (r1 + 1) % m if r0 > 0 else (r1 - 1) % m
    return r1


# -- bit packing (FIPS 204 §7.1) --------------------------------------------

def simple_bit_pack(coeffs: list[int], bits: int) -> bytes:
    out = bytearray(32 * bits)
    pos = 0
    for c in coeffs:
        for j in range(bits):
            out[pos >> 3] |= ((c >> j) & 1) << (pos & 7)
            pos += 1
    return bytes(out)


def simple_bit_unpack(b: bytes, bits: int) -> list[int]:
    coeffs = []
    for i in range(N):
        a = 0
        for j in range(bits):
            pos = i * bits + j
            a |= ((b[pos >> 3] >> (pos & 7)) & 1) << j
        coeffs.append(a)
    return coeffs


def bit_pack(coeffs: list[int], up: int, bits: int) -> bytes:
    """Pack coeffs in [-(2^bits - 1 - up)... ] as ``up - c`` in ``bits`` bits."""
    return simple_bit_pack([(up - _center(c)) for c in coeffs], bits)


def bit_unpack(b: bytes, up: int, bits: int) -> list[int]:
    return [(up - v) % Q for v in simple_bit_unpack(b, bits)]


def hint_bit_pack(p: MLDSAParams, h: list[list[int]]) -> bytes:
    out = bytearray(p.omega + p.k)
    idx = 0
    for i in range(p.k):
        for j in range(N):
            if h[i][j]:
                out[idx] = j
                idx += 1
        out[p.omega + i] = idx
    return bytes(out)


def hint_bit_unpack(p: MLDSAParams, b: bytes) -> list[list[int]] | None:
    h = [[0] * N for _ in range(p.k)]
    idx = 0
    for i in range(p.k):
        end = b[p.omega + i]
        if end < idx or end > p.omega:
            return None
        first = True
        prev = -1
        while idx < end:
            j = b[idx]
            if not first and j <= prev:
                return None  # positions must be strictly increasing
            h[i][j] = 1
            prev = j
            first = False
            idx += 1
    if any(b[i] != 0 for i in range(idx, p.omega)):
        return None
    return h


# -- samplers (FIPS 204 §7.3) -----------------------------------------------

def rej_ntt_poly(seed: bytes) -> list[int]:
    buf = shake128(seed, 168 * 7)
    out = []
    pos = 0
    while len(out) < N:
        t = buf[pos] | (buf[pos + 1] << 8) | ((buf[pos + 2] & 0x7F) << 16)
        pos += 3
        if t < Q:
            out.append(t)
    return out


def rej_bounded_poly(eta: int, seed: bytes) -> list[int]:
    buf = shake256(seed, 136 * 4)
    out = []
    for byte in buf:
        for z in (byte & 0xF, byte >> 4):
            if len(out) == N:
                return out
            if eta == 2 and z < 15:
                out.append((2 - z % 5) % Q)
            elif eta == 4 and z < 9:
                out.append((4 - z) % Q)
    raise RuntimeError("rej_bounded_poly buffer exhausted")


def expand_a(p: MLDSAParams, rho: bytes) -> list[list[list[int]]]:
    return [
        [rej_ntt_poly(rho + bytes([s, r])) for s in range(p.l)]
        for r in range(p.k)
    ]


def expand_s(p: MLDSAParams, rhop: bytes) -> tuple[list, list]:
    s1 = [rej_bounded_poly(p.eta, rhop + n.to_bytes(2, "little")) for n in range(p.l)]
    s2 = [
        rej_bounded_poly(p.eta, rhop + (p.l + n).to_bytes(2, "little"))
        for n in range(p.k)
    ]
    return s1, s2


def expand_mask(p: MLDSAParams, rhop: bytes, kappa: int) -> list[list[int]]:
    y = []
    for r in range(p.l):
        buf = shake256(rhop + (kappa + r).to_bytes(2, "little"), 32 * p.z_bits)
        y.append(bit_unpack(buf, p.gamma1, p.z_bits))
    return y


def sample_in_ball(p: MLDSAParams, ctilde: bytes) -> list[int]:
    buf = hashlib.shake_256(ctilde).digest(8 + 1024)
    signs = int.from_bytes(buf[:8], "little")
    c = [0] * N
    pos = 8
    for i in range(N - p.tau, N):
        while True:
            j = buf[pos]
            pos += 1
            if j <= i:
                break
        c[i] = c[j]
        c[j] = (1 if (signs & 1) == 0 else Q - 1)
        signs >>= 1
    return c


# -- vector/matrix helpers ---------------------------------------------------

def _matvec(a_hat, vec_hat, k, l):
    out = []
    for r in range(k):
        acc = [0] * N
        for s in range(l):
            acc = poly_add(acc, pw_mul(a_hat[r][s], vec_hat[s]))
        out.append(acc)
    return out


# -- keygen / sign / verify (FIPS 204 §6, internal forms) --------------------

def keygen(p: MLDSAParams, xi: bytes) -> tuple[bytes, bytes]:
    """Algorithm 6 ML-DSA.KeyGen_internal: 32-byte seed -> (pk, sk)."""
    seed = shake256(xi + bytes([p.k, p.l]), 128)
    rho, rhop, cap_k = seed[:32], seed[32:96], seed[96:]
    a_hat = expand_a(p, rho)
    s1, s2 = expand_s(p, rhop)
    s1_hat = [ntt(x) for x in s1]
    t = [
        poly_add(ntt_inv(poly), s2[r])
        for r, poly in enumerate(_matvec(a_hat, s1_hat, p.k, p.l))
    ]
    t1 = [[0] * N for _ in range(p.k)]
    t0 = [[0] * N for _ in range(p.k)]
    for r in range(p.k):
        for j in range(N):
            t1[r][j], t0[r][j] = power2round(t[r][j])
    pk = rho + b"".join(simple_bit_pack(t1[r], 23 - D) for r in range(p.k))
    tr = shake256(pk, 64)
    sk = (
        rho
        + cap_k
        + tr
        + b"".join(bit_pack(s, p.eta, p.s_bits) for s in s1)
        + b"".join(bit_pack(s, p.eta, p.s_bits) for s in s2)
        + b"".join(bit_pack(t, 1 << (D - 1), D) for t in t0)
    )
    return pk, sk


def _unpack_sk(p: MLDSAParams, sk: bytes):
    rho, cap_k, tr = sk[:32], sk[32:64], sk[64:128]
    off = 128
    sb = 32 * p.s_bits
    s1 = [bit_unpack(sk[off + i * sb : off + (i + 1) * sb], p.eta, p.s_bits) for i in range(p.l)]
    off += p.l * sb
    s2 = [bit_unpack(sk[off + i * sb : off + (i + 1) * sb], p.eta, p.s_bits) for i in range(p.k)]
    off += p.k * sb
    tb = 32 * D
    t0 = [
        bit_unpack(sk[off + i * tb : off + (i + 1) * tb], 1 << (D - 1), D)
        for i in range(p.k)
    ]
    return rho, cap_k, tr, s1, s2, t0


def sign_internal(p: MLDSAParams, sk: bytes, m_prime: bytes, rnd: bytes = b"\0" * 32) -> bytes:
    """Algorithm 7 ML-DSA.Sign_internal (rnd = zeros -> deterministic variant)."""
    rho, cap_k, tr, s1, s2, t0 = _unpack_sk(p, sk)
    a_hat = expand_a(p, rho)
    s1_hat = [ntt(x) for x in s1]
    s2_hat = [ntt(x) for x in s2]
    t0_hat = [ntt(x) for x in t0]
    mu = shake256(tr + m_prime, 64)
    rhopp = shake256(cap_k + rnd + mu, 64)
    kappa = 0
    while True:
        y = expand_mask(p, rhopp, kappa)
        kappa += p.l
        y_hat = [ntt(x) for x in y]
        w = [ntt_inv(poly) for poly in _matvec(a_hat, y_hat, p.k, p.l)]
        w1 = [[high_bits(p, c) for c in poly] for poly in w]
        w1_enc = b"".join(simple_bit_pack(poly, p.w1_bits) for poly in w1)
        ctilde = shake256(mu + w1_enc, p.ctilde_len)
        c = sample_in_ball(p, ctilde)
        c_hat = ntt(c)
        z = [
            poly_add(y[s], ntt_inv(pw_mul(c_hat, s1_hat[s])))
            for s in range(p.l)
        ]
        if max(inf_norm(poly) for poly in z) >= p.gamma1 - p.beta:
            continue
        cs2 = [ntt_inv(pw_mul(c_hat, s2_hat[r])) for r in range(p.k)]
        r_minus = [poly_sub(w[r], cs2[r]) for r in range(p.k)]
        r0_norm = max(
            max(abs(_center(low_bits(p, cc))) for cc in poly) for poly in r_minus
        )
        if r0_norm >= p.gamma2 - p.beta:
            continue
        ct0 = [ntt_inv(pw_mul(c_hat, t0_hat[r])) for r in range(p.k)]
        if max(inf_norm(poly) for poly in ct0) >= p.gamma2:
            continue
        h = [
            [
                make_hint(p, -_center(ct0[r][j]), _center(r_minus[r][j]) + _center(ct0[r][j]))
                for j in range(N)
            ]
            for r in range(p.k)
        ]
        if sum(sum(poly) for poly in h) > p.omega:
            continue
        return (
            ctilde
            + b"".join(bit_pack(poly, p.gamma1, p.z_bits) for poly in z)
            + hint_bit_pack(p, h)
        )


def verify_internal(p: MLDSAParams, pk: bytes, m_prime: bytes, sigma: bytes) -> bool:
    """Algorithm 8 ML-DSA.Verify_internal."""
    if len(sigma) != p.sig_len or len(pk) != p.pk_len:
        return False
    rho = pk[:32]
    t1 = [
        simple_bit_unpack(pk[32 + r * 320 : 32 + (r + 1) * 320], 23 - D)
        for r in range(p.k)
    ]
    ctilde = sigma[: p.ctilde_len]
    zb = 32 * p.z_bits
    off = p.ctilde_len
    z = [bit_unpack(sigma[off + s * zb : off + (s + 1) * zb], p.gamma1, p.z_bits) for s in range(p.l)]
    h = hint_bit_unpack(p, sigma[off + p.l * zb :])
    if h is None:
        return False
    if max(inf_norm(poly) for poly in z) >= p.gamma1 - p.beta:
        return False
    a_hat = expand_a(p, rho)
    tr = shake256(pk, 64)
    mu = shake256(tr + m_prime, 64)
    c = sample_in_ball(p, ctilde)
    c_hat = ntt(c)
    z_hat = [ntt(x) for x in z]
    az = _matvec(a_hat, z_hat, p.k, p.l)
    w_approx = []
    for r in range(p.k):
        t1_shift = [(coef << D) % Q for coef in t1[r]]
        ct1 = pw_mul(c_hat, ntt(t1_shift))
        w_approx.append(ntt_inv(poly_sub(az[r], ct1)))
    w1 = [
        b"".join(
            simple_bit_pack([use_hint(p, h[r][j], w_approx[r][j]) for j in range(N)], p.w1_bits)
            for r in range(p.k)
        )
    ][0]
    return ctilde == shake256(mu + w1, p.ctilde_len)


# -- external API (ctx-string form, FIPS 204 Algorithms 2-3) -----------------

def sign(p: MLDSAParams, sk: bytes, message: bytes, ctx: bytes = b"",
         rnd: bytes = b"\0" * 32) -> bytes:
    if len(ctx) > 255:
        raise ValueError("context too long")
    m_prime = bytes([0, len(ctx)]) + ctx + message
    return sign_internal(p, sk, m_prime, rnd)


def verify(p: MLDSAParams, pk: bytes, message: bytes, sigma: bytes, ctx: bytes = b"") -> bool:
    if len(ctx) > 255:
        return False
    m_prime = bytes([0, len(ctx)]) + ctx + message
    try:
        return verify_internal(p, pk, m_prime, sigma)
    except Exception:  # qrlint: disable=broad-except  — FIPS 204 verify contract: any malformed signature/key decodes to False, never an exception
        return False
