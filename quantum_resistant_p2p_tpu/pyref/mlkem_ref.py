"""Pure-Python ML-KEM (FIPS 203) — clean-room reference implementation.

Written directly from the FIPS 203 specification (Algorithms 13-21), with
``hashlib`` supplying SHA3-256/512 and SHAKE-128/256.  Used as the
bit-exactness oracle for the batched JAX implementation in
``quantum_resistant_p2p_tpu.kem.mlkem`` and as the CPU provider backend
(the role liboqs ML-KEM plays for the reference app's
``crypto/key_exchange.py:57-186`` MLKEMKeyExchange).

All functions are deterministic: randomness (d, z, m) is an explicit input,
which is exactly the seam FIPS 203 defines (and what liboqs's deterministic
KAT entry points expose), so the same seeds drive both implementations.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

Q = 3329
N = 256


@dataclass(frozen=True)
class MLKEMParams:
    name: str
    k: int
    eta1: int
    eta2: int
    du: int
    dv: int

    @property
    def ek_len(self) -> int:
        return 384 * self.k + 32

    @property
    def dk_len(self) -> int:
        return 768 * self.k + 96

    @property
    def ct_len(self) -> int:
        return 32 * (self.du * self.k + self.dv)


MLKEM512 = MLKEMParams("ML-KEM-512", k=2, eta1=3, eta2=2, du=10, dv=4)
MLKEM768 = MLKEMParams("ML-KEM-768", k=3, eta1=2, eta2=2, du=10, dv=4)
MLKEM1024 = MLKEMParams("ML-KEM-1024", k=4, eta1=2, eta2=2, du=11, dv=5)

PARAMS = {p.name: p for p in (MLKEM512, MLKEM768, MLKEM1024)}


# -- hashes (FIPS 203 §4.1) -------------------------------------------------

def G(data: bytes) -> bytes:
    return hashlib.sha3_512(data).digest()


def H(data: bytes) -> bytes:
    return hashlib.sha3_256(data).digest()


def J(data: bytes) -> bytes:
    return hashlib.shake_256(data).digest(32)


def prf(eta: int, s: bytes, b: int) -> bytes:
    return hashlib.shake_256(s + bytes([b])).digest(64 * eta)


# -- NTT (FIPS 203 §4.3) ----------------------------------------------------

def _bitrev7(i: int) -> int:
    return int(f"{i:07b}"[::-1], 2)


ZETAS = [pow(17, _bitrev7(i), Q) for i in range(128)]
GAMMAS = [pow(17, 2 * _bitrev7(i) + 1, Q) for i in range(128)]
_N_INV = pow(128, -1, Q)  # 3303


def ntt(f: list[int]) -> list[int]:
    f = list(f)
    k = 1
    length = 128
    while length >= 2:
        for start in range(0, N, 2 * length):
            zeta = ZETAS[k]
            k += 1
            for j in range(start, start + length):
                t = (zeta * f[j + length]) % Q
                f[j + length] = (f[j] - t) % Q
                f[j] = (f[j] + t) % Q
        length //= 2
    return f


def ntt_inv(fh: list[int]) -> list[int]:
    f = list(fh)
    k = 127
    length = 2
    while length <= 128:
        for start in range(0, N, 2 * length):
            zeta = ZETAS[k]
            k -= 1
            for j in range(start, start + length):
                t = f[j]
                f[j] = (t + f[j + length]) % Q
                f[j + length] = (zeta * (f[j + length] - t)) % Q
        length *= 2
    return [(x * _N_INV) % Q for x in f]


def multiply_ntts(f: list[int], g: list[int]) -> list[int]:
    h = [0] * N
    for i in range(128):
        a0, a1 = f[2 * i], f[2 * i + 1]
        b0, b1 = g[2 * i], g[2 * i + 1]
        h[2 * i] = (a0 * b0 + a1 * b1 % Q * GAMMAS[i]) % Q
        h[2 * i + 1] = (a0 * b1 + a1 * b0) % Q
    return h


def poly_add(f: list[int], g: list[int]) -> list[int]:
    return [(a + b) % Q for a, b in zip(f, g)]


def poly_sub(f: list[int], g: list[int]) -> list[int]:
    return [(a - b) % Q for a, b in zip(f, g)]


# -- sampling (FIPS 203 §4.2.2) ---------------------------------------------

def sample_ntt(seed34: bytes) -> list[int]:
    """Algorithm 7: rejection-sample a polynomial in NTT domain from XOF."""
    # hashlib's shake is one-shot; squeeze a buffer large enough that running
    # out has negligible probability (448+ candidates for 256 needed).
    buf = hashlib.shake_128(seed34).digest(168 * 6)
    out: list[int] = []
    pos = 0
    while len(out) < N:
        d1 = buf[pos] + 256 * (buf[pos + 1] % 16)
        d2 = (buf[pos + 1] // 16) + 16 * buf[pos + 2]
        pos += 3
        if d1 < Q:
            out.append(d1)
        if d2 < Q and len(out) < N:
            out.append(d2)
    return out


def sample_poly_cbd(eta: int, b: bytes) -> list[int]:
    """Algorithm 8: centered binomial distribution from 64*eta bytes."""
    bits = [(byte >> k) & 1 for byte in b for k in range(8)]
    f = []
    for i in range(N):
        x = sum(bits[2 * i * eta + j] for j in range(eta))
        y = sum(bits[2 * i * eta + eta + j] for j in range(eta))
        f.append((x - y) % Q)
    return f


# -- codecs (FIPS 203 §4.2.1) -----------------------------------------------

def byte_encode(d: int, f: list[int]) -> bytes:
    out = bytearray(32 * d)
    bit = 0
    for a in f:
        for j in range(d):
            out[bit >> 3] |= ((a >> j) & 1) << (bit & 7)
            bit += 1
    return bytes(out)


def byte_decode(d: int, b: bytes) -> list[int]:
    m = Q if d == 12 else (1 << d)
    f = []
    for i in range(N):
        a = 0
        for j in range(d):
            bit = i * d + j
            a |= ((b[bit >> 3] >> (bit & 7)) & 1) << j
        f.append(a % m)
    return f


def compress(d: int, x: int) -> int:
    return ((2 * (x << d) + Q) // (2 * Q)) % (1 << d)


def decompress(d: int, y: int) -> int:
    return (y * Q + (1 << (d - 1))) >> d


# -- K-PKE (FIPS 203 §5) ----------------------------------------------------

def kpke_keygen(p: MLKEMParams, d: bytes) -> tuple[bytes, bytes]:
    rho, sigma = G(d + bytes([p.k]))[:32], G(d + bytes([p.k]))[32:]
    a_hat = [[sample_ntt(rho + bytes([j, i])) for j in range(p.k)] for i in range(p.k)]
    n = 0
    s = []
    for _ in range(p.k):
        s.append(sample_poly_cbd(p.eta1, prf(p.eta1, sigma, n)))
        n += 1
    e = []
    for _ in range(p.k):
        e.append(sample_poly_cbd(p.eta1, prf(p.eta1, sigma, n)))
        n += 1
    s_hat = [ntt(x) for x in s]
    e_hat = [ntt(x) for x in e]
    t_hat = []
    for i in range(p.k):
        acc = e_hat[i]
        for j in range(p.k):
            acc = poly_add(acc, multiply_ntts(a_hat[i][j], s_hat[j]))
        t_hat.append(acc)
    ek = b"".join(byte_encode(12, t) for t in t_hat) + rho
    dk = b"".join(byte_encode(12, sh) for sh in s_hat)
    return ek, dk


def kpke_encrypt(p: MLKEMParams, ek: bytes, m: bytes, r: bytes) -> bytes:
    t_hat = [byte_decode(12, ek[384 * i : 384 * (i + 1)]) for i in range(p.k)]
    rho = ek[384 * p.k :]
    a_hat = [[sample_ntt(rho + bytes([j, i])) for j in range(p.k)] for i in range(p.k)]
    n = 0
    y = []
    for _ in range(p.k):
        y.append(sample_poly_cbd(p.eta1, prf(p.eta1, r, n)))
        n += 1
    e1 = []
    for _ in range(p.k):
        e1.append(sample_poly_cbd(p.eta2, prf(p.eta2, r, n)))
        n += 1
    e2 = sample_poly_cbd(p.eta2, prf(p.eta2, r, n))
    y_hat = [ntt(x) for x in y]
    u = []
    for i in range(p.k):
        acc = [0] * N
        for j in range(p.k):
            acc = poly_add(acc, multiply_ntts(a_hat[j][i], y_hat[j]))  # A^T
        u.append(poly_add(ntt_inv(acc), e1[i]))
    mu = [decompress(1, bit) for bit in byte_decode(1, m)]
    acc = [0] * N
    for j in range(p.k):
        acc = poly_add(acc, multiply_ntts(t_hat[j], y_hat[j]))
    v = poly_add(poly_add(ntt_inv(acc), e2), mu)
    c1 = b"".join(byte_encode(p.du, [compress(p.du, x) for x in ui]) for ui in u)
    c2 = byte_encode(p.dv, [compress(p.dv, x) for x in v])
    return c1 + c2


def kpke_decrypt(p: MLKEMParams, dk: bytes, c: bytes) -> bytes:
    du_bytes = 32 * p.du
    u = [
        [decompress(p.du, y) for y in byte_decode(p.du, c[du_bytes * i : du_bytes * (i + 1)])]
        for i in range(p.k)
    ]
    v = [decompress(p.dv, y) for y in byte_decode(p.dv, c[du_bytes * p.k :])]
    s_hat = [byte_decode(12, dk[384 * i : 384 * (i + 1)]) for i in range(p.k)]
    acc = [0] * N
    for i in range(p.k):
        acc = poly_add(acc, multiply_ntts(s_hat[i], ntt(u[i])))
    w = poly_sub(v, ntt_inv(acc))
    return byte_encode(1, [compress(1, x) for x in w])


# -- ML-KEM (FIPS 203 §6-7) -------------------------------------------------

def keygen(p: MLKEMParams, d: bytes, z: bytes) -> tuple[bytes, bytes]:
    """Algorithm 16 ML-KEM.KeyGen_internal: (ek, dk) from 32-byte seeds d, z."""
    ek, dk_pke = kpke_keygen(p, d)
    dk = dk_pke + ek + H(ek) + z
    return ek, dk


def encaps(p: MLKEMParams, ek: bytes, m: bytes) -> tuple[bytes, bytes]:
    """Algorithm 17 ML-KEM.Encaps_internal: (K, c) from ek and 32-byte m."""
    g = G(m + H(ek))
    key, r = g[:32], g[32:]
    c = kpke_encrypt(p, ek, m, r)
    return key, c


def decaps(p: MLKEMParams, dk: bytes, c: bytes) -> bytes:
    """Algorithm 18 ML-KEM.Decaps_internal with implicit rejection."""
    dk_pke = dk[: 384 * p.k]
    ek = dk[384 * p.k : 768 * p.k + 32]
    h = dk[768 * p.k + 32 : 768 * p.k + 64]
    z = dk[768 * p.k + 64 :]
    m2 = kpke_decrypt(p, dk_pke, c)
    g = G(m2 + h)
    key2, r2 = g[:32], g[32:]
    key_bar = J(z + c)
    c2 = kpke_encrypt(p, ek, m2, r2)
    return key2 if c == c2 else key_bar
