"""Interactive CLI — capability parity with the reference's PyQt5 UI.

The reference ships a Qt desktop app (ui/main_window.py and 7 dialogs,
SURVEY.md §2 rows 17-27).  This framework keeps the identical operations
surface as a terminal client (login gate, peer list, chat, file transfer,
crypto settings + adopt-peer-settings, security metrics, encrypted log
viewer, key history with audited decrypt, password change, destructive
reset), driven by slash-commands over an asyncio stdin reader fused with the
node's event loop — the same loop-fusion role qasync plays in the reference
(__main__.py:82-83 there).
"""

from __future__ import annotations

import asyncio
import getpass
import json
import logging
import shlex
import sys
import time
from pathlib import Path

from .app.message_store import Message, MessageStore
from .app.messaging import SecureMessaging
from .net.discovery import NodeDiscovery
from .net.identity import load_or_generate_node_id
from .net.p2p_node import P2PNode
from .provider import list_kems, list_signatures, list_symmetrics
from .storage.key_storage import KeyStorage, get_app_data_dir
from .storage.secure_logger import SecureLogger

logger = logging.getLogger(__name__)


def _parse_time_point(text: str) -> float:
    """One /logs time arg -> epoch seconds.

    Accepts relative durations ago ("30m", "2h", "1d"), "HH:MM" (today,
    local), or an ISO "YYYY-MM-DD[THH:MM[:SS]]" stamp.
    """
    import datetime as _dt

    units = {"s": 1, "m": 60, "h": 3600, "d": 86400}
    if len(text) >= 2 and text[-1] in units and text[:-1].isdigit():
        return time.time() - int(text[:-1]) * units[text[-1]]
    if ":" in text and "-" not in text:
        today = _dt.datetime.now().strftime("%Y-%m-%d")
        return _dt.datetime.fromisoformat(f"{today}T{text}").timestamp()
    return _dt.datetime.fromisoformat(text).timestamp()


def _parse_time_range(args: list[str]):
    """Split ``--since T`` / ``--until T`` out of a /logs arg list."""
    start_t = end_t = None
    rest: list[str] = []
    i = 0
    while i < len(args):
        if args[i] in ("--since", "--until"):
            if i + 1 >= len(args):
                raise ValueError(f"{args[i]} needs a time argument (30m, HH:MM, ISO)")
            t = _parse_time_point(args[i + 1])
            if args[i] == "--since":
                start_t = t
            else:
                end_t = t
            i += 2
        else:
            rest.append(args[i])
            i += 1
    return start_t, end_t, rest

HELP = """\
commands:
  /peers                     list discovered + connected peers
  /connect <host> [port]     connect to a peer (default port 8000)
  /key <peer>                establish a shared key (handshake)
  /send <peer> <text...>     send an encrypted message
  /sendfile <peer> <path>    send a file
  /settings                  show current + available algorithms
  /set kem|aead|sig <name>   hot-swap an algorithm
  /adopt <peer>              adopt the peer's gossiped settings
  /metrics [prom]            security + operational metrics (queues, breaker,
                             trips, resilience counters; "prom" prints the
                             Prometheus text exposition instead)
  /slo                       SLO burn-rate report: per-objective fast/slow
                             burn, error budget remaining, alert state
  /trace [path]              export recent spans as chrome://tracing JSON
                             (load in chrome://tracing or ui.perfetto.dev)
  /flight [path]             dump the flight-recorder diagnostic bundle
                             (recent redacted events + metrics snapshot)
  /logs [type] [n] [--since T] [--until T]
                             decrypted audit log (latest n, default 20;
                             T: 30m/2h/1d relative, HH:MM, or ISO date)
  /clearlogs                 delete all audit logs
  /keyhistory [peer]         list stored shared-key history entries
  /showkey <entry> [fmt]     decrypt + display a stored key (audited,
                             confirmation required; fmt: hex|base64|decimal)
  /delkey <entry>            delete one key-history entry
  /clearhistory              delete ALL key-history entries
  /passwd                    change the vault password
  /reset                     DESTRUCTIVE vault reset
  /batchstats                TPU batch-queue statistics (if batching on)
  /quit                      exit
"""


class CLI:
    """Command processor; separable from stdin so tests can drive it."""

    def __init__(
        self,
        vault_path: str | None = None,
        port: int = 8000,
        backend: str = "cpu",
        use_batching: bool = False,
        mesh_devices: int = 0,
        enable_discovery: bool = True,
        telemetry_port: int | None = None,
        out=sys.stdout,
    ):
        self.out = out
        self.port = port
        self.backend = backend
        self.use_batching = use_batching
        self.mesh_devices = mesh_devices
        self.telemetry_port = telemetry_port
        self.enable_discovery = enable_discovery
        self.storage = KeyStorage(vault_path)
        self.node: P2PNode | None = None
        self.discovery: NodeDiscovery | None = None
        self.messaging: SecureMessaging | None = None
        self.secure_logger: SecureLogger | None = None
        self.store = MessageStore()
        self._stop = asyncio.Event()
        self._reader: asyncio.StreamReader | None = None

    # ---------------------------------------------------------------- output

    def print(self, *args) -> None:
        print(*args, file=self.out)

    # ----------------------------------------------------------------- login

    def login(self, password: str) -> bool:
        """Unlock-or-initialise the vault (reference: ui/login_dialog.py:92-138)."""
        return self.storage.unlock(password)

    def login_interactive(self) -> bool:
        for attempt in range(3):
            pw = getpass.getpass("vault password: ")
            if self.login(pw):
                return True
            self.print("unlock failed — wrong password or corrupt vault")
        return False

    # ----------------------------------------------------------------- start

    async def start(self) -> None:
        assert self.storage.is_unlocked, "login first"
        log_key = self.storage.get_or_create_purpose_key("secure_logger")
        self.secure_logger = SecureLogger(log_key)
        node_id = load_or_generate_node_id(self.storage)
        self.node = P2PNode(node_id=node_id, host="0.0.0.0", port=self.port)
        await self.node.start()
        if self.enable_discovery:
            self.discovery = NodeDiscovery(node_id, tcp_port=self.node.port)
            await self.discovery.start()
        self.messaging = SecureMessaging(
            self.node,
            key_storage=self.storage,
            secure_logger=self.secure_logger,
            backend=self.backend,
            use_batching=self.use_batching,
            mesh_devices=self.mesh_devices,
            telemetry_port=self.telemetry_port,
        )
        self.messaging.register_message_listener(self._on_message)
        if self.messaging.telemetry_port is not None:
            self.print(f"telemetry endpoints on "
                       f"http://127.0.0.1:{self.messaging.telemetry_port} "
                       "(/metrics /healthz /readyz /slo /trace /cost)")
        self.secure_logger.log_event("initialization", node_id=node_id, port=self.node.port)
        # Explicit native-core availability, the role of the reference's
        # status-bar OQS chip (ui/oqs_status_widget.py:29-31).  load() may
        # run a first-launch g++ build, so keep it off the event loop — the
        # TCP server and discovery are already serving.
        def _probe_native() -> str:
            try:
                from . import native

                if native.load() is not None:
                    return "native C++ core: ✓"
            except Exception:
                # The fallback banner already tells the user; keep the cause
                # findable instead of silently discarding it.
                logger.debug("native core probe failed", exc_info=True)
            return "native C++ core: ✗ (pure-Python fallback)"

        core = await asyncio.get_running_loop().run_in_executor(None, _probe_native)
        self.print(f"node {node_id[:12]}… listening on :{self.node.port} "
                   f"(backend={self.backend}, batching={self.use_batching}, {core})")

    async def stop(self) -> None:
        if self.messaging:
            self.messaging.stop_telemetry()
        if self.discovery:
            await self.discovery.stop()
        if self.node:
            await self.node.stop()
        if self.secure_logger:
            # Key hygiene: the log key must not outlive the session.
            self.secure_logger.zeroize()
        self._stop.set()

    def _on_message(self, peer_id: str, message: Message) -> None:
        self.store.add_message(peer_id, message, unread=True)
        if message.is_file:
            # Path(...).name strips directories — a peer-supplied filename like
            # "../../x" or an absolute path must not escape the received dir.
            safe_name = Path(message.filename or "file.bin").name or "file.bin"
            dest = get_app_data_dir() / "received" / safe_name
            dest.parent.mkdir(parents=True, exist_ok=True)
            dest.write_bytes(message.content)
            self.print(f"\n[{peer_id[:8]}] sent file {message.filename} "
                       f"({len(message.content)} bytes) -> {dest}")
        else:
            tag = "system" if message.is_system else peer_id[:8]
            self.print(f"\n[{tag}] {message.content.decode(errors='replace')}")

    # -------------------------------------------------------------- commands

    async def handle(self, line: str) -> bool:
        """Process one command line; returns False when the CLI should exit."""
        line = line.strip()
        if not line:
            return True
        if not line.startswith("/"):
            self.print("commands start with '/'; /help for a list")
            return True
        try:
            parts = shlex.split(line)
        except ValueError as e:
            self.print(f"parse error: {e}")
            return True
        cmd, args = parts[0].lower(), parts[1:]
        try:
            return await self._dispatch(cmd, args)
        except Exception as e:  # keep the REPL alive
            logger.exception("command failed")
            self.print(f"error: {e}")
            return True

    async def _dispatch(self, cmd: str, args: list[str]) -> bool:
        m = self.messaging
        if cmd in ("/help", "/?"):
            self.print(HELP)
        elif cmd == "/quit":
            await self.stop()
            return False
        elif cmd == "/peers":
            connected = set(self.node.get_peers())
            rows = []
            if self.discovery:
                for pid, info in self.discovery.get_discovered_nodes().items():
                    host, port = info["host"], info["port"]
                    status = "connected" if pid in connected else "discovered"
                    if m.verify_key_exchange_state(pid):
                        status = "secure"
                    match = m.settings_match(pid)
                    warn = " ⚠ settings mismatch" if match is False else ""
                    rows.append(f"  {pid[:12]}…  {host}:{port}  {status}{warn}"
                                f"  unread={self.store.get_unread_count(pid)}")
            for pid in connected:
                if not self.discovery or pid not in self.discovery.get_discovered_nodes():
                    status = "secure" if m.verify_key_exchange_state(pid) else "connected"
                    rows.append(f"  {pid[:12]}…  {status}"
                                f"  unread={self.store.get_unread_count(pid)}")
            self.print("\n".join(rows) if rows else "  (no peers)")
        elif cmd == "/connect":
            host = args[0]
            port = int(args[1]) if len(args) > 1 else 8000
            pid = await self.node.connect_to_peer(host, port)
            self.print(f"connected to {pid[:12]}…" if pid else "connect failed")
        elif cmd == "/key":
            ok = await m.initiate_key_exchange(self._peer(args[0]))
            self.print("shared key established" if ok else "key exchange failed")
        elif cmd == "/send":
            sent = await m.send_message(self._peer(args[0]), " ".join(args[1:]).encode())
            self.print("sent" if sent else "send failed")
        elif cmd == "/sendfile":
            sent = await m.send_file(self._peer(args[0]), Path(args[1]))
            self.print("sent" if sent else "send failed")
        elif cmd == "/settings":
            s = m.get_settings()
            self.print(f"current: kem={s['kem']} aead={s['aead']} sig={s['signature']}")
            self.print(f"kems: {', '.join(list_kems())}")
            self.print(f"aeads: {', '.join(list_symmetrics())}")
            self.print(f"signatures: {', '.join(list_signatures())}")
        elif cmd == "/set":
            kind, name = args[0], args[1]
            if kind == "kem":
                await m.set_key_exchange_algorithm(name)
            elif kind == "aead":
                await m.set_symmetric_algorithm(name)
            elif kind == "sig":
                await m.set_signature_algorithm(name)
            else:
                self.print("usage: /set kem|aead|sig <name>")
                return True
            self.print(f"{kind} -> {name}")
        elif cmd == "/adopt":
            ok = await m.adopt_peer_settings(self._peer(args[0]))
            self.print("adopted peer settings" if ok else "no gossiped settings for peer")
        elif cmd == "/metrics":
            if args and args[0] == "prom":
                # the SAME exposition path the HTTP GET /metrics endpoint
                # serves (obs/http.py) — one serializer, two surfaces
                from .obs.metrics import prometheus_text

                self.print(prometheus_text(m.registry))
            else:
                self.print(json.dumps(
                    {
                        "security": self.secure_logger.get_security_metrics(),
                        "operational": m.metrics(),
                    },
                    indent=2, default=str,
                ))
        elif cmd == "/slo":
            status = m.slo_status()
            self.print(json.dumps(status, indent=2, default=str))
            if status["alerting"]:
                self.print(f"ALERTING: {', '.join(status['alerting'])}")
        elif cmd == "/trace":
            from .obs import trace as obs_trace

            records = obs_trace.TRACER.snapshot()
            path = Path(args[0]) if args else (
                get_app_data_dir() / f"trace_{int(time.time())}.json"
            )

            def _export(records=records, path=path):
                # render + serialize + write all off-loop: at the ring cap
                # that is thousands of event dicts, and the loop is also
                # serving TCP peers
                path.write_text(json.dumps(obs_trace.to_chrome_trace(records)))

            await asyncio.get_running_loop().run_in_executor(None, _export)
            self.print(f"{len(records)} span(s) -> {path} "
                       "(load in chrome://tracing or ui.perfetto.dev)")
        elif cmd == "/flight":
            from .obs import flight as obs_flight

            path = Path(args[0]) if args else (
                get_app_data_dir() / f"flight_{int(time.time())}.json"
            )
            bundle = await asyncio.get_running_loop().run_in_executor(
                None, obs_flight.dump, "manual", path
            )
            self.print(f"{len(bundle['events'])} event(s) -> {path}")
        elif cmd == "/logs":
            # Filter surface of the reference's log viewer (event-type combo +
            # time-range pickers, ui/log_viewer_dialog.py:137-151) as args:
            #   /logs [type] [n] [--since T] [--until T]
            # T = relative (30m/2h/1d), HH:MM (today), or ISO date[Ttime].
            start_t, end_t, rest = _parse_time_range(args)
            etype = rest[0] if rest and not rest[0].isdigit() else None
            n = int(rest[-1]) if rest and rest[-1].isdigit() else 20
            events = self.secure_logger.get_events(
                event_type=etype, start_time=start_t, end_time=end_t
            )[-n:]
            for ev in events:
                ts = time.strftime("%H:%M:%S", time.localtime(ev.get("timestamp", 0)))
                fields = {k: v for k, v in ev.items() if k not in ("timestamp", "event_type")}
                self.print(f"  {ts} {ev.get('event_type')} {fields}")
            if not events:
                self.print("  (no events)")
        elif cmd == "/clearlogs":
            self.print(f"deleted {self.secure_logger.clear_logs()} log file(s)")
        elif cmd == "/keyhistory":
            entries = self.storage.list_key_history(args[0] if args else None)
            for e in entries:
                self.print(f"  {e['name']}  peer={e.get('peer_id', '?')[:12]}  "
                           f"algo={e.get('algo', '?')}")
            if not entries:
                self.print("  (none)")
        elif cmd == "/showkey":
            # Parity with the reference's key-history dialog: security
            # warning before decrypt, hex/base64/decimal display, every
            # access audited (ui/key_history_dialog.py:336-501).
            entry = args[0]
            fmt = args[1] if len(args) > 1 else "hex"
            if fmt not in ("hex", "base64", "decimal"):
                self.print("usage: /showkey <entry> [hex|base64|decimal]")
                return True
            self.print(
                "WARNING: displaying a decrypted key exposes secret material\n"
                "on screen and in terminal scrollback. Anyone who records it\n"
                "can decrypt past traffic protected by this key."
            )
            confirm = await self._prompt("type YES to decrypt and display: ")
            if confirm != "YES":
                self.secure_logger.log_event(
                    "key_history_access", entry=entry, granted=False
                )
                self.print("cancelled")
                return True
            v = self.storage.get_key_history_value(entry)
            self.secure_logger.log_event(
                "key_history_access", entry=entry, granted=True, found=v is not None
            )
            if v is None:
                self.print("not found")
            else:
                import base64

                raw = base64.b64decode(v["key"])  # save_peer_shared_key stores b64
                if fmt == "hex":
                    self.print(f"  hex: {raw.hex()}")  # qrlint: disable=flow-secret-format — /key IS the user-invoked decrypt-and-display command (YES-confirmed + audit-logged), parity with the reference's key-view dialog
                elif fmt == "base64":
                    self.print(f"  base64: {base64.b64encode(raw).decode()}")  # qrlint: disable=flow-secret-format — /key IS the user-invoked decrypt-and-display command (YES-confirmed + audit-logged)
                else:
                    self.print(f"  decimal: {' '.join(str(b) for b in raw)}")  # qrlint: disable=flow-secret-format — /key IS the user-invoked decrypt-and-display command (YES-confirmed + audit-logged)
        elif cmd == "/delkey":
            ok = self.storage.delete_key_history(args[0])
            self.secure_logger.log_event("key_history_changed", deleted=args[0], ok=ok)
            self.print("deleted" if ok else "not found")
        elif cmd == "/clearhistory":
            n = self.storage.clear_key_history()
            self.secure_logger.log_event("key_history_changed", cleared=n)
            self.print(f"deleted {n} entries")
        elif cmd == "/passwd":
            old = await self._getpass("old password: ")
            new = await self._getpass("new password: ")
            if new != await self._getpass("confirm: "):
                self.print("mismatch")
            elif self.storage.change_password(old, new):
                self.secure_logger.log_event("password_change")
                self.print("password changed")
            else:
                self.print("wrong password")
        elif cmd == "/reset":
            confirm = await self._prompt("type RESET to destroy the vault and start fresh: ")
            if confirm == "RESET":
                new = await self._getpass("new password: ")
                self.storage.reset_storage(new)
                self.print("vault reset")
            else:
                self.print("cancelled")
        elif cmd == "/batchstats":
            if m._bkem is None:
                self.print("batching disabled (start with --batch)")
            else:
                self.print(json.dumps({"kem": m._bkem.stats(), "sig": m._bsig.stats()},
                                      indent=2))
        else:
            self.print(f"unknown command {cmd}; /help for a list")
        return True

    async def _prompt(self, text: str) -> str:
        """Read one confirmation line.

        Inside the running REPL, stdin belongs to the asyncio reader
        (connect_read_pipe sets the fd non-blocking — a raw input() would
        raise BlockingIOError), so read through it; programmatic callers
        without a REPL get plain input().
        """
        if self._reader is not None:
            self.print(text)
            line = await self._reader.readline()
            return line.decode().strip()
        # No REPL reader: a blocking input() would stall every connected peer
        # (the loop also serves TCP); read it on a worker thread instead.
        line = await asyncio.get_running_loop().run_in_executor(None, input, text)
        return line.strip()

    async def _getpass(self, prompt: str) -> str:
        """Echo-free password read off the event loop (getpass blocks)."""
        return await asyncio.get_running_loop().run_in_executor(
            None, getpass.getpass, prompt
        )

    def _peer(self, prefix: str) -> str:
        """Resolve a peer-id prefix to a full id."""
        candidates = set(self.node.get_peers())
        if self.discovery:
            candidates |= set(self.discovery.get_discovered_nodes())
        matches = [p for p in candidates if p.startswith(prefix)]
        if len(matches) == 1:
            return matches[0]
        if not matches:
            return prefix  # allow full ids for not-yet-listed peers
        raise ValueError(f"ambiguous peer prefix {prefix!r}: {matches}")

    # ------------------------------------------------------------------ REPL

    async def repl(self) -> None:
        loop = asyncio.get_running_loop()
        reader = asyncio.StreamReader()
        await loop.connect_read_pipe(
            lambda: asyncio.StreamReaderProtocol(reader), sys.stdin
        )
        self._reader = reader
        self.print("type /help for commands")
        while not self._stop.is_set():
            line = await reader.readline()
            if not line:
                await self.stop()
                break
            if not await self.handle(line.decode()):
                break


def main(argv: list[str] | None = None) -> int:
    import argparse

    from .config import Config

    ap = argparse.ArgumentParser(prog="quantum_resistant_p2p_tpu")
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--vault", default=None, help="vault file path")
    ap.add_argument("--backend", choices=("cpu", "tpu", "auto"), default=None)
    ap.add_argument("--batch", action="store_true", help="enable the TPU batch queue")
    ap.add_argument("--mesh-devices", type=int, default=None,
                    help="shard TPU batches across this many chips (0 = one, -1 = all)")
    ap.add_argument("--telemetry-port", type=int, default=None,
                    help="serve live read-only telemetry endpoints on this "
                         "localhost port (0 = ephemeral; default off, or "
                         "QRP2P_HTTP_PORT)")
    ap.add_argument("--config", default=None, help="config file path")
    ap.add_argument("--no-discovery", action="store_true")
    ap.add_argument("--tui", action="store_true",
                    help="two-pane curses UI (live peer list + chat)")
    ap.add_argument("--log-level", default="INFO")
    args = ap.parse_args(argv)

    cfg = Config.load(
        path=args.config,
        port=args.port,
        backend=args.backend,
        use_batching=True if args.batch else None,
        mesh_devices=args.mesh_devices,
    )

    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(name)s %(levelname)s %(message)s",
        filename=str(get_app_data_dir() / "system.log"),
    )

    cli = CLI(
        vault_path=args.vault,
        port=cfg.port,
        backend=cfg.backend,
        use_batching=cfg.use_batching,
        mesh_devices=cfg.mesh_devices,
        enable_discovery=not args.no_discovery,
        telemetry_port=args.telemetry_port,
    )
    if not cli.login_interactive():
        return 1

    if args.tui:
        from .tui import run_tui

        try:
            run_tui(cli)
        except KeyboardInterrupt:
            pass
        return 0

    async def run():
        await cli.start()
        await cli.repl()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
