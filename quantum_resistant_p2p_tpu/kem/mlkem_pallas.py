"""Fused Pallas TPU kernel for ML-KEM's SampleNTT (FIPS 203 Algorithm 7).

Why: XLA cost analysis attributes ~85% of a batched encaps program's HBM
traffic to SampleNTT — 3.52 of 4.14 GB per 512-batch (2.4 GB of it the
bitonic compaction, the rest candidate extraction) — and the op is purely
memory-bound (bench_report.md roofline).  This kernel runs the ENTIRE
SampleNTT pipeline per seed — SHAKE-128 absorb, 4 squeeze permutations,
byte-triple candidate extraction, rejection-key packing, and the 512-wide
bitonic compaction network — inside one Pallas program with every
intermediate resident in VMEM.  HBM traffic per seed drops from ~7 MB to
~1.2 KB (21 input lane-words + 256 output coefficients).

Layout (same recipe as core/keccak_pallas.py): batch lives on the two minor
dimensions — each logical scalar (a Keccak lane word, one of the 512
candidate slots) is an ``(8, 128)`` uint32 tile spanning 1024 sponge
instances, so the whole pipeline is full-width VPU ops between named
registers.  The compaction uses :func:`core.sortnet.bitonic_sort_regs`,
whose compare-exchanges become static min/max pairs between resident tiles
(the array version's reshapes would cross the lane dimension, which Mosaic
penalises heavily).

Spec correspondence: identical output to kem/mlkem.py:sample_ntt (the
fixed-672-byte-squeeze formulation, P[shortfall] < 1e-38) — byte-for-byte
equality is asserted by tests/test_mlkem_pallas.py (kernel body, eagerly on
CPU) and was verified for the native pallas_call against the jnp path on
TPU v5e at B=1500.

Replaces (reference): the rejection-sampling loop inside liboqs ML-KEM
(vendor/oqs.py:310-390 reaches it via OQS_KEM_keypair/encaps/decaps).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core.keccak_pallas import _f1600, absorb_block, block_bytes, sampler_call
from ..core.sortnet import bitonic_sort_regs

Q = 3329
RATE_WORDS = 21  # SHAKE-128 rate: 168 bytes = 21 lanes
N_SQUEEZE = 4  # 4 * 168 = 672 bytes -> 448 candidates for 256 slots
N_CAND = 448
N_SORT = 512  # candidates padded to the next power of two
N_OUT = 256


def _sample_ntt_tiles(in_hi: list, in_lo: list) -> list:
    """The full SampleNTT pipeline over 21 input lane-word tiles.

    Pure function of same-shaped uint32 arrays -> 256 int32 arrays; the
    Pallas kernel calls it on VMEM-resident (8, 128) tiles, and the test
    suite calls it directly on plain arrays (interpret mode would execute
    the ~57k-op body orders of magnitude too slowly).
    """
    sh, sl = absorb_block(in_hi, in_lo, RATE_WORDS)

    # Squeeze 672 bytes; each byte triple (b0, b1, b2) yields two 12-bit
    # candidates d1 = b0 + 256*(b1 mod 16), d2 = (b1 // 16) + 16*b2.
    cand = []
    for blk in range(N_SQUEEZE):
        byts = block_bytes(sh, sl, RATE_WORDS)
        for t in range(len(byts) // 3):
            b0, b1, b2 = byts[3 * t], byts[3 * t + 1], byts[3 * t + 2]
            cand.append(b0 | ((b1 & 0xF) << 8))  # 12-bit bound machine-proved by qrkernel's interval analysis
            cand.append((b1 >> 4) | (b2 << 4))
        if blk + 1 < N_SQUEEZE:
            sh, sl = _f1600(sh, sl)
    assert len(cand) == N_CAND

    # Rejection keys: accepted (bit 21 clear) before rejected, candidate
    # order preserved via the index field, 12-bit value in the low bits —
    # bit-identical to kem/mlkem.py:sample_ntt's packing.  Keys fit in 23
    # bits, so the sort runs in int32 (Mosaic has no unsigned vector min).
    keys = [
        jnp.where(c < Q, 0, 1 << 21) | (i << 12) | c.astype(jnp.int32)
        for i, c in enumerate(cand)
    ]
    sentinel = jnp.full_like(keys[0], 1 << 22)
    keys += [sentinel] * (N_SORT - N_CAND)
    keys = bitonic_sort_regs(keys)
    return [keys[i] & 0xFFF for i in range(N_OUT)]


def _sample_ntt_kernel(in_hi_ref, in_lo_ref, out_ref):
    out = _sample_ntt_tiles(
        [in_hi_ref[w] for w in range(RATE_WORDS)],
        [in_lo_ref[w] for w in range(RATE_WORDS)],
    )
    for i in range(N_OUT):
        out_ref[i] = out[i]


@functools.partial(jax.jit, static_argnames=("interpret",))
def sample_ntt_words(in_hi: jax.Array, in_lo: jax.Array, *, interpret: bool = False):
    """Batched SampleNTT over word-transposed padded seed blocks.

    Args:
      in_hi/in_lo: (21, B) uint32 — the padded 168-byte XOF seed block
        (rho || j || i || 0x1F pad || 0x80) as hi/lo lane words, batch minor.

    Returns:
      (256, B) int32 NTT-domain polynomial coefficients in [0, q).
    """
    return sampler_call(_sample_ntt_kernel, RATE_WORDS, N_OUT, in_hi, in_lo,
                        interpret=interpret)


# --------------------------------------------------------------------------
# PRF + SamplePolyCBD (FIPS 203 Algorithms 7/8): SHAKE-256 -> CBD_eta poly
# --------------------------------------------------------------------------

CBD_RATE_WORDS = 17  # SHAKE-256 rate: 136 bytes = 17 lanes


def _cbd_tiles(in_hi: list, in_lo: list, eta: int) -> list:
    """PRF_eta + CBD_eta over 17 input lane-word tiles -> 256 coeff tiles.

    Squeezes 64*eta bytes (one block for eta=2, two for eta=3) and forms
    coefficient i from bit run [2*eta*i, 2*eta*(i+1)): sum of the first
    eta bits minus the sum of the second eta, mod q — the same byte-major
    LSB-first bit order as kem/mlkem.py:sample_poly_cbd.
    """
    sh, sl = absorb_block(in_hi, in_lo, CBD_RATE_WORDS)
    byts = block_bytes(sh, sl, CBD_RATE_WORDS)
    if 64 * eta > 8 * CBD_RATE_WORDS:  # eta=3: 192 bytes needs a second block
        sh, sl = _f1600(sh, sl)
        byts += block_bytes(sh, sl, CBD_RATE_WORDS)

    def bit(p: int):
        # int32 from the start: the x - y below must not wrap in uint32
        return ((byts[p // 8] >> (p % 8)) & 1).astype(jnp.int32)

    out = []
    for i in range(N_OUT):
        base = 2 * eta * i
        x = bit(base)
        for j in range(1, eta):
            x = x + bit(base + j)
        for j in range(eta):
            x = x - bit(base + eta + j)
        out.append(jnp.where(x < 0, x + Q, x))
    return out


def _cbd_kernel(in_hi_ref, in_lo_ref, out_ref, *, eta: int):
    out = _cbd_tiles(
        [in_hi_ref[w] for w in range(CBD_RATE_WORDS)],
        [in_lo_ref[w] for w in range(CBD_RATE_WORDS)],
        eta,
    )
    for i in range(N_OUT):
        out_ref[i] = out[i]


@functools.partial(jax.jit, static_argnames=("eta", "interpret"))
def cbd_words(in_hi: jax.Array, in_lo: jax.Array, *, eta: int,
              interpret: bool = False):
    """Batched PRF+CBD over word-transposed padded seed blocks.

    Args:
      in_hi/in_lo: (17, B) uint32 — the padded 136-byte PRF seed block
        (s || n || 0x1F pad || 0x80) as hi/lo lane words, batch minor.
      eta: 2 or 3 (static).

    Returns:
      (256, B) int32 CBD_eta coefficients in [0, q).
    """
    return sampler_call(functools.partial(_cbd_kernel, eta=eta),
                        CBD_RATE_WORDS, N_OUT, in_hi, in_lo, interpret=interpret)


# --------------------------------------------------------------------------
# NTT over Z_q[X]/(X^256+1), q = 3329 (FIPS 203 §4.3) — VMEM-resident
# --------------------------------------------------------------------------
#
# Same register-resident recipe as sig/mldsa_pallas.py:ntt_tiles, but the
# small modulus makes the butterflies cheaper: q^2 = 11_082_241 < 2**31, so
# a zeta product is ONE int32 multiply + remainder — no limb split.  The
# jnp formulation (kem/mlkem.py ntt/ntt_inv) materialises the full batched
# coefficient array between each of the 7 butterfly layers — 14 HBM
# round-trips per transform, and an encaps runs k NTTs + k+1 invNTTs.
# Here a poly's 256 coefficients are 256 (8, 128) int32 register tiles
# spanning 1024 lanes; HBM sees one read + one write per transform, and the
# fused CBD->NTT kernel below sees NONE (the CBD output never leaves VMEM).

from ..pyref.mlkem_ref import ZETAS as _ZETAS_PY

_N = 256
_N_INV = pow(128, -1, Q)  # 3303: ML-KEM's NTT has 128 base pairs, not 256 slots


def _mul_zeta(a, z: int):
    """(a * z) % Q for an int32 tile a in [0, q) and STATIC z in [0, q).

    q^2 < 2**31 so the product cannot overflow int32 (unlike ML-DSA's
    q = 8380417, which needs the Horner limb split) — the bound is
    machine-checked by qrkernel's interval analysis from the contracts."""
    # qrkernel: assume a in [0, Q) — FIPS 203 §4.3: butterfly operands are mod-q residues (every caller reduces % Q first)
    # qrkernel: assume z in [0, Q) — zeta table entries are powers of the 256th root of unity mod q
    return (a * z) % Q


def ntt_tiles(f: list) -> list:
    """256 int32 tiles in [0, q) -> NTT domain (bit-exact vs mlkem.ntt)."""
    f = list(f)
    k = 1
    length = 128
    while length >= 2:  # ML-KEM stops at length 2: 128 degree-1 residues
        groups = _N // (2 * length)
        for g in range(groups):
            z = int(_ZETAS_PY[k + g])
            base = g * 2 * length
            for j in range(length):
                i0, i1 = base + j, base + length + j
                t = _mul_zeta(f[i1], z)
                f[i0], f[i1] = (f[i0] + t) % Q, (f[i0] - t) % Q
        k += groups
        length //= 2
    return f


def ntt_inv_tiles(f: list) -> list:
    """Inverse transform; bit-exact vs mlkem.ntt_inv."""
    f = list(f)
    k = 127
    length = 2
    while length <= 128:
        groups = _N // (2 * length)
        zs = [int(_ZETAS_PY[k - groups + 1 + i]) for i in range(groups)][::-1]
        for g in range(groups):
            base = g * 2 * length
            for j in range(length):
                i0, i1 = base + j, base + length + j
                s = (f[i0] + f[i1]) % Q
                t = _mul_zeta((f[i1] - f[i0]) % Q, zs[g])
                f[i0], f[i1] = s, t
        k -= groups
        length *= 2
    return [_mul_zeta(x, _N_INV) for x in f]


def _ntt_kernel(in_ref, out_ref, *, inverse: bool):
    f = [in_ref[i] for i in range(_N)]
    out = ntt_inv_tiles(f) if inverse else ntt_tiles(f)
    for i in range(_N):
        out_ref[i] = out[i]


@functools.partial(jax.jit, static_argnames=("inverse", "interpret"))
def ntt_words(x: jax.Array, *, inverse: bool = False, interpret: bool = False):
    """Batched (inv)NTT over words layout.

    Args:
      x: (256, L) int32 coefficients in [0, q), lanes batch-minor (L is
        padded to the 1024-lane tile internally).

    Returns:
      (256, L) int32 transformed coefficients.
    """
    from jax.experimental import pallas as pl

    from ..core.keccak_pallas import _TL, _TS, BT

    n, l = x.shape
    assert n == _N
    lp = -(-l // BT) * BT
    if lp != l:
        x = jnp.pad(x, ((0, 0), (0, lp - l)))
    x = x.reshape(_N, lp // _TL, _TL)
    out = pl.pallas_call(
        functools.partial(_ntt_kernel, inverse=inverse),
        grid=(lp // BT,),
        in_specs=[pl.BlockSpec((_N, _TS, _TL), lambda i: (0, i, 0))],
        out_specs=pl.BlockSpec((_N, _TS, _TL), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((_N, lp // _TL, _TL), jnp.int32),
        interpret=interpret,
    )(x)
    return out.reshape(_N, lp)[:, :l]


# --------------------------------------------------------------------------
# Fused PRF + SamplePolyCBD + NTT: SHAKE-256 -> CBD_eta -> NTT, one kernel
# --------------------------------------------------------------------------
#
# The noise polynomials that feed matrix products (s, e at keygen; y at
# encrypt) are consumed ONLY in the NTT domain, so the separate cbd_words
# -> HBM -> ntt jnp-layer pipeline pays a full (256, B) round-trip plus 14
# layer materialisations for data that never needed to exist outside VMEM.
# This kernel squeezes the sponge, forms the CBD sums, and runs all 7
# butterfly layers on the register tiles before anything is written back.


def _cbd_ntt_tiles(in_hi: list, in_lo: list, eta: int) -> list:
    """PRF_eta + CBD_eta + NTT over 17 input lane-word tiles.

    Composition of the two tile pipelines above — _cbd_tiles' outputs are
    already reduced to [0, q), the domain ntt_tiles' contracts require."""
    return ntt_tiles(_cbd_tiles(in_hi, in_lo, eta))


def _cbd_ntt_kernel(in_hi_ref, in_lo_ref, out_ref, *, eta: int):
    out = _cbd_ntt_tiles(
        [in_hi_ref[w] for w in range(CBD_RATE_WORDS)],
        [in_lo_ref[w] for w in range(CBD_RATE_WORDS)],
        eta,
    )
    for i in range(N_OUT):
        out_ref[i] = out[i]


@functools.partial(jax.jit, static_argnames=("eta", "interpret"))
def cbd_ntt_words(in_hi: jax.Array, in_lo: jax.Array, *, eta: int,
                  interpret: bool = False):
    """Batched PRF+CBD+NTT over word-transposed padded seed blocks.

    Same contract as cbd_words but the coefficients come back already in
    the NTT domain — the intermediate CBD polynomial never touches HBM.

    Returns:
      (256, B) int32 NTT-domain coefficients in [0, q).
    """
    return sampler_call(functools.partial(_cbd_ntt_kernel, eta=eta),
                        CBD_RATE_WORDS, N_OUT, in_hi, in_lo, interpret=interpret)
