"""Batched FrodoKEM in JAX — dense LWE on the MXU.

TPU-native design
-----------------
FrodoKEM is the most TPU-friendly algorithm in the suite: its cost is dense
n x n (mod 2^16) matrix algebra, which maps directly onto matrix units — no
NTT, no rejection sampling, power-of-two modulus (mod q = free bit-mask).

* The A matrix is never materialised: it is generated (AES-128 counter blocks
  via ``core.aes`` or SHAKE-128 rows via ``core.keccak``) in 16 row-chunks and
  immediately contracted against S / S', keeping memory at
  O(batch * n * n/16) while the matmuls stay MXU-sized.
* All arithmetic is int32 (products bounded by n * 12 * 2^16 < 2^31 — exact),
  masked back to q = 2^D with a bit-and.
* Every op takes an arbitrary leading batch shape; randomness (s, seedSE, z,
  mu) is an explicit input — the deterministic seam the spec defines.

Bit-exactness oracle: ``pyref.frodo_ref`` (tests/test_frodo.py).
Replaces (reference): FrodoKEMKeyExchange's per-call liboqs objects
(crypto/key_exchange.py:312-449); BASELINE.json config 3 names
FrodoKEM-640-AES batch=1024 as the LWE matrix-sampling benchmark.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import aes as jaes
from ..core import aes_bitsliced as jaes_bs
from ..core import keccak
from ..pyref.frodo_ref import NBAR, PARAMS, FrodoParams
from . import frodo_pallas


def _use_bitsliced_aes() -> bool:
    """Bitsliced (table-free) AES by default; QRP2P_AES_GATHER=1 restores the
    gather S-box for A/B runs.  Read at TRACE time (jit caches the choice) —
    flip only in a fresh process, same caveat as QRP2P_PALLAS."""
    import os

    return os.environ.get("QRP2P_AES_GATHER", "0") != "1"

N_CHUNKS = 16  # A-matrix row chunks (n is divisible by 16 in all sets)

#: Largest single-dispatch batch on real TPU hardware.  Round 2 observed
#: batches >= 1024 crashing this environment's remote TPU worker; the
#: round-3 bisection (tools/repro_worker_fault.py,
#: bench_results/worker_fault_bisect.json) could NOT reproduce any
#: deterministic (kernel, batch) fault — fresh-process keygen/encaps ran
#: clean at 1024 and the sub-kernels at 2048, so the failure class is a
#: transient worker-state one.  A late-round sweep then measured 512-row
#: dispatches +24% on 640-SHAKE encaps with clean roundtrips (1024 adds
#: little more and decaps dips), so the cap rose 256 -> 512; the batch
#: queue's cpu fallback absorbs any transient recurrence.
MAX_DEVICE_BATCH = 512


def _shake(p: FrodoParams, data: jax.Array, out_len: int) -> jax.Array:
    fn = keccak.shake128 if p.n == 640 else keccak.shake256
    return fn(data, out_len)


def _le16(b: jax.Array) -> jax.Array:
    """(..., 2k) uint8 -> (..., k) int32 little-endian 16-bit."""
    x = b.astype(jnp.int32).reshape(b.shape[:-1] + (-1, 2))
    return x[..., 0] | (x[..., 1] << 8)


def _to_le16(v: jax.Array) -> jax.Array:
    """(..., k) int32 (mod 2^16) -> (..., 2k) uint8."""
    out = jnp.stack([v & 0xFF, (v >> 8) & 0xFF], axis=-1).astype(jnp.uint8)
    return out.reshape(out.shape[:-2] + (-1,))


# -- error sampling (CDF inversion, vectorised) ------------------------------


def _sample(p: FrodoParams, r16: jax.Array) -> jax.Array:
    """(...,) int32 16-bit randoms -> CDF samples mod q."""
    if keccak._use_pallas():
        # Fused compare-sum on device: never materialises the (M, |cdf|)
        # comparison tensor in HBM (bit-identical, tests/test_frodo_pallas).
        return frodo_pallas.cdf_sample(p, r16)
    cdf = jnp.asarray(np.asarray(p.cdf[:-1], dtype=np.int32))
    t = r16 >> 1
    e = jnp.sum(t[..., None] > cdf, axis=-1)
    return jnp.where(r16 & 1 == 1, -e, e) & (p.q - 1)


# -- packing / encoding ------------------------------------------------------


def _pack(p: FrodoParams, v: jax.Array) -> jax.Array:
    """(..., m) int32 -> (..., m*d/8) uint8, d-bit MSB-first per value."""
    bits = (v[..., :, None] >> np.arange(p.d - 1, -1, -1)) & 1
    bits = bits.reshape(v.shape[:-1] + (-1, 8))
    return jnp.sum(bits << np.arange(7, -1, -1), axis=-1).astype(jnp.uint8)


def _unpack(p: FrodoParams, b: jax.Array) -> jax.Array:
    """(..., m*d/8) uint8 -> (..., m) int32."""
    bits = (b[..., :, None].astype(jnp.int32) >> np.arange(7, -1, -1)) & 1
    bits = bits.reshape(b.shape[:-1] + (-1, p.d))
    return jnp.sum(bits << np.arange(p.d - 1, -1, -1), axis=-1)


def _encode(p: FrodoParams, mu: jax.Array) -> jax.Array:
    """(..., len_sec) uint8 -> (..., 64) int32 (nbar x nbar row-major)."""
    bits = (mu[..., :, None].astype(jnp.int32) >> np.arange(8)) & 1
    bits = bits.reshape(mu.shape[:-1] + (64, p.b))
    vals = jnp.sum(bits << np.arange(p.b), axis=-1)
    return vals << (p.d - p.b)


def _decode(p: FrodoParams, m: jax.Array) -> jax.Array:
    """(..., 64) int32 -> (..., len_sec) uint8."""
    val = (((m & (p.q - 1)) << p.b) + (p.q >> 1)) >> p.d
    val = val & ((1 << p.b) - 1)
    bits = (val[..., :, None] >> np.arange(p.b)) & 1
    bits = bits.reshape(m.shape[:-1] + (-1, 8))
    return jnp.sum(bits << np.arange(8), axis=-1).astype(jnp.uint8)


# -- A-matrix row-chunk generation -------------------------------------------


def _gen_a_chunk(p: FrodoParams, ctx, row_start: int, nrows: int) -> jax.Array:
    """-> (batch, nrows, n) int32; ctx = round_keys (AES) or seed_a (SHAKE)."""
    mask = p.q - 1
    if p.aes:
        rk = ctx
        pt = np.zeros((nrows, p.n // 8, 16), dtype=np.uint8)
        for r in range(nrows):
            i = row_start + r
            pt[r, :, 0] = i & 0xFF
            pt[r, :, 1] = i >> 8
            cols = np.arange(0, p.n, 8)
            pt[r, :, 2] = cols & 0xFF
            pt[r, :, 3] = cols >> 8
        blocks = jnp.asarray(pt.reshape(-1, 16))
        blocks = jnp.broadcast_to(blocks, rk.shape[:-2] + blocks.shape)
        aes_impl = jaes_bs if _use_bitsliced_aes() else jaes
        ct = aes_impl.encrypt_blocks(rk, blocks)
        vals = _le16(ct.reshape(ct.shape[:-2] + (-1,)))
        return vals.reshape(vals.shape[:-1] + (nrows, p.n)) & mask
    seed_a = ctx
    idx = np.zeros((nrows, 2), dtype=np.uint8)
    rows = np.arange(row_start, row_start + nrows)
    idx[:, 0] = rows & 0xFF
    idx[:, 1] = rows >> 8
    lead = seed_a.shape[:-1] + (nrows,)
    seeds = jnp.concatenate(
        [
            jnp.broadcast_to(jnp.asarray(idx), lead + (2,)),
            jnp.broadcast_to(seed_a[..., None, :], lead + (16,)),
        ],
        axis=-1,
    )
    buf = keccak.shake128(seeds, 2 * p.n)  # Gen uses SHAKE128 for every set
    return _le16(buf) & mask


def _a_ctx(p: FrodoParams, seed_a: jax.Array):
    return jaes.key_schedule(seed_a) if p.aes else seed_a


def _a_times_s(p: FrodoParams, ctx, s: jax.Array) -> jax.Array:
    """A @ S: s (batch, n, nbar) -> (batch, n, nbar), without materialising A.

    SHAKE sets route to the fused Pallas matmul (kem/frodo_pallas.py: sponge
    fused into the matmul consumer, A never touches HBM) on real TPU and to
    its bit-identical scanned-jnp twin elsewhere; the AES sets keep the
    bitsliced-AES chunk loop (their matrix stream is not a sponge)."""
    if not p.aes:
        if frodo_pallas.use_pallas_default():
            return frodo_pallas.a_times_s(p, s, ctx)
        return frodo_pallas.a_times_s_jnp(p, s, ctx)
    rows = p.n // N_CHUNKS
    outs = []
    for c in range(N_CHUNKS):
        a_chunk = _gen_a_chunk(p, ctx, c * rows, rows)
        outs.append(jnp.einsum("...rn,...nj->...rj", a_chunk, s) & (p.q - 1))
    return jnp.concatenate(outs, axis=-2)


def _s_times_a(p: FrodoParams, sp: jax.Array, ctx) -> jax.Array:
    """S' @ A: sp (batch, nbar, n) -> (batch, nbar, n).

    Routing mirrors :func:`_a_times_s` (fused Pallas / scanned twin for the
    SHAKE sets, AES chunk loop otherwise)."""
    if not p.aes:
        if frodo_pallas.use_pallas_default():
            return frodo_pallas.s_times_a(p, sp, ctx)
        return frodo_pallas.s_times_a_jnp(p, sp, ctx)
    rows = p.n // N_CHUNKS
    acc = jnp.zeros(sp.shape[:-1] + (p.n,), jnp.int32)
    for c in range(N_CHUNKS):
        a_chunk = _gen_a_chunk(p, ctx, c * rows, rows)
        sp_chunk = sp[..., :, c * rows : (c + 1) * rows]
        acc = (acc + jnp.einsum("...ir,...rn->...in", sp_chunk, a_chunk)) & (p.q - 1)
    return acc


# -- KEM -----------------------------------------------------------------------


def keygen(p: FrodoParams, s: jax.Array, seed_se: jax.Array, z: jax.Array):
    """(..., len_sec) x3 uint8 -> (pk (..., pk_len), sk (..., sk_len))."""
    s = jnp.asarray(s, jnp.uint8)
    seed_se = jnp.asarray(seed_se, jnp.uint8)
    z = jnp.asarray(z, jnp.uint8)
    batch = z.shape[:-1]
    seed_a = _shake(p, z, 16)
    ctx = _a_ctx(p, seed_a)
    pfx = jnp.broadcast_to(jnp.uint8(0x5F), batch + (1,))
    r = _le16(_shake(p, jnp.concatenate([pfx, seed_se], axis=-1), 4 * p.n * NBAR))
    st = _sample(p, r[..., : p.n * NBAR]).reshape(batch + (NBAR, p.n))
    e = _sample(p, r[..., p.n * NBAR :]).reshape(batch + (p.n, NBAR))
    s_mat = jnp.swapaxes(st, -1, -2)
    b_mat = (_a_times_s(p, ctx, s_mat) + e) & (p.q - 1)
    b_packed = _pack(p, b_mat.reshape(batch + (-1,)))
    pk = jnp.concatenate([seed_a, b_packed], axis=-1)
    pkh = _shake(p, pk, p.len_sec)
    # stored as centered signed int16 (v - q when v >= q/2), like the spec
    st_c = st.reshape(batch + (-1,))
    st_bytes = _to_le16((st_c - jnp.where(st_c >= p.q // 2, p.q, 0)) & 0xFFFF)
    sk = jnp.concatenate([s, pk, st_bytes, pkh], axis=-1)
    return pk, sk


def _encaps_noise(p: FrodoParams, mu: jax.Array, pkh: jax.Array):
    """Deterministic encaps randomness: -> (sp, ep, epp, k)."""
    batch = mu.shape[:-1]
    se_k = _shake(p, jnp.concatenate([pkh, mu], axis=-1), 2 * p.len_sec)
    seed_se, k = se_k[..., : p.len_sec], se_k[..., p.len_sec :]
    pfx = jnp.broadcast_to(jnp.uint8(0x96), batch + (1,))
    r = _le16(
        _shake(p, jnp.concatenate([pfx, seed_se], axis=-1),
               (2 * NBAR * p.n + NBAR * NBAR) * 2)
    )
    sp = _sample(p, r[..., : NBAR * p.n]).reshape(batch + (NBAR, p.n))
    ep = _sample(p, r[..., NBAR * p.n : 2 * NBAR * p.n]).reshape(batch + (NBAR, p.n))
    epp = _sample(p, r[..., 2 * NBAR * p.n :]).reshape(batch + (NBAR, NBAR))
    return sp, ep, epp, k


def _assemble_ct(p: FrodoParams, sp: jax.Array, bp: jax.Array,
                 b_mat: jax.Array, epp: jax.Array, mu: jax.Array):
    """Shared encaps tail: B' and the unpacked B matrix -> packed ct."""
    batch = mu.shape[:-1]
    v = (jnp.einsum("...in,...nj->...ij", sp, b_mat) + epp) & (p.q - 1)
    c = (v.reshape(batch + (-1,)) + _encode(p, mu)) & (p.q - 1)
    return jnp.concatenate(
        [_pack(p, bp.reshape(batch + (-1,))), _pack(p, c)], axis=-1
    )


def _reencrypt(p: FrodoParams, pk: jax.Array, mu: jax.Array, pkh: jax.Array):
    """Shared encaps core: -> (ct, k)."""
    batch = mu.shape[:-1]
    seed_a, b_packed = pk[..., :16], pk[..., 16:]
    sp, ep, epp, k = _encaps_noise(p, mu, pkh)
    ctx = _a_ctx(p, seed_a)
    bp = (_s_times_a(p, sp, ctx) + ep) & (p.q - 1)
    b_mat = _unpack(p, b_packed).reshape(batch + (p.n, NBAR))
    return _assemble_ct(p, sp, bp, b_mat, epp, mu), k


def encaps(p: FrodoParams, pk: jax.Array, mu: jax.Array):
    """pk (..., pk_len), mu (..., len_sec) -> (ct (..., ct_len), ss (..., len_sec))."""
    pk = jnp.asarray(pk, jnp.uint8)
    mu = jnp.asarray(mu, jnp.uint8)
    pkh = _shake(p, pk, p.len_sec)
    ct, k = _reencrypt(p, pk, mu, pkh)
    ss = _shake(p, jnp.concatenate([ct, k], axis=-1), p.len_sec)
    return ct, ss


def decaps(p: FrodoParams, sk: jax.Array, ct: jax.Array):
    """sk (..., sk_len), ct (..., ct_len) -> ss (..., len_sec)."""
    sk = jnp.asarray(sk, jnp.uint8)
    ct = jnp.asarray(ct, jnp.uint8)
    batch = ct.shape[:-1]
    s = sk[..., : p.len_sec]
    pk = sk[..., p.len_sec : p.len_sec + p.pk_len]
    st_off = p.len_sec + p.pk_len
    st_bytes = sk[..., st_off : st_off + 2 * NBAR * p.n]
    pkh = sk[..., st_off + 2 * NBAR * p.n :]
    # signed-LE16 mod q == raw 16-bit value masked, since q | 2^16
    st = (_le16(st_bytes) & (p.q - 1)).reshape(batch + (NBAR, p.n))
    c1_len = NBAR * p.n * p.d // 8
    bp = _unpack(p, ct[..., :c1_len]).reshape(batch + (NBAR, p.n))
    c = _unpack(p, ct[..., c1_len:])
    bps = jnp.einsum("...in,...jn->...ij", bp, st) & (p.q - 1)
    m = (c - bps.reshape(batch + (-1,))) & (p.q - 1)
    mu_p = _decode(p, m)
    ct2, kp = _reencrypt(p, pk, mu_p, pkh)
    ok = jnp.all(ct == ct2, axis=-1, keepdims=True)
    tail = jnp.where(ok, kp, s)
    return _shake(p, jnp.concatenate([ct, tail], axis=-1), p.len_sec)


@functools.cache
def get(name: str):
    """Jitted (keygen, encaps, decaps) triple for a parameter-set name."""
    p = PARAMS[name]
    return (
        jax.jit(functools.partial(keygen, p)),
        jax.jit(functools.partial(encaps, p)),
        jax.jit(functools.partial(decaps, p)),
    )


# --------------------------------------------------------------------------
# Per-key precompute (device operand cache seam, provider/opcache.py)
# --------------------------------------------------------------------------


def precompute_pk(p: FrodoParams, pk: jax.Array) -> dict[str, jax.Array]:
    """Per-key device state encaps reuses across dispatches: the MATERIALISED
    A matrix (the dominant per-dispatch regen cost — n^2 sponge/AES bytes),
    the unpacked B matrix, and H(pk).  Computed once per key by the operand
    cache; repeat encaps against the same peer key then run a pure dense
    matmul with zero matrix regeneration.  May be unbatched; broadcasts
    against any mu batch.  A is int32 (n=1344: 7.2 MB/key, bounded by the
    cache's entry cap)."""
    pk = jnp.asarray(pk, jnp.uint8)
    seed_a, b_packed = pk[..., :16], pk[..., 16:]
    ctx = _a_ctx(p, seed_a)
    rows = p.n // N_CHUNKS
    a_mat = jnp.concatenate(
        [_gen_a_chunk(p, ctx, c * rows, rows) for c in range(N_CHUNKS)],
        axis=-2,
    )
    b_mat = _unpack(p, b_packed).reshape(pk.shape[:-1] + (p.n, NBAR))
    return {"a": a_mat, "b": b_mat, "pkh": _shake(p, pk, p.len_sec)}


def encaps_pre(p: FrodoParams, pre: dict[str, jax.Array], mu: jax.Array):
    """``encaps`` over a ``precompute_pk`` pytree — bit-identical output
    (the precompute is a pure hoist of the key-dependent prefix; int32
    products wrap mod 2^32 identically in the dense and fused paths, and
    q | 2^32 keeps the masked results equal)."""
    mu = jnp.asarray(mu, jnp.uint8)
    batch = mu.shape[:-1]
    pkh = jnp.broadcast_to(pre["pkh"], batch + (p.len_sec,))
    sp, ep, epp, k = _encaps_noise(p, mu, pkh)
    bp = (jnp.einsum("...ir,...rn->...in", sp, pre["a"]) + ep) & (p.q - 1)
    ct = _assemble_ct(p, sp, bp, pre["b"], epp, mu)
    ss = _shake(p, jnp.concatenate([ct, k], axis=-1), p.len_sec)
    return ct, ss


def encaps_cold(p: FrodoParams, pk: jax.Array, mu: jax.Array):
    """Cache-filling encaps: ONE dispatch returning the per-key device state
    plus the op results (same rationale as kem/mlkem.encaps_cold — a miss
    must not cost an extra round trip over the uncached path)."""
    pre = precompute_pk(p, pk)
    ct, ss = encaps_pre(p, pre, mu)
    return pre, ct, ss


@functools.cache
def get_pre(name: str):
    """Jitted (encaps_cold, encaps_pre) pair for the device operand cache
    (provider/opcache.py): cold fills the cache in one dispatch; pre runs a
    pure dense matmul over the cached A — single-key batches skip the
    matrix regeneration entirely."""
    p = PARAMS[name]
    return (
        jax.jit(functools.partial(encaps_cold, p)),
        jax.jit(functools.partial(encaps_pre, p)),
    )
