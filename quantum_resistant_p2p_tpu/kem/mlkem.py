"""Batched ML-KEM (FIPS 203) in JAX — the TPU crypto core's flagship KEM.

TPU-native design
-----------------
Every function operates on arrays with an arbitrary leading batch shape and
fixed trailing shapes, so a single jitted program amortises compilation over
thousands of concurrent handshakes (the reference app performs one serial
liboqs FFI call per handshake: crypto/key_exchange.py:125-186).

* Polynomials are ``(..., 256)`` int32 kept reduced in [0, q); q = 3329, so all
  intermediate products fit comfortably in int32 — no 64-bit emulation needed.
* The NTT is the layered butterfly vectorised across all 128 butterflies of a
  layer at once (7 static layers, no data-dependent control flow).
* SampleNTT's rejection loop becomes a fixed-size squeeze (672 bytes -> 448
  candidates, P[shortfall] < 1e-38) followed by a stable-sort compaction —
  identical output to the spec's sequential scan whenever the spec would have
  consumed <= 672 bytes.
* All hashing (G/H/J/PRF/XOF) is the batched Keccak kernel from
  ``core.keccak``; randomness (d, z, m) is an explicit input, giving the
  deterministic seam FIPS 203 defines for KATs.

Bit-exactness oracle: ``pyref.mlkem_ref`` (clean-room FIPS 203 over hashlib).
Replaces (reference): MLKEMKeyExchange's per-call liboqs objects
(crypto/key_exchange.py:57-186, vendor/oqs.py:310-390).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core import keccak
from ..core.sortnet import bitonic_sort
from ..pyref.mlkem_ref import (  # parameter sets + computed constant tables
    GAMMAS,
    MLKEM512,
    MLKEM768,
    MLKEM1024,
    MLKEMParams,
    PARAMS,
    ZETAS,
)

Q = 3329
N = 256

#: Provider slice size: the per-dispatch scaling curve (bench_report.md)
#: plateaus over 1024-2048 rows — the fused Pallas sampler kernels
#: (kem/mlkem_pallas.py) process exactly 1024 sponges per grid step, so
#: smaller dispatches pad and waste tile lanes, while past 2048 the
#: remaining jnp pipeline's working set spills (4096-row single dispatch:
#: 733k encaps/s vs ~1M sliced).  2048 measures ~6% above 1024 and
#: bench.py's raw-ops headline uses it; the provider takes the plateau's
#: LOW end for queue latency.  Providers slice larger batches
#: (provider/base.py sliced_dispatch).
MAX_DEVICE_BATCH = 1024
_N_INV = 3303  # 128^-1 mod q

_ZETAS = np.asarray(ZETAS, dtype=np.int32)
_GAMMAS = np.asarray(GAMMAS, dtype=np.int32)

# --------------------------------------------------------------------------
# Byte codecs (FIPS 203 ByteEncode_d / ByteDecode_d), batched
# --------------------------------------------------------------------------


def byte_decode(b: jax.Array, d: int) -> jax.Array:
    """(..., 32*d) uint8 -> (..., 256) int32 (mod q when d == 12).

    d == 12 (t_hat/s_hat, on every op's path) uses an arithmetic split —
    3 bytes onto 2 coefficients with fixed shifts, ~6x fewer ops than the
    generic bit expansion and no (..., 256, 12) intermediate.  The other
    widths keep the bit path: measured on chip, arithmetic forms of the
    narrow widths (group shapes like (64, 5) for d = 10) misalign TPU
    lanes and run SLOWER than the wide bit-expansion arrays (headline
    1.073M with this split vs 919k all-arithmetic encaps/s).
    """
    if d != 12:
        bits = (b[..., :, None].astype(jnp.int32) >> jnp.arange(8)) & 1
        bits = bits.reshape(b.shape[:-1] + (N, d))
        return jnp.sum(bits << jnp.arange(d), axis=-1)
    t = b.astype(jnp.int32).reshape(b.shape[:-1] + (N // 2, 3))
    lo = t[..., 0] | ((t[..., 1] & 0xF) << 8)
    hi = (t[..., 1] >> 4) | (t[..., 2] << 4)
    return jnp.stack([lo, hi], axis=-1).reshape(b.shape[:-1] + (N,)) % Q


def byte_encode(vals: jax.Array, d: int) -> jax.Array:
    """(..., 256) int32 -> (..., 32*d) uint8 (inverse of byte_decode;
    same d == 12 arithmetic-vs-bit split, see byte_decode)."""
    if d != 12:
        bits = (vals[..., :, None] >> jnp.arange(d)) & 1
        bits = bits.reshape(vals.shape[:-1] + (32 * d, 8))
        return jnp.sum(bits << jnp.arange(8), axis=-1).astype(jnp.uint8)
    v = vals.reshape(vals.shape[:-1] + (N // 2, 2))
    # The arithmetic split would spill bits >= 12 into adjacent bytes (the
    # old bit path truncated them); mask so non-canonical inputs can't.
    lo, hi = v[..., 0] & 0xFFF, v[..., 1] & 0xFFF
    out = jnp.stack([lo & 0xFF, (lo >> 8) | ((hi & 0xF) << 4), hi >> 4], axis=-1)
    return out.reshape(vals.shape[:-1] + (384,)).astype(jnp.uint8)


def compress(x: jax.Array, d: int) -> jax.Array:
    return ((x << (d + 1)) + Q) // (2 * Q) % (1 << d)


def decompress(y: jax.Array, d: int) -> jax.Array:
    return (y * Q + (1 << (d - 1))) >> d


# --------------------------------------------------------------------------
# NTT over Z_q[X]/(X^256+1), q = 3329 (FIPS 203 §4.3), batched & layer-vectorised
# --------------------------------------------------------------------------


def ntt(f: jax.Array) -> jax.Array:
    """(..., 256) int32 in [0,q) -> NTT domain, same shape.

    On TPU the 7 butterfly layers run register-resident in one Pallas
    kernel (kem/mlkem_pallas.py:ntt_words) — the jnp formulation below
    materialises the full batched array between layers, 14 HBM round-trips
    per transform."""
    if keccak._use_pallas():
        from . import mlkem_pallas  # deferred: pallas import

        flat = f.reshape((-1, N))
        return mlkem_pallas.ntt_words(flat.T).T.reshape(f.shape)
    zetas = jnp.asarray(_ZETAS)
    k = 1
    length = 128
    while length >= 2:
        groups = N // (2 * length)
        z = zetas[k : k + groups]
        fr = f.reshape(f.shape[:-1] + (groups, 2, length))
        f0, f1 = fr[..., 0, :], fr[..., 1, :]
        t = (z[:, None] * f1) % Q
        f = jnp.stack([(f0 + t) % Q, (f0 - t) % Q], axis=-2).reshape(f.shape)
        k += groups
        length //= 2
    return f


def ntt_inv(f: jax.Array) -> jax.Array:
    if keccak._use_pallas():
        from . import mlkem_pallas  # deferred: pallas import

        flat = f.reshape((-1, N))
        return mlkem_pallas.ntt_words(flat.T, inverse=True).T.reshape(f.shape)
    zetas = jnp.asarray(_ZETAS)
    k = 127
    length = 2
    while length <= 128:
        groups = N // (2 * length)
        z = zetas[k - groups + 1 : k + 1][::-1]
        fr = f.reshape(f.shape[:-1] + (groups, 2, length))
        f0, f1 = fr[..., 0, :], fr[..., 1, :]
        s = (f0 + f1) % Q
        t = (z[:, None] * ((f1 - f0) % Q)) % Q
        f = jnp.stack([s, t], axis=-2).reshape(f.shape)
        k -= groups
        length *= 2
    return (f * _N_INV) % Q


def multiply_ntts(f: jax.Array, g: jax.Array) -> jax.Array:
    """Pairwise base-case products; broadcasts over leading dims."""
    gam = jnp.asarray(_GAMMAS)
    a0, a1 = f[..., 0::2], f[..., 1::2]
    b0, b1 = g[..., 0::2], g[..., 1::2]
    c0 = (a0 * b0 + (a1 * b1 % Q) * gam) % Q
    c1 = (a0 * b1 + a1 * b0) % Q
    return jnp.stack([c0, c1], axis=-1).reshape(jnp.broadcast_shapes(f.shape, g.shape))


# --------------------------------------------------------------------------
# Samplers (FIPS 203 §4.2.2), batched with fixed shapes
# --------------------------------------------------------------------------

_SAMPLE_NTT_BYTES = 672  # 4 SHAKE-128 rate blocks -> 448 candidates for 256 slots


def sample_ntt(seeds: jax.Array) -> jax.Array:
    """(..., 34) uint8 XOF seeds -> (..., 256) int32 NTT-domain polynomials.

    Fixed-shape replacement for the spec's squeeze-until-256-accepted loop:
    squeeze 672 bytes up front, mark candidates < q, and compact accepted
    candidates to the front in spec order.  The compaction is a gather-free
    bitonic network over packed int32 keys (reject | index | value) — XLA's
    argsort/take_along_axis serialise on TPU and measured 200+ ms per batch,
    the entire encaps budget (core/sortnet.py).

    On TPU the whole pipeline (SHAKE squeeze -> extraction -> compaction)
    runs as one fused Pallas kernel with every intermediate in VMEM
    (kem/mlkem_pallas.py) — it is ~85% of encaps' HBM traffic otherwise.
    """
    if keccak._use_pallas():
        from . import mlkem_pallas  # deferred: pallas import

        ph, plo, batch = keccak.seed_block_words(seeds, 168, 0x1F)
        return mlkem_pallas.sample_ntt_words(ph, plo).T.reshape(batch + (N,))

    buf = keccak.shake128(seeds, _SAMPLE_NTT_BYTES).astype(jnp.int32)
    t = buf.reshape(buf.shape[:-1] + (-1, 3))
    d1 = t[..., 0] + 256 * (t[..., 1] % 16)
    d2 = (t[..., 1] // 16) + 16 * t[..., 2]
    cand = jnp.stack([d1, d2], axis=-1).reshape(buf.shape[:-1] + (-1,))
    nc = cand.shape[-1]
    idx = jnp.arange(nc, dtype=jnp.int32)
    # key: accepted (bit 21 clear) before rejected, index order within each,
    # 12-bit candidate value in the low bits.  Unique keys => stable partition.
    key = jnp.where(cand < Q, 0, 1 << 21) | (idx << 12) | cand
    np2 = 1 << (nc - 1).bit_length()
    key = jnp.pad(
        key,
        [(0, 0)] * (key.ndim - 1) + [(0, np2 - nc)],
        constant_values=1 << 22,
    )
    return bitonic_sort(key)[..., :N] & 0xFFF


def sample_poly_cbd(b: jax.Array, eta: int) -> jax.Array:
    """(..., 64*eta) uint8 PRF output -> (..., 256) int32 CBD_eta polynomial."""
    bits = (b[..., :, None].astype(jnp.int32) >> jnp.arange(8)) & 1
    bits = bits.reshape(b.shape[:-1] + (N, 2, eta))
    x = bits.sum(axis=-1)
    return (x[..., 0] - x[..., 1]) % Q


def _prf_seeds(s: jax.Array, n_consts: np.ndarray) -> jax.Array:
    """PRF seed blocks for a vector of counter bytes: (..., 32) -> (..., len(n_consts), 33) s || n."""
    reps = len(n_consts)
    s_rep = jnp.broadcast_to(s[..., None, :], s.shape[:-1] + (reps, 32))
    n_col = jnp.broadcast_to(
        jnp.asarray(n_consts, dtype=jnp.uint8)[:, None], s.shape[:-1] + (reps, 1)
    )
    return jnp.concatenate([s_rep, n_col], axis=-1)


def _prf_cbd(s: jax.Array, n_consts: np.ndarray, eta: int) -> jax.Array:
    """PRF_eta + SamplePolyCBD: s (..., 32) -> (..., len(n_consts), 256).

    On TPU the SHAKE-256 squeeze and the CBD bit-sums run as one fused
    Pallas kernel (kem/mlkem_pallas.py:cbd_words); elsewhere the jnp
    sponge + sample_poly_cbd path.
    """
    seeds = _prf_seeds(s, n_consts)
    if keccak._use_pallas():
        from . import mlkem_pallas  # deferred: pallas import

        ph, plo, batch = keccak.seed_block_words(seeds, 136, 0x1F)
        return mlkem_pallas.cbd_words(ph, plo, eta=eta).T.reshape(batch + (N,))
    return sample_poly_cbd(keccak.shake256(seeds, 64 * eta), eta)


def _prf_cbd_ntt(s: jax.Array, n_consts: np.ndarray, eta: int) -> jax.Array:
    """``ntt(_prf_cbd(...))`` — fused into ONE Pallas kernel on TPU.

    The noise polynomials that feed matrix products are consumed only in
    the NTT domain, so squeezing, CBD-summing, and all 7 butterfly layers
    run on the same VMEM-resident register tiles; the intermediate CBD
    polynomial never touches HBM (kem/mlkem_pallas.py:cbd_ntt_words).
    Bit-identical to the two-step form on every path."""
    seeds = _prf_seeds(s, n_consts)
    if keccak._use_pallas():
        from . import mlkem_pallas  # deferred: pallas import

        ph, plo, batch = keccak.seed_block_words(seeds, 136, 0x1F)
        return mlkem_pallas.cbd_ntt_words(ph, plo, eta=eta).T.reshape(batch + (N,))
    return ntt(sample_poly_cbd(keccak.shake256(seeds, 64 * eta), eta))


def _expand_matrix(rho: jax.Array, k: int) -> jax.Array:
    """rho (..., 32) -> A_hat (..., k, k, 256) with A[i,j] = SampleNTT(rho||j||i)."""
    ji = np.array([[j, i] for i in range(k) for j in range(k)], dtype=np.uint8)
    rho_rep = jnp.broadcast_to(rho[..., None, :], rho.shape[:-1] + (k * k, 32))
    ji_rep = jnp.broadcast_to(jnp.asarray(ji), rho.shape[:-1] + (k * k, 2))
    seeds = jnp.concatenate([rho_rep, ji_rep], axis=-1)
    a = sample_ntt(seeds)
    return a.reshape(rho.shape[:-1] + (k, k, N))


# --------------------------------------------------------------------------
# K-PKE + ML-KEM (FIPS 203 §5-7), batched
# --------------------------------------------------------------------------


def _kpke_keygen(p: MLKEMParams, d: jax.Array):
    k = p.k
    kin = jnp.concatenate(
        [d, jnp.broadcast_to(jnp.uint8(k), d.shape[:-1] + (1,))], axis=-1
    )
    g = keccak.sha3_512(kin)
    rho, sigma = g[..., :32], g[..., 32:]
    a_hat = _expand_matrix(rho, k)
    noise_hat = _prf_cbd_ntt(sigma, np.arange(2 * k), p.eta1)
    s_hat = noise_hat[..., :k, :]
    e_hat = noise_hat[..., k:, :]
    t_hat = (
        jnp.sum(multiply_ntts(a_hat, s_hat[..., None, :, :]), axis=-2) + e_hat
    ) % Q
    ek = jnp.concatenate(
        [byte_encode(t_hat, 12).reshape(d.shape[:-1] + (384 * k,)), rho], axis=-1
    )
    dk_pke = byte_encode(s_hat, 12).reshape(d.shape[:-1] + (384 * k,))
    return ek, dk_pke


def _kpke_encrypt(p: MLKEMParams, ek: jax.Array, m: jax.Array, r: jax.Array):
    k = p.k
    t_hat = byte_decode(ek[..., : 384 * k].reshape(ek.shape[:-1] + (k, 384)), 12)
    rho = ek[..., 384 * k :]
    a_hat = _expand_matrix(rho, k)
    return _kpke_encrypt_pre(p, t_hat, a_hat, m, r)


def _kpke_encrypt_pre(p: MLKEMParams, t_hat: jax.Array, a_hat: jax.Array,
                      m: jax.Array, r: jax.Array):
    """K-PKE.Encrypt over pre-decoded key material (t_hat, ExpandA output).

    ``t_hat``/``a_hat`` may be unbatched (one key) and broadcast against a
    batched (m, r) — the seam the device operand cache uses to reuse one
    key's ExpandA across every encaps against that key.
    """
    k = p.k
    e1 = _prf_cbd(r, np.arange(k, 2 * k), p.eta2)
    e2 = _prf_cbd(r, np.array([2 * k]), p.eta2)[..., 0, :]
    y_hat = _prf_cbd_ntt(r, np.arange(k), p.eta1)
    # u = invNTT(A^T ∘ y_hat) + e1 : contract over row index i of A[i,j]
    u = (
        ntt_inv(jnp.sum(multiply_ntts(a_hat, y_hat[..., :, None, :]), axis=-3) % Q)
        + e1
    ) % Q
    mu = decompress(byte_decode(m, 1), 1)
    v = (
        ntt_inv(jnp.sum(multiply_ntts(t_hat, y_hat), axis=-2) % Q) + e2 + mu
    ) % Q
    c1e = byte_encode(compress(u, p.du), p.du)  # (..., k, 32*du)
    c1 = c1e.reshape(c1e.shape[:-2] + (32 * p.du * k,))
    c2 = byte_encode(compress(v, p.dv), p.dv)
    return jnp.concatenate([c1, c2], axis=-1)


def _kpke_decrypt(p: MLKEMParams, dk_pke: jax.Array, c: jax.Array):
    k, du, dv = p.k, p.du, p.dv
    c1 = c[..., : 32 * du * k].reshape(c.shape[:-1] + (k, 32 * du))
    u = decompress(byte_decode(c1, du), du)
    v = decompress(byte_decode(c[..., 32 * du * k :], dv), dv)
    s_hat = byte_decode(dk_pke.reshape(dk_pke.shape[:-1] + (k, 384)), 12)
    w = (v - ntt_inv(jnp.sum(multiply_ntts(s_hat, ntt(u)), axis=-2) % Q)) % Q
    return byte_encode(compress(w, 1), 1)


def keygen(p: MLKEMParams, d: jax.Array, z: jax.Array):
    """ML-KEM.KeyGen_internal: seeds d, z (..., 32) -> ek (..., ek_len), dk (..., dk_len)."""
    d = jnp.asarray(d, jnp.uint8)
    z = jnp.asarray(z, jnp.uint8)
    ek, dk_pke = _kpke_keygen(p, d)
    dk = jnp.concatenate([dk_pke, ek, keccak.sha3_256(ek), z], axis=-1)
    return ek, dk


def encaps(p: MLKEMParams, ek: jax.Array, m: jax.Array):
    """ML-KEM.Encaps_internal: ek, m (..., 32) -> K (..., 32), c (..., ct_len)."""
    ek = jnp.asarray(ek, jnp.uint8)
    m = jnp.asarray(m, jnp.uint8)
    g = keccak.sha3_512(jnp.concatenate([m, keccak.sha3_256(ek)], axis=-1))
    key, r = g[..., :32], g[..., 32:]
    c = _kpke_encrypt(p, ek, m, r)
    return key, c


def precompute_ek(p: MLKEMParams, ek: jax.Array) -> dict[str, jax.Array]:
    """Per-key device state encaps reuses across dispatches: the decoded
    t_hat, ExpandA(rho) — ~85% of encaps' sampling work — and H(ek).
    Computed once per key by the operand cache (provider/opcache.py) so
    repeat encaps against the same peer key skip the re-upload and the
    matrix expansion.  May be unbatched; broadcasts against any m batch."""
    ek = jnp.asarray(ek, jnp.uint8)
    k = p.k
    return {
        "t_hat": byte_decode(ek[..., : 384 * k].reshape(ek.shape[:-1] + (k, 384)), 12),
        "a_hat": _expand_matrix(ek[..., 384 * k :], k),
        "h_ek": keccak.sha3_256(ek),
    }


def encaps_pre(p: MLKEMParams, pre: dict[str, jax.Array], m: jax.Array):
    """``encaps`` over a ``precompute_ek`` pytree — bit-identical output
    (the precompute is a pure hoist of the key-dependent prefix)."""
    m = jnp.asarray(m, jnp.uint8)
    h_ek = jnp.broadcast_to(pre["h_ek"], m.shape[:-1] + (32,))
    g = keccak.sha3_512(jnp.concatenate([m, h_ek], axis=-1))
    key, r = g[..., :32], g[..., 32:]
    c = _kpke_encrypt_pre(p, pre["t_hat"], pre["a_hat"], m, r)
    return key, c


def decaps(p: MLKEMParams, dk: jax.Array, c: jax.Array):
    """ML-KEM.Decaps_internal with implicit rejection (branch-free select)."""
    dk = jnp.asarray(dk, jnp.uint8)
    c = jnp.asarray(c, jnp.uint8)
    k = p.k
    dk_pke = dk[..., : 384 * k]
    ek = dk[..., 384 * k : 768 * k + 32]
    h = dk[..., 768 * k + 32 : 768 * k + 64]
    z = dk[..., 768 * k + 64 :]
    m2 = _kpke_decrypt(p, dk_pke, c)
    g = keccak.sha3_512(jnp.concatenate([m2, h], axis=-1))
    key2, r2 = g[..., :32], g[..., 32:]
    key_bar = keccak.shake256(jnp.concatenate([z, c], axis=-1), 32)
    c2 = _kpke_encrypt(p, ek, m2, r2)
    ok = jnp.all(c == c2, axis=-1, keepdims=True)
    return jnp.where(ok, key2, key_bar)


# --------------------------------------------------------------------------
# Jitted per-parameter-set entry points
# --------------------------------------------------------------------------


@functools.cache
def get(name: str):
    """Jitted (keygen, encaps, decaps) triple for a parameter-set name."""
    p = PARAMS[name]
    return (
        jax.jit(functools.partial(keygen, p)),
        jax.jit(functools.partial(encaps, p)),
        jax.jit(functools.partial(decaps, p)),
    )


def encaps_cold(p: MLKEMParams, ek: jax.Array, m: jax.Array):
    """Cache-filling encaps: ONE dispatch returning both the per-key device
    state and the op results.  A cache miss must not cost an extra round
    trip over the uncached path (a separate precompute dispatch would), so
    the precompute rides along as extra outputs — its arrays stay
    device-resident (jit outputs) and go straight into the operand cache."""
    pre = precompute_ek(p, ek)
    key, c = encaps_pre(p, pre, m)
    return pre, key, c


@functools.cache
def get_pre(name: str):
    """Jitted (encaps_cold, encaps_pre) pair for the device operand cache
    (provider/opcache.py): cold fills the cache in one dispatch; pre runs
    over a cached pytree, skipping the ek upload and ExpandA."""
    p = PARAMS[name]
    return (
        jax.jit(functools.partial(encaps_cold, p)),
        jax.jit(functools.partial(encaps_pre, p)),
    )
