"""Batched HQC in JAX — quasi-cyclic GF(2) codes on the VPU.

TPU-native design
-----------------
HQC is the least matmul-shaped algorithm in the suite (SURVEY.md §7.4 ranks
it hardest to map); the decomposition here:

* Code vectors live as dense (batch, n) uint8 bit arrays — no 64-bit packing
  (TPUs have no 64-bit lanes and XLA vectorises byte lanes fine).  The
  sparse-by-dense cyclic product x^p * a mod (x^n - 1) is a gather with
  rotated indices; a fixed-weight product is a ``fori_loop`` of w <= 149 such
  gathers accumulated in int32 and reduced mod 2.
* The inner RM(1,7) decoder is a batched fast Hadamard transform (7 static
  butterfly stages) over soft-combined duplicates — exactly the
  structure TPUs like.
* The outer Reed-Solomon decoder runs entirely in-graph: syndrome evaluation
  and Chien search are GF(256) table lookups (log/exp gathers) contracted
  over static index grids; Berlekamp-Massey is a 2*delta-step scan with
  masked (branch-free) L/b/m updates.
* Fisher-Yates fixed-weight sampling follows the same downward-scan dedup as
  the oracle (sequential fori_loop over w slots, vectorised compares).

Bit-exactness oracle: ``pyref.hqc_ref`` — see that module's compatibility
note: with liboqs stripped from the reference checkout, the PRNG seam is this
framework's own; cpu and tpu backends are bit-exact against each other.
Replaces (reference): HQCKeyExchange (crypto/key_exchange.py:189-309).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import keccak
from ..pyref.hqc_ref import (
    _GF_EXP,
    _GF_LOG,
    _RM_ENC_TABLE,
    RM_N,
    HQCParams,
    PARAMS,
    _rs_gen_poly,
)

#: Single-dispatch batch cap (provider/base.py sliced_dispatch).  Round 2
#: observed a 256-row keygen dispatch crashing the remote TPU worker; the
#: round-3 bisection (tools/repro_worker_fault.py) ran every HQC op and
#: sub-kernel clean at 256-1024 in fresh processes — no deterministic
#: fault exists; the failure class is transient worker state.  The cap
#: stays as a conservative guard (HQC dispatches are seconds-long, so
#: slicing costs ~nothing).
MAX_DEVICE_BATCH = 128

_EXP = np.asarray(_GF_EXP, dtype=np.int32)  # length 512
_LOG = np.asarray(_GF_LOG, dtype=np.int32)

# RM(1,7) encode table as a (256, 128) bit matrix
_RM_BITS = np.array(
    [[(cw >> j) & 1 for j in range(RM_N)] for cw in _RM_ENC_TABLE], dtype=np.int32
)


def _gf_mul(a: jax.Array, b: jax.Array) -> jax.Array:
    exp = jnp.asarray(_EXP)
    log = jnp.asarray(_LOG)
    prod = jnp.take(exp, jnp.take(log, a) + jnp.take(log, b))
    return jnp.where((a == 0) | (b == 0), 0, prod)


def _xor_reduce(x: jax.Array, axis: int) -> jax.Array:
    return lax.reduce(x, np.int32(0), lax.bitwise_xor, (axis % x.ndim,))


# -- bit/byte helpers ---------------------------------------------------------


def _bytes_to_bits(b: jax.Array, nbits: int) -> jax.Array:
    bits = (b[..., :, None].astype(jnp.int32) >> np.arange(8)) & 1
    return bits.reshape(b.shape[:-1] + (-1,))[..., :nbits].astype(jnp.uint8)


def _bits_to_bytes(bits: jax.Array) -> jax.Array:
    nbits = bits.shape[-1]
    pad = (-nbits) % 8
    if pad:
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (pad,), bits.dtype)], axis=-1
        )
    grp = bits.reshape(bits.shape[:-1] + (-1, 8)).astype(jnp.int32)
    return jnp.sum(grp << np.arange(8), axis=-1).astype(jnp.uint8)


# -- sampling -----------------------------------------------------------------


def _seedexpand(seed: jax.Array, out_len: int) -> jax.Array:
    """HQC seedexpander stream: SHAKE256(seed || 0x02) squeezed to out_len.
    Callers slice consecutive reads off one stream (pyref SeedExpander)."""
    dom = jnp.broadcast_to(jnp.uint8(2), seed.shape[:-1] + (1,))
    return keccak.shake256(jnp.concatenate([seed, dom], axis=-1), out_len)


def _u32s(buf: jax.Array) -> jax.Array:
    """(..., 4k) uint8 -> (..., k) uint32 little-endian."""
    b = buf.astype(jnp.uint32).reshape(buf.shape[:-1] + (-1, 4))
    return b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)


def _mulhi32(a: jax.Array, m: int) -> jax.Array:
    """floor(a * m / 2**32) for uint32 a and python int m < 2**16, exactly,
    without 64-bit lanes: split a into 16-bit halves."""
    assert 0 < m < (1 << 16), f"16-bit split requires m < 2^16, got {m}"
    a1 = a >> 16
    a0 = a & jnp.uint32(0xFFFF)
    # a*m = a1*m*2^16 + a0*m ; both partial products fit uint32 (m < 2^16)
    return (a1 * jnp.uint32(m) + ((a0 * jnp.uint32(m)) >> 16)) >> 16


def _fixed_weight_support(p: HQCParams, rand: jax.Array, weight: int) -> jax.Array:
    """(batch, weight) uint32 randoms -> (batch, weight) int32 positions.

    HQC vect_set_random_fixed_weight: i + (rand32 * (n-i)) >> 32, duplicates
    replaced by their index in a reverse scan (oracle-identical dedup).
    """
    cols = [
        (jnp.uint32(i) + _mulhi32(rand[..., i], p.n - i)).astype(jnp.int32)
        for i in range(weight)
    ]
    sup = jnp.stack(cols, axis=-1)

    idx = jnp.arange(weight)

    def fix(k, s):
        i = weight - 1 - k
        si = jnp.take_along_axis(s, jnp.full(s.shape[:-1] + (1,), i), axis=-1)
        clash = jnp.any((s == si) & (idx > i), axis=-1, keepdims=True)
        si_new = jnp.where(clash, i, si)
        return jnp.put_along_axis(
            s, jnp.full(s.shape[:-1] + (1,), i), si_new, axis=-1, inplace=False
        )

    return lax.fori_loop(0, weight, fix, sup)


def _support_to_bits(p: HQCParams, sup: jax.Array) -> jax.Array:
    """(batch, w) positions -> (batch, n) uint8 bits."""
    v = jnp.zeros(sup.shape[:-1] + (p.n,), jnp.uint8)
    return jnp.put_along_axis(v, sup, jnp.uint8(1), axis=-1, inplace=False)


def _sample_random_bits(p: HQCParams, seed: jax.Array) -> jax.Array:
    """h: first n_bytes of the seed's expander stream."""
    return _bytes_to_bits(_seedexpand(seed, p.n_bytes), p.n)


# -- cyclic arithmetic --------------------------------------------------------


def _use_matmul_cyclic() -> bool:
    """Blocked-circulant MXU formulation by default; QRP2P_HQC_GATHER=1
    restores the rotated-gather loop for A/B runs.  Read at TRACE time
    (fresh process per setting, same caveat as QRP2P_PALLAS)."""
    import os

    return os.environ.get("QRP2P_HQC_GATHER", "0") != "1"


def _cyclic_block(n: int) -> int:
    """Shift-block size: bounds the (batch, K, n) Toeplitz transient."""
    return 256 if n <= 20000 else (128 if n <= 40000 else 64)


def _cyclic_mul_matmul(p: HQCParams, dense: jax.Array, sup: jax.Array) -> jax.Array:
    """Gather-free cyclic product: out = dense ⊛ onehot(sup) via blocked
    Toeplitz contractions under a ``lax.scan``.

    Per-lane dynamic gathers (the rotated-index loop below) serialise on
    TPU — the same hazard that cost ML-DSA 25-100x before its samplers went
    gather-free.  Here the support densifies to a one-hot row (a tiny
    w-element scatter), the dense vector is TRIPLED so every rotation is a
    contiguous window, and each block of K shift amounts takes ONE
    scalar-start dynamic window + K static slices (a Toeplitz expansion —
    no per-lane indices anywhere) contracted on the MXU against the one-hot
    slice.  O(n^2) int8 arithmetic instead of O(w*n) serialised gathers;
    arithmetic is what the chip has.
    """
    n = p.n
    k_blk = _cyclic_block(n)
    nblocks = -(-n // k_blk)
    batch = dense.shape[:-1]
    y = _support_to_bits(p, sup).astype(jnp.int8)
    pad = nblocks * k_blk - n
    if pad:
        y = jnp.pad(y, [(0, 0)] * len(batch) + [(0, pad)])
    d3 = jnp.concatenate([dense, dense, dense], axis=-1).astype(jnp.int8)

    def body(acc, blk):
        p0 = blk * k_blk
        # W[j] = d3[2n - p0 - (K-1) + j]; chunk[dp, i] = W[K-1-dp + i]
        #      = dense[(i - p0 - dp) mod n]  (start always > 0: tripled array)
        w_seg = lax.dynamic_slice_in_dim(d3, 2 * n - p0 - (k_blk - 1),
                                         n + k_blk - 1, axis=-1)
        chunk = jnp.stack(
            [w_seg[..., k_blk - 1 - dp : k_blk - 1 - dp + n]
             for dp in range(k_blk)],
            axis=-2,
        )  # (..., K, n)
        y_blk = lax.dynamic_slice_in_dim(y, p0, k_blk, axis=-1)
        acc = acc + jnp.einsum(
            "...kn,...k->...n", chunk, y_blk,
            preferred_element_type=jnp.int32,
        )
        return acc, None

    acc0 = jnp.zeros(batch + (n,), jnp.int32)
    acc, _ = lax.scan(body, acc0, jnp.arange(nblocks))
    return (acc & 1).astype(jnp.uint8)


def _cyclic_mul_sparse(p: HQCParams, dense: jax.Array, sup: jax.Array) -> jax.Array:
    """dense (batch, n) bits x support (batch, w) -> (batch, n) bits.

    out[i] = XOR_k dense[(i - p_k) mod n].  Dispatches to the blocked
    circulant MXU formulation by default; the per-support rotated-gather
    loop remains for A/B (QRP2P_HQC_GATHER=1).
    """
    if _use_matmul_cyclic():
        return _cyclic_mul_matmul(p, dense, sup)
    n = p.n
    w = sup.shape[-1]
    base = jnp.arange(n)

    def step(k, acc):
        pk = jnp.take_along_axis(sup, jnp.full(sup.shape[:-1] + (1,), k), axis=-1)
        idx = (base - pk) % n
        return acc + jnp.take_along_axis(dense.astype(jnp.int32), idx, axis=-1)

    acc = lax.fori_loop(0, w, step, jnp.zeros(dense.shape, jnp.int32))
    return (acc & 1).astype(jnp.uint8)


# -- Reed-Solomon over GF(2^8), in-graph --------------------------------------


def _rs_encode(p: HQCParams, msg: jax.Array) -> jax.Array:
    """(batch, k) int32 bytes -> (batch, n1) codeword."""
    g = jnp.asarray(np.asarray(_rs_gen_poly(p)[: 2 * p.delta], np.int32))
    red = 2 * p.delta
    rem0 = jnp.zeros(msg.shape[:-1] + (red,), jnp.int32)

    def step(j, rem):
        byte = jnp.take_along_axis(
            msg, jnp.full(msg.shape[:-1] + (1,), p.k - 1 - j), axis=-1
        )[..., 0]
        coef = byte ^ rem[..., -1]
        rem = jnp.concatenate([jnp.zeros_like(rem[..., :1]), rem[..., :-1]], axis=-1)
        return rem ^ _gf_mul(g, coef[..., None])

    rem = lax.fori_loop(0, p.k, step, rem0)
    return jnp.concatenate([rem, msg], axis=-1)


def _rs_syndromes(p: HQCParams, cw: jax.Array) -> jax.Array:
    red = 2 * p.delta
    ij = np.outer(np.arange(1, red + 1), np.arange(p.n1)) % 255
    alpha_ij = jnp.asarray(_EXP[ij])  # (red, n1)
    terms = _gf_mul(cw[..., None, :], jnp.broadcast_to(alpha_ij, cw.shape[:-1] + (red, p.n1)))
    return _xor_reduce(terms, -1)  # (batch, red)


def _rs_bm(p: HQCParams, synd: jax.Array) -> jax.Array:
    """Branch-free Berlekamp-Massey -> sigma (batch, red+1) int32."""
    red = 2 * p.delta
    batch = synd.shape[:-1]
    deg = red + 1
    sigma0 = jnp.zeros(batch + (deg,), jnp.int32).at[..., 0].set(1)
    b0 = sigma0
    state = (sigma0, b0, jnp.zeros(batch, jnp.int32), jnp.ones(batch, jnp.int32),
             jnp.ones(batch, jnp.int32))  # sigma, b, L, bb, m

    spad = jnp.concatenate([jnp.zeros(batch + (deg,), jnp.int32), synd], axis=-1)

    def step(n_it, st):
        sigma, b, L, bb, m = st
        # d = XOR_i sigma[i] * S[n_it - i]  (S index via padded gather)
        sidx = (deg + n_it) - jnp.arange(deg)  # positions in spad
        s_slice = jnp.take(spad, sidx, axis=-1) if spad.ndim == 1 else jnp.take_along_axis(
            spad, jnp.broadcast_to(sidx, batch + (deg,)), axis=-1
        )
        d = _xor_reduce(_gf_mul(sigma, s_slice), -1)
        dz = d == 0
        inv_bb = jnp.take(jnp.asarray(_EXP), (255 - jnp.take(jnp.asarray(_LOG), bb)) % 255)
        coef = _gf_mul(d, inv_bb)
        # shifted = x^m * b  (gather with negative-index mask)
        tgt = jnp.arange(deg) - m[..., None]
        shifted = jnp.where(
            tgt >= 0,
            jnp.take_along_axis(b, jnp.maximum(tgt, 0), axis=-1),
            0,
        )
        sigma_new = sigma ^ _gf_mul(coef[..., None], shifted)
        grow = (~dz) & (2 * L <= n_it)
        sigma_out = jnp.where(dz[..., None], sigma, sigma_new)
        b_out = jnp.where(grow[..., None], sigma, b)
        L_out = jnp.where(grow, n_it + 1 - L, L)
        bb_out = jnp.where(grow, d, bb)
        m_out = jnp.where(grow, 1, m + 1)
        return sigma_out, b_out, L_out, bb_out, m_out

    sigma, *_ = lax.fori_loop(0, red, step, state)
    return sigma


def _rs_decode(p: HQCParams, cw: jax.Array) -> jax.Array:
    """(batch, n1) int32 -> (batch, k) message bytes (corrects <= delta errors)."""
    red = 2 * p.delta
    deg = red + 1
    synd = _rs_syndromes(p, cw)
    sigma = _rs_bm(p, synd)
    # Chien over all positions: sigma(alpha^{-j})
    ij = np.outer(np.arange(deg), (255 - np.arange(p.n1)) % 255) % 255
    xpow = jnp.asarray(_EXP[ij])  # (deg, n1): (alpha^{-j})^i
    ev = _xor_reduce(_gf_mul(sigma[..., :, None], xpow), -2)  # (batch, n1)
    is_err = ev == 0
    # omega = S(x) * sigma(x) mod x^red, one static contraction per degree
    omega = []
    for i in range(red):
        terms = []
        for j in range(min(i + 1, deg)):
            terms.append((j, i - j))
        idx_sig = np.array([t[0] for t in terms])
        idx_s = np.array([t[1] for t in terms])
        prod = _gf_mul(sigma[..., idx_sig], synd[..., idx_s])
        omega.append(_xor_reduce(prod, -1))
    omega = jnp.stack(omega, axis=-1)  # (batch, red)
    # Forney at every position (masked by is_err): num = omega(alpha^{-j})
    ijo = np.outer(np.arange(red), (255 - np.arange(p.n1)) % 255) % 255
    xpo = jnp.asarray(_EXP[ijo])  # (red, n1)
    num = _xor_reduce(_gf_mul(omega[..., :, None], xpo), -2)
    # den = sigma'(alpha^{-j}) = sum over odd i of sigma[i] (alpha^{-j})^{i-1}
    odd = np.arange(1, deg, 2)
    ijd = np.outer(odd - 1, (255 - np.arange(p.n1)) % 255) % 255
    xpd = jnp.asarray(_EXP[ijd])  # (len(odd), n1)
    den = _xor_reduce(_gf_mul(sigma[..., odd, None], xpd), -2)
    log = jnp.asarray(_LOG)
    exp = jnp.asarray(_EXP)
    inv_den = jnp.where(den == 0, 0, jnp.take(exp, (255 - jnp.take(log, den)) % 255))
    mag = _gf_mul(num, inv_den)
    corrected = cw ^ jnp.where(is_err & (den != 0), mag, 0)
    return corrected[..., red:]


# -- duplicated RM(1,7) -------------------------------------------------------


def _rm_encode(p: HQCParams, rs_cw: jax.Array) -> jax.Array:
    """(batch, n1) bytes -> (batch, n1*n2) bits."""
    table = jnp.asarray(_RM_BITS, jnp.uint8)
    cw = jnp.take(table, rs_cw, axis=0)  # (batch, n1, 128)
    dup = jnp.repeat(cw[..., None, :], p.dup, axis=-2)  # (batch, n1, dup, 128)
    return dup.reshape(rs_cw.shape[:-1] + (p.n1 * p.n2,))


def _rm_decode(p: HQCParams, bits: jax.Array) -> jax.Array:
    """(batch, n1*n2) bits -> (batch, n1) decoded bytes (soft FHT)."""
    x = bits.reshape(bits.shape[:-1] + (p.n1, p.dup, RM_N)).astype(jnp.int32)
    f = jnp.sum(1 - 2 * x, axis=-2)  # (batch, n1, 128) soft counts
    h = 1
    while h < RM_N:
        fr = f.reshape(f.shape[:-1] + (RM_N // (2 * h), 2, h))
        a, b = fr[..., 0, :], fr[..., 1, :]
        f = jnp.stack([a + b, a - b], axis=-2).reshape(f.shape)
        h *= 2
    best = jnp.argmax(jnp.abs(f), axis=-1)  # (batch, n1)
    fbest = jnp.take_along_axis(f, best[..., None], axis=-1)[..., 0]
    b0 = (fbest < 0).astype(jnp.int32)
    return (best << 1) | b0


# -- hashes -------------------------------------------------------------------


def _hash_dom(data: jax.Array, domain: int, out_len: int = 64) -> jax.Array:
    """SHAKE256-512 with TRAILING domain byte (HQC hash.c shake256_512_ds)."""
    sfx = jnp.broadcast_to(jnp.uint8(domain), data.shape[:-1] + (1,))
    return keccak.shake256(jnp.concatenate([data, sfx], axis=-1), out_len)


# -- KEM ----------------------------------------------------------------------


def keygen(p: HQCParams, sk_seed: jax.Array, sigma: jax.Array, pk_seed: jax.Array):
    """sk_seed (..., 40), sigma (..., k), pk_seed (..., 40) -> (pk, sk)."""
    sk_seed = jnp.asarray(sk_seed, jnp.uint8)
    sigma = jnp.asarray(sigma, jnp.uint8)
    pk_seed = jnp.asarray(pk_seed, jnp.uint8)
    h = _sample_random_bits(p, pk_seed)
    # one sk expander stream: y first, then x (pyref keygen order)
    sk_stream = _u32s(_seedexpand(sk_seed, 8 * p.w))
    y_sup = _fixed_weight_support(p, sk_stream[..., : p.w], p.w)
    x_sup = _fixed_weight_support(p, sk_stream[..., p.w :], p.w)
    x = _support_to_bits(p, x_sup)
    s = x ^ _cyclic_mul_sparse(p, h, y_sup)
    pk = jnp.concatenate([pk_seed, _bits_to_bytes(s)], axis=-1)
    sk = jnp.concatenate([sk_seed, sigma, pk], axis=-1)
    return pk, sk


def _encrypt(p: HQCParams, pk: jax.Array, m: jax.Array, theta: jax.Array):
    pk_seed = pk[..., :40]
    s = _bytes_to_bits(pk[..., 40:], p.n)
    h = _sample_random_bits(p, pk_seed)
    # one theta expander stream: r2, e, r1 (pyref _encrypt order)
    stream = _u32s(_seedexpand(theta, 12 * p.wr))
    r2_sup = _fixed_weight_support(p, stream[..., : p.wr], p.wr)
    e_sup = _fixed_weight_support(p, stream[..., p.wr : 2 * p.wr], p.wr)
    r1_sup = _fixed_weight_support(p, stream[..., 2 * p.wr :], p.wr)
    u = _support_to_bits(p, r1_sup) ^ _cyclic_mul_sparse(p, h, r2_sup)
    code = _rm_encode(p, _rs_encode(p, m.astype(jnp.int32)))
    t = _cyclic_mul_sparse(p, s, r2_sup) ^ _support_to_bits(p, e_sup)
    v = code ^ t[..., : p.n1 * p.n2]
    return u, v


def encaps(p: HQCParams, pk: jax.Array, m: jax.Array, salt: jax.Array):
    """pk, m (..., k), salt (..., 16) -> (ct (..., ct_len), ss (..., 64))."""
    pk = jnp.asarray(pk, jnp.uint8)
    m = jnp.asarray(m, jnp.uint8)
    salt = jnp.asarray(salt, jnp.uint8)
    theta = _hash_dom(jnp.concatenate([m, pk[..., :32], salt], axis=-1), 3)
    u, v = _encrypt(p, pk, m, theta)
    u_b = _bits_to_bytes(u)
    v_b = _bits_to_bytes(v)
    ct = jnp.concatenate([u_b, v_b, salt], axis=-1)
    ss = _hash_dom(jnp.concatenate([m, u_b, v_b], axis=-1), 4)
    return ct, ss


def decaps(p: HQCParams, sk: jax.Array, ct: jax.Array):
    sk = jnp.asarray(sk, jnp.uint8)
    ct = jnp.asarray(ct, jnp.uint8)
    sk_seed = sk[..., :40]
    sigma = sk[..., 40 : 40 + p.k]
    pk = sk[..., 40 + p.k :]
    u_b = ct[..., : p.n_bytes]
    v_b = ct[..., p.n_bytes : p.n_bytes + p.n1n2_bytes]
    salt = ct[..., p.n_bytes + p.n1n2_bytes :]
    u = _bytes_to_bits(u_b, p.n)
    v = _bytes_to_bits(v_b, p.n1 * p.n2)
    # y = first fixed-weight draw off the sk expander stream
    sk_stream = _u32s(_seedexpand(sk_seed, 4 * p.w))
    y_sup = _fixed_weight_support(p, sk_stream, p.w)
    uy = _cyclic_mul_sparse(p, u, y_sup)
    m_p = _rs_decode(p, _rm_decode(p, v ^ uy[..., : p.n1 * p.n2])).astype(jnp.uint8)
    theta_p = _hash_dom(jnp.concatenate([m_p, pk[..., :32], salt], axis=-1), 3)
    u2, v2 = _encrypt(p, pk, m_p, theta_p)
    ok = jnp.all(_bits_to_bytes(u2) == u_b, axis=-1) & jnp.all(
        _bits_to_bytes(v2) == v_b, axis=-1
    )
    good = _hash_dom(jnp.concatenate([m_p, u_b, v_b], axis=-1), 4)
    bad = _hash_dom(jnp.concatenate([sigma, u_b, v_b], axis=-1), 4)
    return jnp.where(ok[..., None], good, bad)


@functools.cache
def get(name: str):
    """Jitted (keygen, encaps, decaps) triple for a parameter-set name."""
    p = PARAMS[name]
    return (
        jax.jit(functools.partial(keygen, p)),
        jax.jit(functools.partial(encaps, p)),
        jax.jit(functools.partial(decaps, p)),
    )
