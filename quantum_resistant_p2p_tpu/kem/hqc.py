"""Batched HQC in JAX — quasi-cyclic GF(2) codes on the VPU.

TPU-native design
-----------------
HQC is the least matmul-shaped algorithm in the suite (SURVEY.md §7.4 ranks
it hardest to map); the decomposition here:

* Code vectors live as dense (batch, n) uint8 bit arrays — no 64-bit packing
  (TPUs have no 64-bit lanes and XLA vectorises byte lanes fine).  The
  sparse-by-dense cyclic product x^p * a mod (x^n - 1) is an exact-f32
  FFT convolution by default (``_cyclic_mul_fft`` — conv values <= w <= 149
  sit far inside float32's exact integer range); the blocked-Toeplitz MXU
  contraction (``QRP2P_HQC_FFT=0``) and the rotated-gather loop
  (``QRP2P_HQC_GATHER=1``) remain for A/B.
* The inner RM(1,7) decoder is a batched fast Hadamard transform (7 static
  butterfly stages) over soft-combined duplicates — exactly the
  structure TPUs like.
* The outer Reed-Solomon decoder runs entirely in-graph and GATHER-FREE:
  GF(256) products against static constants (syndrome grids, Chien/Forney
  evaluation points, generator polynomials) are 8 masked XORs against
  precomputed ``x^k * c`` tables; variable-by-variable products
  (Berlekamp-Massey) are branch-free carry-less-multiply + polynomial
  reduction circuits; inversion is the ``b^254`` addition chain.  BM's
  ``x^m * B(x)`` term — a per-lane dynamic shift in the textbook
  formulation — is maintained incrementally as a shift-by-one of a
  select, so no per-lane indices exist anywhere in the decode path.
  (Round 3 first measurement had log/exp-gather GF ops; this rewrite
  removed the family's last table gathers.)
* Fisher-Yates fixed-weight sampling follows the same downward-scan dedup as
  the oracle (sequential fori_loop over w slots, vectorised compares).

Bit-exactness oracle: ``pyref.hqc_ref`` — see that module's compatibility
note: with liboqs stripped from the reference checkout, the PRNG seam is this
framework's own; cpu and tpu backends are bit-exact against each other.
Replaces (reference): HQCKeyExchange (crypto/key_exchange.py:189-309).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core import keccak
from ..pyref.hqc_ref import (
    _GF_EXP,
    _RM_ENC_TABLE,
    RM_N,
    HQCParams,
    PARAMS,
    _rs_gen_poly,
)

#: Single-dispatch batch cap (provider/base.py sliced_dispatch).  Round 2
#: observed a 256-row keygen dispatch crashing the remote TPU worker; the
#: round-3 bisection (tools/repro_worker_fault.py) found no deterministic
#: fault (transient worker state), and the late-round FFT cyclic product
#: shrank HQC's working set by orders of magnitude (33 MB spectra instead
#: of the Toeplitz chunk expansion), removing the original caution's
#: substance: batch 512 measured clean and ~8% faster than 128
#: (bench_results/r3_hqc_fft_levels.json).  512 balances that against
#: queue latency; the batched provider's cpu fallback + breaker absorb
#: any transient recurrence.
MAX_DEVICE_BATCH = 512

_EXP = np.asarray(_GF_EXP, dtype=np.int32)  # length 512 (host-side table builds)

# RM(1,7) encode table as a (256, 128) bit matrix
_RM_BITS = np.array(
    [[(cw >> j) & 1 for j in range(RM_N)] for cw in _RM_ENC_TABLE], dtype=np.int32
)


# field modulus recovered from the pyref tables: x^8 ≡ exp[8] (mod poly)
# for a degree-8 monic modulus means poly = 0x100 | exp[8]  (= 0x11D for HQC)
_GF_POLY = int(_GF_EXP[8] | 0x100)


def _gf_mul(a: jax.Array, b: jax.Array) -> jax.Array:
    """GF(256) product, gather-free: 8-step carry-less multiply + 7-step
    polynomial reduction, pure AND/XOR/shift on int32 lanes.  Replaces the
    log/exp table lookups (3 per-lane gathers per product — the TPU
    anti-pattern this module eliminated everywhere else)."""
    a = a.astype(jnp.int32) if isinstance(a, jax.Array) else jnp.asarray(a, jnp.int32)
    b = b.astype(jnp.int32) if isinstance(b, jax.Array) else jnp.asarray(b, jnp.int32)
    p = jnp.zeros(jnp.broadcast_shapes(a.shape, b.shape), jnp.int32)
    for k in range(8):
        p = p ^ ((-((b >> k) & 1)) & (a << k))
    for k in range(14, 7, -1):
        p = p ^ ((-((p >> k) & 1)) & (_GF_POLY << (k - 8)))
    return p


def _gf_inv(x: jax.Array) -> jax.Array:
    """x^254 = x^-1 in GF(256) (0 -> 0), 4-multiply/7-square chain."""
    x2 = _gf_mul(x, x)
    x3 = _gf_mul(x2, x)
    x12 = _gf_mul(_gf_mul(x3, x3), _gf_mul(x3, x3))
    x15 = _gf_mul(x12, x3)
    x240 = x15
    for _ in range(4):
        x240 = _gf_mul(x240, x240)
    return _gf_mul(_gf_mul(x240, x12), x2)


def _gf_const_tables(c: np.ndarray) -> np.ndarray:
    """(8,) + c.shape int32 tables t[k] = x^k * c, for masked-XOR products."""
    c = np.asarray(c, np.int64)
    out = np.zeros((8,) + c.shape, np.int32)
    for k in range(8):
        v = c << k
        for j in range(14, 7, -1):
            v = np.where((v >> j) & 1, v ^ (_GF_POLY << (j - 8)), v)
        out[k] = v
    return out


def _gf_mul_const(x: jax.Array, tables: jax.Array) -> jax.Array:
    """GF(256) product of variable x against precomputed constant tables
    (from :func:`_gf_const_tables`): 8 masked XORs, no reduction step."""
    x = x.astype(jnp.int32)
    acc = jnp.zeros(jnp.broadcast_shapes(x.shape, tables.shape[1:]), jnp.int32)
    for k in range(8):
        acc = acc ^ ((-((x >> k) & 1)) & tables[k])
    return acc


def _xor_reduce(x: jax.Array, axis: int) -> jax.Array:
    return lax.reduce(x, np.int32(0), lax.bitwise_xor, (axis % x.ndim,))


# -- bit/byte helpers ---------------------------------------------------------


def _bytes_to_bits(b: jax.Array, nbits: int) -> jax.Array:
    bits = (b[..., :, None].astype(jnp.int32) >> np.arange(8)) & 1
    return bits.reshape(b.shape[:-1] + (-1,))[..., :nbits].astype(jnp.uint8)


def _bits_to_bytes(bits: jax.Array) -> jax.Array:
    nbits = bits.shape[-1]
    pad = (-nbits) % 8
    if pad:
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (pad,), bits.dtype)], axis=-1
        )
    grp = bits.reshape(bits.shape[:-1] + (-1, 8)).astype(jnp.int32)
    return jnp.sum(grp << np.arange(8), axis=-1).astype(jnp.uint8)


# -- sampling -----------------------------------------------------------------


def _seedexpand(seed: jax.Array, out_len: int) -> jax.Array:
    """HQC seedexpander stream: SHAKE256(seed || 0x02) squeezed to out_len.
    Callers slice consecutive reads off one stream (pyref SeedExpander)."""
    dom = jnp.broadcast_to(jnp.uint8(2), seed.shape[:-1] + (1,))
    return keccak.shake256(jnp.concatenate([seed, dom], axis=-1), out_len)


def _u32s(buf: jax.Array) -> jax.Array:
    """(..., 4k) uint8 -> (..., k) uint32 little-endian."""
    b = buf.astype(jnp.uint32).reshape(buf.shape[:-1] + (-1, 4))
    return b[..., 0] | (b[..., 1] << 8) | (b[..., 2] << 16) | (b[..., 3] << 24)


def _mulhi32(a: jax.Array, m: int) -> jax.Array:
    """floor(a * m / 2**32) for uint32 a and python int m < 2**16, exactly,
    without 64-bit lanes: split a into 16-bit halves."""
    assert 0 < m < (1 << 16), f"16-bit split requires m < 2^16, got {m}"
    a1 = a >> 16
    a0 = a & jnp.uint32(0xFFFF)
    # a*m = a1*m*2^16 + a0*m ; both partial products fit uint32 (m < 2^16)
    return (a1 * jnp.uint32(m) + ((a0 * jnp.uint32(m)) >> 16)) >> 16


def _fixed_weight_support(p: HQCParams, rand: jax.Array, weight: int) -> jax.Array:
    """(batch, weight) uint32 randoms -> (batch, weight) int32 positions.

    HQC vect_set_random_fixed_weight: i + (rand32 * (n-i)) >> 32, duplicates
    replaced by their index in a reverse scan (oracle-identical dedup).
    """
    cols = [
        (jnp.uint32(i) + _mulhi32(rand[..., i], p.n - i)).astype(jnp.int32)
        for i in range(weight)
    ]
    sup = jnp.stack(cols, axis=-1)

    idx = jnp.arange(weight)

    def fix(k, s):
        i = weight - 1 - k
        # contiguous dynamic slice + masked write — no per-lane gather/scatter
        si = lax.dynamic_slice_in_dim(s, i, 1, axis=-1)
        clash = jnp.any((s == si) & (idx > i), axis=-1, keepdims=True)
        si_new = jnp.where(clash, i, si)
        return jnp.where(idx == i, si_new, s)

    return lax.fori_loop(0, weight, fix, sup)


def _support_to_bits(p: HQCParams, sup: jax.Array) -> jax.Array:
    """(batch, w) positions -> (batch, n) uint8 bits."""
    v = jnp.zeros(sup.shape[:-1] + (p.n,), jnp.uint8)
    return jnp.put_along_axis(v, sup, jnp.uint8(1), axis=-1, inplace=False)


def _sample_random_bits(p: HQCParams, seed: jax.Array) -> jax.Array:
    """h: first n_bytes of the seed's expander stream."""
    return _bytes_to_bits(_seedexpand(seed, p.n_bytes), p.n)


# -- cyclic arithmetic --------------------------------------------------------


#: Set by :func:`get` when the FFT environment self-check FAILS: overrides
#: the default formulation for the rest of the process (jit caches the
#: traced path, so this must be decided before the first trace).
_FORCED_IMPL: str | None = None


def _cyclic_impl() -> str:
    """Which cyclic-product formulation to trace: "fft" (default),
    "matmul" (QRP2P_HQC_FFT=0 — the blocked-circulant MXU path), or
    "gather" (QRP2P_HQC_GATHER=1 — the rotated-gather loop).  Read at
    TRACE time (fresh process per setting, same caveat as QRP2P_PALLAS)."""
    import os

    if os.environ.get("QRP2P_HQC_GATHER", "0") == "1":
        return "gather"
    if os.environ.get("QRP2P_HQC_FFT", "1") == "0":
        return "matmul"
    if _FORCED_IMPL is not None:
        return _FORCED_IMPL
    return "fft"


def _cyclic_block(n: int) -> int:
    """Shift-block size: bounds the (batch, K, n) Toeplitz transient."""
    return 256 if n <= 20000 else (128 if n <= 40000 else 64)


def _cyclic_mul_matmul(p: HQCParams, dense: jax.Array, sup: jax.Array) -> jax.Array:
    """Gather-free cyclic product: out = dense ⊛ onehot(sup) via blocked
    Toeplitz contractions under a ``lax.scan``.

    Per-lane dynamic gathers (the rotated-index loop below) serialise on
    TPU — the same hazard that cost ML-DSA 25-100x before its samplers went
    gather-free.  Here the support densifies to a one-hot row (a tiny
    w-element scatter), the dense vector is TRIPLED so every rotation is a
    contiguous window, and each block of K shift amounts takes ONE
    scalar-start dynamic window + K static slices (a Toeplitz expansion —
    no per-lane indices anywhere) contracted on the MXU against the one-hot
    slice.  O(n^2) int8 arithmetic instead of O(w*n) serialised gathers;
    arithmetic is what the chip has.
    """
    n = p.n
    k_blk = _cyclic_block(n)
    nblocks = -(-n // k_blk)
    batch = dense.shape[:-1]
    y = _support_to_bits(p, sup).astype(jnp.int8)
    pad = nblocks * k_blk - n
    if pad:
        y = jnp.pad(y, [(0, 0)] * len(batch) + [(0, pad)])
    d3 = jnp.concatenate([dense, dense, dense], axis=-1).astype(jnp.int8)

    def body(acc, blk):
        p0 = blk * k_blk
        # W[j] = d3[2n - p0 - (K-1) + j]; chunk[dp, i] = W[K-1-dp + i]
        #      = dense[(i - p0 - dp) mod n]  (start always > 0: tripled array)
        w_seg = lax.dynamic_slice_in_dim(d3, 2 * n - p0 - (k_blk - 1),
                                         n + k_blk - 1, axis=-1)
        chunk = jnp.stack(
            [w_seg[..., k_blk - 1 - dp : k_blk - 1 - dp + n]
             for dp in range(k_blk)],
            axis=-2,
        )  # (..., K, n)
        y_blk = lax.dynamic_slice_in_dim(y, p0, k_blk, axis=-1)
        acc = acc + jnp.einsum(
            "...kn,...k->...n", chunk, y_blk,
            preferred_element_type=jnp.int32,
        )
        return acc, None

    acc0 = jnp.zeros(batch + (n,), jnp.int32)
    acc, _ = lax.scan(body, acc0, jnp.arange(nblocks))
    return (acc & 1).astype(jnp.uint8)


def _fft_circ(p: HQCParams, dense: jax.Array, sup: jax.Array) -> jax.Array:
    """Float32 circular-convolution counts (pre-rounding) — shared by the
    production path and the environment self-check probe."""
    n = p.n
    nfft = 1 << (2 * n - 2).bit_length()
    y = _support_to_bits(p, sup)
    fd = jnp.fft.rfft(dense.astype(jnp.float32), nfft, axis=-1)
    fy = jnp.fft.rfft(y.astype(jnp.float32), nfft, axis=-1)
    lin = jnp.fft.irfft(fd * fy, nfft, axis=-1)
    tail = jnp.pad(lin[..., n : 2 * n - 1], [(0, 0)] * (lin.ndim - 1) + [(0, 1)])
    return lin[..., :n] + tail


def _cyclic_mul_fft(p: HQCParams, dense: jax.Array, sup: jax.Array) -> jax.Array:
    """Cyclic product as an exact float32 FFT convolution.

    The integer circular convolution of two 0/1 vectors has values
    <= w <= 149 — far inside float32's exact-integer range — and the
    f32 round-trip error at these sizes measures ~1e-4 (worst case
    all-ones dense, asserted in tests/test_hqc.py), a ~5000x margin
    under the 0.5 rounding threshold.  Because that margin is measured,
    not proven, the first :func:`get` in an environment runs
    :func:`_fft_selfcheck` on-device and falls back to the Toeplitz
    path if it fails.  O(N log N) replaces the Toeplitz path's O(n^2)
    MACs and, more importantly, its ~chunk-materialisation HBM traffic
    (the measured bottleneck of every HQC op).  n is prime (no length-n
    FFT), so a pow2-padded LINEAR convolution is folded back to
    circular: circ[i] = lin[i] + lin[i + n].
    """
    circ = _fft_circ(p, dense, sup)
    return (jnp.rint(circ).astype(jnp.int32) & 1).astype(jnp.uint8)


def _cyclic_mul_sparse(p: HQCParams, dense: jax.Array, sup: jax.Array) -> jax.Array:
    """dense (batch, n) bits x support (batch, w) -> (batch, n) bits.

    out[i] = XOR_k dense[(i - p_k) mod n].  Dispatches to the exact-f32
    FFT convolution by default; the blocked-circulant MXU formulation
    (QRP2P_HQC_FFT=0) and the per-support rotated-gather loop
    (QRP2P_HQC_GATHER=1) remain for A/B.

    PRECONDITION: support positions must be pairwise distinct (guaranteed
    by :func:`_fixed_weight_support`'s dedup).  The three formulations
    disagree on duplicates — FFT/matmul go through ``_support_to_bits``
    where duplicates collapse to ONE hit, while the rotated-gather loop
    counts each, so a doubled position cancels mod 2.  Distinctness is the
    stated common contract; nothing in the KEM can violate it, and it is
    asserted below (under ``__debug__``, on concrete inputs only — traced
    values cannot be inspected) so an A/B harness feeding a duplicated
    support fails HERE, not as a silent cross-implementation divergence.
    """
    if __debug__ and not isinstance(sup, jax.core.Tracer):
        _s = np.sort(np.asarray(sup), axis=-1)
        assert bool((np.diff(_s, axis=-1) != 0).all()), (
            "_cyclic_mul_sparse: support positions must be pairwise distinct "
            "(the FFT/matmul and rotated-gather formulations disagree on "
            "duplicates)"
        )
    impl = _cyclic_impl()
    if impl == "fft":
        return _cyclic_mul_fft(p, dense, sup)
    if impl == "matmul":
        return _cyclic_mul_matmul(p, dense, sup)
    n = p.n
    w = sup.shape[-1]
    base = jnp.arange(n)

    def step(k, acc):
        pk = jnp.take_along_axis(sup, jnp.full(sup.shape[:-1] + (1,), k), axis=-1)
        idx = (base - pk) % n
        return acc + jnp.take_along_axis(dense.astype(jnp.int32), idx, axis=-1)

    acc = lax.fori_loop(0, w, step, jnp.zeros(dense.shape, jnp.int32))
    return (acc & 1).astype(jnp.uint8)


# -- FFT environment self-check ----------------------------------------------
#
# The FFT cyclic product is exact only while device-FFT rounding stays under
# 0.5; the measured margin (~1e-4) is empirical, so a new device / XLA / JAX
# version could silently flip KEM bits.  The first `get()` per environment
# therefore runs an on-device probe and falls back to the Toeplitz-MXU path
# on failure.  The verdict is cached per (jax version, jaxlib version,
# device kind) in ~/.cache/qrp2p_tpu so the cost is once per environment,
# not per process.  QRP2P_HQC_SELFCHECK=0 skips the gate (trust the FFT);
# tools/check_pallas_device.py remains the manual on-chip A/B.


def _fft_selfcheck(p: HQCParams) -> tuple[bool, float]:
    """On-device exactness probe for the f32 FFT cyclic product.

    Runs the largest transform in the suite with (a) all-ones dense — the
    worst-case convolution magnitude — and (b) random dense, comparing bits
    against a host-exact XOR-of-rotations and requiring the pre-rounding
    residual max|circ - rint(circ)| < 0.25 (2x margin under the rounding
    threshold).  Returns (ok, worst_residual).
    """
    rng = np.random.default_rng(0x48514346)  # "HQCF"
    sup = np.sort(rng.choice(p.n, size=p.wr, replace=False)).astype(np.int32)

    @jax.jit
    def probe(dense, sup):
        circ = _fft_circ(p, dense, sup)
        bits = (jnp.rint(circ).astype(jnp.int32) & 1).astype(jnp.uint8)
        return bits, jnp.max(jnp.abs(circ - jnp.rint(circ)))

    ok, worst = True, 0.0
    for dense in (np.ones(p.n, np.uint8), rng.integers(0, 2, p.n, np.uint8)):
        bits, resid = probe(dense[None], sup[None])
        acc = np.zeros(p.n, np.int64)
        for pos in sup:
            acc += np.roll(dense.astype(np.int64), pos)
        ok &= bool((np.asarray(bits)[0] == (acc & 1).astype(np.uint8)).all())
        worst = max(worst, float(resid))
    return ok and worst < 0.25, worst


def _fft_env_key() -> str:
    import jaxlib

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform)
    return f"jax={jax.__version__}|jaxlib={jaxlib.__version__}|dev={kind}"


#: In-process memo of the environment verdict (None = not yet decided) —
#: without it an unwritable ~/.cache would re-run the on-device probe on
#: every get() call.
_FFT_ENV_OK: bool | None = None


def _fft_env_validated() -> bool:
    """Cached per-environment verdict; runs the probe on first sight."""
    global _FFT_ENV_OK
    import hashlib
    import json
    import logging
    import pathlib

    if _FFT_ENV_OK is not None:
        return _FFT_ENV_OK
    from ..native import _CACHE_DIR  # shared cache dir (QRP_NATIVE_CACHE)

    key = _fft_env_key()
    p = max(PARAMS.values(), key=lambda q: q.n)  # largest transform + weight
    cache = pathlib.Path(_CACHE_DIR)
    marker = cache / f"hqc_fft_ok_{hashlib.sha256(key.encode()).hexdigest()[:16]}.json"
    try:
        rec = json.loads(marker.read_text())
        # probe_n guards against a stale verdict from an older package
        # whose largest parameter set was smaller than today's.  Only a
        # POSITIVE verdict is trusted from the marker: this platform's
        # device faults are documented transient, so a failed probe
        # re-runs every process (self-healing) rather than pinning the
        # slow Toeplitz path forever.
        if isinstance(rec, dict) and rec.get("key") == key and rec.get("probe_n") == p.n:
            if rec.get("ok"):
                _FFT_ENV_OK = True
                return True
    except (OSError, ValueError, KeyError):
        pass
    ok, resid = _fft_selfcheck(p)
    if not ok:
        logging.getLogger(__name__).warning(
            "HQC f32-FFT self-check FAILED on %s (residual %.3g) — "
            "falling back to the Toeplitz-MXU cyclic product for this "
            "process (re-probed at next process start)", key, resid
        )
    if ok:
        try:
            cache.mkdir(parents=True, exist_ok=True)
            marker.write_text(json.dumps(
                {"key": key, "ok": ok, "worst_residual": resid, "probe_n": p.n}
            ))
        except OSError:
            pass
    _FFT_ENV_OK = ok
    return ok


def _maybe_gate_fft() -> None:
    """Decide the FFT-vs-Toeplitz default for this process (called by
    :func:`get` before anything is traced)."""
    global _FORCED_IMPL
    import os

    if _FORCED_IMPL is not None or _cyclic_impl() != "fft":
        return
    if os.environ.get("QRP2P_HQC_SELFCHECK", "1") == "0":
        return
    if not _fft_env_validated():
        _FORCED_IMPL = "matmul"


# -- Reed-Solomon over GF(2^8), in-graph --------------------------------------


@functools.cache
def _rs_gen_tables(p: HQCParams) -> np.ndarray:
    return _gf_const_tables(np.asarray(_rs_gen_poly(p)[: 2 * p.delta], np.int64))


def _rs_encode(p: HQCParams, msg: jax.Array) -> jax.Array:
    """(batch, k) int32 bytes -> (batch, n1) codeword.

    Unrolled LFSR division (k <= 32 static steps) with the generator
    product as masked XORs against constant tables — no gathers."""
    g_tab = jnp.asarray(_rs_gen_tables(p))
    red = 2 * p.delta
    rem = jnp.zeros(msg.shape[:-1] + (red,), jnp.int32)
    for j in range(p.k):
        coef = msg[..., p.k - 1 - j] ^ rem[..., -1]
        rem = jnp.concatenate([jnp.zeros_like(rem[..., :1]), rem[..., :-1]], axis=-1)
        rem = rem ^ _gf_mul_const(coef[..., None], g_tab)
    return jnp.concatenate([rem, msg], axis=-1)


@functools.cache
def _syndrome_tables(p: HQCParams) -> np.ndarray:
    red = 2 * p.delta
    ij = np.outer(np.arange(1, red + 1), np.arange(p.n1)) % 255
    return _gf_const_tables(_EXP[ij].astype(np.int64))  # (8, red, n1)


def _rs_syndromes(p: HQCParams, cw: jax.Array) -> jax.Array:
    terms = _gf_mul_const(cw[..., None, :], jnp.asarray(_syndrome_tables(p)))
    return _xor_reduce(terms, -1)  # (batch, red)


def _rs_bm(p: HQCParams, synd: jax.Array) -> jax.Array:
    """Branch-free, gather-free Berlekamp-Massey -> sigma (batch, red+1).

    Two reformulations keep per-lane indices out of the scan body: the
    syndrome window S[n_it], .., S[n_it-deg+1] is one contiguous
    ``dynamic_slice`` of the zero-padded syndrome array (reversed — a
    static op), and the textbook ``x^m * B(x)`` update term — a per-lane
    dynamic shift, since m is data-dependent — is carried incrementally:
    ``D_next = x * (sigma_old if grow else D)``, a shift-by-one of a
    select, which reproduces x^m * B exactly (m resets to 1 on growth).
    """
    red = 2 * p.delta
    batch = synd.shape[:-1]
    deg = red + 1
    sigma0 = jnp.zeros(batch + (deg,), jnp.int32).at[..., 0].set(1)
    # D = x^m * B(x); initially m=1, B=1 => D = x
    d0 = jnp.zeros(batch + (deg,), jnp.int32).at[..., 1].set(1)
    state = (sigma0, d0, jnp.zeros(batch, jnp.int32), jnp.ones(batch, jnp.int32))

    spad = jnp.concatenate([jnp.zeros(batch + (deg,), jnp.int32), synd], axis=-1)

    def shift1(v):
        return jnp.concatenate([jnp.zeros_like(v[..., :1]), v[..., :-1]], axis=-1)

    def step(n_it, st):
        sigma, D, L, bb = st
        # d = XOR_i sigma[i] * S[n_it - i]: spad[n_it+1 .. n_it+deg] reversed
        window = lax.dynamic_slice_in_dim(spad, n_it + 1, deg, axis=-1)
        s_slice = jnp.flip(window, axis=-1)
        d = _xor_reduce(_gf_mul(sigma, s_slice), -1)
        dz = d == 0
        coef = _gf_mul(d, _gf_inv(bb))
        sigma_new = sigma ^ _gf_mul(coef[..., None], D)
        grow = (~dz) & (2 * L <= n_it)
        sigma_out = jnp.where(dz[..., None], sigma, sigma_new)
        D_out = shift1(jnp.where(grow[..., None], sigma, D))
        L_out = jnp.where(grow, n_it + 1 - L, L)
        bb_out = jnp.where(grow, d, bb)
        return sigma_out, D_out, L_out, bb_out

    sigma, *_ = lax.fori_loop(0, red, step, state)
    return sigma


@functools.cache
def _chien_forney_tables(p: HQCParams) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    red = 2 * p.delta
    deg = red + 1
    inv_j = (255 - np.arange(p.n1)) % 255
    ij = np.outer(np.arange(deg), inv_j) % 255
    ijo = np.outer(np.arange(red), inv_j) % 255
    odd = np.arange(1, deg, 2)
    ijd = np.outer(odd - 1, inv_j) % 255
    return (
        _gf_const_tables(_EXP[ij].astype(np.int64)),   # (8, deg, n1)
        _gf_const_tables(_EXP[ijo].astype(np.int64)),  # (8, red, n1)
        _gf_const_tables(_EXP[ijd].astype(np.int64)),  # (8, len(odd), n1)
    )


def _rs_decode(p: HQCParams, cw: jax.Array) -> jax.Array:
    """(batch, n1) int32 -> (batch, k) message bytes (corrects <= delta errors)."""
    red = 2 * p.delta
    t_chien, t_omega, t_deriv = (jnp.asarray(t) for t in _chien_forney_tables(p))
    synd = _rs_syndromes(p, cw)
    sigma = _rs_bm(p, synd)
    # Chien over all positions: sigma(alpha^{-j})
    ev = _xor_reduce(_gf_mul_const(sigma[..., :, None], t_chien), -2)  # (batch, n1)
    is_err = ev == 0
    # omega = S(x) * sigma(x) mod x^red: one static-slice contraction per
    # degree (sigma[..., i::-1] is a strided slice, not a gather)
    omega = []
    for i in range(red):
        prod = _gf_mul(sigma[..., : i + 1], jnp.flip(synd[..., : i + 1], -1))
        omega.append(_xor_reduce(prod, -1))
    omega = jnp.stack(omega, axis=-1)  # (batch, red)
    # Forney at every position (masked by is_err): num = omega(alpha^{-j})
    num = _xor_reduce(_gf_mul_const(omega[..., :, None], t_omega), -2)
    # den = sigma'(alpha^{-j}) = sum over odd i of sigma[i] (alpha^{-j})^{i-1}
    den = _xor_reduce(_gf_mul_const(sigma[..., 1::2, None], t_deriv), -2)
    mag = _gf_mul(num, _gf_inv(den))
    corrected = cw ^ jnp.where(is_err & (den != 0), mag, 0)
    return corrected[..., red:]


# -- duplicated RM(1,7) -------------------------------------------------------


# RM(1,7) is linear: encode(m) = XOR of generator rows selected by m's bits.
# Verified against the pyref table at import; kills the (256, 128) per-lane
# table gather in _rm_encode.
_RM_ROWS = np.stack([_RM_BITS[1 << k] for k in range(8)])  # (8, 128)
assert all(
    np.array_equal(
        np.bitwise_xor.reduce(
            [_RM_ROWS[k] for k in range(8) if (v >> k) & 1] or [np.zeros(RM_N, np.int32)]
        ),
        _RM_BITS[v],
    )
    for v in range(256)
), "RM(1,7) table is not linear — generator-row encode would be wrong"


def _rm_encode(p: HQCParams, rs_cw: jax.Array) -> jax.Array:
    """(batch, n1) bytes -> (batch, n1*n2) bits (linear masked-XOR encode)."""
    cw = _gf_mul_const(
        rs_cw[..., None], jnp.asarray(_RM_ROWS, jnp.int32)
    ).astype(jnp.uint8)  # (batch, n1, 128)
    dup = jnp.repeat(cw[..., None, :], p.dup, axis=-2)  # (batch, n1, dup, 128)
    return dup.reshape(rs_cw.shape[:-1] + (p.n1 * p.n2,))


def _rm_decode(p: HQCParams, bits: jax.Array) -> jax.Array:
    """(batch, n1*n2) bits -> (batch, n1) decoded bytes (soft FHT)."""
    x = bits.reshape(bits.shape[:-1] + (p.n1, p.dup, RM_N)).astype(jnp.int32)
    f = jnp.sum(1 - 2 * x, axis=-2)  # (batch, n1, 128) soft counts
    h = 1
    while h < RM_N:
        fr = f.reshape(f.shape[:-1] + (RM_N // (2 * h), 2, h))
        a, b = fr[..., 0, :], fr[..., 1, :]
        f = jnp.stack([a + b, a - b], axis=-2).reshape(f.shape)
        h *= 2
    best = jnp.argmax(jnp.abs(f), axis=-1)  # (batch, n1)
    # select f[best] without a per-lane gather: one-hot contraction
    onehot = (jnp.arange(RM_N) == best[..., None]).astype(jnp.int32)
    fbest = jnp.sum(f * onehot, axis=-1)
    b0 = (fbest < 0).astype(jnp.int32)
    return (best << 1) | b0


# -- hashes -------------------------------------------------------------------


def _hash_dom(data: jax.Array, domain: int, out_len: int = 64) -> jax.Array:
    """SHAKE256-512 with TRAILING domain byte (HQC hash.c shake256_512_ds)."""
    sfx = jnp.broadcast_to(jnp.uint8(domain), data.shape[:-1] + (1,))
    return keccak.shake256(jnp.concatenate([data, sfx], axis=-1), out_len)


# -- KEM ----------------------------------------------------------------------


def keygen(p: HQCParams, sk_seed: jax.Array, sigma: jax.Array, pk_seed: jax.Array):
    """sk_seed (..., 40), sigma (..., k), pk_seed (..., 40) -> (pk, sk)."""
    sk_seed = jnp.asarray(sk_seed, jnp.uint8)
    sigma = jnp.asarray(sigma, jnp.uint8)
    pk_seed = jnp.asarray(pk_seed, jnp.uint8)
    h = _sample_random_bits(p, pk_seed)
    # one sk expander stream: y first, then x (pyref keygen order)
    sk_stream = _u32s(_seedexpand(sk_seed, 8 * p.w))
    y_sup = _fixed_weight_support(p, sk_stream[..., : p.w], p.w)
    x_sup = _fixed_weight_support(p, sk_stream[..., p.w :], p.w)
    x = _support_to_bits(p, x_sup)
    s = x ^ _cyclic_mul_sparse(p, h, y_sup)
    pk = jnp.concatenate([pk_seed, _bits_to_bytes(s)], axis=-1)
    sk = jnp.concatenate([sk_seed, sigma, pk], axis=-1)
    return pk, sk


def _encrypt(p: HQCParams, pk: jax.Array, m: jax.Array, theta: jax.Array):
    pk_seed = pk[..., :40]
    s = _bytes_to_bits(pk[..., 40:], p.n)
    h = _sample_random_bits(p, pk_seed)
    # one theta expander stream: r2, e, r1 (pyref _encrypt order)
    stream = _u32s(_seedexpand(theta, 12 * p.wr))
    r2_sup = _fixed_weight_support(p, stream[..., : p.wr], p.wr)
    e_sup = _fixed_weight_support(p, stream[..., p.wr : 2 * p.wr], p.wr)
    r1_sup = _fixed_weight_support(p, stream[..., 2 * p.wr :], p.wr)
    u = _support_to_bits(p, r1_sup) ^ _cyclic_mul_sparse(p, h, r2_sup)
    code = _rm_encode(p, _rs_encode(p, m.astype(jnp.int32)))
    t = _cyclic_mul_sparse(p, s, r2_sup) ^ _support_to_bits(p, e_sup)
    v = code ^ t[..., : p.n1 * p.n2]
    return u, v


def encaps(p: HQCParams, pk: jax.Array, m: jax.Array, salt: jax.Array):
    """pk, m (..., k), salt (..., 16) -> (ct (..., ct_len), ss (..., 64))."""
    pk = jnp.asarray(pk, jnp.uint8)
    m = jnp.asarray(m, jnp.uint8)
    salt = jnp.asarray(salt, jnp.uint8)
    theta = _hash_dom(jnp.concatenate([m, pk[..., :32], salt], axis=-1), 3)
    u, v = _encrypt(p, pk, m, theta)
    u_b = _bits_to_bytes(u)
    v_b = _bits_to_bytes(v)
    ct = jnp.concatenate([u_b, v_b, salt], axis=-1)
    ss = _hash_dom(jnp.concatenate([m, u_b, v_b], axis=-1), 4)
    return ct, ss


def decaps(p: HQCParams, sk: jax.Array, ct: jax.Array):
    sk = jnp.asarray(sk, jnp.uint8)
    ct = jnp.asarray(ct, jnp.uint8)
    sk_seed = sk[..., :40]
    sigma = sk[..., 40 : 40 + p.k]
    pk = sk[..., 40 + p.k :]
    u_b = ct[..., : p.n_bytes]
    v_b = ct[..., p.n_bytes : p.n_bytes + p.n1n2_bytes]
    salt = ct[..., p.n_bytes + p.n1n2_bytes :]
    u = _bytes_to_bits(u_b, p.n)
    v = _bytes_to_bits(v_b, p.n1 * p.n2)
    # y = first fixed-weight draw off the sk expander stream
    sk_stream = _u32s(_seedexpand(sk_seed, 4 * p.w))
    y_sup = _fixed_weight_support(p, sk_stream, p.w)
    uy = _cyclic_mul_sparse(p, u, y_sup)
    m_p = _rs_decode(p, _rm_decode(p, v ^ uy[..., : p.n1 * p.n2])).astype(jnp.uint8)
    theta_p = _hash_dom(jnp.concatenate([m_p, pk[..., :32], salt], axis=-1), 3)
    u2, v2 = _encrypt(p, pk, m_p, theta_p)
    ok = jnp.all(_bits_to_bytes(u2) == u_b, axis=-1) & jnp.all(
        _bits_to_bytes(v2) == v_b, axis=-1
    )
    good = _hash_dom(jnp.concatenate([m_p, u_b, v_b], axis=-1), 4)
    bad = _hash_dom(jnp.concatenate([sigma, u_b, v_b], axis=-1), 4)
    return jnp.where(ok[..., None], good, bad)


@functools.cache
def get(name: str):
    """Jitted (keygen, encaps, decaps) triple for a parameter-set name."""
    p = PARAMS[name]
    _maybe_gate_fft()
    return (
        jax.jit(functools.partial(keygen, p)),
        jax.jit(functools.partial(encaps, p)),
        jax.jit(functools.partial(decaps, p)),
    )
