"""Batched TPU KEM implementations: ML-KEM, FrodoKEM, HQC."""
