"""Fused Pallas kernels for FrodoKEM's A-matrix products — tiled LWE matmul.

Why a kernel at all: FrodoKEM's cost is the two big products against the
pseudorandom n x n matrix A (A.S in keygen, S'.A in encaps/decaps).  The
chunked jnp path (kem/frodo.py) generates A in 16 row blocks with
``keccak.shake128`` and contracts each against S — so every generated row
round-trips HBM twice (sponge squeeze out, matmul operand in): ~3.4 MB of
A traffic per 640-row key, ~430 GB per 512-batch encaps dispatch, wholly
memory-bound (the same lesson as the FrodoKEM crypto-processor and OpenACC
LWE-KEM papers: tile the matrix product and keep sampling on device).

This kernel fuses the SHAKE-128 row sponge INTO the matmul consumer: each
grid step absorbs the per-row seed block, squeezes a full 2n-byte A row,
and multiply-accumulates it against the resident S tile — A never exists
in HBM at all.  HBM traffic drops to the seed words in and the (nbar x n)
product out.

Layout: the 8 sublanes of every (8, 128) uint32 state-word tile hold 8
CONSECUTIVE A-ROWS of the same sponge seed family; the 128 lanes hold
batch elements — 1024 row-sponges per grid step, the exact
``core/keccak_pallas.py`` register discipline (one vreg per state word).
The per-row 2-byte LE row index lives in the low half of lane word 0, so
one ``broadcasted_iota`` OR per grid step derives all 8 row headers from a
single host-prepared seed block.

All arithmetic is int32: products and accumulations wrap mod 2^32, which
is EXACT mod q because q = 2^15 or 2^16 divides 2^32 — the final ``& (q-1)``
recovers the spec value (the qrkernel wrap-by-design contract, annotated at
each site).

CPU twin: ``a_times_s_jnp`` / ``s_times_a_jnp`` are bit-identical
``lax.scan`` twins over the same 16 row chunks (the ``chacha_pallas``
pattern) — XLA:CPU compiles the 16-step scan well where the fully-unrolled
kernel body chokes LLVM.  Oracle: ``pyref.frodo_ref`` via tests/test_frodo*.

Replaces (hot path): the unrolled ``_gen_a_chunk`` + einsum loops in
kem/frodo.py for the SHAKE parameter sets (the AES sets keep the
bitsliced-AES chunk path — their matrix stream is not a sponge).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from ..core import keccak
from ..core.keccak_pallas import _TL, _TS, BT, _f1600, absorb_block
from ..pyref.frodo_ref import NBAR, FrodoParams

RATE_WORDS = 21  # SHAKE-128 rate: 168 bytes = 21 lanes (Gen for every set)

_N_CHUNKS = 16  # twin row chunks (matches kem/frodo.py N_CHUNKS)


def row_blocks(p: FrodoParams) -> int:
    """Squeeze blocks per A row: ceil(2n / 168) — 8 / 12 / 16."""
    return -(-2 * p.n // 168)


def use_pallas_default() -> bool:
    """Pallas kernel on real TPU, scanned-jnp twin elsewhere (the shared
    ``QRP2P_PALLAS`` policy of core.keccak)."""
    return keccak._use_pallas()


def seed_words(p: FrodoParams, seed_a: jax.Array):
    """seed_a (..., 16) uint8 -> ((21, B), (21, B)) uint32 hi/lo lane words
    of the padded SHAKE-128 row-seed block, row header left ZERO.

    The spec's row message is ``le16(row) || seed_a`` (18 bytes); the two
    row bytes land in the low half of lane word 0, so the kernel derives
    every row's block from this one by OR-ing the row index in.
    """
    zero_row = jnp.zeros(seed_a.shape[:-1] + (2,), jnp.uint8)
    seeds = jnp.concatenate([zero_row, jnp.asarray(seed_a, jnp.uint8)], axis=-1)
    ph, plo, batch = keccak.seed_block_words(seeds, 168, 0x1F)
    return ph, plo, batch


def _le16(b: jax.Array) -> jax.Array:
    """(..., 2k) uint8 -> (..., k) int32 little-endian 16-bit (twin helper)."""
    x = b.astype(jnp.int32).reshape(b.shape[:-1] + (-1, 2))
    return x[..., 0] | (x[..., 1] << 8)


def _squeeze_le16(sh: list, sl: list, ncol: int, q_mask: int) -> list:
    """The 84 LE-16 values of one squeezed rate block, first ``ncol`` only.

    Byte order within a 64-bit lane is little-endian with the low word
    first (core.keccak._words_to_bytes), so the four 16-bit values of lane
    word w are lo&0xFFFF, lo>>16, hi&0xFFFF, hi>>16 in stream order.
    """
    vals = []
    for w in range(RATE_WORDS):
        if len(vals) >= ncol:
            break
        lo, hi = sl[w], sh[w]
        vals += [lo & 0xFFFF, lo >> 16, hi & 0xFFFF, hi >> 16]
    # qrkernel: assume q_mask in [0, 65536) — q = 2^15 or 2^16 for every FrodoKEM set, so masked values fit int32 exactly
    return [(v & q_mask).astype(jnp.int32) for v in vals[:ncol]]


# --------------------------------------------------------------------------
# Kernel bodies — pure tile functions (eagerly testable on CPU arrays)
# --------------------------------------------------------------------------


def _absorb_row_seeds(in_hi: list, in_lo: list, row: jax.Array):
    """Absorb the row-seed block for a tile of absolute row indices.

    in_hi/in_lo: 21 uint32 word tiles broadcastable against ``row`` (the
    host-prepared block with a zero row header); row: uint32 tile of A-row
    indices (< n <= 1344 < 2^16, so the two LE header bytes are exactly
    the low half-word of lane 0).
    """
    ih = [jnp.broadcast_to(h, row.shape) for h in in_hi]
    il = [jnp.broadcast_to(lo, row.shape) for lo in in_lo]
    il[0] = il[0] | row
    return absorb_block(ih, il, RATE_WORDS)


def _s_times_a_tiles(in_hi: list, in_lo: list, sp: jax.Array, row: jax.Array,
                     *, n: int, q_mask: int, n_sq: int) -> jax.Array:
    """Partial S'.A for one 8-row tile of A: returns the (NBAR, n, lanes)
    int32 contribution of rows ``row`` (summed over the 8 sublane rows).

    sp: (NBAR,) + row.shape int32 — S' columns for these 8 A rows.
    Output wraps mod 2^32 (exact mod q); callers mask after the full sum.
    """
    sh, sl = _absorb_row_seeds(in_hi, in_lo, row)
    outs = []
    for sb in range(n_sq):
        if sb:
            sh, sl = _f1600(sh, sl)
        ncol = min(84, n - sb * 84)
        a = jnp.stack(_squeeze_le16(sh, sl, ncol, q_mask))  # (ncol, 8, lanes)
        outs.append(jnp.stack([
            jnp.sum(sp[j][None] * a, axis=1)  # qrkernel: wrapping — int32 LWE product/accumulate wraps mod 2^32; q | 2^32 so the masked result is the exact spec value
            for j in range(NBAR)
        ]))
    return jnp.concatenate(outs, axis=1)


def _a_times_s_tiles(in_hi: list, in_lo: list, s_cols: jax.Array,
                     row: jax.Array, *, n: int, q_mask: int,
                     n_sq: int) -> jax.Array:
    """A.S for one 8-row tile of A: returns (8, NBAR, lanes) int32 rows.

    s_cols: (n, NBAR) + lane shape int32 — the full S matrix (resident).
    Each generated A row contracts against all n S rows in-register; the
    output rows are complete (no cross-step accumulation needed).
    """
    sh, sl = _absorb_row_seeds(in_hi, in_lo, row)
    acc = jnp.zeros(row.shape[:1] + (NBAR,) + row.shape[1:], jnp.int32)
    for sb in range(n_sq):
        if sb:
            sh, sl = _f1600(sh, sl)
        ncol = min(84, n - sb * 84)
        for k, a_c in enumerate(_squeeze_le16(sh, sl, ncol, q_mask)):
            acc = acc + a_c[:, None] * s_cols[sb * 84 + k][None]  # qrkernel: wrapping — int32 LWE product/accumulate wraps mod 2^32; q | 2^32 so the masked result is the exact spec value
    return acc


def _cdf_tiles(r: jax.Array, cdf: tuple[int, ...], q_mask: int) -> jax.Array:
    """Inversion sampling on the CDF: (...,) int32 16-bit randoms -> samples
    in [0, q).  Bit-identical to kem/frodo._sample (the jnp twin)."""
    t = r >> 1
    e = jnp.zeros_like(r)
    for c in cdf[:-1]:
        e = e + (t > c).astype(jnp.int32)
    return jnp.where((r & 1) == 1, -e, e) & q_mask


# --------------------------------------------------------------------------
# Pallas launchers
# --------------------------------------------------------------------------


def _s_times_a_kernel(in_hi_ref, in_lo_ref, sp_ref, out_ref, *, n: int,
                      q_mask: int, n_sq: int):
    rc = pl.program_id(1)

    @pl.when(rc == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    row = (lax.broadcasted_iota(jnp.int32, (_TS, _TL), 0)
           + rc * _TS).astype(jnp.uint32)
    contrib = _s_times_a_tiles(
        [in_hi_ref[w] for w in range(RATE_WORDS)],
        [in_lo_ref[w] for w in range(RATE_WORDS)],
        sp_ref[...], row, n=n, q_mask=q_mask, n_sq=n_sq,
    )
    out_ref[...] += contrib  # qrkernel: wrapping — int32 LWE product/accumulate wraps mod 2^32; q | 2^32 so the masked result is the exact spec value


def _a_times_s_kernel(in_hi_ref, in_lo_ref, s_ref, out_ref, *, n: int,
                      q_mask: int, n_sq: int):
    rc = pl.program_id(1)
    row = (lax.broadcasted_iota(jnp.int32, (_TS, _TL), 0)
           + rc * _TS).astype(jnp.uint32)
    out_ref[...] = _a_times_s_tiles(
        [in_hi_ref[w] for w in range(RATE_WORDS)],
        [in_lo_ref[w] for w in range(RATE_WORDS)],
        s_ref[...], row, n=n, q_mask=q_mask, n_sq=n_sq,
    )


def _pad_lanes(x: jax.Array, b: int, bp: int) -> jax.Array:
    if bp == b:
        return x
    pad = [(0, 0)] * (x.ndim - 1) + [(0, bp - b)]
    return jnp.pad(x, pad)


@functools.partial(jax.jit,
                   static_argnames=("n", "q_mask", "n_sq", "interpret"))
def s_times_a_words(in_hi: jax.Array, in_lo: jax.Array, sp: jax.Array, *,
                    n: int, q_mask: int, n_sq: int,
                    interpret: bool = False) -> jax.Array:
    """S'.A with fused row generation: seed words (21, B), sp (NBAR, n, B)
    int32 -> (NBAR, n, B) int32 (wrapped; callers mask).

    Grid: (B/128 lane tiles) x (n/8 row chunks); the output block stays
    VMEM-resident across the whole row-chunk axis (revisited accumulation,
    init on the first chunk).
    """
    b = in_hi.shape[1]
    bp = -(-b // _TL) * _TL
    in_hi = _pad_lanes(in_hi, b, bp).reshape(RATE_WORDS, bp // _TL, _TL)
    in_lo = _pad_lanes(in_lo, b, bp).reshape(RATE_WORDS, bp // _TL, _TL)
    sp = _pad_lanes(sp, b, bp)
    kern = functools.partial(_s_times_a_kernel, n=n, q_mask=q_mask, n_sq=n_sq)
    out = pl.pallas_call(
        kern,
        grid=(bp // _TL, n // _TS),
        in_specs=[
            pl.BlockSpec((RATE_WORDS, 1, _TL), lambda bt, rc: (0, bt, 0)),
            pl.BlockSpec((RATE_WORDS, 1, _TL), lambda bt, rc: (0, bt, 0)),
            pl.BlockSpec((NBAR, _TS, _TL), lambda bt, rc: (0, rc, bt)),
        ],
        out_specs=pl.BlockSpec((NBAR, n, _TL), lambda bt, rc: (0, 0, bt)),
        out_shape=jax.ShapeDtypeStruct((NBAR, n, bp), jnp.int32),
        interpret=interpret,
    )(in_hi, in_lo, sp)
    return out[..., :b]


@functools.partial(jax.jit,
                   static_argnames=("n", "q_mask", "n_sq", "interpret"))
def a_times_s_words(in_hi: jax.Array, in_lo: jax.Array, s: jax.Array, *,
                    n: int, q_mask: int, n_sq: int,
                    interpret: bool = False) -> jax.Array:
    """A.S with fused row generation: seed words (21, B), s (n, NBAR, B)
    int32 -> (n, NBAR, B) int32 (wrapped; callers mask).

    The full S block is VMEM-resident per lane tile; each grid step emits
    8 finished output rows (no revisiting).
    """
    b = in_hi.shape[1]
    bp = -(-b // _TL) * _TL
    in_hi = _pad_lanes(in_hi, b, bp).reshape(RATE_WORDS, bp // _TL, _TL)
    in_lo = _pad_lanes(in_lo, b, bp).reshape(RATE_WORDS, bp // _TL, _TL)
    s = _pad_lanes(s, b, bp)
    kern = functools.partial(_a_times_s_kernel, n=n, q_mask=q_mask, n_sq=n_sq)
    out = pl.pallas_call(
        kern,
        grid=(bp // _TL, n // _TS),
        in_specs=[
            pl.BlockSpec((RATE_WORDS, 1, _TL), lambda bt, rc: (0, bt, 0)),
            pl.BlockSpec((RATE_WORDS, 1, _TL), lambda bt, rc: (0, bt, 0)),
            pl.BlockSpec((n, NBAR, _TL), lambda bt, rc: (0, 0, bt)),
        ],
        out_specs=pl.BlockSpec((_TS, NBAR, _TL), lambda bt, rc: (rc, 0, bt)),
        out_shape=jax.ShapeDtypeStruct((n, NBAR, bp), jnp.int32),
        interpret=interpret,
    )(in_hi, in_lo, s)
    return out[..., :b]


def _cdf_kernel(r_ref, out_ref, *, cdf: tuple[int, ...], q_mask: int):
    out_ref[...] = _cdf_tiles(r_ref[...], cdf, q_mask)


@functools.partial(jax.jit, static_argnames=("cdf", "q_mask", "interpret"))
def cdf_sample_words(r: jax.Array, *, cdf: tuple[int, ...], q_mask: int,
                     interpret: bool = False) -> jax.Array:
    """Batched CDF inversion on device: (M,) int32 randoms -> samples.

    One flat pass; the compare-sum never materialises the (M, |cdf|)
    comparison tensor in HBM (the jnp path's main traffic)."""
    m = r.shape[0]
    mp = -(-m // BT) * BT
    r = jnp.pad(r, (0, mp - m)).reshape(mp // _TL, _TL)
    out = pl.pallas_call(
        functools.partial(_cdf_kernel, cdf=cdf, q_mask=q_mask),
        grid=(mp // BT,),
        in_specs=[pl.BlockSpec((_TS, _TL), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((_TS, _TL), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((mp // _TL, _TL), jnp.int32),
        interpret=interpret,
    )(r)
    return out.reshape(mp)[:m]


# --------------------------------------------------------------------------
# Shape-marshalling wrappers (the kem/frodo.py routing surface)
# --------------------------------------------------------------------------


def s_times_a(p: FrodoParams, sp: jax.Array, seed_a: jax.Array, *,
              interpret: bool = False) -> jax.Array:
    """S'.A: sp (..., NBAR, n), seed_a (..., 16) -> (..., NBAR, n) in [0, q)."""
    batch = sp.shape[:-2]
    b = int(np.prod(batch)) if batch else 1
    in_hi, in_lo, _ = seed_words(p, seed_a)
    spw = jnp.moveaxis(sp.reshape((b, NBAR, p.n)), 0, -1).astype(jnp.int32)
    out = s_times_a_words(in_hi, in_lo, spw, n=p.n, q_mask=p.q - 1,
                          n_sq=row_blocks(p), interpret=interpret)
    return jnp.moveaxis(out, -1, 0).reshape(batch + (NBAR, p.n)) & (p.q - 1)


def a_times_s(p: FrodoParams, s: jax.Array, seed_a: jax.Array, *,
              interpret: bool = False) -> jax.Array:
    """A.S: s (..., n, NBAR), seed_a (..., 16) -> (..., n, NBAR) in [0, q)."""
    batch = s.shape[:-2]
    b = int(np.prod(batch)) if batch else 1
    in_hi, in_lo, _ = seed_words(p, seed_a)
    sw = jnp.moveaxis(s.reshape((b, p.n, NBAR)), 0, -1).astype(jnp.int32)
    out = a_times_s_words(in_hi, in_lo, sw, n=p.n, q_mask=p.q - 1,
                          n_sq=row_blocks(p), interpret=interpret)
    return jnp.moveaxis(out, -1, 0).reshape(batch + (p.n, NBAR)) & (p.q - 1)


def cdf_sample(p: FrodoParams, r16: jax.Array, *,
               interpret: bool = False) -> jax.Array:
    """CDF samples mod q for (...,) int32 16-bit randoms (kernel path)."""
    shape = r16.shape
    out = cdf_sample_words(r16.reshape(-1), cdf=tuple(p.cdf), q_mask=p.q - 1,
                           interpret=interpret)
    return out.reshape(shape)


# --------------------------------------------------------------------------
# Scanned-jnp CPU twins (bit-identical; the chacha_pallas pattern)
# --------------------------------------------------------------------------


def _gen_rows_jnp(p: FrodoParams, seed_a: jax.Array, row0: jax.Array,
                  nrows: int) -> jax.Array:
    """One chunk of A rows via the sponge scan path: -> (..., nrows, n)."""
    rows = row0 + jnp.arange(nrows)
    idx = jnp.stack([rows & 0xFF, rows >> 8], axis=-1).astype(jnp.uint8)
    lead = seed_a.shape[:-1] + (nrows,)
    seeds = jnp.concatenate(
        [
            jnp.broadcast_to(idx, lead + (2,)),
            jnp.broadcast_to(seed_a[..., None, :], lead + (16,)),
        ],
        axis=-1,
    )
    return _le16(keccak.shake128(seeds, 2 * p.n)) & (p.q - 1)


def s_times_a_jnp(p: FrodoParams, sp: jax.Array, seed_a: jax.Array) -> jax.Array:
    """Scanned twin of :func:`s_times_a` — a 16-step ``lax.scan`` over row
    chunks (XLA:CPU compiles the scan well; the unrolled chunk loop traced
    16x the ops).  Bit-identical: all-integer math, masked mod a power of
    two, so chunk order and masking granularity cannot change the result."""
    rows = p.n // _N_CHUNKS

    def step(acc, c):
        a_chunk = _gen_rows_jnp(p, seed_a, c * rows, rows)
        sp_chunk = lax.dynamic_slice_in_dim(sp, c * rows, rows, axis=-1)
        return (acc + jnp.einsum("...ir,...rn->...in", sp_chunk, a_chunk)) & (p.q - 1), None

    acc0 = jnp.zeros(sp.shape[:-1] + (p.n,), jnp.int32)
    acc, _ = lax.scan(step, acc0, jnp.arange(_N_CHUNKS))
    return acc


def a_times_s_jnp(p: FrodoParams, s: jax.Array, seed_a: jax.Array) -> jax.Array:
    """Scanned twin of :func:`a_times_s` (see :func:`s_times_a_jnp`)."""
    rows = p.n // _N_CHUNKS

    def step(carry, c):
        a_chunk = _gen_rows_jnp(p, seed_a, c * rows, rows)
        return carry, jnp.einsum("...rn,...nj->...rj", a_chunk, s) & (p.q - 1)

    _, ys = lax.scan(step, None, jnp.arange(_N_CHUNKS))
    # ys: (chunks, ..., rows, NBAR) -> (..., chunks * rows, NBAR)
    ys = jnp.moveaxis(ys, 0, -3)
    return ys.reshape(s.shape[:-2] + (p.n, s.shape[-1]))
