"""Secret-name vocabulary — the ONE copy shared by runtime redaction and
static analysis.

``obs/flight.py`` redacts secret-named fields at record time; qrlint's
secret-hygiene pack (``tools/analysis/rules_secret.py``) and qrflow's
taint tracking forbid the same names reaching log/trace sinks statically.
Both sides import THIS module, so the vocabulary cannot drift — the old
arrangement kept two copies pinned byte-equal by a test; now
``tests/test_obs.py`` pins import identity instead.

Stdlib-only on purpose: the obs package must import without the tools/
tree installed, and the analysis tree must import without jax.
"""

from __future__ import annotations

import re

#: identifiers that hold secret material.  ``_key`` suffixes are secret by
#: default in this codebase (entry_key, index_key, log_key, shared_key, ...);
#: the NONSECRET list walks back the public/verification-side names.
SECRET_NAME_RE = re.compile(
    r"(password|passwd|secret|private|master|keypair)"
    r"|(^|_)stek($|_)"
    r"|(^|_)(sk|skey)($|_)"
    r"|(^|_)key$"
    r"|^key$",
    re.IGNORECASE,
)
NONSECRET_NAME_RE = re.compile(r"(public|pub($|_)|(^|_)pk($|_)|verify|test)", re.IGNORECASE)


def is_secret_name(name: str | None) -> bool:
    if not name:
        return False
    return bool(SECRET_NAME_RE.search(name)) and not NONSECRET_NAME_RE.search(name)
