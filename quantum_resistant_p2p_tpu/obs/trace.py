"""Correlated span tracer — the "where did this handshake spend its time"
half of the observability layer (SURVEY.md §5; docs/observability.md).

A :class:`Span` is one timed region with a name, a correlation context
(``trace_id`` shared by a whole causal chain, ``span_id`` unique per
region, ``parent_id`` linking the chain), and a small dict of public
attributes.  The CURRENT span context lives in a :mod:`contextvars`
variable, so it propagates automatically across ``await`` boundaries and
into tasks (``loop.create_task`` / ``call_later`` copy the context at
scheduling time — which is exactly why a batch queue's timer-driven flush
inherits the context of the handshake that enqueued first).

Two boundaries do NOT propagate contextvars and need an explicit handoff:
``loop.run_in_executor`` workers and plain ``threading.Thread`` targets
(the same edges qrflow's ownership-domain pack maps).  Capture
:func:`current` on the loop side and pass it as ``parent=`` on the far
side::

    parent = trace.current()                    # loop side
    def work():                                 # executor/thread side
        with trace.span("device.dispatch", parent=parent, op=label):
            ...

Finished spans land in a bounded ring buffer and are fed to the flight
recorder (obs/flight.py).  :func:`to_chrome_trace` renders a span list as
chrome://tracing / Perfetto trace-event JSON, so a single traced handshake
loads as a flame graph (the PR-2 four-trips-per-handshake budget, visible).

**Cross-peer propagation** (the distributed half, docs/observability.md):
:func:`wire_context` renders the current context as a bounded, ids-only
dict the transport attaches to outbound frames (``_trace``), and
:func:`adopt_wire_context` validates an inbound one from an UNTRUSTED
peer — wrong shape, wrong types, over-long or non-token ids all yield
``None`` (the receiver simply roots a fresh trace; a hostile context can
never alter control flow, only correlation ids ever ride the wire).
``QRP2P_TRACE_PROPAGATE=0`` disables both directions.

**Node attribution**: span records carry a ``node`` field resolved from
the ambient :func:`node_scope` (set by the transport around sends and
handler dispatch) or inherited from the parent context, so one process
hosting many P2P nodes (the swarm benches) still attributes every span to
the node that did the work — the lane key ``tools/trace_merge.py`` groups
merged multi-node flame graphs by.  Contexts adopted from the wire carry
NO node: the responder's spans stay on the responder's lane.

Span attributes are DIAGNOSTIC METADATA — op labels, batch sizes, peer-id
prefixes, states.  Key material must never be passed as an attribute:
qrflow's ``flow-secret-in-trace`` sink rule enforces this statically, and
the flight recorder redacts defensively at record time.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import re
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable

#: the current span context of this task/thread (None outside any span).
#: Module-level so every tracer shares one propagation chain.
_CURRENT: contextvars.ContextVar["SpanContext | None"] = contextvars.ContextVar(
    "qrp2p_obs_span", default=None
)

#: the node this task/thread is doing work FOR (multi-node processes:
#: the swarm benches host hub + thousands of peers in one interpreter)
_NODE: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "qrp2p_obs_node", default=None
)

TRACE_PROPAGATE_ENV = "QRP2P_TRACE_PROPAGATE"

#: wire ``_trace`` field hygiene: ids are short opaque tokens.  Anything
#: longer or outside this alphabet is hostile or corrupt — ignored, so a
#: peer can never inject log/trace-file noise through correlation ids.
WIRE_ID_MAX = 64
#: \Z, not $ — $ matches before a trailing newline, which would wave
#: "evil\n" (and 65-byte "a"*64+"\n") through the hostile-input gate
_WIRE_ID_RE = re.compile(r"^[A-Za-z0-9_.:\-]{1,64}\Z")


class SpanContext:
    """Immutable correlation handle: pass it across executor/thread hops.

    ``node`` is the attribution lane of the span that minted the context
    (``None`` for contexts adopted from the wire — a remote parent must
    not pull the local child onto the remote node's lane)."""

    __slots__ = ("trace_id", "span_id", "node")

    def __init__(self, trace_id: str, span_id: str, node: str | None = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.node = node

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"SpanContext({self.trace_id}/{self.span_id})"


class Span:
    """One live timed region.  All identity fields are fixed at
    construction; the attribute dict is mutated only via :meth:`set_attr`
    (lock-guarded: a span handle may legitimately cross the executor
    boundary it was captured around)."""

    __slots__ = ("name", "context", "parent_id", "attrs", "_lock")

    def __init__(self, name: str, context: SpanContext, parent_id: str | None,
                 attrs: dict[str, Any]):
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.attrs = attrs
        self._lock = threading.Lock()

    def set_attr(self, key: str, value: Any) -> None:
        """Attach one more public attribute to the span."""
        with self._lock:
            self.attrs[key] = value


class Tracer:
    """Bounded-ring span recorder with deterministic id assignment.

    ``clock`` is injectable (tests pin it for byte-stable golden exports);
    the default is a perf_counter timeline relative to tracer creation, so
    exported timestamps are small non-negative microsecond offsets.
    """

    def __init__(self, cap: int = 4096,
                 clock: Callable[[], float] | None = None, tag: str = ""):
        self._lock = threading.Lock()
        self._spans: deque[dict[str, Any]] = deque(maxlen=cap)
        self._listeners: list[Callable[[dict[str, Any]], None]] = []
        self._next_id = 0
        #: id prefix disambiguating ids minted by DIFFERENT tracers inside
        #: one merged multi-node trace: every process's tracer counts from
        #: 1, so without a tag two processes' span/trace ids collide and
        #: tools/trace_merge.py would mislink parent edges.  "" (the
        #: default) keeps single-tracer exports byte-stable for goldens;
        #: the process-wide TRACER uses a pid+random tag (pid alone
        #: collides across containers, where every node is pid 1).
        self._tag = tag
        if clock is None:
            epoch = time.perf_counter()
            clock = lambda: time.perf_counter() - epoch  # noqa: E731
        self._clock = clock

    # -- ids ------------------------------------------------------------------

    def _new_id(self) -> str:
        with self._lock:
            self._next_id += 1
            return f"{self._tag}{self._next_id:08x}"

    # -- span lifecycle -------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, parent: SpanContext | None = None,
             **attrs: Any):
        """Open a span; the block's duration is the span's duration.

        ``parent`` defaults to the ambient context (contextvar); pass an
        explicitly captured :func:`current` when crossing an executor or
        thread boundary.  The span context is installed as ambient for the
        duration of the block, so nested spans chain automatically.
        """
        if parent is None:
            parent = _CURRENT.get()
        if parent is None:
            trace_id = "t" + self._new_id()
            parent_id = None
        else:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        # node attribution: the ambient scope (set by the transport around
        # sends/dispatch) wins; an explicitly handed-off parent carries its
        # creator's node across the executor/thread edges contextvars miss
        node = _NODE.get()
        if node is None and parent is not None:
            node = parent.node
        ctx = SpanContext(trace_id, self._new_id(), node)
        sp = Span(name, ctx, parent_id, dict(attrs))
        token = _CURRENT.set(ctx)
        t0 = self._clock()
        try:
            yield sp
        except BaseException as exc:
            sp.set_attr("error", type(exc).__name__)
            raise
        finally:
            _CURRENT.reset(token)
            self._finish(sp, t0, self._clock() - t0)

    def _finish(self, sp: Span, t0: float, dur: float) -> None:
        with sp._lock:
            # the handle may have crossed to a worker still set_attr-ing;
            # copy under ITS lock or the dict can change size mid-copy
            attrs = dict(sp.attrs)
        rec = {
            "name": sp.name,
            "trace_id": sp.context.trace_id,
            "span_id": sp.context.span_id,
            "parent_id": sp.parent_id,
            "t0": t0,
            "dur": dur,
            "thread": threading.current_thread().name,
            "node": sp.context.node or "",
            "attrs": attrs,
        }
        with self._lock:
            self._spans.append(rec)
            listeners = list(self._listeners)
        for cb in listeners:
            try:
                cb(rec)
            except Exception:  # qrlint: disable=broad-except  — a failing listener (e.g. a torn-down flight recorder in tests) must never break the traced operation
                pass

    # -- consumption ----------------------------------------------------------

    def add_listener(self, cb: Callable[[dict[str, Any]], None]) -> None:
        """Subscribe to finished spans (the flight recorder's feed)."""
        with self._lock:
            if cb not in self._listeners:
                self._listeners.append(cb)

    def snapshot(self) -> list[dict[str, Any]]:
        """Finished spans, oldest first (a copy)."""
        with self._lock:
            return list(self._spans)

    def reset(self) -> None:
        """Drop recorded spans (tests; long-lived sessions before an export)."""
        with self._lock:
            self._spans.clear()

    def now(self) -> float:
        """The tracer's current clock reading — the anchor
        :func:`export_spans` pairs with wall time so dumps from different
        processes can be aligned onto one merged timeline."""
        return self._clock()


def current() -> SpanContext | None:
    """The ambient span context — capture on the loop side, pass as
    ``parent=`` on the far side of an executor/thread hop."""
    return _CURRENT.get()


@contextlib.contextmanager
def node_scope(node_id: str):
    """Attribute spans opened inside the block (and tasks/timers scheduled
    from it — contextvars copy at scheduling time) to ``node_id``.  The
    transport enters this around sends and inbound handler dispatch."""
    token = _NODE.set(node_id)
    try:
        yield
    finally:
        _NODE.reset(token)


def current_node() -> str | None:
    """The ambient node attribution (None outside any :func:`node_scope`)."""
    return _NODE.get()


# -- cross-peer wire propagation ----------------------------------------------


def propagation_enabled() -> bool:
    """Trace-context propagation opt-out (``QRP2P_TRACE_PROPAGATE=0``).
    Read at call time so a live process can be flipped."""
    return os.environ.get(TRACE_PROPAGATE_ENV, "1") != "0"


def wire_context(**extra: str) -> dict[str, str] | None:
    """The current span context as the bounded, ids-only ``_trace`` dict
    the transport attaches to outbound frames — ``None`` when there is no
    current span or propagation is disabled.

    ``extra`` admits additional short PUBLIC correlation tokens (e.g. a
    bench run id); non-string or over-long values are dropped, and the
    receiver ignores everything but the two ids anyway.  ONLY correlation
    ids ever ride the wire: qrflow treats this function as a
    ``flow-secret-in-trace`` sink, so key material reaching any argument
    is a static-analysis error before it is a runtime one."""
    if not propagation_enabled():
        return None
    ctx = _CURRENT.get()
    if ctx is None:
        return None
    out = {"trace_id": ctx.trace_id, "span_id": ctx.span_id}
    for k, v in extra.items():
        if isinstance(v, str) and _WIRE_ID_RE.match(v):
            out[k] = v
    return out


def adopt_wire_context(obj: Any) -> SpanContext | None:
    """Validate an inbound ``_trace`` field from an UNTRUSTED peer into a
    parent :class:`SpanContext` — or ``None``, which simply roots a fresh
    local trace.  Hostile input must never alter control flow: anything
    but a dict of two short token-charset string ids is ignored (wrong
    type, missing/extra nesting, oversized or non-token ids).  The
    adopted context carries no ``node``: the remote parent must not pull
    local spans onto the remote peer's lane."""
    if not propagation_enabled():
        return None
    if not isinstance(obj, dict):
        return None
    trace_id = obj.get("trace_id")
    span_id = obj.get("span_id")
    if not (isinstance(trace_id, str) and isinstance(span_id, str)):
        return None
    if not (_WIRE_ID_RE.match(trace_id) and _WIRE_ID_RE.match(span_id)):
        return None
    return SpanContext(trace_id, span_id)


#: process-wide default tracer: instrumentation sites record here.  The
#: tag keeps ids from concurrently-traced processes disjoint when their
#: span dumps meet in one merged document (trace_merge): the pid half
#: makes ids greppable back to the dump's ``pid`` field, the random half
#: disambiguates processes whose pids collide — containers typically ALL
#: run their node as pid 1, and trace_merge's span index is
#: first-occurrence-wins, so pid alone would mislink cross-node edges in
#: exactly the deployment shape the merge exists for.
TRACER = Tracer(
    tag=f"{os.getpid() & 0xFFFF:04x}{os.urandom(4).hex()}")


def span(name: str, parent: SpanContext | None = None, **attrs: Any):
    """``TRACER.span(...)`` convenience (the form instrumentation uses)."""
    return TRACER.span(name, parent=parent, **attrs)


# -- chrome://tracing export --------------------------------------------------


def to_chrome_trace(records: list[dict[str, Any]]) -> dict[str, Any]:
    """Render finished-span records as a chrome://tracing (trace-event
    format) JSON object: complete events (``"ph": "X"``) with microsecond
    timestamps, one tid lane per recording thread, correlation ids in
    ``args``.  Load the dumped JSON in chrome://tracing or
    https://ui.perfetto.dev to see the flame graph.
    """
    tids: dict[str, int] = {}
    events: list[dict[str, Any]] = []
    for rec in records:
        tid = tids.setdefault(rec["thread"], len(tids) + 1)
        node = rec.get("node") or ""
        events.append({
            "name": rec["name"],
            "ph": "X",
            "ts": round(rec["t0"] * 1e6, 3),
            "dur": round(rec["dur"] * 1e6, 3),
            "pid": 1,
            "tid": tid,
            "cat": rec["name"].split(".", 1)[0],
            "args": {
                "trace_id": rec["trace_id"],
                "span_id": rec["span_id"],
                "parent_id": rec["parent_id"],
                **({"node": node} if node else {}),
                **rec["attrs"],
            },
        })
    meta = [
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
         "args": {"name": thread}}
        for thread, tid in sorted(tids.items(), key=lambda kv: kv[1])
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


SPAN_DUMP_FORMAT = "qrp2p-spans"
SPAN_DUMP_VERSION = 1


def span_dump(node: str = "", tracer: Tracer | None = None,
              records: list[dict[str, Any]] | None = None) -> dict[str, Any]:
    """One node's finished spans as a merge-ready dump document.

    Beyond the records themselves it carries per-node PROCESS metadata —
    node name, pid, and a (wall, mono) clock anchor pair taken at dump
    time — so ``tools/trace_merge.py`` can put each node on its own
    process lane and align dumps from DIFFERENT processes (each tracer's
    clock is relative to its own creation) onto one wall-clock timeline.
    """
    tracer = tracer or TRACER
    return {
        "format": SPAN_DUMP_FORMAT,
        "version": SPAN_DUMP_VERSION,
        "node": node,
        "pid": os.getpid(),
        "wall_anchor": time.time(),
        "mono_anchor": tracer.now(),
        "spans": records if records is not None else tracer.snapshot(),
    }


def export_spans(path: str | Path, node: str = "",
                 tracer: Tracer | None = None) -> dict[str, Any]:
    """Write :func:`span_dump` as JSON; returns the dump document."""
    doc = span_dump(node=node, tracer=tracer)
    Path(path).write_text(json.dumps(doc))
    return doc


@contextlib.contextmanager
def device_trace(log_dir: str = "/tmp/qrp2p_trace"):
    """Profile everything inside the block with ``jax.profiler``; view with
    TensorBoard.  (Moved here from ``utils.profiling`` in PR 5; the
    deprecation shim at the old path has since been removed.)"""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
