"""Correlated span tracer — the "where did this handshake spend its time"
half of the observability layer (SURVEY.md §5; docs/observability.md).

A :class:`Span` is one timed region with a name, a correlation context
(``trace_id`` shared by a whole causal chain, ``span_id`` unique per
region, ``parent_id`` linking the chain), and a small dict of public
attributes.  The CURRENT span context lives in a :mod:`contextvars`
variable, so it propagates automatically across ``await`` boundaries and
into tasks (``loop.create_task`` / ``call_later`` copy the context at
scheduling time — which is exactly why a batch queue's timer-driven flush
inherits the context of the handshake that enqueued first).

Two boundaries do NOT propagate contextvars and need an explicit handoff:
``loop.run_in_executor`` workers and plain ``threading.Thread`` targets
(the same edges qrflow's ownership-domain pack maps).  Capture
:func:`current` on the loop side and pass it as ``parent=`` on the far
side::

    parent = trace.current()                    # loop side
    def work():                                 # executor/thread side
        with trace.span("device.dispatch", parent=parent, op=label):
            ...

Finished spans land in a bounded ring buffer and are fed to the flight
recorder (obs/flight.py).  :func:`to_chrome_trace` renders a span list as
chrome://tracing / Perfetto trace-event JSON, so a single traced handshake
loads as a flame graph (the PR-2 four-trips-per-handshake budget, visible).

Span attributes are DIAGNOSTIC METADATA — op labels, batch sizes, peer-id
prefixes, states.  Key material must never be passed as an attribute:
qrflow's ``flow-secret-in-trace`` sink rule enforces this statically, and
the flight recorder redacts defensively at record time.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from collections import deque
from typing import Any, Callable

#: the current span context of this task/thread (None outside any span).
#: Module-level so every tracer shares one propagation chain.
_CURRENT: contextvars.ContextVar["SpanContext | None"] = contextvars.ContextVar(
    "qrp2p_obs_span", default=None
)


class SpanContext:
    """Immutable correlation handle: pass it across executor/thread hops."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"SpanContext({self.trace_id}/{self.span_id})"


class Span:
    """One live timed region.  All identity fields are fixed at
    construction; the attribute dict is mutated only via :meth:`set_attr`
    (lock-guarded: a span handle may legitimately cross the executor
    boundary it was captured around)."""

    __slots__ = ("name", "context", "parent_id", "attrs", "_lock")

    def __init__(self, name: str, context: SpanContext, parent_id: str | None,
                 attrs: dict[str, Any]):
        self.name = name
        self.context = context
        self.parent_id = parent_id
        self.attrs = attrs
        self._lock = threading.Lock()

    def set_attr(self, key: str, value: Any) -> None:
        """Attach one more public attribute to the span."""
        with self._lock:
            self.attrs[key] = value


class Tracer:
    """Bounded-ring span recorder with deterministic id assignment.

    ``clock`` is injectable (tests pin it for byte-stable golden exports);
    the default is a perf_counter timeline relative to tracer creation, so
    exported timestamps are small non-negative microsecond offsets.
    """

    def __init__(self, cap: int = 4096,
                 clock: Callable[[], float] | None = None):
        self._lock = threading.Lock()
        self._spans: deque[dict[str, Any]] = deque(maxlen=cap)
        self._listeners: list[Callable[[dict[str, Any]], None]] = []
        self._next_id = 0
        if clock is None:
            epoch = time.perf_counter()
            clock = lambda: time.perf_counter() - epoch  # noqa: E731
        self._clock = clock

    # -- ids ------------------------------------------------------------------

    def _new_id(self) -> str:
        with self._lock:
            self._next_id += 1
            return f"{self._next_id:08x}"

    # -- span lifecycle -------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, parent: SpanContext | None = None,
             **attrs: Any):
        """Open a span; the block's duration is the span's duration.

        ``parent`` defaults to the ambient context (contextvar); pass an
        explicitly captured :func:`current` when crossing an executor or
        thread boundary.  The span context is installed as ambient for the
        duration of the block, so nested spans chain automatically.
        """
        if parent is None:
            parent = _CURRENT.get()
        if parent is None:
            trace_id = "t" + self._new_id()
            parent_id = None
        else:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        ctx = SpanContext(trace_id, self._new_id())
        sp = Span(name, ctx, parent_id, dict(attrs))
        token = _CURRENT.set(ctx)
        t0 = self._clock()
        try:
            yield sp
        except BaseException as exc:
            sp.set_attr("error", type(exc).__name__)
            raise
        finally:
            _CURRENT.reset(token)
            self._finish(sp, t0, self._clock() - t0)

    def _finish(self, sp: Span, t0: float, dur: float) -> None:
        with sp._lock:
            # the handle may have crossed to a worker still set_attr-ing;
            # copy under ITS lock or the dict can change size mid-copy
            attrs = dict(sp.attrs)
        rec = {
            "name": sp.name,
            "trace_id": sp.context.trace_id,
            "span_id": sp.context.span_id,
            "parent_id": sp.parent_id,
            "t0": t0,
            "dur": dur,
            "thread": threading.current_thread().name,
            "attrs": attrs,
        }
        with self._lock:
            self._spans.append(rec)
            listeners = list(self._listeners)
        for cb in listeners:
            try:
                cb(rec)
            except Exception:  # qrlint: disable=broad-except  — a failing listener (e.g. a torn-down flight recorder in tests) must never break the traced operation
                pass

    # -- consumption ----------------------------------------------------------

    def add_listener(self, cb: Callable[[dict[str, Any]], None]) -> None:
        """Subscribe to finished spans (the flight recorder's feed)."""
        with self._lock:
            if cb not in self._listeners:
                self._listeners.append(cb)

    def snapshot(self) -> list[dict[str, Any]]:
        """Finished spans, oldest first (a copy)."""
        with self._lock:
            return list(self._spans)

    def reset(self) -> None:
        """Drop recorded spans (tests; long-lived sessions before an export)."""
        with self._lock:
            self._spans.clear()


def current() -> SpanContext | None:
    """The ambient span context — capture on the loop side, pass as
    ``parent=`` on the far side of an executor/thread hop."""
    return _CURRENT.get()


#: process-wide default tracer: instrumentation sites record here
TRACER = Tracer()


def span(name: str, parent: SpanContext | None = None, **attrs: Any):
    """``TRACER.span(...)`` convenience (the form instrumentation uses)."""
    return TRACER.span(name, parent=parent, **attrs)


# -- chrome://tracing export --------------------------------------------------


def to_chrome_trace(records: list[dict[str, Any]]) -> dict[str, Any]:
    """Render finished-span records as a chrome://tracing (trace-event
    format) JSON object: complete events (``"ph": "X"``) with microsecond
    timestamps, one tid lane per recording thread, correlation ids in
    ``args``.  Load the dumped JSON in chrome://tracing or
    https://ui.perfetto.dev to see the flame graph.
    """
    tids: dict[str, int] = {}
    events: list[dict[str, Any]] = []
    for rec in records:
        tid = tids.setdefault(rec["thread"], len(tids) + 1)
        events.append({
            "name": rec["name"],
            "ph": "X",
            "ts": round(rec["t0"] * 1e6, 3),
            "dur": round(rec["dur"] * 1e6, 3),
            "pid": 1,
            "tid": tid,
            "cat": rec["name"].split(".", 1)[0],
            "args": {
                "trace_id": rec["trace_id"],
                "span_id": rec["span_id"],
                "parent_id": rec["parent_id"],
                **rec["attrs"],
            },
        })
    meta = [
        {"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
         "args": {"name": thread}}
        for thread, tid in sorted(tids.items(), key=lambda kv: kv[1])
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


@contextlib.contextmanager
def device_trace(log_dir: str = "/tmp/qrp2p_trace"):
    """Profile everything inside the block with ``jax.profiler``; view with
    TensorBoard.  (Moved from ``utils.profiling``; a deprecation shim keeps
    the old import path working.)"""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()
