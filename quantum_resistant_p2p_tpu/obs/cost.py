"""Device-cost ledger — the serving-cost economics the metrics never had.

The registry (obs/metrics.py) answers "what is this process doing" and
the SLO engine (obs/slo.py) "is it doing it well enough"; this module
answers **what the device time is being spent on** — the quantities a
capacity decision needs (PAPERS.md #1's per-operation cost breakdowns;
PAPERS.md #5's continuous in-hardware evaluation):

* **Batch occupancy / padding waste** — every device flush pads up to a
  pow2 bucket (``provider/batched.py``: ``max(bucket_floor,
  next_pow2(n))``), so real items vs padded slots is real money.  The
  ledger accounts both per (queue, lane) and derives
  ``padding_waste_fraction`` = padded / (real + padded).
* **Compile attribution** — jit compiles cost tens of seconds and were
  never attributed.  Every compile event carries its bucket, shard, wall
  seconds, and WHERE it happened: ``warmup`` (the background facade
  warm-up sweep) vs ``in_flush`` (a live flush hit a cold bucket and
  kicked a background compile while its ops fell back to the cpu).
* **Device seconds** — cumulative on-worker device-program time per op
  family (encaps / sign / keygen_sign / …) and per placement shard, plus
  the headline ``device_seconds_per_1k_handshakes`` derived gauge.
* **Opcache effectiveness** — sliding-window hit rates per cache (the
  cumulative counters hide regressions; a window shows the CURRENT rate).
* **Scalar bypasses** — items the device path never saw (oversized AEAD
  payloads past the bucket caps run scalar and never enqueue:
  ``provider/batched.py``).  Without this family those items silently
  vanish from the occupancy denominator and the ledger's "device-served"
  story overstates coverage; ``device_served_fraction`` derives real /
  (real + bypassed).
* **Autotuner decision journal** — every ``decide()`` step with its
  inputs and chosen bucket/window, sequence-numbered and stamped with the
  tuner's (injectable) clock, so a seeded storm's tuning trajectory is
  reconstructible deterministically.

Everything lands in the engine's metrics registry as labeled instruments
(``cost_compile_events{queue,shard,where}``,
``cost_flush_items_real{queue,lane}`` / ``…_padded``,
``cost_bypass_items{queue,reason}``,
``cost_device_seconds{op}``, ``opcache_hit_rate{cache}``,
``padding_waste_fraction``, ``device_seconds_per_1k_handshakes``) so one
Prometheus scrape exports the economics, and compile events additionally
emit structured flight events (``cost_compile``) so a diagnostic bundle
narrates where the compile seconds went.

Hot-path discipline: the queue hooks are a few dict updates and counter
increments per FLUSH (never per op), ``device_time`` one per dispatch,
``opcache_event`` one deque append per lookup; decisions about WHEN a
flush fires are never touched — the ledger observes, it does not steer
(bit-exactness pins stay green with the ledger attached).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable

from . import flight as obs_flight

#: retained compile events / journal entries (bounded rings: the ledger
#: must stay O(1) memory under an unbounded storm)
COMPILE_EVENT_CAP = 1024
JOURNAL_CAP = 4096
#: opcache sliding-window length (lookups)
OPCACHE_WINDOW = 512
#: journal/compile tail served by snapshot() (full rings via journal())
SNAPSHOT_TAIL = 64


def _op_family(queue_label: str) -> str:
    """``"ML-KEM-768.encaps" -> "encaps"`` — the op family the device
    seconds aggregate by (algorithm names churn across hot-swaps; the op
    families are the stable cost axis)."""
    return queue_label.rsplit(".", 1)[-1] if queue_label else "?"


class CostLedger:
    """Per-engine device-cost accounting (one per ``SecureMessaging``,
    attached to its queues/opcaches/tuners like the autotuner is).

    All mutation is lock-guarded: recorders run on the event loop, the
    dispatch/warmup executors, and the scrape thread reads through gauge
    ``set_fn`` callbacks (qrflow cross-thread-state discipline).
    """

    def __init__(self, registry=None, clock: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        #: (queue, lane) -> [real_items, padded_slots, flushes]
        self._occ: dict[tuple[str, str], list] = {}
        #: (queue, reason) -> items that ran scalar, never enqueued
        self._bypass: dict[tuple[str, str], int] = {}
        #: (queue, shard_key, where) -> [events, wall_seconds]
        self._compile_totals: dict[tuple[str, str, str], list] = {}
        self._compile_events: deque[dict[str, Any]] = deque(maxlen=COMPILE_EVENT_CAP)
        #: op family -> on-worker device-program seconds
        self._device_s: dict[str, float] = {}
        #: placement shard index -> placed-program seconds
        self._shard_s: dict[int, float] = {}
        #: cache kind -> (window deque of 0/1, [hits, misses] cumulative)
        self._opcache: dict[str, tuple[deque, list]] = {}
        self._journal: deque[dict[str, Any]] = deque(maxlen=JOURNAL_CAP)
        self._journal_seq = 0
        self._handshakes_fn: Callable[[], int] | None = None
        # registry instruments (None without a registry: recording-only)
        self._ctr_compile = self._g_compile_s = None
        self._ctr_real = self._ctr_pad = self._ctr_bypass = None
        self._g_dev = self._g_hit = None
        if registry is not None:
            self._ctr_compile = registry.counter(
                "cost_compile_events",
                "device-program compile events, by queue/shard/where")
            self._g_compile_s = registry.gauge(
                "cost_compile_seconds",
                "cumulative compile wall seconds, by queue/shard/where")
            self._ctr_real = registry.counter(
                "cost_flush_items_real",
                "real items carried by device flushes, by queue/lane")
            self._ctr_pad = registry.counter(
                "cost_flush_items_padded",
                "padded pow2 slots dispatched empty, by queue/lane")
            self._ctr_bypass = registry.counter(
                "cost_bypass_items",
                "items served on the scalar path without enqueueing, "
                "by queue/reason")
            self._g_dev = registry.gauge(
                "cost_device_seconds",
                "cumulative on-worker device-program seconds, by op family")
            self._g_hit = registry.gauge(
                "opcache_hit_rate",
                f"operand-cache hit rate over the last {OPCACHE_WINDOW} "
                "lookups, by cache")
            registry.gauge(
                "padding_waste_fraction",
                "fraction of dispatched device-batch slots that were pow2 "
                "padding").set_fn(lambda: self.padding_waste_fraction())
            registry.gauge(
                "device_seconds_per_1k_handshakes",
                "cumulative device seconds per 1000 handshakes "
                "(initiated + admitted)"
            ).set_fn(lambda: self.device_seconds_per_1k_handshakes())

    @staticmethod
    def _child(inst, **kv):
        """Labeled child, or None without a registry.  ``labels()`` is
        already a locked create-or-return cache on the instrument family
        (obs/metrics.py) — a second ledger-side cache would only funnel
        every hook through the ledger-wide lock the scrape gauges contend
        on."""
        return inst.labels(**kv) if inst is not None else None

    # -- feeds ----------------------------------------------------------------

    def set_handshakes_fn(self, fn: Callable[[], int]) -> None:
        """Handshake-count feed for the per-1k derived gauge.  The engine
        wires BOTH halves of the handshake work (initiated attempts +
        admitted inbound ke_inits): a pure fleet gateway only responds,
        and an initiator-only denominator would leave the gauge
        permanently None on exactly the processes the ledger prices."""
        self._handshakes_fn = fn

    def flush_occupancy(self, queue: str, lane: str, real: int, bucket: int,
                        shard: int | None = None) -> None:
        """One device flush: ``real`` items padded up to ``bucket`` slots.
        Called per FLUSH on the device path only — the cpu fallback pads
        nothing, so it never contributes padding waste."""
        padded = max(0, bucket - real)
        with self._lock:
            row = self._occ.setdefault((queue, lane), [0, 0, 0])
            row[0] += real
            row[1] += padded
            row[2] += 1
        c = self._child(self._ctr_real, queue=queue, lane=lane)
        if c is not None:
            c.inc(real)
            self._child(self._ctr_pad, queue=queue, lane=lane).inc(padded)

    def bypass_items(self, queue: str, reason: str, n: int = 1) -> None:
        """``n`` items served on the scalar path WITHOUT enqueueing (e.g.
        AEAD payloads past the device facade's bucket caps).  Keeps the
        device-served denominator honest: these items are real traffic the
        occupancy rows never see."""
        with self._lock:
            key = (queue, reason)
            self._bypass[key] = self._bypass.get(key, 0) + n
        c = self._child(self._ctr_bypass, queue=queue, reason=reason)
        if c is not None:
            c.inc(n)

    def compile_event(self, queue: str, bucket: int, seconds: float,
                      where: str, shard: int | None = None) -> None:
        """One device-program compile: ``where`` is ``"warmup"`` (the
        background facade warm sweep) or ``"in_flush"`` (a live flush hit
        a cold bucket; the wall seconds include the 1-thread warmup pool's
        queueing — the honest time-to-warm the flush path observed)."""
        shard_key = str(shard) if shard is not None else "all"
        with self._lock:
            row = self._compile_totals.setdefault((queue, shard_key, where),
                                                  [0, 0.0])
            row[0] += 1
            row[1] += seconds
            self._compile_events.append({
                "t": round(self._clock(), 6), "queue": queue,
                "bucket": bucket, "shard": shard_key, "where": where,
                "seconds": round(seconds, 6),
            })
        c = self._child(self._ctr_compile, queue=queue, shard=shard_key,
                        where=where)
        if c is not None:
            c.inc()
            self._child(self._g_compile_s, queue=queue, shard=shard_key,
                        where=where).inc(seconds)
        # compiles are rare and expensive: each one is a flight event, so
        # a diagnostic bundle narrates where the compile seconds went
        obs_flight.record("cost_compile", queue=queue, bucket=bucket,
                          shard=shard_key, where=where,
                          seconds=round(seconds, 4))

    def device_time(self, queue: str, seconds: float) -> None:
        """On-worker device-program seconds for one dispatch (the
        ``_traced_call`` measurement — no executor queueing)."""
        fam = _op_family(queue)
        with self._lock:
            self._device_s[fam] = self._device_s.get(fam, 0.0) + seconds
        c = self._child(self._g_dev, op=fam)
        if c is not None:
            c.inc(seconds)

    def shard_device_time(self, shard: int, seconds: float) -> None:
        """Placed-program seconds per placement shard (Shard.run_placed)."""
        with self._lock:
            self._shard_s[shard] = self._shard_s.get(shard, 0.0) + seconds

    def opcache_event(self, cache: str, hit: bool) -> None:
        with self._lock:
            entry = self._opcache.get(cache)
            fresh = entry is None
            if fresh:
                entry = (deque(maxlen=OPCACHE_WINDOW), [0, 0])
                self._opcache[cache] = entry
            entry[0].append(1 if hit else 0)
            entry[1][0 if hit else 1] += 1
        if fresh and self._g_hit is not None:
            # first sighting of this cache: arm its lazy hit-rate child
            self._child(self._g_hit, cache=cache).set_fn(
                lambda c=cache: self.opcache_hit_rate(c))

    def tuner_decision(self, queue: str, t: float, inputs: dict[str, Any],
                       bucket: int, window_s: float, saturated: bool,
                       degraded: bool) -> None:
        """One autotuner ``decide()`` step — EVERY step, not only changes
        (the flight ``tuner_step`` event covers changes; the journal is
        the complete trajectory).  ``t`` is the tuner's own (injectable)
        clock so a seeded storm's journal replays deterministically."""
        with self._lock:
            self._journal_seq += 1
            self._journal.append({
                "seq": self._journal_seq, "t": round(t, 6), "queue": queue,
                "inputs": inputs, "bucket": bucket,
                "window_ms": round(window_s * 1e3, 3),
                "saturated": saturated, "degraded": degraded,
            })

    # -- derived reads --------------------------------------------------------

    def padding_waste_fraction(self, queue: str | None = None) -> float | None:
        """Padded slots / all dispatched slots (None before any flush)."""
        with self._lock:
            real = padded = 0
            for (q, _lane), row in self._occ.items():
                if queue is not None and q != queue:
                    continue
                real += row[0]
                padded += row[1]
        total = real + padded
        return round(padded / total, 6) if total else None

    def device_served_fraction(self, queue: str | None = None) -> float | None:
        """Real device-flushed items / (those + scalar bypasses) — the
        truthful "how much traffic the device actually served" gauge
        (None before any item either way)."""
        with self._lock:
            real = sum(row[0] for (q, _lane), row in self._occ.items()
                       if queue is None or q == queue)
            bypassed = sum(n for (q, _r), n in self._bypass.items()
                           if queue is None or q == queue)
        total = real + bypassed
        return round(real / total, 6) if total else None

    def device_seconds_total(self) -> float:
        with self._lock:
            return sum(self._device_s.values())

    def device_seconds_per_1k_handshakes(self) -> float | None:
        # no defensive except here: the only feed is a registry histogram
        # count read, and the gauge set_fn wrapper (obs/metrics.py
        # Gauge.value) already degrades a crashing callback to None
        fn = self._handshakes_fn
        if fn is None:
            return None
        hs = int(fn())
        if hs <= 0:
            return None
        return round(self.device_seconds_total() * 1000.0 / hs, 6)

    def opcache_hit_rate(self, cache: str) -> float | None:
        with self._lock:
            entry = self._opcache.get(cache)
            if entry is None or not entry[0]:
                return None
            window = list(entry[0])
        return round(sum(window) / len(window), 6)

    def compile_totals(self) -> tuple[int, float]:
        """-> (events, wall seconds) across every queue/shard/where."""
        with self._lock:
            events = sum(r[0] for r in self._compile_totals.values())
            seconds = sum(r[1] for r in self._compile_totals.values())
        return events, round(seconds, 6)

    def journal(self) -> list[dict[str, Any]]:
        """The full (bounded) autotuner decision journal, oldest first."""
        with self._lock:
            return list(self._journal)

    def totals(self) -> dict[str, Any]:
        """The compact cross-process aggregation feed (fleet heartbeats
        carry this; the router sums the numeric fields fleet-wide)."""
        events, seconds = self.compile_totals()
        with self._lock:
            real = sum(r[0] for r in self._occ.values())
            padded = sum(r[1] for r in self._occ.values())
            bypassed = sum(self._bypass.values())
            hits = sum(t[1][0] for t in self._opcache.values())
            misses = sum(t[1][1] for t in self._opcache.values())
            device_s = sum(self._device_s.values())
        total = real + padded
        served = real + bypassed
        looked = hits + misses
        return {
            "items_real": real,
            "items_padded": padded,
            "items_bypassed": bypassed,
            "padding_waste_fraction": (round(padded / total, 6)
                                       if total else None),
            "device_served_fraction": (round(real / served, 6)
                                       if served else None),
            "compile_events": events,
            "compile_seconds": seconds,
            "device_seconds": round(device_s, 6),
            "opcache_hits": hits,
            "opcache_misses": misses,
            "opcache_hit_rate_cumulative": (round(hits / looked, 6)
                                            if looked else None),
        }

    def snapshot(self) -> dict[str, Any]:
        """JSON-ready ledger document (``metrics()["cost"]`` and the HTTP
        ``/cost`` endpoint): per-queue occupancy, compile attribution,
        device seconds, opcache windows, and the journal tail."""
        with self._lock:
            occupancy = {
                f"{q}[{lane}]": {
                    "items_real": row[0], "items_padded": row[1],
                    "flushes": row[2],
                    "waste_fraction": (round(row[1] / (row[0] + row[1]), 6)
                                       if (row[0] + row[1]) else None),
                }
                for (q, lane), row in sorted(self._occ.items())
            }
            compiles = {
                f"{q}[shard={sh},{where}]": {
                    "events": row[0], "seconds": round(row[1], 6),
                }
                for (q, sh, where), row in sorted(self._compile_totals.items())
            }
            compile_tail = list(self._compile_events)[-SNAPSHOT_TAIL:]
            device_s = {k: round(v, 6)
                        for k, v in sorted(self._device_s.items())}
            shard_s = {str(k): round(v, 6)
                       for k, v in sorted(self._shard_s.items())}
            opcache = {
                kind: {
                    "window": len(win),
                    "window_hit_rate": (round(sum(win) / len(win), 6)
                                        if win else None),
                    "hits": totals[0], "misses": totals[1],
                }
                for kind, (win, totals) in sorted(self._opcache.items())
            }
            bypass = {
                f"{q}[{reason}]": n
                for (q, reason), n in sorted(self._bypass.items())
            }
            journal_tail = list(self._journal)[-SNAPSHOT_TAIL:]
            journal_seq = self._journal_seq
        return {
            "padding_waste_fraction": self.padding_waste_fraction(),
            "device_served_fraction": self.device_served_fraction(),
            "bypasses": bypass,
            "device_seconds_total": round(self.device_seconds_total(), 6),
            "device_seconds_per_1k_handshakes":
                self.device_seconds_per_1k_handshakes(),
            "occupancy": occupancy,
            "compiles": compiles,
            "recent_compiles": compile_tail,
            "device_seconds_by_op": device_s,
            "device_seconds_by_shard": shard_s,
            "opcaches": opcache,
            "tuner_journal_len": journal_seq,
            "tuner_journal_tail": journal_tail,
        }
