"""Live telemetry endpoints — the scrapeable per-process surface.

Until this module every operational number was either post-hoc (committed
bench artifacts, flight dumps) or interactive (the CLI ``/metrics`` /
``/slo`` commands).  A production gateway serving heavy traffic needs a
LIVE, pull-based surface a scraper or a dashboard (``tools/qrtop.py``)
can poll; this is it — one stdlib :class:`ThreadingHTTPServer` per
process, read-only, localhost-bound by default, **OFF by default**
(``QRP2P_HTTP_PORT`` env or ``SecureMessaging(telemetry_port=)``; no
listener, no thread, and no import of this module when disabled).

Endpoints (all ``GET``; everything else is 405):

=================  ==========================================================
``/metrics``       Prometheus text exposition — rendered through
                   :func:`obs.metrics.prometheus_text`, the SAME serializer
                   the CLI ``/metrics prom`` uses (one copy of the
                   exposition logic, by construction)
``/metrics.json``  the registry's JSON snapshot (instruments + collectors)
``/healthz``       liveness: 200 with node id + uptime while serving
``/readyz``        readiness: 200 only when the warm-up sweep finished,
                   no breaker is open, AND the engine is not draining (a
                   cold, degraded, or draining gateway answers 503 — with
                   ``draining``/``drain_reason`` in the body — so a load
                   balancer routes around it and qrtop renders the DRAIN
                   state during a rolling restart)
``/slo``           the SLO engine's burn/budget report (evaluating it —
                   a scraped gateway's burn windows advance)
``/trace``         recent spans as a chrome://tracing document (bounded by
                   the tracer's ring)
``/cost``          the device-cost ledger snapshot (obs/cost.py): padding
                   waste, compile attribution, device seconds, opcache
                   windows, autotuner journal tail
=================  ==========================================================

Trust model (docs/observability.md "Live endpoints"): the server binds
``127.0.0.1`` unless told otherwise, serves exclusively read-only
documents built from registry snapshots / SLO reports / span dumps —
surfaces that are secret-free by construction (qrflow's
``flow-secret-in-trace`` and ``flow-secret-to-network`` sinks police
what can reach them, and the HTTP write helper ``_respond`` is itself a
policed network sink) — and bounds both request parsing (the stdlib
handler caps the request line at 64 KiB → 414) and response sizes
(:data:`MAX_RESPONSE_BYTES` → 503, never an unbounded body).

Fleet use: every gateway process opens one on an ephemeral port announced
through its hello/heartbeat (fleet/gateway.py), and the router serves an
aggregated ``/fleet`` view (fleet/manager.py) — ``tools/qrtop.py`` polls
the set.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from .metrics import PROMETHEUS_CONTENT_TYPE, prometheus_text

logger = logging.getLogger(__name__)

#: env knob: unset/empty = disabled (the default), ``0`` = ephemeral
#: port, ``N`` = fixed port.  app/messaging.py reads it at engine
#: construction when no explicit ``telemetry_port=`` is passed.
TELEMETRY_PORT_ENV = "QRP2P_HTTP_PORT"

#: hard response-size bound: a route whose document exceeds this answers
#: 503 instead of streaming an unbounded body to the scraper
MAX_RESPONSE_BYTES = 16 * 1024 * 1024

JSON_TYPE = "application/json"

#: a route: () -> (http status, content type, body bytes)
Route = Callable[[], "tuple[int, str, bytes]"]


def env_port() -> int | None:
    """The :data:`TELEMETRY_PORT_ENV` value, or None when unset/empty/
    malformed (malformed values disable with a WARNING — a typo must not
    crash engine construction)."""
    raw = os.environ.get(TELEMETRY_PORT_ENV, "").strip()
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        logger.warning("ignoring malformed %s=%r (want an integer port; "
                       "0 = ephemeral)", TELEMETRY_PORT_ENV, raw)
        return None


def json_route(fn: Callable[[], Any],
               status_fn: Callable[[Any], int] | None = None) -> Route:
    """Wrap a document builder as a JSON route (sorted keys: scrape
    diffs stay stable)."""
    def route() -> tuple[int, str, bytes]:
        doc = fn()
        body = (json.dumps(doc, default=str, sort_keys=True) + "\n").encode()
        return (status_fn(doc) if status_fn is not None else 200,
                JSON_TYPE, body)

    return route


class _Handler(BaseHTTPRequestHandler):
    """Read-only request handler over the server's route table.

    The stdlib base already bounds hostile input: a request line over
    64 KiB answers 414, header count/size are capped by http.client.
    Everything that is not a ``GET`` of a known path is 404/405.
    """

    server_version = "qrp2p-telemetry"
    sys_version = ""  # no Python version banner in responses
    protocol_version = "HTTP/1.0"  # close per request: one scrape, one
    # thread, no keep-alive thread pinning
    timeout = 10.0

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        path = self.path.split("?", 1)[0]
        route = self.server.routes.get(path)  # type: ignore[attr-defined]
        if route is None:
            self._respond(404, JSON_TYPE, b'{"error": "unknown path"}\n')
            return
        try:
            status, ctype, body = route()
        except Exception:  # qrlint: disable=broad-except  — one crashing route must answer a bounded 500, never kill the handler thread or leak a traceback to the scraper
            logger.exception("telemetry route %s failed", path)
            self._respond(500, JSON_TYPE, b'{"error": "handler failed"}\n')
            return
        if len(body) > MAX_RESPONSE_BYTES:
            self._respond(503, JSON_TYPE,
                          b'{"error": "response too large"}\n')
            return
        self._respond(status, ctype, body)

    def _reject_write(self) -> None:
        self._respond(405, JSON_TYPE,
                      b'{"error": "telemetry is read-only (GET only)"}\n')

    # the surface is read-only by construction: every mutating verb is
    # rejected with one typed reply
    do_POST = do_PUT = do_DELETE = do_PATCH = _reject_write

    def _respond(self, status: int, ctype: str, body: bytes) -> None:
        # the single response-write chokepoint: qrflow polices it as a
        # network sink (flow-secret-to-network) — only registry
        # snapshots / SLO reports / span dumps may flow here
        try:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (ConnectionError, OSError):
            pass  # the scraper went away mid-response; nothing to serve

    def log_message(self, fmt: str, *args: Any) -> None:
        # scrapes are high-frequency background traffic: keep them out of
        # stderr; DEBUG keeps the trail findable
        logger.debug("telemetry %s " + fmt, self.address_string(), *args)


class TelemetryServer:
    """One per-process telemetry listener over a route table.

    ``port=0`` binds an ephemeral port (read it back via :attr:`port`
    after :meth:`start`).  The accept loop runs on a daemon thread;
    request handling threads are daemonic too, so a forgotten server
    never blocks interpreter exit — but callers should :meth:`stop` on
    drain (the engine and the fleet gateway do).
    """

    def __init__(self, routes: dict[str, Route], host: str = "127.0.0.1",
                 port: int = 0):
        self.routes = dict(routes)
        self._host = host
        self._requested_port = port
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> "TelemetryServer":
        if self._server is not None:
            return self
        srv = ThreadingHTTPServer((self._host, self._requested_port),
                                  _Handler)
        srv.daemon_threads = True
        srv.routes = self.routes  # type: ignore[attr-defined]
        self._server = srv
        self._thread = threading.Thread(
            target=srv.serve_forever, name="qrp2p-telemetry", daemon=True,
            kwargs={"poll_interval": 0.25},
        )
        self._thread.start()
        logger.info("telemetry endpoints on http://%s:%d (read-only)",
                    self._host, self.port)
        return self

    @property
    def port(self) -> int | None:
        """The bound port (None before :meth:`start`)."""
        if self._server is None:
            return None
        return self._server.server_address[1]

    @property
    def address(self) -> str | None:
        if self._server is None:
            return None
        return f"{self._host}:{self.port}"

    def stop(self) -> None:
        srv, self._server = self._server, None
        thread, self._thread = self._thread, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if thread is not None:
            thread.join(timeout=5.0)

    # -- canned route tables --------------------------------------------------

    @classmethod
    def for_engine(cls, engine, host: str = "127.0.0.1",
                   port: int = 0) -> "TelemetryServer":
        """The per-gateway route table over one ``SecureMessaging``
        engine: every document is built from the engine's registry /
        SLO engine / cost ledger / the process tracer — read-only
        snapshots, no mutation path."""
        from . import trace as obs_trace

        registry = engine.registry

        def prom() -> tuple[int, str, bytes]:
            # the shared exposition path (obs/metrics.prometheus_text):
            # rendering walks the registry collectors, so a scrape
            # advances the SLO engine exactly like metrics() does
            return 200, PROMETHEUS_CONTENT_TYPE, prometheus_text(
                registry).encode()

        def trace_doc() -> dict[str, Any]:
            return obs_trace.to_chrome_trace(obs_trace.TRACER.snapshot())

        return cls({
            "/metrics": prom,
            "/metrics.json": json_route(registry.snapshot),
            "/healthz": json_route(engine.health_doc),
            "/readyz": json_route(
                engine.ready_status,
                status_fn=lambda doc: 200 if doc.get("ready") else 503),
            "/slo": json_route(engine.slo_status),
            "/trace": json_route(trace_doc),
            "/cost": json_route(engine.cost.snapshot),
        }, host=host, port=port).start()
