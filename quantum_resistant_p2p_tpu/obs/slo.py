"""SLO / burn-rate alert engine — the "should a human care right now"
layer over the metrics registry (docs/observability.md).

The registry (obs/metrics.py) answers "what is this process doing"; this
module answers "is it doing it WELL ENOUGH, and how fast is it eating its
error budget".  A :class:`SLOSpec` declares one objective over one
service-level indicator:

* **event SLIs** — a ``probe()`` returning cumulative ``(good, bad)``
  event totals (handshakes under the latency threshold vs over it,
  device-served vs fallback ops, admitted vs shed requests);
* **time SLIs** — the same shape with seconds as the unit
  (:func:`breaker_availability_probe`: wall time the breaker was closed
  vs degraded).

The engine samples every probe on an INJECTABLE clock and evaluates
multi-window burn rates (Google SRE workbook shape): over a FAST window
(default 5 m) and a SLOW window (default 1 h),

    ``burn = (bad_delta / total_delta) / (1 - objective)``

— burn 1.0 consumes exactly the error budget the objective allows; the
alert fires only when BOTH windows exceed their thresholds (the fast
window gives speed, the slow window immunity to blips).  A process
younger than a window evaluates over the history it has — a sustained
breaker storm in a 30-second chaos run still fires deterministically.

On each alert edge the engine emits a structured ``slo_burn`` flight
event via :func:`obs.flight.trigger` — riding the existing auto-dump
machinery, so an armed recorder writes the diagnostic bundle that
explains the burn — plus ONE rate-limited WARNING per episode.  Budget
and burn gauges land in the registry (``slo_budget_remaining`` /
``slo_burn_fast`` / ``slo_burn_slow``, labeled ``slo=<name>``), and
:meth:`SLOEngine.status` is the JSON the ``metrics()["slo"]`` section and
the CLI ``/slo`` command serve.

Everything here is stdlib-only and allocation-light: probes read counters
other layers already keep; nothing new runs on any hot path.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable

from . import flight as _flight

logger = logging.getLogger(__name__)

#: burn-rate window defaults: fast catches a cliff in minutes, slow
#: confirms it is sustained (SRE-workbook multi-window shape)
FAST_WINDOW_S = 300.0
SLOW_WINDOW_S = 3600.0

#: default burn thresholds, tuned for ~99% objectives (a 100% error rate
#: burns at 1/(1-objective), so specs with looser objectives pass lower
#: thresholds explicitly — the engine caps nothing)
FAST_BURN_THRESHOLD = 14.4
SLOW_BURN_THRESHOLD = 1.0

Probe = Callable[[], "tuple[float, float]"]

#: per-spec cap on retained probe samples.  Evaluation frequency is
#: caller-controlled (every metrics() read / CLI /slo / Prometheus scrape
#: ticks the engine), so a hot scraper can produce far more samples per
#: slow window than any fixed ring holds — when the cap is hit the engine
#: DECIMATES interior samples (halving resolution) instead of evicting
#: the oldest: burn math needs the window BASELINES, and silently
#: dropping them collapses the slow window toward the fast one, which
#: un-filters exactly the blips the multi-window design exists to ignore.
MAX_SAMPLES = 4096


class SLOSpec:
    """One declarative objective: name, target fraction, and the probe
    supplying cumulative ``(good, bad)`` totals for its indicator.

    ``fast_burn``/``slow_burn`` are the per-window alert thresholds; both
    windows must exceed theirs for the spec to alert.  Objectives looser
    than ~99% should pass thresholds below ``1/(1-objective)`` (the burn
    ceiling a total outage can reach) or the alert can never fire.
    """

    __slots__ = ("name", "objective", "probe", "description",
                 "fast_window_s", "slow_window_s", "fast_burn", "slow_burn")

    def __init__(self, name: str, objective: float, probe: Probe,
                 description: str = "",
                 fast_window_s: float = FAST_WINDOW_S,
                 slow_window_s: float = SLOW_WINDOW_S,
                 fast_burn: float = FAST_BURN_THRESHOLD,
                 slow_burn: float = SLOW_BURN_THRESHOLD):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        if fast_window_s >= slow_window_s:
            raise ValueError("fast window must be shorter than the slow one")
        self.name = name
        self.objective = objective
        self.probe = probe
        self.description = description
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.fast_burn = fast_burn
        self.slow_burn = slow_burn


class _SpecState:
    """Engine-private per-spec sample ring + alert latch."""

    __slots__ = ("spec", "samples", "alerting", "alerts", "last_warn_t")

    def __init__(self, spec: SLOSpec):
        self.spec = spec
        #: (t, good_total, bad_total) samples, oldest first; pruned to the
        #: slow window plus one baseline sample just outside it, and
        #: decimated (never baseline-evicted) at MAX_SAMPLES
        self.samples: deque[tuple[float, float, float]] = deque()
        self.alerting = False
        self.alerts = 0
        self.last_warn_t: float | None = None


def _decimate(samples: "deque[tuple[float, float, float]]") -> None:
    """Drop every other INTERIOR sample in place, keeping the oldest
    (the slow window's baseline) and the newest (the latest totals).

    Burn rates only read the newest-at-or-before-cutoff baseline and the
    head, so halving interior resolution costs a little window-edge
    precision; evicting oldest-first (the previous ``deque(maxlen=…)``)
    cost the baseline itself and quietly shortened the slow window."""
    kept = [samples[0]]
    kept.extend(list(samples)[2:-1:2])
    kept.append(samples[-1])
    samples.clear()
    samples.extend(kept)


def _window_rates(samples: "deque[tuple[float, float, float]]",
                  now: float, window_s: float) -> tuple[float, float]:
    """-> (error_rate, total_delta) over the trailing window.

    Baseline = the newest sample at/older than ``now - window_s`` (exact
    window) or the oldest sample available (short-history processes: the
    window is "all of history so far", which is the honest answer for a
    process younger than the window)."""
    if len(samples) < 2:
        return 0.0, 0.0
    cutoff = now - window_s
    base = samples[0]
    for s in samples:
        if s[0] <= cutoff:
            base = s
        else:
            break
    latest = samples[-1]
    good_d = max(0.0, latest[1] - base[1])
    bad_d = max(0.0, latest[2] - base[2])
    total = good_d + bad_d
    if total <= 0.0:
        return 0.0, 0.0
    return bad_d / total, total


class SLOEngine:
    """Evaluates a set of :class:`SLOSpec`\\ s over sampled probe history.

    ``clock`` is injectable (tests drive deterministic timelines);
    ``registry`` (obs/metrics.py) receives the labeled budget/burn gauges
    when provided.  :meth:`status` = sample + evaluate + report; callers
    that only want the side effects (gauges, alerts, flight events) use
    :meth:`evaluate`.
    """

    def __init__(self, registry=None, clock: Callable[[], float] = time.monotonic,
                 warn_interval_s: float = 300.0):
        self._lock = threading.Lock()
        self._states: dict[str, _SpecState] = {}
        self._clock = clock
        self._warn_interval_s = warn_interval_s
        self._g_budget = self._g_fast = self._g_slow = None
        if registry is not None:
            self._g_budget = registry.gauge(
                "slo_budget_remaining",
                "error budget left in the slow window, per SLO (1 = untouched)")
            self._g_fast = registry.gauge(
                "slo_burn_fast", "fast-window burn rate, per SLO")
            self._g_slow = registry.gauge(
                "slo_burn_slow", "slow-window burn rate, per SLO")

    def add(self, spec: SLOSpec) -> SLOSpec:
        """Register a spec (replacing any previous one of the same name)."""
        with self._lock:
            self._states[spec.name] = _SpecState(spec)
        return spec

    def names(self) -> list[str]:
        with self._lock:
            return list(self._states)

    # -- sampling / evaluation ------------------------------------------------

    def tick(self) -> None:
        """Sample every probe once at the current clock reading."""
        now = self._clock()
        with self._lock:
            states = list(self._states.values())
        for st in states:
            try:
                good, bad = st.spec.probe()
            except Exception:
                # one crashing probe (e.g. a mid-teardown queue) must not
                # stop the other SLOs evaluating
                logger.debug("slo probe %s failed", st.spec.name, exc_info=True)
                continue
            with self._lock:
                st.samples.append((now, float(good), float(bad)))
                # prune: everything newer than the slow window stays, plus
                # ONE baseline sample at/older than its left edge
                cutoff = now - st.spec.slow_window_s
                while (len(st.samples) > 2 and st.samples[1][0] <= cutoff):
                    st.samples.popleft()
                if len(st.samples) > MAX_SAMPLES:
                    _decimate(st.samples)

    def probe_totals(self) -> dict[str, tuple[float, float]]:
        """Sample every probe once and return the cumulative ``(good,
        bad)`` totals by spec name — the cross-process aggregation feed:
        a fleet router (fleet/manager.py) sums these across its gateways'
        heartbeats and evaluates ONE engine (this same multi-window burn
        machinery) over the sums; :func:`merge_reports` is the offline
        twin over written ``slo_report.json`` files."""
        out: dict[str, tuple[float, float]] = {}
        with self._lock:
            states = list(self._states.values())
        for st in states:
            try:
                good, bad = st.spec.probe()
            except Exception:
                logger.debug("slo probe %s failed", st.spec.name,
                             exc_info=True)
                continue
            out[st.spec.name] = (float(good), float(bad))
        return out

    def evaluate(self) -> list[dict[str, Any]]:
        """Sample, compute burn rates, update gauges, fire alert edges."""
        self.tick()
        now = self._clock()
        out: list[dict[str, Any]] = []
        with self._lock:
            states = list(self._states.values())
        for st in states:
            spec = st.spec
            with self._lock:
                samples = deque(st.samples)
            err_fast, total_fast = _window_rates(samples, now, spec.fast_window_s)
            err_slow, total_slow = _window_rates(samples, now, spec.slow_window_s)
            budget = 1.0 - spec.objective
            burn_fast = err_fast / budget
            burn_slow = err_slow / budget
            remaining = max(0.0, min(1.0, 1.0 - burn_slow))
            alerting = (total_fast > 0.0
                        and burn_fast >= spec.fast_burn
                        and burn_slow >= spec.slow_burn)
            self._latch(st, alerting, burn_fast, burn_slow, remaining, now)
            if self._g_budget is not None:
                self._g_budget.labels(slo=spec.name).set(round(remaining, 6))
                self._g_fast.labels(slo=spec.name).set(round(burn_fast, 6))
                self._g_slow.labels(slo=spec.name).set(round(burn_slow, 6))
            latest = samples[-1] if samples else (now, 0.0, 0.0)
            out.append({
                "name": spec.name,
                "description": spec.description,
                "objective": spec.objective,
                "windows_s": {"fast": spec.fast_window_s,
                              "slow": spec.slow_window_s},
                "thresholds": {"fast_burn": spec.fast_burn,
                               "slow_burn": spec.slow_burn},
                "good_total": round(latest[1], 6),
                "bad_total": round(latest[2], 6),
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "budget_remaining": round(remaining, 4),
                "alerting": st.alerting,
                "alerts": st.alerts,
            })
        return out

    def _latch(self, st: _SpecState, alerting: bool, burn_fast: float,
               burn_slow: float, remaining: float, now: float) -> None:
        """Alert edge handling: flight event + rate-limited one-time
        WARNING on entry, a structured recovery event on exit."""
        spec = st.spec
        with self._lock:
            entered = alerting and not st.alerting
            recovered = st.alerting and not alerting
            st.alerting = alerting
            if entered:
                st.alerts += 1
            rewarn = (alerting and not entered
                      and st.last_warn_t is not None
                      and now - st.last_warn_t >= self._warn_interval_s)
            if entered or rewarn:
                st.last_warn_t = now
        if entered:
            # the trigger rides the flight recorder's auto-dump machinery:
            # an armed recorder writes the bundle that explains the burn
            _flight.trigger(
                "slo_burn", slo=spec.name, objective=spec.objective,
                burn_fast=round(burn_fast, 4), burn_slow=round(burn_slow, 4),
                budget_remaining=round(remaining, 4), alerts=st.alerts,
            )
        if entered or rewarn:
            logger.warning(
                "SLO %s burning: fast-window burn %.1fx budget (threshold "
                "%.1fx), slow-window %.1fx (threshold %.1fx); error budget "
                "remaining %.0f%%",
                spec.name, burn_fast, spec.fast_burn, burn_slow,
                spec.slow_burn, remaining * 100.0,
            )
        if recovered:
            _flight.record(
                "slo_recovered", slo=spec.name,
                burn_fast=round(burn_fast, 4), burn_slow=round(burn_slow, 4),
            )

    def status(self) -> dict[str, Any]:
        """Evaluate and report — the ``metrics()["slo"]`` / CLI ``/slo``
        document: per-spec burn/budget plus the alerting roll-up."""
        specs = self.evaluate()
        return {
            "specs": specs,
            "alerting": [s["name"] for s in specs if s["alerting"]],
            "alerts_total": sum(s["alerts"] for s in specs),
        }


# -- cross-process aggregation ------------------------------------------------


def merge_reports(reports: "list[dict[str, Any]]") -> dict[str, Any]:
    """Merge N per-node SLO reports (``SecureMessaging.slo_report()``
    documents, one ``slo_report.json`` per gateway process) into ONE
    fleet report: per-SLO fleet totals and burn (cumulative — the
    offline twin of the fleet router's live windowed engine), plus
    worst-node attribution so a fleet-level burn points at the gateway
    eating the budget.  Specs are merged BY NAME, so per-node specs that
    share a name (every gateway's ``handshake_p99``) aggregate while
    node-unique ones pass through with one contributor."""
    slos: dict[str, dict[str, Any]] = {}
    nodes: list[str] = []
    for rep in reports:
        node = str(rep.get("node", f"node{len(nodes)}"))
        nodes.append(node)
        for spec in (rep.get("slo") or {}).get("specs", []):
            name = spec.get("name")
            if not name:
                continue
            e = slos.setdefault(name, {
                "name": name,
                "objective": spec.get("objective"),
                "good_total": 0.0,
                "bad_total": 0.0,
                "nodes": 0,
                "worst_node": None,
                "worst_node_burn_fast": None,
                "alerting_nodes": [],
            })
            e["good_total"] += float(spec.get("good_total") or 0.0)
            e["bad_total"] += float(spec.get("bad_total") or 0.0)
            e["nodes"] += 1
            burn = float(spec.get("burn_fast") or 0.0)
            if (e["worst_node_burn_fast"] is None
                    or burn > e["worst_node_burn_fast"]):
                e["worst_node_burn_fast"] = round(burn, 4)
                e["worst_node"] = node
            if spec.get("alerting"):
                e["alerting_nodes"].append(node)
    worst_node = None
    worst_burn = -1.0
    for e in slos.values():
        total = e["good_total"] + e["bad_total"]
        err = (e["bad_total"] / total) if total else 0.0
        budget = 1.0 - (e["objective"] or 0.0)
        e["fleet_error_rate"] = round(err, 6)
        e["fleet_burn"] = round(err / budget, 4) if budget > 0 else None
        e["good_total"] = round(e["good_total"], 6)
        e["bad_total"] = round(e["bad_total"], 6)
        if (e["worst_node_burn_fast"] or 0.0) > worst_burn:
            worst_burn = e["worst_node_burn_fast"] or 0.0
            worst_node = e["worst_node"]
    return {
        "nodes": nodes,
        "slos": {name: slos[name] for name in sorted(slos)},
        "worst_node": worst_node,
        "alerting": sorted({n for e in slos.values()
                            for n in e["alerting_nodes"]}),
    }


# -- probe builders over the counters other layers already keep ---------------


def latency_probe(hist, threshold_s: float) -> Probe:
    """Event SLI over a fixed-bucket :class:`obs.metrics.Histogram`: good =
    samples at/under the largest bucket boundary <= ``threshold_s`` (pick a
    threshold ON a boundary for an exact split), bad = the rest."""
    boundary = None
    for b in hist.boundaries:
        if b <= threshold_s:
            boundary = b
        else:
            break
    if boundary is None:
        raise ValueError(
            f"threshold {threshold_s}s is below the smallest bucket "
            f"boundary {hist.boundaries[0]}s")
    bucket_le = format(boundary, "g")

    def probe() -> tuple[float, float]:
        counts = hist.bucket_counts()
        total = counts["+Inf"]
        good = counts[bucket_le]
        return float(good), float(total - good)

    return probe


def counter_pair_probe(good_fn: Callable[[], float],
                       bad_fn: Callable[[], float]) -> Probe:
    """Event SLI from two cumulative counter reads."""
    def probe() -> tuple[float, float]:
        return float(good_fn()), float(bad_fn())

    return probe


def breaker_availability_probe(breaker,
                               clock: Callable[[], float] = time.monotonic
                               ) -> Probe:
    """Time SLI over a provider breaker (provider/batched.py): bad = the
    cumulative seconds its device path was NOT closed
    (:meth:`Breaker.degraded_seconds`), good = the rest of wall time.
    Offsets cancel in the engine's window deltas, so the raw clock reading
    works as the total-time side."""
    def probe() -> tuple[float, float]:
        bad = breaker.degraded_seconds()
        return clock() - bad, bad

    return probe
