"""Typed metrics registry — Counter / Gauge / Histogram with exporters.

The single home for operational counters (SURVEY.md §5): the ad-hoc
counters PRs 2-4 grew (``QueueStats``, breaker trip/open/close counts,
``device_served_fraction``, per-handshake trip histograms, rekey/heal/
outbox counters) read through here so one snapshot answers "what is this
process doing" and one Prometheus scrape exports it.

Design constraints, in order:

* **Thread-safe** — instruments are hit from the event loop, the device
  executor, and the warmup thread (qrflow's ownership-domain map); every
  mutation is lock-guarded.
* **No per-record allocation on the hot path** — ``Counter.inc`` is an
  int add, ``Histogram.record`` is a linear scan over a handful of fixed
  bucket boundaries into a preallocated count list.  Percentiles are
  bucket-resolution estimates (exact when the boundaries are exact, e.g.
  integer trip counts); the sliding-window :class:`LatencyHistogram`
  (moved here from ``utils/profiling.py``) stays available where exact
  sample percentiles matter more than allocation-free recording.
* **Two sources, one snapshot** — instruments owned by the registry, plus
  COLLECTORS: callbacks over live objects that already keep their own
  counters (``QueueStats``, ``Breaker``, opcaches), absorbed at snapshot/
  export time instead of double-counted at record time.

Exporters: :meth:`Registry.snapshot` (JSON-ready nested dict) and
:meth:`Registry.to_prometheus` (Prometheus text exposition format).
Metric LABEL VALUES are public metadata only — qrflow's
``flow-secret-in-trace`` rule treats ``labels(...)`` as a secret sink.
"""

from __future__ import annotations

import collections
import contextlib
import re
import threading
import time
import weakref
from typing import Any, Callable

#: all live registries (weak: a torn-down engine's registry disappears)
_REGISTRIES: "weakref.WeakSet[Registry]" = weakref.WeakSet()
_REGISTRIES_LOCK = threading.Lock()

#: default latency bucket boundaries (seconds): 1 ms .. 60 s, roughly 1-2-5
DEFAULT_LATENCY_BUCKETS = (0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2,
                           0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0)

_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_PROM_LABEL_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return _PROM_NAME_RE.sub("_", name)


def _prom_labels(labels: tuple[tuple[str, str], ...]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_PROM_LABEL_RE.sub("_", k)}="{str(v)}"' for k, v in labels
    )
    return "{" + inner + "}"


class _Instrument:
    """Shared base: name, help text, and the labeled-child machinery.

    ``labels(**kv)`` returns (creating on first use) a child instrument of
    the same type keyed by the sorted label set; children are exported as
    extra sample lines carrying the label set.
    """

    kind = "untyped"

    def __init__(self, name: str, desc: str = "",
                 labels: tuple[tuple[str, str], ...] = ()):
        self.name = name
        self.desc = desc
        self.label_set = labels
        self._lock = threading.Lock()
        self._children: dict[tuple[tuple[str, str], ...], _Instrument] = {}

    def labels(self, **kv: Any) -> "_Instrument":
        key = tuple(sorted((k, str(v)) for k, v in kv.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child(key)
                self._children[key] = child
            return child

    def _make_child(self, key: tuple[tuple[str, str], ...]) -> "_Instrument":
        return type(self)(self.name, self.desc, labels=key)

    def _each(self) -> "list[_Instrument]":
        with self._lock:
            return [self, *self._children.values()]


class Counter(_Instrument):
    """Monotonically increasing count (thread-safe int add)."""

    kind = "counter"

    def __init__(self, name: str, desc: str = "",
                 labels: tuple[tuple[str, str], ...] = ()):
        super().__init__(name, desc, labels)
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge(_Instrument):
    """Point-in-time value: ``set``/``inc``/``dec``, or a ``set_fn``
    callback evaluated lazily at snapshot/export time (breaker state-age
    style values that are cheaper to read than to push)."""

    kind = "gauge"

    def __init__(self, name: str, desc: str = "",
                 labels: tuple[tuple[str, str], ...] = ()):
        super().__init__(name, desc, labels)
        self._value: float = 0.0
        self._fn: Callable[[], float] | None = None

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    def set_fn(self, fn: Callable[[], float]) -> None:
        """Make the gauge read ``fn()`` at snapshot time."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float | None:
        """None when a lazy ``set_fn`` crashes — never NaN, which
        json.dumps would serialize as an invalid-JSON token and poison
        every snapshot/bundle embedding it."""
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return float(fn())
        except Exception:  # qrlint: disable=broad-except  — a crashing lazy gauge must degrade to None, not take the whole snapshot/scrape down
            return None


class Histogram(_Instrument):
    """Fixed-boundary histogram: cumulative ``le`` buckets plus sum/count,
    Prometheus-style.  ``record`` is allocation-free (linear scan into a
    preallocated count list — boundary lists are a handful of entries).

    ``percentile(p)`` answers from the bucket counts: the smallest
    boundary covering p% of samples (exact when boundaries are exact for
    the recorded domain, e.g. integer trip counts; bucket-resolution
    otherwise).  ``last`` keeps the most recent raw sample — surfaces like
    "trips in the last handshake" want the latest observation.
    """

    kind = "histogram"

    def __init__(self, name: str, desc: str = "",
                 buckets: tuple[float, ...] = DEFAULT_LATENCY_BUCKETS,
                 labels: tuple[tuple[str, str], ...] = ()):
        super().__init__(name, desc, labels)
        if not buckets or list(buckets) != sorted(buckets):
            raise ValueError("bucket boundaries must be non-empty and sorted")
        self.boundaries = tuple(buckets)
        self._counts = [0] * (len(buckets) + 1)  # +1 overflow bucket
        self._sum = 0.0
        self._count = 0
        self._last: float | None = None

    def _make_child(self, key: tuple[tuple[str, str], ...]) -> "Histogram":
        return Histogram(self.name, self.desc, self.boundaries, labels=key)

    def record(self, v: float) -> None:
        with self._lock:
            i = 0
            for i, b in enumerate(self.boundaries):  # noqa: B007
                if v <= b:
                    break
            else:
                i = len(self.boundaries)
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._last = v

    @contextlib.contextmanager
    def time(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(time.perf_counter() - t0)

    def reset(self) -> None:
        """Zero the histogram (benchmark warmup windows)."""
        with self._lock:
            self._counts = [0] * (len(self.boundaries) + 1)
            self._sum = 0.0
            self._count = 0
            self._last = None

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def total(self) -> float:
        with self._lock:
            return self._sum

    @property
    def last(self) -> float | None:
        with self._lock:
            return self._last

    def percentile(self, p: float) -> float | None:
        """Smallest bucket boundary covering ``p`` percent of samples.
        None when empty OR when the target falls in the overflow bucket
        (beyond the largest boundary) — never +inf, which would poison
        JSON exports (``Infinity`` is not valid JSON); check
        :meth:`bucket_counts` to distinguish the two."""
        with self._lock:
            if self._count == 0:
                return None
            target = max(1, -(-int(p * self._count) // 100))  # ceil(p% * n)
            cum = 0
            for i, c in enumerate(self._counts[:-1]):
                cum += c
                if cum >= target:
                    return self.boundaries[i]
            return None

    def bucket_counts(self) -> dict[str, int]:
        """Cumulative counts keyed by ``le`` boundary (Prometheus shape)."""
        with self._lock:
            out: dict[str, int] = {}
            cum = 0
            for b, c in zip(self.boundaries, self._counts):
                cum += c
                out[format(b, "g")] = cum
            out["+Inf"] = cum + self._counts[-1]
            return out


class LatencyHistogram:
    """Sliding-window percentile tracker over the last ``cap`` samples
    (moved verbatim from ``utils/profiling.py`` in PR 5; the deprecation
    shim at the old path has since been removed).

    A deque of recent samples, sorted on demand: percentiles reflect the
    CURRENT behavior of the system (a lifetime reservoir would keep
    reporting stale latencies long after a regression starts).  Queries
    are rare (metrics dialogs, bench summaries), so the O(cap log cap)
    sort per query is the right trade against per-record cost.
    """

    def __init__(self, cap: int = 1024):
        #: recorders live on the loop AND the dispatch/warmup executors
        #: (qrflow cross-thread-state): all mutation is lock-guarded
        self._lock = threading.Lock()
        self._window: collections.deque[float] = collections.deque(maxlen=cap)
        self.count = 0
        self.total = 0.0
        #: most recent sample (None before the first record): metrics
        #: surfaces like "trips in the last handshake" want the latest
        #: observation, not a percentile of the window
        self.last: float | None = None

    def record(self, seconds: float) -> None:
        with self._lock:
            self.count += 1
            self.total += seconds
            self._window.append(seconds)
            self.last = seconds

    @contextlib.contextmanager
    def time(self):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(time.perf_counter() - t0)

    def percentile(self, p: float) -> float | None:
        with self._lock:
            if not self._window:
                return None
            s = sorted(self._window)
        return s[min(len(s) - 1, int(p / 100.0 * len(s)))]

    def summary(self) -> dict:
        return {
            "count": self.count,
            "mean_s": self.total / self.count if self.count else None,
            "last_s": self.last,
            "p50_s": self.percentile(50),
            "p95_s": self.percentile(95),
            "p99_s": self.percentile(99),
        }


class Registry:
    """A named set of instruments + collectors with two exporters.

    ``counter``/``gauge``/``histogram`` are create-or-return by name (the
    registry is the source of truth, so two call sites asking for the same
    name share one instrument; asking with a different type is an error).
    ``register_collector(name, fn)`` absorbs an external source: ``fn``
    returns a (nested) dict read at snapshot/export time — this is how
    ``QueueStats``/``Breaker``/opcache counters join the registry without
    a second set of hot-path increments.
    """

    def __init__(self, name: str = "default"):
        self.name = name
        self._lock = threading.Lock()
        self._instruments: dict[str, _Instrument] = {}
        self._collectors: dict[str, Callable[[], dict[str, Any]]] = {}
        with _REGISTRIES_LOCK:
            _REGISTRIES.add(self)

    # -- instrument factories -------------------------------------------------

    def _get(self, cls, name: str, desc: str, **kw) -> _Instrument:
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(name, desc, **kw)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {inst.kind}, "
                    f"requested {cls.kind}"
                )
            return inst

    def counter(self, name: str, desc: str = "") -> Counter:
        return self._get(Counter, name, desc)

    def gauge(self, name: str, desc: str = "") -> Gauge:
        return self._get(Gauge, name, desc)

    def histogram(self, name: str, desc: str = "",
                  buckets: tuple[float, ...] | None = None) -> Histogram:
        """``buckets=None`` = "whatever it already has" (DEFAULT_LATENCY_
        BUCKETS on creation); EXPLICIT boundaries that disagree with an
        existing instrument raise — silently recording into someone
        else's buckets yields wrong percentiles at bucket resolution."""
        h = self._get(Histogram, name, desc,
                      buckets=tuple(buckets) if buckets is not None
                      else DEFAULT_LATENCY_BUCKETS)
        if buckets is not None and h.boundaries != tuple(buckets):
            raise TypeError(
                f"histogram {name!r} already registered with boundaries "
                f"{h.boundaries}, requested {tuple(buckets)}"
            )
        return h

    def register_collector(self, name: str,
                           fn: Callable[[], dict[str, Any]]) -> None:
        with self._lock:
            self._collectors[name] = fn

    # -- exporters ------------------------------------------------------------

    def snapshot(self) -> dict[str, Any]:
        """JSON-ready nested dict of every instrument + collector."""
        with self._lock:
            instruments = list(self._instruments.values())
            collectors = dict(self._collectors)
        out: dict[str, Any] = {"registry": self.name, "counters": {},
                               "gauges": {}, "histograms": {}, "collected": {}}
        for inst in instruments:
            for each in inst._each():
                key = each.name + _prom_labels(each.label_set)
                if isinstance(each, Counter):
                    out["counters"][key] = each.value
                elif isinstance(each, Gauge):
                    out["gauges"][key] = each.value
                elif isinstance(each, Histogram):
                    out["histograms"][key] = {
                        "count": each.count,
                        "sum": each.total,
                        "last": each.last,
                        "p50": each.percentile(50),
                        "p99": each.percentile(99),
                        "buckets": each.bucket_counts(),
                    }
        for name, fn in collectors.items():
            try:
                out["collected"][name] = fn()
            except Exception:  # qrlint: disable=broad-except  — one crashing collector (e.g. a mid-teardown queue) must not take the whole snapshot down
                out["collected"][name] = {"error": "collector failed"}
        return out

    def to_prometheus(self, prefix: str = "qrp2p") -> str:
        """Prometheus text exposition format.  Collector dicts are
        flattened path-wise into gauge lines (numeric leaves only; strings
        stay in the JSON snapshot)."""
        snap = self.snapshot()
        reg_label = _prom_labels((("registry", self.name),))
        lines: list[str] = []

        def emit(name: str, kind: str, desc: str, samples: list[tuple[str, Any]]):
            lines.append(f"# HELP {name} {desc}")
            lines.append(f"# TYPE {name} {kind}")
            for suffix, v in samples:
                lines.append(f"{name}{suffix} {_fmt_num(v)}")

        with self._lock:
            instruments = list(self._instruments.values())
        for inst in instruments:
            base = f"{prefix}_{_prom_name(inst.name)}"
            if isinstance(inst, Counter):
                emit(f"{base}_total", "counter", inst.desc or inst.name,
                     [(_merge_labels(each.label_set, self.name), each.value)
                      for each in inst._each()])
            elif isinstance(inst, Gauge):
                emit(base, "gauge", inst.desc or inst.name,
                     [(_merge_labels(each.label_set, self.name), each.value)
                      for each in inst._each()])
            elif isinstance(inst, Histogram):
                lines.append(f"# HELP {base} {inst.desc or inst.name}")
                lines.append(f"# TYPE {base} histogram")
                for each in inst._each():
                    for le, cum in each.bucket_counts().items():
                        lbl = _merge_labels(each.label_set + (("le", le),),
                                            self.name)
                        lines.append(f"{base}_bucket{lbl} {cum}")
                    lbl = _merge_labels(each.label_set, self.name)
                    lines.append(f"{base}_sum{lbl} {_fmt_num(each.total)}")
                    lines.append(f"{base}_count{lbl} {each.count}")
        for cname, collected in snap["collected"].items():
            for path, v in _numeric_leaves(collected):
                name = f"{prefix}_{_prom_name(cname)}_{_prom_name(path)}"
                lines.append(f"{name}{reg_label} {_fmt_num(v)}")
        return "\n".join(lines) + "\n"


def _merge_labels(labels: tuple[tuple[str, str], ...], registry: str) -> str:
    return _prom_labels((("registry", registry),) + labels)


def _fmt_num(v: Any) -> str:
    if v is None:
        return "NaN"  # valid in the Prometheus exposition format (not JSON)
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return format(float(v), "g")


def _numeric_leaves(obj: Any, prefix: str = "") -> list[tuple[str, Any]]:
    """Flatten a collector dict to (dotted_path, number) pairs."""
    out: list[tuple[str, Any]] = []
    if isinstance(obj, dict):
        for k, v in obj.items():
            path = f"{prefix}_{k}" if prefix else str(k)
            out.extend(_numeric_leaves(v, path))
    elif isinstance(obj, (int, float)) and not isinstance(obj, bool):
        out.append((prefix, obj))
    return out


#: the Prometheus exposition content type (RFC'd by the text format spec;
#: obs/http.py serves it on GET /metrics)
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def prometheus_text(registry: "Registry", prefix: str = "qrp2p") -> str:
    """THE Prometheus text exposition path.  Every surface that renders a
    registry as Prometheus text — the CLI ``/metrics prom`` command
    (cli.py) and the HTTP ``GET /metrics`` endpoint (obs/http.py) — calls
    through here, so there is exactly one copy of the exposition logic
    (:meth:`Registry.to_prometheus`) and the two surfaces can never
    drift."""
    return registry.to_prometheus(prefix)


#: process-wide default registry (module-level counters; the flight
#: recorder's dump bundles snapshot EVERY live registry, this one included)
REGISTRY = Registry(name="process")


def global_snapshot() -> dict[str, dict[str, Any]]:
    """Snapshot of every live registry, keyed by registry name (the flight
    recorder embeds this in its diagnostic bundles)."""
    with _REGISTRIES_LOCK:
        regs = list(_REGISTRIES)
    out: dict[str, dict[str, Any]] = {}
    for reg in sorted(regs, key=lambda r: r.name):
        key = reg.name
        n = 2
        while key in out:  # two engines with one name: keep both visible
            key = f"{reg.name}#{n}"
            n += 1
        out[key] = reg.snapshot()
    return out
