"""``obs`` — the unified observability subsystem (docs/observability.md).

Three cooperating pieces, stdlib-only (importable on minimal images, no
jax/cryptography dependency):

* :mod:`.trace`   — correlated span tracer: contextvar-propagated span
  contexts across ``await``/task boundaries with explicit handoff across
  the warmup-thread/executor edges, a bounded ring of finished spans, and
  a chrome://tracing trace-event exporter (one handshake = one flame
  graph proving the 4-trips budget).
* :mod:`.metrics` — typed registry (Counter/Gauge/Histogram, thread-safe,
  allocation-free hot path) with collector absorption of the pre-existing
  counters (``QueueStats``, breaker, opcaches) and JSON-snapshot +
  Prometheus-text exporters.
* :mod:`.flight`  — bounded ring-buffer flight recorder of recent
  spans/events, redacted at record time with qrlint's secret-hygiene
  vocabulary, auto-dumping a diagnostic bundle on breaker-open /
  quarantine / handshake-give-up / injected-fault / SLO-burn triggers.
* :mod:`.slo`     — declarative SLO specs evaluated on injectable clocks
  over multi-window burn rates (fast 5 m / slow 1 h): error-budget
  gauges in the registry, structured ``slo_burn`` flight events, and the
  ``metrics()["slo"]`` / CLI ``/slo`` health report.
* :mod:`.cost`    — device-cost ledger: batch-occupancy / padding-waste
  accounting, warmup-vs-in-flush compile attribution, device seconds per
  op family and shard, opcache hit windows, and the deterministic
  autotuner decision journal.
* :mod:`.http`    — live per-process telemetry endpoints (``/metrics``
  ``/healthz`` ``/readyz`` ``/slo`` ``/trace`` ``/cost``): read-only,
  localhost-bound, OFF by default (``QRP2P_HTTP_PORT`` /
  ``telemetry_port=``); the scrape surface ``tools/qrtop.py`` polls.

Every layer above reports through here: the batch queue and breaker
(provider/batched.py), the protocol engine (app/messaging.py), the
transport (net/p2p_node.py), the fault engine (faults/), the health gate
(provider/health.py), and the bench harnesses (bench.py --slo,
tools/swarm_bench.py).
"""

from __future__ import annotations

from . import flight, metrics, slo, trace  # noqa: F401
from .flight import FlightRecorder  # noqa: F401
from .metrics import (Counter, Gauge, Histogram,  # noqa: F401
                      LatencyHistogram, Registry)
from .slo import SLOEngine, SLOSpec  # noqa: F401
from .trace import (Span, SpanContext, Tracer, current,  # noqa: F401
                    node_scope, span, to_chrome_trace)
