"""Crash-dump flight recorder — "what happened in the 5 seconds before".

A bounded ring buffer of recent structured events (breaker transitions,
health-gate verdicts, injected faults, handshake give-ups, rekeys, heals —
plus every finished trace span, fed from obs/trace.py), with a one-call
diagnostic bundle dump.  The dump is what turns a PR-3 chaos run from "the
breaker opened at some point" into an event-by-event story.

**Redaction happens at record time**, not dump time: key material must
never sit in the ring at all.  The vocabulary (``SECRET_NAME_RE`` /
``NONSECRET_NAME_RE``) lives in obs/redaction.py and is the SAME object
qrlint's secret-hygiene pack imports (tools/analysis/rules_secret.py);
``tests/test_obs.py`` pins the import identity.  Defense in depth:
qrflow's ``flow-secret-in-trace``
rule statically forbids tainted values reaching ``record``/span/label
sinks, and this module redacts whatever arrives anyway (secret-named
fields, raw bytes, oversized strings).

Auto-dump: :meth:`FlightRecorder.trigger` records the event AND writes a
bundle when a dump directory is armed (``QRP2P_FLIGHT_DIR`` env or
:meth:`set_autodump`), rate-limited per trigger kind with a bounded file
count, and written off-thread so a trigger firing on the event loop never
blocks it.  Triggers wired in this PR: breaker open, breaker quarantine
(device-health gate), handshake give-up, injected fault.

Byte-reproducibility: with injected clocks (tests) and a fresh recorder,
the bundle for a seeded fault plan is byte-identical across runs —
``dump`` serialises with sorted keys and compact separators.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable

from . import metrics as _metrics
from . import trace as _trace

# re-exported so existing importers keep working; the vocabulary itself
# lives in redaction.py (shared with tools/analysis/rules_secret.py)
from .redaction import NONSECRET_NAME_RE, SECRET_NAME_RE, is_secret_name

#: strings longer than this are summarised, not stored (payload hygiene +
#: ring size bound; no legitimate flight field is this long)
MAX_STR = 256
#: structures nested deeper than this are summarised wholesale
MAX_DEPTH = 4

FLIGHT_DIR_ENV = "QRP2P_FLIGHT_DIR"
BUNDLE_VERSION = 1


_is_secret_field = is_secret_name


def redact_value(name: str, value: Any, depth: int = 0) -> Any:
    """One field of a flight event, made safe to persist.

    Secret-NAMED fields are replaced by a typed placeholder whatever their
    value; raw bytes are never stored (length only); oversized strings are
    summarised; dicts/lists recurse with their own key checks; anything
    non-JSON-native is reduced to its type name.
    """
    if _is_secret_field(name):
        try:
            n = len(value)  # type: ignore[arg-type]
        except TypeError:
            n = -1
        return f"[redacted:{type(value).__name__}:{n}]"
    if isinstance(value, (bytes, bytearray, memoryview)):
        return f"[bytes:{len(value)}]"
    if isinstance(value, str):
        if len(value) > MAX_STR:
            return f"[str:{len(value)} chars]"
        return value
    if isinstance(value, (bool, int, float)) or value is None:
        return value
    if depth >= MAX_DEPTH:
        return f"[{type(value).__name__}]"
    if isinstance(value, dict):
        return {str(k): redact_value(str(k), v, depth + 1)
                for k, v in value.items()}
    if isinstance(value, (list, tuple, set, frozenset)):
        return [redact_value(name, v, depth + 1) for v in value]
    return f"[{type(value).__name__}]"


class FlightRecorder:
    """Bounded ring of redacted events + the diagnostic-bundle dump.

    ``clock``/``mono`` are injectable so tests produce byte-identical
    bundles; defaults are wall time (event timestamps humans correlate
    with logs) and monotonic time (rate limiting).
    """

    def __init__(self, cap: int = 2048,
                 clock: Callable[[], float] = time.time,
                 mono: Callable[[], float] = time.monotonic):
        self._lock = threading.Lock()
        self._events: deque[dict[str, Any]] = deque(maxlen=cap)
        self._seq = 0
        self._clock = clock
        self._mono = mono
        self._dump_dir: Path | None = None
        env_dir = os.environ.get(FLIGHT_DIR_ENV)
        if env_dir:
            self._dump_dir = Path(env_dir)
        self._min_interval_s = 30.0
        self._keep = 8
        self._last_dump: dict[str, float] = {}
        self._dump_count = 0

    # -- recording ------------------------------------------------------------

    def record(self, kind: str, **fields: Any) -> dict[str, Any]:
        """Append one event (redacted immediately; see module doc)."""
        safe = {k: redact_value(k, v) for k, v in fields.items()}
        with self._lock:
            self._seq += 1
            entry = {"seq": self._seq, "t": round(self._clock(), 6),
                     "kind": kind, **safe}
            self._events.append(entry)
        return entry

    def snapshot(self) -> list[dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._seq = 0
            self._last_dump.clear()

    # -- dumping --------------------------------------------------------------

    def set_autodump(self, directory: str | Path | None,
                     min_interval_s: float = 30.0, keep: int = 8) -> None:
        """Arm (or, with None, disarm) automatic bundle dumps on triggers."""
        with self._lock:
            self._dump_dir = Path(directory) if directory is not None else None
            self._min_interval_s = min_interval_s
            self._keep = keep

    def dump(self, trigger: str, path: str | Path | None = None,
             registries: dict[str, Any] | None = None) -> dict[str, Any]:
        """Build (and optionally write) the diagnostic bundle.

        ``registries`` overrides the metrics section (tests pass ``{}`` for
        byte-reproducibility; the default embeds a snapshot of every live
        registry).  Serialisation is sorted-key/compact, so equal state
        yields equal bytes.
        """
        if registries is None:
            registries = _metrics.global_snapshot()
        bundle = {
            "bundle_version": BUNDLE_VERSION,
            "trigger": trigger,
            "t": round(self._clock(), 6),
            "events": self.snapshot(),
            "metrics": registries,
        }
        if path is not None:
            path = Path(path)
            path.parent.mkdir(parents=True, exist_ok=True)
            # atomic publish: dumps are written off-thread while consumers
            # (operators, tests) poll the directory — a reader must never
            # observe a half-written bundle
            tmp = path.with_name(path.name + ".tmp")
            tmp.write_text(
                json.dumps(bundle, sort_keys=True, separators=(",", ":"),
                           default=str)
            )
            os.replace(tmp, path)
        return bundle

    def trigger(self, kind: str, **fields: Any) -> None:
        """Record the event AND auto-dump a bundle (if armed; rate-limited
        per kind; written off-thread so event-loop callers never block)."""
        self.record(kind, **fields)
        with self._lock:
            directory = self._dump_dir
            if directory is None:
                return
            now = self._mono()
            last = self._last_dump.get(kind)
            if last is not None and now - last < self._min_interval_s:
                return
            self._last_dump[kind] = now
            self._dump_count += 1
            n = self._dump_count
        path = directory / f"flight_{n:04d}_{_safe_name(kind)}.json"

        def _build_and_write() -> None:
            # the bundle build itself (registry snapshots across every live
            # engine + a ring copy) happens HERE, off the caller's thread:
            # triggers fire from the event loop, often under the breaker
            # lock, exactly when the system is already degraded
            try:
                self.dump(kind, path=path)
                self._prune(directory)
            except OSError:
                pass  # a full/unwritable dump dir must never break the caller

        threading.Thread(target=_build_and_write, name="qrp2p-flight-dump",
                         daemon=True).start()

    def _prune(self, directory: Path) -> None:
        dumps = sorted(directory.glob("flight_*.json"))
        for old in dumps[: max(0, len(dumps) - self._keep)]:
            try:
                old.unlink()
            except OSError:
                pass


def _safe_name(kind: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_.-]", "_", kind)[:48]


#: process-wide default recorder: instrumentation sites record here.
#: Module FUNCTIONS below resolve it at call time, so tests can swap in a
#: fresh recorder (monkeypatch) and every producer follows.
RECORDER = FlightRecorder()


def record(kind: str, **fields: Any) -> None:
    RECORDER.record(kind, **fields)


def trigger(kind: str, **fields: Any) -> None:
    RECORDER.trigger(kind, **fields)


def dump(trigger_name: str, path: str | Path | None = None,
         registries: dict[str, Any] | None = None) -> dict[str, Any]:
    return RECORDER.dump(trigger_name, path, registries=registries)


def _on_span(rec: dict[str, Any]) -> None:
    """Span feed: every finished span becomes a flight event (the ring is
    the recent-history buffer the dump narrates from)."""
    RECORDER.record(
        "span", name=rec["name"], trace_id=rec["trace_id"],
        span_id=rec["span_id"], parent_id=rec["parent_id"],
        t0=round(rec["t0"], 6), dur=round(rec["dur"], 6),
        thread=rec["thread"], node=rec.get("node") or None,
        attrs=rec["attrs"],
    )


_trace.TRACER.add_listener(_on_span)
