"""Two-node demo: the full post-quantum secure messaging flow via the public API.

Run:  python examples/two_node_demo.py

Creates two complete stacks (encrypted vault + TCP node + protocol engine) in
one process, performs the authenticated ML-KEM-768 + ML-DSA-65 + AES-256-GCM
handshake over real localhost TCP, exchanges a verified message and a file,
then prints audit-log metrics and key history.
"""

import asyncio
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

from quantum_resistant_p2p_tpu.app import SecureMessaging
from quantum_resistant_p2p_tpu.net import P2PNode
from quantum_resistant_p2p_tpu.storage import KeyStorage, SecureLogger


async def main() -> None:
    tmp = Path(tempfile.mkdtemp(prefix="qrp2p_demo_"))

    stacks = {}
    for name in ("alice", "bob"):
        vault = KeyStorage(tmp / f"{name}.vault.json")
        assert vault.unlock(f"{name}-password"), "fresh vault unlock"
        audit = SecureLogger(vault.get_or_create_purpose_key("audit"), tmp / f"{name}.logs")
        node = P2PNode(node_id=name, host="127.0.0.1", port=0)
        await node.start()
        sm = SecureMessaging(node, key_storage=vault, secure_logger=audit)
        stacks[name] = (vault, audit, node, sm)

    alice_vault, alice_audit, alice_node, alice = stacks["alice"]
    bob_vault, bob_audit, bob_node, bob = stacks["bob"]

    inbox: list = []
    bob.register_message_listener(lambda peer, m: inbox.append((peer, m)))

    peer = await alice_node.connect_to_peer("127.0.0.1", bob_node.port)
    print(f"[1] alice connected to: {peer}")

    ok = await alice.initiate_key_exchange("bob")
    print(f"[2] handshake (ML-KEM-768 + ML-DSA-65): {'OK' if ok else 'FAILED'}")
    print(f"    alice state: {alice.ke_state['bob'].value}")
    same = alice.shared_keys["bob"] == bob.shared_keys["alice"]
    print(f"    both sides derived the same AEAD key: {same}")

    msg = await alice.send_message("bob", b"hello post-quantum world")
    await asyncio.sleep(0.3)
    texts = [(p, m.content) for p, m in inbox if not m.is_system]
    print(f"[3] bob received: {texts}")

    blob = tmp / "paper.pdf"
    blob.write_bytes(b"%PDF-1.4 fake" * 9000)  # ~115 KiB -> chunked transport
    await alice.send_file("bob", blob)
    await asyncio.sleep(0.5)
    files = [(m.filename, len(m.content)) for _, m in inbox if m.is_file]
    print(f"[4] bob received file: {files}")

    print(f"[5] alice audit metrics: {alice_audit.get_security_metrics()['event_counts']}")
    hist = alice_vault.list_key_history("bob")
    print(f"[6] alice key history entries for bob: {len(hist)} (algo: "
          f"{alice_vault.get_key_history_value(hist[0]['name'])['algorithm']})")

    # negative probes
    locked = KeyStorage(tmp / "alice.vault.json")
    print(f"[7] vault unlock with wrong password: {locked.unlock('wrong')}")

    before = len([m for _, m in inbox if not m.is_system])
    await alice_node.send_message("bob", "secure_message", ct=b"\x00" * 64, ad=b"{}")
    await asyncio.sleep(0.3)
    after = len([m for _, m in inbox if not m.is_system])
    print(f"[8] forged ciphertext delivered to app layer: {after != before}")

    for _, _, node, _ in stacks.values():
        await node.stop()
    print("[9] clean shutdown")


if __name__ == "__main__":
    asyncio.run(main())
