"""Headline benchmark: batched ML-KEM-768 encapsulation throughput.

Prints ONE JSON line with the required keys {"metric", "value", "unit",
"vs_baseline"} plus dispatch-size labels (an ADVICE round-3 item): the
headline "value" is measured at the scaling-plateau dispatch size
(``dispatch_rows``, 2x the provider cap) and the shipped provider
configuration's figure rides along as ``value_at_provider_dispatch`` /
``provider_dispatch_rows``.  The metric name embeds the headline dispatch
size (so it reads ``mlkem768_encaps_batch4096_dispatch2048``; rounds 1-3
recorded the same quantity as ``mlkem768_encaps_batch4096``).

``--slo`` switches to the latency SLO probe: 32 sequential warm handshakes
through the tpu+batch stack (tools/swarm_bench.py at concurrency 1), with
single-handshake warm p50/p99 and MEASURED dispatch trips per handshake in
the emitted JSON — so BENCH_* rounds track the latency frontier (dispatch
count, docs/dispatch_budget.md) alongside the encaps/s headline.  The SLO
baseline is round 4's measured warm p50 (bench_results/
slo_single_handshake_r4.json, pre-fusion, same tunnel class):
``vs_baseline`` > 1 means faster than round 4.

Baseline: BASELINE.md / BASELINE.json north star — >= 50,000 ML-KEM-768
encaps/sec on one v5e chip (the reference's serial liboqs path measures
~4 full handshakes/sec end-to-end), so vs_baseline is value / 50_000.

Methodology (see utils/benchmarking.py and bench_report.md): every timed
region ends with a host readback that forces device completion —
``block_until_ready`` alone does NOT block on this remote-TPU platform and
inflated round 1's number ~6000x.  Fresh random inputs, first call excluded
(compile), best-of-3 trials of 3 back-to-back dispatches.

The full BASELINE.json config suite (keygen/decaps, FrodoKEM, ML-DSA,
SPHINCS+, swarm) lives in tools/full_bench.py.
"""

from __future__ import annotations

import argparse
import json

import numpy as np

BATCH = 4096
BASELINE_OPS_PER_S = 50_000.0
#: round-4 single-handshake warm p50 (pre-fusion; ~9-11 serial trips/hs)
SLO_BASELINE_P50_S = 1.5412
SLO_PEERS = 32


#: the --slo run FAILS (non-zero exit) when less than this fraction of the
#: warm window's ops rode the device path — the round-3 "silent CPU swarm"
#: regression (breaker open, fleet quietly degraded) is a tooling error now
SLO_MIN_DEVICE_SERVED = 0.9


def _write_slo_report(mode: str, slo: dict | None) -> None:
    """The SLO engines' burn/budget evaluation for the bench run (CI
    uploads it next to the trace and metrics artifacts;
    ``if-no-files-found: ignore`` covers runs without one).  The path is
    per-mode — ``slo_report.json`` for the ``--slo`` probe,
    ``storm_slo_report.json`` for ``--storm`` — so a session running both
    benches leaves BOTH evaluations on disk instead of the last writer
    silently replacing the other's under the slo-probe's name."""
    from pathlib import Path

    if slo is None:
        return
    name = "slo_report.json" if mode == "slo" else f"{mode}_slo_report.json"
    Path("bench_results").mkdir(exist_ok=True)
    Path(f"bench_results/{name}").write_text(
        json.dumps({"mode": mode, "slo": slo}, indent=2) + "\n")


def _write_cost_snapshot(mode: str, cost: dict | None) -> None:
    """The device-cost ledger snapshot for the bench run (obs/cost.py):
    padding waste, compile counts/seconds, opcache hit rates — written as
    ``bench_results/{mode}_cost_snapshot.json`` next to the storm
    artifacts and uploaded by ci.yml (``if-no-files-found: ignore``)."""
    from pathlib import Path

    if cost is None:
        return
    Path("bench_results").mkdir(exist_ok=True)
    Path(f"bench_results/{mode}_cost_snapshot.json").write_text(
        json.dumps({"mode": mode, "cost": cost}, indent=2,
                   sort_keys=True) + "\n")


def slo_main(out_path: str | None = None, peers: int = SLO_PEERS,
             warmup: int = 4) -> int:
    """Single-handshake SLO probe as a first-class bench output.

    Exit status gates CI: non-zero when any handshake failed OR when the
    warm run was < ``SLO_MIN_DEVICE_SERVED`` device-served (i.e. the "TPU"
    pipeline was actually the cpu fallback).
    """
    import asyncio
    import sys

    from tools.swarm_bench import run_swarm, write_obs_artifacts

    stats = asyncio.run(
        run_swarm(peers, backend="tpu", use_batching=True, max_batch=4096,
                  max_wait_ms=2.0, concurrency=1, warmup=warmup,
                  prewarm=True, slo=True)
    )
    # obs/ artifacts ride along with the SLO JSON (bench_results/): the
    # trace-event file renders the measured handshakes as flame graphs
    # (the 4-trips budget, visible), the MERGED multi-node trace puts the
    # hub and the peers on separate process lanes under the propagated
    # trace ids, and the metrics snapshot captures the queue/breaker state
    # the p50/p99 numbers were measured under
    write_obs_artifacts(stats, "bench_results", stem="slo")
    _write_slo_report("slo", stats.get("slo"))
    p50 = stats.get("p50_handshake_s")
    fraction = stats.get("device_served_fraction")
    out = {
        "metric": f"single_handshake_warm_p50_seq{peers}",
        "value": p50,
        "unit": "s",
        # latency SLO: >1 means faster than the round-4 (pre-fusion) probe
        "vs_baseline": round(SLO_BASELINE_P50_S / p50, 3) if p50 else None,
        "p99_handshake_s": stats.get("p99_handshake_s"),
        "trips_per_handshake": stats.get("trips_per_handshake"),
        "initiator_trips_p50": stats.get("initiator_trips_p50"),
        "initiator_trips_max": stats.get("initiator_trips_max"),
        "device_served_pct": stats.get("device_served_pct"),
        "device_served_fraction": fraction,
        "min_device_served_fraction": SLO_MIN_DEVICE_SERVED,
        "failures": stats.get("failures"),
        "detail": stats,
    }
    line = json.dumps(out)
    print(line)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")
    if stats.get("failures"):
        print(f"SLO FAIL: {stats['failures']} handshake failure(s)",
              file=sys.stderr)
        return 1
    if fraction is not None and fraction < SLO_MIN_DEVICE_SERVED:
        print(
            f"SLO FAIL: warm run only {fraction:.1%} device-served "
            f"(< {SLO_MIN_DEVICE_SERVED:.0%}): the device path is degraded "
            "(breaker state "
            f"{stats.get('breaker_state')!r}) — the 'TPU' numbers above "
            "measure the cpu fallback", file=sys.stderr)
        return 1
    return 0


#: storm ratchet configuration: the seeded trace the CI gate replays.
#: Moderate-load shape (bounded concurrency, paced arrival): the gateway
#: keeps up, device-served stays ~1.0, and run-to-run variance is small
#: enough for a meaningful tuned-vs-static comparison on this class of
#: host (full-saturation storms measured ±20-40% session noise).
STORM_SESSIONS = 1000
STORM_ARRIVAL_RATE = 150.0
STORM_CONCURRENCY = 128
STORM_SEED = 11
STORM_REPS = 2  # interleaved (static, tuned) pairs; metrics compared on means


def storm_main(out_path: str | None = None, sessions: int = STORM_SESSIONS,
               reps: int = STORM_REPS) -> int:
    """Gateway storm ratchet (docs/gateway.md): replay one seeded
    sustained-traffic trace under the STATIC flush policy and under the
    autotuner, write ``bench_results/storm_r0N.json``, and gate on:

    * zero failed handshakes and >= 0.9 device-served in every run;
    * the autotuner beating the static configuration on handshakes/s OR
      p99 (means over ``reps`` interleaved pairs — single-run comparisons
      flap with host noise);
    * the checked-in budget (``bench_results/storm_budget.json``), whose
      thresholds carry headroom for this host class's session variance.
    """
    import asyncio
    import statistics
    import sys
    from pathlib import Path

    from tools.swarm_bench import run_storm, write_obs_artifacts

    params = dict(
        sessions=sessions, arrival_rate=STORM_ARRIVAL_RATE,
        concurrency=STORM_CONCURRENCY, msgs_per_session=2, rekey_every=2,
        churn_fraction=0.1, seed=STORM_SEED,
    )
    runs: dict[bool, list[dict]] = {False: [], True: []}
    for _ in range(reps):
        for tuned in (False, True):  # interleaved: host drift hits both
            runs[tuned].append(
                asyncio.run(run_storm(autotune=tuned, **params)))

    def agg(tuned: bool, key: str) -> float:
        return round(statistics.mean(r[key] for r in runs[tuned]), 4)

    failures = sum(r["failures"] for rs in runs.values() for r in rs)
    min_served = min(r["device_served_fraction"] or 0.0
                     for rs in runs.values() for r in rs)
    tuned_hs, static_hs = agg(True, "handshakes_per_s"), agg(False, "handshakes_per_s")
    tuned_p99, static_p99 = agg(True, "p99_handshake_s"), agg(False, "p99_handshake_s")
    beats = tuned_hs >= static_hs or tuned_p99 <= static_p99

    budget_path = Path("bench_results/storm_budget.json")
    budget = (json.loads(budget_path.read_text()) if budget_path.exists()
              else None)
    out = {
        "metric": f"storm_{sessions}_sessions_handshakes_per_s",
        "value": tuned_hs,
        "unit": "handshakes/s",
        "vs_baseline": (round(tuned_hs / budget["min_handshakes_per_s"], 3)
                        if budget else None),
        "sessions": sessions,
        "reps_per_config": reps,
        "failures": failures,
        "min_device_served_fraction": min_served,
        "tuned": {"handshakes_per_s": tuned_hs, "p99_handshake_s": tuned_p99,
                  "p99_rekey_s": agg(True, "p99_rekey_s"),
                  "runs": runs[True]},
        "static": {"handshakes_per_s": static_hs,
                   "p99_handshake_s": static_p99,
                   "p99_rekey_s": agg(False, "p99_rekey_s"),
                   "runs": runs[False]},
        "autotuner_beats_static": beats,
        "budget": budget,
        "ok": True,
    }
    # obs artifacts for the LAST (tuned) storm window: merged multi-node
    # trace + metrics snapshot, plus the SLO engines' burn report and the
    # device-cost ledger snapshot (padding waste / compiles / opcache)
    write_obs_artifacts(out, "bench_results", stem="storm")
    _write_slo_report("storm", runs[True][-1].get("slo"))
    _write_cost_snapshot("storm", runs[True][-1].get("cost"))
    rc = 0
    if failures:
        print(f"STORM FAIL: {failures} handshake failure(s)", file=sys.stderr)
        rc = 1
    if min_served < SLO_MIN_DEVICE_SERVED:
        print(f"STORM FAIL: a run was only {min_served:.1%} device-served "
              f"(< {SLO_MIN_DEVICE_SERVED:.0%})", file=sys.stderr)
        rc = 1
    if not beats:
        print(f"STORM FAIL: autotuner beat static on neither handshakes/s "
              f"({tuned_hs} vs {static_hs}) nor p99 ({tuned_p99}s vs "
              f"{static_p99}s)", file=sys.stderr)
        rc = 1
    if budget is not None:
        if tuned_hs < budget["min_handshakes_per_s"]:
            print(f"STORM FAIL: {tuned_hs} handshakes/s under the budget "
                  f"floor {budget['min_handshakes_per_s']}", file=sys.stderr)
            rc = 1
        if tuned_p99 > budget["max_p99_handshake_s"]:
            print(f"STORM FAIL: p99 {tuned_p99}s over the budget cap "
                  f"{budget['max_p99_handshake_s']}s", file=sys.stderr)
            rc = 1
    out["ok"] = rc == 0
    line = json.dumps(out)
    print(line)
    Path("bench_results").mkdir(exist_ok=True)
    n = 1
    while Path(f"bench_results/storm_r{n:02d}.json").exists():
        n += 1
    Path(f"bench_results/storm_r{n:02d}.json").write_text(line + "\n")
    if out_path:
        Path(out_path).write_text(line + "\n")
    return rc


#: bulk-mix storm ratchet configuration (docs/gateway.md "Bulk-heavy
#: storms"): a bulk-heavy seeded trace (15 messages/session, 8 KiB
#: payloads) replayed twice — once on the scalar ChaCha20-Poly1305 path,
#: once through the batched device AEAD — and gated on the speedup.
#: 8 KiB payloads make the AEAD the dominant per-message cost (the shape
#: the data plane exists for — small-payload storms measure the Python
#: protocol loop, which both paths share); concurrency is a power of two
#: so every coalesced flush lands on a prewarmed pow2 batch bucket.
BULK_SESSIONS = 48
BULK_MSGS_PER_SESSION = 15
BULK_PAYLOAD_BYTES = 8192
BULK_CONCURRENCY = 64
BULK_ARRIVAL_RATE = 30.0
#: the tentpole's ratchet: batched bulk messages/s must beat the scalar
#: path by at least this factor, with zero failures and a p99 bound
MIN_BULK_SPEEDUP = 5.0
MAX_BULK_P99_MSG_S = 1.0


def bulk_storm_main(out_path: str | None = None,
                    sessions: int = BULK_SESSIONS,
                    msgs_per_session: int = BULK_MSGS_PER_SESSION) -> int:
    """Bulk-heavy storm ratchet (the data-plane gate): replay one seeded
    bulk-mix trace on the SCALAR ChaCha20-Poly1305 path and through the
    BATCHED device AEAD + binary wire, write
    ``bench_results/bulk_storm_r0N.json``, and gate on:

    * zero failed handshakes/sends in both runs;
    * batched bulk messages/s >= ``MIN_BULK_SPEEDUP`` x the scalar path;
    * batched p99 per-message latency <= ``MAX_BULK_P99_MSG_S``;
    * the batched run >= ``SLO_MIN_DEVICE_SERVED`` device-served (a
      quietly-degraded data plane must not pass on fallback numbers).

    Small session counts (tools/ci_smoke.sh) run in smoke mode: gates on
    failures only — sub-noise-floor ratio comparisons and the committed
    artifact are full-size-run territory.
    """
    import asyncio
    import sys
    from pathlib import Path

    from tools.swarm_bench import run_storm

    smoke = sessions < BULK_SESSIONS
    params = dict(
        sessions=sessions, arrival_rate=BULK_ARRIVAL_RATE,
        concurrency=BULK_CONCURRENCY, msgs_per_session=msgs_per_session,
        payload_bytes=BULK_PAYLOAD_BYTES, seed=STORM_SEED,
    )
    # untimed warm pass: compiles the batched AEAD's live (batch, length)
    # buckets so the measured window starts device-served (the in-process
    # jit cache persists across run_storm calls)
    asyncio.run(run_storm(aead_mode="chacha",
                          **{**params, "sessions": min(24, sessions)}))
    batched = asyncio.run(run_storm(aead_mode="chacha", **params))
    scalar = asyncio.run(run_storm(aead_mode="chacha-scalar", **params))

    speedup = (round(batched["msgs_per_s"] / scalar["msgs_per_s"], 2)
               if scalar["msgs_per_s"] else None)
    out = {
        "metric": (f"bulk_storm_{sessions}x{msgs_per_session}"
                   f"x{BULK_PAYLOAD_BYTES}B_msgs_per_s"),
        "value": batched["msgs_per_s"],
        "unit": "msgs/s",
        "vs_baseline": speedup,  # the scalar path IS the baseline
        "min_speedup": MIN_BULK_SPEEDUP,
        "max_p99_msg_s": MAX_BULK_P99_MSG_S,
        "speedup": speedup,
        "batched": batched,
        "scalar": scalar,
        "ok": True,
    }
    rc = 0
    failures = batched["failures"] + scalar["failures"]
    if failures:
        print(f"BULK STORM FAIL: {failures} failure(s)", file=sys.stderr)
        rc = 1
    if not smoke:
        if speedup is None or speedup < MIN_BULK_SPEEDUP:
            print(f"BULK STORM FAIL: batched path only {speedup}x the "
                  f"scalar baseline (< {MIN_BULK_SPEEDUP}x): "
                  f"{batched['msgs_per_s']} vs {scalar['msgs_per_s']} msgs/s",
                  file=sys.stderr)
            rc = 1
        if (batched["p99_msg_s"] or 0) > MAX_BULK_P99_MSG_S:
            print(f"BULK STORM FAIL: batched p99 message latency "
                  f"{batched['p99_msg_s']}s over the {MAX_BULK_P99_MSG_S}s "
                  "bound", file=sys.stderr)
            rc = 1
        served = batched["device_served_fraction"] or 0.0
        if served < SLO_MIN_DEVICE_SERVED:
            print(f"BULK STORM FAIL: batched run only {served:.1%} "
                  f"device-served (< {SLO_MIN_DEVICE_SERVED:.0%}) — the "
                  "'batched' numbers measure the scalar fallback",
                  file=sys.stderr)
            rc = 1
    out["ok"] = rc == 0
    line = json.dumps(out)
    print(line)
    if not smoke:
        Path("bench_results").mkdir(exist_ok=True)
        n = 1
        while Path(f"bench_results/bulk_storm_r{n:02d}.json").exists():
            n += 1
        Path(f"bench_results/bulk_storm_r{n:02d}.json").write_text(line + "\n")
    if out_path:
        Path(out_path).write_text(line + "\n")
    return rc


#: fleet chaos ratchet configuration (docs/fleet.md): the seeded
#: gateway-death storm the CI gate replays.  gw1 is SIGKILLed on its 8th
#: fleet health tick (~2 s in, mid-ramp at the paced arrival rate), so a
#: slice of live and in-flight sessions really does lose its gateway.
FLEET_GATEWAYS = 3
FLEET_KILL_GATEWAY = "gw1"
FLEET_KILL_TICK = 8


def fleet_storm_main(out_path: str | None = None,
                     sessions: int = STORM_SESSIONS,
                     gateways: int = FLEET_GATEWAYS,
                     spawn: str = "process") -> int:
    """Fleet chaos-storm ratchet (docs/fleet.md): replay ONE seeded
    sustained-traffic trace through ``gateways`` gateway PROCESSES behind
    the consistent-hash router, SIGKILL ``gw1`` mid-storm via the fault
    plan's process scope, write ``bench_results/fleet_storm_r0N.json``,
    and gate on the chaos number:

    * **zero lost established sessions** — every session that completed a
      handshake finished its workload (re-routed to the ring successor
      and re-keyed where needed);
    * **zero plaintext sends** (structural: the engine refuses to send
      without a shared key);
    * fleet ``device_served_fraction`` >= ``SLO_MIN_DEVICE_SERVED``
      across every gateway process plus the client plane;
    * the kill actually fired (the seeded ``injected`` log is non-empty)
      and the handshake-failure burst stayed BOUNDED — no larger than one
      concurrency window of attempts.

    ``--fleet 1`` runs the same harness with a single gateway and no kill
    (there is no successor to hand off to) — the within-noise comparison
    point against the single-process ``storm_r0N.json`` gate.
    """
    import asyncio
    import sys
    from pathlib import Path

    from quantum_resistant_p2p_tpu.fleet.storm import (
        default_kill_rules, run_fleet_storm, write_fleet_artifacts)
    from tools.swarm_bench import write_obs_artifacts

    # smoke mode (tools/ci_smoke.sh): a small session count finishes well
    # before the ratchet's ~2 s kill point, so tighten the heartbeat and
    # kill tick to keep the death genuinely MID-storm — and skip the
    # committed-artifact writes, which record official full-size runs only
    smoke = sessions < 500
    hb_interval = 0.1 if smoke else 0.25
    kill_tick = 4 if smoke else FLEET_KILL_TICK
    rules = (default_kill_rules(FLEET_KILL_GATEWAY, kill_tick)
             if gateways > 1 else None)
    # only the full-size CHAOS config owns the committed per-node reports
    # (the files ci.yml uploads): smoke runs and the --fleet 1 parity run
    # must not overwrite them — None -> run_fleet_storm uses a tempdir
    chaos_run = rules is not None
    report_dir = (Path("bench_results/fleet_reports")
                  if chaos_run and not smoke else None)
    # live telemetry rides every fleet ratchet run: one scrapeable
    # endpoint per gateway (announced via hello/heartbeat) and a mid-storm
    # qrtop --snapshot against them — the committed
    # fleet_storm_cost_snapshot.json is produced by the SAME scrape path
    # a human's dashboard uses (tools/qrtop.py)
    from tools.qrtop import snapshot_endpoints

    out = asyncio.run(run_fleet_storm(
        sessions, gateways=gateways, seed=STORM_SEED,
        arrival_rate=STORM_ARRIVAL_RATE, concurrency=STORM_CONCURRENCY,
        msgs_per_session=2, spawn=spawn, fault_rules=rules,
        hb_interval=hb_interval, report_dir=report_dir,
        telemetry=True, scrape_cb=snapshot_endpoints,
    ))
    served = out["device_served_fraction"] or 0.0
    burst_budget = STORM_CONCURRENCY
    out.update({
        "metric": f"fleet_storm_{sessions}x{gateways}_lost_established",
        "value": out["lost_established_sessions"],
        "unit": "sessions",
        "vs_baseline": None,
        "burst_budget": burst_budget,
    })
    rc = 0
    if out["lost_established_sessions"]:
        print(f"FLEET STORM FAIL: {out['lost_established_sessions']} "
              "established session(s) lost", file=sys.stderr)
        rc = 1
    if out["plaintext_sends"]:
        print(f"FLEET STORM FAIL: {out['plaintext_sends']} plaintext "
              "send(s)", file=sys.stderr)
        rc = 1
    if served < SLO_MIN_DEVICE_SERVED:
        print(f"FLEET STORM FAIL: fleet only {served:.1%} device-served "
              f"(< {SLO_MIN_DEVICE_SERVED:.0%})", file=sys.stderr)
        rc = 1
    if rules is not None and not out.get("chaos", {}).get("injected"):
        print("FLEET STORM FAIL: the seeded gateway kill never fired",
              file=sys.stderr)
        rc = 1
    if out["handshake_failures"] > burst_budget:
        print(f"FLEET STORM FAIL: handshake-failure burst "
              f"{out['handshake_failures']} exceeds one concurrency window "
              f"({burst_budget})", file=sys.stderr)
        rc = 1
    out["ok"] = rc == 0
    line = json.dumps(out)
    print(line)
    if not smoke:
        if chaos_run:
            # the shared artifact names (traces, merged fleet SLO) record
            # the flagship chaos run, never the parity comparison point
            write_obs_artifacts(out, "bench_results", stem="fleet_storm")
            write_fleet_artifacts(out, "bench_results")
            _write_cost_snapshot("fleet_storm", {
                "snapshot": out.get("cost_snapshot"),
                "fleet_totals": out.get("fleet_cost"),
                "telemetry": out.get("telemetry"),
            })
        Path("bench_results").mkdir(exist_ok=True)
        n = 1
        while Path(f"bench_results/fleet_storm_r{n:02d}.json").exists():
            n += 1
        Path(f"bench_results/fleet_storm_r{n:02d}.json").write_text(line + "\n")
    if out_path:
        Path(out_path).write_text(line + "\n")
    return rc


#: resume-mix storm ratchet configuration (docs/protocol.md "Session
#: resumption"): every session drops its TCP connection mid-workload and
#: re-establishes — with a held ticket that is a 1-RTT resume.  The gates
#: pin the three claims the resumption machinery makes: reconnects
#: actually resume (rate), resumes are CHEAP (p50 under the full
#: handshake's), and they cost ~0 device-seconds (the sequential probe).
RESUME_SESSIONS = 400
RESUME_MSGS_PER_SESSION = 4
RESUME_CONCURRENCY = 128
RESUME_ARRIVAL_RATE = 150.0
MIN_RESUME_RATE = 0.9


def resume_storm_main(out_path: str | None = None,
                      sessions: int = RESUME_SESSIONS) -> int:
    """Resume-mix storm ratchet: one seeded trace where every session
    reconnects mid-workload via its resumption ticket.  Writes
    ``bench_results/resume_storm_r0N.json`` and gates on:

    * zero failures (every reconnect ends established — fallback included);
    * ticket-resume rate >= ``MIN_RESUME_RATE`` (reconnects actually skip
      the KEM + 3 signatures);
    * resume p50 <= full-handshake p50 (the abbreviated exchange is the
      cheap path it claims to be);
    * the sequential cost probe's device trips stay ~0 (no device dispatch
      rides a resume — at most a straggler flush from the storm tail).
    """
    import asyncio
    import sys
    from pathlib import Path

    from tools.swarm_bench import run_storm

    smoke = sessions < 48
    out = asyncio.run(run_storm(
        sessions, seed=STORM_SEED, arrival_rate=RESUME_ARRIVAL_RATE,
        concurrency=RESUME_CONCURRENCY,
        msgs_per_session=RESUME_MSGS_PER_SESSION, resume_mix=True,
    ))
    rate = out.get("ticket_resume_rate") or 0.0
    probe = out.get("resume_cost_probe") or {}
    out.update({
        "metric": f"resume_storm_{sessions}_sessions_resume_rate",
        "value": rate,
        "unit": "fraction",
        "vs_baseline": None,
    })
    rc = 0
    if out["failures"]:
        print(f"RESUME STORM FAIL: {out['failures']} failed session(s)",
              file=sys.stderr)
        rc = 1
    if rate < MIN_RESUME_RATE:
        print(f"RESUME STORM FAIL: ticket-resume rate {rate:.1%} < "
              f"{MIN_RESUME_RATE:.0%}", file=sys.stderr)
        rc = 1
    p50_resume = out.get("p50_resume_s")
    p50_full = out.get("p50_handshake_s")
    if (p50_resume is not None and p50_full is not None
            and p50_resume > p50_full):
        print(f"RESUME STORM FAIL: resume p50 {p50_resume}s slower than "
              f"the full handshake's {p50_full}s", file=sys.stderr)
        rc = 1
    if probe and probe.get("resumes") and (
            probe.get("device_trips", 0) > probe["resumes"] // 2):
        print(f"RESUME STORM FAIL: {probe['device_trips']} device trips "
              f"across {probe['resumes']} pure resumes — resumes are "
              "supposed to cost ~0 device dispatches", file=sys.stderr)
        rc = 1
    out["ok"] = rc == 0
    line = json.dumps(out)
    print(line)
    if not smoke:
        Path("bench_results").mkdir(exist_ok=True)
        n = 1
        while Path(f"bench_results/resume_storm_r{n:02d}.json").exists():
            n += 1
        Path(f"bench_results/resume_storm_r{n:02d}.json").write_text(
            line + "\n")
    if out_path:
        Path(out_path).write_text(line + "\n")
    return rc


#: fleet rolling-restart ratchet configuration (docs/robustness.md
#: "Rolling restarts"): the full fleet-storm trace with a mid-storm
#: rolling SIGTERM restart of EVERY gateway plus one SIGKILL — the
#: planned-maintenance and the crash case in one run.  gw2 is killed
#: late enough that the roll is already in flight.
ROLL_DELAY_S = 2.0
ROLL_KILL_GATEWAY = "gw2"
ROLL_KILL_TICK = 16
MIN_POST_ROLL_RESUME_RATE = 0.9


def fleet_roll_main(out_path: str | None = None,
                    sessions: int = STORM_SESSIONS,
                    gateways: int = FLEET_GATEWAYS,
                    spawn: str = "process") -> int:
    """Fleet rolling-restart chaos ratchet: replay the seeded fleet trace
    while ``GatewayFleet.rolling_restart()`` drains + respawns every
    gateway mid-storm and the fault plan SIGKILLs one.  Writes
    ``bench_results/fleet_roll_r0N.json`` and gates on:

    * **zero lost established sessions** and **zero plaintext sends** —
      the fleet-storm invariants hold through a full rolling restart;
    * >= ``MIN_POST_ROLL_RESUME_RATE`` of post-restart reconnects resumed
      VIA TICKET (not full handshake) — the reconnect wave after a
      restart is the cheap path, which is the whole point of ISSUE 15;
    * the rolling restart itself completed (every gateway re-registered).
    """
    import asyncio
    import sys
    from pathlib import Path

    from quantum_resistant_p2p_tpu.fleet.storm import (default_kill_rules,
                                                       run_fleet_storm)
    from tools.swarm_bench import write_obs_artifacts

    smoke = sessions < 500
    hb_interval = 0.1 if smoke else 0.25
    # smoke runs pace arrivals slowly enough that sessions are genuinely
    # IN FLIGHT when the roll begins (a burst of tiny sessions finishes
    # before any gateway drains and proves nothing)
    roll_delay = 0.8 if smoke else ROLL_DELAY_S
    arrival = min(STORM_ARRIVAL_RATE, sessions / 3.0) if smoke \
        else STORM_ARRIVAL_RATE
    # the SIGKILL rides only the full-size chaos run with >= 3 gateways
    # (a 2-gateway smoke losing one to a kill AND one to a drain has no
    # capacity left to hand off to)
    rules = (default_kill_rules(ROLL_KILL_GATEWAY, ROLL_KILL_TICK)
             if not smoke and gateways > 2 else None)
    out = asyncio.run(run_fleet_storm(
        sessions, gateways=gateways, seed=STORM_SEED,
        arrival_rate=arrival, concurrency=STORM_CONCURRENCY,
        msgs_per_session=8, spawn=spawn, fault_rules=rules,
        hb_interval=hb_interval, roll=True, roll_delay_s=roll_delay,
        session_attempts=8, msg_interval_s=0.1 if smoke else 0.05,
    ))
    out.update({
        "metric": f"fleet_roll_{sessions}x{gateways}_lost_established",
        "value": out["lost_established_sessions"],
        "unit": "sessions",
        "vs_baseline": None,
    })
    rc = 0
    if out["lost_established_sessions"]:
        print(f"FLEET ROLL FAIL: {out['lost_established_sessions']} "
              "established session(s) lost", file=sys.stderr)
        rc = 1
    if out["plaintext_sends"]:
        print(f"FLEET ROLL FAIL: {out['plaintext_sends']} plaintext "
              "send(s)", file=sys.stderr)
        rc = 1
    if not (out.get("roll") or {}).get("ok"):
        print("FLEET ROLL FAIL: the rolling restart did not complete "
              "(a gateway never re-registered)", file=sys.stderr)
        rc = 1
    post = (out.get("post_roll_resumed") or 0) + (out.get("post_roll_full")
                                                  or 0)
    rate = out.get("post_roll_resume_rate")
    if smoke:
        # smoke gate: at least ONE displaced session must have resumed
        # via ticket (tiny smokes produce a handful of reconnects)
        if not out.get("resumed_reconnects"):
            print("FLEET ROLL FAIL: no ticket resume observed across the "
                  "rolling restart", file=sys.stderr)
            rc = 1
    elif post and (rate or 0.0) < MIN_POST_ROLL_RESUME_RATE:
        print(f"FLEET ROLL FAIL: post-restart ticket-resume rate "
              f"{rate:.1%} < {MIN_POST_ROLL_RESUME_RATE:.0%} "
              f"({out['post_roll_resumed']}/{post})", file=sys.stderr)
        rc = 1
    if rules is not None and not out.get("chaos", {}).get("injected"):
        print("FLEET ROLL FAIL: the seeded mid-roll gateway kill never "
              "fired", file=sys.stderr)
        rc = 1
    out["ok"] = rc == 0
    line = json.dumps(out)
    print(line)
    if not smoke:
        # fleet_roll_* obs artifacts only: the shared fleet_slo_report.json
        # name stays owned by the flagship kill-storm run
        write_obs_artifacts(out, "bench_results", stem="fleet_roll")
        Path("bench_results").mkdir(exist_ok=True)
        n = 1
        while Path(f"bench_results/fleet_roll_r{n:02d}.json").exists():
            n += 1
        Path(f"bench_results/fleet_roll_r{n:02d}.json").write_text(
            line + "\n")
    if out_path:
        Path(out_path).write_text(line + "\n")
    return rc


ROUTER_ROLL_ROUTERS = 2
ROUTER_KILL_TICK = 8
MIN_POST_FAILOVER_RESUME_RATE = 0.9


def router_roll_main(out_path: str | None = None,
                     sessions: int = STORM_SESSIONS,
                     gateways: int = FLEET_GATEWAYS,
                     routers: int = ROUTER_ROLL_ROUTERS,
                     spawn: str = "process") -> int:
    """Router-roll chaos ratchet (``--storm --fleet N --router-roll``):
    the control plane is ``routers`` replicated router processes behind a
    leader lease (fleet/router.py), and the chaos targets THEM — the
    seeded fault plan SIGKILLs the leader replica mid-storm, then a
    rolling restart cycles every router while the sessions run.  Writes
    ``bench_results/router_roll_r0N.json`` and gates on:

    * **zero lost established sessions** and **zero plaintext sends** —
      router death moves routing + STEK authority, never the data plane;
    * >= ``MIN_POST_FAILOVER_RESUME_RATE`` of post-failover reconnects
      resumed VIA TICKET — tickets minted under the dead leader's STEK
      still redeem after the lease moves (the replicated dual-key
      window, docs/fleet.md "HA control plane");
    * the seeded leader kill fired and the rolling restart completed.
    """
    import asyncio
    import sys
    from pathlib import Path

    from quantum_resistant_p2p_tpu.fleet.storm import (
        default_router_kill_rules, run_router_storm)
    from tools.swarm_bench import write_obs_artifacts

    smoke = sessions < 500
    hb_interval = 0.1 if smoke else 0.25
    roll_delay = 1.2 if smoke else ROLL_DELAY_S
    arrival = min(STORM_ARRIVAL_RATE, sessions / 3.0) if smoke \
        else STORM_ARRIVAL_RATE
    # rt0 (rank 0) claims first by construction, so the kill rule names
    # the replica that IS the leader when the storm opens
    rules = default_router_kill_rules("rt0", ROUTER_KILL_TICK)
    out = asyncio.run(run_router_storm(
        sessions, gateways=gateways, routers=routers, seed=STORM_SEED,
        arrival_rate=arrival, concurrency=STORM_CONCURRENCY,
        msgs_per_session=8, spawn=spawn, fault_rules=rules,
        hb_interval=hb_interval, roll=True, roll_delay_s=roll_delay,
        session_attempts=8, msg_interval_s=0.1 if smoke else 0.05,
        lease_ttl_s=0.8 if smoke else 1.0,
    ))
    out.update({
        "metric": (f"router_roll_{sessions}x{gateways}gw{routers}rt"
                   "_lost_established"),
        "value": out["lost_established_sessions"],
        "unit": "sessions",
        "vs_baseline": None,
    })
    rc = 0
    if out["lost_established_sessions"]:
        print(f"ROUTER ROLL FAIL: {out['lost_established_sessions']} "
              "established session(s) lost", file=sys.stderr)
        rc = 1
    if out["plaintext_sends"]:
        print(f"ROUTER ROLL FAIL: {out['plaintext_sends']} plaintext "
              "send(s)", file=sys.stderr)
        rc = 1
    if not out.get("chaos", {}).get("injected"):
        print("ROUTER ROLL FAIL: the seeded leader SIGKILL never fired",
              file=sys.stderr)
        rc = 1
    if not (out.get("roll") or {}).get("ok"):
        print("ROUTER ROLL FAIL: the router rolling restart did not "
              "complete (a replica never came back)", file=sys.stderr)
        rc = 1
    post = (out.get("post_failover_resumed") or 0) + (
        out.get("post_failover_full") or 0)
    rate = out.get("post_failover_resume_rate")
    if smoke:
        # smoke gate: at least one reconnect AFTER the failover must have
        # redeemed a ticket minted before it
        if not out.get("post_failover_resumed"):
            print("ROUTER ROLL FAIL: no post-failover ticket resume "
                  "observed", file=sys.stderr)
            rc = 1
    elif not post:
        print("ROUTER ROLL FAIL: no reconnects landed after the "
              "failover — the storm proves nothing", file=sys.stderr)
        rc = 1
    elif (rate or 0.0) < MIN_POST_FAILOVER_RESUME_RATE:
        print(f"ROUTER ROLL FAIL: post-failover ticket-resume rate "
              f"{rate:.1%} < {MIN_POST_FAILOVER_RESUME_RATE:.0%} "
              f"({out['post_failover_resumed']}/{post})", file=sys.stderr)
        rc = 1
    out["ok"] = rc == 0
    line = json.dumps(out)
    print(line)
    if not smoke:
        write_obs_artifacts(out, "bench_results", stem="router_roll")
        Path("bench_results").mkdir(exist_ok=True)
        n = 1
        while Path(f"bench_results/router_roll_r{n:02d}.json").exists():
            n += 1
        Path(f"bench_results/router_roll_r{n:02d}.json").write_text(
            line + "\n")
    if out_path:
        Path(out_path).write_text(line + "\n")
    return rc


def multichip_main(out_path: str | None, shards: str, hs_peers: int,
                   emulate: int) -> int:
    """1→N-chip scaling probe (tools/swarm_bench.run_multichip): batch-4096
    ML-KEM-768 encaps/s on a GSPMD-sharded mesh plus warm handshakes/s
    through the placement scheduler, at each shard count.  Writes the
    scaling-curve JSON (a REAL ``MULTICHIP_r0N.json`` — earlier rounds
    only recorded reachability) to ``--out`` and, for the CI artifact, to
    ``bench_results/multichip_scaling.json``.

    Exit status: non-zero when any shard count's handshake window had
    failures (reachability-only environments still exit 0 with the
    encaps-only curve).
    """
    import sys

    from tools.swarm_bench import run_multichip

    counts = tuple(int(c) for c in shards.split(",") if c)
    out = run_multichip(shard_counts=counts, hs_peers=hs_peers,
                        emulate=emulate)
    line = json.dumps(out)
    print(line)
    from pathlib import Path

    Path("bench_results").mkdir(exist_ok=True)
    Path("bench_results/multichip_scaling.json").write_text(line + "\n")
    if out_path:
        Path(out_path).write_text(line + "\n")
    failures = sum(e.get("failures") or 0 for e in out["shards"].values())
    if failures:
        print(f"MULTICHIP FAIL: {failures} handshake failure(s) across the "
              "scaling sweep", file=sys.stderr)
        return 1
    return 0


#: default dispatch rows for the --raw-ops --family frodo probe: a full
#: lane tile x2 (the kernel's (8, 128) layout) — big enough to amortise
#: the tunnel's fixed round trip, small enough for CPU-twin smoke runs
FRODO_RAW_BATCH = 256
#: the frodo raw-ops probe FAILS when less than this fraction of its ops
#: rode the device path (same bar as --slo): a silently-degraded kernel
#: path must not report fallback numbers as device numbers
FRODO_MIN_DEVICE_SERVED = SLO_MIN_DEVICE_SERVED


def frodo_raw_ops_main(out_path: str | None = None,
                       batch: int = FRODO_RAW_BATCH,
                       name: str = "FrodoKEM-640-SHAKE") -> int:
    """Raw-ops probe for the FrodoKEM device path (``--raw-ops --family
    frodo``): keygen / cold encaps / warm (operand-cached) encaps / decaps
    per second at ``batch`` rows, same forced-readback methodology as the
    ML-KEM headline (device-resident operands, 1-element readback fence).

    The run is gated the way the SLO probe is: the pinned pyref KAT must
    pass through the device path FIRST (provider/health.py), and the cost
    ledger's ``device_served_fraction`` over the run must stay >=
    ``FRODO_MIN_DEVICE_SERVED`` — a minimal image whose kernel path
    regressed to fallback exits non-zero instead of shipping wrong numbers.
    """
    import sys
    from pathlib import Path

    import jax

    from quantum_resistant_p2p_tpu.kem import frodo
    from quantum_resistant_p2p_tpu.obs.cost import CostLedger
    from quantum_resistant_p2p_tpu.provider import health
    from quantum_resistant_p2p_tpu.provider.kem_providers import (
        FrodoKEMKeyExchange)
    from quantum_resistant_p2p_tpu.utils.benchmarking import (
        enable_compile_cache, sync, timeit)

    enable_compile_cache()
    level = {"FrodoKEM-640-SHAKE": 1, "FrodoKEM-976-SHAKE": 3,
             "FrodoKEM-1344-SHAKE": 5}[name]
    kem = FrodoKEMKeyExchange(security_level=level, backend="tpu",
                              use_aes=False)
    p = kem.params
    ledger = CostLedger()
    kem.opcache.attach_cost(ledger, "frodo_pk")
    ops_done = 0
    ledger.set_handshakes_fn(lambda: max(ops_done, 1))

    verdict = health._check_frodo_kat(kem)
    short = name.replace("FrodoKEM-", "frodo").replace("-SHAKE", "shake")
    out: dict = {
        "metric": f"{short}_encaps_warm_batch{batch}",
        "unit": "encaps/s",
        "vs_baseline": None,  # no committed frodo baseline before this round
        "platform": jax.devices()[0].platform,
        "batch": batch,
        "kat_ok": bool(verdict.ok),
        "kat_detail": verdict.detail,
        "min_device_served_fraction": FRODO_MIN_DEVICE_SERVED,
    }
    rc = 0
    if not verdict.ok:
        # every op this run WOULD have done is a bypass: the device path
        # is not trustworthy, so nothing below is worth timing
        ledger.bypass_items("frodo.encaps", "kat_failed", batch)
        out.update({"value": None, "device_served_fraction": 0.0})
        rc = 1
    else:
        rng = np.random.default_rng(640)

        def dev(shape):
            a = jax.device_put(
                rng.integers(0, 256, size=shape, dtype=np.uint8))
            sync(a)
            return a

        kg, _, dec = frodo.get(p.name)
        enc_cold, enc_pre = frodo.get_pre(p.name)
        s, se, z = (dev((batch, p.len_sec)) for _ in range(3))
        mu = dev((batch, p.len_sec))
        pk, sk = kg(s, se, z)
        sync((pk, sk))
        keygen_s = timeit(lambda: kg(s, se, z))
        # single-key batch (the handshake shape): cold fills the per-key
        # operand cache in one dispatch, warm reuses the device-resident
        # expanded A matrix — the provider's opcache fast path
        pk0 = jax.device_put(np.asarray(pk)[0])
        sync(pk0)
        cold_s = timeit(lambda: enc_cold(pk0, mu))
        pre, ct, ss = enc_cold(pk0, mu)
        sync((ct, ss))
        warm_s = timeit(lambda: enc_pre(pre, mu))
        skb = jax.device_put(np.broadcast_to(np.asarray(sk)[0],
                                             (batch, p.sk_len)))
        sync(skb)
        decaps_s = timeit(lambda: dec(skb, ct))
        for op, secs in (("keygen", keygen_s), ("encaps_cold", cold_s),
                         ("encaps_warm", warm_s), ("decaps", decaps_s)):
            # full rows, full bucket: raw ops pad nothing — the padding
            # waste the ledger reports is genuinely the dispatch shape's
            ledger.flush_occupancy(f"frodo.{op}", "bulk", batch, batch)
            ledger.device_time(f"frodo.{op}", secs)
            ops_done += batch
        # provider surface: one cold + one warm single-key batch so the
        # opcache accounting (hit rate, device-served story) reflects the
        # path handshakes actually take
        pks = np.broadcast_to(np.asarray(pk)[0], (batch, p.pk_len)).copy()
        for _ in range(2):
            kem.encapsulate_batch(pks)
            ledger.flush_occupancy("frodo.encaps_provider", "bulk", batch,
                                   batch)
            ops_done += batch
        served = ledger.device_served_fraction()
        totals = ledger.totals()
        out.update({
            "value": round(batch / warm_s, 1),
            "keygen_per_s": round(batch / keygen_s, 1),
            "encaps_cold_per_s": round(batch / cold_s, 1),
            "encaps_warm_per_s": round(batch / warm_s, 1),
            "decaps_per_s": round(batch / decaps_s, 1),
            "warm_vs_cold": round(cold_s / warm_s, 2),
            "device_served_fraction": served,
            "device_seconds_per_1k_ops":
                ledger.device_seconds_per_1k_handshakes(),
            "padding_waste_fraction": ledger.padding_waste_fraction(),
            "opcache": kem.opcache.stats(),
            "cost": totals,
        })
        if (served or 0.0) < FRODO_MIN_DEVICE_SERVED:
            print(f"RAW-OPS FAIL: frodo run only {(served or 0.0):.1%} "
                  f"device-served (< {FRODO_MIN_DEVICE_SERVED:.0%})",
                  file=sys.stderr)
            rc = 1
    if not out["kat_ok"]:
        print(f"RAW-OPS FAIL: frodo device KAT failed: {verdict.detail}",
              file=sys.stderr)
    line = json.dumps(out)
    print(line)
    Path("bench_results").mkdir(exist_ok=True)
    Path("bench_results/frodo_raw_ops.json").write_text(line + "\n")
    if out_path:
        Path(out_path).write_text(line + "\n")
    return rc


def main() -> None:
    from quantum_resistant_p2p_tpu.kem import mlkem
    from quantum_resistant_p2p_tpu.utils.benchmarking import enable_compile_cache, sync, timeit

    enable_compile_cache()

    # The 4096 batch runs as back-to-back dispatches at TWO dispatch sizes,
    # both emitted (an ADVICE round-3 item: the headline must carry its
    # dispatch size, since the two differ ~6%):
    #   * 2048 rows — the top of the per-dispatch scaling plateau
    #     (bench_report.md; one-to-two full grid steps of the fused Pallas
    #     SampleNTT kernel) — this is the headline "value";
    #   * 1024 rows — MAX_DEVICE_BATCH, what the shipped provider actually
    #     dispatches (kept lower for queue latency) — emitted as
    #     "value_at_provider_dispatch".
    # Raw-ops methodology: operands stay device-resident between dispatches;
    # the provider's per-slice host work and the slow device tunnel
    # (~0.4-2.2 MB/s across sessions, see audit_tunnel in
    # bench_results/full_bench_r2.json) are excluded here and measured by
    # the swarm benchmark instead.
    import jax

    kg, enc, _ = mlkem.get("ML-KEM-768")
    rng = np.random.default_rng(0)

    def measure(step: int) -> float:
        assert BATCH % step == 0, "ops_per_s assumes reps * step == BATCH"
        reps = BATCH // step
        d = rng.integers(0, 256, size=(step, 32), dtype=np.uint8)
        z = rng.integers(0, 256, size=(step, 32), dtype=np.uint8)
        m = rng.integers(0, 256, size=(step, 32), dtype=np.uint8)
        ek, _ = kg(d, z)
        sync(ek)
        # Device-resident operands per the raw-ops methodology above (ek
        # already lives on device as kg's output; without this, every
        # dispatch re-sends m through this environment's ~MB/s tunnel and
        # the number measures the tunnel, not the chip).
        m = jax.device_put(m)
        sync(m)

        def run():
            out = None
            for _ in range(reps):
                out = enc(ek, m)
            return out

        return BATCH / timeit(run)

    provider_step = mlkem.MAX_DEVICE_BATCH
    plateau_step = 2 * mlkem.MAX_DEVICE_BATCH
    at_provider = measure(provider_step)
    at_plateau = measure(plateau_step)
    print(
        json.dumps(
            {
                "metric": f"mlkem768_encaps_batch4096_dispatch{plateau_step}",
                "value": round(at_plateau, 1),
                "unit": "encaps/s",
                "vs_baseline": round(at_plateau / BASELINE_OPS_PER_S, 3),
                "dispatch_rows": plateau_step,
                "value_at_provider_dispatch": round(at_provider, 1),
                "provider_dispatch_rows": provider_step,
                "vs_baseline_at_provider_dispatch": round(
                    at_provider / BASELINE_OPS_PER_S, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--slo", action="store_true",
                    help="latency SLO probe (sequential warm handshakes + "
                         "trips/handshake) instead of the throughput headline")
    ap.add_argument("--multichip", action="store_true",
                    help="1->N-chip scaling sweep (encaps/s on a sharded "
                         "mesh + handshakes/s through the placement "
                         "scheduler) instead of the single-chip headline")
    ap.add_argument("--storm", action="store_true",
                    help="gateway storm ratchet: one seeded 1000-session "
                         "sustained-traffic trace, static flush policy vs "
                         "the autotuner, gated on the checked-in budget "
                         "(docs/gateway.md)")
    ap.add_argument("--fleet", type=int, default=0,
                    help="with --storm: run the FLEET chaos ratchet instead "
                         "— this many gateway processes behind the "
                         "consistent-hash router, one seeded mid-storm "
                         "gateway kill, gated on zero lost established "
                         "sessions (docs/fleet.md)")
    ap.add_argument("--spawn", default="process",
                    choices=("process", "task"),
                    help="fleet gateway isolation (--storm --fleet): real "
                         "subprocesses or in-process asyncio tasks")
    ap.add_argument("--resume-mix", action="store_true",
                    help="with --storm: run the session-RESUMPTION ratchet "
                         "instead — every session reconnects mid-workload "
                         "via its ticket, gated on resume rate / latency / "
                         "~0 device cost (docs/protocol.md)")
    ap.add_argument("--roll", action="store_true",
                    help="with --storm --fleet: run the ROLLING-RESTART "
                         "chaos ratchet instead — every gateway drained "
                         "and respawned mid-storm (+ one SIGKILL), gated "
                         "on 0 lost sessions and a >=90%% post-restart "
                         "ticket-resume rate (docs/robustness.md)")
    ap.add_argument("--router-roll", action="store_true",
                    help="with --storm --fleet: run the ROUTER-roll chaos "
                         "ratchet — N replicated routers behind a leader "
                         "lease, seeded mid-storm SIGKILL of the leader "
                         "plus a rolling restart of every router, gated "
                         "on 0 lost sessions and a >=90%% post-failover "
                         "ticket-resume rate (docs/fleet.md)")
    ap.add_argument("--routers", type=int, default=ROUTER_ROLL_ROUTERS,
                    help="router replica count for --router-roll")
    ap.add_argument("--bulk-mix", action="store_true",
                    help="with --storm: run the BULK-heavy data-plane "
                         "ratchet instead — one seeded bulk-mix trace on "
                         "the scalar ChaCha20-Poly1305 path vs the batched "
                         "device AEAD, gated on >=5x messages/s and a p99 "
                         "message-latency bound (docs/gateway.md)")
    ap.add_argument("--sessions", type=int, default=STORM_SESSIONS,
                    help="concurrent sessions in the storm ratchet")
    ap.add_argument("--reps", type=int, default=STORM_REPS,
                    help="interleaved (static, tuned) pairs in the storm "
                         "ratchet")
    ap.add_argument("--out", default=None,
                    help="also write the JSON line to this path "
                         "(slo/multichip modes)")
    ap.add_argument("--peers", type=int, default=SLO_PEERS,
                    help="handshakes in the slo probe")
    ap.add_argument("--warmup", type=int, default=4,
                    help="untimed warmup handshakes in the slo probe")
    ap.add_argument("--shards", default="1,2,4,8",
                    help="comma-separated shard counts for --multichip")
    ap.add_argument("--hs-peers", type=int, default=32,
                    help="warm handshakes per shard count in --multichip "
                         "(0 skips the handshake half of the sweep)")
    ap.add_argument("--emulate", type=int, default=0,
                    help="force an N-device virtual CPU platform for "
                         "--multichip (single-accelerator hosts)")
    ap.add_argument("--raw-ops", action="store_true",
                    help="raw per-op device throughput for one KEM family "
                         "(see --family) instead of the handshake modes: "
                         "keygen / cold + warm (operand-cached) encaps / "
                         "decaps per second with forced readback, gated on "
                         "the device KAT and >=90%% device-served")
    ap.add_argument("--family", default="mlkem",
                    choices=("mlkem", "frodo"),
                    help="KEM family for --raw-ops: mlkem routes to the "
                         "headline benchmark, frodo runs the FrodoKEM "
                         "device-path probe")
    ap.add_argument("--batch", type=int, default=FRODO_RAW_BATCH,
                    help="dispatch rows for --raw-ops --family frodo")
    ap.add_argument("--full-snapshots", action="store_true",
                    help="write RAW per-registry metrics snapshots "
                         "(~MBs for a storm) instead of the compact "
                         "committed digests")
    args = ap.parse_args()
    from tools.swarm_bench import set_full_snapshots
    set_full_snapshots(args.full_snapshots)
    if args.raw_ops and args.family == "frodo":
        raise SystemExit(frodo_raw_ops_main(args.out, args.batch))
    if args.slo:
        raise SystemExit(slo_main(args.out, args.peers, args.warmup))
    if args.storm and args.fleet and args.router_roll:
        raise SystemExit(router_roll_main(args.out, args.sessions,
                                          args.fleet, args.routers,
                                          args.spawn))
    if args.storm and args.fleet and args.roll:
        raise SystemExit(fleet_roll_main(args.out, args.sessions,
                                         args.fleet, args.spawn))
    if args.storm and args.fleet:
        raise SystemExit(fleet_storm_main(args.out, args.sessions,
                                          args.fleet, args.spawn))
    if args.storm and args.resume_mix:
        sessions = (args.sessions if args.sessions != STORM_SESSIONS
                    else RESUME_SESSIONS)
        raise SystemExit(resume_storm_main(args.out, sessions))
    if args.storm and args.bulk_mix:
        sessions = (args.sessions if args.sessions != STORM_SESSIONS
                    else BULK_SESSIONS)
        raise SystemExit(bulk_storm_main(args.out, sessions))
    if args.storm:
        raise SystemExit(storm_main(args.out, args.sessions, args.reps))
    if args.multichip:
        raise SystemExit(multichip_main(args.out, args.shards, args.hs_peers,
                                        args.emulate))
    main()
