"""Headline benchmark: batched ML-KEM-768 encapsulation throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: BASELINE.md / BASELINE.json north star — >= 50,000 ML-KEM-768
encaps/sec on one v5e chip (the reference's serial liboqs path measures
~4 full handshakes/sec end-to-end), so vs_baseline is value / 50_000.

Methodology (see utils/benchmarking.py and bench_report.md): every timed
region ends with a host readback that forces device completion —
``block_until_ready`` alone does NOT block on this remote-TPU platform and
inflated round 1's number ~6000x.  Fresh random inputs, first call excluded
(compile), best-of-3 trials of 3 back-to-back dispatches.

The full BASELINE.json config suite (keygen/decaps, FrodoKEM, ML-DSA,
SPHINCS+, swarm) lives in tools/full_bench.py.
"""

from __future__ import annotations

import json

import numpy as np

BATCH = 4096
BASELINE_OPS_PER_S = 50_000.0


def main() -> None:
    from quantum_resistant_p2p_tpu.kem import mlkem
    from quantum_resistant_p2p_tpu.utils.benchmarking import enable_compile_cache, sync, timeit

    enable_compile_cache()

    # The 4096 batch runs as 2048-row back-to-back dispatches: the
    # per-dispatch scaling curve (bench_report.md) plateaus over 1024-2048
    # rows (one-to-two full grid steps of the fused Pallas SampleNTT
    # kernel) and 2048 measures ~6% above 1024 in same-session A/B.  The
    # provider keeps MAX_DEVICE_BATCH = 1024 for queue latency; the raw-ops
    # headline takes the plateau's top.  Raw-ops methodology: operands stay
    # device-resident between dispatches; the provider's per-slice host work
    # and the slow device tunnel (~0.4-2.2 MB/s across sessions, see
    # audit_tunnel in bench_results/full_bench_r2.json) are excluded here
    # and measured by the swarm benchmark instead.
    step = 2 * mlkem.MAX_DEVICE_BATCH
    assert BATCH % step == 0, "ops_per_s below assumes reps * step == BATCH"
    reps = BATCH // step
    rng = np.random.default_rng(0)
    d = rng.integers(0, 256, size=(step, 32), dtype=np.uint8)
    z = rng.integers(0, 256, size=(step, 32), dtype=np.uint8)
    m = rng.integers(0, 256, size=(step, 32), dtype=np.uint8)

    kg, enc, _ = mlkem.get("ML-KEM-768")
    ek, _ = kg(d, z)
    sync(ek)
    # Device-resident operands per the raw-ops methodology above (ek already
    # lives on device as kg's output; without this, every dispatch re-sends
    # m through this environment's ~MB/s tunnel and the number measures the
    # tunnel, not the chip).
    import jax

    m = jax.device_put(m)
    sync(m)

    def run():
        out = None
        for _ in range(reps):
            out = enc(ek, m)
        return out

    secs = timeit(run)
    ops_per_s = BATCH / secs
    print(
        json.dumps(
            {
                "metric": "mlkem768_encaps_batch4096",
                "value": round(ops_per_s, 1),
                "unit": "encaps/s",
                "vs_baseline": round(ops_per_s / BASELINE_OPS_PER_S, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
