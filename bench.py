"""Headline benchmark: batched ML-KEM-768 encapsulation throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: BASELINE.md / BASELINE.json north star — >= 50,000 ML-KEM-768
encaps/sec on one v5e chip (the reference's serial liboqs path measures
~4 full handshakes/sec end-to-end; 50k/s is the agreed chip-level target, so
vs_baseline is value / 50_000).
"""

from __future__ import annotations

import json
import time

import numpy as np

BATCH = 4096
BASELINE_OPS_PER_S = 50_000.0


def main() -> None:
    import jax

    from quantum_resistant_p2p_tpu.kem import mlkem
    from quantum_resistant_p2p_tpu.pyref.mlkem_ref import MLKEM768

    rng = np.random.default_rng(0)
    d = rng.integers(0, 256, size=(BATCH, 32), dtype=np.uint8)
    z = rng.integers(0, 256, size=(BATCH, 32), dtype=np.uint8)
    m = rng.integers(0, 256, size=(BATCH, 32), dtype=np.uint8)

    kg, enc, _ = mlkem.get("ML-KEM-768")
    ek, _ = jax.block_until_ready(kg(d, z))

    # Warm-up compiles + populates caches.
    jax.block_until_ready(enc(ek, m))

    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(enc(ek, m))
        best = min(best, time.perf_counter() - t0)

    ops_per_s = BATCH / best
    print(
        json.dumps(
            {
                "metric": "mlkem768_encaps_batch4096",
                "value": round(ops_per_s, 1),
                "unit": "encaps/s",
                "vs_baseline": round(ops_per_s / BASELINE_OPS_PER_S, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
