// qrp_native — C++ host crypto core for the CPU backend fast path.
//
// The reference app's CPU crypto is native C (vendored liboqs, loaded via
// ctypes: reference vendor/oqs.py:122-183).  This library fills the same role
// for this framework: Keccak (SHAKE-128/256, SHA3-256/512) and a complete
// ML-KEM-512/768/1024 (FIPS 203) with deterministic seams, exposed as a thin
// extern "C" surface loaded via ctypes (no pybind11 in this environment).
// The pure-Python pyref stays as the bit-exactness oracle; this is the
// production CPU path.
//
// Build: g++ -O3 -shared -fPIC -o libqrp_native.so qrp_native.cpp

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

// ---------------------------------------------------------------- Keccak

const uint64_t RC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL};

inline uint64_t rotl(uint64_t x, int n) { return (x << n) | (x >> (64 - n)); }

void keccak_f1600(uint64_t s[25]) {
  for (int round = 0; round < 24; ++round) {
    uint64_t c[5], d[5];
    for (int x = 0; x < 5; ++x)
      c[x] = s[x] ^ s[x + 5] ^ s[x + 10] ^ s[x + 15] ^ s[x + 20];
    for (int x = 0; x < 5; ++x) {
      d[x] = c[(x + 4) % 5] ^ rotl(c[(x + 1) % 5], 1);
      for (int y = 0; y < 5; ++y) s[x + 5 * y] ^= d[x];
    }
    // rho + pi
    uint64_t b[25];
    static const int RHO[25] = {0,  1,  62, 28, 27, 36, 44, 6,  55, 20, 3,  10, 43,
                                25, 39, 41, 45, 15, 21, 8,  18, 2,  61, 56, 14};
    for (int x = 0; x < 5; ++x)
      for (int y = 0; y < 5; ++y) {
        int src = x + 5 * y;
        int dst = y + 5 * ((2 * x + 3 * y) % 5);
        b[dst] = rotl(s[src], RHO[src]);
      }
    // chi
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        s[x + 5 * y] = b[x + 5 * y] ^ ((~b[(x + 1) % 5 + 5 * y]) & b[(x + 2) % 5 + 5 * y]);
    s[0] ^= RC[round];
  }
}

struct Sponge {
  uint64_t s[25];
  unsigned rate;  // bytes
  unsigned pos;
  explicit Sponge(unsigned rate_bytes) : rate(rate_bytes), pos(0) {
    std::memset(s, 0, sizeof(s));
  }
  void absorb(const uint8_t* data, size_t len) {
    while (len) {
      size_t take = rate - pos;
      if (take > len) take = len;
      for (size_t i = 0; i < take; ++i)
        reinterpret_cast<uint8_t*>(s)[pos + i] ^= data[i];
      data += take;
      len -= take;
      pos += take;
      if (pos == rate) {
        keccak_f1600(s);
        pos = 0;
      }
    }
  }
  void finish(uint8_t ds) {
    reinterpret_cast<uint8_t*>(s)[pos] ^= ds;
    reinterpret_cast<uint8_t*>(s)[rate - 1] ^= 0x80;
    keccak_f1600(s);
    pos = 0;
  }
  void squeeze(uint8_t* out, size_t len) {
    while (len) {
      if (pos == rate) {
        keccak_f1600(s);
        pos = 0;
      }
      size_t take = rate - pos;
      if (take > len) take = len;
      std::memcpy(out, reinterpret_cast<uint8_t*>(s) + pos, take);
      out += take;
      len -= take;
      pos += take;
    }
  }
};

void shake(unsigned rate, const uint8_t* in, size_t inlen, uint8_t* out, size_t outlen) {
  Sponge sp(rate);
  sp.absorb(in, inlen);
  sp.finish(0x1f);
  sp.squeeze(out, outlen);
}

void sha3(unsigned rate, const uint8_t* in, size_t inlen, uint8_t* out, size_t outlen) {
  Sponge sp(rate);
  sp.absorb(in, inlen);
  sp.finish(0x06);
  sp.squeeze(out, outlen);
}

// ---------------------------------------------------------------- ML-KEM

constexpr int N = 256;
constexpr int Q = 3329;

struct MLKEMParams {
  int k, eta1, eta2, du, dv;
};

MLKEMParams params_for(int k) {
  if (k == 2) return {2, 3, 2, 10, 4};
  if (k == 3) return {3, 2, 2, 10, 4};
  return {4, 2, 2, 11, 5};
}

int16_t ZETAS[128];
int16_t GAMMAS[128];

struct ZetaInit {
  ZetaInit() {
    auto pw = [](int b, int e) {
      long r = 1, base = b;
      while (e) {
        if (e & 1) r = r * base % Q;
        base = base * base % Q;
        e >>= 1;
      }
      return (int)r;
    };
    auto bitrev7 = [](int i) {
      int r = 0;
      for (int b = 0; b < 7; ++b)
        if (i & (1 << b)) r |= 1 << (6 - b);
      return r;
    };
    for (int i = 0; i < 128; ++i) ZETAS[i] = (int16_t)pw(17, bitrev7(i));
    for (int i = 0; i < 128; ++i) GAMMAS[i] = (int16_t)pw(17, 2 * bitrev7(i) + 1);
  }
} zeta_init;

void ntt(int16_t f[N]) {
  int kidx = 1;
  for (int len = 128; len >= 2; len >>= 1)
    for (int start = 0; start < N; start += 2 * len) {
      int z = ZETAS[kidx++];
      for (int j = start; j < start + len; ++j) {
        int t = (int)z * f[j + len] % Q;
        f[j + len] = (int16_t)((f[j] - t + Q) % Q);
        f[j] = (int16_t)((f[j] + t) % Q);
      }
    }
}

void ntt_inv(int16_t f[N]) {
  int kidx = 127;
  for (int len = 2; len <= 128; len <<= 1)
    for (int start = 0; start < N; start += 2 * len) {
      int z = ZETAS[kidx--];
      for (int j = start; j < start + len; ++j) {
        int t = f[j];
        f[j] = (int16_t)((t + f[j + len]) % Q);
        f[j + len] = (int16_t)((long)z * ((f[j + len] - t + Q) % Q) % Q);
      }
    }
  for (int j = 0; j < N; ++j) f[j] = (int16_t)((long)f[j] * 3303 % Q);
}

void basemul(const int16_t a[N], const int16_t b[N], int16_t out[N]) {
  for (int i = 0; i < 128; ++i) {
    int a0 = a[2 * i], a1 = a[2 * i + 1], b0 = b[2 * i], b1 = b[2 * i + 1];
    out[2 * i] = (int16_t)(((long)a0 * b0 + (long)a1 * b1 % Q * GAMMAS[i]) % Q);
    out[2 * i + 1] = (int16_t)(((long)a0 * b1 + (long)a1 * b0) % Q);
  }
}

void sample_ntt(const uint8_t seed[34], int16_t out[N]) {
  Sponge sp(168);
  sp.absorb(seed, 34);
  sp.finish(0x1f);
  int count = 0;
  uint8_t buf[168];
  while (count < N) {
    sp.squeeze(buf, 168);
    for (int i = 0; i + 3 <= 168 && count < N; i += 3) {
      int d1 = buf[i] | ((buf[i + 1] & 0x0f) << 8);
      int d2 = (buf[i + 1] >> 4) | (buf[i + 2] << 4);
      if (d1 < Q) out[count++] = (int16_t)d1;
      if (d2 < Q && count < N) out[count++] = (int16_t)d2;
    }
  }
}

void cbd(const uint8_t* buf, int eta, int16_t out[N]) {
  for (int i = 0; i < N; ++i) {
    int a = 0, b = 0;
    for (int j = 0; j < eta; ++j) {
      int bit = 2 * i * eta + j;
      a += (buf[bit >> 3] >> (bit & 7)) & 1;
      bit = (2 * i + 1) * eta + j;
      b += (buf[bit >> 3] >> (bit & 7)) & 1;
    }
    out[i] = (int16_t)((a - b + Q) % Q);
  }
}

void prf(const uint8_t seed[32], uint8_t n, int eta, uint8_t* out) {
  uint8_t in[33];
  std::memcpy(in, seed, 32);
  in[32] = n;
  shake(136, in, 33, out, 64 * eta);
}

void byte_encode(const int16_t* vals, int d, uint8_t* out) {
  std::memset(out, 0, 32 * d);
  int pos = 0;
  for (int i = 0; i < N; ++i)
    for (int j = 0; j < d; ++j, ++pos)
      out[pos >> 3] |= ((vals[i] >> j) & 1) << (pos & 7);
}

void byte_decode(const uint8_t* in, int d, int16_t* out) {
  int pos = 0;
  for (int i = 0; i < N; ++i) {
    int v = 0;
    for (int j = 0; j < d; ++j, ++pos) v |= ((in[pos >> 3] >> (pos & 7)) & 1) << j;
    out[i] = (int16_t)(d == 12 ? v % Q : v);
  }
}

int compress(int x, int d) { return (int)((((long)x << (d + 1)) + Q) / (2 * Q)) % (1 << d); }
int decompress(int y, int d) { return ((y * Q) + (1 << (d - 1))) >> d; }

struct KpkeKey {
  int16_t t_hat[4][N];
  int16_t s_hat[4][N];
  uint8_t rho[32];
};

void expand_a(const uint8_t rho[32], int k, int16_t a[4][4][N], bool transposed) {
  uint8_t seed[34];
  std::memcpy(seed, rho, 32);
  for (int i = 0; i < k; ++i)
    for (int j = 0; j < k; ++j) {
      seed[32] = (uint8_t)(transposed ? i : j);
      seed[33] = (uint8_t)(transposed ? j : i);
      sample_ntt(seed, a[i][j]);
    }
}

void kpke_keygen(const MLKEMParams& p, const uint8_t d[32], uint8_t* ek, uint8_t* dk) {
  uint8_t g_in[33], g_out[64];
  std::memcpy(g_in, d, 32);
  g_in[32] = (uint8_t)p.k;
  sha3(72, g_in, 33, g_out, 64);
  const uint8_t* rho = g_out;
  const uint8_t* sigma = g_out + 32;
  int16_t a[4][4][N];
  expand_a(rho, p.k, a, false);
  int16_t s[4][N], e[4][N];
  uint8_t buf[64 * 3];
  for (int i = 0; i < p.k; ++i) {
    prf(sigma, (uint8_t)i, p.eta1, buf);
    cbd(buf, p.eta1, s[i]);
    ntt(s[i]);
  }
  for (int i = 0; i < p.k; ++i) {
    prf(sigma, (uint8_t)(p.k + i), p.eta1, buf);
    cbd(buf, p.eta1, e[i]);
    ntt(e[i]);
  }
  for (int i = 0; i < p.k; ++i) {
    int16_t acc[N] = {0}, tmp[N];
    for (int j = 0; j < p.k; ++j) {
      basemul(a[i][j], s[j], tmp);
      for (int n = 0; n < N; ++n) acc[n] = (int16_t)((acc[n] + tmp[n]) % Q);
    }
    for (int n = 0; n < N; ++n) acc[n] = (int16_t)((acc[n] + e[i][n]) % Q);
    byte_encode(acc, 12, ek + 384 * i);
    byte_encode(s[i], 12, dk + 384 * i);
  }
  std::memcpy(ek + 384 * p.k, rho, 32);
}

void kpke_encrypt(const MLKEMParams& p, const uint8_t* ek, const uint8_t m[32],
                  const uint8_t r[32], uint8_t* ct) {
  int16_t t_hat[4][N];
  for (int i = 0; i < p.k; ++i) byte_decode(ek + 384 * i, 12, t_hat[i]);
  const uint8_t* rho = ek + 384 * p.k;
  int16_t at[4][4][N];
  expand_a(rho, p.k, at, true);
  int16_t y[4][N], e1[4][N], e2[N];
  uint8_t buf[64 * 3];
  for (int i = 0; i < p.k; ++i) {
    prf(r, (uint8_t)i, p.eta1, buf);
    cbd(buf, p.eta1, y[i]);
    ntt(y[i]);
  }
  for (int i = 0; i < p.k; ++i) {
    prf(r, (uint8_t)(p.k + i), p.eta2, buf);
    cbd(buf, p.eta2, e1[i]);
  }
  prf(r, (uint8_t)(2 * p.k), p.eta2, buf);
  cbd(buf, p.eta2, e2);
  // u = invNTT(A^T y) + e1
  for (int i = 0; i < p.k; ++i) {
    int16_t acc[N] = {0}, tmp[N];
    for (int j = 0; j < p.k; ++j) {
      basemul(at[i][j], y[j], tmp);
      for (int n = 0; n < N; ++n) acc[n] = (int16_t)((acc[n] + tmp[n]) % Q);
    }
    ntt_inv(acc);
    for (int n = 0; n < N; ++n) acc[n] = (int16_t)((acc[n] + e1[i][n]) % Q);
    int16_t cmp[N];
    for (int n = 0; n < N; ++n) cmp[n] = (int16_t)compress(acc[n], p.du);
    byte_encode(cmp, p.du, ct + 32 * p.du * i);
  }
  // v = invNTT(t^T y) + e2 + Decompress(mu)
  int16_t acc[N] = {0}, tmp[N];
  for (int j = 0; j < p.k; ++j) {
    basemul(t_hat[j], y[j], tmp);
    for (int n = 0; n < N; ++n) acc[n] = (int16_t)((acc[n] + tmp[n]) % Q);
  }
  ntt_inv(acc);
  int16_t mu[N];
  byte_decode(m, 1, mu);
  for (int n = 0; n < N; ++n)
    acc[n] = (int16_t)((acc[n] + e2[n] + decompress(mu[n], 1)) % Q);
  int16_t cmp[N];
  for (int n = 0; n < N; ++n) cmp[n] = (int16_t)compress(acc[n], p.dv);
  byte_encode(cmp, p.dv, ct + 32 * p.du * p.k);
}

void kpke_decrypt(const MLKEMParams& p, const uint8_t* dk, const uint8_t* ct,
                  uint8_t m[32]) {
  int16_t u[4][N], v[N];
  for (int i = 0; i < p.k; ++i) {
    int16_t cmp[N];
    byte_decode(ct + 32 * p.du * i, p.du, cmp);
    for (int n = 0; n < N; ++n) u[i][n] = (int16_t)decompress(cmp[n], p.du);
    ntt(u[i]);
  }
  int16_t cmpv[N];
  byte_decode(ct + 32 * p.du * p.k, p.dv, cmpv);
  for (int n = 0; n < N; ++n) v[n] = (int16_t)decompress(cmpv[n], p.dv);
  int16_t acc[N] = {0}, tmp[N], s_hat[N];
  for (int i = 0; i < p.k; ++i) {
    byte_decode(dk + 384 * i, 12, s_hat);
    basemul(s_hat, u[i], tmp);
    for (int n = 0; n < N; ++n) acc[n] = (int16_t)((acc[n] + tmp[n]) % Q);
  }
  ntt_inv(acc);
  int16_t w[N];
  for (int n = 0; n < N; ++n) w[n] = (int16_t)((v[n] - acc[n] + Q) % Q);
  int16_t bits[N];
  for (int n = 0; n < N; ++n) bits[n] = (int16_t)compress(w[n], 1);
  byte_encode(bits, 1, m);
}

}  // namespace

extern "C" {

// -------- hashes ------------------------------------------------------------

void qrp_shake128(const uint8_t* in, size_t inlen, uint8_t* out, size_t outlen) {
  shake(168, in, inlen, out, outlen);
}
void qrp_shake256(const uint8_t* in, size_t inlen, uint8_t* out, size_t outlen) {
  shake(136, in, inlen, out, outlen);
}
void qrp_sha3_256(const uint8_t* in, size_t inlen, uint8_t* out) {
  sha3(136, in, inlen, out, 32);
}
void qrp_sha3_512(const uint8_t* in, size_t inlen, uint8_t* out) {
  sha3(72, in, inlen, out, 64);
}

// -------- utilities ---------------------------------------------------------

void qrp_zeroize(uint8_t* buf, size_t len) {
  volatile uint8_t* p = buf;
  while (len--) *p++ = 0;
}

// -------- ML-KEM (FIPS 203 internal forms; k = 2/3/4) -----------------------

void qrp_mlkem_keygen(int k, const uint8_t d[32], const uint8_t z[32],
                      uint8_t* ek, uint8_t* dk) {
  MLKEMParams p = params_for(k);
  int eklen = 384 * k + 32;
  kpke_keygen(p, d, ek, dk);
  std::memcpy(dk + 384 * k, ek, eklen);
  sha3(136, ek, (size_t)eklen, dk + 384 * k + eklen, 32);
  std::memcpy(dk + 384 * k + eklen + 32, z, 32);
}

void qrp_mlkem_encaps(int k, const uint8_t* ek, const uint8_t m[32],
                      uint8_t* key, uint8_t* ct) {
  MLKEMParams p = params_for(k);
  int eklen = 384 * k + 32;
  uint8_t g_in[64], g_out[64];
  std::memcpy(g_in, m, 32);
  sha3(136, ek, (size_t)eklen, g_in + 32, 32);
  sha3(72, g_in, 64, g_out, 64);
  std::memcpy(key, g_out, 32);
  kpke_encrypt(p, ek, m, g_out + 32, ct);
}

void qrp_mlkem_decaps(int k, const uint8_t* dk, const uint8_t* ct, uint8_t* key) {
  MLKEMParams p = params_for(k);
  int eklen = 384 * k + 32;
  int ctlen = 32 * (p.du * p.k + p.dv);
  const uint8_t* dk_pke = dk;
  const uint8_t* ek = dk + 384 * k;
  const uint8_t* h = dk + 384 * k + eklen;
  const uint8_t* z = h + 32;
  uint8_t m2[32], g_in[64], g_out[64];
  kpke_decrypt(p, dk_pke, ct, m2);
  std::memcpy(g_in, m2, 32);
  std::memcpy(g_in + 32, h, 32);
  sha3(72, g_in, 64, g_out, 64);
  // key_bar = SHAKE256(z || ct, 32)
  uint8_t kb_in[32 + 32 * (11 * 4 + 5)];
  std::memcpy(kb_in, z, 32);
  std::memcpy(kb_in + 32, ct, (size_t)ctlen);
  uint8_t key_bar[32];
  shake(136, kb_in, (size_t)(32 + ctlen), key_bar, 32);
  uint8_t ct2[32 * (11 * 4 + 5)];
  kpke_encrypt(p, ek, m2, g_out + 32, ct2);
  // constant-time compare + select
  uint8_t diff = 0;
  for (int i = 0; i < ctlen; ++i) diff |= (uint8_t)(ct[i] ^ ct2[i]);
  uint8_t mask = (uint8_t)(((int)diff - 1) >> 8);  // 0xff iff diff == 0
  for (int i = 0; i < 32; ++i)
    key[i] = (uint8_t)((g_out[i] & mask) | (key_bar[i] & ~mask));
}

int qrp_version(void) { return 1; }

}  // extern "C"
