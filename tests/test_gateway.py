"""Gateway serving tier (ISSUE 8): admission control, priority lanes, and
the metrics-driven batch autotuner.

Covered here (acceptance criteria):

* the ``decide`` policy converges on a synthetic offered-load trace —
  deterministic, no wall-clock (injected clocks throughout);
* degraded plane (breaker-probe / fallback traffic) snaps the tuner to
  the floor bucket and the minimum window;
* priority-lane flush order: a flush that cannot carry everything takes
  rekeys first — a bulk flood defers bulk, never the rekey lane — and a
  bounded bulk lane SHEDS loudly instead of growing without bound;
* engine-level starvation bound: under a concurrent bulk flood, forced
  re-keys all complete promptly while bulk sends are shed;
* connection budget (P2PNode.max_peers) sheds inbound dials with a typed
  ``__busy__`` (fast + retryable), counted on both sides;
* responder handshake budget: over-budget ke_init draws a typed BUSY
  rejection the initiator retries; re-keys of established peers are
  exempt;
* ``QRP2P_AUTOTUNE=0`` (and the pre-first-step cold start) is bit-for-bit
  the static flush behavior;
* a seeded storm-lite chaos run (tools/swarm_bench.run_storm) with device
  kills + injected net delays: zero failed handshakes, reproducible
  injected-fault log, and the tuner observed degraded.
"""

import asyncio
import hashlib
import hmac
import os
import time

import pytest

from quantum_resistant_p2p_tpu.app import messaging as messaging_mod
from quantum_resistant_p2p_tpu.app.messaging import SecureMessaging
from quantum_resistant_p2p_tpu.faults import FaultRule
from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode
from quantum_resistant_p2p_tpu.obs import flight as obs_flight
from quantum_resistant_p2p_tpu.provider.autotune import (QueueTuner,
                                                         TunerConfig, decide)
from quantum_resistant_p2p_tpu.provider.base import (KeyExchangeAlgorithm,
                                                     SignatureAlgorithm,
                                                     SymmetricAlgorithm)
from quantum_resistant_p2p_tpu.provider.batched import (LANE_BULK,
                                                        LANE_HANDSHAKE,
                                                        LANE_REKEY,
                                                        LaneShed, OpQueue)
from quantum_resistant_p2p_tpu.provider.registry import (register_kem,
                                                         register_signature)

# -- stdlib toys (the scheduler/faults-suite pattern: real gateway stack,
# hash-toy crypto) ------------------------------------------------------------


def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    out = b""
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(key + nonce + ctr.to_bytes(8, "big")).digest()
        ctr += 1
    return out[:n]


class GwAEAD(SymmetricAlgorithm):
    name = "GW-AEAD"
    display_name = "GW-AEAD"
    key_size = 32
    nonce_size = 16

    def encrypt(self, key, plaintext, associated_data=None):
        nonce = os.urandom(self.nonce_size)
        ct = bytes(a ^ b for a, b in
                   zip(plaintext, _keystream(key, nonce, len(plaintext))))
        tag = hmac.new(key, nonce + ct + (associated_data or b""),
                       hashlib.sha256).digest()
        return nonce + ct + tag

    def decrypt(self, key, data, associated_data=None):
        if len(data) < self.nonce_size + 32:
            raise ValueError("ciphertext too short")
        nonce, ct, tag = (data[: self.nonce_size], data[self.nonce_size:-32],
                          data[-32:])
        want = hmac.new(key, nonce + ct + (associated_data or b""),
                        hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise ValueError("authentication failed")
        return bytes(a ^ b for a, b in zip(ct, _keystream(key, nonce, len(ct))))


class GwKEM(KeyExchangeAlgorithm):
    name = "GW-KEM"
    display_name = "GW-KEM"
    public_key_len = 32
    secret_key_len = 32
    ciphertext_len = 32
    shared_secret_len = 32

    def __init__(self, backend="cpu"):
        self.backend = backend

    def generate_keypair(self):
        sk = os.urandom(32)
        return hashlib.sha256(b"pk" + sk).digest(), sk

    def encapsulate(self, public_key):
        ct = os.urandom(32)
        return ct, hashlib.sha256(public_key + ct).digest()

    def decapsulate(self, secret_key, ciphertext):
        pk = hashlib.sha256(b"pk" + secret_key).digest()
        return hashlib.sha256(pk + ciphertext).digest()


class GwSig(SignatureAlgorithm):
    name = "GW-SIG"
    display_name = "GW-SIG"
    public_key_len = 32
    secret_key_len = 32
    signature_len = 32

    def __init__(self, backend="cpu"):
        self.backend = backend

    def generate_keypair(self):
        sk = os.urandom(32)
        return hashlib.sha256(b"pk" + sk).digest(), sk

    def sign(self, secret_key, message):
        pk = hashlib.sha256(b"pk" + secret_key).digest()
        return hashlib.sha256(b"sig" + pk + message).digest()

    def verify(self, public_key, message, signature):
        return hmac.compare_digest(
            signature, hashlib.sha256(b"sig" + public_key + message).digest()
        )


register_kem("GW-KEM", lambda backend, devices=0: GwKEM(backend),
             ("cpu", "tpu"))
register_signature("GW-SIG", lambda backend, devices=0: GwSig(backend),
                   ("cpu", "tpu"))


@pytest.fixture(autouse=True)
def fast_protocol(monkeypatch):
    monkeypatch.setattr(messaging_mod, "KEY_EXCHANGE_TIMEOUT", 3.0)
    monkeypatch.setattr(messaging_mod, "KE_RETRY_BACKOFF_S", 0.05)
    monkeypatch.setenv("QRP2P_HEALTH_GATE", "0")


# -- the decision policy (pure function, no wall-clock) -----------------------


def test_decide_converges_on_ramping_offered_load():
    """Synthetic offered-load trace: flushes grow from solo ops to ~100-op
    waves, dispatch cost grows with them.  The bucket jumps to the demand
    pow2 and the window tracks 2x the device p50 — and the whole decision
    sequence is a pure function of the trace (two runs are identical)."""
    cfg = TunerConfig()
    trace = [
        # (avg_batch, p50_device_s)
        (1.0, 0.0002),
        (3.0, 0.0005),
        (12.0, 0.001),
        (60.0, 0.004),
        (110.0, 0.006),
        (110.0, 0.006),
    ]

    def run():
        bucket, out = 1, []
        for avg, p50 in trace:
            bucket, window, _sat = decide(bucket, 1, avg, p50,
                                          p50 * 2, False, cfg)
            out.append((bucket, round(window, 6)))
        return out

    a, b = run(), run()
    assert a == b  # deterministic
    buckets = [x[0] for x in a]
    # demand-following: jumps straight to the pow2 covering each wave
    assert buckets == [1, 4, 16, 64, 128, 128]
    # windows track 2x p50, clamped at the configured bounds
    assert a[0][1] == cfg.min_window_s
    assert a[3][1] == pytest.approx(0.008)
    assert a[4][1] == pytest.approx(0.012)
    # and the steady state is stable
    assert a[-1] == a[-2]


def test_decide_shrinks_one_pow2_per_step_and_caps_window():
    cfg = TunerConfig()
    # demand collapsed from 128 to ~1: shrink is hysteretic (one pow2)
    bucket, _, _ = decide(128, 1, 1.0, 0.001, 0.002, False, cfg)
    assert bucket == 64
    # very slow device programs: the window still caps at the bound
    _, window, sat = decide(64, 1, 64.0, 0.2, 0.25, False, cfg)
    assert not sat
    assert window == min(cfg.max_window_s, cfg.latency_budget_s)


def test_decide_opens_window_under_host_saturation():
    """Loop-observed dispatch latency far above on-worker program time =
    the dispatch path is queueing (host-bound): the window opens to the
    cap so batches amortise per-flush overhead, instead of shattering the
    work into more of it."""
    cfg = TunerConfig()
    # keeping up: cheap device, no queueing gap -> responsive min window
    _, window, sat = decide(8, 1, 8.0, 0.0002, 0.0003, False, cfg)
    assert window == cfg.min_window_s and not sat
    # same device cost but a 50ms queueing gap -> saturated, open wide
    _, window, sat = decide(8, 1, 8.0, 0.0002, 0.050, False, cfg)
    assert sat
    assert window == min(cfg.max_window_s, cfg.latency_budget_s)


def test_decide_degraded_snaps_to_floor_and_min_window():
    cfg = TunerConfig()
    bucket, window, _sat = decide(64, 4, 64.0, 0.01, 0.02, True, cfg)
    assert bucket == 4  # the floor
    assert window == cfg.min_window_s


def test_queue_tuner_steps_from_injected_clock_only(run):
    """The stateful stepper consumes queue counters + an injected clock —
    no wall-clock reads — so a synthetic trace reproduces the exact
    decision sequence."""

    async def main():
        q = OpQueue(lambda items: [x + 1 for x in items], max_batch=64,
                    max_wait_ms=50.0, label="tuned.op")
        q._warm_buckets.update({1, 2, 4, 8, 16})
        now = [0.0]
        tuner = QueueTuner(q, TunerConfig(), clock=lambda: now[0])
        q.tuner = tuner
        assert tuner.flush_at() is None and tuner.wait_s() is None
        # cold start: the static path (flush at max_batch / static timer)
        assert q._flush_at() == 64
        assert q._wait_s() == pytest.approx(0.05)
        # drive 8-op waves; step the tuner on the synthetic clock
        for _ in range(5):
            await asyncio.gather(*(q.submit(i) for i in range(8)))
        now[0] = 1.0
        assert tuner.maybe_step()
        snap = tuner.snapshot()
        assert snap["bucket"] == 8  # demand pow2 of the 8-op waves
        if not snap["saturated"]:
            assert q._flush_at() == 16  # trigger = 2x bucket, keeping up
        assert snap["steps"] == 1 and not snap["degraded"]
        # decision state is reproducible: same counters + same clock value
        # -> same decision (idempotent because the cadence gate holds)
        assert not tuner.maybe_step()

    run(main())


def test_tuner_degraded_on_fallback_traffic_and_flight_event(run, monkeypatch):
    recorder = obs_flight.FlightRecorder(clock=lambda: 0.0, mono=lambda: 0.0)
    monkeypatch.setattr(obs_flight, "RECORDER", recorder)

    async def main():
        q = OpQueue(lambda items: [x + 1 for x in items], max_batch=8,
                    max_wait_ms=1.0,
                    fallback_fn=lambda items: [x + 1 for x in items],
                    label="degraded.op")
        q._warm_buckets.update({1, 2, 4, 8})
        now = [0.0]
        tuner = QueueTuner(q, TunerConfig(), clock=lambda: now[0])
        q.tuner = tuner
        await asyncio.gather(*(q.submit(i) for i in range(8)))
        now[0] = 1.0
        tuner.step()
        assert not tuner.snapshot()["degraded"]
        # breaker opens: the plane is degraded -> floor bucket, min window
        q.breaker.trip()
        now[0] = 2.0
        tuner.step()
        snap = tuner.snapshot()
        assert snap["degraded"]
        assert snap["bucket"] == 1
        assert snap["window_ms"] == pytest.approx(
            TunerConfig().min_window_s * 1e3)

    run(main())
    kinds = [e["kind"] for e in recorder.snapshot()]
    assert "tuner_step" in kinds


# -- priority lanes at the queue ----------------------------------------------


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


def test_lane_priority_flush_order(run):
    """An over-full queue drains rekeys first, then handshakes, then bulk
    — and the flush lane attr reports the highest-priority lane aboard."""
    batches: list[list[int]] = []

    async def main():
        q = OpQueue(lambda items: (batches.append(list(items)),
                                   [x for x in items])[1],
                    max_batch=4, max_wait_ms=500.0, label="lanes.op")
        q._warm_buckets.update({1, 2, 4})
        # hold the full-batch trigger open while enqueuing, so all six ops
        # are pending when the over-full drain runs
        q.max_batch = 100
        futs = [asyncio.ensure_future(q.submit(i, LANE_BULK))
                for i in range(3)]
        futs += [asyncio.ensure_future(q.submit(10 + i, LANE_REKEY))
                 for i in range(2)]
        futs += [asyncio.ensure_future(q.submit(20, LANE_HANDSHAKE))]
        await asyncio.sleep(0)  # let every submit enqueue (6 pending > 4)
        q.max_batch = 4
        q._flush_local()
        await asyncio.gather(*futs)

    run(main())
    # first flush: both rekeys, the handshake, then the OLDEST bulk;
    # second flush: the deferred bulk remainder in arrival order
    assert batches[0] == [10, 11, 20, 0]
    assert batches[1] == [1, 2]


def test_single_lane_drain_is_insertion_order(run):
    """Single-lane traffic (every pre-gateway caller) drains exactly as
    the old insertion-order slice — the bit-for-bit contract."""
    batches: list[list[int]] = []

    async def main():
        q = OpQueue(lambda items: (batches.append(list(items)),
                                   list(items))[1],
                    max_batch=3, max_wait_ms=500.0, label="plain.op")
        q._warm_buckets.update({1, 2, 4})
        futs = [asyncio.ensure_future(q.submit(i)) for i in range(7)]
        await asyncio.sleep(0)
        q._flush_local()
        await asyncio.gather(*futs)

    run(main())
    assert batches == [[0, 1, 2], [3, 4, 5], [6]]


def test_bulk_lane_capacity_sheds_loudly(run, monkeypatch):
    recorder = obs_flight.FlightRecorder(clock=lambda: 0.0, mono=lambda: 0.0)
    monkeypatch.setattr(obs_flight, "RECORDER", recorder)

    async def main():
        q = OpQueue(lambda items: list(items), max_batch=64,
                    max_wait_ms=500.0, label="shed.op",
                    lane_capacity={LANE_BULK: 2})
        futs = [asyncio.ensure_future(q.submit(i, LANE_BULK))
                for i in range(2)]
        await asyncio.sleep(0)
        with pytest.raises(LaneShed):
            await q.submit(99, LANE_BULK)
        # rekey lane is NOT bounded by the bulk cap
        futs.append(asyncio.ensure_future(q.submit(7, LANE_REKEY)))
        await asyncio.sleep(0)
        q._flush_local()
        await asyncio.gather(*futs)
        assert q.stats.lane_sheds == {LANE_BULK: 1}
        assert q.stats.as_dict()["lane_sheds"] == {"bulk": 1}

    run(main())
    sheds = [e for e in recorder.snapshot() if e["kind"] == "load_shed"]
    assert sheds and sheds[0]["where"] == "lane" and sheds[0]["lane"] == "bulk"


# -- engine-level: starvation bound + admission control -----------------------


async def _pair(**kwargs):
    from quantum_resistant_p2p_tpu.provider import get_kem, get_signature

    a_node = P2PNode(node_id="alice", host="127.0.0.1", port=0)
    b_node = P2PNode(node_id="bob", host="127.0.0.1", port=0)
    await a_node.start()
    await b_node.start()
    kw = dict(kem=get_kem("GW-KEM", "tpu"),
              signature=get_signature("GW-SIG", "tpu"),
              use_batching=True, max_batch=64, max_wait_ms=1.0)
    kw.update(kwargs)
    a = SecureMessaging(a_node, symmetric=GwAEAD(), **kw)
    b = SecureMessaging(b_node, symmetric=GwAEAD(), **kw)
    assert await a_node.connect_to_peer("127.0.0.1", b_node.port) == "bob"
    for _ in range(100):
        if b_node.is_connected("alice"):
            break
        await asyncio.sleep(0.01)
    return a, b


def test_rekey_lane_bounded_under_bulk_flood(run):
    """Bulk flood + forced re-keys: every re-key completes promptly (the
    rekey lane jumps the queue), bulk beyond the lane bound is SHED (loud,
    counted), and the handshake ops were classified onto the rekey lane."""

    async def main():
        # static flush policy (autotune off): the 50 ms window holds the
        # queue pending long enough that the lane bound deterministically
        # binds — the tuner would drain it faster and mask the shed
        a, b = await _pair(autotune=False, bulk_lane_capacity=4,
                           max_wait_ms=50.0)
        assert await a.initiate_key_exchange("bob")

        # flood the sign queue's BULK lane directly: 48 concurrent bulk
        # ops against a capacity of 4 — the excess sheds at submit
        async def bulk_op(i):
            try:
                await a._sign(b"bulk %d" % i, LANE_BULK)
                return True
            except LaneShed:
                return False

        flood = [asyncio.ensure_future(bulk_op(i)) for i in range(48)]
        rekey_lat = []
        for _ in range(4):
            a.shared_keys.pop("bob", None)
            a.ke_state["bob"] = messaging_mod.KeyExchangeState.NONE
            t0 = time.perf_counter()
            assert await a.initiate_key_exchange("bob")
            rekey_lat.append(time.perf_counter() - t0)
        # and the end-to-end bulk path: concurrent sends over the live
        # session shed at the same bound, through send_message
        flood2 = [asyncio.ensure_future(a.send_message("bob", b"x" * 64))
                  for _ in range(24)]
        sent = [m for m in await asyncio.gather(*flood2) if m is not None]
        flood_ok = await asyncio.gather(*flood)
        ma = a.metrics()
        await a.node.stop()
        await b.node.stop()
        return rekey_lat, sent, flood_ok, ma

    rekey_lat, sent, flood_ok, ma = run(main())
    # every rekey beat the protocol timeout comfortably despite the flood
    assert max(rekey_lat) < messaging_mod.KEY_EXCHANGE_TIMEOUT
    # the direct flood was shed at the bulk bound (some served, most shed)
    assert any(flood_ok) and flood_ok.count(False) > 0
    assert ma["sig_queue"]["sign"]["lane_sheds"].get("bulk", 0) > 0
    # the send_message path counts its sheds on the gateway counter
    assert ma["gateway"]["bulk_sheds"] > 0
    assert len(sent) < 24
    # the rekey handshakes actually rode the REKEY lane
    lanes = ma["sig_queue"]["sign"]["lanes"]
    assert lanes.get("rekey", 0) > 0 and lanes.get("bulk", 0) > 0


def test_connection_budget_sheds_inbound_dials(run, monkeypatch):
    recorder = obs_flight.FlightRecorder(clock=lambda: 0.0, mono=lambda: 0.0)
    monkeypatch.setattr(obs_flight, "RECORDER", recorder)

    async def main():
        hub = P2PNode(node_id="hub", host="127.0.0.1", port=0, max_peers=2)
        await hub.start()
        dialers = [P2PNode(node_id=f"d{i}", host="127.0.0.1", port=0)
                   for i in range(4)]
        got = []
        for d in dialers:
            got.append(await d.connect_to_peer("127.0.0.1", hub.port,
                                               retries=0))
        ok = [g for g in got if g == "hub"]
        shed = [g for g in got if g is None]
        busy = sum(d.busy_rejects for d in dialers)
        sheds = hub.sheds
        for d in dialers:
            await d.stop()
        await hub.stop()
        return ok, shed, busy, sheds

    ok, shed, busy, sheds = run(main())
    assert len(ok) == 2 and len(shed) == 2  # budget respected exactly
    assert sheds == 2 and busy == 2         # both sides counted it
    events = [e for e in recorder.snapshot() if e["kind"] == "load_shed"]
    assert events and events[0]["where"] == "connection"


def test_handshake_budget_busy_reject_retry_and_rekey_exemption(run):
    async def main():
        a, b = await _pair(max_inflight_handshakes=1)
        # jam the responder's budget: a fresh peer's init draws BUSY
        b._responding = 1
        ok = await a.initiate_key_exchange("bob", retries=1)
        assert not ok
        sheds_while_jammed = b._ctr_handshake_sheds.value
        # budget drains -> the same initiator succeeds on a fresh attempt
        b._responding = 0
        assert await a.initiate_key_exchange("bob")
        # established peers RE-KEY through a jammed budget (exemption)
        b._responding = 1
        a.shared_keys.pop("bob", None)
        a.ke_state["bob"] = messaging_mod.KeyExchangeState.NONE
        rekey_ok = await a.initiate_key_exchange("bob")
        mb = b.metrics()
        await a.node.stop()
        await b.node.stop()
        return sheds_while_jammed, rekey_ok, mb

    sheds, rekey_ok, mb = run(main())
    assert sheds >= 2  # the first attempt AND its retry were shed, counted
    assert rekey_ok    # the rekey exemption held
    assert mb["gateway"]["handshake_sheds"] == sheds


# -- tuner-off is bit-for-bit static ------------------------------------------


def test_autotune_env_off_attaches_no_tuner(run, monkeypatch):
    monkeypatch.setenv("QRP2P_AUTOTUNE", "0")

    async def main():
        a, b = await _pair()  # autotune=None -> env default -> OFF
        assert a._autotuner is None
        for q in (a._bkem._kg, a._bkem._enc, a._bkem._dec,
                  a._bsig._sign, a._bsig._verify):
            assert q.tuner is None
        assert await a.initiate_key_exchange("bob")
        assert a.metrics()["gateway"]["autotune"] == {"enabled": False}
        await a.node.stop()
        await b.node.stop()

    run(main())


def test_tuner_cold_start_is_bit_for_bit_static(run):
    """Identical submission schedules through a static queue and a tuner-
    attached queue BEFORE its first step produce identical flush-size
    sequences AND identical timer windows — the static prior is literal."""

    async def drive(q):
        sizes = []
        orig = q._take_batch

        def spy():
            items, futs, lane = orig()
            sizes.append(len(items))
            return items, futs, lane

        q._take_batch = spy
        for wave in (3, 1, 5, 2):
            await asyncio.gather(*(q.submit(i) for i in range(wave)))
        return sizes, q._wait_s(), q._flush_at()

    async def main():
        def bf(items):
            return list(items)

        static = OpQueue(bf, max_batch=4, max_wait_ms=2.0, label="s.op")
        static._warm_buckets.update({1, 2, 4})
        tuned = OpQueue(bf, max_batch=4, max_wait_ms=2.0, label="t.op")
        tuned._warm_buckets.update({1, 2, 4})
        tuner = QueueTuner(tuned, TunerConfig(), clock=lambda: 0.0)
        tuned.tuner = tuner  # attached but never stepped (cold start)
        s = await drive(static)
        t = await drive(tuned)
        assert s == t
        assert tuner.snapshot()["steps"] == 0

    run(main())


# -- obs surface --------------------------------------------------------------


def test_autotune_gauges_exported_with_queue_labels(run):
    async def main():
        a, b = await _pair(autotune=True)
        assert await a.initiate_key_exchange("bob")
        prom = a.registry.to_prometheus()
        assert "qrp2p_autotune_chosen_bucket" in prom
        assert "qrp2p_autotune_flush_window_ms" in prom
        assert 'queue="GW-KEM.kg"' in prom
        snap = a.metrics()["gateway"]["autotune"]
        assert snap["enabled"] and "GW-SIG.sign" in snap["queues"]
        await a.node.stop()
        await b.node.stop()

    run(main())


def test_queue_flush_spans_carry_lane_attr(run, monkeypatch):
    from quantum_resistant_p2p_tpu.obs import trace as obs_trace

    async def main():
        obs_trace.TRACER.reset()
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        await a.send_message("bob", b"bulk ride")
        spans = obs_trace.TRACER.snapshot()
        await a.node.stop()
        await b.node.stop()
        return spans

    spans = run(main())
    lanes = {s["attrs"].get("lane") for s in spans
             if s["name"] == "queue.flush"}
    assert "handshake" in lanes and "bulk" in lanes


# -- storm-lite chaos (seeded, reproducible) ----------------------------------


def _storm_lite(seed: int):
    from tools.swarm_bench import run_storm

    rules = [
        # kill a few device dispatches mid-storm (no scheduler: the single
        # plane's breaker opens, the tuner must observe degraded traffic)
        FaultRule("device.dispatch", "raise", match={"op": "STORM-SIG"},
                  nth=8, times=2),
        # and inject net delays on the hub's wire
        FaultRule("net.send", "delay", match={"msg_type": "ke_response"},
                  nth=3, times=4, delay_s=0.02),
    ]
    # 4 messages x rekey-every-1 per session: the storm must SPAN the
    # autotuner's 250 ms decision cadence — a fast host finishes a
    # 1-message storm before any tuner window fills, and the degraded-
    # plane assertion below then has no decision to observe (flaked
    # order-dependently on fast hosts)
    return asyncio.run(run_storm(
        24, concurrency=24, msgs_per_session=4, rekey_every=1,
        churn_fraction=0.0, seed=seed, max_wait_ms=1.0, autotune=True,
        handshake_budget=16, ke_timeout=10.0, fault_rules=rules,
    ))


def test_storm_lite_chaos_zero_failures_and_reproducible(monkeypatch):
    monkeypatch.setenv("QRP2P_HEALTH_GATE", "0")
    monkeypatch.setattr(messaging_mod, "KE_RETRY_BACKOFF_S", 0.05)
    s1 = _storm_lite(31337)
    s2 = _storm_lite(31337)
    # chaos shed nothing it shouldn't: every handshake completed (the
    # breaker + retry machinery absorbed the kills; admission never let a
    # timeout through)
    assert s1["failures"] == 0 and s2["failures"] == 0
    # seeded reproducibility: the same rules fired, in full, both runs
    assert s1["chaos"]["injected"] == s2["chaos"]["injected"]
    assert ([ (e["scope"], e["action"]) for e in s1["chaos"]["first_injected"] ]
            == [ (e["scope"], e["action"]) for e in s2["chaos"]["first_injected"] ])
    assert s1["chaos"]["injected"] >= 2
    # the tuner saw the degraded plane (device kills -> fallback traffic):
    # at least one queue stepped while degraded or ended at the floor
    # minimum window
    tuners = {**s1["autotune_hub"]["queues"], **s1["autotune_clients"]["queues"]}
    assert any(t["degraded"] or (t["window_ms"] is not None
                                 and t["window_ms"] <= 0.5)
               for t in tuners.values()), tuners
