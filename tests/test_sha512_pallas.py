"""Bit-exactness of the Pallas SHA-512 compression kernel body.

Same strategy as tests/test_sha256_pallas.py: the kernel body is a pure
tile-list function run eagerly here; the native pallas_call is exercised
on the chip by the SPHINCS+ 192/256 sections of tools/full_bench.py.
"""

import hashlib

import jax.numpy as jnp
import numpy as np

from quantum_resistant_p2p_tpu.core import sha512, sha512_pallas


def _rand_state_block(seed, b):
    rng = np.random.default_rng(seed)
    sh = jnp.asarray(rng.integers(0, 2**32, (b, 8), dtype=np.uint32))
    sl = jnp.asarray(rng.integers(0, 2**32, (b, 8), dtype=np.uint32))
    block = jnp.asarray(rng.integers(0, 256, (b, 128), dtype=np.uint8))
    return sh, sl, block


def test_compress_tiles_bit_exact_vs_jnp(monkeypatch):
    monkeypatch.setenv("QRP2P_PALLAS", "0")  # reference = jnp compress
    sh, sl, block = _rand_state_block(6, 64)
    rh, rl = sha512.compress((sh, sl), block)
    bh, bl = sha512._block_words(block)
    words = [(sh.T[i], sl.T[i]) for i in range(8)] + [
        (bh.T[i], bl.T[i]) for i in range(16)
    ]
    out = sha512_pallas._compress_tiles(words)
    got_h = np.stack([np.asarray(o[0]) for o in out], axis=-1)
    got_l = np.stack([np.asarray(o[1]) for o in out], axis=-1)
    assert np.array_equal(got_h, np.asarray(rh))
    assert np.array_equal(got_l, np.asarray(rl))


def test_compress_kernel_split_semantics(monkeypatch):
    # Exercises _compress_kernel's 24/24 transport split, ref indexing, and
    # the int32 output cast with numpy arrays standing in for VMEM refs
    # (interpret mode unusable — see tests/test_sha256_pallas.py).
    monkeypatch.setenv("QRP2P_PALLAS", "0")
    TS, TL = 8, 128
    sh, sl, block = _rand_state_block(8, TS * TL)
    rh, rl = sha512.compress((sh, sl), block)
    bh, bl = sha512._block_words(block)
    in_hi = jnp.concatenate([sh.T, sl.T, bh.T[:8]], axis=0).reshape(24, TS, TL)
    in_lo = jnp.concatenate([bh.T[8:], bl.T], axis=0).reshape(24, TS, TL)
    out_ref = np.zeros((16, TS, TL), np.int32)
    sha512_pallas._compress_kernel(np.asarray(in_hi), np.asarray(in_lo), out_ref)
    got_h = out_ref[:8].reshape(8, TS * TL).T.astype(np.uint32)
    got_l = out_ref[8:].reshape(8, TS * TL).T.astype(np.uint32)
    assert np.array_equal(got_h, np.asarray(rh))
    assert np.array_equal(got_l, np.asarray(rl))


def test_compress_gate_routes_through_kernel(monkeypatch):
    # The production compress() gate: flat batch >= _PALLAS_MIN_BATCH with
    # the pallas flag on must produce identical state updates through the
    # transpose/reshape round-trip.
    sh, sl, block = _rand_state_block(9, 300)
    monkeypatch.setenv("QRP2P_PALLAS", "0")
    rh, rl = (np.asarray(x) for x in sha512.compress((sh, sl), block))
    monkeypatch.setenv("QRP2P_PALLAS", "1")

    def tile_compress_words(swh, swl, bwh, bwl):
        out = sha512_pallas._compress_tiles(
            [(swh[i], swl[i]) for i in range(8)]
            + [(bwh[i], bwl[i]) for i in range(16)]
        )
        return jnp.stack([o[0] for o in out]), jnp.stack([o[1] for o in out])

    monkeypatch.setattr(sha512_pallas, "compress_words", tile_compress_words)
    gh, gl = (np.asarray(x) for x in sha512.compress((sh, sl), block))
    assert np.array_equal(gh, rh)
    assert np.array_equal(gl, rl)


def test_full_digest_still_hashlib_anchored():
    rng = np.random.default_rng(7)
    msg = rng.integers(0, 256, (5, 211), dtype=np.uint8)
    d = np.asarray(sha512.sha512(jnp.asarray(msg)))
    for i in range(5):
        assert bytes(d[i]) == hashlib.sha512(msg[i].tobytes()).digest()
