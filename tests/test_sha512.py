"""SHA-512 (uint32-pair emulated) vs hashlib oracle."""

import hashlib

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.core import sha512 as jsha


@pytest.mark.parametrize("length", [0, 1, 54, 111, 112, 127, 128, 129, 300])
def test_sha512_matches_hashlib(length):
    rng = np.random.default_rng(length)
    data = rng.integers(0, 256, size=(3, length), dtype=np.uint8)
    out = np.asarray(jsha.sha512(data))
    for i in range(3):
        assert bytes(out[i]) == hashlib.sha512(data[i].tobytes()).digest()


def test_midstate_equals_full_hash():
    rng = np.random.default_rng(9)
    prefix = rng.integers(0, 256, size=(2, 128), dtype=np.uint8)
    tail = rng.integers(0, 256, size=(2, 22 + 48), dtype=np.uint8)
    st = jsha.midstate(prefix)
    out = np.asarray(jsha.sha512_from_midstate(st, tail, prefix_blocks=1))
    for i in range(2):
        assert bytes(out[i]) == hashlib.sha512(prefix[i].tobytes() + tail[i].tobytes()).digest()
