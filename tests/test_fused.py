"""Fused composite handshake programs: bit-exactness against the separate-op
path, transcript-offset probing, the device operand cache, and the
double-buffered slicer.

The wire-compatibility claim (fused and unfused stacks are indistinguishable
to a peer) reduces to: under the same injected seeds, the fused programs'
outputs are byte-identical to the separate-op providers'.  Small batches run
in tier-1 on the cpu platform; the batch-256 shape rides nightly (`slow`).
"""

import json

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.fused import mlkem_mldsa as fused_ops
from quantum_resistant_p2p_tpu.kem import mlkem as jax_mlkem
from quantum_resistant_p2p_tpu.provider.base import sliced_dispatch
from quantum_resistant_p2p_tpu.provider.fused_providers import (
    FusedMLKEMMLDSA, init_pk_offset, resp_ct_offset)
from quantum_resistant_p2p_tpu.provider.kem_providers import MLKEMKeyExchange
from quantum_resistant_p2p_tpu.provider.opcache import DeviceOperandCache
from quantum_resistant_p2p_tpu.provider.sig_providers import MLDSASignature

KEM_NAME, SIG_NAME, LEVEL = "ML-KEM-512", "ML-DSA-44", 1
AEAD = "AES-256-GCM"


@pytest.fixture(scope="module")
def pair():
    kem = MLKEMKeyExchange(security_level=LEVEL, backend="tpu")
    sig = MLDSASignature(security_level=2, backend="tpu")
    return kem, sig, FusedMLKEMMLDSA(kem, sig)


def _init_template(kem) -> bytes:
    d = {"aead": AEAD, "kem": kem.name, "message_id": "x" * 36,
         "public_key": "0" * (2 * kem.public_key_len),
         "recipient": "bob", "sender": "alice", "timestamp": 1234.5}
    return json.dumps(d, sort_keys=True, separators=(",", ":")).encode()


def _resp_template(kem) -> bytes:
    d = {"ciphertext": "0" * (2 * kem.ciphertext_len), "message_id": "x" * 36,
         "recipient": "alice", "sender": "bob", "timestamp": 1234.5}
    return json.dumps(d, sort_keys=True, separators=(",", ":")).encode()


def test_offsets_match_canonical_json_layout():
    """The probed offsets point exactly at the hex payload gap."""
    kem = MLKEMKeyExchange(security_level=LEVEL, backend="cpu")
    t = _init_template(kem)
    off = init_pk_offset(kem.name, AEAD)
    assert t[off: off + 2 * kem.public_key_len] == b"0" * (2 * kem.public_key_len)
    assert t[off - len('"public_key":"'): off] == b'"public_key":"'
    r = _resp_template(kem)
    coff = resp_ct_offset()
    assert r[coff: coff + 2 * kem.ciphertext_len] == b"0" * (2 * kem.ciphertext_len)


def test_encode_hex_matches_bytes_hex():
    data = np.frombuffer(bytes(range(256)), np.uint8)
    out = np.asarray(fused_ops.encode_hex(data))
    assert bytes(out) == bytes(range(256)).hex().encode()


def _roundtrip(pair, n):
    """Drive all three composite programs and cross-check every output
    against the separate-op providers under the same injected seeds."""
    kem, sig, fused = pair
    pk_off, ct_off = init_pk_offset(kem.name, AEAD), resp_ct_offset()
    spk, ssk = sig.generate_keypair()
    sks = np.stack([np.frombuffer(ssk, np.uint8)] * n)
    spks = np.stack([np.frombuffer(spk, np.uint8)] * n)
    rnd = [bytes([i] * 32) for i in range(n)]
    m = [bytes([0x40 | i] * 32) for i in range(n)]

    # -- ke_init: keygen + sign --------------------------------------------
    tmpl = _init_template(kem)
    eks, dks, sigs = fused.keygen_sign_batch(sks, [tmpl] * n, pk_off, rnd=rnd)
    rendered = [
        tmpl[:pk_off] + bytes(ek).hex().encode()
        + tmpl[pk_off + 2 * kem.public_key_len:]
        for ek in eks
    ]
    # byte-identical to the per-op signature over the rendered transcript
    per_op = sig.sign_batch(sks, rendered, rnd=rnd)
    assert [bytes(s) for s in per_op] == [bytes(s) for s in sigs]
    assert sig.verify(spk, rendered[0], sigs[0])

    # -- ke_response: verify + encaps + sign -------------------------------
    rtmpl = _resp_template(kem)
    oks, cts, sss, rsigs = fused.encaps_verify_sign_batch(
        eks, spks, rendered, sigs, sks, [rtmpl] * n, ct_off, m=m, rnd=rnd)
    assert oks.all()
    # encaps bit-exact vs the separate-op jitted program with the same m
    keys2, cts2 = jax_mlkem.get(kem.params.name)[1](
        np.asarray(eks), np.stack([np.frombuffer(x, np.uint8) for x in m]))
    assert (np.asarray(cts) == np.asarray(cts2)).all()
    assert (np.asarray(sss) == np.asarray(keys2)).all()
    rrend = [
        rtmpl[:ct_off] + bytes(ct).hex().encode()
        + rtmpl[ct_off + 2 * kem.ciphertext_len:]
        for ct in cts
    ]
    assert sig.verify(spk, rrend[0], rsigs[0])

    # -- ke_confirm: verify + decaps + sign --------------------------------
    confirm = b'{"message_id":"y","recipient":"b","sender":"a","timestamp":2}'
    oks2, sss2, csigs = fused.decaps_verify_sign_batch(
        dks, np.asarray(cts), spks, rrend, rsigs, sks, [confirm] * n, rnd=rnd)
    assert oks2.all()
    assert (np.asarray(sss2) == np.asarray(sss)).all()  # decaps inverts encaps
    per_op_ss = kem.decapsulate_batch(np.asarray(dks), np.asarray(cts))
    assert (np.asarray(sss2) == np.asarray(per_op_ss)).all()
    assert sig.verify(spk, confirm, csigs[0])

    # -- negative: tampered inputs fail closed ------------------------------
    bad_sig = bytes([sigs[0][0] ^ 1]) + bytes(sigs[0][1:])
    oks3, _, _, _ = fused.encaps_verify_sign_batch(
        eks, spks, rendered, [bad_sig] * n, sks, [rtmpl] * n, ct_off,
        m=m, rnd=rnd)
    assert not oks3.any()
    bad_ct = np.array(cts, copy=True)
    bad_ct[:, 0] ^= 1
    _, sss4, _ = fused.decaps_verify_sign_batch(
        dks, bad_ct, spks, rrend, rsigs, sks, [confirm] * n, rnd=rnd)
    # implicit rejection: wrong ct yields a DIFFERENT (pseudorandom) secret
    assert not (np.asarray(sss4) == np.asarray(sss)).any(axis=1).all()


def test_fused_bit_exact_vs_separate_ops_small(pair):
    _roundtrip(pair, 2)


@pytest.mark.slow
def test_fused_bit_exact_vs_separate_ops_batch256(pair):
    """Acceptance shape: composite == separate at batch >= 256."""
    _roundtrip(pair, 256)


# ---------------------------------------------------------------- opcache


def test_opcache_lru_and_stats():
    c = DeviceOperandCache(capacity=2)
    assert c.lookup("k", b"a") is None
    c.put("k", b"a", 1)
    c.put("k", b"b", 2)
    assert c.lookup("k", b"a") == 1     # refreshes 'a'
    c.put("k", b"c", 3)                 # evicts 'b' (LRU)
    assert c.lookup("k", b"b") is None
    assert c.lookup("k", b"a") == 1 and c.lookup("k", b"c") == 3
    assert len(c) == 2
    st = c.stats()
    assert st["hits"] == 3 and st["misses"] == 2 and st["evictions"] == 1
    # kind partitions the key space: same key bytes, different entry —
    # inserting it evicts the LRU entry ("k", a), not ("k", c)
    c.put("other", b"a", 9)
    assert c.lookup("other", b"a") == 9 and c.lookup("k", b"c") == 3
    assert c.lookup("k", b"a") is None


def test_mlkem_encaps_opcache_hit_is_bit_identical():
    """Cold (cache-filling) and warm (precomputed-operand) encaps programs
    produce identical ct/ss for the same key and message randomness."""
    kem = MLKEMKeyExchange(security_level=LEVEL, backend="tpu", opcache_size=4)
    assert kem.opcache is not None
    pk, sk = kem.generate_keypair()
    pks = np.stack([np.frombuffer(pk, np.uint8)] * 2)

    np.random.seed(7)
    import os
    real_urandom = os.urandom
    try:
        os.urandom = lambda n: b"\x05" * n  # pin the encaps message
        cts_cold, sss_cold = kem.encapsulate_batch(pks)   # miss: fills cache
        assert kem.opcache.stats()["misses"] >= 1
        cts_warm, sss_warm = kem.encapsulate_batch(pks)   # hit: pre path
        assert kem.opcache.stats()["hits"] >= 1
    finally:
        os.urandom = real_urandom
    assert (np.asarray(cts_cold) == np.asarray(cts_warm)).all()
    assert (np.asarray(sss_cold) == np.asarray(sss_warm)).all()
    # and the outputs decapsulate correctly through the normal path
    ss = kem.decapsulate_batch(
        np.stack([np.frombuffer(sk, np.uint8)] * 2), np.asarray(cts_cold))
    assert (np.asarray(ss) == np.asarray(sss_cold)).all()


def test_mldsa_sign_verify_opcache_hit_is_bit_identical():
    sig = MLDSASignature(security_level=2, backend="tpu", opcache_size=4)
    assert sig.opcache is not None
    pk, sk = sig.generate_keypair()
    sks = np.stack([np.frombuffer(sk, np.uint8)] * 2)
    msgs = [b"alpha", b"beta"]
    rnd = [b"\x01" * 32, b"\x02" * 32]
    s_cold = sig.sign_batch(sks, msgs, rnd=rnd)     # miss: fills "sk" cache
    s_warm = sig.sign_batch(sks, msgs, rnd=rnd)     # hit: precomputed path
    assert [bytes(s) for s in s_cold] == [bytes(s) for s in s_warm]
    pks = np.stack([np.frombuffer(pk, np.uint8)] * 2)
    ok_cold = sig.verify_batch(pks, msgs, s_cold)   # miss: fills "pk" cache
    ok_warm = sig.verify_batch(pks, msgs, s_warm)   # hit
    assert ok_cold.all() and ok_warm.all()
    st = sig.opcache.stats()
    assert st["hits"] >= 2 and st["misses"] >= 2
    # tampered signature still rejects through the cached-verify path
    bad = [bytes([s_cold[0][0] ^ 1]) + bytes(s_cold[0][1:]), bytes(s_cold[1])]
    oks = sig.verify_batch(pks, msgs, bad)
    assert not oks[0] and oks[1]


def test_mixed_key_batch_bypasses_opcache():
    """The single-key fast path must not fire for mixed-key batches."""
    kem = MLKEMKeyExchange(security_level=LEVEL, backend="tpu", opcache_size=4)
    pk1, _ = kem.generate_keypair()
    pk2, _ = kem.generate_keypair()
    pks = np.stack([np.frombuffer(pk1, np.uint8), np.frombuffer(pk2, np.uint8)])
    before = dict(kem.opcache.stats())
    cts, sss = kem.encapsulate_batch(pks)
    after = kem.opcache.stats()
    assert after["hits"] == before["hits"] and after["misses"] == before["misses"]
    assert np.asarray(cts).shape[0] == 2 and np.asarray(sss).shape[0] == 2


# ---------------------------------------------------- double-buffered slicer


def test_sliced_dispatch_double_buffered_matches_naive():
    """Pipelined slicing returns exactly what per-slice application would."""
    calls = []

    def fn(a, b):
        calls.append(a.shape[0])
        return a * 2, a + b

    a = np.arange(10, dtype=np.int64).reshape(10, 1)
    b = np.ones((10, 1), dtype=np.int64)
    x, y = sliced_dispatch(fn, 4, a, b)
    assert calls == [4, 4, 4]  # padded full slices
    assert x.shape == (10, 1) and (x == a * 2).all() and (y == a + 1).all()

    # single-output fn, exact multiple of step
    out = sliced_dispatch(lambda v: v - 1, 5, np.arange(10).reshape(10, 1))
    assert (out == np.arange(10).reshape(10, 1) - 1).all()


# ------------------------------------------------------------- donation safety


def test_donated_sig_in_reuse_raises_in_twin(pair):
    """Operand reuse after a donating fused call must raise in the test twin.

    The verify+sign programs donate argnum 4 (the peer's verified signature,
    whose buffer is reused for the response sigma).  On TPU a later read of
    the donated buffer raises; on CPU donation is a silent no-op, so
    ``donation_twin`` restores the TPU semantics by deleting the donated
    jax.Array after the call — a call site that still reads it fails HERE
    instead of corrupting data on the accelerator.
    """
    import jax.numpy as jnp

    kem, sig, fused = pair
    n = 2
    pk_off, ct_off = init_pk_offset(kem.name, AEAD), resp_ct_offset()
    spk, ssk = sig.generate_keypair()
    sks = np.stack([np.frombuffer(ssk, np.uint8)] * n)
    spks = np.stack([np.frombuffer(spk, np.uint8)] * n)
    rnd = [bytes([i] * 32) for i in range(n)]
    m = [bytes([0x40 | i] * 32) for i in range(n)]
    tmpl = _init_template(kem)
    eks, dks, sigs = fused.keygen_sign_batch(sks, [tmpl] * n, pk_off, rnd=rnd)
    rendered = [
        tmpl[:pk_off] + bytes(ek).hex().encode()
        + tmpl[pk_off + 2 * kem.public_key_len:]
        for ek in eks
    ]
    mus_in = fused._mus_from_peer_pks(spks, rendered)
    # the donated operand must be a jax.Array: numpy operands have no device
    # buffer to donate, so the twin (like XLA) leaves them untouched
    sig_arr = jnp.asarray(
        np.stack([np.frombuffer(bytes(s), np.uint8) for s in sigs]))
    rtmpl = _resp_template(kem)
    tmpl_arr = np.stack(
        [np.frombuffer(rtmpl.ljust(fused.resp_template_len, b"\0"), np.uint8)] * n)
    lens = np.full((n,), len(rtmpl), np.int32)
    program = fused_ops.get_encaps_verify_sign(kem.name, sig.name, ct_off)
    twin = fused_ops.donation_twin(
        program, fused_ops.DONATED_ARGNUMS["encaps_verify_sign"])
    ok, ct, key, sigma, done = twin(
        np.asarray(eks), np.stack([np.frombuffer(x, np.uint8) for x in m]),
        spks, mus_in, sig_arr, sks,
        np.stack([np.frombuffer(r, np.uint8) for r in rnd]), tmpl_arr, lens)
    assert np.asarray(ok).all() and np.asarray(done).all()
    # the outputs are live and correct...
    assert sig.verify(
        spk,
        rtmpl[:ct_off] + bytes(np.asarray(ct)[0]).hex().encode()
        + rtmpl[ct_off + 2 * kem.ciphertext_len:],
        bytes(np.asarray(sigma)[0]))
    # ...but the donated operand is consumed: ANY later read must raise
    with pytest.raises(RuntimeError):
        np.asarray(sig_arr)


def test_fused_providers_pass_fresh_operands_through_twin(pair):
    """The shipping call sites never reuse a donated operand: the whole
    provider roundtrip still passes when every donating program is replaced
    by its deleting twin."""
    kem, sig, fused = pair
    real_enc, real_dec = fused._enc_vfy_sign, fused._dec_vfy_sign
    try:
        fused._enc_vfy_sign = lambda off: fused_ops.donation_twin(
            real_enc(off), fused_ops.DONATED_ARGNUMS["encaps_verify_sign"])
        fused._dec_vfy_sign = lambda: fused_ops.donation_twin(
            real_dec(), fused_ops.DONATED_ARGNUMS["decaps_verify_sign"])
        _roundtrip(pair, 2)
    finally:
        fused._enc_vfy_sign = real_enc
        fused._dec_vfy_sign = real_dec
