"""Bit-exactness of the Pallas SHA-256 compression kernel body.

Same strategy as tests/test_mlkem_pallas.py: the kernel body is a pure
tile-list function run eagerly here; the native pallas_call is exercised
on the chip by the SPHINCS+ sections of tools/full_bench.py.
"""

import hashlib

import jax.numpy as jnp
import numpy as np

from quantum_resistant_p2p_tpu.core import sha256, sha256_pallas


def test_compress_tiles_bit_exact_vs_jnp(monkeypatch):
    monkeypatch.setenv("QRP2P_PALLAS", "0")  # reference = jnp compress
    rng = np.random.default_rng(6)
    B = 64
    state = jnp.asarray(rng.integers(0, 2**32, (B, 8), dtype=np.uint32))
    block = jnp.asarray(rng.integers(0, 256, (B, 64), dtype=np.uint8))
    ref = np.asarray(sha256.compress(state, block))
    words = [state.T[i] for i in range(8)] + [
        sha256._block_words(block).T[i] for i in range(16)
    ]
    out = sha256_pallas._compress_tiles(words)
    got = np.stack([np.asarray(o) for o in out], axis=-1)
    assert np.array_equal(got, ref)


def test_compress_kernel_split_semantics(monkeypatch):
    # Exercises _compress_kernel's 12/12 hi/lo word split, ref indexing, and
    # the int32 output cast with numpy arrays standing in for VMEM refs.
    # (Pallas interpret mode is unusable here: it re-jits the unrolled body
    # and XLA-CPU's LLVM backend chokes — the same pathology documented in
    # tests/test_mlkem_pallas.py, observed even under jax.disable_jit.)
    monkeypatch.setenv("QRP2P_PALLAS", "0")
    rng = np.random.default_rng(8)
    TS, TL = 8, 128
    state = jnp.asarray(rng.integers(0, 2**32, (TS * TL, 8), dtype=np.uint32))
    block = jnp.asarray(rng.integers(0, 256, (TS * TL, 64), dtype=np.uint8))
    ref = np.asarray(sha256.compress(state, block))
    words = jnp.concatenate(
        [state.T, sha256._block_words(block).T], axis=0
    ).reshape(24, TS, TL)
    out_ref = np.zeros((8, TS, TL), np.int32)
    sha256_pallas._compress_kernel(
        np.asarray(words[:12]), np.asarray(words[12:]), out_ref
    )
    got = out_ref.reshape(8, TS * TL).T.astype(np.uint32)
    assert np.array_equal(got, ref)


def test_compress_gate_routes_through_kernel(monkeypatch):
    # The production compress() gate: flat batch >= _PALLAS_MIN_BATCH with
    # the pallas flag on must produce identical state updates through the
    # transpose/reshape round-trip.
    rng = np.random.default_rng(9)
    B = 300
    state = jnp.asarray(rng.integers(0, 2**32, (B, 8), dtype=np.uint32))
    block = jnp.asarray(rng.integers(0, 256, (B, 64), dtype=np.uint8))
    monkeypatch.setenv("QRP2P_PALLAS", "0")
    ref = np.asarray(sha256.compress(state, block))
    monkeypatch.setenv("QRP2P_PALLAS", "1")
    def tile_compress_words(sw, bw):
        # stand-in with the real kernel body, skipping only pallas_call
        out = sha256_pallas._compress_tiles(
            [sw[i] for i in range(8)] + [bw[i] for i in range(16)]
        )
        return jnp.stack(out)

    monkeypatch.setattr(sha256_pallas, "compress_words", tile_compress_words)
    got = np.asarray(sha256.compress(state, block))
    assert np.array_equal(got, ref)


def test_full_digest_still_hashlib_anchored():
    rng = np.random.default_rng(7)
    msg = rng.integers(0, 256, (5, 117), dtype=np.uint8)
    d = np.asarray(sha256.sha256(jnp.asarray(msg)))
    for i in range(5):
        assert bytes(d[i]) == hashlib.sha256(msg[i].tobytes()).digest()
