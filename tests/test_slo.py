"""obs/slo.py — SLO / burn-rate alert engine acceptance suite.

Covers: window-delta burn math on injected-clock timelines, the
both-windows alert condition, alert edges (structured ``slo_burn`` flight
events through the auto-dump machinery, rate-limited one-time WARNINGs,
recovery events), budget gauges in the registry, the probe builders
(latency-histogram split, counter pairs, breaker degraded-time), the
``SecureMessaging.metrics()["slo"]`` section, and the seeded chaos
acceptance: a breaker storm deterministically fires the fast-burn alert
and the flight dump tells the story event by event.

Stdlib-only; runs on minimal images.
"""

from __future__ import annotations

import asyncio
import logging
import time

import pytest

from quantum_resistant_p2p_tpu.app.messaging import SecureMessaging
from quantum_resistant_p2p_tpu.faults import FaultPlan, FaultRule
from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode
from quantum_resistant_p2p_tpu.obs import flight as obs_flight
from quantum_resistant_p2p_tpu.obs import slo as obs_slo
from quantum_resistant_p2p_tpu.obs.flight import FlightRecorder
from quantum_resistant_p2p_tpu.obs.metrics import Histogram, Registry
from quantum_resistant_p2p_tpu.obs.slo import (SLOEngine, SLOSpec,
                                               breaker_availability_probe,
                                               counter_pair_probe,
                                               latency_probe)
from quantum_resistant_p2p_tpu.provider.batched import Breaker, OpQueue


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


class _Clock:
    """Settable deterministic clock."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def _engine(clock, registry=None, **kw):
    return SLOEngine(registry=registry, clock=clock, **kw)


# -- spec validation ----------------------------------------------------------


def test_spec_validation():
    probe = lambda: (0.0, 0.0)  # noqa: E731
    with pytest.raises(ValueError):
        SLOSpec("x", objective=1.0, probe=probe)
    with pytest.raises(ValueError):
        SLOSpec("x", objective=0.0, probe=probe)
    with pytest.raises(ValueError):
        SLOSpec("x", objective=0.99, probe=probe,
                fast_window_s=600.0, slow_window_s=600.0)


# -- burn math ----------------------------------------------------------------


def test_burn_rates_over_fast_and_slow_windows():
    """Errors concentrated in the recent past burn the FAST window hard
    while the slow window dilutes them over its longer baseline."""
    clock = _Clock()
    good, bad = [0.0], [0.0]
    eng = _engine(clock)
    eng.add(SLOSpec("svc", objective=0.99,
                    probe=lambda: (good[0], bad[0]),
                    fast_window_s=300.0, slow_window_s=3600.0))
    # one clean hour: 10 good/s, no errors
    for _ in range(60):
        clock.t += 60.0
        good[0] += 600.0
        eng.tick()
    (s,) = eng.evaluate()
    assert s["burn_fast"] == 0.0 and s["burn_slow"] == 0.0
    assert s["budget_remaining"] == 1.0 and not s["alerting"]
    # then five bad minutes: half the traffic errors
    for _ in range(5):
        clock.t += 60.0
        good[0] += 300.0
        bad[0] += 300.0
        eng.tick()
    (s,) = eng.evaluate()
    # fast window: ~50% errors against a 1% budget -> ~50x burn
    assert 40.0 <= s["burn_fast"] <= 50.0
    # slow window: 1500 bad / ~37500 total -> ~4x burn
    assert 3.0 <= s["burn_slow"] <= 5.0
    assert s["alerting"]  # 50x >= 14.4 and 4x >= 1.0
    assert s["budget_remaining"] < 1.0


def test_hot_scraper_keeps_slow_window_baseline():
    """A scraper ticking at 5 Hz produces ~90k samples/h — far over the
    retention cap.  The engine must DECIMATE interior samples, never evict
    the slow-window baseline: with baseline eviction (the old fixed-size
    ring) the slow window silently collapsed to ~14 min and a 5-minute
    blip burned both windows alike, un-filtering exactly what the
    multi-window design exists to filter."""
    clock = _Clock()
    good, bad = [0.0], [0.0]
    eng = _engine(clock)
    eng.add(SLOSpec("svc", objective=0.99,
                    probe=lambda: (good[0], bad[0]),
                    fast_window_s=300.0, slow_window_s=3600.0))
    # one clean hour scraped at 5 Hz: 10 good/s
    for _ in range(18_000):
        clock.t += 0.2
        good[0] += 2.0
        eng.tick()
    # then five bad minutes, still at 5 Hz: half the traffic errors
    for _ in range(1_500):
        clock.t += 0.2
        good[0] += 1.0
        bad[0] += 1.0
        eng.tick()
    (s,) = eng.evaluate()
    assert 40.0 <= s["burn_fast"] <= 55.0
    # the slow window still reaches back through the clean hour: 1500 bad
    # over ~36000 total -> ~4x burn (a collapsed window reads ~18x)
    assert 3.5 <= s["burn_slow"] <= 6.0
    samples = eng._states["svc"].samples
    assert 2 <= len(samples) <= obs_slo.MAX_SAMPLES
    # the retained baseline really spans the slow window
    assert samples[-1][0] - samples[0][0] >= 3600.0 - 1.0


def test_alert_requires_both_windows():
    """A fast-window spike alone must not page: the slow-window condition
    is the flap filter."""
    clock = _Clock()
    bad = [0.0]
    eng = _engine(clock)
    eng.add(SLOSpec("svc", objective=0.9, probe=lambda: (10_000.0, bad[0]),
                    fast_burn=1.0, slow_burn=10_000.0))  # slow: unreachable
    for _ in range(3):
        clock.t += 60.0
        bad[0] += 500.0
        eng.tick()
    (s,) = eng.evaluate()
    assert s["burn_fast"] >= 1.0
    assert not s["alerting"]


def test_short_history_process_still_evaluates():
    """A process younger than its windows evaluates over the history it
    has (the chaos-run case): total outage -> burn at the 1/(1-objective)
    ceiling on both windows."""
    clock = _Clock()
    bad = [0.0]
    eng = _engine(clock)
    eng.add(SLOSpec("svc", objective=0.9, probe=lambda: (0.0, bad[0]),
                    fast_burn=5.0, slow_burn=2.0))
    eng.tick()
    clock.t += 30.0
    bad[0] += 64.0
    (s,) = eng.evaluate()
    assert s["burn_fast"] == 10.0 and s["burn_slow"] == 10.0
    assert s["alerting"]


# -- alert edges: flight events, warnings, gauges -----------------------------


def test_alert_edge_fires_flight_event_and_one_warning(monkeypatch, caplog):
    rec = FlightRecorder()
    monkeypatch.setattr(obs_flight, "RECORDER", rec)
    clock = _Clock()
    bad = [0.0]
    eng = _engine(clock, warn_interval_s=600.0)
    eng.add(SLOSpec("svc", objective=0.9, probe=lambda: (0.0, bad[0]),
                    fast_burn=5.0, slow_burn=2.0))
    eng.tick()
    with caplog.at_level(logging.WARNING, logger="quantum_resistant_p2p_tpu.obs.slo"):
        for _ in range(5):  # stays alerting across evaluations
            clock.t += 30.0
            bad[0] += 10.0
            eng.evaluate()
    warnings = [r for r in caplog.records if "SLO svc burning" in r.message]
    assert len(warnings) == 1  # one-time per episode (rate-limited)
    burns = [e for e in rec.snapshot() if e["kind"] == "slo_burn"]
    assert len(burns) == 1
    assert burns[0]["slo"] == "svc" and burns[0]["burn_fast"] >= 5.0


def test_rewarn_after_interval_while_still_burning(monkeypatch, caplog):
    rec = FlightRecorder()
    monkeypatch.setattr(obs_flight, "RECORDER", rec)
    clock = _Clock()
    bad = [0.0]
    eng = _engine(clock, warn_interval_s=120.0)
    eng.add(SLOSpec("svc", objective=0.9, probe=lambda: (0.0, bad[0]),
                    fast_burn=5.0, slow_burn=2.0))
    eng.tick()
    with caplog.at_level(logging.WARNING, logger="quantum_resistant_p2p_tpu.obs.slo"):
        for _ in range(6):  # 6 x 30s = 180s alerting > 120s re-warn interval
            clock.t += 30.0
            bad[0] += 10.0
            eng.evaluate()
    warnings = [r for r in caplog.records if "SLO svc burning" in r.message]
    assert len(warnings) == 2  # entry + one re-warn, not six
    # but still only ONE slo_burn flight event (edge-triggered)
    assert len([e for e in rec.snapshot() if e["kind"] == "slo_burn"]) == 1


def test_recovery_event_recorded(monkeypatch):
    rec = FlightRecorder()
    monkeypatch.setattr(obs_flight, "RECORDER", rec)
    clock = _Clock()
    good, bad = [0.0], [0.0]
    eng = _engine(clock)
    eng.add(SLOSpec("svc", objective=0.9,
                    probe=lambda: (good[0], bad[0]),
                    fast_window_s=60.0, slow_window_s=300.0,
                    fast_burn=5.0, slow_burn=2.0))
    eng.tick()
    clock.t += 30.0
    bad[0] += 100.0
    eng.evaluate()
    # clean traffic long enough to slide both windows past the incident
    for _ in range(20):
        clock.t += 30.0
        good[0] += 1000.0
        eng.evaluate()
    kinds = [e["kind"] for e in rec.snapshot()]
    assert "slo_burn" in kinds and "slo_recovered" in kinds
    assert kinds.index("slo_burn") < kinds.index("slo_recovered")


def test_budget_gauges_land_in_registry():
    reg = Registry("slo-test")
    clock = _Clock()
    bad = [0.0]
    eng = _engine(clock, registry=reg)
    eng.add(SLOSpec("svc", objective=0.9, probe=lambda: (0.0, bad[0]),
                    fast_burn=5.0, slow_burn=2.0))
    eng.tick()
    clock.t += 30.0
    bad[0] += 10.0
    eng.evaluate()
    snap = reg.snapshot()
    assert snap["gauges"]['slo_budget_remaining{slo="svc"}'] == 0.0
    assert snap["gauges"]['slo_burn_fast{slo="svc"}'] == 10.0
    prom = reg.to_prometheus()
    assert 'qrp2p_slo_budget_remaining{registry="slo-test",slo="svc"} 0' in prom


def test_crashing_probe_degrades_to_stale_not_engine_death():
    clock = _Clock()
    eng = _engine(clock)
    eng.add(SLOSpec("broken", objective=0.99,
                    probe=lambda: 1 // 0))  # type: ignore[arg-type]
    eng.add(SLOSpec("fine", objective=0.99, probe=lambda: (10.0, 0.0)))
    report = eng.status()
    names = {s["name"] for s in report["specs"]}
    assert names == {"broken", "fine"}
    assert report["alerting"] == []


# -- probe builders -----------------------------------------------------------


def test_latency_probe_splits_on_bucket_boundary():
    h = Histogram("lat", "t", buckets=(0.5, 1.0, 2.0, 5.0))
    for v in (0.1, 0.9, 2.0, 4.0, 9.0):
        h.record(v)
    good, bad = latency_probe(h, 2.0)()
    assert (good, bad) == (3.0, 2.0)  # <=2.0s is good; 4.0 and 9.0 burn
    with pytest.raises(ValueError):
        latency_probe(h, 0.1)  # below the smallest boundary: no exact split


def test_counter_pair_probe_reads_live():
    a, b = [5], [1]
    p = counter_pair_probe(lambda: a[0], lambda: b[0])
    assert p() == (5.0, 1.0)
    a[0], b[0] = 7, 2
    assert p() == (7.0, 2.0)


def test_breaker_degraded_seconds_and_availability_probe():
    b = Breaker(cooloff_s=0.05)
    assert b.degraded_seconds() == 0.0
    b.trip()  # closed -> open
    time.sleep(0.02)
    assert b.degraded_seconds() > 0.0
    time.sleep(0.04)  # past the cool-off: probe route heals it
    claim = b.acquire_dispatch()
    assert claim == "probe"
    b.record_success(claim)  # half_open -> closed
    settled = b.degraded_seconds()
    assert settled >= 0.05
    time.sleep(0.01)
    assert b.degraded_seconds() == settled  # ledger frozen while closed
    good, bad = breaker_availability_probe(b)()
    assert bad == pytest.approx(settled, rel=0.2)
    assert good > 0.0


# -- engine wiring (SecureMessaging) ------------------------------------------


def test_messaging_metrics_slo_section(monkeypatch):
    monkeypatch.setattr(SecureMessaging, "_spawn_warmup",
                        lambda self, **kw: None)
    node = P2PNode(node_id="slopeer", host="127.0.0.1", port=0)
    m = SecureMessaging(node, backend="tpu", use_batching=True,
                        sig_keypair=(b"p", b"s"),
                        symmetric=type("A", (), {"name": "X"})())
    out = m.metrics()
    names = {s["name"] for s in out["slo"]["specs"]}
    assert {"handshake_p99", "gateway_shed_rate", "device_served_shard0",
            "breaker_availability"} <= names
    assert out["slo"]["alerting"] == []
    assert m.slo_status()["alerts_total"] == 0
    # budget gauges ride the engine registry -> Prometheus scrape
    prom = m.registry.to_prometheus()
    assert 'slo="handshake_p99"' in prom


def test_prometheus_scrape_advances_slo_engine(monkeypatch):
    """A gateway watched ONLY through Prometheus must still evaluate its
    SLOs: the registry's slo_health collector rides every scrape, so the
    burn gauges refresh and alert edges can fire without anyone calling
    metrics() or /slo."""
    node = P2PNode(node_id="sloprom", host="127.0.0.1", port=0)
    m = SecureMessaging(node, backend="cpu", sig_keypair=(b"p", b"s"),
                        symmetric=type("A", (), {"name": "X"})())
    prom = m.registry.to_prometheus()  # scrape, not metrics()
    assert 'qrp2p_slo_health_alerts_total' in prom
    assert 'slo="handshake_p99"' in prom  # evaluation set the gauges
    snap = m.registry.snapshot()
    assert snap["collected"]["slo_health"]["alerting_count"] == 0


def test_gateway_shed_sli_is_symmetric_per_boundary():
    """Connection sheds count as bad, so connection ADMISSIONS must count
    as good: a reconnect wave of admitted peers that never handshake must
    not read as an admission outage."""
    node = P2PNode(node_id="slosym", host="127.0.0.1", port=0)
    m = SecureMessaging(node, backend="cpu", sig_keypair=(b"p", b"s"),
                        symmetric=type("A", (), {"name": "X"})())
    # 64 admitted connections, 36 shed, zero handshakes yet
    node.admitted, node.sheds = 64, 36
    (spec,) = [s for s in m.slo_status()["specs"]
               if s["name"] == "gateway_shed_rate"]
    assert spec["good_total"] == 64.0 and spec["bad_total"] == 36.0
    assert m.metrics()["gateway"]["connections_admitted"] == 64


def test_messaging_without_batching_has_core_slos():
    node = P2PNode(node_id="slocpu", host="127.0.0.1", port=0)
    m = SecureMessaging(node, backend="cpu", sig_keypair=(b"p", b"s"),
                        symmetric=type("A", (), {"name": "X"})())
    names = set(m.slo.names())
    # resume_success joined the core set in PR 15 (docs/protocol.md
    # "Session resumption") — like the other two it needs no scheduler
    assert names == {"handshake_p99", "gateway_shed_rate",
                     "resume_success"}


# -- the seeded chaos acceptance ----------------------------------------------


def test_breaker_storm_fires_fast_burn_alert_deterministically(
        run, monkeypatch):
    """Acceptance (ISSUE 10): a seeded breaker storm — every device
    dispatch raising, ops degrading to the fallback — deterministically
    fires the fast-burn SLO alert, and the flight dump tells the story:
    breaker_open first, then slo_burn, with the burn numbers attached."""
    rec = FlightRecorder(clock=lambda: 1000.0, mono=lambda: 0.0)
    monkeypatch.setattr(obs_flight, "RECORDER", rec)
    breaker = Breaker(cooloff_s=30.0)
    clock = _Clock()
    eng = _engine(clock)
    eng.add(SLOSpec(
        "device_served_shard0", objective=0.9,
        probe=counter_pair_probe(lambda: breaker.device_trips,
                                 lambda: breaker.fallback_trips),
        description="dispatch steps served by the device path",
        fast_burn=5.0, slow_burn=2.0,
    ))
    eng.tick()  # t=0 baseline: nothing burned

    async def main():
        q = OpQueue(lambda items: [("dev", i) for i in items],
                    max_batch=4, max_wait_ms=0.5,
                    fallback_fn=lambda items: [("cpu", i) for i in items],
                    breaker=breaker, label="storm.enc")
        q.mark_warm(1)
        plan = FaultPlan(seed=23, rules=[
            FaultRule("device.dispatch", "raise", nth=1, times=64),
        ])
        with plan.activate():
            for i in range(12):
                assert await q.submit(i) == ("cpu", i)  # degraded, not failed
        return plan

    plan = run(main())
    assert plan.injected  # the storm really fired
    assert breaker.state == "open"
    clock.t += 60.0
    report = eng.status()
    (spec,) = report["specs"]
    # deterministic trip ledger given the seed: the ONE device attempt
    # that raised (counted before its outcome), then 12 fallback flushes
    # -> error rate 12/13 against a 0.1 budget on both windows
    expected_burn = round((12 / 13) / 0.1, 4)
    assert spec["good_total"] == 1.0 and spec["bad_total"] == 12.0
    assert spec["burn_fast"] == expected_burn
    assert spec["burn_slow"] == expected_burn
    assert report["alerting"] == ["device_served_shard0"]
    # the dump narrates: breaker opened, then the SLO burned
    bundle = rec.dump("chaos", registries={})
    kinds = [e["kind"] for e in bundle["events"]]
    assert "breaker_open" in kinds and "slo_burn" in kinds
    assert kinds.index("breaker_open") < kinds.index("slo_burn")
    (burn,) = [e for e in bundle["events"] if e["kind"] == "slo_burn"]
    assert burn["slo"] == "device_served_shard0"
    assert burn["burn_fast"] == expected_burn
    assert burn["budget_remaining"] == 0.0
    # byte-stable snapshot given the injected clocks: same drive -> same
    # story (the events carry no wall-clock jitter)
    assert all(e["t"] == 1000.0 for e in bundle["events"])
