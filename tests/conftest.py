"""Test configuration: force an 8-device virtual CPU mesh before JAX use.

Real-TPU execution is exercised by bench.py / __graft_entry__.py (run by the
driver); the test suite runs on a virtual 8-device CPU platform so sharding
paths (pjit over a Mesh) are testable without multi-chip hardware.

Note: this environment's TPU bootstrap (sitecustomize) force-prepends the
remote-TPU platform to ``jax.config.jax_platforms`` regardless of the
JAX_PLATFORMS env var, so the config must be overridden explicitly — env vars
alone are ignored.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the crypto kernels are compile-heavy; caching
# cuts repeat suite runs from tens of minutes to minutes.  Set via config (not
# env): this image's TPU bootstrap imports jax at interpreter start, before
# conftest env vars could be seen.  Shared with the bench entry points.
from quantum_resistant_p2p_tpu.utils.benchmarking import enable_compile_cache  # noqa: E402

enable_compile_cache()
