"""Test configuration: force an 8-device virtual CPU mesh before JAX imports.

Real-TPU execution is exercised by bench.py / __graft_entry__.py (run by the
driver); the test suite runs on a virtual 8-device CPU platform so sharding
paths (pjit over a Mesh) are testable without multi-chip hardware.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
