"""Bit-exactness of the fused Pallas SampleNTT pipeline (kem/mlkem_pallas.py).

The kernel body is a pure function over lane-word tiles
(``_sample_ntt_tiles``), so it runs here EAGERLY on plain CPU arrays —
interpret mode executes the ~57k-op body orders of magnitude too slowly,
and XLA-CPU's LLVM backend chokes compiling the fully-unrolled graph.
Native Mosaic compilation + execution of the full ``pallas_call`` is
exercised on the real chip by bench.py / tools/full_bench.py (and was
verified bit-exact vs the jnp path for B=1500 on TPU v5e).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from quantum_resistant_p2p_tpu.core import keccak
from quantum_resistant_p2p_tpu.core.sortnet import bitonic_sort, bitonic_sort_regs
from quantum_resistant_p2p_tpu.kem import mlkem, mlkem_pallas


def test_sort_regs_matches_array_sort():
    rng = np.random.default_rng(3)
    x = rng.integers(0, 1 << 23, (32, 7), dtype=np.int32)
    regs = bitonic_sort_regs([jnp.asarray(x[i]) for i in range(32)])
    got = np.stack([np.asarray(r) for r in regs])
    ref = np.asarray(bitonic_sort(jnp.asarray(x.T))).T
    assert np.array_equal(got, ref)


def test_sample_ntt_tiles_bit_exact_vs_jnp_path(monkeypatch):
    monkeypatch.setenv("QRP2P_PALLAS", "0")  # reference = jnp sample_ntt
    rng = np.random.default_rng(7)
    B = 64
    seeds = jnp.asarray(rng.integers(0, 256, (B, 34), dtype=np.uint8))
    ref = np.asarray(mlkem.sample_ntt(seeds))

    # Same padded-block prep as the production sample_ntt pallas branch.
    block = keccak.pad_single_block(seeds, 168, 0x1F)
    ph, plo = keccak._bytes_to_words(block)
    out = mlkem_pallas._sample_ntt_tiles(
        [ph[:, w] for w in range(mlkem_pallas.RATE_WORDS)],
        [plo[:, w] for w in range(mlkem_pallas.RATE_WORDS)],
    )
    got = np.stack([np.asarray(o) for o in out], axis=-1)
    assert np.array_equal(got, ref)
    # Sanity: accepted coefficients are reduced mod q.
    assert got.max() < mlkem.Q


@pytest.mark.parametrize("eta", [2, 3])
def test_cbd_tiles_bit_exact_vs_jnp_path(eta, monkeypatch):
    # eta=3 exercises the two-block squeeze (ML-KEM-512's eta1).
    monkeypatch.setenv("QRP2P_PALLAS", "0")
    rng = np.random.default_rng(10 + eta)
    B = 48
    s = jnp.asarray(rng.integers(0, 256, (B, 32), dtype=np.uint8))
    n_consts = np.arange(2, dtype=np.uint8)
    ref = np.asarray(mlkem._prf_cbd(s, n_consts, eta))
    seeds = mlkem._prf_seeds(s, n_consts)
    block = keccak.pad_single_block(seeds.reshape(-1, 33), 136, 0x1F)
    ph, plo = keccak._bytes_to_words(block)
    out = mlkem_pallas._cbd_tiles(
        [ph[:, w] for w in range(mlkem_pallas.CBD_RATE_WORDS)],
        [plo[:, w] for w in range(mlkem_pallas.CBD_RATE_WORDS)],
        eta,
    )
    got = np.stack([np.asarray(o) for o in out], axis=-1).reshape(B, 2, 256)
    assert np.array_equal(got, ref)


@pytest.mark.parametrize("ds", ["ML-KEM-512", "ML-KEM-768", "ML-KEM-1024"])
def test_kem_roundtrip_small_batch(ds):
    rng = np.random.default_rng(11)
    kg, enc, dec = mlkem.get(ds)
    d, z, m = (
        jnp.asarray(rng.integers(0, 256, (3, 32), dtype=np.uint8)) for _ in range(3)
    )
    ek, dk = kg(d, z)
    key, ct = enc(ek, m)
    key2 = dec(dk, ct)
    assert np.array_equal(np.asarray(key), np.asarray(key2))


def test_sliced_dispatch_pads_and_trims_non_divisible_tail():
    from quantum_resistant_p2p_tpu.provider.base import sliced_dispatch

    calls = []

    def fn(a, b):
        calls.append(a.shape[0])
        return a * 2, a + b

    rng = np.random.default_rng(5)
    a = rng.integers(0, 100, (11, 3), dtype=np.int64)
    b = rng.integers(0, 100, (11, 3), dtype=np.int64)
    x, y = sliced_dispatch(fn, 4, a, b)
    assert calls == [4, 4, 4]  # tail of 3 padded to a full compiled shape
    assert np.array_equal(x, a * 2) and np.array_equal(y, a + b)
    # Single-output fn, exactly divisible: no padding branch.
    calls.clear()
    z = sliced_dispatch(lambda a: a - 1, 4, a[:8])
    assert calls == [] and np.array_equal(z, a[:8] - 1)


def test_sliced_dispatch_through_kem_provider_past_knee(monkeypatch):
    # Drive a real TPU-backend KEM provider through a batch bigger than its
    # dispatch ceiling (and not a multiple of it), so the pad-and-trim path
    # runs inside the production keygen/encaps/decaps wrappers.
    from quantum_resistant_p2p_tpu.provider import registry

    algo = registry.get_kem("ML-KEM-512", backend="tpu")
    monkeypatch.setattr(algo, "_max_dispatch", 4, raising=True)
    n = 11
    ek, dk = algo.generate_keypair_batch(n)
    ct, key = algo.encapsulate_batch(ek)
    key2 = algo.decapsulate_batch(dk, ct)
    assert key.shape == (n, 32) and np.array_equal(key, key2)
