"""TUI: pure helpers + key handling driven without a terminal.

The render loop needs a real curses screen (driven manually / via the
verify skill in tmux); everything else — peer rows, wrapping, the pane
writer, and the full key->command path over a live two-node stack — is
exercised here.
"""

import asyncio
import collections

import pytest

pytest.importorskip("cryptography")  # the CLI stack unlocks the AES-GCM vault

from quantum_resistant_p2p_tpu.cli import CLI
from quantum_resistant_p2p_tpu.tui import Tui, _PaneWriter, peer_rows, wrap_lines


def test_wrap_lines_wraps_and_tails():
    lines = ["abcdef", "", "xy"]
    assert wrap_lines(lines, 3, 10) == ["abc", "def", "", "xy"]
    assert wrap_lines(lines, 3, 2) == ["", "xy"]


def test_pane_writer_splits_lines():
    buf = collections.deque()
    w = _PaneWriter(buf)
    print("one", file=w)
    print("two\nthree", file=w)
    assert list(buf) == ["one", "two", "three"]


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


def _mk(tmp_path, name):
    cli = CLI(vault_path=str(tmp_path / f"{name}.vault.json"), port=0,
              backend="cpu", enable_discovery=False)
    assert cli.login("pw-" + name)
    return cli


def test_tui_keys_drive_chat_over_live_stack(run, tmp_path):
    async def main():
        a = _mk(tmp_path, "a")
        b = _mk(tmp_path, "b")
        await a.start()
        await b.start()
        tui = Tui(a)  # captures a.out into tui.lines

        async def type_line(text):
            for c in text:
                assert await tui.on_key(ord(c))
            return await tui.on_key(10)  # Enter

        await type_line(f"/connect 127.0.0.1 {b.node.port}")
        await asyncio.sleep(0.05)
        peer_b = a.node.get_peers()[0]
        rows = peer_rows(a, 0)
        assert rows and rows[0][1] and peer_b[:12] in rows[0][0]
        assert "conn" in rows[0][0]

        await type_line(f"/key {peer_b[:8]}")
        assert any("shared key established" in ln for ln in tui.lines)
        rows = peer_rows(a, 0)
        assert "secure" in rows[0][0]

        # plain text goes to the selected peer
        got = asyncio.Event()
        b.messaging.register_message_listener(lambda p, m: got.set())
        await type_line("hello from the tui")
        await asyncio.wait_for(got.wait(), 5)

        # backspace edits, /quit exits the loop contract
        for c in "/quitX":
            await tui.on_key(ord(c))
        assert await tui.on_key(127)  # strip the X
        assert tui.input == "/quit"
        assert not await tui.on_key(10)

        await b.stop()

    run(main())


def test_unread_counts_in_peer_rows(run, tmp_path):
    async def main():
        a = _mk(tmp_path, "a3")
        b = _mk(tmp_path, "b3")
        await a.start()
        await b.start()
        await a.handle(f"/connect 127.0.0.1 {b.node.port}")
        await asyncio.sleep(0.05)
        peer_a = b.node.get_peers()[0]
        peer_b = a.node.get_peers()[0]
        await a.handle(f"/key {peer_b[:8]}")
        await a.handle(f"/send {peer_b[:8]} ping")
        for _ in range(100):
            if b.store.get_unread_count(peer_a):
                break
            await asyncio.sleep(0.02)
        rows = peer_rows(b, 0)
        assert any("(" in r[0] for r in rows)  # unread badge shown
        b.store.mark_read(peer_a)
        rows = peer_rows(b, 0)
        assert not any("(" in r[0] for r in rows)
        await a.stop()
        await b.stop()

    run(main())
