"""End-to-end: two in-process nodes complete an ML-KEM-768 + ML-DSA-65 +
AES-256-GCM handshake and exchange verified messages over localhost TCP.

Models the reference's integration harness (tests/crypto_algorithms_tester.py:
two full stacks in one process, real TCP, event-driven sync).  CPU backend —
the TPU provider path is exercised by the jax test modules and bench.py.
"""

import asyncio

import pytest

# the vault + the AES-256-GCM transport AEAD need the OpenSSL wheel; minimal
# images run the protocol-layer coverage via tests/test_faults.py instead
pytest.importorskip("cryptography")

from quantum_resistant_p2p_tpu.app import Message, MessageStore, SecureMessaging
from quantum_resistant_p2p_tpu.net import P2PNode
from quantum_resistant_p2p_tpu.storage import KeyStorage


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


class Stack:
    """A full node stack minus UI: storage + transport + protocol engine."""

    def __init__(self, name: str, tmp_path, **sm_kwargs):
        self.storage = KeyStorage(tmp_path / f"{name}.vault.json")
        assert self.storage.unlock("test_password")
        self.node = P2PNode(node_id=name, host="127.0.0.1", port=0)
        self.messaging = None
        self.inbox: list[tuple[str, Message]] = []
        self.got_message = asyncio.Event()
        self._sm_kwargs = sm_kwargs

    async def start(self):
        await self.node.start()
        self.messaging = SecureMessaging(
            self.node, key_storage=self.storage, **self._sm_kwargs
        )
        self.messaging.register_message_listener(self._on_msg)

    def _on_msg(self, peer_id, message):
        self.inbox.append((peer_id, message))
        self.got_message.set()

    async def stop(self):
        await self.node.stop()


async def _connected_pair(tmp_path, **kw):
    a, b = Stack("alice", tmp_path, **kw), Stack("bob", tmp_path, **kw)
    await a.start()
    await b.start()
    assert await a.node.connect_to_peer("127.0.0.1", b.node.port) == "bob"
    for _ in range(100):
        if b.node.is_connected("alice"):
            break
        await asyncio.sleep(0.01)
    return a, b


def test_handshake_and_messaging(run, tmp_path):
    async def main():
        a, b = await _connected_pair(tmp_path)
        ok = await a.messaging.initiate_key_exchange("bob")
        assert ok
        assert a.messaging.verify_key_exchange_state("bob")
        # responder reaches ESTABLISHED after the confirm message arrives
        for _ in range(100):
            if b.messaging.verify_key_exchange_state("alice"):
                break
            await asyncio.sleep(0.01)
        assert b.messaging.verify_key_exchange_state("alice")
        # both derived the same AEAD key
        assert a.messaging.shared_keys["bob"] == b.messaging.shared_keys["alice"]

        sent = await a.messaging.send_message("bob", b"hello post-quantum world")
        assert sent is not None
        peers = []
        for _ in range(200):
            peers = [m for m in b.inbox if not m[1].is_system]
            if peers:
                break
            await asyncio.sleep(0.02)
        assert peers and peers[-1][0] == "alice"
        assert peers[-1][1].content == b"hello post-quantum world"

        # reply in the other direction
        b.got_message.clear()
        a.got_message.clear()
        assert await b.messaging.send_message("alice", b"ack") is not None
        await asyncio.wait_for(a.got_message.wait(), 5)
        assert any(m.content == b"ack" for _, m in a.inbox)

        # shared-key history was persisted on both sides
        assert a.storage.list_key_history("bob")
        assert b.storage.list_key_history("alice")
        await a.stop()
        await b.stop()

    run(main())


def test_file_transfer(run, tmp_path):
    async def main():
        a, b = await _connected_pair(tmp_path)
        assert await a.messaging.initiate_key_exchange("bob")
        payload = bytes(range(256)) * 512  # 128 KiB -> exercises chunking
        f = tmp_path / "blob.bin"
        f.write_bytes(payload)
        assert await a.messaging.send_file("bob", f) is not None
        for _ in range(200):
            if any(m.is_file for _, m in b.inbox):
                break
            await asyncio.sleep(0.02)
        files = [m for _, m in b.inbox if m.is_file]
        assert files and files[0].content == payload and files[0].filename == "blob.bin"
        await a.stop()
        await b.stop()

    run(main())


def test_tampered_ciphertext_rejected(run, tmp_path):
    async def main():
        a, b = await _connected_pair(tmp_path)
        assert await a.messaging.initiate_key_exchange("bob")
        # send a raw secure_message with corrupted ciphertext
        key_count = len(b.inbox)
        ct = b"\x00" * 64
        await a.node.send_message("bob", "secure_message", ct=ct, ad=b"{}")
        await asyncio.sleep(0.2)
        assert len([m for m in b.inbox if not m[1].is_system]) == key_count
        await a.stop()
        await b.stop()

    run(main())


def test_settings_gossip_and_mismatch_block(run, tmp_path):
    async def main():
        a, b = await _connected_pair(tmp_path)
        # gossip happens on connect; wait for it
        for _ in range(100):
            if a.messaging.peer_settings.get("bob"):
                break
            await asyncio.sleep(0.01)
        assert a.messaging.settings_match("bob") is True
        # switch bob's AEAD: alice should see a mismatch after gossip
        await b.messaging.set_symmetric_algorithm("ChaCha20-Poly1305")
        for _ in range(100):
            if a.messaging.peer_settings.get("bob", {}).get("aead") == "ChaCha20-Poly1305":
                break
            await asyncio.sleep(0.01)
        assert a.messaging.settings_match("bob") is False
        # adopt peer settings and handshake again
        assert await a.messaging.adopt_peer_settings("bob")
        assert a.messaging.settings_match("bob") is True
        assert await a.messaging.initiate_key_exchange("bob")
        assert await a.messaging.send_message("bob", b"after swap") is not None
        await a.stop()
        await b.stop()

    run(main())


def test_message_store():
    store = MessageStore()
    m = Message(content=b"x", sender_id="a", recipient_id="b")
    store.add_message("a", m, unread=True)
    assert store.get_unread_count("a") == 1
    store.mark_read("a")
    assert store.get_unread_count("a") == 0
    assert store.get_messages("a")[0].content == b"x"
    d = m.to_dict()
    assert Message.from_dict(d).content == b"x"


def test_handshake_with_batched_tpu_provider(run, tmp_path):
    """North-star path: handshake crypto routed through the batch queue."""

    async def main():
        kw = dict(backend="tpu", use_batching=True, max_batch=64, max_wait_ms=2.0)
        a, b = await _connected_pair(tmp_path, **kw)
        assert a.messaging._bkem is not None
        # background warmup precompiles the size-1 buckets; waiting here keeps
        # cold-jit time out of the protocol timeout (the round-1 flake)
        await a.messaging.wait_ready()
        await b.messaging.wait_ready()
        ok = await a.messaging.initiate_key_exchange("bob")
        assert ok
        assert a.messaging.shared_keys["bob"] == b.messaging.shared_keys["alice"]
        assert await a.messaging.send_message("bob", b"batched hello") is not None
        for _ in range(200):
            if any(m.content == b"batched hello" for _, m in b.inbox):
                break
            await asyncio.sleep(0.02)
        assert any(m.content == b"batched hello" for _, m in b.inbox)
        # ML-KEM-768 + ML-DSA-65 advertises the fused capability, so the
        # handshake crypto rides the composite queues: keygen+sign on the
        # initiator, verify+encaps+sign on the responder, verify+decaps+sign
        # back on the initiator — NOT the per-op kem/sig queues.
        assert a.messaging._bfused is not None
        fa, fb = a.messaging._bfused.stats(), b.messaging._bfused.stats()
        assert fa["keygen_sign"]["ops"] >= 1
        assert fa["decaps_verify_sign"]["ops"] >= 1
        assert fb["encaps_verify_sign"]["ops"] >= 1
        assert a.messaging._bkem.stats()["keygen"]["ops"] == 0
        # the secure message itself still signs through the per-op queue
        sig_st = a.messaging._bsig.stats()
        assert sig_st["sign"]["ops"] >= 1
        # the tentpole claim, measured: the initiator's handshake spent
        # <= 4 serial dispatch trips (2 fused on its own breaker)
        trips = a.messaging.metrics()["handshake_trips"]
        assert trips["count"] == 1
        assert trips["last"] is not None and trips["last"] <= 4
        await a.stop()
        await b.stop()

    run(main())
