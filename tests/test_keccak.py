"""Keccak sponge kernel vs the independent hashlib oracle."""

import hashlib

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.core import keccak

LENGTHS = [0, 1, 3, 8, 71, 72, 73, 135, 136, 137, 167, 168, 169, 200, 500]


@pytest.mark.parametrize("n", LENGTHS)
def test_sha3_256(n):
    rng = np.random.default_rng(n)
    msg = rng.integers(0, 256, size=n, dtype=np.uint8)
    got = bytes(np.asarray(keccak.sha3_256(msg)))
    assert got == hashlib.sha3_256(msg.tobytes()).digest()


@pytest.mark.parametrize("n", LENGTHS)
def test_sha3_512(n):
    rng = np.random.default_rng(100 + n)
    msg = rng.integers(0, 256, size=n, dtype=np.uint8)
    got = bytes(np.asarray(keccak.sha3_512(msg)))
    assert got == hashlib.sha3_512(msg.tobytes()).digest()


@pytest.mark.parametrize("n", LENGTHS)
@pytest.mark.parametrize("out_len", [16, 32, 136, 168, 400])
def test_shake(n, out_len):
    rng = np.random.default_rng(1000 + n + out_len)
    msg = rng.integers(0, 256, size=n, dtype=np.uint8)
    got128 = bytes(np.asarray(keccak.shake128(msg, out_len)))
    assert got128 == hashlib.shake_128(msg.tobytes()).digest(out_len)
    got256 = bytes(np.asarray(keccak.shake256(msg, out_len)))
    assert got256 == hashlib.shake_256(msg.tobytes()).digest(out_len)


def test_batched_matches_serial():
    rng = np.random.default_rng(7)
    msgs = rng.integers(0, 256, size=(16, 64), dtype=np.uint8)
    got = np.asarray(keccak.sha3_256(msgs))
    for i in range(16):
        assert bytes(got[i]) == hashlib.sha3_256(msgs[i].tobytes()).digest()


def test_nested_batch_shape():
    rng = np.random.default_rng(8)
    msgs = rng.integers(0, 256, size=(2, 3, 33), dtype=np.uint8)
    got = np.asarray(keccak.shake256(msgs, 64))
    assert got.shape == (2, 3, 64)
    for i in range(2):
        for j in range(3):
            assert bytes(got[i, j]) == hashlib.shake_256(msgs[i, j].tobytes()).digest(64)
