"""Keccak sponge kernel vs the independent hashlib oracle."""

import hashlib

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.core import keccak

LENGTHS = [0, 1, 3, 8, 71, 72, 73, 135, 136, 137, 167, 168, 169, 200, 500]


@pytest.mark.parametrize("n", LENGTHS)
def test_sha3_256(n):
    rng = np.random.default_rng(n)
    msg = rng.integers(0, 256, size=n, dtype=np.uint8)
    got = bytes(np.asarray(keccak.sha3_256(msg)))
    assert got == hashlib.sha3_256(msg.tobytes()).digest()


@pytest.mark.parametrize("n", LENGTHS)
def test_sha3_512(n):
    rng = np.random.default_rng(100 + n)
    msg = rng.integers(0, 256, size=n, dtype=np.uint8)
    got = bytes(np.asarray(keccak.sha3_512(msg)))
    assert got == hashlib.sha3_512(msg.tobytes()).digest()


@pytest.mark.parametrize("n", LENGTHS)
@pytest.mark.parametrize("out_len", [16, 32, 136, 168, 400])
def test_shake(n, out_len):
    rng = np.random.default_rng(1000 + n + out_len)
    msg = rng.integers(0, 256, size=n, dtype=np.uint8)
    got128 = bytes(np.asarray(keccak.shake128(msg, out_len)))
    assert got128 == hashlib.shake_128(msg.tobytes()).digest(out_len)
    got256 = bytes(np.asarray(keccak.shake256(msg, out_len)))
    assert got256 == hashlib.shake_256(msg.tobytes()).digest(out_len)


def test_batched_matches_serial():
    rng = np.random.default_rng(7)
    msgs = rng.integers(0, 256, size=(16, 64), dtype=np.uint8)
    got = np.asarray(keccak.sha3_256(msgs))
    for i in range(16):
        assert bytes(got[i]) == hashlib.sha3_256(msgs[i].tobytes()).digest()


def test_nested_batch_shape():
    rng = np.random.default_rng(8)
    msgs = rng.integers(0, 256, size=(2, 3, 33), dtype=np.uint8)
    got = np.asarray(keccak.shake256(msgs, 64))
    assert got.shape == (2, 3, 64)
    for i in range(2):
        for j in range(3):
            assert bytes(got[i, j]) == hashlib.shake_256(msgs[i, j].tobytes()).digest(64)


def test_shake256_varlen_sweeps_block_boundaries():
    """sponge_varlen matches hashlib for every length across the rate
    boundaries (ds byte mid-block, at block end, first byte of next block)
    with garbage past the true length."""
    rate = 136
    lmax = 2 * rate + 5
    rng = np.random.default_rng(9)
    lengths = sorted({0, 1, rate - 2, rate - 1, rate, rate + 1,
                      2 * rate - 1, 2 * rate, 2 * rate + 1, lmax})
    buf = rng.integers(0, 256, size=(len(lengths), lmax), dtype=np.uint8)
    lens = np.asarray(lengths, np.int32)
    got = np.asarray(keccak.shake256_varlen(buf, lens, 64))
    for i, n in enumerate(lengths):
        want = hashlib.shake_256(buf[i, :n].tobytes()).digest(64)
        assert bytes(got[i]) == want, f"varlen mismatch at length {n}"


def test_shake256_varlen_masks_garbage_tail():
    """Bytes past the true length must not influence the digest."""
    msg = b"fused transcript"
    a = np.zeros((1, 300), np.uint8)
    a[0, : len(msg)] = np.frombuffer(msg, np.uint8)
    b = np.full((1, 300), 0xAB, np.uint8)
    b[0, : len(msg)] = np.frombuffer(msg, np.uint8)
    lens = np.asarray([len(msg)], np.int32)
    da = bytes(np.asarray(keccak.shake256_varlen(a, lens, 32))[0])
    db = bytes(np.asarray(keccak.shake256_varlen(b, lens, 32))[0])
    assert da == db == hashlib.shake_256(msg).digest(32)
