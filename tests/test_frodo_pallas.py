"""FrodoKEM fused Pallas matmul: tile math vs twins, KAT matrix vs pyref.

The kernel BODIES (``_s_times_a_tiles`` / ``_a_times_s_tiles`` /
``_cdf_tiles``) are pure tile functions, tested eagerly on CPU arrays
against the scanned-jnp twins — the same discipline as
tests/test_keccak_pallas.py (interpret mode is orders of magnitude too
slow for sponge kernels; the bench exercises native Mosaic on-chip).
The end-to-end keygen/encaps/decaps path is pinned against
``pyref.frodo_ref`` across all three SHAKE parameter sets and batch
1/4/256 (the big/slow cells marked slow).
"""

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.pyref import frodo_ref as fr

RNG = np.random.default_rng(6408)

SET_640 = "FrodoKEM-640-SHAKE"


# --------------------------------------------------------------------------
# Tile functions vs twin math (eager, CPU)
# --------------------------------------------------------------------------


def _tile_inputs(p, lanes, row0):
    """Seed-block word tiles + absolute-row tile for an 8-row chunk."""
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.kem import frodo_pallas as fp

    seed_a = jnp.asarray(
        RNG.integers(0, 256, size=(lanes, 16), dtype=np.uint8))
    ph, plo, _ = fp.seed_words(p, seed_a)
    in_hi = [ph[w] for w in range(fp.RATE_WORDS)]
    in_lo = [plo[w] for w in range(fp.RATE_WORDS)]
    row = jnp.broadcast_to(
        (jnp.arange(8)[:, None] + row0).astype(jnp.uint32), (8, lanes))
    return seed_a, in_hi, in_lo, row


def test_s_times_a_tiles_match_row_twin():
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.kem import frodo_pallas as fp

    p = fr.PARAMS[SET_640]
    lanes, row0 = 4, 16
    seed_a, in_hi, in_lo, row = _tile_inputs(p, lanes, row0)
    sp_full = jnp.asarray(
        RNG.integers(0, p.q, size=(lanes, fr.NBAR, p.n), dtype=np.int32))
    # S' columns for the 8 A rows of this tile: (NBAR, 8, lanes)
    sp_tile = jnp.moveaxis(sp_full[..., row0:row0 + 8], 0, -1)
    got = fp._s_times_a_tiles(in_hi, in_lo, sp_tile, row,
                              n=p.n, q_mask=p.q - 1, n_sq=fp.row_blocks(p))
    a_rows = fp._gen_rows_jnp(p, seed_a, row0, 8)  # (lanes, 8, n)
    ref = jnp.einsum("lir,lrn->inl", sp_full[..., row0:row0 + 8], a_rows)
    assert (np.asarray(got) & (p.q - 1) == np.asarray(ref) & (p.q - 1)).all()


def test_a_times_s_tiles_match_row_twin():
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.kem import frodo_pallas as fp

    p = fr.PARAMS[SET_640]
    lanes, row0 = 4, 632  # last row chunk: exercises the ragged squeeze tail
    seed_a, in_hi, in_lo, row = _tile_inputs(p, lanes, row0)
    s_full = jnp.asarray(
        RNG.integers(0, p.q, size=(lanes, p.n, fr.NBAR), dtype=np.int32))
    got = fp._a_times_s_tiles(in_hi, in_lo, jnp.moveaxis(s_full, 0, -1), row,
                              n=p.n, q_mask=p.q - 1, n_sq=fp.row_blocks(p))
    a_rows = fp._gen_rows_jnp(p, seed_a, row0, 8)  # (lanes, 8, n)
    ref = jnp.einsum("lrn,lnj->rjl", a_rows, s_full)
    assert (np.asarray(got) & (p.q - 1) == np.asarray(ref) & (p.q - 1)).all()


def test_cdf_tiles_match_sample_twin():
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.kem import frodo_pallas as fp

    for name in ("FrodoKEM-640-SHAKE", "FrodoKEM-976-SHAKE",
                 "FrodoKEM-1344-SHAKE"):
        p = fr.PARAMS[name]
        r = jnp.asarray(
            RNG.integers(0, 1 << 16, size=(8, 128), dtype=np.int32))
        got = fp._cdf_tiles(r, tuple(p.cdf), p.q - 1)
        # the spec's inversion sampling, written independently of the tile fn
        t = np.asarray(r) >> 1
        e = np.zeros_like(t)
        for c in p.cdf[:-1]:
            e += (t > c).astype(np.int32)
        ref = np.where((np.asarray(r) & 1) == 1, -e, e) & (p.q - 1)
        assert (np.asarray(got) == ref).all()


def test_cdf_launcher_interpret_matches_tiles():
    """The one launcher cheap enough for interpret mode (no sponge)."""
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.kem import frodo_pallas as fp

    p = fr.PARAMS[SET_640]
    r = jnp.asarray(RNG.integers(0, 1 << 16, size=(300,), dtype=np.int32))
    got = fp.cdf_sample_words(r, cdf=tuple(p.cdf), q_mask=p.q - 1,
                              interpret=True)
    ref = fp._cdf_tiles(r, tuple(p.cdf), p.q - 1)
    assert (np.asarray(got) == np.asarray(ref)).all()


# --------------------------------------------------------------------------
# Device(-twin) path vs pyref oracle: 3 SHAKE sets x batch 1/4/256
# --------------------------------------------------------------------------

_slow = pytest.mark.slow

KAT_MATRIX = [
    ("FrodoKEM-640-SHAKE", 1, 1, []),
    ("FrodoKEM-640-SHAKE", 4, 2, []),
    ("FrodoKEM-640-SHAKE", 256, 2, [_slow]),
    ("FrodoKEM-976-SHAKE", 1, 1, [_slow]),
    ("FrodoKEM-976-SHAKE", 4, 1, [_slow]),
    ("FrodoKEM-976-SHAKE", 256, 1, [_slow]),
    ("FrodoKEM-1344-SHAKE", 1, 1, [_slow]),
    ("FrodoKEM-1344-SHAKE", 4, 1, [_slow]),
    ("FrodoKEM-1344-SHAKE", 256, 1, [_slow]),
]


@pytest.mark.parametrize(
    "name,batch,oracle_lanes",
    [pytest.param(n, b, o, marks=m, id=f"{n}-b{b}") for n, b, o, m in KAT_MATRIX])
def test_kat_matrix_vs_pyref(name, batch, oracle_lanes):
    """keygen/encaps/decaps byte-exact vs pyref for the first
    ``oracle_lanes`` lanes (pyref is pure Python — seconds per lane at 976+),
    decaps self-consistency + implicit-rejection across the whole batch."""
    from quantum_resistant_p2p_tpu.kem import frodo as jfr

    p = fr.PARAMS[name]
    kg, enc, dec = jfr.get(name)
    sec = p.len_sec
    s = RNG.integers(0, 256, size=(batch, sec), dtype=np.uint8)
    se = RNG.integers(0, 256, size=(batch, sec), dtype=np.uint8)
    z = RNG.integers(0, 256, size=(batch, sec), dtype=np.uint8)
    mu = RNG.integers(0, 256, size=(batch, sec), dtype=np.uint8)
    pk, sk = kg(s, se, z)
    pk, sk = np.asarray(pk), np.asarray(sk)
    ct, ss = enc(pk, mu)
    ct, ss = np.asarray(ct), np.asarray(ss)
    ss_dec = np.asarray(dec(sk, ct))
    assert (ss_dec == ss).all()
    for i in range(oracle_lanes):
        rpk, rsk = fr.keygen(p, s[i].tobytes(), se[i].tobytes(), z[i].tobytes())
        assert bytes(pk[i]) == rpk
        assert bytes(sk[i]) == rsk
        rct, rss = fr.encaps(p, rpk, mu[i].tobytes())
        assert bytes(ct[i]) == rct
        assert bytes(ss[i]) == rss
    # implicit rejection: tampered ct must not reproduce the shared secret
    bad = ct.copy()
    bad[:, 3] ^= 0xFF
    assert not (np.asarray(dec(sk, bad)) == ss).all(axis=-1).any()


# --------------------------------------------------------------------------
# Per-key precompute (opcache seam) + provider path + health gate
# --------------------------------------------------------------------------


def test_encaps_pre_bit_identical_to_plain():
    from quantum_resistant_p2p_tpu.kem import frodo as jfr

    p = fr.PARAMS[SET_640]
    kg, enc, _ = jfr.get(SET_640)
    enc_cold, enc_pre = jfr.get_pre(SET_640)
    sec = p.len_sec
    seeds = RNG.integers(0, 256, size=(4, 1, sec), dtype=np.uint8)
    pk, _ = kg(seeds[0], seeds[1], seeds[2])
    mu = RNG.integers(0, 256, size=(3, sec), dtype=np.uint8)
    pk3 = np.broadcast_to(np.asarray(pk)[0], (3, p.pk_len))
    ct0, ss0 = enc(pk3, mu)
    pre, ct1, ss1 = enc_cold(np.asarray(pk)[0], mu)
    ct2, ss2 = enc_pre(pre, mu)
    assert (np.asarray(ct0) == np.asarray(ct1)).all()
    assert (np.asarray(ss0) == np.asarray(ss1)).all()
    assert (np.asarray(ct1) == np.asarray(ct2)).all()
    assert (np.asarray(ss1) == np.asarray(ss2)).all()


def test_provider_opcache_single_key_path():
    from quantum_resistant_p2p_tpu.provider.kem_providers import (
        FrodoKEMKeyExchange,
    )

    kem = FrodoKEMKeyExchange(security_level=1, backend="tpu", use_aes=False)
    assert kem.opcache is not None
    pk, sk = kem.generate_keypair()
    # cold miss fills the cache, warm hit serves from it — both roundtrip
    ct1, ss1 = kem.encapsulate(pk)
    assert kem.opcache.misses == 1 and kem.opcache.hits == 0
    ct2, ss2 = kem.encapsulate(pk)
    assert kem.opcache.hits == 1
    assert kem.decapsulate(sk, ct1) == ss1
    assert kem.decapsulate(sk, ct2) == ss2
    # mixed-key batch bypasses the single-key opcache path
    pk2, sk2 = kem.generate_keypair()
    pks = np.stack([np.frombuffer(pk, np.uint8), np.frombuffer(pk2, np.uint8)])
    hits_before = kem.opcache.hits
    ct, ss = kem.encapsulate_batch(pks)
    assert kem.opcache.hits == hits_before
    assert kem.decapsulate(sk2, bytes(ct[1])) == bytes(ss[1])


def test_opcache_disabled_by_size_zero():
    from quantum_resistant_p2p_tpu.provider.kem_providers import (
        FrodoKEMKeyExchange,
    )

    kem = FrodoKEMKeyExchange(security_level=1, backend="tpu", use_aes=False,
                              opcache_size=0)
    assert kem.opcache is None
    pk, sk = kem.generate_keypair()
    ct, ss = kem.encapsulate(pk)
    assert kem.decapsulate(sk, ct) == ss


def test_health_frodo_kat_probe():
    from quantum_resistant_p2p_tpu.provider import health
    from quantum_resistant_p2p_tpu.provider.kem_providers import (
        FrodoKEMKeyExchange,
    )

    kem = FrodoKEMKeyExchange(security_level=1, backend="tpu", use_aes=False)
    verdict = health._check_frodo_kat(kem)
    assert verdict.ok, verdict.detail
    assert "KAT ok" in verdict.detail
