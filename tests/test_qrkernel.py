"""qrkernel self-tests: interval/known-bits domain mechanics, per-rule
trigger/clean/suppressed fixtures (interval proof, wrap-by-design, vmap
axis loss, grid/BlockSpec mismatch, read-after-donate, recompile hazard),
the qrlint-deferral contract (a proved site needs no suppression comment),
the suppression-justification + budget ratchets, SARIF schema validation —
and the live codebase is violation-free (the third CI ratchet).

Pure AST / abstract interpretation: no jax import anywhere, so this file
runs on minimal no-jax images.
"""

from __future__ import annotations

import json
import textwrap
import time
from pathlib import Path

from tools.analysis import default_rules
from tools.analysis.engine import Engine, FileContext, Project
from tools.analysis.flow.sarif import check_sarif, to_sarif
from tools.analysis.kernel import kernel_rules
from tools.analysis.kernel.absdom import IVal, bitand, bitor, lshift, mul
from tools.analysis.kernel.interp import FuncVal, Interp
from tools.analysis.kernel.packs import KernelAnalysis, site_status
from tools.analysis.kernel.run import main as qrkernel_main
from tools.analysis.all import main as all_main

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "quantum_resistant_p2p_tpu"


def lint(source: str):
    findings, suppressed = Engine(kernel_rules()).lint_source(textwrap.dedent(source))
    return findings, suppressed


def rule_ids(source: str) -> list[str]:
    return sorted(f.rule for f in lint(source)[0])


def _sites(source: str, path: str = "fixture.py"):
    interp = Interp()
    mod = interp.loader.get(path, textwrap.dedent(source))
    interp.check_paths.add(mod.path)
    for name in sorted(mod.scope_funcs()):
        fn = mod.funcs.get(name)
        if fn is not None:
            interp.summary(FuncVal(fn, mod))
    return {line: s for (p, line), s in interp.sites.items() if p == mod.path}


# -- abstract domain mechanics ------------------------------------------------


def test_interval_and_knownbits_transfer():
    byte = bitand(IVal(), IVal.const(0xFF))  # x & 0xFF of a TOP value
    assert (byte.lo, byte.hi) == (0, 0xFF)
    nib = bitand(IVal(), IVal.const(0xF))
    hi = lshift(nib, IVal.const(8))
    assert hi.hi == 0xF00
    packed = bitor(byte, hi)  # disjoint maybe-bits: exact 12-bit OR
    assert packed.hi == 0xFFF
    assert packed.fits("int32") is True
    prod = mul(IVal.range(0, 2**23 - 1), IVal.range(0, 0x7F))
    assert prod.fits("int32") is True  # the _mm_zeta limb bound
    wide = mul(IVal.range(0, 2**23), IVal.range(0, 0x100))
    assert wide.fits("int32") is None  # reaches 2**31: not provable


def test_site_proof_through_helper_and_loop():
    """The mlkem-shaped pipeline: helper returns bytes, loop assembles
    12-bit candidates — the shift site is proved without any annotation."""
    sites = _sites(
        """
        import jax.numpy as jnp

        def unpack(words, n):
            out = []
            for w in range(n):
                out.append((words[w] >> 4) & 0xFF)
            return out

        def sample_tiles(words):
            byts = unpack(words, 21)
            cand = []
            for t in range(56):
                b0, b1 = byts[2 * t], byts[2 * t + 1]
                cand.append(b0 | ((b1 & 0xF) << 8))
            return cand
        """
    )
    assert sites and all(s.proved for s in sites.values())
    assert max(s.bound for s in sites.values()) <= 0xF00


def test_assume_contract_seeds_proof():
    sites = _sites(
        """
        import jax.numpy as jnp
        Q = 8380417

        def mulmod_tiles(a, z: int):
            # qrkernel: assume a in [0, Q) — mod-q residue by FIPS 204
            # qrkernel: assume z in [0, Q) — zeta table entry
            b2 = z >> 16
            return (a * b2) % Q
        """
    )
    (site,) = sites.values()
    assert site.proved and site.bound < 2**31


# -- per-rule trigger / clean / suppressed fixtures ---------------------------


def test_widened_loop_cannot_claim_stale_proof():
    """Soundness: a tile value that keeps growing in a symbolic loop is
    widened — and the site must OBSERVE the widened state, not keep the
    narrow bound from the first fixpoint passes."""
    sites = _sites(
        """
        import jax.numpy as jnp

        def grow_tiles(a, n: int):
            x = a & 0xFF
            for i in range(n):
                x = x * 2
            return x
        """
    )
    (site,) = sites.values()
    assert not site.proved


def test_conditional_break_demotes_the_unrolled_proof():
    """Soundness: a break under an ABSTRACT condition makes the unrolled
    loop inexact — the loop must fall back to the fixpoint (where growth is
    widened) instead of keeping the straight-line 'proved' bound."""
    sites = _sites(
        """
        import jax.numpy as jnp

        def f_tiles(a, flag):
            acc = a & 0x1
            for i in range(6):
                if flag:
                    break
                acc = acc * 40000
            y = acc * acc
            return y
        """
    )
    assert sites
    assert not all(s.proved for s in sites.values())


def test_inplace_list_growth_is_not_proved():
    """Soundness: fixpoint change detection must see IN-PLACE list mutation
    (snapshots clone LVals) — a list growing each pass widens instead of
    'converging' at its first-pass bounds."""
    sites = _sites(
        """
        import jax.numpy as jnp

        def f_tiles(x, n: int):
            cand = [x & 0x1]
            for i in range(n):
                cand.append(cand[-1] * 3)
            y = cand[-1] * cand[-1]
            return y
        """
    )
    assert sites
    assert not all(s.proved for s in sites.values())


def test_break_branch_state_joins_into_the_proof():
    """Soundness: a bound assigned right before a conditional break must be
    VISIBLE to later sites — the break-path state joins the merge, it is
    not discarded with the branch."""
    sites = _sites(
        """
        import jax.numpy as jnp

        def f_tiles(x, flag):
            y = x & 0xF
            for i in range(24):
                if flag:
                    y = x & 0xFFFFFF
                    break
                y = y & 0xF
            return y * y
        """
    )
    assert sites
    assert not all(s.proved for s in sites.values())


def test_descending_symbolic_range_is_not_minus_one():
    """Soundness: `range(n, 0, -1)` counts DOWN from an unbounded n — the
    loop variable must not be modeled as a small constant."""
    sites = _sites(
        """
        import jax.numpy as jnp

        def f_tiles(x, n: int):
            acc = x & 0x3FF
            for i in range(n, 0, -1):
                acc = (x & 0x3FF) * i
            return acc
        """
    )
    assert sites
    assert not all(s.proved for s in sites.values())


def test_nested_function_dataflow_findings_are_deduped():
    findings, _ = lint(
        """
        import functools
        import jax

        def build():
            @functools.partial(jax.jit, donate_argnums=(0,))
            def step(state, x):
                return state + x

            def drive(state, xs):
                out = step(state, xs)
                return state + out
            return drive
        """
    )
    assert [f.rule for f in findings] == ["kernel-read-after-donate"]


def test_float_tiles_are_not_overflow_sites():
    """Float math rounds, it does not wrap: a float32 kernel multiply must
    produce NO kernel-int32-overflow finding (the Frodo/SPHINCS+ growth
    path is float-adjacent)."""
    assert rule_ids(
        """
        import jax.numpy as jnp

        def f_tiles(x):
            f = x.astype(jnp.float32)
            return f * f
        """
    ) == []


def test_int32_overflow_trigger_clean_suppressed():
    trigger = """
        import jax.numpy as jnp

        def f_tiles(a, b):
            return (a * b) % 3329
        """
    assert rule_ids(trigger) == ["kernel-int32-overflow"]
    clean = """
        import jax.numpy as jnp

        def f_tiles(a, b):
            return ((a & 0xFF) * (b & 0xFF)) % 3329
        """
    assert rule_ids(clean) == []
    findings, suppressed = lint(
        trigger.replace(
            "% 3329",
            "% 3329  # qrkernel: disable=kernel-int32-overflow — bench-only toy op on small ints"))
    assert not findings
    assert [s.rule for s in suppressed] == ["kernel-int32-overflow"]


def test_wrapping_annotation_is_policed():
    justified = """
        import jax.numpy as jnp

        def rot_tiles(hi):
            return (hi << 7)  # qrkernel: wrapping — uint32 rotation: dropped bits recovered from the partner word
        """
    assert rule_ids(justified) == []
    unjustified = justified.replace(
        " — uint32 rotation: dropped bits recovered from the partner word", "")
    assert rule_ids(unjustified) == ["kernel-unjustified-annotation"]


def test_contract_violation_trigger_and_clean():
    src = """
        import jax.numpy as jnp
        Q = 3329

        def mulmod_tiles(a):
            # qrkernel: assume a in [0, Q) — mod-q residue by protocol
            return (a * 255) % Q

        def caller_tiles(x):
            arg = {arg}
            return mulmod_tiles(arg)
        """
    bad = rule_ids(src.format(arg="(x & 0xFF) + 5000"))
    assert bad == ["kernel-contract-violation"]
    assert rule_ids(src.format(arg="(x & 0xFF) % Q")) == []


def test_shape_mismatch_concrete_and_symbolic():
    assert rule_ids(
        """
        import jax.numpy as jnp

        def f(x):
            y = jnp.zeros((4, 128))
            return y.reshape(4, 64)
        """
    ) == ["kernel-shape-mismatch"]
    # symbolic batch dim: coefficients differ with equal symbolic factors
    assert rule_ids(
        """
        import jax.numpy as jnp

        def f(x):
            b = x.shape[0]
            y = jnp.zeros((b, 128))
            return y.reshape(b, 64)
        """
    ) == ["kernel-shape-mismatch"]
    # consistent symbolic flatten stays silent
    assert rule_ids(
        """
        import jax.numpy as jnp

        def f(x):
            b = x.shape[0]
            y = jnp.zeros((b, 128))
            return y.reshape(b * 128)
        """
    ) == []


def test_unknown_batch_prefix_shapes_stay_silent():
    """Regression (kem/frodo.py keygen): `batch + (n, m)` with an unknown
    batch prefix must yield an UNKNOWN shape, not a fabricated rank-1 one —
    the swapaxes/reshape chain below is exactly the live keygen pattern and
    must not false-positive."""
    assert rule_ids(
        """
        import jax.numpy as jnp

        def keygen(z, r, n, nbar):
            batch = z.shape[:-1]
            st = r.reshape(batch + (nbar, n))
            s_mat = jnp.swapaxes(st, -1, -2)
            return st.reshape(batch + (-1,)), s_mat
        """
    ) == []


def test_vmap_axis_loss_trigger_clean_suppressed():
    trigger = """
        import jax
        import jax.numpy as jnp

        def apply(f):
            x = jnp.zeros((8, 128))
            return jax.vmap(f, in_axes=2)(x)
        """
    assert rule_ids(trigger) == ["kernel-batch-axis"]
    assert rule_ids(trigger.replace("in_axes=2", "in_axes=1")) == []
    findings, suppressed = lint(trigger.replace(
        "(x)\n",
        "(x)  # qrkernel: disable=kernel-batch-axis — fixture: axis checked by the caller\n"))
    assert not findings
    assert [s.rule for s in suppressed] == ["kernel-batch-axis"]


def test_vmap_in_axes_arity_mismatch():
    assert rule_ids(
        """
        import jax
        import jax.numpy as jnp

        def apply(f, a, b):
            return jax.vmap(f, in_axes=(0, 0, None))(a, b)
        """
    ) == ["kernel-batch-axis"]


def test_grid_blockspec_divisibility_and_bounds():
    src = """
        import jax
        from jax.experimental import pallas as pl

        def k_kernel(o_ref):
            pass

        def launch():
            return pl.pallas_call(
                k_kernel, grid=({grid},),
                out_specs=pl.BlockSpec(({block}, 128), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((8, 128), "int32"),
            )()
        """
    # 8 not divisible by 3 AND the index map overruns -> two findings
    ids = rule_ids(src.format(grid=4, block=3))
    assert ids == ["kernel-grid-blockspec", "kernel-grid-blockspec"]
    # divisible blocks, in-bounds index map: clean
    assert rule_ids(src.format(grid=4, block=2)) == []
    # divisible blocks but a grid that drives the index map out of bounds
    assert rule_ids(src.format(grid=8, block=2)) == ["kernel-grid-blockspec"]


def test_accum_dtype_preferred_element_type_and_store():
    assert rule_ids(
        """
        import jax
        import jax.numpy as jnp

        def f():
            x = jnp.zeros((8, 8), dtype=jnp.float32)
            y = jnp.zeros((8, 8), dtype=jnp.float32)
            return jnp.matmul(x, y, preferred_element_type=jnp.bfloat16)
        """
    ) == ["kernel-accum-dtype"]
    # a kernel storing an int32 value into an out ref declared int16
    assert rule_ids(
        """
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def acc_kernel(x_ref, o_ref):
            s = x_ref[0].astype(jnp.int32) * 1
            o_ref[0] = s

        def launch(x):
            return pl.pallas_call(
                acc_kernel, grid=(1,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
                out_shape=jax.ShapeDtypeStruct((8, 128), "int16"),
            )(x)
        """
    ) == ["kernel-accum-dtype"]
    # matching dtypes: clean
    assert rule_ids(
        """
        import jax
        import jax.numpy as jnp

        def f():
            x = jnp.zeros((8, 8), dtype=jnp.float32)
            y = jnp.zeros((8, 8), dtype=jnp.float32)
            return jnp.matmul(x, y, preferred_element_type=jnp.float32)
        """
    ) == []


def test_read_after_donate_trigger_clean_suppressed():
    trigger = """
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, x):
            return state + x

        def drive(state, xs):
            out = step(state, xs)
            return state + out
        """
    assert rule_ids(trigger) == ["kernel-read-after-donate"]
    clean = trigger.replace("return state + out", "return out")
    assert rule_ids(clean) == []
    rebind = trigger.replace("out = step(state, xs)",
                             "state = step(state, xs)").replace(
        "return state + out", "return state")
    assert rule_ids(rebind) == []
    findings, suppressed = lint(trigger.replace(
        "return state + out",
        "return state + out  # qrkernel: disable=kernel-read-after-donate — fixture: interpret-mode twin, no aliasing"))
    assert not findings
    assert [s.rule for s in suppressed] == ["kernel-read-after-donate"]


def test_recompile_hazard_trigger_and_clean():
    trigger = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return x * 2

        def run(data):
            outs = []
            for i in range(100):
                outs.append(f(data[:i]))
            return outs
        """
    assert rule_ids(trigger) == ["kernel-recompile-hazard"]
    # fixed-shape slice in the loop: no hazard
    assert rule_ids(trigger.replace("data[:i]", "data[:16]")) == []
    # loop-derived constructor shape: hazard
    assert rule_ids(trigger.replace("f(data[:i])", "f(jnp.zeros(i))")) == [
        "kernel-recompile-hazard"]


# -- qrlint deferral ----------------------------------------------------------


def test_qrlint_defers_to_proved_sites():
    """A tile-shift site the interval analysis PROVES needs no suppression:
    qrlint's int32-narrowing stays silent.  An unprovable twin still fires."""
    proved = textwrap.dedent(
        """
        import jax
        from jax.experimental import pallas as pl

        def pack_tiles(words):
            b0 = words[0] & 0xFF
            b1 = words[1] & 0xFF
            return b0 | ((b1 & 0xF) << 8)
        """
    )
    findings, _ = Engine(default_rules()).lint_source(proved, "fixture.py")
    assert "int32-narrowing" not in {f.rule for f in findings}
    unproved = proved.replace("(b1 & 0xF) << 8", "(b1 + words[2]) << 8")
    findings, _ = Engine(default_rules()).lint_source(unproved, "fixture2.py")
    assert "int32-narrowing" in {f.rule for f in findings}


def test_qrlint_defers_to_wrapping_annotation():
    src = textwrap.dedent(
        """
        import jax
        from jax.experimental import pallas as pl

        def rot_tiles(hi, lo, n: int):
            return (hi << n) | (lo >> (32 - n))  # qrkernel: wrapping — uint32 rotation: dropped bits recovered from partner word
        """
    )
    findings, _ = Engine(default_rules()).lint_source(src, "fixture.py")
    assert "int32-narrowing" not in {f.rule for f in findings}


def test_live_tree_proof_ledger():
    """The exact sites PR 1 hand-justified are now machine-checked: the
    mlkem/mldsa byte-assembly shifts and the _mm_zeta Horner limbs are
    PROVED (with their bounds), the Keccak rotations are wrapping-annotated,
    and NO int32-narrowing suppression remains in the kernel tree."""
    mlkem = PACKAGE / "kem" / "mlkem_pallas.py"
    mldsa = PACKAGE / "sig" / "mldsa_pallas.py"
    keccak = PACKAGE / "core" / "keccak_pallas.py"
    st = site_status(str(mlkem), mlkem.read_text(encoding="utf-8"))
    # byte-assembly shifts + the NTT single-multiply (q^2 < 2^31, so
    # _mul_zeta needs no Horner limb split — proved from its contracts)
    assert sorted(st.values()).count("proved") == 3
    st = site_status(str(mldsa), mldsa.read_text(encoding="utf-8"))
    assert sorted(st.values()).count("proved") >= 4  # candidate + 3 limb lines
    st = site_status(str(keccak), keccak.read_text(encoding="utf-8"))
    assert set(st.values()) == {"wrapping"}
    for f in (mlkem, mldsa, keccak):
        assert "disable=int32-narrowing" not in f.read_text(encoding="utf-8")


def test_live_tree_mm_zeta_bounds_match_the_old_comments():
    """The machine-computed bounds reproduce the hand-written claims the
    suppressions used to make: every _mm_zeta limb product stays < 2**31."""
    mldsa = PACKAGE / "sig" / "mldsa_pallas.py"
    interp = Interp()
    mod = interp.loader.get(str(mldsa))
    interp.check_paths.add(mod.path)
    for name in sorted(mod.scope_funcs()):
        fn = mod.funcs.get(name)
        if fn is not None:
            interp.summary(FuncVal(fn, mod))
    limb_sites = [s for (p, _line), s in interp.sites.items()
                  if p == mod.path and s.op in ("*", "<<") and s.proved]
    assert limb_sites
    assert all(s.bound < 2**31 for s in limb_sites)


# -- suppression-justification + budget ratchets ------------------------------


def test_unjustified_suppression_fires_and_justified_passes():
    bad = """
        import jax.numpy as jnp

        def f_tiles(a, b):
            return (a * b)  # qrkernel: disable=kernel-int32-overflow
        """
    assert rule_ids(bad) == ["kernel-unjustified-annotation"]
    good = bad.replace("disable=kernel-int32-overflow",
                       "disable=kernel-int32-overflow — fixture: toy op")
    assert rule_ids(good) == []


def test_assume_annotation_requires_justification():
    bad = """
        import jax.numpy as jnp
        Q = 3329

        def f_tiles(a):
            # qrkernel: assume a in [0, Q)
            return (a * 255) % Q
        """
    assert rule_ids(bad) == ["kernel-unjustified-annotation"]


def test_budget_file_matches_live_tree(capsys):
    """The committed suppression budget equals the live counts: any PR that
    adds a suppression overruns it and fails the unified driver."""
    budget = json.loads(
        (REPO_ROOT / "tools" / "analysis" / "suppression_budget.json")
        .read_text(encoding="utf-8"))
    assert set(budget) == {"qrlint", "qrflow", "qrkernel", "qrproto", "qrlife"}
    assert budget["qrkernel"] == 0  # every kernel site is proved, not waived
    assert budget["qrproto"] == 0   # every protocol contract holds, not waived


def test_budget_overrun_fails_loudly(tmp_path, monkeypatch, capsys):
    from tools.analysis import all as driver

    pkg = tmp_path / "quantum_resistant_p2p_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(textwrap.dedent(
        """
        import jax.numpy as jnp

        def f_tiles(a, b):
            return (a * b)  # qrkernel: disable=kernel-int32-overflow — fixture: toy
        """
    ))
    budget = tmp_path / "budget.json"
    budget.write_text(
        '{"qrlint": 0, "qrflow": 0, "qrkernel": 0, "qrproto": 0}\n')
    monkeypatch.setattr(driver, "BUDGET_PATH", budget)
    monkeypatch.chdir(tmp_path)
    rc = driver.main(["quantum_resistant_p2p_tpu"])
    err = capsys.readouterr().err
    assert rc == 1
    assert "suppression budget violation" in err
    assert "kernel-int32-overflow" in err
    # within budget after re-pinning
    rc = driver.main(["quantum_resistant_p2p_tpu", "--update-budget"])
    capsys.readouterr()
    assert rc == 0
    rc = driver.main(["quantum_resistant_p2p_tpu"])
    assert rc == 0
    # UNDER budget is a violation too (equality pin): removing the
    # suppression without re-pinning tells the PR to ratchet the file down
    (pkg / "mod.py").write_text("x = 1\n")
    rc = driver.main(["quantum_resistant_p2p_tpu"])
    err = capsys.readouterr().err
    assert rc == 1
    assert "--update-budget" in err


# -- output formats -----------------------------------------------------------


def test_sarif_output_passes_schema_check(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(
        """
        import jax.numpy as jnp

        def f_tiles(a, b):
            return (a * b) % 3329

        def g_tiles(a, b):
            return (a * b) % 3329  # qrkernel: disable=kernel-int32-overflow — fixture: suppressed on purpose
        """
    ))
    rc = qrkernel_main([str(bad), "--format", "sarif"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert check_sarif(doc) == []
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "qrkernel"
    live = [r for r in run["results"] if "suppressions" not in r]
    waived = [r for r in run["results"] if "suppressions" in r]
    assert [r["ruleId"] for r in live] == ["kernel-int32-overflow"]
    assert [r["ruleId"] for r in waived] == ["kernel-int32-overflow"]


def test_merged_sarif_has_one_run_per_analyzer(tmp_path, capsys):
    out = tmp_path / "merged.sarif"
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    rc = all_main([str(clean), "--sarif-out", str(out), "--format", "json"])
    capsys.readouterr()
    assert rc == 0
    doc = json.loads(out.read_text(encoding="utf-8"))
    assert check_sarif(doc) == []
    names = [r["tool"]["driver"]["name"] for r in doc["runs"]]
    assert names == ["qrlint", "qrflow", "qrkernel", "qrproto", "qrlife"]


def test_cli_json_select_proofs_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax.numpy as jnp\n\n"
                   "def f_tiles(a, b):\n    return a * b\n")
    assert qrkernel_main([str(bad)]) == 1
    capsys.readouterr()
    rc = qrkernel_main([str(bad), "--format", "json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"]["error"] == 1
    (finding,) = payload["findings"]
    assert finding["rule"] == "kernel-int32-overflow"
    assert finding["path"] == str(bad) and finding["line"] == 4
    assert qrkernel_main([str(bad), "--select", "kernel-batch-axis"]) == 0
    assert qrkernel_main([str(bad), "--select", "no-such-rule"]) == 2
    capsys.readouterr()
    rc = qrkernel_main([str(bad), "--proofs"])
    out = capsys.readouterr().out
    assert rc == 1 and "unproven" in out


def test_list_rules(capsys):
    assert qrkernel_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("kernel-int32-overflow", "kernel-contract-violation",
                "kernel-shape-mismatch", "kernel-batch-axis",
                "kernel-grid-blockspec", "kernel-accum-dtype",
                "kernel-read-after-donate", "kernel-recompile-hazard",
                "kernel-unjustified-annotation"):
        assert rid in out


# -- the CI ratchet -----------------------------------------------------------


def test_live_codebase_is_violation_free(capsys):
    """The whole package passes qrkernel: every kernel site is proved,
    wrapping-annotated, or fixed.  New violations fail here AND in CI."""
    rc = qrkernel_main([str(PACKAGE)])
    out = capsys.readouterr().out
    assert rc == 0, f"qrkernel found new violations:\n{out}"


def test_live_run_is_fast_enough_for_ci():
    """Summaries are context-insensitive and memoized: the whole package
    must verify in seconds, not minutes (<30s perf gate)."""
    contexts = {str(p): FileContext(str(p), p.read_text(encoding="utf-8"))
                for p in sorted(PACKAGE.rglob("*.py"))}
    t0 = time.perf_counter()
    analysis = KernelAnalysis(Project(contexts))
    dt = time.perf_counter() - t0
    assert dt < 30.0, f"kernel abstract interpretation took {dt:.1f}s"
    assert analysis.interp.summaries  # the summary cache is actually in use


def test_accum_dtype_sees_augassign_accumulation():
    """The revisited-accumulation store shape (frodo_pallas's
    ``out_ref[...] += contrib``): same-kind integer promotion keeps the
    accumulated value's dtype across the AugAssign read-modify-write, so a
    narrower out ref still triggers; a matching int32 out ref is clean."""
    src = """
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def lwe_kernel(a_ref, s_ref, out_ref):
            a = a_ref[...].astype(jnp.int32) & 0xFFFF
            s = s_ref[...].astype(jnp.int32) & 0xFFFF
            contrib = a * s  # qrkernel: wrapping — int32 LWE product wraps mod 2^32; q | 2^32 so the masked result is exact
            out_ref[...] += contrib

        def launch(a, s):
            return pl.pallas_call(
                lwe_kernel, grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0)),
                          pl.BlockSpec((8, 128), lambda i: (i, 0))],
                out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
                out_shape=jax.ShapeDtypeStruct((8, 128), "{dt}"),
            )(a, s)
        """
    assert rule_ids(src.format(dt="int16")) == ["kernel-accum-dtype"]
    assert rule_ids(src.format(dt="int32")) == []


def test_read_after_donate_factory_vs_assigned_program():
    """The fused-program donation shapes: a module-level assigned donating
    program whose donated operand is read after the call triggers; the
    factory-return shape (fused/mlkem_mldsa.py — the jitted program never
    escapes into a module binding) is outside the static rule's reach and
    stays clean — its contract is enforced at runtime by ``donation_twin``
    (tests/test_fused.py donation-safety regression)."""
    assigned = """
        import jax

        def run(a, b, sig_in):
            return a + b, sig_in * 2

        prog = jax.jit(run, donate_argnums=(2,))

        def drive(a, b, sig):
            out, sigma = prog(a, b, sig)
            return out + sig
        """
    assert rule_ids(assigned) == ["kernel-read-after-donate"]
    # consuming only the outputs: clean
    assert rule_ids(assigned.replace("return out + sig", "return out + sigma")) == []
    factory = """
        import jax

        def get_program():
            def run(a, b, sig_in):
                return a + b, sig_in * 2
            # sig_in's buffer is aliased to the second output
            return jax.jit(run, donate_argnums=(2,))
        """
    assert rule_ids(factory) == []
