"""ML-KEM: JAX batch implementation vs the pure-Python FIPS 203 oracle."""

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.kem import mlkem
from quantum_resistant_p2p_tpu.pyref import mlkem_ref as ref

PARAM_NAMES = ["ML-KEM-512", "ML-KEM-768", "ML-KEM-1024"]


def _rand(rng, *shape):
    return rng.integers(0, 256, size=shape, dtype=np.uint8)


@pytest.mark.parametrize("name", PARAM_NAMES)
def test_cross_implementation_bit_exact(name):
    """keygen/encaps/decaps bit-exact vs the oracle for a batch of seeds."""
    p = ref.PARAMS[name]
    kg, enc, dec = mlkem.get(name)
    rng = np.random.default_rng(hash(name) % 2**32)
    B = 4
    d, z, m = _rand(rng, B, 32), _rand(rng, B, 32), _rand(rng, B, 32)

    ek_j, dk_j = map(np.asarray, kg(d, z))
    key_j, ct_j = map(np.asarray, enc(ek_j, m))
    key2_j = np.asarray(dec(dk_j, ct_j))

    for i in range(B):
        ek_r, dk_r = ref.keygen(p, d[i].tobytes(), z[i].tobytes())
        assert bytes(ek_j[i]) == ek_r, f"ek mismatch lane {i}"
        assert bytes(dk_j[i]) == dk_r, f"dk mismatch lane {i}"
        key_r, ct_r = ref.encaps(p, ek_r, m[i].tobytes())
        assert bytes(ct_j[i]) == ct_r, f"ct mismatch lane {i}"
        assert bytes(key_j[i]) == key_r, f"K mismatch lane {i}"
        assert bytes(key2_j[i]) == key_r, f"decaps K mismatch lane {i}"


@pytest.mark.parametrize("name", PARAM_NAMES)
def test_implicit_rejection(name):
    """Tampered ciphertext must yield J(z||c), matching the oracle."""
    p = ref.PARAMS[name]
    kg, enc, dec = mlkem.get(name)
    rng = np.random.default_rng(99)
    B = 4  # same batch shape as the cross-impl test -> shared jit cache
    d, z, m = _rand(rng, B, 32), _rand(rng, B, 32), _rand(rng, B, 32)
    ek, dk = map(np.asarray, kg(d, z))
    _, ct = map(np.asarray, enc(ek, m))
    bad = ct.copy()
    bad[:, 0] ^= 1
    key_bad = np.asarray(dec(dk, bad))
    for i in range(2):
        _, dk_r = ref.keygen(p, d[i].tobytes(), z[i].tobytes())
        want = ref.decaps(p, dk_r, bad[i].tobytes())
        assert bytes(key_bad[i]) == want


def test_sizes():
    for name in PARAM_NAMES:
        p = ref.PARAMS[name]
        kg, enc, dec = mlkem.get(name)
        rng = np.random.default_rng(1)
        B = 4
        d, z, m = _rand(rng, B, 32), _rand(rng, B, 32), _rand(rng, B, 32)
        ek, dk = kg(d, z)
        key, ct = enc(np.asarray(ek), m)
        assert ek.shape == (B, p.ek_len)
        assert dk.shape == (B, p.dk_len)
        assert ct.shape == (B, p.ct_len)
        assert key.shape == (B, 32)
