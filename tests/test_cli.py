"""CLI: login gate + command surface driven programmatically (no stdin)."""

import asyncio
import io

import pytest

pytest.importorskip("cryptography")  # the CLI unlocks the AES-GCM vault

from quantum_resistant_p2p_tpu.cli import CLI


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


def _mk(tmp_path, name, port=0):
    out = io.StringIO()
    cli = CLI(
        vault_path=str(tmp_path / f"{name}.vault.json"),
        port=port,
        backend="cpu",
        enable_discovery=False,
        out=out,
    )
    assert cli.login("pw-" + name)
    return cli, out


def test_two_clis_chat(run, tmp_path):
    async def main():
        a, a_out = _mk(tmp_path, "a")
        b, b_out = _mk(tmp_path, "b")
        await a.start()
        await b.start()
        assert await a.handle(f"/connect 127.0.0.1 {b.node.port}")
        await asyncio.sleep(0.05)
        peer_b = a.node.get_peers()[0]
        assert await a.handle(f"/key {peer_b[:8]}")
        assert "shared key established" in a_out.getvalue()
        assert await a.handle(f"/send {peer_b[:8]} hello from cli")
        for _ in range(100):
            if "hello from cli" in b_out.getvalue():
                break
            await asyncio.sleep(0.02)
        assert "hello from cli" in b_out.getvalue()

        # settings / metrics / logs / keyhistory surfaces all respond
        assert await a.handle("/settings")
        assert "ML-KEM-768" in a_out.getvalue()
        assert await a.handle("/metrics")
        assert await a.handle("/logs")
        assert "key_exchange" in a_out.getvalue()
        assert await a.handle("/keyhistory")
        assert "peer=" in a_out.getvalue()
        assert await a.handle("/set aead ChaCha20-Poly1305")
        assert a.messaging.symmetric.name == "ChaCha20-Poly1305"
        assert await a.handle("/peers")
        assert not await a.handle("/quit")
        await b.stop()

    run(main())


def test_trace_flight_and_prometheus_commands(run, tmp_path):
    """The obs/ surface: /trace exports loadable chrome://tracing JSON,
    /flight dumps a diagnostic bundle, /metrics prom emits the text
    exposition format (docs/observability.md)."""
    import json

    async def main():
        a, a_out = _mk(tmp_path, "obs")
        await a.start()
        tpath = tmp_path / "trace.json"
        assert await a.handle(f"/trace {tpath}")
        doc = json.loads(tpath.read_text())
        assert "traceEvents" in doc and doc["displayTimeUnit"] == "ms"
        fpath = tmp_path / "flight.json"
        assert await a.handle(f"/flight {fpath}")
        bundle = json.loads(fpath.read_text())
        assert bundle["trigger"] == "manual"
        assert "events" in bundle and "metrics" in bundle
        assert await a.handle("/metrics prom")
        assert "qrp2p_" in a_out.getvalue()
        assert await a.handle("/metrics")
        assert '"operational"' in a_out.getvalue()
        assert await a.handle("/slo")
        out = a_out.getvalue()
        assert '"handshake_p99"' in out and '"budget_remaining"' in out
        assert "ALERTING" not in out  # a fresh node has burned nothing
        assert not await a.handle("/quit")

    run(main())


def test_showkey_formats_warning_and_audit(run, tmp_path, monkeypatch):
    async def main():
        a, a_out = _mk(tmp_path, "a2")
        b, _ = _mk(tmp_path, "b2")
        await a.start()
        await b.start()
        await a.handle(f"/connect 127.0.0.1 {b.node.port}")
        await asyncio.sleep(0.05)
        peer_b = a.node.get_peers()[0]
        await a.handle(f"/key {peer_b[:8]}")
        entries = a.storage.list_key_history()
        assert entries
        name = entries[0]["name"]

        # declined confirmation: no key material shown, denial audited
        monkeypatch.setattr("builtins.input", lambda *_: "no")
        await a.handle(f"/showkey {name}")
        assert "cancelled" in a_out.getvalue()
        assert "hex:" not in a_out.getvalue()

        monkeypatch.setattr("builtins.input", lambda *_: "YES")
        await a.handle(f"/showkey {name}")
        assert "WARNING" in a_out.getvalue() and "hex:" in a_out.getvalue()
        await a.handle(f"/showkey {name} base64")
        assert "base64:" in a_out.getvalue()
        await a.handle(f"/showkey {name} decimal")
        assert "decimal:" in a_out.getvalue()
        # every access (granted and denied) is in the audit log
        accesses = [e for e in a.secure_logger.get_events(event_type="key_history_access")]
        assert len(accesses) == 4
        assert any(e.get("granted") is False for e in accesses)

        await a.stop()
        await b.stop()

    run(main())


def test_logs_time_and_type_filters(run, tmp_path):
    async def main():
        a, out = _mk(tmp_path, "logsf")
        await a.start()
        # startup line states native-core availability explicitly
        assert "native C++ core:" in out.getvalue()
        a.secure_logger.log_event("connection", peer="x")
        a.secure_logger.log_event("message_sent", peer="x")

        out.truncate(0), out.seek(0)
        await a.handle("/logs connection")
        assert "connection" in out.getvalue() and "message_sent" not in out.getvalue()

        out.truncate(0), out.seek(0)
        await a.handle("/logs --since 1h")
        assert "message_sent" in out.getvalue()

        out.truncate(0), out.seek(0)
        await a.handle("/logs --until 1h")  # everything is newer than 1h ago
        assert "(no events)" in out.getvalue()

        out.truncate(0), out.seek(0)
        await a.handle("/logs --since 23:59 --until 23:59")
        assert "(no events)" in out.getvalue()
        await a.stop()

    run(main())


def test_unknown_command_and_bad_args_keep_repl_alive(run, tmp_path):
    async def main():
        a, out = _mk(tmp_path, "solo")
        await a.start()
        assert await a.handle("/nope")
        assert "unknown command" in out.getvalue()
        assert await a.handle("/connect")  # IndexError -> caught
        assert "error:" in out.getvalue()
        assert await a.handle("not-a-command")
        await a.stop()

    run(main())
