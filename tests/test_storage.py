"""KeyStorage / SecureLogger / AtomicFile behavior tests (host-only, no JAX)."""

import json
import os
import time

import pytest

pytest.importorskip("cryptography")  # vault + audit log encrypt with AES-GCM

from quantum_resistant_p2p_tpu.storage import AtomicFile, KeyStorage, SecureLogger
from quantum_resistant_p2p_tpu.storage.key_storage import KeyStorageError


@pytest.fixture
def vault(tmp_path):
    ks = KeyStorage(tmp_path / "vault.json")
    assert ks.unlock("hunter2-long-pass")
    return ks


def test_unlock_wrong_password(tmp_path):
    ks = KeyStorage(tmp_path / "vault.json")
    assert ks.unlock("correct horse")
    ks.lock()
    assert not ks.is_unlocked
    ks2 = KeyStorage(tmp_path / "vault.json")
    assert not ks2.unlock("wrong pass")
    assert ks2.unlock("correct horse")


def test_store_retrieve_delete(vault):
    vault.store("alpha", {"x": 1})
    assert vault.retrieve("alpha") == {"x": 1}
    vault.store_bytes("blob", b"\x00\x01\xff")
    assert vault.retrieve_bytes("blob") == b"\x00\x01\xff"
    assert vault.delete("alpha")
    assert vault.retrieve("alpha") is None
    assert not vault.delete("alpha")


def test_names_not_on_disk(vault, tmp_path):
    vault.store("super_secret_entry_name", {"v": 1})
    raw = (tmp_path / "vault.json").read_text()
    assert "super_secret_entry_name" not in raw


def test_purpose_key_stable_and_survives_password_change(vault):
    k1 = vault.get_or_create_purpose_key("audit")
    assert len(k1) == 32
    assert vault.get_or_create_purpose_key("audit") == k1
    assert vault.change_password("hunter2-long-pass", "new-password-9")
    assert vault.get_or_create_purpose_key("audit") == k1


def test_change_password_requires_old(vault):
    assert not vault.change_password("nope", "x")


def test_key_history(vault):
    vault.save_peer_shared_key("peerA", b"k" * 32, "ML-KEM-768")
    time.sleep(0.01)
    vault.save_peer_shared_key("peerA", b"j" * 32, "ML-KEM-768")
    vault.save_peer_shared_key("peerB", b"i" * 32, "ML-KEM-1024")
    hist = vault.list_key_history()
    assert len(hist) == 3
    hist_a = vault.list_key_history("peerA")
    assert len(hist_a) == 2
    newest = vault.get_key_history_value(hist_a[0]["name"])
    assert newest["peer_id"] == "peerA"
    assert vault.clear_key_history() == 3
    assert vault.list_key_history() == []


def test_reset_storage(vault, tmp_path):
    vault.store("gone", {"v": 1})
    vault.reset_storage("fresh-password")
    assert vault.retrieve("gone") is None
    ks2 = KeyStorage(tmp_path / "vault.json")
    assert not ks2.unlock("hunter2-long-pass")
    assert ks2.unlock("fresh-password")


def test_locked_raises(tmp_path):
    ks = KeyStorage(tmp_path / "vault.json")
    with pytest.raises(KeyStorageError):
        ks.store("a", 1)


def test_atomic_file_backup_recovery(tmp_path):
    af = AtomicFile(tmp_path / "data.json")
    af.write_json({"gen": 1})
    af.write_json({"gen": 2})
    # corrupt the primary; read should fall back to the .bak (gen 1)
    (tmp_path / "data.json").write_text("{truncated")
    assert af.read_json() == {"gen": 1}


def test_secure_logger_roundtrip_and_metrics(tmp_path):
    key = os.urandom(32)
    sl = SecureLogger(key, tmp_path)
    sl.log_event("message_sent", size=100, algorithm="AES-256-GCM")
    sl.log_event("message_received", size=40, algorithm="AES-256-GCM")
    sl.log_event("key_exchange", algorithm="ML-KEM-768", peer="p1")
    events = sl.get_events()
    assert len(events) == 3
    assert sl.get_events(event_type="key_exchange")[0]["peer"] == "p1"
    summary = sl.get_event_summary()
    assert summary["message_sent"] == 1
    m = sl.get_security_metrics()
    assert m["bytes_sent"] == 100 and m["bytes_received"] == 40
    assert m["algorithms_used"]["AES-256-GCM"] == 2
    assert sl.clear_logs() == 1
    assert sl.get_events() == []


def test_secure_logger_corruption_recovery(tmp_path):
    key = os.urandom(32)
    sl = SecureLogger(key, tmp_path)
    sl.log_event("a")
    path = next(tmp_path.glob("*.qlog"))
    good = path.read_bytes()
    # splice garbage between two valid records
    sl.log_event("b")
    full = path.read_bytes()
    second = full[len(good):]
    path.write_bytes(good + b"\xde\xad\xbe\xef" + second)
    events = sl.get_events()
    assert [e["event_type"] for e in events] == ["a", "b"]


def test_secure_logger_wrong_key_reads_nothing(tmp_path):
    sl = SecureLogger(os.urandom(32), tmp_path)
    sl.log_event("a")
    sl2 = SecureLogger(os.urandom(32), tmp_path)
    assert sl2.get_events() == []
