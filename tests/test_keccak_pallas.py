"""Pallas Keccak kernel (interpret mode) vs the jnp implementation."""

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.core import keccak as jk
from quantum_resistant_p2p_tpu.core import keccak_pallas as kp

pytestmark = pytest.mark.skipif(not kp._HAVE_PALLAS, reason="no pallas")


@pytest.mark.parametrize("batch", [1, 128, 200])
def test_matches_jnp(batch):
    rng = np.random.default_rng(batch)
    hi = rng.integers(0, 2**32, size=(batch, 25), dtype=np.uint32)
    lo = rng.integers(0, 2**32, size=(batch, 25), dtype=np.uint32)
    ph, plo = kp.keccak_f1600(hi, lo, interpret=True)
    jh, jlo = jk.keccak_f1600(hi, lo)
    assert (np.asarray(ph) == np.asarray(jh)).all()
    assert (np.asarray(plo) == np.asarray(jlo)).all()
