"""Pallas sponge kernel vs hashlib, in interpret mode on CPU.

The kernel's native path is exercised on the real chip by bench.py and the
TPU provider; here interpret mode checks bit-exactness of the fused
absorb-permute-squeeze pipeline.

Slow tier: interpret mode simulates every vector op of the fully-unrolled
24-round network over (8, 128) tiles — minutes of wall time and tens of GB
of trace memory per case, even at tiny logical shapes.  The fast tier
covers the same byte-level behavior through the jnp sponge (test_keccak.py,
hashlib oracle); this module proves kernel==sponge and runs nightly.
"""

import hashlib

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.core import keccak
from quantum_resistant_p2p_tpu.core.keccak_pallas import sponge_words

pytestmark = pytest.mark.slow


def _run(msgs: np.ndarray, rate: int, ds: int, out_len: int) -> np.ndarray:
    b, msg_len = msgs.shape
    nblocks = msg_len // rate + 1
    padded = np.zeros((b, nblocks * rate), np.uint8)
    padded[:, :msg_len] = msgs
    padded[:, msg_len] = ds
    padded[:, -1] |= 0x80
    ph, plo = keccak._bytes_to_words(padded)
    n_sq = -(-out_len // rate)
    oh, ol = sponge_words(
        np.asarray(ph).T, np.asarray(plo).T, rate_words=rate // 8,
        n_abs=nblocks, n_sq=n_sq, interpret=True,
    )
    out = keccak._words_to_bytes(np.asarray(oh).T, np.asarray(ol).T)
    return np.asarray(out)[:, :out_len]


@pytest.mark.parametrize(
    "rate,ds,out_len,href,msg_len",
    [
        (168, 0x1F, 672, lambda b: hashlib.shake_128(b).digest(672), 34),
        (136, 0x1F, 32, lambda b: hashlib.shake_256(b).digest(32), 200),
        (72, 0x06, 64, lambda b: hashlib.sha3_512(b).digest(), 64),
    ],
    ids=["shake128-xof", "shake256-2absorb", "sha3-512"],
)
def test_sponge_words_matches_hashlib(rate, ds, out_len, href, msg_len):
    rng = np.random.default_rng(7)
    msgs = rng.integers(0, 256, (3, msg_len), np.uint8)
    got = _run(msgs, rate, ds, out_len)
    exp = np.stack([np.frombuffer(href(bytes(m)), np.uint8) for m in msgs])
    assert (got == exp).all()
