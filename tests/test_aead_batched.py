"""BatchedAEAD facade: queue coalescing, degrade paths, lanes, e2e wiring.

The data plane's facade (provider/batched.py ``BatchedAEAD``) must behave
exactly like the scalar AEAD at the byte level while riding the OpQueue →
scheduler → breaker machinery — and must degrade (never fail) when the
device path is cold, slow, or raising.  Wheel-less friendly: the scalar
twin is the pyref fallback.
"""

from __future__ import annotations

import asyncio
import os

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.provider import get_batched_aead, get_symmetric
from quantum_resistant_p2p_tpu.provider.batched import (LANE_BULK, BatchedAEAD,
                                                        LaneShed)


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


def _facade(**kw):
    device = get_batched_aead("ChaCha20-Poly1305")
    assert device is not None
    kw.setdefault("max_wait_ms", 1.0)
    kw.setdefault("warm_shapes", ((64, 16),))
    return BatchedAEAD(device, get_symmetric("ChaCha20-Poly1305"), **kw)


def test_capability_env_kill_switch(monkeypatch):
    monkeypatch.setenv("QRP2P_BATCH_AEAD", "0")
    assert get_batched_aead("ChaCha20-Poly1305") is None
    monkeypatch.delenv("QRP2P_BATCH_AEAD")
    assert get_batched_aead("ChaCha20-Poly1305") is not None
    # AES-GCM has no device kernel: capability absent, scalar path serves
    assert get_batched_aead("AES-256-GCM") is None


def test_facade_roundtrip_and_scalar_parity(run):
    f = _facade()
    scalar = f.scalar
    key = os.urandom(32)

    async def main():
        pts = [os.urandom(n) for n in (0, 1, 17, 48)]
        ads = [b"", b"ad", b"", b"x" * 12]
        outs = await asyncio.gather(
            *(f.encrypt(key, p, a) for p, a in zip(pts, ads)))
        for p, a, o in zip(pts, ads, outs):
            # the scalar twin opens facade output, and vice versa
            assert scalar.decrypt(key, o, a or None) == p
            assert await f.decrypt(key, o, a) == p
        assert await f.decrypt(key, scalar.encrypt(key, b"x", b"a"),
                               b"a") == b"x"
        # memoryview input (the binary wire's zero-copy slice)
        assert await f.decrypt(key, memoryview(outs[2]), ads[2]) == pts[2]

    run(main())


def test_warm_buckets_serve_from_device(run):
    f = _facade()
    key = os.urandom(32)

    async def main():
        # warmup compiles batch bucket 1 at the (64, 16) shape and marks
        # it; warm-shape traffic then rides the device path (sequential
        # sends -> size-1 flushes)
        await asyncio.get_running_loop().run_in_executor(None, f.warmup, (1,))
        for _ in range(4):
            out = await f.encrypt(key, b"warm-shape msg", b"ad")
            assert await f.decrypt(key, out, b"ad") == b"warm-shape msg"
        stats = f.stats()
        assert stats["seal"]["ops"] >= 4
        assert stats["seal"]["fallback_ops"] < stats["seal"]["ops"], (
            "warm traffic still served from the cpu fallback")

    run(main())


def test_cold_length_bucket_degrades_not_trips(run):
    """A novel (msg, aad) length bucket on a warm batch bucket must serve
    from the fallback while the background warm compiles the live shape —
    never jit inside a live dispatch and trip the breaker as 'slow'."""
    f = _facade()
    key = os.urandom(32)

    async def main():
        await asyncio.get_running_loop().run_in_executor(None, f.warmup, (1,))
        big = os.urandom(3000)  # novel L bucket (4096)
        out = await f.encrypt(key, big, b"ad")
        assert await f.decrypt(key, out, b"ad") == big
        assert f.breaker.state == "closed"
        # the shape warms in the background; poll until the device covers
        # it, then traffic moves off the fallback
        for _ in range(200):
            if f.device.covers(True, 1, len(big), 2):
                break
            await asyncio.sleep(0.1)
        assert f.device.covers(True, 1, len(big), 2)
        fb0 = f.stats()["seal"]["fallback_ops"]
        out2 = await f.encrypt(key, os.urandom(3000), b"ad")
        assert len(out2) == 12 + 3000 + 16
        assert f.stats()["seal"]["fallback_ops"] == fb0

    run(main())


def test_tampered_item_fails_alone(run):
    f = _facade()
    key = os.urandom(32)

    async def main():
        good = await f.encrypt(key, b"good", b"")
        bad = bytearray(await f.encrypt(key, b"evil", b""))
        bad[20] ^= 0xFF
        results = await asyncio.gather(
            f.decrypt(key, bytes(bad)), f.decrypt(key, good),
            return_exceptions=True)
        assert isinstance(results[0], ValueError)
        assert results[1] == b"good"

    run(main())


def test_oversized_items_take_scalar_path(run):
    f = _facade()
    key = os.urandom(32)

    async def main():
        big = os.urandom(f.device.max_len + 1)
        out = await f.encrypt(key, big, b"ad")
        assert f.scalar.decrypt(key, out, b"ad") == big
        assert await f.decrypt(key, out, b"ad") == big
        # never touched the queues
        assert f.stats()["seal"]["ops"] == 0

    run(main())


def test_bulk_lane_capacity_sheds_loudly(run):
    f = _facade(lane_capacity={LANE_BULK: 2}, max_wait_ms=50.0)
    key = os.urandom(32)

    async def main():
        sends = [asyncio.create_task(f.encrypt(key, b"m%d" % i))
                 for i in range(6)]
        results = await asyncio.gather(*sends, return_exceptions=True)
        sheds = [r for r in results if isinstance(r, LaneShed)]
        ok = [r for r in results if isinstance(r, bytes)]
        assert sheds and ok
        assert f.stats()["seal"]["lane_sheds"].get("bulk", 0) == len(sheds)

    run(main())


def test_breaker_open_serves_fallback(run):
    f = _facade()
    key = os.urandom(32)

    async def main():
        f.breaker.trip()
        assert f.breaker.is_open()
        out = await f.encrypt(key, b"degraded", b"")
        assert await f.decrypt(key, out) == b"degraded"
        stats = f.stats()
        assert stats["seal"]["fallback_ops"] >= 1
        assert stats["open"]["fallback_ops"] >= 1

    run(main())


def test_facade_queues_include_aead(run):
    from quantum_resistant_p2p_tpu.provider.batched import facade_queues

    f = _facade()
    labels = {q.label for q in facade_queues(f)}
    assert labels == {"ChaCha20-Poly1305.seal", "ChaCha20-Poly1305.open"}


def test_aead_dispatch_is_a_fault_boundary(run):
    """A chaos plan can target the AEAD device dispatch by op label — the
    fault raises at the boundary and the breaker/fallback machinery serves
    the op anyway (degrade, not fail)."""
    from quantum_resistant_p2p_tpu.faults import FaultPlan, FaultRule

    f = _facade()
    key = os.urandom(32)

    async def main():
        await asyncio.get_running_loop().run_in_executor(None, f.warmup, (1,))
        plan = FaultPlan(seed=3, rules=[
            FaultRule("device.dispatch", "raise",
                      match={"op": "ChaCha20-Poly1305.seal"}, nth=1),
        ])
        with plan.activate():
            out = await f.encrypt(key, b"chaos msg", b"ad")
        assert await f.decrypt(key, out, b"ad") == b"chaos msg"
        assert any(e["op"] == "ChaCha20-Poly1305.seal" for e in plan.injected)
        assert f.stats()["seal"]["fallback_ops"] >= 1

    run(main())
