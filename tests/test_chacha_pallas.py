"""Batched ChaCha20-Poly1305 KATs: RFC 8439 vectors + scalar-twin parity.

The device data plane (core/chacha_pallas.py) must be bit-exact against
the pure-Python scalar twin (pyref/chacha_ref.py) — and, when the OpenSSL
wheel is present, against the ``cryptography`` package — at EVERY length
bucket, masked-tail edge (15/16/17-byte plaintexts), and AAD shape
(including empty AAD).  Fast tier runs the jnp twin; the Pallas kernel's
interpret-mode equality is slow-tier (interpret mode simulates every
vector op, like the keccak kernel tests).
"""

from __future__ import annotations

import importlib.util

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.core import chacha_pallas as cp
from quantum_resistant_p2p_tpu.pyref import chacha_ref as ref

# -- RFC 8439 vectors ---------------------------------------------------------

#: §2.8.2 AEAD vector
KEY = bytes(range(0x80, 0xA0))
NONCE = bytes([0x07, 0, 0, 0]) + bytes(range(0x40, 0x48))
AAD = bytes.fromhex("50515253c0c1c2c3c4c5c6c7")
PLAINTEXT = (b"Ladies and Gentlemen of the class of '99: If I could offer "
             b"you only one tip for the future, sunscreen would be it.")
CT_HEX = (
    "d31a8d34648e60db7b86afbc53ef7ec2a4aded51296e08fea9e2b5a736ee62d6"
    "3dbea45e8ca9671282fafb69da92728b1a71de0a9e060b2905d6a5b67ecd3b36"
    "92ddbd7f2d778b8c9803aee328091b58fab324e4fad675945585808b4831d7bc"
    "3ff4def08e4b7a9de576d26586cec64b6116"
)
TAG_HEX = "1ae10b594f09e26a7e902ecbd0600691"

#: §2.3.2 block function vector
BLOCK_KEY = bytes(range(32))
BLOCK_NONCE = bytes.fromhex("000000090000004a00000000")
BLOCK_OUT_HEX = (
    "10f1e7e4d13b5915500fdd1fa32071c4c7d1f4c733c068030422aa9ac3d46c4e"
    "d2826446079faa0914c2d705d98b02a2b5129cd1de164eb9cbd083e8a2503c4e"
)

#: §2.5.2 Poly1305 vector
POLY_KEY = bytes.fromhex(
    "85d6be7857556d337f4452fe42d506a80103808afb0db2fd4abff6af4149f51b")
POLY_MSG = b"Cryptographic Forum Research Group"
POLY_TAG = bytes.fromhex("a8061dc1305136c6c22b8baf0c0127a9")


def _seal_batch(keys, nonces, datas, aads, *, seal=True, use_pallas=False,
                interpret=False):
    """Pad a ragged batch to its pow2 buckets and run the jitted core."""
    from quantum_resistant_p2p_tpu.utils import next_pow2

    b = len(datas)
    l_bucket = 64 * next_pow2(max(1, max(-(-len(d) // 64) for d in datas)))
    a_bucket = 16 * next_pow2(max(1, max(-(-len(a) // 16) for a in aads)))
    data = np.zeros((b, l_bucket), np.uint8)
    aad = np.zeros((b, a_bucket), np.uint8)
    for i, (d, a) in enumerate(zip(datas, aads)):
        data[i, : len(d)] = np.frombuffer(d, np.uint8)
        aad[i, : len(a)] = np.frombuffer(a, np.uint8)
    out, tags = cp.aead_core(
        np.stack([np.frombuffer(k, np.uint8) for k in keys]),
        np.stack([np.frombuffer(n, np.uint8) for n in nonces]),
        data, np.array([len(d) for d in datas], np.int32),
        aad, np.array([len(a) for a in aads], np.int32),
        seal=seal, use_pallas=use_pallas, interpret=interpret,
    )
    return np.asarray(out), np.asarray(tags)


# -- pyref scalar twin vs the spec -------------------------------------------


def test_pyref_block_function_rfc_2_3_2():
    assert ref.chacha20_block(BLOCK_KEY, 1, BLOCK_NONCE).hex() == BLOCK_OUT_HEX


def test_pyref_poly1305_rfc_2_5_2():
    assert ref.poly1305_mac(POLY_KEY, POLY_MSG) == POLY_TAG


def test_pyref_aead_rfc_2_8_2():
    sealed = ref.seal(KEY, NONCE, PLAINTEXT, AAD)
    assert sealed[:-16].hex() == CT_HEX
    assert sealed[-16:].hex() == TAG_HEX
    assert ref.open_(KEY, NONCE, sealed, AAD) == PLAINTEXT
    bad = bytes([sealed[0] ^ 1]) + sealed[1:]
    with pytest.raises(ValueError):
        ref.open_(KEY, NONCE, bad, AAD)


# -- batched jnp core vs the spec and the twin --------------------------------


def test_device_core_rfc_2_8_2():
    out, tags = _seal_batch([KEY], [NONCE], [PLAINTEXT], [AAD])
    assert bytes(out[0][: len(PLAINTEXT)]).hex() == CT_HEX
    assert bytes(tags[0]).hex() == TAG_HEX
    # padded region stays zero (masked tail)
    assert not out[0][len(PLAINTEXT):].any()


#: every bucket edge the masking must get right: empty, sub-block,
#: one-byte-each-side of the 16-byte Poly1305 and 64-byte ChaCha blocks,
#: and across the pow2 length-bucket boundaries
TAIL_LENS = [0, 1, 15, 16, 17, 31, 32, 63, 64, 65, 127, 128, 129, 255, 256]


def test_device_core_masked_tails_match_pyref():
    rng = np.random.default_rng(7)
    keys = [bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in TAIL_LENS]
    nonces = [bytes(rng.integers(0, 256, 12, dtype=np.uint8)) for _ in TAIL_LENS]
    pts = [bytes(rng.integers(0, 256, n, dtype=np.uint8)) for n in TAIL_LENS]
    # every third item has EMPTY aad; the rest sweep aad block edges
    aads = [b"" if i % 3 == 0
            else bytes(rng.integers(0, 256, 5 * i + 1, dtype=np.uint8))
            for i in range(len(TAIL_LENS))]
    out, tags = _seal_batch(keys, nonces, pts, aads)
    for i, n in enumerate(TAIL_LENS):
        expect = ref.seal(keys[i], nonces[i], pts[i], aads[i])
        assert bytes(out[i][:n]) == expect[:-16], f"ct mismatch at len {n}"
        assert bytes(tags[i]) == expect[-16:], (
            f"tag mismatch at len {n}, aad {len(aads[i])}")


def test_device_core_open_path_and_tag_recompute():
    rng = np.random.default_rng(11)
    keys = [bytes(rng.integers(0, 256, 32, dtype=np.uint8)) for _ in range(4)]
    nonces = [bytes(rng.integers(0, 256, 12, dtype=np.uint8)) for _ in range(4)]
    pts = [bytes(rng.integers(0, 256, n, dtype=np.uint8))
           for n in (17, 64, 100, 200)]
    aads = [b"", b"a", b"ad" * 10, b"x" * 33]
    sealed = [ref.seal(k, n, p, a) for k, n, p, a in zip(keys, nonces, pts, aads)]
    out, tags = _seal_batch(keys, nonces, [s[:-16] for s in sealed], aads,
                            seal=False)
    for i, p in enumerate(pts):
        assert bytes(out[i][: len(p)]) == p
        assert bytes(tags[i]) == sealed[i][-16:]


def test_per_bucket_sizes_are_bit_exact():
    """One seal per bucket size (batch of 1 at each L bucket) — the shape
    the live queue compiles is exactly the shape the KAT pins.  The 64-,
    128- and 256-byte buckets are already covered batch-wise by the
    masked-tail sweep above; this pins the batch-1 programs at the
    buckets bracketing it (compile time is the suite's budget currency,
    so the sweep is minimal-but-bracketing)."""
    rng = np.random.default_rng(3)
    for n in (40, 700):
        key = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        nonce = bytes(rng.integers(0, 256, 12, dtype=np.uint8))
        pt = bytes(rng.integers(0, 256, n, dtype=np.uint8))
        out, tags = _seal_batch([key], [nonce], [pt], [b"bucket-aad"])
        assert (bytes(out[0][:n]) + bytes(tags[0])
                == ref.seal(key, nonce, pt, b"bucket-aad"))


# -- cross-check vs the OpenSSL wheel (skipped wheel-less) --------------------


@pytest.mark.skipif(importlib.util.find_spec("cryptography") is None,
                    reason="cryptography wheel not installed")
def test_device_core_matches_cryptography_wheel():
    from cryptography.hazmat.primitives.ciphers.aead import (
        ChaCha20Poly1305 as WheelChaCha)

    rng = np.random.default_rng(5)
    for n in (0, 16, 17, 64, 129):
        key = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        nonce = bytes(rng.integers(0, 256, 12, dtype=np.uint8))
        pt = bytes(rng.integers(0, 256, n, dtype=np.uint8))
        out, tags = _seal_batch([key], [nonce], [pt], [b"wheel-aad"])
        assert (bytes(out[0][:n]) + bytes(tags[0])
                == WheelChaCha(key).encrypt(nonce, pt, b"wheel-aad"))


# -- device capability + scalar provider -------------------------------------


def test_chacha_device_capability_roundtrip():
    from quantum_resistant_p2p_tpu.provider.aead_device import ChaChaPolyDevice

    dev = ChaChaPolyDevice(use_pallas=False)
    rng = np.random.default_rng(9)
    keys = rng.integers(0, 256, (3, 32), dtype=np.uint8)
    nonces = rng.integers(0, 256, (3, 12), dtype=np.uint8)
    pts = [b"", b"short", bytes(rng.integers(0, 256, 99, dtype=np.uint8))]
    aads = [b"", b"ad", b""]
    sealed = dev.seal_batch(keys, nonces, pts, aads)
    for i, s in enumerate(sealed):
        assert s == ref.seal(bytes(keys[i]), bytes(nonces[i]), pts[i], aads[i])
    opened = dev.open_batch(keys, nonces, sealed, aads)
    assert opened == pts
    # one tampered item fails alone — its batch mates still open
    bad = list(sealed)
    bad[1] = bytes([bad[1][0] ^ 0xFF]) + bad[1][1:]
    results = dev.open_batch(keys, nonces, bad, aads)
    assert results[0] == pts[0] and results[2] == pts[2]
    assert isinstance(results[1], ValueError)


def test_scalar_provider_wheel_less_fallback():
    """The registry's scalar ChaCha20-Poly1305 works without the OpenSSL
    wheel (pyref twin) and is KAT-exact + wire-compatible both ways."""
    from quantum_resistant_p2p_tpu.provider import get_symmetric

    algo = get_symmetric("ChaCha20-Poly1305")
    assert algo.seal(KEY, NONCE, PLAINTEXT, AAD).hex() == CT_HEX + TAG_HEX
    blob = algo.encrypt(KEY, b"scalar wire", b"ad")
    assert algo.decrypt(KEY, blob, b"ad") == b"scalar wire"
    # pyref opens what the provider sealed (format: nonce || ct || tag)
    assert ref.open_(KEY, blob[:12], blob[12:], b"ad") == b"scalar wire"
    with pytest.raises(ValueError):
        algo.decrypt(KEY, blob[:-1] + bytes([blob[-1] ^ 1]), b"ad")
    with pytest.raises(ValueError):
        algo.decrypt(b"short", blob, b"ad")


def test_aead_health_probe_passes_and_rejects_broken_device():
    from quantum_resistant_p2p_tpu.provider import get_symmetric
    from quantum_resistant_p2p_tpu.provider.aead_device import ChaChaPolyDevice
    from quantum_resistant_p2p_tpu.provider.health import _check_aead

    class _Facade:
        def __init__(self):
            self.device = ChaChaPolyDevice(use_pallas=False)
            self.scalar = get_symmetric("ChaCha20-Poly1305")
            self.name = self.device.name

    facade = _Facade()
    verdict = _check_aead(facade)
    assert verdict.ok, verdict.detail

    # a device computing wrong bytes must FAIL the gate (quarantine path)
    broken = _Facade()
    good_seal = broken.device.seal_batch

    def bad_seal(keys, nonces, pts, aads):
        out = good_seal(keys, nonces, pts, aads)
        return [bytes(len(s)) for s in out]

    broken.device.seal_batch = bad_seal
    assert not _check_aead(broken).ok


# -- Pallas kernel (interpret mode; slow tier like the keccak kernel) --------


@pytest.mark.slow
def test_pallas_kernel_matches_jnp_twin_and_spec():
    out, tags = _seal_batch([KEY], [NONCE], [PLAINTEXT], [AAD],
                            use_pallas=True, interpret=True)
    assert bytes(out[0][: len(PLAINTEXT)]).hex() == CT_HEX
    assert bytes(tags[0]).hex() == TAG_HEX


@pytest.mark.slow
def test_pallas_block_launcher_matches_jnp():
    rng = np.random.default_rng(13)
    states = rng.integers(0, 2 ** 32, (12, 7), dtype=np.uint32)
    a = np.asarray(cp.chacha_blocks(states, interpret=True))
    b = np.asarray(cp.chacha_blocks_jnp(states))
    assert (a == b).all()
