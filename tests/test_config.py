"""Config precedence: kwargs > env > file > defaults."""

import json

from quantum_resistant_p2p_tpu.config import Config


def test_defaults():
    c = Config.load(path="/nonexistent/config.json")
    assert c.kem == "ML-KEM-768" and c.backend == "auto" and c.port == 8000


def test_file_env_override(tmp_path, monkeypatch):
    p = tmp_path / "config.json"
    p.write_text(json.dumps({"kem": "HQC-128", "port": 9000, "unknown_key": 1}))
    monkeypatch.setenv("QRP2P_PORT", "9100")
    monkeypatch.setenv("QRP2P_USE_BATCHING", "true")
    c = Config.load(path=p, port=9200)
    assert c.kem == "HQC-128"  # file
    assert c.use_batching is True  # env bool
    assert c.port == 9200  # kwarg beats env beats file


def test_malformed_file_falls_back(tmp_path):
    p = tmp_path / "config.json"
    p.write_text("{not json")
    c = Config.load(path=p)
    assert c.kem == "ML-KEM-768"


def test_save_roundtrip(tmp_path):
    c = Config.load(path="/nonexistent")
    c.kem = "FrodoKEM-640-AES"
    out = c.save(tmp_path / "cfg.json")
    c2 = Config.load(path=out)
    assert c2.kem == "FrodoKEM-640-AES"
