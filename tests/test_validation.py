"""Input-length validation at the provider boundary (ADVICE r1, high).

Attacker-controlled public keys / ciphertexts of the wrong length must raise
ValueError at the plugin boundary — BEFORE reaching the native C++ core
(which reads fixed lengths from the buffer it is handed: a short pk would be
a heap out-of-bounds read) or the JAX backends (opaque reshape errors).
The reference gets this for free from liboqs's internal checks
(vendor/oqs.py:332-381); here it is the provider's job.
"""

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.provider import get_kem, get_signature


@pytest.mark.parametrize("name", ["ML-KEM-768", "FrodoKEM-640-AES", "HQC-128"])
def test_kem_scalar_rejects_bad_lengths(name):
    kem = get_kem(name, "cpu")
    pk, sk = kem.generate_keypair()
    ct, _ = kem.encapsulate(pk)

    with pytest.raises(ValueError):
        kem.encapsulate(pk[:-1])
    with pytest.raises(ValueError):
        kem.encapsulate(pk + b"\x00")
    with pytest.raises(ValueError):
        kem.encapsulate(b"")
    with pytest.raises(ValueError):
        kem.decapsulate(sk, ct[:-1])
    with pytest.raises(ValueError):
        kem.decapsulate(sk[:-1], ct)

    # well-formed input still round-trips
    ss = kem.decapsulate(sk, ct)
    assert len(ss) == kem.shared_secret_len


@pytest.mark.parametrize("name", ["ML-KEM-512"])
def test_kem_batch_rejects_bad_shapes(name):
    kem = get_kem(name, "cpu")
    pks, sks = kem.generate_keypair_batch(2)
    cts, _ = kem.encapsulate_batch(pks)

    with pytest.raises(ValueError):
        kem.encapsulate_batch(pks[:, :-1])
    with pytest.raises(ValueError):
        kem.decapsulate_batch(sks, cts[:, :-1])
    with pytest.raises(ValueError):
        kem.decapsulate_batch(sks[:, 1:], cts)


def test_signature_sign_rejects_bad_sk_and_verify_returns_false():
    sig = get_signature("ML-DSA-44", "cpu")
    pk, sk = sig.generate_keypair()
    with pytest.raises(ValueError):
        sig.sign(sk[:-1], b"msg")
    s = sig.sign(sk, b"msg")
    # verify never raises on malformed input — contract is False
    assert sig.verify(pk[:-1], b"msg", s) is False
    assert sig.verify(pk, b"msg", s[:-1]) is False
    assert sig.verify(pk, b"msg", s) is True


def test_sphincs_tpu_verify_batch_normalizes_2d_signature_elements():
    """Scalar verify wraps operands as (1, L) arrays; verify_batch's digest
    derivation must byte-slice the NORMALIZED rows, not the raw elements
    (a (1, L) element row-slices to the whole signature and poisons h_msg's
    randomizer — the scalar tpu-verify path returned False for every valid
    signature until round 3)."""
    sig_alg = get_signature("SPHINCS+-SHA2-128s-simple", backend="tpu")
    p = sig_alg.params
    rng = np.random.default_rng(3)
    pk = rng.integers(0, 256, (1, p.pk_len), dtype=np.uint8)
    sig_flat = rng.integers(0, 256, (p.sig_len,), dtype=np.uint8)
    seen = []

    def fake_verify(pks, digests, sigs):
        seen.append(np.asarray(digests).copy())
        return np.ones(len(np.asarray(pks)), dtype=bool)

    sig_alg._verify_digest = fake_verify
    sig_alg._mesh = None
    sig_alg.verify_batch(pk, [b"m"], [sig_flat])          # 1-D element
    sig_alg.verify_batch(pk, [b"m"], [sig_flat[None]])    # (1, L) element
    assert (seen[0] == seen[1]).all(), "2-D element changed the derived digest"


def test_sphincs_tpu_sign_batch_sliced_at_compile_ceiling():
    """s-set sign dispatches are capped at the measured compile ceiling
    (_SLH_MAX_SIGN_BATCH): a queue-sized batch must arrive as fixed-size
    slices, never as one giant program the compile helper cannot build."""
    from quantum_resistant_p2p_tpu.provider import sig_providers

    sig_alg = get_signature("SPHINCS+-SHA2-256s-simple", backend="tpu")
    p = sig_alg.params
    cap = sig_providers._SLH_MAX_SIGN_BATCH[p.name]
    assert cap == 32
    batches = []

    def fake_sign(sks, rs, digests):
        batches.append(len(np.asarray(sks)))
        return np.zeros((len(np.asarray(sks)), p.sig_len), np.uint8)

    sig_alg._sign_digest = fake_sign
    sig_alg._mesh = None
    n = 70
    rng = np.random.default_rng(5)
    sks = rng.integers(0, 256, (n, p.sk_len), dtype=np.uint8)
    out = sig_alg.sign_batch(sks, [b"m%d" % i for i in range(n)])
    assert len(out) == n
    assert batches == [cap, cap, cap]  # 70 rows -> 3 padded slices of 32


def test_sphincs_tpu_sign_batch_mesh_keeps_global_cap():
    """With a provider mesh, the sign cap stays a GLOBAL bound: the compile
    ceiling limits the whole traced program, so the per-device step must be
    cap // mesh.size, never cap per device."""
    from quantum_resistant_p2p_tpu.provider import sig_providers

    sig_alg = get_signature("SPHINCS+-SHA2-256s-simple", backend="tpu", devices=8)
    assert sig_alg._mesh is not None and sig_alg._mesh.size == 8
    p = sig_alg.params
    cap = sig_providers._SLH_MAX_SIGN_BATCH[p.name]  # 32
    batches = []

    def fake_sign(sks, rs, digests):
        b = len(sks)
        batches.append(b)
        import jax.numpy as jnp

        return jnp.zeros((b, p.sig_len), jnp.uint8)

    sig_alg._sign_digest = fake_sign
    n = 70
    rng = np.random.default_rng(6)
    sks = rng.integers(0, 256, (n, p.sk_len), dtype=np.uint8)
    out = sig_alg.sign_batch(sks, [b"m%d" % i for i in range(n)])
    assert len(out) == n
    assert max(batches) <= cap  # global dispatch never exceeds the ceiling
    assert sum(batches) >= n
