"""qrlint self-tests: every rule pack fires on a minimal bad fixture, stays
quiet on the good twin, honours inline suppression — and the live codebase
is violation-free (the CI ratchet this suite exists to keep taut)."""

from __future__ import annotations

import json
import textwrap
from pathlib import Path

import pytest

from tools.analysis import default_rules
from tools.analysis.engine import Engine
from tools.analysis.run import main as qrlint_main

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "quantum_resistant_p2p_tpu"


def lint(source: str):
    findings, suppressed = Engine(default_rules()).lint_source(textwrap.dedent(source))
    return findings, suppressed


def rule_ids(source: str) -> list[str]:
    return [f.rule for f in lint(source)[0]]


# -- secret-hygiene pack ------------------------------------------------------


def test_secret_in_log_fires_on_logging_sink():
    ids = rule_ids(
        """
        import logging
        logger = logging.getLogger(__name__)

        def unlock(secret_key):
            logger.info("derived %s", secret_key)
        """
    )
    assert ids == ["secret-in-log"]


def test_secret_in_log_fires_on_exception_and_repr_and_fstring():
    src = """
        def f(master_key):
            raise ValueError(master_key)

        def g(shared_key):
            return repr(shared_key)

        def h(entry_key):
            return f"state: {entry_key!r}"
        """
    assert rule_ids(src) == ["secret-in-log"] * 3


def test_secret_in_log_allows_sanitized_and_public_values():
    ids = rule_ids(
        """
        import logging
        logger = logging.getLogger(__name__)

        def f(secret_key, public_key):
            logger.info("have %d-byte key", len(secret_key))
            logger.info("peer pk %s", public_key.hex())
        """
    )
    assert ids == []


def test_secret_in_log_suppression():
    findings, suppressed = lint(
        """
        import logging
        logger = logging.getLogger(__name__)

        def f(secret_key):
            logger.debug("kat trace %s", secret_key)  # qrlint: disable=secret-in-log
        """
    )
    assert not findings
    assert [s.rule for s in suppressed] == ["secret-in-log"]


def test_zeroize_incomplete_fires_and_clean_twin_passes():
    bad = """
        class Vault:
            def __init__(self, key):
                self._master_key = key
                self._aead = AESGCM(key)

            def zeroize(self):
                self._master_key = None
        """
    assert rule_ids(bad) == ["zeroize-incomplete"]
    good = bad.replace(
        "self._master_key = None",
        "self._master_key = None\n                self._aead = None",
    )
    assert rule_ids(good) == []


# -- jax-kernel pack ----------------------------------------------------------


def test_traced_branch_fires_inside_jit():
    ids = rule_ids(
        """
        import jax

        @jax.jit
        def f(x):
            if x > 0:
                return x
            return -x
        """
    )
    assert ids == ["traced-branch"]


def test_traced_branch_allows_shape_and_static_argnames():
    ids = rule_ids(
        """
        import functools
        import jax

        @jax.jit
        def f(x):
            if x.shape[0] > 8:
                return x
            return -x

        @functools.partial(jax.jit, static_argnames=("n",))
        def g(x, n):
            if n > 0:
                return x
            return -x
        """
    )
    assert ids == []


def test_int32_narrowing_fires_on_tile_multiply():
    ids = rule_ids(
        """
        from jax.experimental import pallas as pl

        def _square_kernel(a_ref, out_ref):
            a = a_ref[...]
            out_ref[...] = a * a
        """
    )
    assert ids == ["int32-narrowing"]


def test_int32_narrowing_allows_host_scalars_and_suppression():
    findings, suppressed = lint(
        """
        from jax.experimental import pallas as pl

        def _scale_kernel(a_ref, out_ref, n: int):
            stride = n * 4
            a = a_ref[...]
            out_ref[...] = a * a  # qrlint: disable=int32-narrowing — fixture: inputs bounded by 2**15
        """
    )
    assert not findings
    assert [s.rule for s in suppressed] == ["int32-narrowing"]


def test_host_sync_fires_on_item_inside_jit():
    ids = rule_ids(
        """
        import jax

        @jax.jit
        def f(x):
            return x.item()
        """
    )
    assert ids == ["host-sync"]


# -- asyncio-discipline pack --------------------------------------------------


def test_dangling_task_fires_on_discarded_create_task():
    ids = rule_ids(
        """
        import asyncio

        async def main(worker):
            asyncio.create_task(worker())
        """
    )
    assert ids == ["dangling-task"]


def test_dangling_task_allows_stored_reference():
    ids = rule_ids(
        """
        import asyncio

        async def main(worker):
            task = asyncio.create_task(worker())
            await task
        """
    )
    assert ids == []


def test_unawaited_coroutine_fires():
    ids = rule_ids(
        """
        async def worker():
            pass

        def main():
            worker()
        """
    )
    assert ids == ["unawaited-coroutine"]


def test_blocking_in_async_fires_on_sleep_open_and_sync_lock():
    ids = rule_ids(
        """
        import time

        async def f(path, lock):
            time.sleep(0.05)
            open(path)
            path.read_bytes()
            lock.acquire()
        """
    )
    assert ids == ["blocking-in-async"] * 4


def test_blocking_calls_fine_outside_async():
    ids = rule_ids(
        """
        import time

        def f(path):
            time.sleep(0.05)
            return open(path)
        """
    )
    assert ids == []


def test_broad_except_fires_when_silent_and_passes_when_logged():
    bad = """
        def f(g):
            try:
                g()
            except Exception:
                pass
        """
    assert rule_ids(bad) == ["broad-except"]
    good = """
        import logging
        logger = logging.getLogger(__name__)

        def f(g):
            try:
                g()
            except Exception:
                logger.exception("g failed")
        """
    assert rule_ids(good) == []


def test_bare_except_fires_even_with_logging():
    # bare except swallows CancelledError; logging does not excuse it
    ids = rule_ids(
        """
        import logging

        def f(g):
            try:
                g()
            except:
                logging.error("boom")
        """
    )
    assert ids == ["broad-except"]


# -- provider-contract pack (cross-file) --------------------------------------

_BASE = """
import abc


class KeyExchangeAlgorithm(abc.ABC):
    @abc.abstractmethod
    def encapsulate(self, public_key):
        ...

    def encapsulate_batch(self, public_keys):
        return [self.encapsulate(pk) for pk in public_keys]
"""

_REGISTRY = """
from .impls import BadKEM, GoodKEM


def register_kem(name, factory, backends=None):
    pass


register_kem("good", lambda: GoodKEM())
register_kem("bad", lambda: BadKEM())
"""

_IMPLS = """
from .base import KeyExchangeAlgorithm


class GoodKEM(KeyExchangeAlgorithm):
    def encapsulate(self, public_key):
        return b""


class BadKEM(KeyExchangeAlgorithm):
    def encapsulate_batch(self, keys):
        return []
"""


def _write_provider_tree(tmp_path: Path) -> Path:
    pkg = tmp_path / "provider"
    pkg.mkdir()
    (pkg / "base.py").write_text(_BASE)
    (pkg / "registry.py").write_text(_REGISTRY)
    (pkg / "impls.py").write_text(_IMPLS)
    return pkg


def test_provider_contract_flags_missing_and_renamed(tmp_path):
    pkg = _write_provider_tree(tmp_path)
    findings, _ = Engine(default_rules()).lint_paths([pkg])
    contract = [f for f in findings if f.rule == "provider-contract"]
    messages = "\n".join(f.message for f in contract)
    assert "BadKEM" in messages and "encapsulate()" in messages
    assert "encapsulate_batch(keys)" in messages  # renamed positional param
    assert "GoodKEM" not in messages


_FUSED_BASE = """
import abc


class FusedHandshakeOps(abc.ABC):
    @abc.abstractmethod
    def keygen_sign_batch(self, sig_sks, templates, pk_off, rnd=None):
        ...

    @abc.abstractmethod
    def decaps_verify_sign_batch(self, secret_keys, ciphertexts,
                                 peer_sig_pks, msgs_in, sigs_in, sig_sks,
                                 msgs_out, rnd=None):
        ...

    def warmup(self, sizes=(1,), pk_off=None, ct_off=None):
        pass
"""

_FUSED_REGISTRY = """
from .impls import DriftedFused, GoodFused, SuppressedFused


def register_fused(kem_name, sig_name, factory):
    pass


register_fused("ML-KEM-768", "ML-DSA-65", lambda kem, sig: GoodFused(kem, sig))
register_fused("ML-KEM-512", "ML-DSA-44", lambda kem, sig: DriftedFused(kem, sig))
register_fused("ML-KEM-1024", "ML-DSA-87",
               lambda kem, sig: SuppressedFused(kem, sig))
"""

_FUSED_IMPLS = """
from .base import FusedHandshakeOps


class GoodFused(FusedHandshakeOps):
    def __init__(self, kem, sig):
        pass

    def keygen_sign_batch(self, sig_sks, templates, pk_off, rnd=None):
        return None

    def decaps_verify_sign_batch(self, secret_keys, ciphertexts,
                                 peer_sig_pks, msgs_in, sigs_in, sig_sks,
                                 msgs_out, rnd=None):
        return None


class DriftedFused(FusedHandshakeOps):
    def __init__(self, kem, sig):
        pass

    # positional drift: the composite queue forwards these positionally
    def keygen_sign_batch(self, sks, tmpls, offset, rnd=None):
        return None


class SuppressedFused(FusedHandshakeOps):  # qrlint: disable=provider-contract  — capability implemented in a C extension shim
    def __init__(self, kem, sig):
        pass

    def keygen_sign_batch(self, sig_sks, templates, pk_off, rnd=None):
        return None
"""


def test_provider_contract_covers_fused_capability(tmp_path):
    """register_fused binds implementations to the FusedHandshakeOps
    capability surface: missing composite ops and positional drift are
    flagged, a conforming class is clean, inline suppression holds."""
    pkg = tmp_path / "provider"
    pkg.mkdir()
    (pkg / "base.py").write_text(_FUSED_BASE)
    (pkg / "registry.py").write_text(_FUSED_REGISTRY)
    (pkg / "impls.py").write_text(_FUSED_IMPLS)
    findings, suppressed = Engine(default_rules()).lint_paths([pkg])
    contract = [f for f in findings if f.rule == "provider-contract"]
    messages = "\n".join(f.message for f in contract)
    # trigger: DriftedFused misses one abstract op and renames positionals
    assert "DriftedFused" in messages
    assert "decaps_verify_sign_batch()" in messages
    assert "keygen_sign_batch(sks, tmpls, offset, rnd)" in messages
    # clean: the conforming implementation draws no findings
    assert "GoodFused" not in messages
    # suppressed: the annotated class is reported as suppressed, not live
    assert "SuppressedFused" not in messages
    assert any(s.rule == "provider-contract" and "SuppressedFused" in s.message
               for s in suppressed)


# -- dispatch/breaker discipline ----------------------------------------------


def test_dispatch_except_no_breaker_fires_on_swallowed_dispatch_failure():
    """Trigger: an except around a device dispatch (a batch_fn call or a
    device-executor submission) that neither re-raises nor records the
    failure to the breaker."""
    # scope to the rule under test: the same fixtures legitimately also
    # trip the independent broad-except rule
    ids = [i for i in rule_ids(
        """
        class Q:
            def run(self, items):
                try:
                    return self.batch_fn(items)
                except Exception:
                    return None

            async def run2(self, loop, items):
                try:
                    fut = loop.run_in_executor(self.breaker.device_executor,
                                               self.batch_fn, items)
                    return await fut
                except TimeoutError:
                    return None
        """
    ) if i == "dispatch-except-no-breaker"]
    assert ids == ["dispatch-except-no-breaker"] * 2


def test_dispatch_except_no_breaker_clean_when_recorded_or_reraised():
    """Clean twins: recording to the breaker (record_failure / trip / a
    *_trip_breaker helper) or re-raising satisfies the rule; narrow
    excepts and non-dispatch try bodies are out of scope."""
    ids = [i for i in rule_ids(
        """
        class Q:
            def run(self, items):
                try:
                    return self.batch_fn(items)
                except Exception:
                    self.breaker.record_failure("device")
                    return None

            def run2(self, items):
                try:
                    return self.batch_fn(items)
                except Exception as exc:
                    self._trip_breaker("raised", 0.0, "device")
                    return None

            def run3(self, items):
                try:
                    return self.batch_fn(items)
                except Exception:
                    raise

            def run4(self, items):
                try:
                    return self.batch_fn(items)
                except ValueError:   # narrow: not this rule's concern
                    return None

            def run5(self, items):
                try:
                    return self.other_fn(items)  # not a dispatch
                except Exception:
                    return None
        """
    ) if i == "dispatch-except-no-breaker"]
    assert ids == []


def test_dispatch_except_no_breaker_covers_placed_dispatches():
    """Trigger (sharded crypto plane): ``run_placed`` is the scheduler's
    placement boundary — one placed device program — so an except that
    swallows its failure without recording to the PLACED shard's breaker
    leaves that shard's degrade/heal machinery blind."""
    ids = [i for i in rule_ids(
        """
        class Q:
            def run(self, shard, items):
                try:
                    return shard.run_placed(self.batch_fn, items)
                except Exception:
                    return None
        """
    ) if i == "dispatch-except-no-breaker"]
    assert ids == ["dispatch-except-no-breaker"]


def test_dispatch_except_no_breaker_placed_clean_when_shard_breaker_records():
    """Clean twin: recording the failure to the per-shard breaker (the
    object run_placed's shard carries) satisfies the rule."""
    ids = [i for i in rule_ids(
        """
        class Q:
            def run(self, shard, items):
                try:
                    return shard.run_placed(self.batch_fn, items)
                except Exception:
                    shard.breaker.record_failure("device")
                    return None
        """
    ) if i == "dispatch-except-no-breaker"]
    assert ids == []


def test_dispatch_except_no_breaker_placed_suppression():
    findings, suppressed = lint(
        """
        class Q:
            def run(self, shard, items):
                try:
                    return shard.run_placed(self.batch_fn, items)
                except Exception:  # qrlint: disable=dispatch-except-no-breaker, broad-except
                    return None
        """
    )
    assert [f.rule for f in findings] == []
    assert sorted(s.rule for s in suppressed) == [
        "broad-except", "dispatch-except-no-breaker"]


def test_dispatch_except_no_breaker_suppression():
    findings, suppressed = lint(
        """
        class Q:
            def run(self, items):
                try:
                    return self.batch_fn(items)
                except Exception:  # qrlint: disable=dispatch-except-no-breaker, broad-except
                    return None
        """
    )
    assert [f.rule for f in findings] == []
    assert sorted(s.rule for s in suppressed) == [
        "broad-except", "dispatch-except-no-breaker"]


def test_dispatch_except_no_breaker_covers_lane_drain_dispatch():
    """Trigger (gateway lanes, ISSUE 8): a lane-priority drain helper that
    dispatches its drained batch is still a device dispatch — an except
    swallowing its failure without recording to the breaker hides a
    degraded lane exactly like any other flush."""
    ids = [i for i in rule_ids(
        """
        class LaneQueue:
            def drain_and_dispatch(self, lane):
                items = [it for it, ln in self._pending if ln == lane]
                try:
                    return self.batch_fn(items)
                except Exception:
                    return [None] * len(items)   # lane silently degraded
        """
    ) if i == "dispatch-except-no-breaker"]
    assert ids == ["dispatch-except-no-breaker"]


def test_dispatch_except_no_breaker_lane_drain_clean_and_suppressed():
    clean = """
        class LaneQueue:
            def drain_and_dispatch(self, lane):
                items = [it for it, ln in self._pending if ln == lane]
                try:
                    return self.batch_fn(items)
                except Exception:
                    self.breaker.record_failure("device")
                    return [None] * len(items)
        """
    assert "dispatch-except-no-breaker" not in rule_ids(clean)
    findings, suppressed = lint(
        """
        class LaneQueue:
            def drain_and_dispatch(self, lane):
                items = [it for it, ln in self._pending if ln == lane]
                try:
                    return self.batch_fn(items)
                except Exception:  # qrlint: disable=dispatch-except-no-breaker, broad-except
                    return [None] * len(items)
        """
    )
    assert [f.rule for f in findings] == []
    assert sorted(s.rule for s in suppressed) == [
        "broad-except", "dispatch-except-no-breaker"]


def test_dispatch_except_no_breaker_covers_fleet_probe_call():
    """Trigger (gateway fleet, ISSUE 11): ``_probe_call`` is the fleet
    breaker's half-open canary dispatch — one control round-trip to a
    maybe-dead gateway.  An except swallowing its failure without
    recording to that member's breaker leaves the breaker half-open
    forever: the fleet-scope twin of a swallowed device canary."""
    ids = [i for i in rule_ids(
        """
        class Fleet:
            async def probe(self, member, n):
                try:
                    await self._probe_call(member, n)
                except Exception:
                    return None   # member stuck half-open forever
        """
    ) if i == "dispatch-except-no-breaker"]
    assert ids == ["dispatch-except-no-breaker"]


def test_dispatch_except_no_breaker_fleet_probe_clean_and_suppressed():
    clean = """
        class Fleet:
            async def probe(self, member, n):
                try:
                    await self._probe_call(member, n)
                except Exception:
                    member.breaker.record_failure("probe")
                    return None
        """
    assert "dispatch-except-no-breaker" not in rule_ids(clean)
    findings, suppressed = lint(
        """
        class Fleet:
            async def probe(self, member, n):
                try:
                    await self._probe_call(member, n)
                except Exception:  # qrlint: disable=dispatch-except-no-breaker, broad-except
                    return None
        """
    )
    assert [f.rule for f in findings] == []
    assert sorted(s.rule for s in suppressed) == [
        "broad-except", "dispatch-except-no-breaker"]


# -- engine mechanics ---------------------------------------------------------


def test_file_level_suppression():
    findings, suppressed = lint(
        """
        # qrlint: disable-file=broad-except

        def f(g):
            try:
                g()
            except Exception:
                pass

        def h(g):
            try:
                g()
            except Exception:
                pass
        """
    )
    assert not findings
    assert [s.rule for s in suppressed] == ["broad-except"] * 2


def test_multi_rule_suppression_on_one_line():
    findings, _ = lint(
        """
        import asyncio

        async def main(worker):
            asyncio.create_task(worker())  # qrlint: disable=dangling-task, unawaited-coroutine
        """
    )
    assert not findings


def test_findings_carry_location_and_json_shape(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("async def f():\n    import time\n    time.sleep(1)\n")
    rc = qrlint_main([str(bad), "--json"])
    assert rc == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["counts"]["error"] == 1
    (finding,) = payload["findings"]
    assert finding["rule"] == "blocking-in-async"
    assert finding["path"] == str(bad) and finding["line"] == 3


# -- the CI ratchet -----------------------------------------------------------


def test_cli_exits_nonzero_on_violation(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(g):\n    try:\n        g()\n    except Exception:\n        pass\n")
    assert qrlint_main([str(bad)]) == 1
    bad.write_text("def f(g):\n    g()\n")
    assert qrlint_main([str(bad)]) == 0
    capsys.readouterr()


def test_cli_select_and_unknown_rule(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("async def f():\n    import time\n    time.sleep(1)\n")
    # selecting an unrelated rule skips the finding; unknown ids are an error
    assert qrlint_main([str(bad), "--select", "broad-except"]) == 0
    assert qrlint_main([str(bad), "--select", "no-such-rule"]) == 2
    capsys.readouterr()


def test_live_codebase_is_violation_free(capsys):
    """The whole package lints clean: every historical finding is either
    fixed or carries a justified inline suppression.  New violations fail
    here AND in the CI qrlint step."""
    rc = qrlint_main([str(PACKAGE)])
    out = capsys.readouterr().out
    assert rc == 0, f"qrlint found new violations:\n{out}"
