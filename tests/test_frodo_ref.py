"""FrodoKEM pure-Python oracle: sizes + roundtrip + implicit rejection."""

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.pyref import frodo_ref as fr

RNG = np.random.default_rng(64)


def _rand(n):
    return bytes(RNG.integers(0, 256, size=n, dtype=np.uint8))


@pytest.mark.parametrize("name", ["FrodoKEM-640-AES", "FrodoKEM-640-SHAKE"])
def test_roundtrip(name):
    if "AES" in name:
        pytest.importorskip("cryptography")  # AES matrix expansion
    p = fr.PARAMS[name]
    pk, sk = fr.keygen(p, _rand(p.len_sec), _rand(p.len_sec), _rand(p.len_sec))
    assert len(pk) == p.pk_len and len(sk) == p.sk_len
    mu = _rand(p.len_sec)
    ct, ss = fr.encaps(p, pk, mu)
    assert len(ct) == p.ct_len and len(ss) == p.len_sec
    assert fr.decaps(p, sk, ct) == ss
    # implicit rejection: corrupt ciphertext -> pseudorandom, not an error
    bad = bytearray(ct)
    bad[5] ^= 0xFF
    ss_bad = fr.decaps(p, sk, bytes(bad))
    assert ss_bad != ss and len(ss_bad) == p.len_sec


def test_determinism():
    pytest.importorskip("cryptography")  # AES matrix expansion
    p = fr.PARAMS["FrodoKEM-640-AES"]
    seeds = (_rand(p.len_sec), _rand(p.len_sec), _rand(p.len_sec))
    assert fr.keygen(p, *seeds) == fr.keygen(p, *seeds)
