"""BatchedProvider: futures resolve correctly, ops coalesce into batches."""

import asyncio

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.provider import get_kem, get_signature
from quantum_resistant_p2p_tpu.provider.batched import (BatchedKEM,
    BatchedSignature, Breaker, OpQueue)


def test_opqueue_coalesces_and_resolves():
    calls = []

    def batch_fn(items):
        calls.append(len(items))
        return [x * 2 for x in items]

    async def run():
        q = OpQueue(batch_fn, max_batch=64, max_wait_ms=5.0)
        outs = await asyncio.gather(*(q.submit(i) for i in range(10)))
        return outs

    outs = asyncio.run(run())
    assert outs == [i * 2 for i in range(10)]
    assert sum(calls) == 10
    assert len(calls) <= 2  # coalesced, not one flush per op


def test_opqueue_max_batch_triggers_immediate_flush():
    calls = []

    def batch_fn(items):
        calls.append(len(items))
        return items

    async def run():
        q = OpQueue(batch_fn, max_batch=4, max_wait_ms=1000.0)  # rely on size trigger
        await asyncio.gather(*(q.submit(i) for i in range(8)))

    asyncio.run(run())
    assert calls and max(calls) <= 4 and sum(calls) == 8


def test_opqueue_propagates_errors():
    def batch_fn(items):
        raise RuntimeError("boom")

    async def run():
        q = OpQueue(batch_fn, max_batch=4, max_wait_ms=1.0)
        with pytest.raises(RuntimeError):
            await q.submit(1)

    asyncio.run(run())


def test_batched_kem_end_to_end():
    kem = BatchedKEM(get_kem("ML-KEM-768", backend="tpu"), max_batch=8, max_wait_ms=2.0)

    async def run():
        pairs = await asyncio.gather(*(kem.generate_keypair() for _ in range(4)))
        encs = await asyncio.gather(*(kem.encapsulate(pk) for pk, _ in pairs))
        decs = await asyncio.gather(
            *(kem.decapsulate(sk, ct) for (_, sk), (ct, _) in zip(pairs, encs))
        )
        return encs, decs

    encs, decs = asyncio.run(run())
    for (ct, ss), ss2 in zip(encs, decs):
        assert ss == ss2
    st = kem.stats()
    assert st["encaps"]["ops"] == 4 and st["encaps"]["flushes"] >= 1


def test_batched_signature_end_to_end():
    sig = BatchedSignature(get_signature("ML-DSA-44", backend="tpu"),
                           max_batch=8, max_wait_ms=2.0)
    pk, sk = sig.algo.generate_keypair()

    async def run():
        msgs = [b"m%d" % i for i in range(3)]
        sigs = await asyncio.gather(*(sig.sign(sk, m) for m in msgs))
        oks = await asyncio.gather(*(sig.verify(pk, m, s) for m, s in zip(msgs, sigs)))
        bad = await sig.verify(pk, b"other", sigs[0])
        return oks, bad

    oks, bad = asyncio.run(run())
    assert all(oks) and not bad


def test_opqueue_slow_dispatch_trips_breaker_to_fallback():
    """A slow device dispatch opens the breaker; later flushes run on the
    fallback, and after the cool-off the device path is probed again."""
    import time as _time

    device_calls, fb_calls = [], []

    def slow_device(items):
        device_calls.append(len(items))
        _time.sleep(0.05)  # > degrade_after_ms
        return [("dev", x) for x in items]

    def fallback(items):
        fb_calls.append(len(items))
        return [("cpu", x) for x in items]

    async def run():
        q = OpQueue(slow_device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=fallback, degrade_after_ms=10.0,
                    dispatch_timeout_ms=5000.0, breaker=Breaker(cooloff_s=0.2))
        q._warm_buckets.add(1)  # steady state: bucket already compiled
        a = await q.submit(1)            # slow -> served by device, trips breaker
        b = await q.submit(2)            # breaker open -> fallback
        c = await q.submit(3)            # still open -> fallback
        await asyncio.sleep(0.25)        # cool-off expires
        d = await q.submit(4)            # probe: device again (still slow)
        e = await q.submit(5)            # re-opened -> fallback
        return a, b, c, d, e, q.stats

    a, b, c, d, e, st = asyncio.run(run())
    assert a == ("dev", 1) and d == ("dev", 4)
    assert b == ("cpu", 2) and c == ("cpu", 3) and e == ("cpu", 5)
    assert st.fallback_ops == 3 and st.breaker_trips == 2
    assert device_calls == [1, 1] and fb_calls == [1, 1, 1]


def test_opqueue_hung_dispatch_times_out_to_fallback():
    """A hung device call is abandoned (finishes in background) and its ops
    are served by the fallback — no waiter ever fails."""
    import threading

    hang = threading.Event()

    def hung_device(items):
        hang.wait(5.0)
        return [("dev", x) for x in items]

    def fallback(items):
        return [("cpu", x) for x in items]

    async def run():
        q = OpQueue(hung_device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=fallback, degrade_after_ms=1000.0,
                    dispatch_timeout_ms=50.0, breaker=Breaker(cooloff_s=10.0))
        q._warm_buckets.add(1)  # steady state: device path is live
        out = await asyncio.wait_for(q.submit(7), timeout=2.0)
        st = q.stats
        return out, st

    out, st = asyncio.run(run())
    hang.set()  # release the background thread
    assert out == ("cpu", 7)
    assert st.fallback_ops == 1 and st.breaker_trips == 1


def test_batched_kem_fallback_results_interoperate():
    """cpu-fallback results are protocol-compatible with the device path:
    a keypair produced through the fallback decapsulates a device-encaps."""
    tpu = get_kem("ML-KEM-512", backend="tpu")
    cpu = get_kem("ML-KEM-512", backend="cpu")

    # Force every flush onto the fallback via an always-open breaker.
    kem = BatchedKEM(tpu, max_batch=4, max_wait_ms=1.0, fallback=cpu,
                     degrade_after_ms=0.0, cooloff_s=60.0)
    for q in (kem._kg, kem._enc, kem._dec):
        q._warm_buckets.add(1)  # cold-compile exemption off: trip on any slow

    async def run():
        pk, sk = await kem.generate_keypair()   # device (trips breaker after)
        ct, ss = await kem.encapsulate(pk)      # fallback (cpu)
        ss2 = await kem.decapsulate(sk, ct)     # fallback (cpu)
        return ss, ss2, kem.stats()

    ss, ss2, st = asyncio.run(run())
    assert ss == ss2
    assert st["encaps"]["fallback_ops"] + st["decaps"]["fallback_ops"] >= 1


def test_opqueue_cold_bucket_serves_fallback_and_warms_in_background():
    """A cold bucket's ops are served by the fallback immediately (never
    hostage to a jit compile); the device warms in the background and takes
    over once the bucket is marked warm."""
    import time as _time

    def device(items):
        _time.sleep(0.02)  # "compile"
        return [("dev", x) for x in items]

    async def run():
        q = OpQueue(device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=lambda items: [("cpu", x) for x in items],
                    degrade_after_ms=5000.0, dispatch_timeout_ms=10000.0,
                    breaker=Breaker(cooloff_s=60.0))
        a = await q.submit(1)                  # cold: fallback, warm-up starts
        assert a == ("cpu", 1) and q.breaker.trips == 0
        for _ in range(100):                   # wait for background warm-up
            if 1 in q._warm_buckets:
                break
            await asyncio.sleep(0.02)
        assert 1 in q._warm_buckets
        b = await q.submit(2)                  # warm: device path
        assert b == ("dev", 2)
        return q.stats

    st = asyncio.run(run())
    assert st.fallback_ops == 1 and st.breaker_trips == 0
