"""BatchedProvider: futures resolve correctly, ops coalesce into batches."""

import asyncio

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.provider import get_kem, get_signature
from quantum_resistant_p2p_tpu.provider.batched import BatchedKEM, BatchedSignature, OpQueue


def test_opqueue_coalesces_and_resolves():
    calls = []

    def batch_fn(items):
        calls.append(len(items))
        return [x * 2 for x in items]

    async def run():
        q = OpQueue(batch_fn, max_batch=64, max_wait_ms=5.0)
        outs = await asyncio.gather(*(q.submit(i) for i in range(10)))
        return outs

    outs = asyncio.run(run())
    assert outs == [i * 2 for i in range(10)]
    assert sum(calls) == 10
    assert len(calls) <= 2  # coalesced, not one flush per op


def test_opqueue_max_batch_triggers_immediate_flush():
    calls = []

    def batch_fn(items):
        calls.append(len(items))
        return items

    async def run():
        q = OpQueue(batch_fn, max_batch=4, max_wait_ms=1000.0)  # rely on size trigger
        await asyncio.gather(*(q.submit(i) for i in range(8)))

    asyncio.run(run())
    assert calls and max(calls) <= 4 and sum(calls) == 8


def test_opqueue_propagates_errors():
    def batch_fn(items):
        raise RuntimeError("boom")

    async def run():
        q = OpQueue(batch_fn, max_batch=4, max_wait_ms=1.0)
        with pytest.raises(RuntimeError):
            await q.submit(1)

    asyncio.run(run())


def test_batched_kem_end_to_end():
    kem = BatchedKEM(get_kem("ML-KEM-768", backend="tpu"), max_batch=8, max_wait_ms=2.0)

    async def run():
        pairs = await asyncio.gather(*(kem.generate_keypair() for _ in range(4)))
        encs = await asyncio.gather(*(kem.encapsulate(pk) for pk, _ in pairs))
        decs = await asyncio.gather(
            *(kem.decapsulate(sk, ct) for (_, sk), (ct, _) in zip(pairs, encs))
        )
        return encs, decs

    encs, decs = asyncio.run(run())
    for (ct, ss), ss2 in zip(encs, decs):
        assert ss == ss2
    st = kem.stats()
    assert st["encaps"]["ops"] == 4 and st["encaps"]["flushes"] >= 1


def test_batched_signature_end_to_end():
    sig = BatchedSignature(get_signature("ML-DSA-44", backend="tpu"),
                           max_batch=8, max_wait_ms=2.0)
    pk, sk = sig.algo.generate_keypair()

    async def run():
        msgs = [b"m%d" % i for i in range(3)]
        sigs = await asyncio.gather(*(sig.sign(sk, m) for m in msgs))
        oks = await asyncio.gather(*(sig.verify(pk, m, s) for m, s in zip(msgs, sigs)))
        bad = await sig.verify(pk, b"other", sigs[0])
        return oks, bad

    oks, bad = asyncio.run(run())
    assert all(oks) and not bad
