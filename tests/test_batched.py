"""BatchedProvider: futures resolve correctly, ops coalesce into batches."""

import asyncio

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.provider import get_kem, get_signature
from quantum_resistant_p2p_tpu.provider.batched import (BatchedKEM,
    BatchedSignature, Breaker, OpQueue)


def test_opqueue_coalesces_and_resolves():
    calls = []

    def batch_fn(items):
        calls.append(len(items))
        return [x * 2 for x in items]

    async def run():
        q = OpQueue(batch_fn, max_batch=64, max_wait_ms=5.0)
        outs = await asyncio.gather(*(q.submit(i) for i in range(10)))
        return outs

    outs = asyncio.run(run())
    assert outs == [i * 2 for i in range(10)]
    assert sum(calls) == 10
    assert len(calls) <= 2  # coalesced, not one flush per op


def test_opqueue_max_batch_triggers_immediate_flush():
    calls = []

    def batch_fn(items):
        calls.append(len(items))
        return items

    async def run():
        q = OpQueue(batch_fn, max_batch=4, max_wait_ms=1000.0)  # rely on size trigger
        await asyncio.gather(*(q.submit(i) for i in range(8)))

    asyncio.run(run())
    assert calls and max(calls) <= 4 and sum(calls) == 8


def test_opqueue_propagates_errors():
    def batch_fn(items):
        raise RuntimeError("boom")

    async def run():
        q = OpQueue(batch_fn, max_batch=4, max_wait_ms=1.0)
        with pytest.raises(RuntimeError):
            await q.submit(1)

    asyncio.run(run())


def test_batched_kem_end_to_end():
    kem = BatchedKEM(get_kem("ML-KEM-768", backend="tpu"), max_batch=8, max_wait_ms=2.0)

    async def run():
        pairs = await asyncio.gather(*(kem.generate_keypair() for _ in range(4)))
        encs = await asyncio.gather(*(kem.encapsulate(pk) for pk, _ in pairs))
        decs = await asyncio.gather(
            *(kem.decapsulate(sk, ct) for (_, sk), (ct, _) in zip(pairs, encs))
        )
        return encs, decs

    encs, decs = asyncio.run(run())
    for (ct, ss), ss2 in zip(encs, decs):
        assert ss == ss2
    st = kem.stats()
    assert st["encaps"]["ops"] == 4 and st["encaps"]["flushes"] >= 1


def test_batched_signature_end_to_end():
    sig = BatchedSignature(get_signature("ML-DSA-44", backend="tpu"),
                           max_batch=8, max_wait_ms=2.0)
    pk, sk = sig.algo.generate_keypair()

    async def run():
        msgs = [b"m%d" % i for i in range(3)]
        sigs = await asyncio.gather(*(sig.sign(sk, m) for m in msgs))
        oks = await asyncio.gather(*(sig.verify(pk, m, s) for m, s in zip(msgs, sigs)))
        bad = await sig.verify(pk, b"other", sigs[0])
        return oks, bad

    oks, bad = asyncio.run(run())
    assert all(oks) and not bad


def test_opqueue_slow_dispatch_trips_breaker_to_fallback():
    """A slow device dispatch opens the breaker; later flushes run on the
    fallback, and after the cool-off the device path is probed again."""
    import time as _time

    device_calls, fb_calls = [], []

    def slow_device(items):
        device_calls.append(len(items))
        _time.sleep(0.05)  # > degrade_after_ms
        return [("dev", x) for x in items]

    def fallback(items):
        fb_calls.append(len(items))
        return [("cpu", x) for x in items]

    async def run():
        q = OpQueue(slow_device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=fallback, degrade_after_ms=10.0,
                    dispatch_timeout_ms=5000.0, breaker=Breaker(cooloff_s=0.2))
        q._warm_buckets.add(1)  # steady state: bucket already compiled
        a = await q.submit(1)            # slow -> served by device, trips breaker
        b = await q.submit(2)            # breaker open -> fallback
        c = await q.submit(3)            # still open -> fallback
        await asyncio.sleep(0.25)        # cool-off expires
        d = await q.submit(4)            # probe: device again (still slow)
        e = await q.submit(5)            # re-opened -> fallback
        return a, b, c, d, e, q.stats

    a, b, c, d, e, st = asyncio.run(run())
    assert a == ("dev", 1) and d == ("dev", 4)
    assert b == ("cpu", 2) and c == ("cpu", 3) and e == ("cpu", 5)
    assert st.fallback_ops == 3 and st.breaker_trips == 2
    assert device_calls == [1, 1] and fb_calls == [1, 1, 1]


def test_opqueue_hung_dispatch_times_out_to_fallback():
    """A hung device call is abandoned (finishes in background) and its ops
    are served by the fallback — no waiter ever fails."""
    import threading

    hang = threading.Event()

    def hung_device(items):
        hang.wait(5.0)
        return [("dev", x) for x in items]

    def fallback(items):
        return [("cpu", x) for x in items]

    async def run():
        q = OpQueue(hung_device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=fallback, degrade_after_ms=1000.0,
                    dispatch_timeout_ms=50.0, breaker=Breaker(cooloff_s=10.0))
        q._warm_buckets.add(1)  # steady state: device path is live
        out = await asyncio.wait_for(q.submit(7), timeout=2.0)
        st = q.stats
        return out, st

    out, st = asyncio.run(run())
    hang.set()  # release the background thread
    assert out == ("cpu", 7)
    assert st.fallback_ops == 1 and st.breaker_trips == 1


def test_batched_kem_fallback_results_interoperate():
    """cpu-fallback results are protocol-compatible with the device path:
    a keypair produced through the fallback decapsulates a device-encaps."""
    tpu = get_kem("ML-KEM-512", backend="tpu")
    cpu = get_kem("ML-KEM-512", backend="cpu")

    # Force every flush onto the fallback via an always-open breaker.
    kem = BatchedKEM(tpu, max_batch=4, max_wait_ms=1.0, fallback=cpu,
                     degrade_after_ms=0.0, cooloff_s=60.0)
    for q in (kem._kg, kem._enc, kem._dec):
        q._warm_buckets.add(1)  # cold-compile exemption off: trip on any slow

    async def run():
        pk, sk = await kem.generate_keypair()   # device (trips breaker after)
        ct, ss = await kem.encapsulate(pk)      # fallback (cpu)
        ss2 = await kem.decapsulate(sk, ct)     # fallback (cpu)
        return ss, ss2, kem.stats()

    ss, ss2, st = asyncio.run(run())
    assert ss == ss2
    assert st["encaps"]["fallback_ops"] + st["decaps"]["fallback_ops"] >= 1


def test_opqueue_cold_bucket_serves_fallback_and_warms_in_background():
    """A cold bucket's ops are served by the fallback immediately (never
    hostage to a jit compile); the device warms in the background and takes
    over once the bucket is marked warm."""
    import time as _time

    def device(items):
        _time.sleep(0.02)  # "compile"
        return [("dev", x) for x in items]

    async def run():
        q = OpQueue(device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=lambda items: [("cpu", x) for x in items],
                    degrade_after_ms=5000.0, dispatch_timeout_ms=10000.0,
                    breaker=Breaker(cooloff_s=60.0))
        a = await q.submit(1)                  # cold: fallback, warm-up starts
        assert a == ("cpu", 1) and q.breaker.trips == 0
        for _ in range(100):                   # wait for background warm-up
            if 1 in q._warm_buckets:
                break
            await asyncio.sleep(0.02)
        assert 1 in q._warm_buckets
        b = await q.submit(2)                  # warm: device path
        assert b == ("dev", 2)
        return q.stats

    st = asyncio.run(run())
    assert st.fallback_ops == 1 and st.breaker_trips == 0


def test_opqueue_fallback_while_open_never_touches_device():
    """Every flush while the breaker is open runs on the fallback: the
    device fn is never called, and the breaker's aggregate fallback-trip
    counter advances once per flush."""
    device_calls, fb_calls = [], []

    def device(items):
        device_calls.append(len(items))
        return [("dev", x) for x in items]

    async def run():
        br = Breaker(cooloff_s=60.0)
        q = OpQueue(device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=lambda items: (
                        fb_calls.append(len(items)) or [("cpu", x) for x in items]
                    ),
                    breaker=br)
        q._warm_buckets.add(1)
        br.trip()  # device declared slow by a sibling queue
        outs = [await q.submit(i) for i in range(3)]
        return outs, q.stats, br

    outs, st, br = asyncio.run(run())
    assert outs == [("cpu", i) for i in range(3)]
    assert device_calls == [] and fb_calls == [1, 1, 1]
    assert st.fallback_flushes == 3 and st.device_trips == 0
    assert br.fallback_trips == 3 and br.device_trips == 0


def test_opqueue_warmup_failure_keeps_fallback_then_recovers():
    """A failed cold-compile must not poison the queue: the bucket stays
    cold (ops keep flowing through the fallback), and a later flush retries
    the warm-up on the warmup executor until it succeeds."""
    attempts = []

    def flaky_device(items):
        attempts.append(len(items))
        if len(attempts) == 1:
            raise RuntimeError("compile OOM")  # first warm-up dies
        return [("dev", x) for x in items]

    async def run():
        q = OpQueue(flaky_device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=lambda items: [("cpu", x) for x in items],
                    breaker=Breaker(cooloff_s=60.0))
        a = await q.submit(1)           # cold: fallback; warm-up #1 fails
        for _ in range(100):
            if len(attempts) >= 1 and not q._warming:
                break
            await asyncio.sleep(0.02)
        assert 1 not in q._warm_buckets  # failure did NOT mark the bucket warm
        b = await q.submit(2)           # still cold: fallback; warm-up #2 runs
        for _ in range(100):
            if 1 in q._warm_buckets:
                break
            await asyncio.sleep(0.02)
        assert 1 in q._warm_buckets
        c = await q.submit(3)           # warm now: device
        return a, b, c, q.stats

    a, b, c, st = asyncio.run(run())
    assert a == ("cpu", 1) and b == ("cpu", 2) and c == ("dev", 3)
    assert len(attempts) == 3           # 2 warm-ups + 1 live device flush
    assert st.breaker_trips == 0        # cold-compile is not a degradation


def test_opqueue_warmup_watchdog_unsticks_hung_compile():
    """A hung warm-up must not pin the bucket in _warming forever: after the
    watchdog fires, a later flush retries the warm-up."""
    import threading

    release = threading.Event()
    attempts = []

    def device(items):
        attempts.append(len(items))
        if len(attempts) == 1:
            release.wait(10.0)  # first warm-up hangs
        return [("dev", x) for x in items]

    async def run():
        q = OpQueue(device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=lambda items: [("cpu", x) for x in items],
                    breaker=Breaker(cooloff_s=60.0))
        q.warmup_watchdog_s = 0.05
        a = await q.submit(1)            # cold: fallback; warm-up #1 hangs
        await asyncio.sleep(0.3)         # watchdog clears the _warming flag
        assert not q._warming
        b = await q.submit(2)            # retries the warm-up (queued behind
        release.set()                    # the hung one on the 1-thread pool)
        for _ in range(200):
            if 1 in q._warm_buckets:
                break
            await asyncio.sleep(0.02)
        assert 1 in q._warm_buckets
        c = await q.submit(3)            # device
        return a, b, c

    a, b, c = asyncio.run(run())
    assert a == ("cpu", 1) and b == ("cpu", 2) and c == ("dev", 3)


def test_breaker_coalesces_sibling_queues_into_one_window():
    """Queues sharing a breaker flush together: when one queue flushes, a
    sibling's pending items go in flight in the same scheduling window
    instead of riding out their own max_wait timer."""

    async def run():
        br = Breaker(cooloff_s=60.0)
        qa = OpQueue(lambda items: [("a", x) for x in items],
                     max_batch=2, max_wait_ms=10_000.0, breaker=br)
        qb = OpQueue(lambda items: [("b", x) for x in items],
                     max_batch=64, max_wait_ms=10_000.0, breaker=br)
        fb = asyncio.ensure_future(qb.submit(7))   # pending, timer far out
        await asyncio.sleep(0)
        # filling qa to max_batch flushes it AND coalesces qb's pending item
        outs = await asyncio.gather(qa.submit(1), qa.submit(2))
        got_b = await asyncio.wait_for(fb, timeout=1.0)
        return outs, got_b, qa.stats, qb.stats

    outs, got_b, sta, stb = asyncio.run(run())
    assert outs == [("a", 1), ("a", 2)] and got_b == ("b", 7)
    assert sta.flushes == 1 and stb.flushes == 1
    assert stb.total_wait_s < 5.0  # did not ride out its 10s timer


def test_trip_counters_aggregate_across_shared_breaker():
    """device_trips/fallback_trips are the handshake SLO currency: each
    queue counts its own, and the shared breaker aggregates both so
    SecureMessaging can diff one number around a handshake."""

    async def run():
        br = Breaker(cooloff_s=60.0)
        qa = OpQueue(lambda items: list(items), max_batch=4, max_wait_ms=1.0,
                     breaker=br)
        qb = OpQueue(lambda items: list(items), max_batch=4, max_wait_ms=1.0,
                     fallback_fn=lambda items: list(items), breaker=br)
        qa._warm_buckets.add(1)
        qb._warm_buckets.add(1)
        await qa.submit(1)      # no-fallback queue: plain device trip
        await qb.submit(2)      # armed queue, warm: device trip
        br.trip()
        await qb.submit(3)      # open: fallback trip
        return qa.stats, qb.stats, br

    sta, stb, br = asyncio.run(run())
    assert sta.device_trips == 1 and stb.device_trips == 1
    assert stb.fallback_ops == 1
    assert br.device_trips == 2 and br.fallback_trips == 1
    assert sta.as_dict()["device_trips"] == 1


def test_batched_fused_composite_falls_back_to_per_op_cpu():
    """The composite queue degrades to per-op cpu work that is
    wire-identical: with the breaker open, keygen_sign / encaps_verify_sign
    / decaps_verify_sign compose the cpu twins and their outputs
    interoperate with plain per-op providers."""
    import json

    from quantum_resistant_p2p_tpu.provider import get_fused
    from quantum_resistant_p2p_tpu.provider.batched import BatchedFused
    from quantum_resistant_p2p_tpu.provider.fused_providers import (
        init_pk_offset, resp_ct_offset)

    tpu_kem = get_kem("ML-KEM-512", backend="tpu")
    tpu_sig = get_signature("ML-DSA-44", backend="tpu")
    cpu_kem = get_kem("ML-KEM-512", backend="cpu")
    cpu_sig = get_signature("ML-DSA-44", backend="cpu")
    fused = get_fused(tpu_kem, tpu_sig)
    assert fused is not None
    # cpu pairs advertise no capability -> callers stay entirely per-op
    assert get_fused(cpu_kem, cpu_sig) is None

    pk_off = init_pk_offset("ML-KEM-512", "AES-256-GCM")
    ct_off = resp_ct_offset()
    bf = BatchedFused(fused, pk_off=pk_off, ct_off=ct_off, max_batch=4,
                      max_wait_ms=1.0, fallback_kem=cpu_kem,
                      fallback_sig=cpu_sig, breaker=Breaker(cooloff_s=60.0))
    bf.breaker.trip()  # force every composite flush onto the cpu fallback

    spk, ssk = cpu_sig.generate_keypair()
    init = {"aead": "AES-256-GCM", "kem": "ML-KEM-512",
            "message_id": "x" * 36, "public_key": "0" * (2 * tpu_kem.public_key_len),
            "recipient": "bob", "sender": "alice", "timestamp": 1.5}
    tmpl = json.dumps(init, sort_keys=True, separators=(",", ":")).encode()

    async def run():
        pk, sk, sig = await bf.keygen_sign(ssk, tmpl)
        rendered = tmpl[:pk_off] + pk.hex().encode() + \
            tmpl[pk_off + 2 * len(pk):]
        assert cpu_sig.verify(spk, rendered, sig)  # per-op interop

        resp = {"ciphertext": "0" * (2 * tpu_kem.ciphertext_len),
                "message_id": "x" * 36, "recipient": "alice",
                "sender": "bob", "timestamp": 1.5}
        rtmpl = json.dumps(resp, sort_keys=True, separators=(",", ":")).encode()
        ok, ct, ss, rsig = await bf.encaps_verify_sign(
            pk, spk, rendered, sig, ssk, rtmpl)
        assert ok
        rrend = rtmpl[:ct_off] + ct.hex().encode() + \
            rtmpl[ct_off + 2 * len(ct):]
        assert cpu_sig.verify(spk, rrend, rsig)
        assert cpu_kem.decapsulate(sk, ct) == ss  # per-op decaps interop

        confirm = b'{"message_id":"y","recipient":"b","sender":"a","timestamp":2}'
        ok2, ss2, csig = await bf.decaps_verify_sign(
            sk, ct, spk, rrend, rsig, ssk, confirm)
        assert ok2 and ss2 == ss
        assert cpu_sig.verify(spk, confirm, csig)

        # a tampered peer signature fails as ok=False, not an exception
        bad = bytes([sig[0] ^ 1]) + sig[1:]
        ok3, _, _, _ = await bf.encaps_verify_sign(
            pk, spk, rendered, bad, ssk, rtmpl)
        assert not ok3
        return bf.stats()

    st = asyncio.run(run())
    for qname in ("keygen_sign", "encaps_verify_sign", "decaps_verify_sign"):
        assert st[qname]["fallback_flushes"] >= 1
        assert st[qname]["device_trips"] == 0


# -- breaker state machine (closed -> open -> half-open -> closed) ------------


def test_breaker_half_open_probe_heals_and_fraction_recovers():
    """A transiently-raising device opens the breaker; after the cool-off
    ONE queued flush runs as the canary probe, its success closes the
    breaker, and every later op rides the device path again — the
    device_served_fraction of the post-heal window is 1.0 (the r3 fix:
    no more silently-permanent degradation)."""
    boom = {"n": 2}  # dispatches 2..3 raise

    def device(items):
        boom["n"] -= 0  # keep a stable reference
        if boom.get("fail"):
            raise RuntimeError("transient device fault")
        return [("dev", x) for x in items]

    async def run():
        br = Breaker(cooloff_s=0.05)
        q = OpQueue(device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=lambda items: [("cpu", x) for x in items],
                    breaker=br)
        q._warm_buckets.add(1)
        a = await q.submit(1)                 # closed: device
        assert a == ("dev", 1) and br.state == "closed"
        boom["fail"] = True
        b = await q.submit(2)                 # device raises -> fallback, OPEN
        assert b == ("cpu", 2) and br.state == "open"
        c = await q.submit(3)                 # open: fallback, device untouched
        assert c == ("cpu", 3)
        boom["fail"] = False                  # device recovers
        await asyncio.sleep(0.08)             # cool-off expires
        pre = q.stats.ops - q.stats.fallback_ops
        d = await q.submit(4)                 # half-open canary probe: device
        assert d == ("dev", 4) and br.state == "closed"
        outs = [await q.submit(i) for i in range(5, 10)]
        assert outs == [("dev", i) for i in range(5, 9 + 1)]
        post = (q.stats.ops - q.stats.fallback_ops) - pre
        assert post == 6  # probe + 5 healed ops: post-heal fraction is 1.0
        return br, q.stats.as_dict()

    br, st = asyncio.run(run())
    assert br.opens == 1 and br.closes == 1 and br.trips == 1
    assert br.cooloff_s == br.base_cooloff_s  # reset on close
    assert 0 < st["device_served_fraction"] < 1  # cumulative gauge visible


def test_breaker_probe_failure_reopens_with_exponential_backoff():
    """A failed canary re-opens the breaker with a doubled (capped)
    cool-off; only ONE probe dispatch reaches the still-broken device per
    half-open window."""
    device_calls = []

    def device(items):
        device_calls.append(len(items))
        raise RuntimeError("still broken")

    async def run():
        br = Breaker(cooloff_s=0.04, cooloff_max_s=0.1)
        q = OpQueue(device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=lambda items: [("cpu", x) for x in items],
                    breaker=br)
        q._warm_buckets.add(1)
        await q.submit(1)                     # trip 1: cooloff 0.04
        assert br.state == "open" and abs(br.cooloff_s - 0.04) < 1e-9
        await asyncio.sleep(0.06)
        await q.submit(2)                     # probe fails: cooloff 0.08
        assert br.state == "open" and abs(br.cooloff_s - 0.08) < 1e-9
        await asyncio.sleep(0.1)
        await q.submit(3)                     # probe fails: capped at 0.1
        assert abs(br.cooloff_s - 0.1) < 1e-9
        # while open, a burst of flushes must not touch the device at all
        n_dev = len(device_calls)
        outs = [await q.submit(i) for i in range(4, 8)]
        assert outs == [("cpu", i) for i in range(4, 8)]
        assert len(device_calls) == n_dev
        return br

    br = asyncio.run(run())
    assert br.trips == 3 and br.closes == 0
    assert len(device_calls) == 3  # one per closed/half-open window


def test_breaker_quarantine_pins_fallback_forever():
    """A health-gate quarantine (wrong answers, not slowness) pins the cpu
    fallback: no cool-off, no probe, for the process lifetime."""
    device_calls = []

    async def run():
        br = Breaker(cooloff_s=0.01)
        q = OpQueue(lambda items: device_calls.append(len(items)) or items,
                    max_batch=4, max_wait_ms=1.0,
                    fallback_fn=lambda items: [("cpu", x) for x in items],
                    breaker=br)
        q._warm_buckets.add(1)
        br.quarantine("KAT mismatch")
        assert br.state == "quarantined" and br.is_open()
        await asyncio.sleep(0.03)             # a cool-off would have expired
        outs = [await q.submit(i) for i in range(3)]
        assert outs == [("cpu", i) for i in range(3)]
        br.trip()                             # later trips cannot demote it
        assert br.state == "quarantined"
        return q.stats

    st = asyncio.run(run())
    assert device_calls == [] and st.fallback_ops == 3


def test_device_exception_serves_waiters_from_fallback():
    """A raising device dispatch (worker crash / injected fault) must not
    fail its waiters when a fallback is armed: ops are re-served from the
    cpu and the failure is recorded to the breaker."""

    def device(items):
        raise RuntimeError("XLA worker died")

    async def run():
        q = OpQueue(device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=lambda items: [("cpu", x) for x in items],
                    breaker=Breaker(cooloff_s=60.0))
        q._warm_buckets.update({1, 2})
        out = await asyncio.gather(q.submit(1), q.submit(2))
        return out, q.stats

    out, st = asyncio.run(run())
    assert out == [("cpu", 1), ("cpu", 2)]
    assert st.breaker_trips == 1 and st.fallback_ops == 2
    assert st.as_dict()["device_served_fraction"] == 0.0


def test_breaker_mutations_are_thread_safe():
    """Regression for the qrflow cross-thread-state finding: the breaker is
    mutated from the event loop (dispatch outcomes) AND the warmup thread
    (health-gate quarantine).  N concurrent trip() calls from worker threads
    must never lose a count, and a quarantine racing loop-side trips must
    stick — both fail intermittently without Breaker._lock."""
    import threading

    br = Breaker(cooloff_s=60.0)
    N_THREADS, N_TRIPS = 16, 200
    start = threading.Barrier(N_THREADS)

    def hammer():
        start.wait()
        for _ in range(N_TRIPS):
            br.trip()

    threads = [threading.Thread(target=hammer) for _ in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert br.trips == N_THREADS * N_TRIPS
    assert br.state == "open"

    # quarantine from a "warmup thread" while loop-side failures keep landing
    br2 = Breaker(cooloff_s=60.0)
    stop = threading.Event()

    def loop_side():
        while not stop.is_set():
            br2.record_failure("device")

    t = threading.Thread(target=loop_side)
    t.start()
    try:
        quarantiner = threading.Thread(
            target=br2.quarantine, args=("health gate failed",))
        quarantiner.start()
        quarantiner.join()
    finally:
        stop.set()
        t.join()
    assert br2.state == "quarantined"           # later trips cannot demote it
    assert br2.acquire_dispatch() == "fallback"


def test_mark_warm_from_thread_is_visible_to_loop_dispatch():
    """Regression for the qrflow OpQueue._warm_buckets finding: the facade
    warmup marks buckets from the warmup thread; a loop-side flush must see
    the marking (locked handoff, no direct set poke) and take the device
    path instead of re-warming."""
    import threading

    device_calls = []

    def device(items):
        device_calls.append(len(items))
        return [("dev", x) for x in items]

    async def run():
        q = OpQueue(device, max_batch=4, max_wait_ms=1.0,
                    fallback_fn=lambda items: [("cpu", x) for x in items],
                    breaker=Breaker(cooloff_s=60.0))
        t = threading.Thread(target=q.mark_warm, args=(1,))
        t.start()
        t.join()
        assert 1 in q._warm_buckets and 1 not in q._warming
        out = await q.submit("a")
        return out, q.stats

    out, st = asyncio.run(run())
    assert out == ("dev", "a")
    assert device_calls == [1]
    assert st.fallback_ops == 0 and st.device_trips == 1
