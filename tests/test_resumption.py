"""Session-resumption tickets + graceful drain (docs/protocol.md
"Session resumption"; ISSUE 15).

Covered here:

* STEKRing seal/open mechanics: roundtrip, the dual-key rotation accept
  window, typed rejects for every hostile blob shape, replay-cache bounds;
* the e2e happy path: full handshake -> ticket delivered IN the
  ke_response frame -> disconnect -> reconnect -> 1-RTT resume (no
  KEM/sig) -> messages under the resumed key -> ratcheted fresh ticket;
* the hostile-ticket matrix end-to-end: truncated / oversized / garbage /
  flipped-epoch / wrong-STEK / expired / replayed / foreign-holder
  tickets each draw a TYPED reject and fall back to a full handshake —
  never a stall, never plaintext;
* the faults/ ``ticket`` scope: seeded corrupt/expire/replay injection
  with a byte-reproducible injected log;
* ``QRP2P_RESUMPTION=0`` and un-negotiated peers: wire byte-identical to
  the pre-resumption protocol (hello golden + message-type trace);
* graceful drain: /readyz 503 draining, BUSY sheds, typed resume reject,
  rehome nudges, outbox flush;
* seeded reconnect jitter (the thundering-herd fix) pinned deterministic
  under an injected RNG.

Stdlib toy algorithms (RES-KEM/RES-SIG twins of the chaos suite's toys)
keep the whole suite wheel-less and fast.
"""

import asyncio
import hashlib
import hmac
import os
import random
import time

import pytest

from quantum_resistant_p2p_tpu.app import messaging as messaging_mod
from quantum_resistant_p2p_tpu.app import resumption as resumption_mod
from quantum_resistant_p2p_tpu.app.messaging import SecureMessaging
from quantum_resistant_p2p_tpu.app.resumption import (
    ReplayCache, STEKRing, TicketError, derive_resumption_secret,
    mint_fields, resumption_default)
from quantum_resistant_p2p_tpu.faults import FaultPlan, FaultRule
from quantum_resistant_p2p_tpu.net.p2p_node import (P2PNode,
                                                    RECONNECT_JITTER_S)
from quantum_resistant_p2p_tpu.provider.base import (KeyExchangeAlgorithm,
                                                     SignatureAlgorithm,
                                                     SymmetricAlgorithm)
from quantum_resistant_p2p_tpu.provider.registry import (register_kem,
                                                         register_signature)

# -- stdlib toys (the chaos suite's pattern; distinct names so registries
# -- never collide across test modules) ---------------------------------------


def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    out = b""
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(key + nonce + ctr.to_bytes(8, "big")).digest()
        ctr += 1
    return out[:n]


class ToyAEAD(SymmetricAlgorithm):
    name = "RES-AEAD"
    display_name = "RES-AEAD"
    key_size = 32
    nonce_size = 16

    def encrypt(self, key, plaintext, associated_data=None):
        nonce = os.urandom(self.nonce_size)
        ct = bytes(a ^ b for a, b in
                   zip(plaintext, _keystream(key, nonce, len(plaintext))))
        tag = hmac.new(key, nonce + ct + (associated_data or b""),
                       hashlib.sha256).digest()
        return nonce + ct + tag

    def decrypt(self, key, data, associated_data=None):
        if len(data) < self.nonce_size + 32:
            raise ValueError("ciphertext too short")
        nonce, ct, tag = (data[: self.nonce_size], data[self.nonce_size:-32],
                          data[-32:])
        want = hmac.new(key, nonce + ct + (associated_data or b""),
                        hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise ValueError("authentication failed")
        return bytes(a ^ b for a, b in zip(ct, _keystream(key, nonce, len(ct))))


class ToyKEM(KeyExchangeAlgorithm):
    name = "RES-KEM"
    display_name = "RES-KEM"
    public_key_len = 32
    secret_key_len = 32
    ciphertext_len = 32
    shared_secret_len = 32

    def __init__(self, backend="cpu"):
        self.backend = backend

    def generate_keypair(self):
        sk = os.urandom(32)
        return hashlib.sha256(b"pk" + sk).digest(), sk

    def encapsulate(self, public_key):
        ct = os.urandom(32)
        return ct, hashlib.sha256(public_key + ct).digest()

    def decapsulate(self, secret_key, ciphertext):
        pk = hashlib.sha256(b"pk" + secret_key).digest()
        return hashlib.sha256(pk + ciphertext).digest()


class ToySig(SignatureAlgorithm):
    name = "RES-SIG"
    display_name = "RES-SIG"
    public_key_len = 32
    secret_key_len = 32
    signature_len = 32

    def __init__(self, backend="cpu"):
        self.backend = backend

    def generate_keypair(self):
        sk = os.urandom(32)
        return hashlib.sha256(b"pk" + sk).digest(), sk

    def sign(self, secret_key, message):
        pk = hashlib.sha256(b"pk" + secret_key).digest()
        return hashlib.sha256(b"sig" + pk + message).digest()

    def verify(self, public_key, message, signature):
        return hmac.compare_digest(
            signature, hashlib.sha256(b"sig" + public_key + message).digest()
        )


register_kem("RES-KEM", lambda backend, devices=0: ToyKEM(backend),
             ("cpu", "tpu"))
register_signature("RES-SIG", lambda backend, devices=0: ToySig(backend),
                   ("cpu", "tpu"))


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


@pytest.fixture(autouse=True)
def fast_timeout(monkeypatch):
    monkeypatch.setattr(messaging_mod, "KEY_EXCHANGE_TIMEOUT", 1.5)
    monkeypatch.setattr(messaging_mod, "KE_RETRY_BACKOFF_S", 0.05)
    monkeypatch.setattr(messaging_mod, "HEAL_BACKOFF_S", 0.05)


def _engine(node, **kw):
    return SecureMessaging(node, kem=ToyKEM(), symmetric=ToyAEAD(),
                           signature=ToySig(), **kw)


async def _pair(a_kw=None, b_kw=None, a_node_kw=None, b_node_kw=None):
    a_node = P2PNode(node_id="alice", host="127.0.0.1", port=0,
                     **(a_node_kw or {}))
    b_node = P2PNode(node_id="bob", host="127.0.0.1", port=0,
                     **(b_node_kw or {}))
    await a_node.start()
    await b_node.start()
    a = _engine(a_node, **(a_kw or {}))
    b = _engine(b_node, **(b_kw or {}))
    assert await a_node.connect_to_peer("127.0.0.1", b_node.port) == "bob"
    for _ in range(100):
        if b_node.is_connected("alice"):
            break
        await asyncio.sleep(0.01)
    return a, b


async def _stop(*engines):
    for e in engines:
        await e.node.stop()


async def _reconnect(a, b):
    await a.node.disconnect_from_peer("bob", intentional=True)
    await asyncio.sleep(0.05)
    assert await a.node.connect_to_peer("127.0.0.1", b.node.port) == "bob"


# -- STEKRing / ReplayCache units ---------------------------------------------


def test_seal_open_roundtrip_and_secret_separation():
    ring = STEKRing()
    fields = mint_fields("alice", "bob", b"s" * 32, "K", "A", "S", 4e9)
    blob = ring.seal_ticket(fields)
    meta, secret = ring.open_ticket(blob)
    assert secret == b"s" * 32
    assert "secret" not in meta  # metadata and secret never travel together
    assert meta["holder"] == "alice" and meta["nonce"] == fields["nonce"]


def test_dual_key_rotation_window():
    ring = STEKRing()
    blob = ring.seal_ticket(mint_fields("a", "b", b"x" * 32, "K", "A", "S", 4e9))
    ring.rotate()
    # previous key still in the accept window
    meta, _ = ring.open_ticket(blob)
    assert meta["holder"] == "a"
    ring.rotate()
    # two rotations on: the sealing key left the window
    with pytest.raises(TicketError) as e:
        ring.open_ticket(blob)
    assert e.value.reason == "unknown_stek"


def test_install_export_roundtrip_distributes_the_ring():
    router = STEKRing()
    gw = STEKRing()  # private random ring, about to be replaced
    blob = router.seal_ticket(mint_fields("a", "b", b"y" * 32, "K", "A", "S",
                                          4e9))
    with pytest.raises(TicketError):
        gw.open_ticket(blob)  # never saw the STEK
    gw.install([(e, bytes.fromhex(k)) for e, k in router.export()])
    meta, secret = gw.open_ticket(blob)
    assert secret == b"y" * 32


@pytest.mark.parametrize("doctor, reason", [
    (lambda b: b[:10], "malformed_ticket"),                 # truncated
    (lambda b: b + b"z" * 5000, "malformed_ticket"),        # oversized
    (lambda b: b"garbage", "malformed_ticket"),             # garbage
    (lambda b: b"XX" + b[2:], "malformed_ticket"),          # wrong magic
    (lambda b: b[:3] + b"ffffffff" + b[11:], "unknown_stek"),  # flipped epoch
    (lambda b: b[:-1] + bytes([b[-1] ^ 0xFF]), "bad_ticket_auth"),  # bad MAC
    (lambda b: b[:20] + bytes([b[20] ^ 0xFF]) + b[21:], "bad_ticket_auth"),
])
def test_hostile_blob_matrix_is_typed(doctor, reason):
    ring = STEKRing()
    blob = ring.seal_ticket(mint_fields("a", "b", b"x" * 32, "K", "A", "S",
                                        4e9))
    with pytest.raises(TicketError) as e:
        ring.open_ticket(doctor(blob))
    assert e.value.reason == reason


def test_same_epoch_different_key_fails_auth():
    """A forged ring reusing the REAL epoch name cannot mint: the MAC is
    keyed by the key, not named by the epoch."""
    ring = STEKRing()
    forged = STEKRing()
    forged.rotate(stek=os.urandom(32), epoch=ring.current_epoch)
    blob = forged.seal_ticket(mint_fields("a", "b", b"x" * 32, "K", "A", "S",
                                          4e9))
    with pytest.raises(TicketError) as e:
        ring.open_ticket(blob)
    assert e.value.reason == "bad_ticket_auth"


def test_replay_cache_single_use_and_bounds():
    cache = ReplayCache(capacity=8)
    assert not cache.seen("n0", 100.0, 0.0)
    assert cache.seen("n0", 100.0, 0.0)
    assert cache.replays == 1
    # expired first-uses do not count as replays
    assert not cache.seen("exp", 1.0, 0.0)
    assert not cache.seen("exp", 50.0, 10.0)  # its expiry passed: fresh again
    # bounded: a nonce flood evicts the earliest-expiring half
    for i in range(20):
        cache.seen(f"flood{i}", 1000.0 + i, 0.0)
    assert len(cache) <= 9


# -- e2e: happy path ----------------------------------------------------------


def test_resume_happy_path_end_to_end(run):
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        # the ticket rides the ke_response frame: held the instant the
        # session is live (no separate-delivery window)
        entry = a.ticket_for("bob")
        assert entry is not None
        first_blob = bytes(entry["ticket"])
        assert b._ctr_tickets_minted.value == 1
        await _reconnect(a, b)
        assert await a.initiate_key_exchange("bob")
        assert a._ctr_resumes_used.value == 1
        assert b._ctr_resumes_ok.value == 1
        assert a._ctr_resume_fallbacks.value == 0
        # messages flow under the resumed key
        got = []
        b.register_message_listener(lambda p, m: got.append(m))
        assert await a.send_message("bob", b"resumed traffic") is not None
        for _ in range(100):
            if got:
                break
            await asyncio.sleep(0.01)
        assert got and got[0].content == b"resumed traffic"
        # a FRESH single-use ticket (ratcheted secret) replaced the used one
        entry2 = a.ticket_for("bob")
        assert entry2 is not None and bytes(entry2["ticket"]) != first_blob
        await _stop(a, b)

    run(main())


def test_resume_skips_kem_and_signatures(run):
    """The abbreviated exchange does no KEM/sig work: provider scalar-op
    counters (via the fault hook's event stream) stay untouched."""
    async def main():
        a, b = await _pair()
        # bob's "established" system message marks the ke_test decrypt —
        # the FIRST handshake's last crypto op — as fully processed, so
        # the plan window below sees resume-only traffic
        done = []
        b.register_message_listener(
            lambda p, m: done.append(m) if m.is_system else None)
        assert await a.initiate_key_exchange("bob")
        for _ in range(200):
            if done:
                break
            await asyncio.sleep(0.01)
        assert done
        plan = FaultPlan(0, [FaultRule("scalar.op", "raise", nth=10_000)])
        with plan.activate():
            await _reconnect(a, b)
            assert await a.initiate_key_exchange("bob")
        assert a._ctr_resumes_used.value == 1
        # no scalar crypto op (keygen/encaps/decaps/sign/verify) ran
        # during the resume: the plan matched ZERO scalar events
        assert plan._matched == [0]
        await _stop(a, b)

    run(main())


def test_in_session_rekey_still_runs_full_handshake(run):
    """Resumption is a RECONNECT fast path only: dropping the key on a
    live connection (the AEAD-failure rekey shape) re-keys through the
    full KEM handshake — fresh entropy, no ticket consumed."""
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        a.shared_keys.pop("bob", None)
        a.ke_state["bob"] = messaging_mod.KeyExchangeState.NONE
        assert await a.initiate_key_exchange("bob")
        assert a._ctr_resumes_used.value == 0  # no resume on a live conn
        assert a.ticket_for("bob") is not None  # ticket intact (refreshed)
        await _stop(a, b)

    run(main())


# -- e2e: hostile tickets -----------------------------------------------------


def _doctored_entry(entry, blob):
    return {"ticket": blob, "expires_at": entry["expires_at"],
            "secret": bytearray(entry["secret"])}


@pytest.mark.parametrize("doctor", [
    lambda blob: blob[:10],                                  # truncated
    lambda blob: blob + b"x" * 5000,                         # oversized
    lambda blob: os.urandom(len(blob)),                      # garbage
    lambda blob: blob[:3] + b"00000000" + blob[11:],         # flipped epoch
    lambda blob: blob[:-4] + bytes(4),                       # broken MAC
])
def test_hostile_ticket_falls_back_to_full_handshake(run, doctor):
    """Every hostile shape ends in: typed reject at the responder, loud
    fallback at the initiator, an ESTABLISHED session via the full
    handshake — no plaintext, no stall."""
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        entry = a.take_ticket("bob")
        await _reconnect(a, b)
        a.adopt_ticket("bob", _doctored_entry(entry, doctor(entry["ticket"])))
        assert await a.initiate_key_exchange("bob")  # fallback established
        assert a._ctr_resumes_used.value == 0
        assert a._ctr_resume_fallbacks.value == 1
        assert b._ctr_resume_rejects.value == 1
        assert b._ctr_resumes_ok.value == 0
        assert a.verify_key_exchange_state("bob")
        await _stop(a, b)

    run(main())


def test_replayed_ticket_second_use_full_handshakes_and_counts(run):
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        entry = a.ticket_for("bob")
        saved = _doctored_entry(entry, bytes(entry["ticket"]))
        await _reconnect(a, b)
        assert await a.initiate_key_exchange("bob")
        assert a._ctr_resumes_used.value == 1  # first use: resumed
        await _reconnect(a, b)
        a.adopt_ticket("bob", saved)  # replay the consumed single-use blob
        assert await a.initiate_key_exchange("bob")
        assert a._ctr_resumes_used.value == 1  # did NOT resume again
        assert b._replay.replays == 1          # the replay counter bumped
        assert b._ctr_resume_rejects.value == 1
        assert a.verify_key_exchange_state("bob")
        await _stop(a, b)

    run(main())


def test_expired_ticket_rejected_typed(run):
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        # a ticket the RESPONDER sealed, already expired
        secret = bytearray(os.urandom(32))
        blob = b.tickets.seal_ticket(mint_fields(
            "alice", "bob", bytes(secret), a.kem.name, a.symmetric.name,
            a.signature.name, time.time() - 5.0))
        await _reconnect(a, b)
        a.adopt_ticket("bob", {"ticket": blob,
                               "expires_at": time.time() + 100.0,
                               "secret": secret})
        assert await a.initiate_key_exchange("bob")
        assert a._ctr_resumes_used.value == 0
        assert b._ctr_resume_rejects.value == 1
        await _stop(a, b)

    run(main())


def test_ticket_to_gateway_that_never_saw_the_stek(run):
    """A valid ticket presented to a responder with a DIFFERENT (private)
    STEK ring: unknown_stek -> typed reject -> full-handshake fallback."""
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        entry = a.take_ticket("bob")
        await _reconnect(a, b)
        b.tickets = STEKRing()  # bob "restarted" without the fleet's keys
        a.adopt_ticket("bob", entry)
        assert await a.initiate_key_exchange("bob")
        assert a._ctr_resumes_used.value == 0
        assert a._ctr_resume_fallbacks.value == 1
        assert b._ctr_resume_rejects.value == 1
        assert a.verify_key_exchange_state("bob")
        await _stop(a, b)

    run(main())


def test_stolen_blob_without_secret_fails_binder(run):
    """Holding the sealed blob alone authorizes nothing: a presenter with
    the wrong resumption secret draws bad_binder and never a session."""
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        entry = a.take_ticket("bob")
        await _reconnect(a, b)
        a.adopt_ticket("bob", {"ticket": entry["ticket"],
                               "expires_at": entry["expires_at"],
                               "secret": bytearray(os.urandom(32))})
        assert await a.initiate_key_exchange("bob")  # full-handshake fallback
        assert a._ctr_resumes_used.value == 0
        assert b._ctr_resume_rejects.value == 1
        await _stop(a, b)

    run(main())


# -- faults/ ticket scope -----------------------------------------------------


@pytest.mark.parametrize("action", ["corrupt", "expire", "replay"])
def test_ticket_fault_injection_is_typed_and_logged(run, action):
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        await _reconnect(a, b)
        plan = FaultPlan(5, [FaultRule("ticket", action, nth=1)])
        with plan.activate():
            assert await a.initiate_key_exchange("bob")  # fallback heals it
        assert a._ctr_resumes_used.value == 0
        assert a._ctr_resume_fallbacks.value == 1
        assert b._ctr_resume_rejects.value == 1
        assert plan.injected and plan.injected[0]["action"] == action
        assert a.verify_key_exchange_state("bob")
        await _stop(a, b)

    run(main())


# -- opt-out / negotiation golden ---------------------------------------------


def test_env_opt_out_and_hello_golden(monkeypatch):
    monkeypatch.setenv("QRP2P_RESUMPTION", "0")
    assert resumption_default() is False
    node = P2PNode(node_id="n", port=7)
    # byte-identical to the PRE-resumption hello (the PR-13 shape)
    assert node._hello() == {"type": "__hello__", "node_id": "n",
                             "listen_port": 7, "wire": ["bin1"]}
    monkeypatch.setenv("QRP2P_RESUMPTION", "1")
    node2 = P2PNode(node_id="n", port=7)
    assert node2._hello()["resume"] == ["tik1"]


def test_opted_out_wire_is_byte_identical_to_pre_pr(run):
    """With resumption off (either side), the full message-type sequence
    and every frame's key set are EXACTLY the pre-resumption protocol's —
    pinned by spying on both transports."""
    async def main():
        sent: list[tuple[str, frozenset]] = []

        a, b = await _pair(a_kw={"resumption": False},
                           b_kw={"resumption": False},
                           a_node_kw={"resumption": False},
                           b_node_kw={"resumption": False})
        for node in (a.node, b.node):
            orig = node.send_message

            async def spy(peer_id, msg_type, _orig=orig, **payload):
                sent.append((msg_type, frozenset(payload)))
                return await _orig(peer_id, msg_type, **payload)

            node.send_message = spy
        assert await a.initiate_key_exchange("bob")
        types = [t for t, _ in sent if t.startswith("ke_")]
        assert types == ["ke_init", "ke_response", "ke_confirm", "ke_test"]
        resp_keys = next(keys for t, keys in sent if t == "ke_response")
        assert resp_keys == frozenset(
            {"ke_data", "sig", "sig_algo", "sig_pk"})  # no ticket fields
        assert a.ticket_for("bob") is None
        assert b._ctr_tickets_minted.value == 0
        await _stop(a, b)

    run(main())


def test_unnegotiated_peer_gets_no_tickets(run):
    """One side opted out -> negotiation fails -> NO ticket minted, NO
    resume attempted; reconnects run the classic full handshake."""
    async def main():
        a, b = await _pair(b_kw={"resumption": False},
                           b_node_kw={"resumption": False})
        assert await a.initiate_key_exchange("bob")
        assert a.ticket_for("bob") is None
        assert b._ctr_tickets_minted.value == 0
        await _reconnect(a, b)
        assert await a.initiate_key_exchange("bob")
        assert a._ctr_resumes_used.value == 0
        assert a._ctr_resume_fallbacks.value == 0  # never even attempted
        await _stop(a, b)

    run(main())


def test_resume_ok_without_fresh_ticket_stores_no_secret(run, monkeypatch):
    """A degraded responder can confirm the resume without re-minting
    (empty ticket field on ke_resume_ok): the initiator installs the
    resumed key but must NOT ratchet or store anything — a ratcheted
    secret with no ticket to bind it to would be an unaccounted copy of
    key material (the qrlife wipe-completeness discipline)."""
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        assert a.ticket_for("bob") is not None
        await _reconnect(a, b)
        monkeypatch.setattr(b.tickets, "seal_ticket", lambda fields: b"")
        assert await a.initiate_key_exchange("bob")
        assert a._ctr_resumes_used.value == 1
        assert a.shared_keys.get("bob") is not None  # session is live
        assert a.ticket_for("bob") is None  # consumed, nothing re-stored
        await _stop(a, b)

    run(main())


# -- graceful drain -----------------------------------------------------------


def test_drain_readyz_sheds_and_nudges(run):
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        res = await b.drain("rolling-restart-test")
        assert b.draining and res["nudged"] == 1
        ready = b.ready_status()
        assert ready["ready"] is False
        assert ready["draining"] is True
        assert ready["drain_reason"] == "rolling-restart-test"
        # the nudge reached alice (counted + surfaced as a system message)
        for _ in range(100):
            if a._ctr_rehome_nudges.value:
                break
            await asyncio.sleep(0.01)
        assert a._ctr_rehome_nudges.value == 1
        # new full handshakes shed with the typed BUSY...
        a.shared_keys.pop("bob", None)
        a._tickets.pop("bob", None)
        a.ke_state["bob"] = messaging_mod.KeyExchangeState.NONE
        assert not await a.initiate_key_exchange("bob", retries=0)
        assert b._ctr_handshake_sheds.value >= 1
        # ...and resumes draw the typed draining reject
        assert b.draining
        assert (await b._resume_respond("alice", {}, {}, "x")) == "draining"
        # drain is idempotent
        again = await b.drain("second")
        assert again.get("already_draining")
        await _stop(a, b)

    run(main())


def test_drain_flushes_outbox(run):
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        got = []
        b.register_message_listener(
            lambda p, m: got.append(m) if not m.is_system else None)
        # park a message in alice's outbox by hand, then drain alice
        a._outbox["bob"] = [messaging_mod.Message(
            content=b"parked", sender_id=a.node_id, recipient_id="bob")]
        res = await a.drain("test")
        assert res["flushed"] == 1
        for _ in range(100):
            if got:
                break
            await asyncio.sleep(0.01)
        assert got and got[0].content == b"parked"
        await _stop(a, b)

    run(main())


# -- reconnect jitter (thundering herd) ---------------------------------------


def test_reconnect_jitter_is_seeded_and_bounded():
    rng = random.Random(42)
    expected = [random.Random(42).uniform(0.0, RECONNECT_JITTER_S)
                for _ in range(1)][0]
    node = P2PNode(node_id="j", port=0, jitter_rng=rng)
    draws = [node._reconnect_jitter() for _ in range(8)]
    assert draws[0] == expected
    assert all(0.0 <= d < RECONNECT_JITTER_S for d in draws)
    # same injected seed -> identical sequence (determinism pinned)
    node2 = P2PNode(node_id="j2", port=0, jitter_rng=random.Random(42))
    assert [node2._reconnect_jitter() for _ in range(8)] == draws


def test_reconnect_sleeps_the_jitter(run, monkeypatch):
    async def main():
        slept = []
        node = P2PNode(node_id="j", port=0, jitter_rng=random.Random(1))
        node._addr["ghost"] = ("127.0.0.1", 1)  # nothing listens there

        real_sleep = asyncio.sleep

        async def spy_sleep(d):
            slept.append(d)
            await real_sleep(0)

        monkeypatch.setattr(asyncio, "sleep", spy_sleep)
        assert not await node.reconnect("ghost", timeout=0.2, retries=0)
        expected = random.Random(1).uniform(0.0, RECONNECT_JITTER_S)
        assert slept and slept[0] == expected

    run(main())


# -- surface checks -----------------------------------------------------------


def test_metrics_resumption_section_and_slo_spec(run):
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        m = b.metrics()["resumption"]
        for key in ("enabled", "tickets_minted", "tickets_held",
                    "resumes_ok", "resume_rejects", "resumes_used",
                    "resume_fallbacks", "replay_cache", "draining"):
            assert key in m
        assert m["tickets_minted"] == 1
        assert "resume_success" in b.slo.names()
        counters = b.slo_report()["counters"]
        assert counters["tickets_minted"] == 1
        await _stop(a, b)

    run(main())


def test_ticket_secrets_wiped_on_drop_paths(run):
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        entry = a.ticket_for("bob")
        buf = entry["secret"]
        assert any(buf)
        a._drop_ticket("bob")
        assert not any(buf)  # zeroized in place
        await _stop(a, b)

    run(main())
