"""Native C++ core: bit-exact vs hashlib and the pure-Python ML-KEM oracle."""

import hashlib

import numpy as np
import pytest

from quantum_resistant_p2p_tpu import native
from quantum_resistant_p2p_tpu.pyref import mlkem_ref

pytestmark = pytest.mark.skipif(native.load() is None, reason="no C++ toolchain")

RNG = np.random.default_rng(3329)


def test_shake256_matches_hashlib():
    for ln in (0, 1, 135, 136, 137, 500):
        data = bytes(RNG.integers(0, 256, size=ln, dtype=np.uint8))
        assert native.shake256(data, 64) == hashlib.shake_256(data).digest(64)


@pytest.mark.parametrize("name", ["ML-KEM-512", "ML-KEM-768", "ML-KEM-1024"])
def test_mlkem_matches_pyref(name):
    p = mlkem_ref.PARAMS[name]
    nk = native.NativeMLKEM(name)
    d = bytes(RNG.integers(0, 256, size=32, dtype=np.uint8))
    z = bytes(RNG.integers(0, 256, size=32, dtype=np.uint8))
    m = bytes(RNG.integers(0, 256, size=32, dtype=np.uint8))
    ek, dk = nk.keygen(d, z)
    rek, rdk = mlkem_ref.keygen(p, d, z)
    assert ek == rek and dk == rdk
    key, ct = nk.encaps(ek, m)
    rkey, rct = mlkem_ref.encaps(p, ek, m)
    assert key == rkey and ct == rct
    assert nk.decaps(dk, ct) == key
    # implicit rejection path agrees with the oracle too
    bad = bytearray(ct)
    bad[0] ^= 1
    assert nk.decaps(dk, bytes(bad)) == mlkem_ref.decaps(p, dk, bytes(bad))


@pytest.mark.parametrize("name", ["ML-DSA-44", "ML-DSA-65", "ML-DSA-87"])
def test_mldsa_matches_pyref(name):
    from quantum_resistant_p2p_tpu.pyref import mldsa_ref

    p = mldsa_ref.PARAMS[name]
    nd = native.NativeMLDSA(name)
    xi = bytes(RNG.integers(0, 256, size=32, dtype=np.uint8))
    rnd = bytes(RNG.integers(0, 256, size=32, dtype=np.uint8))
    pk, sk = nd.keygen(xi)
    rpk, rsk = mldsa_ref.keygen(p, xi)
    assert pk == rpk and sk == rsk
    m_prime = bytes([0, 0]) + b"native vs pyref"
    sig = nd.sign_internal(sk, m_prime, rnd)
    assert sig == mldsa_ref.sign_internal(p, sk, m_prime, rnd)
    assert nd.verify_internal(pk, m_prime, sig)
    assert mldsa_ref.verify_internal(p, pk, m_prime, sig)
    bad = bytearray(sig)
    bad[17] ^= 1
    assert not nd.verify_internal(pk, m_prime, bytes(bad))
    assert not nd.verify_internal(pk, bytes([0, 0]) + b"other message", sig)


def test_mldsa_provider_native_cpu_interop():
    """cpu provider (native fast path) and pyref agree through the plugin API."""
    from quantum_resistant_p2p_tpu.provider.sig_providers import MLDSASignature

    alg = MLDSASignature(security_level=3, backend="cpu")
    assert alg._native is not None  # toolchain present (module-level skip)
    pk, sk = alg.generate_keypair()
    sig = alg.sign(sk, b"interop message")
    assert alg.verify(pk, b"interop message", sig)
    assert not alg.verify(pk, b"tampered message", sig)
    from quantum_resistant_p2p_tpu.pyref import mldsa_ref

    assert mldsa_ref.verify(mldsa_ref.MLDSA65, pk, b"interop message", sig)


def test_sha2_matches_hashlib():
    import hmac as hmac_mod

    lib = native.load()
    for ln in (0, 1, 55, 56, 63, 64, 65, 111, 112, 127, 128, 300):
        data = bytes(RNG.integers(0, 256, size=ln, dtype=np.uint8))
        out32 = (__import__("ctypes").c_uint8 * 32)()
        out64 = (__import__("ctypes").c_uint8 * 64)()
        lib.qrp_sha256(native._buf(data), ln, out32)
        lib.qrp_sha512(native._buf(data), ln, out64)
        assert bytes(out32) == hashlib.sha256(data).digest()
        assert bytes(out64) == hashlib.sha512(data).digest()
    key = bytes(RNG.integers(0, 256, size=32, dtype=np.uint8))
    msg = bytes(RNG.integers(0, 256, size=99, dtype=np.uint8))
    out32 = (__import__("ctypes").c_uint8 * 32)()
    lib.qrp_hmac_sha256(native._buf(key), 32, native._buf(msg), 99, out32)
    assert bytes(out32) == hmac_mod.new(key, msg, hashlib.sha256).digest()


@pytest.mark.parametrize(
    "name",
    [
        "SPHINCS+-SHA2-128s-simple",
        "SPHINCS+-SHA2-128f-simple",
        pytest.param("SPHINCS+-SHA2-192s-simple", marks=pytest.mark.slow),
        pytest.param("SPHINCS+-SHA2-192f-simple", marks=pytest.mark.slow),
        pytest.param("SPHINCS+-SHA2-256s-simple", marks=pytest.mark.slow),
        pytest.param("SPHINCS+-SHA2-256f-simple", marks=pytest.mark.slow),
    ],
)
def test_slhdsa_matches_pyref(name):
    from quantum_resistant_p2p_tpu.pyref import slhdsa_ref

    p = slhdsa_ref.PARAMS[name]
    ns = native.NativeSLHDSA(name)
    ss, sp, ps = (bytes(RNG.integers(0, 256, size=p.n, dtype=np.uint8)) for _ in range(3))
    pk, sk = ns.keygen(ss, sp, ps)
    rpk, rsk = slhdsa_ref.keygen(p, ss, sp, ps)
    assert pk == rpk and sk == rsk
    msg = b"native vs pyref slhdsa"
    sig = ns.sign_internal(msg, sk)
    assert sig == slhdsa_ref.sign_internal(p, msg, sk, None)
    assert ns.verify_internal(msg, sig, pk)
    bad = bytearray(sig)
    bad[40] ^= 1
    assert not ns.verify_internal(msg, bytes(bad), pk)
    assert not ns.verify_internal(b"other", sig, pk)
    # hedged variant agrees too
    ar = bytes(RNG.integers(0, 256, size=p.n, dtype=np.uint8))
    assert ns.sign_internal(msg, sk, ar) == slhdsa_ref.sign_internal(p, msg, sk, ar)


def test_slhdsa_provider_native_cpu_interop():
    from quantum_resistant_p2p_tpu.provider.sig_providers import SPHINCSSignature

    alg = SPHINCSSignature(security_level=1, backend="cpu", fast=True)
    assert alg._native is not None
    pk, sk = alg.generate_keypair()
    sig = alg.sign(sk, b"interop")
    assert alg.verify(pk, b"interop", sig)
    assert not alg.verify(pk, b"tampered", sig)
    from quantum_resistant_p2p_tpu.pyref import slhdsa_ref

    assert slhdsa_ref.verify(slhdsa_ref.SLH128F, pk, b"interop", sig)
    # small-signature variant through the registry
    from quantum_resistant_p2p_tpu.provider import get_signature

    s128 = get_signature("SPHINCS+-SHA2-128s-simple", backend="cpu")
    assert s128.signature_len == 7856
    pk, sk = s128.generate_keypair()
    sig = s128.sign(sk, b"small sig")
    assert s128.verify(pk, b"small sig", sig)


def test_aes128_matches_fips197_and_openssl():
    import ctypes

    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    lib = native.load()
    out = (ctypes.c_uint8 * 16)()
    # FIPS-197 Appendix C.1
    key = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    lib.qrp_aes128_ecb(native._buf(key), native._buf(pt), 1, out)
    assert bytes(out).hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"
    for _ in range(20):
        key = bytes(RNG.integers(0, 256, size=16, dtype=np.uint8))
        pt = bytes(RNG.integers(0, 256, size=16, dtype=np.uint8))
        ref = Cipher(algorithms.AES(key), modes.ECB()).encryptor().update(pt)
        lib.qrp_aes128_ecb(native._buf(key), native._buf(pt), 1, out)
        assert bytes(out) == ref


@pytest.mark.parametrize(
    "name",
    [
        "FrodoKEM-640-AES",
        "FrodoKEM-640-SHAKE",
        pytest.param("FrodoKEM-976-AES", marks=pytest.mark.slow),
        pytest.param("FrodoKEM-976-SHAKE", marks=pytest.mark.slow),
        pytest.param("FrodoKEM-1344-AES", marks=pytest.mark.slow),
        pytest.param("FrodoKEM-1344-SHAKE", marks=pytest.mark.slow),
    ],
)
def test_frodo_matches_pyref(name):
    from quantum_resistant_p2p_tpu.pyref import frodo_ref

    if "AES" in name:
        pytest.importorskip("cryptography")  # pyref AES matrix expansion
    p = frodo_ref.PARAMS[name]
    nf = native.NativeFrodoKEM(name)
    s, se, z, mu = (
        bytes(RNG.integers(0, 256, size=p.len_sec, dtype=np.uint8)) for _ in range(4)
    )
    pk, sk = nf.keygen(s, se, z)
    rpk, rsk = frodo_ref.keygen(p, s, se, z)
    assert pk == rpk and sk == rsk
    ct, ss = nf.encaps(pk, mu)
    rct, rss = frodo_ref.encaps(p, pk, mu)
    assert ct == rct and ss == rss
    assert nf.decaps(sk, ct) == ss
    bad = bytearray(ct)
    bad[5] ^= 1
    assert nf.decaps(sk, bytes(bad)) == frodo_ref.decaps(p, sk, bytes(bad))


@pytest.mark.parametrize(
    "name",
    ["HQC-128",
     pytest.param("HQC-192", marks=pytest.mark.slow),
     pytest.param("HQC-256", marks=pytest.mark.slow)],
)
def test_hqc_matches_pyref(name):
    from quantum_resistant_p2p_tpu.pyref import hqc_ref

    p = hqc_ref.PARAMS[name]
    nh = native.NativeHQC(name)
    sk_seed = bytes(RNG.integers(0, 256, size=40, dtype=np.uint8))
    pk_seed = bytes(RNG.integers(0, 256, size=40, dtype=np.uint8))
    sigma = bytes(RNG.integers(0, 256, size=p.k, dtype=np.uint8))
    m = bytes(RNG.integers(0, 256, size=p.k, dtype=np.uint8))
    salt = bytes(RNG.integers(0, 256, size=16, dtype=np.uint8))
    pk, sk = nh.keygen(sk_seed, sigma, pk_seed)
    rpk, rsk = hqc_ref.keygen(p, sk_seed, sigma, pk_seed)
    assert pk == rpk and sk == rsk
    ct, ss = nh.encaps(pk, m, salt)
    rct, rss = hqc_ref.encaps(p, pk, m, salt)
    assert ct == rct and ss == rss
    assert nh.decaps(sk, ct) == ss
    # corrupted ciphertext follows the oracle through decode + implicit reject
    bad = bytearray(ct)
    bad[11] ^= 0xFF
    assert nh.decaps(sk, bytes(bad)) == hqc_ref.decaps(p, sk, bytes(bad))


def test_hqc_provider_native_cpu_interop():
    from quantum_resistant_p2p_tpu.provider.kem_providers import HQCKeyExchange

    alg = HQCKeyExchange(security_level=1, backend="cpu")
    assert alg._native is not None
    pk, sk = alg.generate_keypair()
    ct, ss = alg.encapsulate(pk)
    assert alg.decapsulate(sk, ct) == ss
    assert "native C++" in alg.description


def test_frodo_provider_native_cpu_interop():
    from quantum_resistant_p2p_tpu.provider.kem_providers import FrodoKEMKeyExchange

    alg = FrodoKEMKeyExchange(security_level=1, backend="cpu", use_aes=True)
    assert alg._native is not None
    pk, sk = alg.generate_keypair()
    ct, ss = alg.encapsulate(pk)
    assert alg.decapsulate(sk, ct) == ss
    assert "native C++" in alg.description


def test_zeroize():
    buf = bytearray(b"secret material")
    native.zeroize(buf)
    assert bytes(buf) == b"\0" * len(buf)


def test_wipe_polyglot_buffers():
    """native.wipe() is the shared end-of-life marker for secret buffers
    of whatever type a provider handed back: bytearrays go through the
    native cleanse, writable array-likes are zero-filled in place, and
    immutable operands (bytes, read-only/device arrays) are tolerated —
    the GC handoff is a documented limitation, not a crash."""
    buf = bytearray(b"secret material")
    arr = np.arange(8, dtype=np.float32) + 1.0
    frozen = b"immutable"
    native.wipe(buf, arr, frozen, None)
    assert bytes(buf) == b"\0" * len(buf)
    assert not arr.any()  # zero-filled for real, not just dereferenced
    assert frozen == b"immutable"
    ro = np.ones(4, dtype=np.float32)
    ro.setflags(write=False)
    native.wipe(ro)  # read-only: the immutable-operand path, no raise
    assert ro.any()
