"""Native C++ core: bit-exact vs hashlib and the pure-Python ML-KEM oracle."""

import hashlib

import numpy as np
import pytest

from quantum_resistant_p2p_tpu import native
from quantum_resistant_p2p_tpu.pyref import mlkem_ref

pytestmark = pytest.mark.skipif(native.load() is None, reason="no C++ toolchain")

RNG = np.random.default_rng(3329)


def test_shake256_matches_hashlib():
    for ln in (0, 1, 135, 136, 137, 500):
        data = bytes(RNG.integers(0, 256, size=ln, dtype=np.uint8))
        assert native.shake256(data, 64) == hashlib.shake_256(data).digest(64)


@pytest.mark.parametrize("name", ["ML-KEM-512", "ML-KEM-768", "ML-KEM-1024"])
def test_mlkem_matches_pyref(name):
    p = mlkem_ref.PARAMS[name]
    nk = native.NativeMLKEM(name)
    d = bytes(RNG.integers(0, 256, size=32, dtype=np.uint8))
    z = bytes(RNG.integers(0, 256, size=32, dtype=np.uint8))
    m = bytes(RNG.integers(0, 256, size=32, dtype=np.uint8))
    ek, dk = nk.keygen(d, z)
    rek, rdk = mlkem_ref.keygen(p, d, z)
    assert ek == rek and dk == rdk
    key, ct = nk.encaps(ek, m)
    rkey, rct = mlkem_ref.encaps(p, ek, m)
    assert key == rkey and ct == rct
    assert nk.decaps(dk, ct) == key
    # implicit rejection path agrees with the oracle too
    bad = bytearray(ct)
    bad[0] ^= 1
    assert nk.decaps(dk, bytes(bad)) == mlkem_ref.decaps(p, dk, bytes(bad))


@pytest.mark.parametrize("name", ["ML-DSA-44", "ML-DSA-65", "ML-DSA-87"])
def test_mldsa_matches_pyref(name):
    from quantum_resistant_p2p_tpu.pyref import mldsa_ref

    p = mldsa_ref.PARAMS[name]
    nd = native.NativeMLDSA(name)
    xi = bytes(RNG.integers(0, 256, size=32, dtype=np.uint8))
    rnd = bytes(RNG.integers(0, 256, size=32, dtype=np.uint8))
    pk, sk = nd.keygen(xi)
    rpk, rsk = mldsa_ref.keygen(p, xi)
    assert pk == rpk and sk == rsk
    m_prime = bytes([0, 0]) + b"native vs pyref"
    sig = nd.sign_internal(sk, m_prime, rnd)
    assert sig == mldsa_ref.sign_internal(p, sk, m_prime, rnd)
    assert nd.verify_internal(pk, m_prime, sig)
    assert mldsa_ref.verify_internal(p, pk, m_prime, sig)
    bad = bytearray(sig)
    bad[17] ^= 1
    assert not nd.verify_internal(pk, m_prime, bytes(bad))
    assert not nd.verify_internal(pk, bytes([0, 0]) + b"other message", sig)


def test_mldsa_provider_native_cpu_interop():
    """cpu provider (native fast path) and pyref agree through the plugin API."""
    from quantum_resistant_p2p_tpu.provider.sig_providers import MLDSASignature

    alg = MLDSASignature(security_level=3, backend="cpu")
    assert alg._native is not None  # toolchain present (module-level skip)
    pk, sk = alg.generate_keypair()
    sig = alg.sign(sk, b"interop message")
    assert alg.verify(pk, b"interop message", sig)
    assert not alg.verify(pk, b"tampered message", sig)
    from quantum_resistant_p2p_tpu.pyref import mldsa_ref

    assert mldsa_ref.verify(mldsa_ref.MLDSA65, pk, b"interop message", sig)


def test_zeroize():
    buf = bytearray(b"secret material")
    native.zeroize(buf)
    assert bytes(buf) == b"\0" * len(buf)
