"""Binary wire format: negotiation, compat pinning, hostile-input hardening.

The tentpole's transport half (net/p2p_node.py): a per-connection
negotiated length-prefixed binary framing with zero-copy ciphertext
pass-through.  Pins the two compatibility contracts:

* ``QRP2P_BINARY_WIRE=0`` and un-negotiated peers produce BYTE-IDENTICAL
  JSON frames (golden-bytes test);
* hostile binary input — oversized lengths, truncated headers, a wrong
  negotiation token, corrupt ciphertext mid-chunk — fails typed-and-loud
  (``WireError`` + counter + flight event) without killing the serving
  loop, mirroring PR 10's wire-context hardening.

Wheel-less friendly: the messaging-level tests ride the storm toy
providers + the pyref-backed ChaCha20-Poly1305.
"""

from __future__ import annotations

import asyncio
import json
import struct

import pytest

from quantum_resistant_p2p_tpu.net.p2p_node import (_BIN_TOKEN, _CHUNK_HEADER,
                                                    _FLAG_BIN, _FLAG_CHUNK,
                                                    _HEADER, _MAGIC, _VERSION,
                                                    P2PNode, WireError,
                                                    _decode_bin, _encode_bin)


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


async def _pair(a_bin=True, b_bin=True):
    a = P2PNode(node_id="node-a", host="127.0.0.1", port=0, binary_wire=a_bin)
    b = P2PNode(node_id="node-b", host="127.0.0.1", port=0, binary_wire=b_bin)
    await a.start()
    await b.start()
    assert await a.connect_to_peer("127.0.0.1", b.port) == "node-b"
    for _ in range(100):
        if b.is_connected("node-a"):
            break
        await asyncio.sleep(0.01)
    return a, b


# -- negotiation --------------------------------------------------------------


@pytest.mark.parametrize("a_bin,b_bin,expect", [
    (True, True, "bin1"),
    (True, False, "json"),
    (False, True, "json"),
    (False, False, "json"),
], ids=["both", "only-dialer", "only-listener", "neither"])
def test_wire_negotiation_requires_both_sides(run, a_bin, b_bin, expect):
    async def main():
        a, b = await _pair(a_bin, b_bin)
        assert a.peer_wire_format("node-b") == expect
        assert b.peer_wire_format("node-a") == expect
        # traffic flows in the negotiated format either way
        got = asyncio.Event()
        seen = {}

        async def on_ping(peer_id, msg):
            seen.update(msg)
            got.set()

        b.register_message_handler("ping", on_ping)
        assert await a.send_message("node-b", "ping", blob=b"\x01\x02", n=7)
        await asyncio.wait_for(got.wait(), 5)
        assert bytes(seen["blob"]) == b"\x01\x02" and seen["n"] == 7
        await a.stop()
        await b.stop()

    run(main())


def test_env_flag_and_hello_compat(run, monkeypatch):
    """QRP2P_BINARY_WIRE=0 (+ QRP2P_RESUMPTION=0, the session-resumption
    offer's twin knob) keeps the hello payload EXACTLY the historical
    dict (no ``wire``/``resume`` keys) — un-upgraded peers see nothing
    new."""
    monkeypatch.setenv("QRP2P_BINARY_WIRE", "0")
    monkeypatch.setenv("QRP2P_RESUMPTION", "0")
    node = P2PNode(node_id="n", host="127.0.0.1", port=4242)
    assert node.binary_wire is False
    assert node._hello() == {"type": "__hello__", "node_id": "n",
                             "listen_port": 4242}
    monkeypatch.delenv("QRP2P_BINARY_WIRE")
    node2 = P2PNode(node_id="n", host="127.0.0.1", port=4242)
    assert node2.binary_wire is True
    assert node2._hello()["wire"] == ["bin1"]
    assert "resume" not in node2._hello()  # resumption still opted out
    monkeypatch.delenv("QRP2P_RESUMPTION")
    node3 = P2PNode(node_id="n", host="127.0.0.1", port=4242)
    assert node3._hello()["resume"] == ["tik1"]


def test_json_frames_byte_identical_when_disabled(run, monkeypatch):
    """Golden-bytes pin: with the binary wire disabled, a sent message hits
    the socket as EXACTLY the historical JSON frame."""
    monkeypatch.setenv("QRP2P_TRACE_PROPAGATE", "0")  # ids vary run-to-run

    async def main():
        a, b = await _pair(a_bin=False, b_bin=False)
        peer = a._peers["node-b"]
        captured = bytearray()
        orig_write = peer.writer.write

        def spy(data):
            captured.extend(bytes(data))
            return orig_write(data)

        peer.writer.write = spy
        assert await a.send_message("node-b", "ping", n=1, blob=b"\x00\xff")
        body = json.dumps({"type": "ping", "n": 1,
                           "blob": {"__b64__": "AP8="}},
                          separators=(",", ":")).encode()
        golden = _HEADER.pack(_MAGIC, _VERSION, 0, len(body)) + body
        assert bytes(captured) == golden
        await a.stop()
        await b.stop()

    run(main())


# -- encoding unit coverage ---------------------------------------------------


def test_bin_codec_roundtrip_zero_copy():
    msg = {"type": "secure_message", "ct": b"\x00" * 40, "ad": b"{}",
           "_trace": {"trace_id": "t", "span_id": "s"}, "n": 3}
    body = b"".join(_encode_bin(msg))
    out = _decode_bin(body)
    assert out["type"] == "secure_message"
    # raw fields come back as zero-copy memoryviews into the frame buffer
    assert isinstance(out["ct"], memoryview)
    assert bytes(out["ct"]) == msg["ct"]
    assert out["_trace"] == msg["_trace"] and out["n"] == 3


@pytest.mark.parametrize("mutate,why", [
    (lambda b: b"XX" + b[2:], "bad token"),
    (lambda b: b[:5], "truncated mid-type"),
    (lambda b: b + b"garbage", "trailing bytes"),
    (lambda b: b[:-10], "truncated value"),
], ids=["token", "truncated", "trailing", "short-value"])
def test_bin_codec_hostile_inputs_are_typed(mutate, why):
    body = b"".join(_encode_bin({"type": "ping", "ct": b"x" * 32}))
    with pytest.raises(WireError):
        _decode_bin(mutate(bytes(body)))


def test_bin_codec_oversized_declared_length():
    # header declares a 1 GiB field the frame does not carry
    evil = (_BIN_TOKEN + bytes([4]) + b"ping" + bytes([1])
            + bytes([2]) + b"ct" + bytes([0])
            + (1 << 30).to_bytes(4, "big") + b"tiny")
    with pytest.raises(WireError):
        _decode_bin(evil)


# -- hostile frames against a live node --------------------------------------


async def _raw_hello(port: int, offer_bin: bool):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    hello = {"type": "__hello__", "node_id": "evil", "listen_port": 1}
    if offer_bin:
        hello["wire"] = ["bin1"]
    body = json.dumps(hello).encode()
    writer.write(_HEADER.pack(_MAGIC, _VERSION, 0, len(body)) + body)
    await writer.drain()
    # consume the hello reply
    hdr = await reader.readexactly(_HEADER.size)
    _, _, _, length = _HEADER.unpack(hdr)
    await reader.readexactly(length)
    return reader, writer


@pytest.mark.parametrize("frame", [
    # oversized length in the frame header
    _HEADER.pack(_MAGIC, _VERSION, _FLAG_BIN, 17 * 1024 * 1024),
    # wrong negotiation token
    _HEADER.pack(_MAGIC, _VERSION, _FLAG_BIN, 6) + b"XY" + b"ping",
    # truncated binary header (field count missing)
    _HEADER.pack(_MAGIC, _VERSION, _FLAG_BIN, 3) + _BIN_TOKEN + bytes([9]),
    # chunk index out of range
    _HEADER.pack(_MAGIC, _VERSION, _FLAG_CHUNK | _FLAG_BIN,
                 _CHUNK_HEADER.size) + _CHUNK_HEADER.pack(b"s" * 16, 5, 2),
    # bad magic
    struct.pack(">2sBBI", b"ZZ", 1, 0, 0),
], ids=["oversized", "bad-token", "truncated", "chunk-range", "bad-magic"])
def test_hostile_frame_drops_connection_not_node(run, frame):
    async def main():
        victim = P2PNode(node_id="victim", host="127.0.0.1", port=0)
        await victim.start()
        reader, writer = await _raw_hello(victim.port, offer_bin=True)
        for _ in range(100):
            if victim.is_connected("evil"):
                break
            await asyncio.sleep(0.01)
        errors0 = victim.wire_errors
        writer.write(frame)
        await writer.drain()
        # the node drops exactly this connection, typed and counted
        assert await reader.read() == b""  # server closed our socket
        for _ in range(100):
            if victim.wire_errors > errors0:
                break
            await asyncio.sleep(0.01)
        assert victim.wire_errors == errors0 + 1
        assert not victim.is_connected("evil")
        # the serving loop survives: a well-behaved peer connects and talks
        friend = P2PNode(node_id="friend", host="127.0.0.1", port=0)
        await friend.start()
        got = asyncio.Event()
        victim.register_message_handler(
            "hi", lambda p, m: (got.set(), None)[1])
        assert await friend.connect_to_peer("127.0.0.1", victim.port) == "victim"
        assert await friend.send_message("victim", "hi")
        await asyncio.wait_for(got.wait(), 5)
        await friend.stop()
        await victim.stop()

    run(main())


def test_binary_frame_from_unnegotiated_peer_is_rejected(run):
    async def main():
        victim = P2PNode(node_id="victim", host="127.0.0.1", port=0)
        await victim.start()
        # hello WITHOUT the wire offer: the connection is JSON-only
        reader, writer = await _raw_hello(victim.port, offer_bin=False)
        for _ in range(100):
            if victim.is_connected("evil"):
                break
            await asyncio.sleep(0.01)
        body = b"".join(_encode_bin({"type": "ping"}))
        writer.write(_HEADER.pack(_MAGIC, _VERSION, _FLAG_BIN, len(body)) + body)
        await writer.drain()
        assert await reader.read() == b""
        assert victim.wire_errors == 1
        await victim.stop()

    run(main())


def test_oversized_field_falls_back_to_json_wire(run, monkeypatch):
    """A message carrying a bytes value past the binary decoder's
    per-field cap (a huge file send) must ride the JSON wire for that
    one message instead of being dropped as hostile by the receiver —
    a bin1 peer accepts JSON frames at any time."""
    from quantum_resistant_p2p_tpu.net import p2p_node as p2p_mod

    async def main():
        a, b = await _pair()
        assert a.peer_wire_format("node-b") == "bin1"
        # shrink only the SEND-side routing threshold; the receiver's
        # frame bounds are untouched
        monkeypatch.setattr(p2p_mod, "_BIN_MAX_FIELD", 1024)
        got = asyncio.Event()
        seen = {}

        async def on_file(peer_id, msg):
            seen.update(msg)
            got.set()

        b.register_message_handler("file", on_file)
        big = bytes(range(256)) * 16  # 4 KiB > the shrunken cap
        assert await a.send_message("node-b", "file", data=big, small=b"s")
        await asyncio.wait_for(got.wait(), 5)
        # delivered via JSON (b64-decoded bytes, not a frame memoryview)
        assert isinstance(seen["data"], bytes)
        assert seen["data"] == big
        assert b.wire_errors == 0
        # small messages keep riding the binary wire afterwards
        got.clear()
        seen.clear()
        assert await a.send_message("node-b", "file", data=b"tiny")
        await asyncio.wait_for(got.wait(), 5)
        assert isinstance(seen["data"], memoryview)
        await a.stop()
        await b.stop()

    run(main())


def test_chunked_binary_roundtrip_and_zero_copy(run):
    async def main():
        a, b = await _pair()
        a.chunk_size = 4096
        assert a.peer_wire_format("node-b") == "bin1"
        got = asyncio.Event()
        seen = {}

        async def on_big(peer_id, msg):
            seen.update(msg)
            got.set()

        b.register_message_handler("big", on_big)
        payload = bytes(range(256)) * 256  # 64 KiB -> chunked binary frames
        assert await a.send_message("node-b", "big", data=payload, small=b"s")
        await asyncio.wait_for(got.wait(), 10)
        # raw fields arrive as memoryviews into the (reassembled) buffer —
        # the zero-copy contract the AEAD open batch relies on
        assert isinstance(seen["small"], memoryview)
        assert bytes(seen["data"]) == payload
        await a.stop()
        await b.stop()

    run(main())


# -- messaging-level: corrupt ciphertext mid-session --------------------------


def test_corrupt_ciphertext_over_binary_wire_triggers_rekey_not_crash(run):
    """A fault-plan-corrupted ciphertext on the binary wire must fail the
    AEAD open (typed), trigger the rekey machinery, and leave the
    connection + serving loop alive — the subsequent message delivers."""
    from quantum_resistant_p2p_tpu.app.messaging import SecureMessaging
    from quantum_resistant_p2p_tpu.faults import FaultPlan, FaultRule
    from quantum_resistant_p2p_tpu.fleet.stormlib import (
        register_storm_providers)
    from quantum_resistant_p2p_tpu.provider import (get_kem, get_signature,
                                                    get_symmetric)

    register_storm_providers()

    async def main():
        a_node = P2PNode(node_id="alice", host="127.0.0.1", port=0)
        b_node = P2PNode(node_id="bob", host="127.0.0.1", port=0)
        await a_node.start()
        await b_node.start()
        kw = dict(kem=get_kem("STORM-KEM"), signature=get_signature("STORM-SIG"),
                  symmetric=get_symmetric("ChaCha20-Poly1305"))
        a, b = SecureMessaging(a_node, **kw), SecureMessaging(b_node, **kw)
        inbox = []
        b.register_message_listener(
            lambda p, m: None if m.is_system else inbox.append(m.content))
        assert await a_node.connect_to_peer("127.0.0.1", b_node.port) == "bob"
        assert a_node.peer_wire_format("bob") == "bin1"
        assert await a.initiate_key_exchange("bob")

        old_key = a.shared_keys["bob"]
        plan = FaultPlan(seed=5, rules=[
            FaultRule("net.send", "corrupt", corrupt_field="ct",
                      match={"msg_type": "secure_message"}, nth=1),
        ])
        with plan.activate():
            await a.send_message("bob", b"corrupted in flight")
        assert plan.injected, "the corrupt rule never fired"
        for _ in range(100):
            if b._ctr_rekeys.value:
                break
            await asyncio.sleep(0.05)
        assert b._ctr_rekeys.value == 1  # AEAD failure -> automatic rekey
        assert b_node.wire_errors == 0  # transport stayed healthy
        # loop alive: once the NEW key lands on both sides (a send during
        # the rekey overlap would ride the dropped key — undecryptable by
        # design), the next message delivers
        for _ in range(200):
            if (a.shared_keys.get("bob") not in (None, old_key)
                    and b.verify_key_exchange_state("alice")
                    and a.verify_key_exchange_state("bob")):
                break
            await asyncio.sleep(0.05)
        assert a.shared_keys.get("bob") not in (None, old_key)
        await a.send_message("bob", b"after the storm")
        for _ in range(100):
            if inbox:
                break
            await asyncio.sleep(0.05)
        assert inbox == [b"after the storm"]
        await a_node.stop()
        await b_node.stop()

    run(main())
