"""Bit-exactness of the fused Pallas RejNTTPoly pipeline (sig/mldsa_pallas.py).

Same testing strategy as tests/test_mlkem_pallas.py: the kernel body is a
pure tile-list function run EAGERLY here (interpret mode and XLA-CPU both
choke on the ~110k-op unrolled body); the native pallas_call is exercised
on the real chip by tools/full_bench.py config 4.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from quantum_resistant_p2p_tpu.core import keccak
from quantum_resistant_p2p_tpu.core.sortnet import (
    bitonic_sort_pairs,
    bitonic_sort_pairs_regs,
)
from quantum_resistant_p2p_tpu.sig import mldsa, mldsa_pallas


def test_sort_pairs_regs_matches_array_sort_pairs():
    rng = np.random.default_rng(4)
    n, lanes = 64, 5
    keys = rng.permutation(n * 3)[:n].astype(np.int32)  # unique
    keys = np.stack([rng.permutation(keys) for _ in range(lanes)], axis=1)  # (n, lanes)
    vals = rng.integers(0, 1 << 23, (n, lanes), dtype=np.int32)
    ks, vs = bitonic_sort_pairs_regs(
        [jnp.asarray(keys[i]) for i in range(n)],
        [jnp.asarray(vals[i]) for i in range(n)],
    )
    got_k = np.stack([np.asarray(k) for k in ks])
    got_v = np.stack([np.asarray(v) for v in vs])
    ref_k, ref_v = bitonic_sort_pairs(jnp.asarray(keys.T), jnp.asarray(vals.T))
    assert np.array_equal(got_k, np.asarray(ref_k).T)
    assert np.array_equal(got_v, np.asarray(ref_v).T)


def test_rej_ntt_tiles_bit_exact_vs_jnp_path(monkeypatch):
    monkeypatch.setenv("QRP2P_PALLAS", "0")  # reference = jnp rej_ntt_poly
    rng = np.random.default_rng(9)
    B = 32
    seeds = jnp.asarray(rng.integers(0, 256, (B, 34), dtype=np.uint8))
    ref = np.asarray(mldsa.rej_ntt_poly(seeds))

    block = keccak.pad_single_block(seeds, 168, 0x1F)
    ph, plo = keccak._bytes_to_words(block)
    out = mldsa_pallas._rej_ntt_tiles(
        [ph[:, w] for w in range(mldsa_pallas.RATE_WORDS)],
        [plo[:, w] for w in range(mldsa_pallas.RATE_WORDS)],
    )
    got = np.stack([np.asarray(o) for o in out], axis=-1)
    assert np.array_equal(got, ref)
    assert got.max() < mldsa.Q


@pytest.mark.parametrize("eta", [2, 4])
def test_rej_bounded_tiles_bit_exact_vs_jnp_path(eta, monkeypatch):
    monkeypatch.setenv("QRP2P_PALLAS", "0")
    rng = np.random.default_rng(3 + eta)
    B = 32
    seeds = jnp.asarray(rng.integers(0, 256, (B, 66), dtype=np.uint8))
    ref = np.asarray(mldsa.rej_bounded_poly(eta, seeds))
    block = keccak.pad_single_block(seeds, 136, 0x1F)
    ph, plo = keccak._bytes_to_words(block)
    out = mldsa_pallas._rej_bounded_tiles(
        [ph[:, w] for w in range(mldsa_pallas.RB_RATE_WORDS)],
        [plo[:, w] for w in range(mldsa_pallas.RB_RATE_WORDS)],
        eta,
    )
    z = np.stack([np.asarray(o) for o in out], axis=-1)
    got = (2 - z % 5) % mldsa.Q if eta == 2 else (4 - z) % mldsa.Q
    assert np.array_equal(got, ref)


def test_ntt_tiles_bit_exact_vs_jnp(monkeypatch):
    """VMEM NTT/invNTT tile functions (eager) against the jnp transforms,
    plus round-trip."""
    monkeypatch.setenv("QRP2P_PALLAS", "0")  # reference = jnp ntt/ntt_inv
    rng = np.random.default_rng(21)
    lanes = 7
    f = rng.integers(0, mldsa.Q, (lanes, 256), dtype=np.int32)
    tiles = [jnp.asarray(f[:, i]) for i in range(256)]

    fwd = mldsa_pallas.ntt_tiles(tiles)
    got_fwd = np.stack([np.asarray(t) for t in fwd], axis=-1)
    ref_fwd = np.asarray(mldsa.ntt(jnp.asarray(f)))
    assert np.array_equal(got_fwd, ref_fwd)

    inv = mldsa_pallas.ntt_inv_tiles(fwd)
    got_inv = np.stack([np.asarray(t) for t in inv], axis=-1)
    ref_inv = np.asarray(mldsa.ntt_inv(jnp.asarray(ref_fwd)))
    assert np.array_equal(got_inv, ref_inv)
    assert np.array_equal(got_inv, f)  # round-trip
