"""Multi-chip sharding in the PRODUCTION provider path (8-device virtual mesh).

The conftest pins an 8-device virtual CPU platform, so these tests exercise
the same GSPMD partitioning a real multi-chip TPU pod would run: providers
constructed with ``devices=8`` shard every device batch across the mesh via
provider.base.mesh_dispatch (computation follows data — no collectives on the
hot path), and results must be BIT-EXACT vs the single-device path, including
batches not divisible by (or smaller than) the mesh.

Reference analog: none — the reference has no device parallelism (SURVEY.md
§2.3); this is the framework's TPU-native scale-out axis.
"""

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.parallel.mesh import make_mesh
from quantum_resistant_p2p_tpu.provider import get_kem, get_signature
from quantum_resistant_p2p_tpu.provider.base import mesh_dispatch, sliced_dispatch

RNG = np.random.default_rng(20260730)
NDEV = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(NDEV)


def test_mesh_dispatch_kernel_bit_exact_nondivisible(mesh):
    """Raw jitted kernels, batch 11 on 8 devices: sharded == unsharded."""
    from quantum_resistant_p2p_tpu.kem import mlkem

    kg, enc, dec = mlkem.get("ML-KEM-512")
    n = 11
    d, z, m = (RNG.integers(0, 256, (n, 32), dtype=np.uint8) for _ in range(3))

    ek_s, dk_s = mesh_dispatch(kg, mesh, d, z)
    ek_r, dk_r = (np.asarray(o) for o in kg(d, z))
    assert np.array_equal(ek_s, ek_r) and np.array_equal(dk_s, dk_r)

    key_s, ct_s = mesh_dispatch(enc, mesh, ek_r, m)
    key_r, ct_r = (np.asarray(o) for o in enc(ek_r, m))
    assert np.array_equal(key_s, key_r) and np.array_equal(ct_s, ct_r)

    key2_s = mesh_dispatch(dec, mesh, dk_r, ct_r)
    assert np.array_equal(key2_s, key_r)


def test_mesh_dispatch_batch_smaller_than_mesh(mesh):
    """3 rows on 8 devices: padded to one row per device, trimmed back."""
    from quantum_resistant_p2p_tpu.kem import mlkem

    kg, _, _ = mlkem.get("ML-KEM-512")
    d, z = (RNG.integers(0, 256, (3, 32), dtype=np.uint8) for _ in range(2))
    ek_s, dk_s = mesh_dispatch(kg, mesh, d, z)
    ek_r, dk_r = (np.asarray(o) for o in kg(d, z))
    assert np.array_equal(ek_s, ek_r) and np.array_equal(dk_s, dk_r)


def test_kem_provider_mesh_bit_exact_vs_single_device():
    """Production ML-KEM provider with devices=8 vs devices=0, batch 11."""
    single = get_kem("ML-KEM-512", backend="tpu")
    sharded = get_kem("ML-KEM-512", backend="tpu", devices=NDEV)
    assert sharded._mesh is not None and single._mesh is None

    n = 11
    eks, dks = single.generate_keypair_batch(n)
    cts, keys = single.encapsulate_batch(eks)
    # decaps is deterministic given (sk, ct): sharded must match bit-for-bit
    assert np.array_equal(sharded.decapsulate_batch(dks, cts), keys)
    # full roundtrip through the sharded provider (encaps draws fresh m)
    cts2, keys2 = sharded.encapsulate_batch(eks)
    assert np.array_equal(sharded.decapsulate_batch(dks, cts2), keys2)


def test_sliced_dispatch_shards_each_slice(mesh, monkeypatch):
    """Per-device cap + mesh: a 20-row batch on cap=1 x 8 devices runs as
    ceil(20/8)=3 sharded dispatches and still matches the unsharded result."""
    from quantum_resistant_p2p_tpu.kem import mlkem

    _, _, dec = mlkem.get("ML-KEM-512")
    single = get_kem("ML-KEM-512", backend="tpu")
    n = 20
    eks, dks = single.generate_keypair_batch(n)
    cts, keys = single.encapsulate_batch(eks)

    calls = []
    real = mesh_dispatch

    def counting(fn, m, *arrays):
        calls.append(arrays[0].shape[0])
        return real(fn, m, *arrays)

    import quantum_resistant_p2p_tpu.provider.base as base

    monkeypatch.setattr(base, "mesh_dispatch", counting)
    got = base.sliced_dispatch(dec, 1, dks, cts, mesh=mesh)
    assert np.array_equal(got, keys)
    assert calls == [8, 8, 8]  # 20 rows -> two full slices + padded tail


def test_mldsa_provider_mesh_sign_verify_bit_exact():
    """ML-DSA sign (fixed rnd) and verify, devices=8 vs devices=0, batch 5."""
    single = get_signature("ML-DSA-44", backend="tpu")
    sharded = get_signature("ML-DSA-44", backend="tpu", devices=NDEV)

    pk, sk = single.generate_keypair()
    n = 5
    sks = np.broadcast_to(np.frombuffer(sk, np.uint8), (n, len(sk)))
    pks = np.broadcast_to(np.frombuffer(pk, np.uint8), (n, len(pk)))
    msgs = [b"mesh msg %d" % i for i in range(n)]
    rnd = [bytes([i]) * 32 for i in range(n)]

    ref = single.sign_batch(sks, msgs, rnd=rnd)
    got = sharded.sign_batch(sks, msgs, rnd=rnd)
    assert [bytes(s) for s in got] == [bytes(s) for s in ref]

    oks = sharded.verify_batch(pks, msgs, got)
    assert np.asarray(oks).all()
    bad = sharded.verify_batch(pks, [m + b"!" for m in msgs], got)
    assert not np.asarray(bad).any()


@pytest.mark.slow
def test_sphincs_provider_mesh_verify_bit_exact():
    """SPHINCS+ verify through the mesh, batch 3 (slow tier: JAX sign)."""
    single = get_signature("SPHINCS+-SHA2-128f-simple", backend="tpu")
    sharded = get_signature("SPHINCS+-SHA2-128f-simple", backend="tpu", devices=NDEV)

    pk, sk = single.generate_keypair()
    n = 3
    sks = np.broadcast_to(np.frombuffer(sk, np.uint8), (n, len(sk)))
    pks = np.broadcast_to(np.frombuffer(pk, np.uint8), (n, len(pk)))
    msgs = [b"slh mesh %d" % i for i in range(n)]
    sigs = single.sign_batch(sks, msgs)  # deterministic variant
    assert [bytes(s) for s in sharded.sign_batch(sks, msgs)] == [
        bytes(s) for s in sigs
    ]
    assert np.asarray(sharded.verify_batch(pks, msgs, sigs)).all()
    assert not np.asarray(
        sharded.verify_batch(pks, [m + b"x" for m in msgs], sigs)
    ).any()


def test_messaging_constructs_with_mesh_devices(tmp_path):
    """Config knob reaches the providers through SecureMessaging."""
    pytest.importorskip("cryptography")  # messaging pulls host HKDF/AEAD
    from quantum_resistant_p2p_tpu.app.messaging import SecureMessaging
    from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode

    node = P2PNode(node_id="mesh-test-node", host="127.0.0.1", port=0)
    m = SecureMessaging(node, backend="tpu", mesh_devices=NDEV)
    assert m.kem._mesh is not None and m.kem._mesh.size == NDEV
    assert m.signature._mesh is not None
