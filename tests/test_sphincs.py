"""SPHINCS+ batched JAX vs pure-Python oracle (bit-exact).

Slow tier: the hypertree graphs (d layers x unrolled WOTS chains) cost
minutes of TRACE time per parameter set — jax's persistent cache skips XLA
compilation but not tracing, so these pay their cost on every run.  The
fast tier still proves SPHINCS+ correctness for every parameter set through
the native C++/pyref KATs (tests/test_native.py, tests/test_kat.py); this
module proves the JAX implementation bit-exact and runs nightly.
"""

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.pyref import slhdsa_ref as slh
from quantum_resistant_p2p_tpu.sig import sphincs as jslh

pytestmark = pytest.mark.slow

RNG = np.random.default_rng(20260730)

FAST_SETS = [
    "SPHINCS+-SHA2-128f-simple",
    pytest.param("SPHINCS+-SHA2-192f-simple", marks=pytest.mark.slow),
    pytest.param("SPHINCS+-SHA2-256f-simple", marks=pytest.mark.slow),
    pytest.param("SPHINCS+-SHA2-128s-simple", marks=pytest.mark.slow),
    pytest.param("SPHINCS+-SHA2-192s-simple", marks=pytest.mark.slow),
    pytest.param("SPHINCS+-SHA2-256s-simple", marks=pytest.mark.slow),
]


def _batch_seeds(p, batch):
    return [RNG.integers(0, 256, size=(batch, p.n), dtype=np.uint8) for _ in range(3)]


def _oracle_keys_rs_digests(p, batch, msgs):
    """Oracle keygen + FIPS message path: -> (sks list, sk_arr, r_arr, digest_arr)."""
    sk_seed, sk_prf, pk_seed = _batch_seeds(p, batch)
    sks = [
        slh.keygen(p, sk_seed[i].tobytes(), sk_prf[i].tobytes(), pk_seed[i].tobytes())[1]
        for i in range(batch)
    ]
    rs, digests = [], []
    for i in range(batch):
        skb = sks[i]
        r = slh.prf_msg(p, skb[p.n : 2 * p.n], skb[2 * p.n : 3 * p.n], msgs[i])
        rs.append(np.frombuffer(r, np.uint8))
        digests.append(
            np.frombuffer(
                slh.h_msg(p, r, skb[2 * p.n : 3 * p.n], skb[3 * p.n :], msgs[i]), np.uint8
            )
        )
    sk_arr = np.stack([np.frombuffer(s, np.uint8) for s in sks])
    return sks, sk_arr, np.stack(rs), np.stack(digests)


@pytest.mark.parametrize("name", FAST_SETS)
def test_keygen_matches_oracle(name):
    p = slh.PARAMS[name]
    batch = 2
    sk_seed, sk_prf, pk_seed = _batch_seeds(p, batch)
    kg, _, _ = jslh.get(name)
    pk, sk = kg(sk_seed, sk_prf, pk_seed)
    for i in range(batch):
        rpk, rsk = slh.keygen(
            p, sk_seed[i].tobytes(), sk_prf[i].tobytes(), pk_seed[i].tobytes()
        )
        assert bytes(np.asarray(pk)[i]) == rpk
        assert bytes(np.asarray(sk)[i]) == rsk


@pytest.mark.parametrize("name", ["SPHINCS+-SHA2-128f-simple"])
def test_sign_verify_match_oracle(name):
    p = slh.PARAMS[name]
    batch = 2
    _, sign_digest, verify_digest = jslh.get(name)
    msgs = [b"msg-%d" % i * (i + 1) for i in range(batch)]
    sks, sk_arr, r_arr, digest_arr = _oracle_keys_rs_digests(p, batch, msgs)
    sigs = np.asarray(sign_digest(sk_arr, r_arr, digest_arr))
    for i in range(batch):
        ref_sig = slh.sign(p, sks[i], msgs[i])
        assert bytes(sigs[i]) == ref_sig, f"lane {i} diverges from oracle"
    pk_arr = sk_arr[:, 2 * p.n :]
    ok = np.asarray(verify_digest(pk_arr, digest_arr, sigs))
    assert ok.all()
    bad = sigs.copy()
    bad[:, p.n + 3] ^= 0xFF
    assert not np.asarray(verify_digest(pk_arr, digest_arr, bad)).any()


def test_provider_roundtrip_and_cross_backend():
    from quantum_resistant_p2p_tpu.provider import get_signature

    tpu = get_signature("SPHINCS+-SHA2-128f-simple", backend="tpu")
    cpu = get_signature("SPHINCS+-SHA2-128f-simple", backend="cpu")
    pk, sk = tpu.generate_keypair()
    msg = b"sphincs provider parity"
    sig = tpu.sign(sk, msg)
    assert len(sig) == tpu.signature_len
    assert tpu.verify(pk, msg, sig)
    assert cpu.verify(pk, msg, sig)
    assert not tpu.verify(pk, msg + b"x", sig)
    cpu_sig = cpu.sign(sk, msg)
    assert cpu_sig == sig  # both deterministic


def test_layered_sign_matches_oracle_128f():
    """sign_digest_layered is bit-exact vs the oracle across all 22 layers.

    Cheaper than the monolithic oracle test: the layered path compiles one
    FORS program plus ONE XMSS-layer program (layer index traced), so its
    trace is ~d x smaller than sign_digest's.
    """
    name = "SPHINCS+-SHA2-128f-simple"
    p = slh.PARAMS[name]
    batch = 2
    msgs = [b"layered-%d" % i for i in range(batch)]
    sks, sk_arr, r_arr, digest_arr = _oracle_keys_rs_digests(p, batch, msgs)
    sigs = np.asarray(jslh.sign_digest_layered(p, sk_arr, r_arr, digest_arr))
    for i in range(batch):
        assert bytes(sigs[i]) == slh.sign(p, sks[i], msgs[i]), f"lane {i} diverges"


def test_layered_sign_128s_matches_oracle_and_verifies():
    """The s-set default path: bit-exact vs the oracle + verify/tamper.

    Keys come from the ORACLE keygen (sk is just seeds || pk), which skips
    the expensive JAX-keygen trace; the layered sign itself compiles only
    the FORS + one-XMSS-layer programs.
    """
    name = "SPHINCS+-SHA2-128s-simple"
    p = slh.PARAMS[name]
    batch = 2
    msgs = [b"layered-s-%d" % i for i in range(batch)]
    sks, sk_arr, r_arr, digest_arr = _oracle_keys_rs_digests(p, batch, msgs)
    sigs = np.asarray(jslh.sign_digest_layered(p, sk_arr, r_arr, digest_arr))
    for i in range(batch):
        assert bytes(sigs[i]) == slh.sign(p, sks[i], msgs[i]), f"lane {i} diverges"
    pk_arr = sk_arr[:, 2 * p.n :]
    assert np.asarray(jslh.verify_digest(p, pk_arr, digest_arr, sigs)).all()
    bad = sigs.copy()
    bad[:, p.n + 3] ^= 0xFF
    assert not np.asarray(jslh.verify_digest(p, pk_arr, digest_arr, bad)).any()
