"""SPHINCS+ batched JAX vs pure-Python oracle (bit-exact).

Slow tier: the hypertree graphs (d layers x unrolled WOTS chains) cost
minutes of TRACE time per parameter set — jax's persistent cache skips XLA
compilation but not tracing, so these pay their cost on every run.  The
fast tier still proves SPHINCS+ correctness for every parameter set through
the native C++/pyref KATs (tests/test_native.py, tests/test_kat.py); this
module proves the JAX implementation bit-exact and runs nightly.
"""

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.pyref import slhdsa_ref as slh
from quantum_resistant_p2p_tpu.sig import sphincs as jslh

pytestmark = pytest.mark.slow

RNG = np.random.default_rng(20260730)

FAST_SETS = [
    "SPHINCS+-SHA2-128f-simple",
    pytest.param("SPHINCS+-SHA2-192f-simple", marks=pytest.mark.slow),
    pytest.param("SPHINCS+-SHA2-256f-simple", marks=pytest.mark.slow),
    pytest.param("SPHINCS+-SHA2-128s-simple", marks=pytest.mark.slow),
    pytest.param("SPHINCS+-SHA2-192s-simple", marks=pytest.mark.slow),
    pytest.param("SPHINCS+-SHA2-256s-simple", marks=pytest.mark.slow),
]


def _batch_seeds(p, batch):
    return [RNG.integers(0, 256, size=(batch, p.n), dtype=np.uint8) for _ in range(3)]


@pytest.mark.parametrize("name", FAST_SETS)
def test_keygen_matches_oracle(name):
    p = slh.PARAMS[name]
    batch = 2
    sk_seed, sk_prf, pk_seed = _batch_seeds(p, batch)
    kg, _, _ = jslh.get(name)
    pk, sk = kg(sk_seed, sk_prf, pk_seed)
    for i in range(batch):
        rpk, rsk = slh.keygen(
            p, sk_seed[i].tobytes(), sk_prf[i].tobytes(), pk_seed[i].tobytes()
        )
        assert bytes(np.asarray(pk)[i]) == rpk
        assert bytes(np.asarray(sk)[i]) == rsk


@pytest.mark.parametrize("name", ["SPHINCS+-SHA2-128f-simple"])
def test_sign_verify_match_oracle(name):
    p = slh.PARAMS[name]
    batch = 2
    sk_seed, sk_prf, pk_seed = _batch_seeds(p, batch)
    kg, sign_digest, verify_digest = jslh.get(name)
    pk, sk = np.asarray(kg(sk_seed, sk_prf, pk_seed)[0]), None
    pks, sks = [], []
    for i in range(batch):
        rpk, rsk = slh.keygen(p, sk_seed[i].tobytes(), sk_prf[i].tobytes(), pk_seed[i].tobytes())
        pks.append(rpk)
        sks.append(rsk)
    msgs = [b"msg-%d" % i * (i + 1) for i in range(batch)]
    rs, digests = [], []
    for i in range(batch):
        skb = sks[i]
        r = slh.prf_msg(p, skb[p.n : 2 * p.n], skb[2 * p.n : 3 * p.n], msgs[i])
        rs.append(np.frombuffer(r, np.uint8))
        digests.append(
            np.frombuffer(
                slh.h_msg(p, r, skb[2 * p.n : 3 * p.n], skb[3 * p.n :], msgs[i]), np.uint8
            )
        )
    sk_arr = np.stack([np.frombuffer(s, np.uint8) for s in sks])
    sigs = np.asarray(sign_digest(sk_arr, np.stack(rs), np.stack(digests)))
    for i in range(batch):
        ref_sig = slh.sign(p, sks[i], msgs[i])
        assert bytes(sigs[i]) == ref_sig, f"lane {i} diverges from oracle"
    pk_arr = np.stack([np.frombuffer(k, np.uint8) for k in pks])
    ok = np.asarray(verify_digest(pk_arr, np.stack(digests), sigs))
    assert ok.all()
    bad = sigs.copy()
    bad[:, p.n + 3] ^= 0xFF
    assert not np.asarray(verify_digest(pk_arr, np.stack(digests), bad)).any()


def test_provider_roundtrip_and_cross_backend():
    from quantum_resistant_p2p_tpu.provider import get_signature

    tpu = get_signature("SPHINCS+-SHA2-128f-simple", backend="tpu")
    cpu = get_signature("SPHINCS+-SHA2-128f-simple", backend="cpu")
    pk, sk = tpu.generate_keypair()
    msg = b"sphincs provider parity"
    sig = tpu.sign(sk, msg)
    assert len(sig) == tpu.signature_len
    assert tpu.verify(pk, msg, sig)
    assert cpu.verify(pk, msg, sig)
    assert not tpu.verify(pk, msg + b"x", sig)
    cpu_sig = cpu.sign(sk, msg)
    assert cpu_sig == sig  # both deterministic
