"""Real pallas_call launcher plumbing, gated on a TPU backend.

The CPU suite (conftest pins an 8-device virtual CPU platform) covers the
kernel *bodies* eagerly; these tests run the actual ``pallas_call`` —
Mosaic compilation, BlockSpec/grid setup, hi/lo word transport — and so
only execute when the process sees a TPU.  The driver-facing entry point is
``python -m tools.check_pallas_device`` (same checks, standalone process,
respecting the one-TPU-process rule); its latest on-chip result is recorded
in bench_report.md.
"""

import pytest

jax = pytest.importorskip("jax")

from tools import check_pallas_device  # noqa: E402

pytestmark = pytest.mark.skipif(
    jax.devices()[0].platform != "tpu",
    reason="real pallas_call needs Mosaic/TPU (suite is CPU-pinned); "
    "run tools/check_pallas_device.py on the chip",
)


@pytest.mark.parametrize("name,fn", check_pallas_device.CHECKS,
                         ids=[n for n, _ in check_pallas_device.CHECKS])
def test_pallas_launcher_bit_exact(name, fn):
    fn()
