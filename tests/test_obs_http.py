"""obs/http.py + obs/cost.py acceptance suite (ISSUE 12).

Live telemetry endpoints: golden responses per path, hostile input
(unknown path, oversized request line, write verbs, concurrent scrapes),
readiness flipping on breaker state, scrape-advances-SLO-engine parity
with the PR 10 ``slo_health`` collector, and the disabled-by-default
contract (no listener, bit-identical engine surface).

Device-cost ledger: padding-waste math at pow2 bucket boundaries, compile
attribution (warmup-thread vs in-flush cold compile), opcache hit-rate
windows, the per-1k-handshakes derived gauge, and autotuner-journal
determinism under injected clocks.

Stdlib-only; runs on minimal images.
"""

from __future__ import annotations

import asyncio
import json
import socket
import threading
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import pytest

from quantum_resistant_p2p_tpu.app.messaging import SecureMessaging
from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode
from quantum_resistant_p2p_tpu.obs.cost import (JOURNAL_CAP, CostLedger,
                                                OPCACHE_WINDOW)
from quantum_resistant_p2p_tpu.obs.http import (MAX_RESPONSE_BYTES,
                                                TelemetryServer, env_port,
                                                json_route)
from quantum_resistant_p2p_tpu.obs.metrics import (PROMETHEUS_CONTENT_TYPE,
                                                   Registry, prometheus_text)
from quantum_resistant_p2p_tpu.provider.autotune import QueueTuner, TunerConfig
from quantum_resistant_p2p_tpu.provider.batched import (LANE_HANDSHAKE,
                                                        OpQueue)
from quantum_resistant_p2p_tpu.provider.opcache import DeviceOperandCache


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


def _mk_engine(monkeypatch, **kw):
    monkeypatch.setattr(SecureMessaging, "_spawn_warmup",
                        lambda self, **k: None)
    node = P2PNode(node_id="httppeer", host="127.0.0.1", port=0)
    return SecureMessaging(node, backend="tpu", use_batching=True,
                           sig_keypair=(b"p", b"s"),
                           symmetric=type("A", (), {"name": "X"})(), **kw)


@pytest.fixture
def engine(monkeypatch):
    m = _mk_engine(monkeypatch, telemetry_port=0)
    yield m
    m.stop_telemetry()


def _get(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as r:
        return r.status, r.headers.get("Content-Type"), r.read()


# -- endpoint goldens ----------------------------------------------------------


def test_endpoint_goldens(engine):
    port = engine.telemetry_port
    assert port and port > 0

    status, ctype, body = _get(port, "/healthz")
    doc = json.loads(body)
    assert status == 200 and ctype == "application/json"
    assert doc["ok"] is True and doc["node"] == "httppeer"
    assert doc["uptime_s"] >= 0

    status, ctype, body = _get(port, "/metrics")
    text = body.decode()
    assert status == 200 and ctype == PROMETHEUS_CONTENT_TYPE
    assert text.startswith("# HELP")
    assert "qrp2p_padding_waste_fraction" in text
    assert "qrp2p_cost_compile_events_total" in text
    assert "qrp2p_device_seconds_per_1k_handshakes" in text
    assert "qrp2p_handshake_trips" in text

    status, _, body = _get(port, "/metrics.json")
    snap = json.loads(body)
    assert snap["registry"].startswith("messaging:")
    assert "queues" in snap["collected"]

    status, _, body = _get(port, "/slo")
    slo = json.loads(body)
    assert {s["name"] for s in slo["specs"]} >= {"handshake_p99"}
    assert slo["alerting"] == []

    status, _, body = _get(port, "/cost")
    cost = json.loads(body)
    assert {"padding_waste_fraction", "occupancy", "compiles",
            "device_seconds_by_op", "opcaches",
            "tuner_journal_tail"} <= set(cost)

    status, _, body = _get(port, "/trace")
    trace = json.loads(body)
    assert "traceEvents" in trace


def test_http_metrics_shares_the_cli_serializer_path(engine):
    """Satellite: ONE Prometheus exposition path.  The HTTP body and the
    CLI's prometheus_text() must agree on the full metric schema (HELP/
    TYPE lines) — they are the same function, so only sample values that
    move between the two renders may differ."""
    _, _, body = _get(engine.telemetry_port, "/metrics")
    schema_http = {l for l in body.decode().splitlines()
                   if l.startswith("# ")}
    schema_cli = {l for l in prometheus_text(engine.registry).splitlines()
                  if l.startswith("# ")}
    assert schema_http == schema_cli


# -- hostile input -------------------------------------------------------------


def test_unknown_path_is_404(engine):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(engine.telemetry_port, "/secrets")
    assert e.value.code == 404
    assert json.loads(e.value.read())["error"] == "unknown path"


def test_write_verbs_rejected(engine):
    req = urllib.request.Request(
        f"http://127.0.0.1:{engine.telemetry_port}/metrics",
        data=b"x=1", method="POST")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=5)
    assert e.value.code == 405


def test_oversized_request_line_bounded(engine):
    """A request line past the stdlib's 64 KiB cap answers 414 — parsing
    is bounded, the listener survives, and the next scrape works."""
    with socket.create_connection(("127.0.0.1", engine.telemetry_port),
                                  timeout=5) as s:
        s.sendall(b"GET /" + b"a" * 70_000 + b" HTTP/1.0\r\n\r\n")
        status_line = s.recv(4096).split(b"\r\n", 1)[0]
    assert b"414" in status_line
    status, _, _ = _get(engine.telemetry_port, "/healthz")
    assert status == 200


def test_concurrent_scrapes(engine):
    port = engine.telemetry_port

    def scrape(_):
        status, _, body = _get(port, "/metrics")
        return status, b"qrp2p_padding_waste_fraction" in body

    with ThreadPoolExecutor(max_workers=8) as pool:
        results = list(pool.map(scrape, range(16)))
    assert all(status == 200 and found for status, found in results)


def test_oversized_response_bounded():
    srv = TelemetryServer({
        "/big": lambda: (200, "application/json",
                         b"x" * (MAX_RESPONSE_BYTES + 1)),
        "/boom": json_route(lambda: 1 / 0),
    }).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(srv.port, "/big")
        assert e.value.code == 503
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(srv.port, "/boom")
        assert e.value.code == 500
        assert json.loads(e.value.read())["error"] == "handler failed"
    finally:
        srv.stop()


# -- readiness -----------------------------------------------------------------


def test_readiness_flips_on_breaker_open(engine):
    port = engine.telemetry_port
    status, _, body = _get(port, "/readyz")
    assert status == 200 and json.loads(body)["ready"] is True

    engine._queue_breaker.trip()
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(port, "/readyz")
    assert e.value.code == 503
    doc = json.loads(e.value.read())
    assert doc["ready"] is False and doc["degraded"] == ["shard0"]
    # liveness is unaffected: the process is alive, just not ready
    status, _, _ = _get(port, "/healthz")
    assert status == 200


def test_readiness_waits_for_warmup(monkeypatch):
    """A gateway mid-warmup answers 503: its first handshakes would be
    served from the cpu fallback at cpu latency."""
    release = threading.Event()

    def slow_warm(self, **kw):
        t = threading.Thread(target=release.wait, daemon=True)
        t.start()
        self._warmup_thread = t

    monkeypatch.setattr(SecureMessaging, "_spawn_warmup", slow_warm)
    node = P2PNode(node_id="warmpeer", host="127.0.0.1", port=0)
    m = SecureMessaging(node, backend="tpu", use_batching=True,
                        sig_keypair=(b"p", b"s"),
                        symmetric=type("A", (), {"name": "X"})(),
                        telemetry_port=0)
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(m.telemetry_port, "/readyz")
        assert e.value.code == 503
        assert json.loads(e.value.read())["warm"] is False
        release.set()
        m._warmup_thread.join(timeout=5)
        status, _, body = _get(m.telemetry_port, "/readyz")
        assert status == 200 and json.loads(body)["ready"] is True
    finally:
        release.set()
        m.stop_telemetry()


# -- scrape-advances-the-SLO-engine parity ------------------------------------


def test_http_scrape_advances_slo_engine(engine):
    """Parity with the PR 10 ``slo_health`` collector contract
    (tests/test_slo.py::test_prometheus_scrape_advances_slo_engine): a
    gateway watched ONLY through HTTP scrapes still evaluates its SLOs —
    the endpoint renders through the registry, whose collector ticks the
    engine."""
    before = engine.slo._states["handshake_p99"].samples
    n0 = len(before)
    _, _, body = _get(engine.telemetry_port, "/metrics")
    text = body.decode()
    assert "qrp2p_slo_health_alerts_total" in text
    assert 'slo="handshake_p99"' in text  # evaluation set the gauges
    assert len(engine.slo._states["handshake_p99"].samples) > n0
    snap = json.loads(_get(engine.telemetry_port, "/metrics.json")[2])
    assert snap["collected"]["slo_health"]["alerting_count"] == 0


# -- disabled by default -------------------------------------------------------


def test_disabled_by_default_no_listener(monkeypatch):
    monkeypatch.delenv("QRP2P_HTTP_PORT", raising=False)
    m = _mk_engine(monkeypatch)
    assert m.telemetry is None and m.telemetry_port is None
    assert not [t for t in threading.enumerate()
                if t.name == "qrp2p-telemetry"]
    # the engine surface is identical with telemetry off: same metrics
    # document shape, same SLO specs, same cost ledger
    m2 = _mk_engine(monkeypatch, telemetry_port=0)
    try:
        assert set(m.metrics()) == set(m2.metrics())
        assert m.slo.names() == m2.slo.names()
        assert set(m.cost.snapshot()) == set(m2.cost.snapshot())
    finally:
        m2.stop_telemetry()
    m.stop_telemetry()  # idempotent no-op when disabled


def test_unbindable_port_degrades_instead_of_crashing(monkeypatch, caplog):
    """A fixed telemetry port that cannot bind (in use / privileged) must
    degrade to disabled with a WARNING — an optional observability
    listener never kills the serving engine."""
    taken = socket.socket()
    taken.bind(("127.0.0.1", 0))
    taken.listen(1)
    port = taken.getsockname()[1]
    try:
        with caplog.at_level("WARNING"):
            m = _mk_engine(monkeypatch, telemetry_port=port)
        assert m.telemetry is None and m.telemetry_port is None
        assert any("telemetry endpoints disabled" in r.message
                   for r in caplog.records)
    finally:
        taken.close()


def test_env_port_parsing(monkeypatch):
    monkeypatch.delenv("QRP2P_HTTP_PORT", raising=False)
    assert env_port() is None
    monkeypatch.setenv("QRP2P_HTTP_PORT", "")
    assert env_port() is None
    monkeypatch.setenv("QRP2P_HTTP_PORT", "0")
    assert env_port() == 0
    monkeypatch.setenv("QRP2P_HTTP_PORT", "9100")
    assert env_port() == 9100
    monkeypatch.setenv("QRP2P_HTTP_PORT", "nope")
    assert env_port() is None  # malformed -> disabled, never a crash


def test_stop_telemetry_closes_the_listener(monkeypatch):
    m = _mk_engine(monkeypatch, telemetry_port=0)
    port = m.telemetry_port
    m.stop_telemetry()
    assert m.telemetry is None
    with pytest.raises(OSError):
        socket.create_connection(("127.0.0.1", port), timeout=0.5).close()


# -- cost ledger: padding-waste math -------------------------------------------


def test_padding_waste_math_at_bucket_boundaries():
    reg = Registry(name="costtest")
    ledger = CostLedger(registry=reg)
    assert ledger.padding_waste_fraction() is None  # no flushes yet
    # exactly full bucket: zero waste
    ledger.flush_occupancy("K.encaps", "handshake", 8, 8)
    assert ledger.padding_waste_fraction() == 0.0
    # one past the boundary: 5 real rows pad to 8 -> 3 wasted of 16 total
    ledger.flush_occupancy("K.encaps", "handshake", 5, 8)
    assert ledger.padding_waste_fraction() == pytest.approx(3 / 16)
    # per-queue split and labels in the scrape
    ledger.flush_occupancy("S.sign", "bulk", 1, 4)
    assert ledger.padding_waste_fraction("K.encaps") == pytest.approx(3 / 16)
    assert ledger.padding_waste_fraction("S.sign") == pytest.approx(3 / 4)
    prom = reg.to_prometheus()
    assert ('qrp2p_cost_flush_items_padded_total{registry="costtest",'
            'lane="bulk",queue="S.sign"} 3') in prom
    snap = ledger.snapshot()
    assert snap["occupancy"]["S.sign[bulk]"]["waste_fraction"] == 0.75


def test_queue_records_occupancy_on_device_flush(run):
    """Integration: a warm OpQueue flush records real-vs-pow2-bucket
    occupancy, and a fallback flush records none (the cpu pads nothing)."""
    ledger = CostLedger()
    q = OpQueue(lambda items: [x * 2 for x in items], max_batch=64,
                max_wait_ms=1.0, bucket_floor=4, label="T.op")
    q.cost = ledger

    async def drive():
        return await asyncio.gather(*(q.submit(i) for i in range(5)))

    assert run(drive()) == [0, 2, 4, 6, 8]
    occ = ledger.snapshot()["occupancy"]["T.op[handshake]"]
    # 5 items pad to the pow2 bucket 8 (floor 4): 3 padded slots
    assert occ["items_real"] == 5 and occ["items_padded"] == 3
    assert occ["flushes"] == 1


def test_fallback_flush_records_no_occupancy(run):
    ledger = CostLedger()
    q = OpQueue(lambda items: items, max_batch=64, max_wait_ms=1.0,
                fallback_fn=lambda items: items, bucket_floor=4,
                label="T.cold")
    q.cost = ledger
    q.breaker.quarantine("test")  # pin the fallback: no device flush
    run(q.submit(7))
    assert ledger.padding_waste_fraction() is None
    assert "T.cold[handshake]" not in ledger.snapshot()["occupancy"]


# -- cost ledger: compile attribution ------------------------------------------


def test_compile_attribution_warmup_vs_in_flush(run):
    """The two compile paths label themselves: a facade warm sweep is
    ``warmup``; a live flush hitting a cold bucket is ``in_flush``."""
    ledger = CostLedger()
    ledger.compile_event("K", 4, 1.5, where="warmup", shard=1)

    q = OpQueue(lambda items: items, max_batch=64, max_wait_ms=1.0,
                fallback_fn=lambda items: items, label="T.cold2")
    q.cost = ledger

    async def drive():
        # cold bucket: ops served from the fallback, background compile
        out = await q.submit(9)
        for _ in range(100):
            if ledger.compile_totals()[0] >= 2:
                break
            await asyncio.sleep(0.02)
        return out

    assert run(drive()) == 9
    snap = ledger.snapshot()
    assert snap["compiles"]["K[shard=1,warmup]"]["events"] == 1
    assert snap["compiles"]["K[shard=1,warmup]"]["seconds"] == 1.5
    assert snap["compiles"]["T.cold2[shard=all,in_flush]"]["events"] == 1
    wheres = {e["where"] for e in snap["recent_compiles"]}
    assert wheres == {"warmup", "in_flush"}


# -- cost ledger: opcache windows + derived gauges -----------------------------


def test_opcache_hit_rate_sliding_window():
    reg = Registry(name="opctest")
    ledger = CostLedger(registry=reg)
    cache = DeviceOperandCache(capacity=4)
    cache.attach_cost(ledger, "kem")
    assert cache.lookup("ek", b"k1") is None  # miss
    cache.put("ek", b"k1", "state")
    assert cache.lookup("ek", b"k1") == "state"  # hit
    assert cache.lookup("ek", b"k1") == "state"  # hit
    assert ledger.opcache_hit_rate("kem") == pytest.approx(2 / 3)
    snap = ledger.snapshot()["opcaches"]["kem"]
    assert snap["hits"] == 2 and snap["misses"] == 1
    assert 'qrp2p_opcache_hit_rate{registry="opctest",cache="kem"}' in \
        reg.to_prometheus()
    # the window slides: an old miss ages out of the rate
    for _ in range(OPCACHE_WINDOW):
        cache.lookup("ek", b"k1")
    assert ledger.opcache_hit_rate("kem") == 1.0


def test_device_seconds_per_1k_handshakes():
    ledger = CostLedger()
    ledger.device_time("K.encaps", 0.25)
    ledger.device_time("S.sign", 0.75)
    assert ledger.device_seconds_per_1k_handshakes() is None  # no feed
    hs = {"n": 0}
    ledger.set_handshakes_fn(lambda: hs["n"])
    assert ledger.device_seconds_per_1k_handshakes() is None  # 0 handshakes
    hs["n"] = 500
    assert ledger.device_seconds_per_1k_handshakes() == pytest.approx(2.0)
    assert ledger.snapshot()["device_seconds_by_op"] == {
        "encaps": 0.25, "sign": 0.75}


def test_totals_feed_for_fleet_heartbeats():
    ledger = CostLedger()
    ledger.flush_occupancy("K.encaps", "handshake", 6, 8)
    ledger.compile_event("K", 8, 2.0, where="in_flush")
    ledger.device_time("K.encaps", 0.5)
    t = ledger.totals()
    assert t["items_real"] == 6 and t["items_padded"] == 2
    assert t["padding_waste_fraction"] == 0.25
    assert t["compile_events"] == 1 and t["compile_seconds"] == 2.0
    assert t["device_seconds"] == 0.5


# -- cost ledger: autotuner journal --------------------------------------------


class _FakeHist:
    def __init__(self, p50):
        self._p50 = p50

    def percentile(self, p):
        return self._p50


class _FakeStats:
    def __init__(self):
        self.ops = 0
        self.flushes = 0
        self.fallback_flushes = 0
        self.device_hist = _FakeHist(0.002)
        self.dispatch_hist = _FakeHist(0.002)


class _FakeBreaker:
    state = "closed"


class _FakeQueue:
    def __init__(self):
        self.label = "J.q"
        self.bucket_floor = 2
        self.stats = _FakeStats()
        self.breaker = _FakeBreaker()
        self.tuner = None


def _drive_tuner(ledger) -> list:
    clock = {"t": 0.0}
    q = _FakeQueue()
    tuner = QueueTuner(q, TunerConfig(), clock=lambda: clock["t"],
                       cost=ledger)
    # a deterministic offered-load trace: same deltas -> same decisions
    for step, (ops, flushes) in enumerate(
            [(64, 8), (256, 12), (1024, 14), (1100, 18)]):
        clock["t"] += 1.0
        q.stats.ops, q.stats.flushes = ops, flushes
        tuner.step()
    return ledger.journal()


def test_autotuner_journal_reconstructs_trajectory_deterministically():
    """Two tuners driven by the same injected clock over the same counter
    trace journal byte-identical trajectories — the property that makes a
    seeded storm's tuning history reconstructible from the ledger."""
    j1 = _drive_tuner(CostLedger())
    j2 = _drive_tuner(CostLedger())
    assert j1 == j2
    assert len(j1) == 4
    assert [e["seq"] for e in j1] == [1, 2, 3, 4]
    assert all(e["queue"] == "J.q" for e in j1)
    # every step carries its inputs and the chosen knobs
    assert {"avg_batch", "p50_device_ms", "p50_dispatch_ms",
            "rate_ops_s"} <= set(j1[0]["inputs"])
    assert j1[0]["bucket"] >= 2 and j1[0]["window_ms"] > 0
    # the demand-following bucket moved with the trace
    assert j1[2]["bucket"] > j1[0]["bucket"]


def test_journal_ring_is_bounded():
    ledger = CostLedger()
    for i in range(JOURNAL_CAP + 10):
        ledger.tuner_decision("q", float(i), {}, 4, 0.001, False, False)
    j = ledger.journal()
    assert len(j) == JOURNAL_CAP
    assert j[-1]["seq"] == JOURNAL_CAP + 10  # seq keeps counting
    assert ledger.snapshot()["tuner_journal_len"] == JOURNAL_CAP + 10
