"""HQC: batched JAX vs pure-Python oracle (bit-exact) + KEM properties."""

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.pyref import hqc_ref as hq

RNG = np.random.default_rng(17669)


@pytest.mark.parametrize(
    "name",
    ["HQC-128", pytest.param("HQC-192", marks=pytest.mark.slow)],
)
def test_matches_oracle(name):
    from quantum_resistant_p2p_tpu.kem import hqc as jhq

    p = hq.PARAMS[name]
    batch = 2
    kg, enc, dec = jhq.get(name)
    sk_seed = RNG.integers(0, 256, size=(batch, 40), dtype=np.uint8)
    sigma = RNG.integers(0, 256, size=(batch, p.k), dtype=np.uint8)
    pk_seed = RNG.integers(0, 256, size=(batch, 40), dtype=np.uint8)
    m = RNG.integers(0, 256, size=(batch, p.k), dtype=np.uint8)
    salt = RNG.integers(0, 256, size=(batch, 16), dtype=np.uint8)
    pk, sk = kg(sk_seed, sigma, pk_seed)
    pk, sk = np.asarray(pk), np.asarray(sk)
    ct, ss = enc(pk, m, salt)
    ct, ss = np.asarray(ct), np.asarray(ss)
    ss_dec = np.asarray(dec(sk, ct))
    for i in range(batch):
        rpk, rsk = hq.keygen(p, sk_seed[i].tobytes(), sigma[i].tobytes(), pk_seed[i].tobytes())
        assert bytes(pk[i]) == rpk
        assert bytes(sk[i]) == rsk
        rct, rss = hq.encaps(p, rpk, m[i].tobytes(), salt[i].tobytes())
        assert bytes(ct[i]) == rct
        assert bytes(ss[i]) == rss
        assert bytes(ss_dec[i]) == rss
    # implicit rejection
    bad = ct.copy()
    bad[:, 7] ^= 0xFF
    assert not (np.asarray(dec(sk, bad)) == ss).all(axis=-1).any()


@pytest.mark.slow
def test_hqc256_roundtrip_jax():
    from quantum_resistant_p2p_tpu.kem import hqc as jhq

    p = hq.PARAMS["HQC-256"]
    kg, enc, dec = jhq.get("HQC-256")
    sk_seed = RNG.integers(0, 256, size=(1, 40), dtype=np.uint8)
    sigma = RNG.integers(0, 256, size=(1, p.k), dtype=np.uint8)
    pk_seed = RNG.integers(0, 256, size=(1, 40), dtype=np.uint8)
    m = RNG.integers(0, 256, size=(1, p.k), dtype=np.uint8)
    salt = RNG.integers(0, 256, size=(1, 16), dtype=np.uint8)
    pk, sk = kg(sk_seed, sigma, pk_seed)
    ct, ss = enc(np.asarray(pk), m, salt)
    assert (np.asarray(dec(np.asarray(sk), np.asarray(ct))) == np.asarray(ss)).all()


def test_cyclic_mul_matmul_matches_gather_loop():
    """The blocked-Toeplitz MXU formulation is bit-exact vs the retained
    rotated-gather loop (the QRP2P_HQC_GATHER=1 A/B path) on real params."""
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.kem import hqc as H
    from quantum_resistant_p2p_tpu.pyref.hqc_ref import PARAMS

    p = PARAMS["HQC-128"]
    rng = np.random.default_rng(11)
    dense = jnp.asarray(rng.integers(0, 2, (2, p.n), dtype=np.int32))
    sup = jnp.asarray(rng.integers(0, p.n, (2, p.w), dtype=np.int32))
    got = np.asarray(H._cyclic_mul_matmul(p, dense, sup))

    # gather loop, bypassing the env switch
    import jax
    from jax import lax

    n, w = p.n, p.w
    base = jnp.arange(n)

    def step(k, acc):
        pk = jnp.take_along_axis(sup, jnp.full(sup.shape[:-1] + (1,), k), axis=-1)
        idx = (base - pk) % n
        return acc + jnp.take_along_axis(dense, idx, axis=-1)

    ref = np.asarray(
        (lax.fori_loop(0, w, step, jnp.zeros(dense.shape, jnp.int32)) & 1)
    ).astype(np.uint8)
    assert np.array_equal(got, ref)


def test_cyclic_mul_fft_bit_exact_adversarial():
    """The f32-FFT cyclic product (the default since late round 3) is
    bit-exact vs an np.roll oracle at every parameter set, including the
    worst-case-precision input (dense = all ones, maximal support
    weight); also re-checks the support-duplicate path."""
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.kem import hqc as H
    from quantum_resistant_p2p_tpu.pyref.hqc_ref import PARAMS

    rng = np.random.default_rng(13)
    for name in ("HQC-128", "HQC-192", "HQC-256"):
        p = PARAMS[name]
        dense = np.stack([
            np.ones(p.n, np.int32),  # adversarial: maximal spectral norm
            rng.integers(0, 2, p.n, dtype=np.int32),
        ])
        sup = np.stack([
            rng.choice(p.n, size=p.w, replace=False).astype(np.int32),
            np.concatenate([  # duplicate positions collapse to one scatter hit
                np.full(2, 7, np.int32),
                rng.choice(p.n, size=p.w - 2, replace=False).astype(np.int32),
            ]),
        ])
        got = np.asarray(H._cyclic_mul_fft(p, jnp.asarray(dense), jnp.asarray(sup)))
        for b in range(2):
            onehot = np.zeros(p.n, np.int64)
            onehot[sup[b]] = 1  # duplicates collapse, matching _support_to_bits
            ref = np.zeros(p.n, np.int64)
            for pos in np.nonzero(onehot)[0]:
                ref ^= np.roll(dense[b].astype(np.int64), pos)
            assert np.array_equal(got[b], ref.astype(np.uint8)), (name, b)


def test_fft_selfcheck_passes_and_gates(monkeypatch, tmp_path):
    """The FFT environment self-check passes on this platform, caches its
    verdict, and a failing verdict forces the Toeplitz path for the
    process (the ADVICE round-3 gating requirement)."""
    from quantum_resistant_p2p_tpu.kem import hqc as H
    from quantum_resistant_p2p_tpu.pyref.hqc_ref import PARAMS

    ok, resid = H._fft_selfcheck(PARAMS["HQC-128"])
    assert ok and resid < 0.25

    # fresh cache dir + cleared memo: the verdict is computed once, then
    # served from the in-process memo; a second process (memo cleared
    # again) reads the marker without re-probing
    from quantum_resistant_p2p_tpu import native as native_mod

    monkeypatch.setattr(native_mod, "_CACHE_DIR", tmp_path / "cache")
    monkeypatch.setattr(H, "_FFT_ENV_OK", None)
    calls = []
    real = H._fft_selfcheck
    monkeypatch.setattr(H, "_fft_selfcheck", lambda p: calls.append(p) or real(p))
    assert H._fft_env_validated() is True
    assert H._fft_env_validated() is True  # in-process memo
    monkeypatch.setattr(H, "_FFT_ENV_OK", None)  # "new process"
    assert H._fft_env_validated() is True  # marker read, no re-probe
    assert len(calls) == 1

    # corrupted non-dict marker: re-probe instead of crashing
    monkeypatch.setattr(H, "_FFT_ENV_OK", None)
    markers = list((tmp_path / "cache").glob("hqc_fft_ok_*.json"))
    assert markers
    for mk in markers:
        mk.write_text("[1]")
    assert H._fft_env_validated() is True
    assert len(calls) == 2

    # a FAILING probe is never persisted: next "process" re-probes
    for mk in (tmp_path / "cache").glob("hqc_fft_ok_*.json"):
        mk.unlink()
    monkeypatch.setattr(H, "_FFT_ENV_OK", None)
    monkeypatch.setattr(H, "_fft_selfcheck", lambda p: (False, 0.7))
    assert H._fft_env_validated() is False
    monkeypatch.setattr(H, "_fft_selfcheck", lambda p: calls.append(p) or real(p))
    monkeypatch.setattr(H, "_FFT_ENV_OK", None)
    assert H._fft_env_validated() is True  # self-healed on re-probe
    assert len(calls) == 3

    # a failing environment forces matmul before anything is traced
    monkeypatch.setattr(H, "_FORCED_IMPL", None)
    monkeypatch.setattr(H, "_fft_env_validated", lambda: False)
    monkeypatch.delenv("QRP2P_HQC_SELFCHECK", raising=False)
    H._maybe_gate_fft()
    assert H._cyclic_impl() == "matmul"

    # QRP2P_HQC_SELFCHECK=0 trusts the FFT without probing
    monkeypatch.setattr(H, "_FORCED_IMPL", None)
    monkeypatch.setenv("QRP2P_HQC_SELFCHECK", "0")
    H._maybe_gate_fft()
    assert H._cyclic_impl() == "fft"


def test_cyclic_mul_matmul_large_n_block_branch():
    """The K=64 branch (n > 40000, HQC-256's regime) against an np.roll
    oracle on a synthetic parameter size — keeps _cyclic_block's largest-n
    branch from rotting without paying a full HQC-256 CPU run."""
    import types

    from quantum_resistant_p2p_tpu.kem import hqc as H

    n = 40961  # odd, > 40000 -> K=64, non-divisible block count
    assert H._cyclic_block(n) == 64
    fake = types.SimpleNamespace(n=n)
    rng = np.random.default_rng(12)
    dense = rng.integers(0, 2, (1, n), dtype=np.int32)
    sup = rng.integers(0, n, (1, 9), dtype=np.int32)
    got = np.asarray(H._cyclic_mul_matmul(fake, dense, sup))
    ref = np.zeros(n, dtype=np.int64)
    for pos in sup[0]:
        ref ^= np.roll(dense[0], pos)
    assert np.array_equal(got[0], ref.astype(np.uint8))
