"""HQC: batched JAX vs pure-Python oracle (bit-exact) + KEM properties."""

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.pyref import hqc_ref as hq

RNG = np.random.default_rng(17669)


@pytest.mark.parametrize(
    "name",
    ["HQC-128", pytest.param("HQC-192", marks=pytest.mark.slow)],
)
def test_matches_oracle(name):
    from quantum_resistant_p2p_tpu.kem import hqc as jhq

    p = hq.PARAMS[name]
    batch = 2
    kg, enc, dec = jhq.get(name)
    sk_seed = RNG.integers(0, 256, size=(batch, 40), dtype=np.uint8)
    sigma = RNG.integers(0, 256, size=(batch, p.k), dtype=np.uint8)
    pk_seed = RNG.integers(0, 256, size=(batch, 40), dtype=np.uint8)
    m = RNG.integers(0, 256, size=(batch, p.k), dtype=np.uint8)
    salt = RNG.integers(0, 256, size=(batch, 16), dtype=np.uint8)
    pk, sk = kg(sk_seed, sigma, pk_seed)
    pk, sk = np.asarray(pk), np.asarray(sk)
    ct, ss = enc(pk, m, salt)
    ct, ss = np.asarray(ct), np.asarray(ss)
    ss_dec = np.asarray(dec(sk, ct))
    for i in range(batch):
        rpk, rsk = hq.keygen(p, sk_seed[i].tobytes(), sigma[i].tobytes(), pk_seed[i].tobytes())
        assert bytes(pk[i]) == rpk
        assert bytes(sk[i]) == rsk
        rct, rss = hq.encaps(p, rpk, m[i].tobytes(), salt[i].tobytes())
        assert bytes(ct[i]) == rct
        assert bytes(ss[i]) == rss
        assert bytes(ss_dec[i]) == rss
    # implicit rejection
    bad = ct.copy()
    bad[:, 7] ^= 0xFF
    assert not (np.asarray(dec(sk, bad)) == ss).all(axis=-1).any()


@pytest.mark.slow
def test_hqc256_roundtrip_jax():
    from quantum_resistant_p2p_tpu.kem import hqc as jhq

    p = hq.PARAMS["HQC-256"]
    kg, enc, dec = jhq.get("HQC-256")
    sk_seed = RNG.integers(0, 256, size=(1, 40), dtype=np.uint8)
    sigma = RNG.integers(0, 256, size=(1, p.k), dtype=np.uint8)
    pk_seed = RNG.integers(0, 256, size=(1, 40), dtype=np.uint8)
    m = RNG.integers(0, 256, size=(1, p.k), dtype=np.uint8)
    salt = RNG.integers(0, 256, size=(1, 16), dtype=np.uint8)
    pk, sk = kg(sk_seed, sigma, pk_seed)
    ct, ss = enc(np.asarray(pk), m, salt)
    assert (np.asarray(dec(np.asarray(sk), np.asarray(ct))) == np.asarray(ss)).all()
