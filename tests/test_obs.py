"""obs/ — unified observability subsystem acceptance suite.

Covers: registry semantics (concurrent increments, histogram buckets,
create-or-return), span-context propagation across ``await``/task/thread/
executor hops, Prometheus-text and chrome-trace golden formats, flight-
recorder redaction + byte-reproducible seeded dumps + auto-dump triggers
+ the breaker open→half-open→close story, ``SecureMessaging.metrics()``
key parity with the pre-obs layout, and the end-to-end assert that a
traced warm ML-KEM-768×ML-DSA-65 handshake yields exactly 4
device-dispatch spans (the PR-2 budget, now visible in a flame graph).

Runs on minimal images: the AEAD is the stdlib toy from the faults suite
(no ``cryptography`` wheel needed) and obs/ itself is stdlib-only.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import itertools
import json
import os
import threading
import time

import pytest

from quantum_resistant_p2p_tpu.app import messaging as messaging_mod
from quantum_resistant_p2p_tpu.app.messaging import SecureMessaging
from quantum_resistant_p2p_tpu.faults import FaultPlan, FaultRule
from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode
from quantum_resistant_p2p_tpu.obs import flight as obs_flight
from quantum_resistant_p2p_tpu.obs import metrics as obs_metrics
from quantum_resistant_p2p_tpu.obs import trace as obs_trace
from quantum_resistant_p2p_tpu.obs.flight import FlightRecorder, redact_value
from quantum_resistant_p2p_tpu.obs.metrics import (Counter, Histogram,
                                                   Registry)
from quantum_resistant_p2p_tpu.obs.trace import Tracer, to_chrome_trace
from quantum_resistant_p2p_tpu.provider.base import SymmetricAlgorithm
from quantum_resistant_p2p_tpu.provider.batched import Breaker, OpQueue


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


def _fake_clock(step: float = 1.0):
    counter = itertools.count()
    return lambda: next(counter) * step


# -- registry semantics -------------------------------------------------------


def test_counter_concurrent_increments_lose_nothing():
    c = Counter("ops", "test")
    N_THREADS, N_INCS = 8, 5000

    def hammer():
        for _ in range(N_INCS):
            c.inc()

    threads = [threading.Thread(target=hammer) for _ in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == N_THREADS * N_INCS


def test_histogram_buckets_percentiles_and_reset():
    h = Histogram("trips", "test", buckets=(1.0, 2.0, 4.0, 8.0))
    for v in (1, 2, 2, 4, 9):
        h.record(v)
    assert h.count == 5 and h.last == 9 and h.total == 18
    assert h.bucket_counts() == {"1": 1, "2": 3, "4": 4, "8": 4, "+Inf": 5}
    assert h.percentile(50) == 2.0      # 3rd of 5 samples lands in le=2
    # the 9 overflows the boundaries: None, never inf (JSON-exportable)
    assert h.percentile(99) is None
    h.reset()
    assert h.count == 0 and h.last is None and h.percentile(50) is None


def test_registry_create_or_return_and_type_conflict():
    r = Registry("t1")
    c1 = r.counter("x", "d")
    assert r.counter("x") is c1  # create-or-return: one instrument per name
    with pytest.raises(TypeError):
        r.gauge("x")
    g = r.gauge("lazy")
    g.set_fn(lambda: 41 + 1)
    snap = r.snapshot()
    assert snap["gauges"]["lazy"] == 42.0
    # a crashing lazy gauge degrades to None (JSON-safe), NaN only in prom
    r.gauge("broken").set_fn(lambda: 1 // 0)
    snap = r.snapshot()
    assert snap["gauges"]["broken"] is None
    json.dumps(snap, allow_nan=False)  # strictly valid JSON, no NaN/Inf
    assert 'qrp2p_broken{registry="t1"} NaN' in r.to_prometheus()
    r.register_collector("ext", lambda: {"nested": {"n": 7}})
    assert r.snapshot()["collected"]["ext"] == {"nested": {"n": 7}}
    h = r.histogram("trips", buckets=(1.0, 2.0))
    assert r.histogram("trips") is h          # None = keep what it has
    with pytest.raises(TypeError):
        r.histogram("trips", buckets=(5.0,))  # explicit mismatch is an error


def test_labeled_children_share_the_family():
    r = Registry("t2")
    c = r.counter("reqs", "test")
    c.labels(op="enc").inc(2)
    c.labels(op="enc").inc()
    c.labels(op="dec").inc()
    snap = r.snapshot()
    assert snap["counters"]['reqs{op="enc"}'] == 3
    assert snap["counters"]['reqs{op="dec"}'] == 1


def test_prometheus_text_golden():
    r = Registry("bench")
    r.counter("ops", "operations").inc(5)
    r.counter("ops").labels(op="enc").inc(2)
    r.gauge("served_fraction", "device-served fraction").set(0.75)
    h = r.histogram("lat_s", "dispatch latency", buckets=(0.1, 1.0))
    h.record(0.05)
    h.record(0.5)
    r.register_collector("queues", lambda: {"kem": {"ops": 3, "state": "ok"}})
    assert r.to_prometheus() == (
        '# HELP qrp2p_ops_total operations\n'
        '# TYPE qrp2p_ops_total counter\n'
        'qrp2p_ops_total{registry="bench"} 5\n'
        'qrp2p_ops_total{registry="bench",op="enc"} 2\n'
        '# HELP qrp2p_served_fraction device-served fraction\n'
        '# TYPE qrp2p_served_fraction gauge\n'
        'qrp2p_served_fraction{registry="bench"} 0.75\n'
        '# HELP qrp2p_lat_s dispatch latency\n'
        '# TYPE qrp2p_lat_s histogram\n'
        'qrp2p_lat_s_bucket{registry="bench",le="0.1"} 1\n'
        'qrp2p_lat_s_bucket{registry="bench",le="1"} 2\n'
        'qrp2p_lat_s_bucket{registry="bench",le="+Inf"} 2\n'
        'qrp2p_lat_s_sum{registry="bench"} 0.55\n'
        'qrp2p_lat_s_count{registry="bench"} 2\n'
        'qrp2p_queues_kem_ops{registry="bench"} 3\n'
    )


def test_profiling_shim_is_gone():
    """PR 5 promised the utils/profiling deprecation shim would be removed
    once nothing imported it; this pins the removal (and that the real
    homes still serve the moved objects)."""
    with pytest.raises(ModuleNotFoundError):
        import quantum_resistant_p2p_tpu.utils.profiling  # noqa: F401
    h = obs_metrics.LatencyHistogram()
    h.record(0.5)
    assert h.summary()["count"] == 1 and h.percentile(50) == 0.5
    assert callable(obs_trace.device_trace)


# -- span propagation ---------------------------------------------------------


def test_span_context_propagates_across_await_and_tasks(run):
    tr = Tracer()

    async def inner():
        with tr.span("child"):
            await asyncio.sleep(0)

    async def main():
        with tr.span("root"):
            await asyncio.get_running_loop().create_task(inner())

    run(main())
    recs = {r["name"]: r for r in tr.snapshot()}
    assert recs["child"]["trace_id"] == recs["root"]["trace_id"]
    assert recs["child"]["parent_id"] == recs["root"]["span_id"]
    assert recs["root"]["parent_id"] is None


def test_span_context_needs_explicit_handoff_across_threads(run):
    """contextvars do not cross run_in_executor / Thread — the captured
    ``current()`` handed as ``parent=`` is the supported handoff (the
    warmup-thread / device-executor edges)."""
    tr = Tracer()
    seen: dict[str, object] = {}

    def fresh_thread():
        seen["thread_ctx"] = obs_trace.current()

    t = threading.Thread(target=fresh_thread)
    t.start()
    t.join()
    assert seen["thread_ctx"] is None  # no ambient context off-loop

    async def main():
        loop = asyncio.get_running_loop()
        with tr.span("root"):
            parent = obs_trace.current()

            def work():
                with tr.span("far_side", parent=parent):
                    pass

            await loop.run_in_executor(None, work)

    run(main())
    recs = {r["name"]: r for r in tr.snapshot()}
    assert recs["far_side"]["parent_id"] == recs["root"]["span_id"]
    assert recs["far_side"]["trace_id"] == recs["root"]["trace_id"]
    assert recs["far_side"]["thread"] != recs["root"]["thread"]


def test_span_error_attribute_and_nesting_restored():
    tr = Tracer()
    with pytest.raises(ValueError):
        with tr.span("boom"):
            raise ValueError("x")
    assert obs_trace.current() is None  # context restored after the raise
    (rec,) = tr.snapshot()
    assert rec["attrs"]["error"] == "ValueError"


def test_chrome_trace_export_golden():
    tr = Tracer(clock=_fake_clock())
    with tr.span("handshake.initiate", peer="ab"):
        with tr.span("device.dispatch", op="enc"):
            pass
    assert to_chrome_trace(tr.snapshot()) == {
        "traceEvents": [
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 1,
             "args": {"name": "MainThread"}},
            {"name": "device.dispatch", "ph": "X", "ts": 1000000.0,
             "dur": 1000000.0, "pid": 1, "tid": 1, "cat": "device",
             "args": {"trace_id": "t00000001", "span_id": "00000003",
                      "parent_id": "00000002", "op": "enc"}},
            {"name": "handshake.initiate", "ph": "X", "ts": 0.0,
             "dur": 3000000.0, "pid": 1, "tid": 1, "cat": "handshake",
             "args": {"trace_id": "t00000001", "span_id": "00000002",
                      "parent_id": None, "peer": "ab"}},
        ],
        "displayTimeUnit": "ms",
    }


# -- flight recorder ----------------------------------------------------------


def test_redaction_vocabulary_matches_qrlint():
    """Runtime redaction and qrlint's secret-hygiene pack share ONE
    vocabulary module (obs/redaction.py) — pin the import identity, not
    just pattern equality, so a re-forked copy can't sneak back in."""
    from tools.analysis import rules_secret
    from quantum_resistant_p2p_tpu.obs import redaction

    assert obs_flight.SECRET_NAME_RE is rules_secret.SECRET_NAME_RE
    assert obs_flight.SECRET_NAME_RE is redaction.SECRET_NAME_RE
    assert obs_flight.NONSECRET_NAME_RE is rules_secret.NONSECRET_NAME_RE
    assert rules_secret.is_secret_name is redaction.is_secret_name


def test_flight_redacts_at_record_time():
    rec = FlightRecorder()
    rec.record("ev", secret_key=b"\x01" * 32, shared_secret="ab" * 16,
               note="fine", n=3, public_key="cc" * 16,
               nested={"sk": "dd" * 40, "count": 2}, blob=b"xx" * 300,
               huge="z" * 1000)
    (e,) = rec.snapshot()
    assert e["secret_key"] == "[redacted:bytes:32]"
    assert e["shared_secret"] == "[redacted:str:32]"
    assert e["nested"]["sk"] == "[redacted:str:80]"
    assert e["nested"]["count"] == 2
    assert e["blob"] == "[bytes:600]"          # raw bytes never stored
    assert e["huge"] == "[str:1000 chars]"
    assert e["note"] == "fine" and e["n"] == 3
    # public-named values survive (NONSECRET walks back the match)
    assert e["public_key"] == "cc" * 16
    dumped = json.dumps(rec.dump("t", registries={}))
    assert "dd" * 40 not in dumped and "ab" * 16 not in dumped


def test_redact_value_depth_and_types():
    deep = {"a": {"b": {"c": {"d": {"e": 1}}}}}
    out = redact_value("x", deep)
    assert out["a"]["b"]["c"]["d"] == "[dict]"
    assert redact_value("x", object()).startswith("[object")
    assert redact_value("x", [b"ab", "ok"]) == ["[bytes:2]", "ok"]


def test_flight_dump_byte_reproducible_given_seed(tmp_path, monkeypatch):
    """Same seed + same event stream + injected clocks -> byte-identical
    diagnostic bundles (the chaos-run explainability contract)."""

    def drive(out_path):
        rec = FlightRecorder(clock=_fake_clock(0.25), mono=_fake_clock(0.25))
        monkeypatch.setattr(obs_flight, "RECORDER", rec)
        plan = FaultPlan(seed=11, rules=[
            FaultRule("net.send", "corrupt", match={"msg_type": "m"}, nth=1),
            FaultRule("device.dispatch", "raise", nth=2),
        ])
        with plan.activate():
            for _ in range(3):
                plan.net_send("a", "b", "m", {"ct": bytes(8)})
            for _ in range(3):
                try:
                    plan.device_dispatch("q.enc", 1)
                except Exception:
                    pass
        rec.dump("chaos", path=out_path, registries={})
        return out_path.read_bytes()

    b1 = drive(tmp_path / "d1.json")
    b2 = drive(tmp_path / "d2.json")
    assert b1 == b2
    doc = json.loads(b1)
    assert doc["trigger"] == "chaos"
    assert [e["kind"] for e in doc["events"]].count("fault_injected") == 2


def test_seeded_chaos_run_dump_tells_the_breaker_story(run, monkeypatch):
    """Acceptance: a seeded chaos run produces a redacted dump containing
    the breaker open -> half-open -> close transitions, event by event."""
    rec = FlightRecorder()
    monkeypatch.setattr(obs_flight, "RECORDER", rec)

    async def main():
        q = OpQueue(lambda items: [("dev", i) for i in items],
                    max_batch=4, max_wait_ms=1.0,
                    fallback_fn=lambda items: [("cpu", i) for i in items],
                    breaker=Breaker(cooloff_s=0.05), label="chaos.enc")
        q.mark_warm(1)
        plan = FaultPlan(3, [FaultRule("device.dispatch", "raise", nth=1)])
        with plan.activate():
            assert await q.submit(1) == ("cpu", 1)   # fault -> open -> fallback
        await asyncio.sleep(0.08)                    # ride out the cool-off
        assert await q.submit(2) == ("dev", 2)       # canary heals -> closed

    run(main())
    events = rec.snapshot()
    states = [e["state"] for e in events if e["kind"].startswith("breaker")]
    assert states == ["open", "half_open", "closed"]
    assert any(e["kind"] == "fault_injected" for e in events)
    # the dispatch spans rode along into the ring
    assert any(e["kind"] == "span" and e["name"] == "fallback.dispatch"
               for e in events)
    assert any(e["kind"] == "span" and e["name"] == "device.dispatch"
               for e in events)
    bundle = rec.dump("chaos", registries={})
    assert [e["state"] for e in bundle["events"]
            if e["kind"].startswith("breaker")] == ["open", "half_open", "closed"]


def test_autodump_fires_on_breaker_open_trigger(tmp_path, monkeypatch):
    rec = FlightRecorder()
    rec.set_autodump(tmp_path, min_interval_s=0.0, keep=4)
    monkeypatch.setattr(obs_flight, "RECORDER", rec)
    Breaker(cooloff_s=0.1).trip()
    files = []
    for _ in range(100):
        files = sorted(tmp_path.glob("flight_*.json"))
        if files:
            break
        time.sleep(0.05)
    assert files, "breaker open did not auto-dump a bundle"
    doc = json.loads(files[0].read_text())
    assert doc["trigger"] == "breaker_open"
    assert any(e["kind"] == "breaker_open" for e in doc["events"])


def test_autodump_rate_limit_and_prune(tmp_path):
    rec = FlightRecorder(mono=_fake_clock(1.0))
    rec.set_autodump(tmp_path, min_interval_s=10.0, keep=2)
    rec.trigger("fault_injected", n=1)   # mono 0 -> dump
    rec.trigger("fault_injected", n=2)   # mono 1 -> rate-limited
    rec.trigger("other_kind", n=3)       # separate kind -> dump
    deadline = time.time() + 5
    while time.time() < deadline:
        if len(list(tmp_path.glob("flight_*.json"))) >= 2:
            break
        time.sleep(0.05)
    assert len(list(tmp_path.glob("flight_*.json"))) == 2


# -- SecureMessaging metrics parity ------------------------------------------


def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    out = b""
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(key + nonce + ctr.to_bytes(8, "big")).digest()
        ctr += 1
    return out[:n]


class StdlibAEAD(SymmetricAlgorithm):
    """Stdlib encrypt-then-MAC AEAD (the faults-suite toy): lets the full
    protocol stack run on images without the OpenSSL wheel."""

    name = "TOY-AEAD"
    display_name = "TOY-AEAD"
    key_size = 32
    nonce_size = 16

    def encrypt(self, key, plaintext, associated_data=None):
        nonce = os.urandom(self.nonce_size)
        ct = bytes(a ^ b for a, b in
                   zip(plaintext, _keystream(key, nonce, len(plaintext))))
        tag = hmac.new(key, nonce + ct + (associated_data or b""),
                       hashlib.sha256).digest()
        return nonce + ct + tag

    def decrypt(self, key, data, associated_data=None):
        if len(data) < self.nonce_size + 32:
            raise ValueError("ciphertext too short")
        nonce, ct, tag = (data[: self.nonce_size], data[self.nonce_size:-32],
                          data[-32:])
        want = hmac.new(key, nonce + ct + (associated_data or b""),
                        hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise ValueError("authentication failed")
        return bytes(a ^ b for a, b in zip(ct, _keystream(key, nonce, len(ct))))


#: the exact metrics() layout shipped before obs/ (PR 2-4): removing or
#: renaming ANY of these is a compatibility break — adding keys is fine
LEGACY_TOP_KEYS = {
    "backend", "batching", "kem_queue", "sig_queue", "fused_queue",
    "device_trips", "fallback_trips", "breaker_trips", "breaker_state",
    "breaker_opens", "breaker_closes", "device_served_fraction",
    "handshake_trips",
}
LEGACY_QUEUE_KEYS = {
    "ops", "flushes", "max_batch_seen", "avg_batch", "avg_dispatch_ms",
    "p50_dispatch_ms", "p99_dispatch_ms", "fallback_ops", "fallback_flushes",
    "breaker_trips", "device_trips", "device_served_fraction",
}
LEGACY_TRIPS_KEYS = {"count", "last", "p50", "p99"}


def test_metrics_keys_parity_with_pre_obs_layout(monkeypatch):
    monkeypatch.setattr(SecureMessaging, "_spawn_warmup",
                        lambda self, **kw: None)
    node = P2PNode(node_id="paritypeer", host="127.0.0.1", port=0)
    m = SecureMessaging(node, backend="tpu", use_batching=True,
                        symmetric=StdlibAEAD(), sig_keypair=(b"p", b"s"))
    out = m.metrics()
    missing = LEGACY_TOP_KEYS - set(out)
    assert not missing, f"metrics() lost key(s): {sorted(missing)}"
    for fam in ("kem_queue", "sig_queue", "fused_queue"):
        for qname, q in out[fam].items():
            qmissing = LEGACY_QUEUE_KEYS - set(q)
            assert not qmissing, f"{fam}.{qname} lost {sorted(qmissing)}"
    assert LEGACY_TRIPS_KEYS <= set(out["handshake_trips"])
    # and the new single source serves the same data other ways too
    assert out["resilience"]["rekeys"] == 0
    snap = m.registry.snapshot()
    assert snap["collected"]["queues"]["breaker_state"] == out["breaker_state"]
    prom = m.registry.to_prometheus()
    assert "qrp2p_handshake_trips" in prom
    assert "qrp2p_queues_device_trips" in prom


def test_metrics_parity_without_batching():
    node = P2PNode(node_id="nobatch", host="127.0.0.1", port=0)
    m = SecureMessaging(node, backend="cpu", symmetric=StdlibAEAD(),
                        sig_keypair=(b"p", b"s"))
    out = m.metrics()
    assert out["backend"] == "cpu" and out["batching"] is False
    assert LEGACY_TRIPS_KEYS <= set(out["handshake_trips"])
    assert "kem_queue" not in out  # batching off: same shape as before obs/


# -- cross-peer trace propagation ---------------------------------------------


class ToyKEM:
    """Deterministic hash-based toy KEM (the faults-suite pattern): lets
    the two-node propagation e2e run the REAL protocol in milliseconds."""

    name = "TOY-KEM"
    display_name = "TOY-KEM"
    public_key_len = 32
    secret_key_len = 32
    ciphertext_len = 32
    shared_secret_len = 32
    backend = "cpu"

    def generate_keypair(self):
        sk = os.urandom(32)
        return hashlib.sha256(b"pk" + sk).digest(), sk

    def encapsulate(self, public_key):
        ct = os.urandom(32)
        return ct, hashlib.sha256(public_key + ct).digest()

    def decapsulate(self, secret_key, ciphertext):
        pk = hashlib.sha256(b"pk" + secret_key).digest()
        return hashlib.sha256(pk + ciphertext).digest()


class ToySig:
    name = "TOY-SIG"
    display_name = "TOY-SIG"
    public_key_len = 32
    secret_key_len = 32
    signature_len = 32
    backend = "cpu"

    def generate_keypair(self):
        sk = os.urandom(32)
        return hashlib.sha256(b"pk" + sk).digest(), sk

    def sign(self, secret_key, message):
        pk = hashlib.sha256(b"pk" + secret_key).digest()
        return hashlib.sha256(b"sig" + pk + message).digest()

    def verify(self, public_key, message, signature):
        return hmac.compare_digest(
            signature, hashlib.sha256(b"sig" + public_key + message).digest())


async def _toy_pair():
    a_node = P2PNode(node_id="alice", host="127.0.0.1", port=0)
    b_node = P2PNode(node_id="bob", host="127.0.0.1", port=0)
    await a_node.start()
    await b_node.start()
    kw = dict(kem=ToyKEM(), signature=ToySig(), symmetric=StdlibAEAD())
    a = SecureMessaging(a_node, **kw)
    b = SecureMessaging(b_node, **kw)
    assert await a_node.connect_to_peer("127.0.0.1", b_node.port) == "bob"
    for _ in range(100):
        if b_node.is_connected("alice"):
            break
        await asyncio.sleep(0.01)
    return a, b


def _spans_by_name(spans):
    out = {}
    for s in spans:
        out.setdefault(s["name"], []).append(s)
    return out


def test_wire_context_validation_rejects_hostile_input(monkeypatch):
    """adopt_wire_context: peers are untrusted — anything but a dict of
    two short token-charset string ids is ignored, never an exception or
    a control-flow change."""
    tr = Tracer()
    with tr.span("root"):
        ctx = obs_trace.wire_context()
        assert set(ctx) == {"trace_id", "span_id"}
        # extra kwargs: only short token strings survive; qrflow polices
        # the surface statically so nothing tainted can reach here
        rich = obs_trace.wire_context(run="r1", huge="x" * 65, n=3)  # type: ignore[arg-type]
        assert rich["run"] == "r1" and "huge" not in rich and "n" not in rich
    good = obs_trace.adopt_wire_context(ctx)
    assert good is not None and good.trace_id == ctx["trace_id"]
    assert good.node is None  # remote parents never claim a local lane
    for hostile in (
        None, 7, "t1/s1", [], {"trace_id": "a"},                  # shapes
        {"trace_id": 5, "span_id": "b"},                          # types
        {"trace_id": "a" * 65, "span_id": "b"},                   # oversize
        {"trace_id": "ok", "span_id": "bad\nid"},                 # charset
        {"trace_id": "evil\n", "span_id": "b"},                   # $-anchor hole
        {"trace_id": "a" * 64 + "\n", "span_id": "b"},            # 65B via \n
        {"trace_id": "ok", "span_id": "sp", "extra": object()},   # junk rides
    ):
        adopted = obs_trace.adopt_wire_context(hostile)
        if isinstance(hostile, dict) and hostile.get("span_id") == "sp":
            assert adopted is not None  # extra keys ignored, ids adopted
        else:
            assert adopted is None, hostile
    monkeypatch.setenv("QRP2P_TRACE_PROPAGATE", "0")
    with tr.span("root2"):
        assert obs_trace.wire_context() is None
    assert obs_trace.adopt_wire_context(ctx) is None


async def _handshake_spans(a, b, *needed):
    """Run one a->b handshake and snapshot spans once ``needed`` names
    have all been recorded (the responder's tail work is async)."""
    obs_trace.TRACER.reset()
    assert await a.initiate_key_exchange("bob")
    spans = []
    for _ in range(200):
        spans = obs_trace.TRACER.snapshot()
        if all(any(s["name"] == n for s in spans) for n in needed):
            break
        await asyncio.sleep(0.01)
    await a.node.stop()
    await b.node.stop()
    return spans


def test_two_node_handshake_joins_one_trace(run, monkeypatch):
    """Acceptance (ISSUE 10): initiator and responder handshake spans
    share ONE trace_id, and the responder's chain parents onto the
    initiator's net.send span via the propagated wire context."""
    monkeypatch.setattr(messaging_mod, "KEY_EXCHANGE_TIMEOUT", 10.0)

    async def main():
        a, b = await _toy_pair()
        return await _handshake_spans(
            a, b, "handshake.initiate", "handshake.respond",
            "handshake.confirm")

    spans = run(main())
    by_name = _spans_by_name(spans)
    (initiate,) = by_name["handshake.initiate"]
    (respond,) = by_name["handshake.respond"]
    (confirm,) = by_name["handshake.confirm"]
    # one causal chain across both peers
    assert respond["trace_id"] == initiate["trace_id"]
    assert confirm["trace_id"] == initiate["trace_id"]
    # node attribution: each side's protocol spans sit on its own lane
    assert initiate["node"] == "alice"
    assert respond["node"] == "bob"
    # the responder chain parents onto the initiator's ke_init net.send
    by_id = {s["span_id"]: s for s in spans}
    recv_init = by_id[respond["parent_id"]]
    assert recv_init["name"] == "net.recv"
    assert recv_init["attrs"]["msg_type"] == "ke_init"
    send_init = by_id[recv_init["parent_id"]]
    assert send_init["name"] == "net.send"
    assert send_init["attrs"]["msg_type"] == "ke_init"
    assert send_init["node"] == "alice"
    # every net.recv of the exchange adopted a remote parent (no orphan
    # re-roots anywhere in the 5-message chain)
    ke_recvs = [s for s in by_name["net.recv"]
                if s["attrs"]["msg_type"].startswith("ke_")]
    assert ke_recvs and all(s["trace_id"] == initiate["trace_id"]
                            and s["parent_id"] for s in ke_recvs)


def test_propagation_optout_restores_disjoint_traces(run, monkeypatch):
    """QRP2P_TRACE_PROPAGATE=0: no ``_trace`` field rides any frame
    (wire-identical to the pre-propagation protocol) and the two sides'
    traces are disjoint again."""
    monkeypatch.setenv("QRP2P_TRACE_PROPAGATE", "0")
    monkeypatch.setattr(messaging_mod, "KEY_EXCHANGE_TIMEOUT", 10.0)
    sent_messages = []
    orig = P2PNode._send_frame

    async def spy(self, writer, lock, message):
        sent_messages.append(message)
        return await orig(self, writer, lock, message)

    monkeypatch.setattr(P2PNode, "_send_frame", spy)

    async def main():
        a, b = await _toy_pair()
        return await _handshake_spans(
            a, b, "handshake.initiate", "handshake.respond")

    spans = run(main())
    assert all("_trace" not in m for m in sent_messages)
    by_name = _spans_by_name(spans)
    (initiate,) = by_name["handshake.initiate"]
    (respond,) = by_name["handshake.respond"]
    assert respond["trace_id"] != initiate["trace_id"]
    assert all(s["parent_id"] is None for s in by_name["net.recv"])


def test_propagation_on_attaches_ids_only_field(run, monkeypatch):
    """With propagation ON (the default), ke_* frames carry exactly the
    bounded ids-only ``_trace`` dict — and handlers never see it.  Both
    frame encoders are spied: peers that negotiated the binary wire send
    the same message dicts through ``_send_frame_bin``."""
    monkeypatch.setattr(messaging_mod, "KEY_EXCHANGE_TIMEOUT", 10.0)
    sent_messages = []
    seen_by_handler = []
    orig = P2PNode._send_frame
    orig_bin = P2PNode._send_frame_bin

    async def spy(self, writer, lock, message):
        sent_messages.append(message)
        return await orig(self, writer, lock, message)

    async def spy_bin(self, writer, lock, message):
        sent_messages.append(message)
        return await orig_bin(self, writer, lock, message)

    monkeypatch.setattr(P2PNode, "_send_frame", spy)
    monkeypatch.setattr(P2PNode, "_send_frame_bin", spy_bin)

    async def main():
        a, b = await _toy_pair()

        async def on_init(peer_id, msg):
            seen_by_handler.append(msg)

        b.node.register_message_handler("ke_init", on_init)
        assert await a.initiate_key_exchange("bob")
        await a.node.stop()
        await b.node.stop()

    run(main())
    traced = [m for m in sent_messages if "_trace" in m]
    assert traced, "no frame carried the propagated context"
    for m in traced:
        assert set(m["_trace"]) == {"trace_id", "span_id"}
        assert all(isinstance(v, str) and len(v) <= obs_trace.WIRE_ID_MAX
                   for v in m["_trace"].values())
    assert seen_by_handler and all("_trace" not in m for m in seen_by_handler)


def test_chunked_message_gets_one_recv_span_with_chunk_attr(run):
    """Satellite (ISSUE 10): a reassembled chunked message carries ONE
    net.recv span for the logical message, with a ``chunks=`` attr, still
    parented on the sender's propagated context."""

    async def main():
        a = P2PNode(node_id="alice", host="127.0.0.1", port=0)
        b = P2PNode(node_id="bob", host="127.0.0.1", port=0)
        await a.start()
        await b.start()
        assert await a.connect_to_peer("127.0.0.1", b.port) == "bob"
        for _ in range(100):
            if b.is_connected("alice"):
                break
            await asyncio.sleep(0.01)
        a.chunk_size = 4096
        got = asyncio.Event()

        async def on_big(peer_id, msg):
            got.set()

        b.register_message_handler("big", on_big)
        obs_trace.TRACER.reset()
        with obs_trace.TRACER.span("caller"):
            assert await a.send_message("bob", "big", data=bytes(40_000))
        await asyncio.wait_for(got.wait(), 10)
        spans = obs_trace.TRACER.snapshot()
        await a.stop()
        await b.stop()
        return spans

    spans = run(main())
    by_name = _spans_by_name(spans)
    recvs = [s for s in by_name.get("net.recv", [])
             if s["attrs"]["msg_type"] == "big"]
    assert len(recvs) == 1, recvs  # one span per LOGICAL message
    (recv,) = recvs
    (send,) = [s for s in by_name["net.send"]
               if s["attrs"]["msg_type"] == "big"]
    assert recv["attrs"]["chunks"] >= 2  # ~40KB over 4KiB chunks
    assert recv["parent_id"] == send["span_id"]
    assert recv["trace_id"] == send["trace_id"]


def test_merged_two_node_trace_has_process_lanes_and_flow_edges(run, monkeypatch):
    """Acceptance: the merged chrome document shows both nodes as separate
    process lanes under a single trace id, with cross-node flow arrows on
    the propagated parent edges (tools/trace_merge.py)."""
    from tools import trace_merge

    monkeypatch.setattr(messaging_mod, "KEY_EXCHANGE_TIMEOUT", 10.0)

    async def main():
        a, b = await _toy_pair()
        return await _handshake_spans(
            a, b, "handshake.initiate", "handshake.respond",
            "handshake.confirm")

    spans = run(main())
    doc = trace_merge.merge([obs_trace.span_dump(records=spans)])
    other = doc["otherData"]
    assert {"alice", "bob"} <= set(other["merged_nodes"])
    assert other["cross_node_edges"] >= 2  # ke_init + at least one reply
    xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    procs = {e["name"]: e["args"]["name"] for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert procs  # process_name metadata present
    pid_by_node = {}
    for e in doc["traceEvents"]:
        if e.get("ph") == "M" and e["name"] == "process_name":
            pid_by_node[e["args"]["name"]] = e["pid"]
    hs = {e["name"]: e for e in xs if e["name"].startswith("handshake.")}
    assert hs["handshake.initiate"]["pid"] == pid_by_node["alice"]
    assert hs["handshake.respond"]["pid"] == pid_by_node["bob"]
    assert (hs["handshake.respond"]["args"]["trace_id"]
            == hs["handshake.initiate"]["args"]["trace_id"])
    flows = [e for e in doc["traceEvents"] if e.get("ph") in ("s", "f")]
    assert flows and len(flows) == 2 * other["cross_node_edges"]
    # loadable JSON round-trip
    json.loads(json.dumps(doc))


def test_trace_merge_aligns_multi_process_dumps(tmp_path):
    """Dumps from different processes (distinct clock epochs, distinct
    tracer tags) merge onto one timeline with parent edges intact."""
    from tools import trace_merge

    ta = Tracer(clock=_fake_clock(0.5), tag="aaaa")
    with obs_trace.node_scope("alice"), ta.span("net.send"):
        wire = obs_trace.wire_context()
    da = obs_trace.span_dump(node="alice", tracer=ta)
    da["wall_anchor"], da["mono_anchor"] = 1000.0, da["mono_anchor"]

    tb = Tracer(clock=_fake_clock(0.5), tag="bbbb")
    parent = obs_trace.adopt_wire_context(wire)
    with obs_trace.node_scope("bob"), tb.span("net.recv", parent=parent):
        pass
    db = obs_trace.span_dump(node="bob", tracer=tb)
    db["wall_anchor"], db["mono_anchor"] = 1002.0, db["mono_anchor"]

    (tmp_path / "a.json").write_text(json.dumps(da))
    (tmp_path / "b.json").write_text(json.dumps(db))
    doc = trace_merge.merge_files([tmp_path / "a.json", tmp_path / "b.json"])
    assert doc["otherData"]["merged_nodes"] == ["alice", "bob"]
    assert doc["otherData"]["cross_node_edges"] == 1
    xs = {e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "X"}
    # bob's dump anchors 2 wall-seconds later: its span lands later on the
    # merged timeline even though both tracers' raw clocks started at ~0
    assert xs["net.recv"]["ts"] > xs["net.send"]["ts"]
    assert xs["net.recv"]["pid"] != xs["net.send"]["pid"]


# -- end to end: the traced warm handshake -----------------------------------


def test_traced_warm_handshake_yields_exactly_four_dispatch_spans(
        run, monkeypatch):
    """Acceptance: one warm ML-KEM-768 x ML-DSA-65 fused handshake =
    exactly 4 device-dispatch spans (initiator keygen+sign, responder
    verify+encaps+sign, initiator verify+decaps+sign, responder confirm
    verify — docs/dispatch_budget.md), and the trace exports as loadable
    chrome://tracing JSON."""
    monkeypatch.setenv("QRP2P_HEALTH_GATE", "0")
    monkeypatch.setattr(messaging_mod, "WARMUP_SIZES", (1,))
    monkeypatch.setattr(messaging_mod, "KEY_EXCHANGE_TIMEOUT", 120.0)

    async def main():
        a_node = P2PNode(node_id="alice", host="127.0.0.1", port=0)
        b_node = P2PNode(node_id="bob", host="127.0.0.1", port=0)
        await a_node.start()
        await b_node.start()
        kw = dict(backend="tpu", use_batching=True, max_batch=64,
                  max_wait_ms=2.0, symmetric=StdlibAEAD())
        a = SecureMessaging(a_node, **kw)
        b = SecureMessaging(b_node, **kw)
        assert a._bfused is not None  # the pair advertises the fused path
        assert await a_node.connect_to_peer("127.0.0.1", b_node.port) == "bob"
        for _ in range(100):
            if b_node.is_connected("alice"):
                break
            await asyncio.sleep(0.01)
        # background warmup compiles the size-1 buckets; waiting here makes
        # the measured handshake WARM (no warmup-route dispatches)
        await a.wait_ready()
        await b.wait_ready()
        obs_trace.TRACER.reset()
        assert await a.initiate_key_exchange("bob")
        # the responder's confirm-verify dispatch completes asynchronously —
        # and each device span's queue.flush PARENT closes on the loop side
        # a beat after the worker-side dispatch span does, so wait for the
        # parents too (snapshotting the gap made this flake on loaded hosts)
        spans = []
        for _ in range(200):
            spans = obs_trace.TRACER.snapshot()
            dev = [s for s in spans if s["name"] == "device.dispatch"]
            seen = {s["span_id"] for s in spans}
            if len(dev) >= 4 and all(d["parent_id"] in seen for d in dev):
                break
            await asyncio.sleep(0.05)
        device = [s for s in spans if s["name"] == "device.dispatch"]
        fallback = [s for s in spans if s["name"] == "fallback.dispatch"]
        assert len(device) == 4, (
            f"expected exactly 4 device-dispatch spans, got "
            f"{[s['attrs'] for s in device]} + fallback "
            f"{[s['attrs'] for s in fallback]}"
        )
        assert not fallback  # warm run: nothing degraded to the cpu path
        ops = sorted(s["attrs"]["op"] for s in device)
        assert ops == sorted([
            "ML-KEM-768+ML-DSA-65.keygen_sign",
            "ML-KEM-768+ML-DSA-65.encaps_verify_sign",
            "ML-KEM-768+ML-DSA-65.decaps_verify_sign",
            "ML-DSA-65.verify",
        ])
        # each dispatch span chains into a queue.flush parent, which chains
        # into the protocol span that enqueued first — one correlated story
        by_id = {s["span_id"]: s for s in spans}
        for d in device:
            parent = by_id.get(d["parent_id"])
            assert parent is not None and parent["name"] == "queue.flush"
        # the flame graph is loadable chrome://tracing JSON
        doc = json.loads(json.dumps(to_chrome_trace(spans)))
        events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert {e["name"] for e in events} >= {"device.dispatch", "queue.flush"}
        assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in events)
        # the span count and the trip metric tell the same story
        trips = a.metrics()["handshake_trips"]
        assert trips["count"] == 1 and trips["last"] is not None
        assert trips["last"] <= 4
        await a_node.stop()
        await b_node.stop()

    run(main())


def test_storm_snapshot_digest_folds_per_peer_registries():
    """A storm mints one metrics registry PER SESSION, so raw committed
    snapshots ran to ~240k lines; write_obs_artifacts now digests them
    (tools/swarm_bench.snapshot_digest) unless --full-snapshots.  Pin
    the fold: registries group by class key, counters sum, gauges fold
    to min/mean/max, histograms merge to bucketless count/sum/p-ranges."""
    from tools.swarm_bench import snapshot_digest

    def reg(counters, gauges, hists):
        return {"counters": counters, "gauges": gauges, "histograms": hists}

    snap = {
        "messaging:peer00001": reg(
            {"sent": 2}, {"outbox": 1.0},
            {"rtt": {"count": 4, "sum": 2.0, "p50": 0.4, "p99": 0.9}}),
        "messaging:peer00002#1": reg(
            {"sent": 3}, {"outbox": 3.0},
            {"rtt": {"count": 6, "sum": 4.0, "p50": 0.6, "p99": 1.1}}),
        "router": reg({"frames": 7}, {}, {}),
    }
    d = snapshot_digest(snap)
    assert d["_digest"] == {"registries": 3,
                            "groups": {"messaging": 2, "router": 1}}
    m = d["messaging"]
    assert m["instances"] == 2
    assert m["counters"] == {"sent": 5}
    assert m["gauges"]["outbox"] == {"min": 1.0, "max": 3.0, "mean": 2.0}
    assert m["histograms"]["rtt"] == {"count": 10, "sum": 6.0,
                                      "p50_range": [0.4, 0.6],
                                      "p99_range": [0.9, 1.1]}
    assert d["router"]["counters"] == {"frames": 7}
