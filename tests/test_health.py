"""provider/health.py — the device-health gate.

The accelerated path must be re-validated per environment (PQC-HA,
arXiv:2308.06621) before it is trusted: correct providers pass (and the
verdict is cached keyed by the environment fingerprint), wrong answers
quarantine the batched facade's breaker onto the cpu fallback, and the HQC
FFT gate re-routes to the Toeplitz product.  Negative verdicts are never
cached — a transient device fault must re-probe at next startup.
"""

import asyncio
import hashlib
import hmac
import os

import pytest

from quantum_resistant_p2p_tpu.provider import health
from quantum_resistant_p2p_tpu.provider.base import (KeyExchangeAlgorithm,
                                                     SignatureAlgorithm)
from quantum_resistant_p2p_tpu.provider.batched import BatchedKEM, Breaker


class _GoodKEM(KeyExchangeAlgorithm):
    name = "GOOD-KEM"
    public_key_len = secret_key_len = ciphertext_len = 32

    def __init__(self, backend="tpu"):
        self.backend = backend
        self.probes = 0

    def generate_keypair(self):
        self.probes += 1
        sk = os.urandom(32)
        return hashlib.sha256(b"pk" + sk).digest(), sk

    def encapsulate(self, public_key):
        ct = os.urandom(32)
        return ct, hashlib.sha256(public_key + ct).digest()

    def decapsulate(self, secret_key, ciphertext):
        pk = hashlib.sha256(b"pk" + secret_key).digest()
        return hashlib.sha256(pk + ciphertext).digest()


class _BrokenKEM(_GoodKEM):
    """Device computes a WRONG shared secret on decaps (a numerics fault a
    latency breaker can never see)."""

    name = "BROKEN-KEM"

    def decapsulate(self, secret_key, ciphertext):
        return hashlib.sha256(b"wrong" + secret_key + ciphertext).digest()


class _GoodSig(SignatureAlgorithm):
    name = "GOOD-SIG"
    public_key_len = secret_key_len = signature_len = 32

    def __init__(self, backend="tpu"):
        self.backend = backend

    def generate_keypair(self):
        sk = os.urandom(32)
        return hashlib.sha256(b"pk" + sk).digest(), sk

    def sign(self, secret_key, message):
        pk = hashlib.sha256(b"pk" + secret_key).digest()
        return hashlib.sha256(b"sig" + pk + message).digest()

    def verify(self, public_key, message, signature):
        return hmac.compare_digest(
            signature, hashlib.sha256(b"sig" + public_key + message).digest()
        )


class _RubberStampSig(_GoodSig):
    """Accepts anything — the tamper check must catch it."""

    name = "STAMP-SIG"

    def verify(self, public_key, message, signature):
        return True


@pytest.fixture(autouse=True)
def tmp_health_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("QRP2P_HEALTH_CACHE", str(tmp_path / "health"))
    monkeypatch.delenv("QRP2P_HEALTH_GATE", raising=False)
    yield tmp_path / "health"


def test_fingerprint_names_the_numerics_axes():
    key = health.env_fingerprint()
    for axis in ("jax=", "jaxlib=", "platform=", "dev=", "probe="):
        assert axis in key


def test_cpu_backend_needs_no_gate():
    v = health.ensure_validated(_GoodKEM(backend="cpu"))
    assert v.ok and "cpu backend" in v.detail


def test_positive_verdict_cached_per_environment(tmp_health_cache):
    kem = _GoodKEM()
    v1 = health.ensure_validated(kem, cpu_twin=_GoodKEM("cpu"))
    assert v1.ok and not v1.cached
    probes = kem.probes
    assert list(tmp_health_cache.glob("health_*.json"))
    v2 = health.ensure_validated(kem, cpu_twin=_GoodKEM("cpu"))
    assert v2.ok and v2.cached
    assert kem.probes == probes  # no re-probe: the disk verdict was trusted


def test_wrong_answers_fail_and_are_never_cached(tmp_health_cache):
    kem = _BrokenKEM()
    v1 = health.ensure_validated(kem)
    assert not v1.ok and "decaps" in v1.detail
    assert not list(tmp_health_cache.glob("health_*.json"))
    probes = kem.probes
    v2 = health.ensure_validated(kem)  # self-healing: re-probed, not pinned
    assert not v2.ok and kem.probes == probes + 1


def test_cross_impl_disagreement_detected():
    """Device internally consistent but disagreeing with the cpu reference
    must fail (the PQC-HA per-environment re-validation)."""

    class SelfConsistentButWrong(_GoodKEM):
        name = "DRIFTED-KEM"

        def encapsulate(self, public_key):
            ct = os.urandom(32)
            return ct, hashlib.sha256(b"drift" + public_key + ct).digest()

        def decapsulate(self, secret_key, ciphertext):
            pk = hashlib.sha256(b"pk" + secret_key).digest()
            return hashlib.sha256(b"drift" + pk + ciphertext).digest()

    v = health.ensure_validated(SelfConsistentButWrong(), cpu_twin=_GoodKEM("cpu"))
    assert not v.ok and "cpu reference" in v.detail


def test_rubber_stamp_verify_fails_tamper_check():
    v = health.ensure_validated(_RubberStampSig())
    assert not v.ok and "tampered" in v.detail


def test_gate_facades_quarantines_broken_device_onto_fallback():
    """A failed family pins the facade's shared breaker on the cpu fallback
    for the process: wrong answers cannot be probed back to health."""
    kem = BatchedKEM(_BrokenKEM(), max_batch=4, max_wait_ms=1.0,
                     fallback=_GoodKEM("cpu"), breaker=Breaker(cooloff_s=0.01))
    for q in (kem._kg, kem._enc, kem._dec):
        q._warm_buckets.add(1)
    verdicts = health.gate_facades(kem)
    assert [v.ok for v in verdicts] == [False]
    assert kem.breaker.state == "quarantined"

    async def run():
        pk, sk = await kem.generate_keypair()
        ct, ss = await kem.encapsulate(pk)
        assert await kem.decapsulate(sk, ct) == ss  # GOOD math: the fallback
        return kem.stats()

    st = asyncio.run(run())
    assert st["decaps"]["device_trips"] == 0
    assert st["decaps"]["device_served_fraction"] == 0.0


def test_gate_facades_leaves_healthy_device_closed():
    kem = BatchedKEM(_GoodKEM(), max_batch=4, max_wait_ms=1.0,
                     fallback=_GoodKEM("cpu"), breaker=Breaker(cooloff_s=0.01))
    verdicts = health.gate_facades(kem)
    assert [v.ok for v in verdicts] == [True]
    assert kem.breaker.state == "closed"


def test_gate_disabled_by_env(monkeypatch):
    monkeypatch.setenv("QRP2P_HEALTH_GATE", "0")
    kem = BatchedKEM(_BrokenKEM(), max_batch=4, max_wait_ms=1.0,
                     fallback=_GoodKEM("cpu"), breaker=Breaker(cooloff_s=0.01))
    assert health.gate_facades(kem) == []
    assert kem.breaker.state == "closed"


def test_hqc_gate_reroutes_to_toeplitz(monkeypatch):
    """An unvalidated FFT environment routes HQC to the exact Toeplitz
    product (kem.hqc's forced impl) and says why; the verdict is healed
    (ok) and never disk-cached by health (hqc owns its marker)."""
    from quantum_resistant_p2p_tpu.kem import hqc as H

    monkeypatch.setattr(H, "_FORCED_IMPL", None)
    monkeypatch.setattr(H, "_fft_env_validated", lambda: False)
    monkeypatch.delenv("QRP2P_HQC_FFT", raising=False)
    monkeypatch.delenv("QRP2P_HQC_GATHER", raising=False)
    monkeypatch.delenv("QRP2P_HQC_SELFCHECK", raising=False)

    class FakeHQC(_GoodKEM):
        name = "HQC-128"

    v = health.ensure_validated(FakeHQC())
    assert v.ok and "re-routed" in v.detail and not v.cacheable
    assert H._FORCED_IMPL == "matmul"


def test_mlkem_kat_pins_the_device_path():
    """The pinned FIPS 203 vector (computed from pyref) passes through the
    jax path — the per-environment KAT the gate runs for the flagship
    family."""
    pytest.importorskip("jax")

    class FakeMLKEM(_GoodKEM):
        name = "ML-KEM-768"

    v = health.ensure_validated(FakeMLKEM())
    assert v.ok and "KAT ok" in v.detail


def test_fused_facade_probe_validates_against_cpu_twins():
    """The composite fused-handshake path is its own device code path; the
    gate probes keygen_sign at the LIVE offsets against the cpu twins."""
    pytest.importorskip("jax")
    from quantum_resistant_p2p_tpu.provider import get_fused, get_kem, get_signature
    from quantum_resistant_p2p_tpu.provider.batched import BatchedFused
    from quantum_resistant_p2p_tpu.provider.fused_providers import (
        init_pk_offset, resp_ct_offset)

    fused = get_fused(get_kem("ML-KEM-512", "tpu"),
                      get_signature("ML-DSA-44", "tpu"))
    bf = BatchedFused(fused, pk_off=init_pk_offset("ML-KEM-512", "AES-256-GCM"),
                      ct_off=resp_ct_offset(), max_batch=4, max_wait_ms=1.0,
                      fallback_kem=get_kem("ML-KEM-512", "cpu"),
                      fallback_sig=get_signature("ML-DSA-44", "cpu"),
                      breaker=Breaker(cooloff_s=0.01))
    verdicts = health.gate_facades(bf)
    assert [v.ok for v in verdicts] == [True]
    assert bf.breaker.state == "closed"
    assert verdicts[0].family.startswith("fused:")
