"""Two-node P2P transport tests over localhost TCP (host-only, no JAX).

Models the reference's in-process two-node approach
(tests/crypto_algorithms_tester.py:455-464) at the transport layer.
"""

import asyncio

import pytest

from quantum_resistant_p2p_tpu.net import P2PNode


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


async def _pair():
    a = P2PNode(node_id="node-a", host="127.0.0.1", port=0)
    b = P2PNode(node_id="node-b", host="127.0.0.1", port=0)
    await a.start()
    await b.start()
    peer = await a.connect_to_peer("127.0.0.1", b.port)
    assert peer == "node-b"
    for _ in range(100):
        if b.is_connected("node-a"):
            break
        await asyncio.sleep(0.01)
    assert b.is_connected("node-a")
    return a, b


def test_hello_and_roundtrip_message(run):
    async def main():
        a, b = await _pair()
        got = asyncio.Event()
        received = {}

        async def on_ping(peer_id, msg):
            received.update(msg, peer=peer_id)
            got.set()

        b.register_message_handler("ping", on_ping)
        assert await a.send_message("node-b", "ping", n=42, blob=b"\x00\xff")
        await asyncio.wait_for(got.wait(), 5)
        assert received["n"] == 42
        assert received["blob"] == b"\x00\xff"
        assert received["peer"] == "node-a"
        await a.stop()
        await b.stop()

    run(main())


def test_large_message_chunked(run):
    async def main():
        a, b = await _pair()
        a.chunk_size = 4096  # force chunking
        got = asyncio.Event()
        received = {}

        async def on_big(peer_id, msg):
            received.update(msg)
            got.set()

        b.register_message_handler("big", on_big)
        payload = bytes(range(256)) * 1024  # 256 KiB
        assert await a.send_message("node-b", "big", data=payload)
        await asyncio.wait_for(got.wait(), 10)
        assert received["data"] == payload
        await a.stop()
        await b.stop()

    run(main())


def test_disconnect_event(run):
    async def main():
        a, b = await _pair()
        events = []
        b.register_connection_handler(lambda ev, pid: events.append((ev, pid)))
        await a.stop()
        for _ in range(100):
            if ("disconnect", "node-a") in events:
                break
            await asyncio.sleep(0.01)
        assert ("disconnect", "node-a") in events
        assert not b.is_connected("node-a")
        await b.stop()

    run(main())


def test_send_to_unknown_peer(run):
    async def main():
        a = P2PNode(node_id="solo", host="127.0.0.1", port=0)
        await a.start()
        assert not await a.send_message("ghost", "ping")
        await a.stop()

    run(main())
