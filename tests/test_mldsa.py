"""ML-DSA: batched JAX implementation bit-exact vs the pure-Python oracle."""

import hashlib

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.pyref import mldsa_ref
from quantum_resistant_p2p_tpu.sig import mldsa as jmldsa

RNG = np.random.default_rng(20260729)


def _mu(tr: bytes, message: bytes) -> bytes:
    return hashlib.shake_256(tr + bytes([0, 0]) + message).digest(64)


@pytest.mark.parametrize("name", ["ML-DSA-44", "ML-DSA-65", "ML-DSA-87"])
def test_keygen_matches_oracle(name):
    p = mldsa_ref.PARAMS[name]
    xi = RNG.integers(0, 256, size=(3, 32), dtype=np.uint8)
    kg, _, _ = jmldsa.get(name)
    pk, sk = kg(xi)
    for i in range(3):
        rpk, rsk = mldsa_ref.keygen(p, xi[i].tobytes())
        assert bytes(np.asarray(pk)[i]) == rpk
        assert bytes(np.asarray(sk)[i]) == rsk


@pytest.mark.parametrize(
    "name",
    # 44 and 87 ride the slow tier: the fast tier keeps their JAX coverage
    # through test_kat.py's mldsa KATs at a third of the wall-clock (the
    # pure-Python oracle signing dominates this test's 3 minutes).
    [pytest.param("ML-DSA-44", marks=pytest.mark.slow), "ML-DSA-65",
     pytest.param("ML-DSA-87", marks=pytest.mark.slow)],
)
def test_sign_matches_oracle_and_verifies(name):
    p = mldsa_ref.PARAMS[name]
    batch = 3
    xi = RNG.integers(0, 256, size=(batch, 32), dtype=np.uint8)
    rnd = RNG.integers(0, 256, size=(batch, 32), dtype=np.uint8)
    msgs = [bytes(RNG.integers(0, 256, size=40 + 13 * i, dtype=np.uint8)) for i in range(batch)]

    kg, sign_mu, verify_mu = jmldsa.get(name)
    pk, sk = np.asarray(kg(xi)[0]), np.asarray(jmldsa.keygen(p, xi)[1])
    mus = np.stack(
        [np.frombuffer(_mu(bytes(sk[i][64:128]), msgs[i]), np.uint8) for i in range(batch)]
    )
    sigs, done = sign_mu(sk, mus, rnd)
    sigs = np.asarray(sigs)
    assert np.asarray(done).all()
    for i in range(batch):
        ref_sig = mldsa_ref.sign(p, bytes(sk[i]), msgs[i], rnd=bytes(rnd[i]))
        assert bytes(sigs[i]) == ref_sig, f"lane {i} diverges from oracle"
        assert mldsa_ref.verify(p, bytes(pk[i]), msgs[i], bytes(sigs[i]))

    ok = np.asarray(verify_mu(pk, mus, sigs))
    assert ok.all()

    # tampered message must fail
    bad_mus = mus.copy()
    bad_mus[:, 0] ^= 1
    assert not np.asarray(verify_mu(pk, bad_mus, sigs)).any()

    # tampered signature must fail
    bad_sigs = sigs.copy()
    bad_sigs[:, -1] ^= 0xFF
    assert not np.asarray(verify_mu(pk, mus, bad_sigs)).any()


def test_provider_tpu_backend_roundtrip():
    from quantum_resistant_p2p_tpu.provider import get_signature

    alg = get_signature("ML-DSA-44", backend="tpu")
    pk, sk = alg.generate_keypair()
    assert len(pk) == alg.public_key_len and len(sk) == alg.secret_key_len
    msg = b"tpu-native ml-dsa provider"
    sig = alg.sign(sk, msg)
    assert alg.verify(pk, msg, sig)
    assert not alg.verify(pk, msg + b"!", sig)
    # cross-backend: cpu verifies tpu signature and vice versa
    cpu = get_signature("ML-DSA-44", backend="cpu")
    assert cpu.verify(pk, msg, sig)
    cpu_sig = cpu.sign(sk, msg)
    assert alg.verify(pk, msg, cpu_sig)


def test_batch_sign_verify():
    from quantum_resistant_p2p_tpu.provider import get_signature

    alg = get_signature("ML-DSA-44", backend="tpu")
    pk, sk = alg.generate_keypair()
    n = 4
    sks = np.broadcast_to(np.frombuffer(sk, np.uint8), (n, len(sk)))
    pks = np.broadcast_to(np.frombuffer(pk, np.uint8), (n, len(pk)))
    msgs = [b"msg-%d" % i for i in range(n)]
    sigs = alg.sign_batch(sks, msgs)
    assert alg.verify_batch(pks, msgs, sigs).all()
    assert not alg.verify_batch(pks, [m + b"x" for m in msgs], sigs).any()


def test_strict_sampler_guard(monkeypatch):
    # With the guard on, sampling must pass silently for honest seeds (the
    # truncated 1024-candidate buffer virtually always fills), and the check
    # itself must trip on an under-filled buffer.
    monkeypatch.setattr(jmldsa, "STRICT_SAMPLERS", True)
    seeds = RNG.integers(0, 256, size=(8, 66), dtype=np.uint8)
    out = np.asarray(jmldsa.rej_bounded_poly(2, seeds))
    assert out.shape == (8, 256)
    with pytest.raises(AssertionError, match="rej_bounded_poly"):
        jmldsa._check_sampler_fill(np.array([True, False]), "rej_bounded_poly")


def test_sign_compact_bit_exact_vs_full_loop():
    """Compact-and-refill signing produces bit-identical signatures to the
    run-to-completion loop (same per-lane kappa sequences), across several
    compaction rounds (round_iters=1 forces refills)."""
    name = "ML-DSA-44"
    p = mldsa_ref.PARAMS[name]
    kg, sign_mu, _ = jmldsa.get(name)
    n = 10
    xi = RNG.integers(0, 256, (n, 32), dtype=np.uint8)
    _, sk = kg(xi)
    sk = np.asarray(sk)
    mus = RNG.integers(0, 256, (n, 64), dtype=np.uint8)
    rnds = RNG.integers(0, 256, (n, 32), dtype=np.uint8)

    ref_sig, ref_done = (np.asarray(a) for a in sign_mu(sk, mus, rnds))
    assert ref_done.all()
    got_sig, got_done = jmldsa.sign_mu_compact(
        name, sk, mus, rnds, schedule=(1, 1, 2), min_bucket=1
    )
    assert got_done.all()
    assert np.array_equal(got_sig, ref_sig)


def test_sign_rounds_unroll_bit_exact_and_validated():
    """sign_mu_rounds(unroll=k) is bit-identical to unroll=1 — including
    the returned resumption state — when n_iters is a multiple of k, and
    rejects budgets that are not (the overshoot would change (done,
    kappa) semantics) and non-positive unroll (non-terminating loop)."""
    import pytest

    name = "ML-DSA-44"
    p = mldsa_ref.PARAMS[name]
    kg, _, _ = jmldsa.get(name)
    n = 6
    xi = RNG.integers(0, 256, (n, 32), dtype=np.uint8)
    _, sk = kg(xi)
    sk = np.asarray(sk)
    mus = RNG.integers(0, 256, (n, 64), dtype=np.uint8)
    rnds = RNG.integers(0, 256, (n, 32), dtype=np.uint8)
    k0 = np.zeros(n, np.int32)
    ref = tuple(np.asarray(a)
                for a in jmldsa.sign_mu_rounds(p, sk, mus, rnds, k0, 6, unroll=1))
    for u in (2, 3):
        got = tuple(np.asarray(a)
                    for a in jmldsa.sign_mu_rounds(p, sk, mus, rnds, k0, 6, unroll=u))
        for g, r in zip(got, ref):
            assert np.array_equal(g, r), u
    with pytest.raises(ValueError):
        jmldsa.sign_mu_rounds(p, sk, mus, rnds, k0, 6, unroll=4)
    with pytest.raises(ValueError):
        jmldsa.sign_mu_rounds(p, sk, mus, rnds, k0, 6, unroll=0)


def test_provider_sign_batch_uses_compact_driver():
    from quantum_resistant_p2p_tpu.provider.sig_providers import MLDSASignature

    alg = MLDSASignature(2, backend="tpu", compact_sign=True)
    pk, sk = alg.generate_keypair()
    n = 5
    sks = np.broadcast_to(np.frombuffer(sk, np.uint8), (n, len(sk)))
    pks = np.broadcast_to(np.frombuffer(pk, np.uint8), (n, len(pk)))
    msgs = [b"compact-%d" % i for i in range(n)]
    sigs = alg.sign_batch(sks, msgs)
    assert alg.verify_batch(pks, msgs, sigs).all()


@pytest.mark.slow
def test_on_device_rejection_loop_matches_host_loop():
    """Distribution pin for the on-device rejection loop: driving
    ``sign_mu_rounds`` ONE attempt at a time from a host-side while loop
    (per-lane resume from the returned kappa) reproduces the fused
    ``lax.while_loop`` byte-for-byte — signatures AND per-lane iteration
    counts — for a seeded batch.  Any drift in the device loop's attempt
    sequencing (kappa stepping, first-accept selection) fails here."""
    name = "ML-DSA-44"
    p = mldsa_ref.PARAMS[name]
    kg, sign_mu, _ = jmldsa.get(name)
    n = 8
    xi = RNG.integers(0, 256, (n, 32), dtype=np.uint8)
    _, sk = kg(xi)
    sk = np.asarray(sk)
    mus = RNG.integers(0, 256, (n, 64), dtype=np.uint8)
    rnds = RNG.integers(0, 256, (n, 32), dtype=np.uint8)

    dev_sig, dev_done, dev_kappa = (
        np.asarray(a)
        for a in jmldsa.sign_mu_rounds(p, sk, mus, rnds, np.int32(0),
                                       jmldsa.MAX_SIGN_ITERS)
    )
    assert dev_done.all()

    # host loop: one device attempt per step, keep each lane's FIRST accept
    sig = np.zeros_like(dev_sig)
    done = np.zeros(n, bool)
    kappa = np.zeros(n, np.int32)
    for _ in range(jmldsa.MAX_SIGN_ITERS):
        s, d, k = (np.asarray(a) for a in jmldsa.sign_mu_rounds(
            p, sk, mus, rnds, kappa, 1))
        fresh = np.asarray(d) & ~done
        sig[fresh] = s[fresh]
        kappa = np.where(done, kappa, k)
        done |= np.asarray(d)
        if done.all():
            break
    assert done.all()
    assert np.array_equal(sig, dev_sig)
    # the per-lane attempt counts (the rejection distribution) match too
    assert np.array_equal(kappa, dev_kappa)
    # sanity: the seeded batch genuinely exercises rejection (some lane > 1
    # attempt), so the pin is not vacuous
    assert (kappa > 1).any()
