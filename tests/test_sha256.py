"""SHA-256 / HMAC / HKDF kernels vs hashlib/hmac oracles."""

import hashlib
import hmac as hmac_mod

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.core import sha256 as jsha


@pytest.mark.parametrize("length", [0, 1, 3, 32, 55, 56, 63, 64, 65, 127, 128, 300])
def test_sha256_matches_hashlib(length):
    rng = np.random.default_rng(length)
    data = rng.integers(0, 256, size=(4, length), dtype=np.uint8)
    out = np.asarray(jsha.sha256(data))
    for i in range(4):
        assert bytes(out[i]) == hashlib.sha256(data[i].tobytes()).digest()


def test_midstate_equals_full_hash():
    rng = np.random.default_rng(7)
    prefix = rng.integers(0, 256, size=(3, 64), dtype=np.uint8)
    tail = rng.integers(0, 256, size=(3, 22 + 16), dtype=np.uint8)
    st = jsha.midstate(prefix)
    out = np.asarray(jsha.sha256_from_midstate(st, tail, prefix_blocks=1))
    for i in range(3):
        assert bytes(out[i]) == hashlib.sha256(prefix[i].tobytes() + tail[i].tobytes()).digest()


@pytest.mark.parametrize("key_len,msg_len", [(32, 13), (64, 100), (80, 64)])
def test_hmac_matches_stdlib(key_len, msg_len):
    rng = np.random.default_rng(key_len * 100 + msg_len)
    key = rng.integers(0, 256, size=(2, key_len), dtype=np.uint8)
    msg = rng.integers(0, 256, size=(2, msg_len), dtype=np.uint8)
    out = np.asarray(jsha.hmac_sha256(key, msg))
    for i in range(2):
        ref = hmac_mod.new(key[i].tobytes(), msg[i].tobytes(), hashlib.sha256).digest()
        assert bytes(out[i]) == ref


@pytest.mark.parametrize("length", [32, 42, 64, 100])
def test_hkdf_matches_cryptography(length):
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF

    rng = np.random.default_rng(length)
    ikm = rng.integers(0, 256, size=(2, 32), dtype=np.uint8)
    salt = rng.integers(0, 256, size=(2, 16), dtype=np.uint8)
    info = rng.integers(0, 256, size=(2, 20), dtype=np.uint8)
    out = np.asarray(jsha.hkdf_sha256(ikm, salt, info, length))
    for i in range(2):
        ref = HKDF(
            algorithm=hashes.SHA256(), length=length,
            salt=salt[i].tobytes(), info=info[i].tobytes(),
        ).derive(ikm[i].tobytes())
        assert bytes(out[i]) == ref
