"""Gateway-pod fleet (fleet/) — the handoff-edge acceptance suite.

Covers ISSUE 11's test satellite:

* consistent-hash ring determinism + stability (adding/removing one
  gateway moves ONLY its arc),
* the shared two-level placement policy (``provider.scheduler.select_slot``)
  picking among :class:`GatewayMember` slots exactly as it picks among
  local shards,
* fleet admission shed at the router with the typed ``__busy__`` reply,
* ring-walk routing past a breaker-open (dead) gateway to its successor,
  with client-side ``exclude`` honored,
* gateway death mid-handshake: the initiator's in-flight handshake fails
  FAST with a typed reason (never burning the protocol timeout) so the
  fleet retry loop can re-route promptly — and nothing plaintext moves,
* the healed gateway's half-open re-entry: partition -> missed heartbeats
  -> fleet breaker opens -> arc drains to the successor -> probe succeeds
  -> arc snaps back (live task-mode fleet over real localhost TCP),
* seeded kill-chaos determinism: the same plan seed yields the same
  ``injected`` log, byte for byte,
* ``storm_env`` restoring the module-global protocol timeout even when
  the storm raises,
* per-node SLO report merging (``obs.slo.merge_reports`` +
  ``tools/slo_merge.py``): fleet totals, worst-node attribution.

Everything runs on minimal images: stdlib toy crypto (fleet/stormlib.py),
injectable clocks for the breaker timelines, in-process (``spawn="task"``)
gateways for the live-fleet cases — same control protocol, real TCP.
"""

import asyncio
import json
import time

import pytest

from quantum_resistant_p2p_tpu.app import messaging as messaging_mod
from quantum_resistant_p2p_tpu.faults import FaultPlan, FaultRule
from quantum_resistant_p2p_tpu.fleet import control as fleet_control
from quantum_resistant_p2p_tpu.fleet.manager import (FleetBusy, GatewayFleet,
                                                     GatewayMember)
from quantum_resistant_p2p_tpu.fleet.ring import HashRing
from quantum_resistant_p2p_tpu.fleet.stormlib import storm_env
from quantum_resistant_p2p_tpu.obs.slo import merge_reports
from quantum_resistant_p2p_tpu.provider.scheduler import select_slot


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


@pytest.fixture(autouse=True)
def fast_timeout(monkeypatch):
    monkeypatch.setattr(messaging_mod, "KEY_EXCHANGE_TIMEOUT", 5.0)
    monkeypatch.setattr(messaging_mod, "KE_RETRY_BACKOFF_S", 0.05)


KEYS = [f"peer{i:04d}" for i in range(400)]


# -- consistent-hash ring -----------------------------------------------------


def test_ring_deterministic_across_instances():
    """Same (seed, membership) -> byte-identical assignment, regardless of
    insertion order: the router and any offline tool agree without
    coordination."""
    a = HashRing(["gw0", "gw1", "gw2"], seed=7)
    b = HashRing(["gw2", "gw0", "gw1"], seed=7)
    assert [a.assign(k) for k in KEYS] == [b.assign(k) for k in KEYS]
    c = HashRing(["gw0", "gw1", "gw2"], seed=8)
    assert [a.assign(k) for k in KEYS] != [c.assign(k) for k in KEYS]


def test_ring_add_moves_only_the_new_members_arc():
    ring = HashRing(["gw0", "gw1", "gw2"], seed=0)
    before = {k: ring.assign(k) for k in KEYS}
    ring.add("gw3")
    moved = {k for k in KEYS if ring.assign(k) != before[k]}
    assert moved  # the new member takes a real share
    assert all(ring.assign(k) == "gw3" for k in moved)


def test_ring_remove_moves_only_the_dead_members_arc():
    ring = HashRing(["gw0", "gw1", "gw2"], seed=0)
    before = {k: ring.assign(k) for k in KEYS}
    ring.remove("gw1")
    for k in KEYS:
        if before[k] != "gw1":
            assert ring.assign(k) == before[k]
        else:
            assert ring.assign(k) in ("gw0", "gw2")


def test_ring_successors_start_at_owner_and_cover_members():
    ring = HashRing(["gw0", "gw1", "gw2"], seed=0)
    for k in KEYS[:32]:
        order = list(ring.successors(k))
        assert order[0] == ring.assign(k)
        assert sorted(order) == ["gw0", "gw1", "gw2"]


# -- the shared two-level placement policy ------------------------------------


def _member(gid, index, clock):
    return GatewayMember(gid, index, cooloff_s=1.0, cooloff_max_s=8.0,
                         clock=clock)


def test_select_slot_places_among_gateway_members():
    """GatewayMember satisfies the same slot protocol as a local Shard:
    least-loaded closed member wins, ties break on index."""
    now = [100.0]
    members = [_member(f"gw{i}", i, lambda: now[0]) for i in range(3)]
    members[0].inflight = 5
    members[1].inflight = 2
    members[2].inflight = 2
    assert select_slot(members) is members[1]


def test_select_slot_prefers_probe_ready_member_then_degrades():
    now = [100.0]
    members = [_member(f"gw{i}", i, lambda: now[0]) for i in range(3)]
    members[1].breaker.record_failure("device")  # open, cooloff 1s
    assert select_slot(members) is members[0]  # closed beats open
    now[0] += 2.0  # past cool-off: the dead member is probe-eligible
    assert select_slot(members) is members[1]
    # quarantined members are never placed while an alternative exists
    members[1].breaker.record_failure("probe")  # re-open (not quarantine)
    members[0].breaker.quarantine("test")
    assert select_slot(members) is members[2]


# -- router-side routing and admission (offline: no processes) ----------------


def _offline_fleet(n=3, per_gateway_max_peers=0, clock=None):
    fleet = GatewayFleet(n, spawn="task",
                         per_gateway_max_peers=per_gateway_max_peers,
                         clock=clock or time.monotonic)
    for m in fleet.members.values():  # pretend every gateway registered
        m.host, m.port = "127.0.0.1", 40000 + m.index
    return fleet


def test_fleet_admission_shed_is_typed_busy():
    """Over-budget route queries shed AT THE ROUTER: FleetBusy in-process,
    the typed ``__busy__`` frame on the wire — the same shape a gateway's
    own connection budget uses."""
    fleet = _offline_fleet(2, per_gateway_max_peers=2)  # fleet budget 4
    for i in range(4):
        assert fleet.route(f"peer{i}") is not None
    with pytest.raises(FleetBusy):
        fleet.route("peer4")
    reply = fleet._route_reply({"peer_id": "peer5"})
    assert reply == {"type": fleet_control.BUSY, "scope": "fleet"}
    assert fleet.route_sheds == 2
    # a finished session releases its slot and routing resumes
    fleet.session_done(fleet.ring.assign("peer0"))
    assert fleet.route("peer6") is not None


def test_fleet_budget_excludes_open_members():
    """A dead gateway's capacity is not capacity: the fleet budget is the
    sum over CLOSED members only."""
    now = [100.0]
    fleet = _offline_fleet(3, per_gateway_max_peers=5, clock=lambda: now[0])
    assert fleet.fleet_budget() == 15
    fleet.members["gw1"].breaker.record_failure("device")
    assert fleet.fleet_budget() == 10


def test_all_dead_budget_sheds_instead_of_admitting_unbounded():
    """Zero healthy capacity is budget 0, NOT 'unconfigured': with every
    breaker open a configured fleet sheds route queries with the typed
    busy frame rather than piling unlimited sessions onto degraded
    members (None, not 0, is the no-budget sentinel)."""
    now = [100.0]
    fleet = _offline_fleet(3, per_gateway_max_peers=5, clock=lambda: now[0])
    for m in fleet.members.values():
        m.breaker.record_failure("device")
    assert fleet.fleet_budget() == 0
    with pytest.raises(FleetBusy):
        fleet.route("peer0")
    assert _offline_fleet(3).fleet_budget() is None  # unconfigured


def test_probe_heal_refreshes_liveness_no_instant_redeath(run):
    """A successful half-open canary IS fresh liveness evidence: the next
    health tick must not re-declare the just-healed member dead off its
    stale pre-outage heartbeat timestamp (the heal-flap edge)."""
    now = [100.0]
    fleet = _offline_fleet(2, clock=lambda: now[0])
    gw1 = fleet.members["gw1"]
    gw1.last_hb = now[0]
    now[0] += fleet.hb_miss_limit * fleet.hb_interval + 1.0  # outage
    fleet._health_tick()
    assert gw1.breaker.state == "open"
    now[0] += gw1.breaker.cooloff_s + 0.1  # cool-off over: probe-eligible
    assert gw1.breaker.acquire_dispatch() == "probe"

    async def wire_probe_ok(member, n):
        return None

    fleet._probe_call = wire_probe_ok  # canary round-trip succeeds
    run(fleet._probe_gateway(gw1, 1))
    assert gw1.breaker.state == "closed"
    # the very next tick, BEFORE any post-outage heartbeat lands, must not
    # flap the breaker back open off the stale timestamp
    fleet._health_tick()
    assert gw1.breaker.state == "closed"


def test_route_hands_open_members_arc_to_ring_successor():
    now = [100.0]
    fleet = _offline_fleet(3, clock=lambda: now[0])
    owner_key = next(k for k in KEYS if fleet.ring.assign(k) == "gw1")
    successor = list(fleet.ring.successors(owner_key))[1]
    assert fleet.route(owner_key).gateway_id == "gw1"
    fleet.members["gw1"].breaker.record_failure("device")  # gw1 is dead
    assert fleet.route(owner_key).gateway_id == successor
    assert fleet.handoffs == 1
    # client-side exclude is honored even while the breaker is closed
    # (the router may be one heartbeat behind the client's observation)
    key2 = next(k for k in KEYS if fleet.ring.assign(k) == "gw0")
    assert fleet.route(key2, exclude=("gw0",)).gateway_id != "gw0"


# -- seeded process-scope chaos ----------------------------------------------


def test_process_chaos_log_is_deterministic_from_seed():
    """Same seed + same health-tick event stream -> the same ``injected``
    log, byte for byte (the fleet storm's reproducibility claim)."""

    def drive(seed):
        plan = FaultPlan(seed, [
            FaultRule("process", "kill_gateway", match={"gateway": "gw1"},
                      nth=3),
            FaultRule("process", "pause_gateway", match={"gateway": "gw0"},
                      nth=2, delay_s=0.5),
        ])
        with plan.activate():
            from quantum_resistant_p2p_tpu.faults import plan as plan_mod

            for _tick in range(4):  # the health loop: sorted order, 1 poll
                for gid in ("gw0", "gw1", "gw2"):  # per gateway per tick
                    plan_mod.process_control(gid)
        return json.dumps(plan.injected, sort_keys=True)

    log = drive(11)
    assert log == drive(11)
    assert json.loads(log) == [
        {"scope": "process", "action": "pause_gateway", "n": 2,
         "gateway": "gw0", "delay_s": 0.5},
        {"scope": "process", "action": "kill_gateway", "n": 3,
         "gateway": "gw1"},
    ]
    assert drive(12) == log  # seed only feeds RNG-bearing actions


def test_process_control_is_noop_without_plan():
    from quantum_resistant_p2p_tpu.faults import plan as plan_mod

    assert plan_mod.process_control("gw0") == []


# -- storm_env ----------------------------------------------------------------


def test_storm_env_restores_timeout_even_on_raise():
    before = messaging_mod.KEY_EXCHANGE_TIMEOUT
    with pytest.raises(RuntimeError):
        with storm_env(99.0):
            assert messaging_mod.KEY_EXCHANGE_TIMEOUT == 99.0
            raise RuntimeError("storm blew up")
    assert messaging_mod.KEY_EXCHANGE_TIMEOUT == before


# -- per-node SLO report merging ---------------------------------------------


def _node_report(node, good, bad, burn_fast, alerting=False):
    return {
        "node": node,
        "slo": {"specs": [{
            "name": "handshake_p99", "objective": 0.99,
            "good_total": good, "bad_total": bad,
            "burn_fast": burn_fast, "alerting": alerting,
        }]},
    }


def test_merge_reports_fleet_totals_and_worst_node():
    merged = merge_reports([
        _node_report("gw0", 98.0, 2.0, 0.5),
        _node_report("gw1", 40.0, 10.0, 20.0, alerting=True),
        _node_report("gw2", 100.0, 0.0, 0.0),
    ])
    slo = merged["slos"]["handshake_p99"]
    assert slo["good_total"] == 238.0 and slo["bad_total"] == 12.0
    assert slo["fleet_error_rate"] == round(12.0 / 250.0, 6)
    assert slo["fleet_burn"] == round((12.0 / 250.0) / 0.01, 4)
    assert slo["worst_node"] == "gw1"
    assert merged["worst_node"] == "gw1"
    assert merged["alerting"] == ["gw1"]


def test_slo_merge_cli_merges_a_report_dir(tmp_path, capsys):
    from tools import slo_merge

    for i in range(2):
        (tmp_path / f"gw{i}_slo_report.json").write_text(
            json.dumps(_node_report(f"gw{i}", 10.0 * (i + 1), float(i), 0.1)))
    out = tmp_path / "fleet.json"
    assert slo_merge.main([str(tmp_path), "--out", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert doc["nodes"] == ["gw0", "gw1"]
    assert doc["slos"]["handshake_p99"]["good_total"] == 30.0
    assert "2 node report(s)" in capsys.readouterr().out


# -- live fleet: death, handoff, half-open heal (task mode, real TCP) ---------


FAST = dict(hb_interval=0.05, cooloff_s=0.25, cooloff_max_s=2.0,
            register_timeout=30.0)


def test_gateway_death_mid_handshake_fails_fast_typed(run):
    """The messaging-layer half of the handoff contract: when the gateway
    drops mid-handshake, the initiator's in-flight exchange fails NOW with
    a typed reason — never burning KEY_EXCHANGE_TIMEOUT — so the fleet
    retry loop can walk to the ring successor promptly.  Nothing plaintext
    is ever sent (no shared key exists)."""

    async def scenario():
        fleet = GatewayFleet(2, spawn="task", **FAST)
        await fleet.start()
        try:
            from quantum_resistant_p2p_tpu.fleet.stormlib import (
                StormAEAD, register_storm_providers)
            from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode
            from quantum_resistant_p2p_tpu.provider import (get_kem,
                                                            get_signature)

            register_storm_providers()
            node = P2PNode(node_id="client", host="127.0.0.1", port=0)
            sm = messaging_mod.SecureMessaging(
                node, kem=get_kem("STORM-KEM", "cpu"),
                symmetric=StormAEAD(),
                signature=get_signature("STORM-SIG", "cpu"), auto_heal=False)
            victim = fleet.members["gw0"]
            assert await node.connect_to_peer(
                "127.0.0.1", victim.port) == "gw0"
            # pin the race: the gateway's ke_response is dropped by the
            # seeded plan, so the initiator is PROVABLY mid-handshake
            # (waiting on a response that can never arrive) when the
            # gateway dies
            plan = FaultPlan(0, [FaultRule(
                "net.send", "drop", match={"msg_type": "ke_response"},
                nth=1)])
            with plan.activate():
                task = asyncio.ensure_future(
                    sm.initiate_key_exchange("gw0"))
                await asyncio.sleep(0.15)
                fleet.kill("gw0")
                t0 = time.monotonic()
                ok = await task
                waited = time.monotonic() - t0
            assert plan.injected  # the drop really happened
            assert ok is False
            # typed fast-fail, not a protocol-timeout burn
            assert waited < messaging_mod.KEY_EXCHANGE_TIMEOUT / 2
            assert "gw0" not in sm.shared_keys
            assert await sm.send_message("gw0", b"secret") is None
            await node.stop()
        finally:
            await fleet.stop()

    run(scenario())


def test_partitioned_gateway_heals_via_half_open_probe(run):
    """The false-dead case end to end on a LIVE task-mode fleet: a control
    partition makes gw1 miss heartbeats -> its fleet breaker opens and the
    ring arc drains to gw0 -> the partition lifts -> the half-open canary
    probe succeeds -> the breaker closes and gw1's arc snaps back (ring
    membership never changed)."""

    async def scenario():
        fleet = GatewayFleet(2, spawn="task", **FAST)
        events = []
        fleet.on_event(lambda ev, gid: events.append((ev, gid)))
        await fleet.start()
        try:
            owned = next(k for k in KEYS if fleet.ring.assign(k) == "gw1")
            assert fleet.route(owned).gateway_id == "gw1"
            fleet.partition("gw1", 0.6)
            for _ in range(100):  # detection: hb_miss_limit * hb_interval
                if fleet.members["gw1"].breaker.state != "closed":
                    break
                await asyncio.sleep(0.05)
            assert fleet.members["gw1"].breaker.state == "open"
            assert ("gateway_dead", "gw1") in events
            assert fleet.route(owned).gateway_id == "gw0"  # arc drained
            for _ in range(200):  # partition lifts; probe closes it
                if fleet.members["gw1"].breaker.state == "closed":
                    break
                await asyncio.sleep(0.05)
            assert fleet.members["gw1"].breaker.state == "closed"
            assert ("gateway_healed", "gw1") in events
            assert fleet.route(owned).gateway_id == "gw1"  # arc snapped back
        finally:
            await fleet.stop()

    run(scenario())


def test_fleet_storm_survives_seeded_gateway_kill(run):
    """The chaos acceptance shape in miniature (the CI ratchet runs it at
    1000 sessions via ``bench.py --storm --fleet 3``): a seeded mid-storm
    gateway kill, every established session finishes (ring-successor
    handoff + re-key), 0 plaintext sends, and the injected log replays
    byte-for-byte from the seed."""
    from quantum_resistant_p2p_tpu.fleet.storm import (default_kill_rules,
                                                       run_fleet_storm)

    # msg_interval_s paces sessions so they are ALIVE at the kill tick —
    # on a fast host an unpaced 10-session storm finishes before tick 2
    # and the kill (the thing under test) never fires
    out = run(run_fleet_storm(
        sessions=10, gateways=3, spawn="task", concurrency=10,
        msgs_per_session=4, hb_interval=0.05, ke_timeout=30.0,
        msg_interval_s=0.1, session_attempts=8,
        fault_rules=default_kill_rules("gw1", tick=2), seed=5))
    assert out["completed_sessions"] == 10
    assert out["lost_established_sessions"] == 0
    assert out["plaintext_sends"] == 0
    assert out["chaos"]["injected_log"] == [
        {"scope": "process", "action": "kill_gateway", "n": 2,
         "gateway": "gw1"}]
    assert out["fleet"]["members"][1]["killed"] is True


# -- graceful drain / rolling restart / STEK distribution (ISSUE 15) ----------


def test_draining_member_excluded_from_routing():
    fleet = _offline_fleet(3)
    fleet.members["gw0"].draining = True
    for peer in (f"p{i}" for i in range(24)):
        m = fleet.route(peer)
        assert m is not None and m.gateway_id != "gw0"
        fleet.session_done(m.gateway_id)
    # budget counts only non-draining capacity
    fleet.per_gateway_max_peers = 4
    assert fleet.fleet_budget() == 8


def test_drain_gateway_is_a_valid_chaos_action():
    FaultRule("process", "drain_gateway", match={"gateway": "gw0"})
    with pytest.raises(ValueError):
        FaultRule("process", "nonsense")
    # the ticket scope exists with exactly its three typed actions
    for action in ("corrupt", "expire", "replay"):
        FaultRule("ticket", action)
    with pytest.raises(ValueError):
        FaultRule("ticket", "drop")


def test_reset_for_respawn_forgets_the_dead_incarnation():
    m = GatewayMember("gw0", 0, clock=time.monotonic)
    m.host, m.port, m.pid = "127.0.0.1", 40000, 123
    m.last_hb = 1.0
    m.breaker.record_failure("device")
    m.inflight = 7
    m.reset_for_respawn()
    assert not m.registered and m.pid is None and m.last_hb is None
    assert m.breaker.state == "closed"  # a planned restart is not failure
    assert m.inflight == 0 and m.restarts == 1


def test_stek_pushed_on_registration_and_rotation(run):
    """Every gateway's ticket ring is the ROUTER's ring (pushed at hello),
    and a rotation re-pushes the new window — the property that makes a
    ticket minted by gw0 resume on gw1, and on a respawned gw0."""
    async def main():
        fleet = GatewayFleet(2, spawn="task", hb_interval=0.05)
        try:
            await fleet.start()
            blob = fleet.ticket_keys.seal_ticket(
                {"v": 1, "holder": "x", "secret": "00" * 32, "nonce": "n"})
            epoch0 = fleet.ticket_keys.current_epoch
            epoch1 = await fleet.rotate_stek()
            assert epoch1 != epoch0
            # dual-key window: the pre-rotation blob still opens
            meta, _secret = fleet.ticket_keys.open_ticket(blob)
            assert meta["holder"] == "x"
            assert fleet.stats()["stek_epoch"] == epoch1
        finally:
            await fleet.stop()

    run(main())


def test_rolling_restart_respawns_and_reregisters(run):
    async def main():
        fleet = GatewayFleet(2, spawn="task", hb_interval=0.05)
        try:
            await fleet.start()
            rep = await fleet.rolling_restart(drain_timeout=10.0)
            assert rep["ok"] is True
            assert [r["gateway"] for r in rep["restarted"]] == ["gw0", "gw1"]
            assert all(r["graceful_exit"] and r["registered"]
                       for r in rep["restarted"])
            assert all(m.registered and not m.draining
                       for m in fleet.members.values())
            assert all(m.restarts == 1 for m in fleet.members.values())
        finally:
            await fleet.stop()

    run(main())


def test_roll_storm_sessions_survive_and_resume(run):
    """The rolling-restart acceptance shape in miniature (the CI ratchet
    runs it at 1000 sessions via ``bench.py --storm --fleet 3 --roll``):
    every gateway drained + respawned mid-storm, 0 lost established
    sessions, 0 plaintext, and displaced sessions resume VIA TICKET on
    wherever the ring re-routes them."""
    from quantum_resistant_p2p_tpu.fleet.storm import run_fleet_storm

    out = run(run_fleet_storm(
        sessions=24, gateways=2, spawn="task", concurrency=8,
        msgs_per_session=6, arrival_rate=20.0, hb_interval=0.05,
        ke_timeout=30.0, seed=5, roll=True, roll_delay_s=0.5,
        drain_timeout=10.0, session_attempts=8, msg_interval_s=0.05))
    assert out["completed_sessions"] == 24
    assert out["lost_established_sessions"] == 0
    assert out["plaintext_sends"] == 0
    assert out["roll"] and out["roll"]["ok"]
    assert out["resumed_reconnects"] >= 1
    assert out["full_handshake_reconnects"] == 0
    assert out["post_roll_resume_rate"] in (None, 1.0)
