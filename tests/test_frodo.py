"""FrodoKEM batched JAX vs pure-Python oracle + AES kernel checks."""

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.pyref import frodo_ref as fr

RNG = np.random.default_rng(640)


def test_aes_kernel_matches_cryptography():
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    from quantum_resistant_p2p_tpu.core import aes as jaes

    keys = RNG.integers(0, 256, size=(3, 16), dtype=np.uint8)
    blocks = RNG.integers(0, 256, size=(3, 5, 16), dtype=np.uint8)
    rk = jaes.key_schedule(keys)
    out = np.asarray(jaes.encrypt_blocks(rk, blocks))
    for i in range(3):
        enc = Cipher(algorithms.AES(keys[i].tobytes()), modes.ECB()).encryptor()
        ref = enc.update(blocks[i].tobytes())
        assert out[i].tobytes() == ref


@pytest.mark.parametrize("name", ["FrodoKEM-640-AES", "FrodoKEM-640-SHAKE"])
def test_matches_oracle(name):
    if "AES" in name:
        pytest.importorskip("cryptography")  # pyref oracle's matrix expansion
    from quantum_resistant_p2p_tpu.kem import frodo as jfr

    p = fr.PARAMS[name]
    batch = 2
    kg, enc, dec = jfr.get(name)
    s = RNG.integers(0, 256, size=(batch, p.len_sec), dtype=np.uint8)
    se = RNG.integers(0, 256, size=(batch, p.len_sec), dtype=np.uint8)
    z = RNG.integers(0, 256, size=(batch, p.len_sec), dtype=np.uint8)
    mu = RNG.integers(0, 256, size=(batch, p.len_sec), dtype=np.uint8)
    pk, sk = np.asarray(kg(s, se, z)[0]), np.asarray(kg(s, se, z)[1])
    ct, ss = enc(pk, mu)
    ct, ss = np.asarray(ct), np.asarray(ss)
    ss_dec = np.asarray(dec(sk, ct))
    for i in range(batch):
        rpk, rsk = fr.keygen(p, s[i].tobytes(), se[i].tobytes(), z[i].tobytes())
        assert bytes(pk[i]) == rpk
        assert bytes(sk[i]) == rsk
        rct, rss = fr.encaps(p, rpk, mu[i].tobytes())
        assert bytes(ct[i]) == rct
        assert bytes(ss[i]) == rss
        assert bytes(ss_dec[i]) == rss
    # implicit rejection on tampered ct
    bad = ct.copy()
    bad[:, 3] ^= 0xFF
    ss_bad = np.asarray(dec(sk, bad))
    assert not (ss_bad == ss).all(axis=-1).any()


@pytest.mark.slow
@pytest.mark.parametrize("name", ["FrodoKEM-976-SHAKE", "FrodoKEM-1344-AES"])
def test_large_sets_roundtrip(name):
    """976/1344: JAX self-consistency (pyref too slow at these sizes)."""
    from quantum_resistant_p2p_tpu.kem import frodo as jfr

    p = fr.PARAMS[name]
    kg, enc, dec = jfr.get(name)
    s = RNG.integers(0, 256, size=(1, p.len_sec), dtype=np.uint8)
    se = RNG.integers(0, 256, size=(1, p.len_sec), dtype=np.uint8)
    z = RNG.integers(0, 256, size=(1, p.len_sec), dtype=np.uint8)
    mu = RNG.integers(0, 256, size=(1, p.len_sec), dtype=np.uint8)
    pk, sk = kg(s, se, z)
    assert pk.shape[-1] == p.pk_len and sk.shape[-1] == p.sk_len
    ct, ss = enc(np.asarray(pk), mu)
    assert ct.shape[-1] == p.ct_len
    assert (np.asarray(dec(np.asarray(sk), np.asarray(ct))) == np.asarray(ss)).all()


def test_provider_cross_backend():
    from quantum_resistant_p2p_tpu.provider import get_kem

    tpu = get_kem("FrodoKEM-640-AES", backend="tpu")
    cpu = get_kem("FrodoKEM-640-AES", backend="cpu")
    pk, sk = tpu.generate_keypair()
    ct, ss = cpu.encapsulate(pk)
    assert tpu.decapsulate(sk, ct) == ss


def test_bitsliced_aes_matches_gather_and_openssl():
    """The table-free bitsliced AES (core/aes_bitsliced.py) is bit-exact vs
    both the gather implementation and the OpenSSL oracle, including a
    non-multiple-of-32 block count (packing pad path)."""
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes

    from quantum_resistant_p2p_tpu.core import aes, aes_bitsliced

    rng = np.random.default_rng(7)
    keys = rng.integers(0, 256, (3, 16), dtype=np.uint8)
    blocks = rng.integers(0, 256, (3, 45, 16), dtype=np.uint8)
    rk = aes.key_schedule(keys)
    ref = np.asarray(aes.encrypt_blocks(rk, blocks))
    got = np.asarray(aes_bitsliced.encrypt_blocks(rk, blocks))
    assert np.array_equal(got, ref)
    enc = Cipher(algorithms.AES(bytes(keys[1])), modes.ECB()).encryptor()
    assert enc.update(bytes(blocks[1].reshape(-1))) == bytes(got[1].reshape(-1))


def test_sbox_circuits_exhaustive():
    """Both bitsliced S-box circuits (Boyar-Peralta default + the derived
    field circuit) equal the table S-box on all 256 byte values."""
    import jax.numpy as jnp

    from quantum_resistant_p2p_tpu.core import aes_bitsliced as bs

    vals = np.arange(256, dtype=np.uint64)
    # pack 256 values as bit planes, 4 uint32 words x 2 lanes shape (8,)
    planes = []
    for i in range(8):
        bits = ((vals >> i) & 1).astype(np.uint32)
        words = (bits.reshape(8, 32) << np.arange(32, dtype=np.uint32)).sum(
            axis=1, dtype=np.uint32
        )
        planes.append(jnp.asarray(words))
    for circuit in (bs._sbox_planes_bp, bs._sbox_planes_derived):
        out = [np.asarray(p) for p in circuit(planes)]
        res = np.zeros(256, dtype=np.uint8)
        for i in range(8):
            bits = (out[i][:, None] >> np.arange(32, dtype=np.uint32)) & 1
            res |= (bits.reshape(-1).astype(np.uint8) << i)
        assert np.array_equal(res, bs._SBOX), circuit.__name__
