"""qrproto self-tests: protocol-model extraction mechanics (send sites,
splat fields, verb constants, dispatch compares, shared pre-dispatch
reads, registry handler-table resolution, negotiated features), per-rule
trigger/clean/suppressed fixtures, the three seeded-mutation pins against
the live ``app/messaging.py`` (deleting one handler registration, one
send-site kwarg, or one negotiation guard each flips its rule), the
docs/protocol.md drift pin, SARIF schema validation, and the live-tree
clean + perf gates (the fourth CI ratchet).

Pure AST on the qrlint engine: no jax import anywhere, so this file runs
on minimal no-jax images.
"""

from __future__ import annotations

import json
import re
import textwrap
import time
from functools import lru_cache
from pathlib import Path

from tools.analysis.engine import Engine, FileContext, Project
from tools.analysis.flow.callgraph import build_callgraph
from tools.analysis.flow.sarif import check_sarif
from tools.analysis.proto import proto_rules
from tools.analysis.proto.model import (ProtocolModel, extract_model,
                                        render_model_markdown)
from tools.analysis.proto.packs import ProtoAnalysis
from tools.analysis.proto.run import main as qrproto_main

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "quantum_resistant_p2p_tpu"
MESSAGING = PACKAGE / "app" / "messaging.py"


def lint(source: str, path: str = "fixture.py"):
    findings, suppressed = Engine(proto_rules()).lint_source(
        textwrap.dedent(source), path)
    return findings, suppressed


def rule_ids(source: str, path: str = "fixture.py") -> list[str]:
    return sorted(f.rule for f in lint(source, path)[0])


def model_of(source: str, path: str = "fixture.py") -> ProtocolModel:
    src = textwrap.dedent(source)
    return extract_model(Project({path: FileContext(path, src)}))


@lru_cache(maxsize=1)
def _live_project() -> Project:
    contexts = {str(p): FileContext(str(p), p.read_text(encoding="utf-8"))
                for p in sorted(PACKAGE.rglob("*.py"))}
    return Project(contexts)


# -- extraction mechanics -----------------------------------------------------


def test_send_site_fields_splat_and_open():
    m = model_of(
        """
        class Node:
            async def hello(self, peer):
                opts = {"resume": 1}
                opts["wire"] = 2
                await self.conn.send_message(peer, "__x__", a=1, **opts)

            async def fwd(self, peer, **extra):
                await self.conn.send_message(peer, "__y__", **extra)
        """
    )
    (sx,) = m.sends_of("__x__")
    assert sx.fields == ("a",)
    assert sx.optional == ("resume", "wire")  # resolved through the dict build
    assert sx.open_fields is False
    (sy,) = m.sends_of("__y__")
    assert sy.open_fields is True  # **extra is unresolvable: field set open


def test_verb_constant_resolves_through_dict_literal():
    m = model_of(
        """
        BUSY = "__busy__"

        class Router:
            def make(self, scope):
                return {"type": BUSY, "scope": scope}
        """
    )
    assert m.verb_consts["BUSY"] == "__busy__"
    (s,) = m.sends_of("__busy__")
    assert s.fields == ("scope",)


def test_dispatch_compare_via_assigned_local_and_shared_reads():
    """Pre-dispatch reads fold into EVERY branch's field set; sibling
    dispatch branches and statements after the compare are pruned so one
    verb's fields never leak onto another's."""
    m = model_of(
        """
        class Fleet:
            async def loop(self, msg, peer):
                sender = msg.get("gateway")
                mtype = msg.get("type")
                if mtype == "__a__":
                    self.a = msg.get("x")
                elif mtype == "__b__":
                    self.b = msg.get("y")
                self.after = msg.get("z")
        """
    )
    (ha,) = m.handlers_of("__a__")
    (hb,) = m.handlers_of("__b__")
    assert set(ha.reads) == {"gateway", "type", "x"}
    assert set(hb.reads) == {"gateway", "type", "y"}  # no x, no z
    # "type" is an envelope field: excluded from the contract checks


def test_non_frame_compare_is_not_a_handler():
    m = model_of(
        """
        if __name__ == "__main__":
            print("hi")

        def check(kind):
            if kind == "__gw_stop__":
                return True
        """
    )
    assert m.handlers == []  # neither compare traces to msg["type"]


def test_registry_handler_table_resolves_through_callgraph():
    """Satellite: the qrflow callgraph emits handler:<verb> edges for the
    messaging.py tuple-table idiom, and qrproto builds HandlerSites (with
    field reads) from them."""
    src = textwrap.dedent(
        """
        class App:
            def start(self):
                for mtype, handler in (
                    ("ke_init", self._on_init),
                    ("ke_ok", self._on_ok),
                ):
                    self.node.register_message_handler(mtype, handler)
                self.node.register_handler("__stop__", self._on_stop)

            async def _on_init(self, peer, msg):
                self.x = msg.get("a")

            async def _on_ok(self, peer, msg):
                self.y = msg["b"]

            async def _on_stop(self, peer, msg):
                self.stopped = True
        """
    )
    project = Project({"fixture.py": FileContext("fixture.py", src)})
    cg = build_callgraph(project)
    labels = {e.label: e.callee.qualname for e in cg.edges
              if e.label.startswith("handler:")}
    assert labels == {"handler:ke_init": "App._on_init",
                      "handler:ke_ok": "App._on_ok",
                      "handler:__stop__": "App._on_stop"}
    m = extract_model(project)
    (hi,) = m.handlers_of("ke_init")
    assert hi.kind == "registry" and hi.reads == ("a",)
    (ho,) = m.handlers_of("ke_ok")
    assert ho.reads == ("b",)


def test_live_model_features_and_verbs():
    m = extract_model(_live_project())
    features = {f.offer_key: f for f in m.features}
    assert features["resume"].env == "QRP2P_RESUMPTION"
    assert "tik1" in features["resume"].tokens
    assert features["wire"].env == "QRP2P_BINARY_WIRE"
    assert m.feature_of("ke_resume").offer_key == "resume"
    verbs = m.verbs()
    for v in ("ke_init", "ke_response", "ke_resume", "__hello__",
              "__gw_heartbeat__", "__route__", "__route_ok__", "__busy__"):
        assert v in verbs, f"{v} missing from the extracted model"


# -- rule fixtures: trigger / clean / suppressed ------------------------------

_PING_HANDLED = """
    class Node:
        async def ping(self, peer):
            await self.conn.send_message(peer, "__ping__", n=1)

        async def on_frame(self, msg):
            if msg.get("type") == "__ping__":
                self.total += int(msg.get("n") or 0)
"""


def test_unhandled_type_trigger_clean_suppressed():
    trigger = """
        class Node:
            async def ping(self, peer):
                await self.conn.send_message(peer, "__ping__", n=1)
    """
    assert rule_ids(trigger) == ["proto-unhandled-type"]
    assert rule_ids(_PING_HANDLED) == []
    suppressed_src = """
        class Node:
            async def ping(self, peer):
                await self.conn.send_message(peer, "__ping__", n=1)  # qrproto: disable=proto-unhandled-type — fixture: receiver lives out of tree
    """
    findings, suppressed = lint(suppressed_src)
    assert findings == []
    assert [s.rule for s in suppressed] == ["proto-unhandled-type"]


def test_dead_handler_trigger_and_clean():
    trigger = """
        class Node:
            async def on_frame(self, msg):
                if msg.get("type") == "__ping__":
                    self.last = msg.get("n")
    """
    assert rule_ids(trigger) == ["proto-dead-handler"]
    assert rule_ids(_PING_HANDLED) == []


def test_field_mismatch_read_direction():
    trigger = """
        class Node:
            async def ping(self, peer):
                await self.conn.send_message(peer, "__ping__", n=1)

            async def on_frame(self, msg):
                if msg.get("type") == "__ping__":
                    self.total = msg.get("n") + msg.get("seq")
    """
    (f,) = lint(trigger)[0]
    assert f.rule == "proto-field-mismatch" and "'seq'" in f.message


def test_field_mismatch_sent_direction_and_wildcard():
    trigger = """
        class Node:
            async def ping(self, peer):
                await self.conn.send_message(peer, "__ping__", n=1, extra=2)

            async def on_frame(self, msg):
                if msg.get("type") == "__ping__":
                    self.total = msg.get("n")
    """
    (f,) = lint(trigger)[0]
    assert f.rule == "proto-field-mismatch" and "'extra'" in f.message
    wildcard = """
        class Node:
            async def ping(self, peer):
                await self.conn.send_message(peer, "__ping__", n=1, extra=2)

            async def on_frame(self, msg):
                if msg.get("type") == "__ping__":
                    self.snapshot = dict(msg)
    """
    assert rule_ids(wildcard) == []  # whole-dict use: field set unknowable


def test_open_fields_send_suppresses_read_direction():
    src = """
        class Node:
            async def fwd(self, peer, **extra):
                await self.conn.send_message(peer, "__ping__", **extra)

            async def on_frame(self, msg):
                if msg.get("type") == "__ping__":
                    self.total = msg.get("n")
    """
    assert rule_ids(src) == []  # **extra may carry n: benefit of the doubt


def test_unnegotiated_send_trigger_and_guarded_clean():
    trigger = """
        class Node:
            def start(self):
                self.node.register_message_handler("ke_resume", self._on_resume)

            async def _on_resume(self, peer, msg):
                self.last_ticket = msg.get("ticket")

            async def resume(self, peer, ticket):
                await self.conn.send_message(peer, "ke_resume", ticket=ticket)
    """
    assert rule_ids(trigger) == ["proto-unnegotiated-send"]
    clean = """
        class Node:
            def start(self):
                self.node.register_message_handler("ke_resume", self._on_resume)

            async def _on_resume(self, peer, msg):
                self.last_ticket = msg.get("ticket")

            async def resume(self, peer, ticket):
                if not self._resumption_negotiated(peer):
                    return
                await self.conn.send_message(peer, "ke_resume", ticket=ticket)
    """
    assert rule_ids(clean) == []


def test_guard_does_not_propagate_through_async_send_path():
    """A negotiation check inside one async callee (e.g. the app send
    path) guards THAT function's frames, not every caller's — otherwise
    the rule is vacuous on the live tree."""
    src = """
        class Node:
            def start(self):
                self.node.register_message_handler("ke_resume", self._on_resume)

            async def _on_resume(self, peer, msg):
                self.last_ticket = msg.get("ticket")

            async def deliver(self, peer):
                if self._resumption_negotiated(peer):
                    self.n += 1

            async def resume(self, peer, ticket):
                await self.deliver(peer)
                await self.conn.send_message(peer, "ke_resume", ticket=ticket)
    """
    assert rule_ids(src) == ["proto-unnegotiated-send"]


def test_reject_dead_end_trigger_clean_suppressed():
    trigger = """
        class Client:
            async def ask(self, peer):
                await self.conn.send_message(peer, "__busy__", scope="fleet")

            async def on_frame(self, msg):
                if msg.get("type") == "__busy__":
                    self.note = msg.get("scope")
    """
    assert rule_ids(trigger) == ["proto-reject-dead-end"]
    clean = """
        class Client:
            async def ask(self, peer):
                await self.conn.send_message(peer, "__busy__", scope="fleet")

            async def on_frame(self, msg):
                if msg.get("type") == "__busy__":
                    self.note = msg.get("scope")
                    self.busy_backoffs += 1
    """
    assert rule_ids(clean) == []
    suppressed_src = """
        class Client:
            async def ask(self, peer):
                await self.conn.send_message(peer, "__busy__", scope="fleet")

            async def on_frame(self, msg):
                if msg.get("type") == "__busy__":  # qrproto: disable=proto-reject-dead-end — fixture: the caller's select loop re-dials
                    self.note = msg.get("scope")
    """
    findings, suppressed = lint(suppressed_src)
    assert findings == []
    assert [s.rule for s in suppressed] == ["proto-reject-dead-end"]


def test_state_unreachable_require_trigger_and_clean():
    trigger = """
        class Node:
            async def ping(self, peer):
                await self.conn.send_message(peer, "__ping__", n=1)

            async def on_frame(self, peer, msg):
                if msg.get("type") == "__ping__":
                    if self.ke_state[peer] == KeyExchangeState.CONFIRMING:
                        self.n = msg.get("n")
    """
    (f,) = lint(trigger)[0]
    assert f.rule == "proto-state-unreachable"
    assert "KeyExchangeState.CONFIRMING" in f.message
    clean = trigger + """
            def arm(self, peer):
                self.ke_state[peer] = KeyExchangeState.CONFIRMING
    """
    assert rule_ids(clean) == []


def test_state_unreachable_reply_graph():
    """A verb sent only from inside handlers of verbs nothing triggers is
    dead protocol state."""
    src = """
        class Node:
            async def on_a(self, msg, peer):
                if msg.get("type") == "__a_ok__":
                    await self.conn.send_message(peer, "__b__")

            async def on_b(self, msg):
                if msg.get("type") == "__b__":
                    self.done = True
    """
    ids = rule_ids(src)
    assert "proto-state-unreachable" in ids  # __b__ only reachable via __a_ok__
    assert "proto-dead-handler" in ids       # nothing ever sends __a_ok__


def test_unjustified_suppression_fires():
    src = """
        class Node:
            async def ping(self, peer):
                await self.conn.send_message(peer, "__ping__", n=1)  # qrproto: disable=proto-unhandled-type
    """
    ids = rule_ids(src)
    assert ids == ["proto-unjustified-suppression"]


# -- seeded mutation pins (live app/messaging.py) -----------------------------


def _lint_messaging(source: str) -> list:
    findings, _ = Engine(proto_rules()).lint_source(
        source, str(MESSAGING.relative_to(REPO_ROOT)))
    return findings


def test_messaging_is_contract_clean():
    assert _lint_messaging(MESSAGING.read_text(encoding="utf-8")) == []


def test_mutation_deleted_handler_registration_flips_unhandled_type():
    src = MESSAGING.read_text(encoding="utf-8")
    mutated = src.replace('("ke_rehome", self._handle_ke_rehome),\n', "")
    assert mutated != src, "handler-table entry moved: update the pin"
    ids = {f.rule for f in _lint_messaging(mutated)}
    assert "proto-unhandled-type" in ids


def test_mutation_deleted_send_kwarg_flips_field_mismatch():
    src = MESSAGING.read_text(encoding="utf-8")
    mutated = re.sub(r'"ke_rehome",\s*\n\s*reason=reason', '"ke_rehome"', src)
    assert mutated != src, "ke_rehome send site moved: update the pin"
    findings = _lint_messaging(mutated)
    assert any(f.rule == "proto-field-mismatch" and "'reason'" in f.message
               for f in findings)


def test_mutation_deleted_negotiation_guard_flips_unnegotiated_send():
    src = MESSAGING.read_text(encoding="utf-8")
    guard = ('if not self._resumption_negotiated(peer_id):\n'
             '            return "resumption_disabled"\n'
             '        if self.draining:')
    mutated = src.replace(guard, "if self.draining:", 1)
    assert mutated != src, "_resume_respond guard moved: update the pin"
    findings = _lint_messaging(mutated)
    assert any(f.rule == "proto-unnegotiated-send"
               and "'ke_resume_ok'" in f.message for f in findings)


# -- docs drift pin -----------------------------------------------------------


def test_protocol_doc_verb_table_in_sync():
    """docs/protocol.md embeds `qrproto --dump-model` between markers;
    regenerate the block after any protocol change."""
    doc = (REPO_ROOT / "docs" / "protocol.md").read_text(encoding="utf-8")
    begin, end = "<!-- qrproto:model:begin -->", "<!-- qrproto:model:end -->"
    assert begin in doc and end in doc
    block = doc.split(begin, 1)[1].split(end, 1)[0].strip("\n")
    rendered = render_model_markdown(extract_model(_live_project())).strip("\n")
    assert block == rendered, (
        "docs/protocol.md verb table drifted — regenerate with\n"
        "  python -m tools.analysis.proto.run quantum_resistant_p2p_tpu "
        "--dump-model")


# -- CLI / output formats -----------------------------------------------------


def test_list_rules(capsys):
    assert qrproto_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("proto-unhandled-type", "proto-dead-handler",
                "proto-field-mismatch", "proto-unnegotiated-send",
                "proto-reject-dead-end", "proto-state-unreachable",
                "proto-unjustified-suppression"):
        assert rid in out


def test_cli_select_json_sarif_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(
        """
        class Node:
            async def ping(self, peer):
                await self.conn.send_message(peer, "__ping__", n=1)
        """
    ))
    assert qrproto_main([str(bad)]) == 1
    capsys.readouterr()
    assert qrproto_main([str(bad), "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    (finding,) = payload["findings"]
    assert finding["rule"] == "proto-unhandled-type"
    assert qrproto_main([str(bad), "--select", "proto-dead-handler"]) == 0
    assert qrproto_main([str(bad), "--select", "no-such-rule"]) == 2
    capsys.readouterr()
    assert qrproto_main([str(bad), "--format", "sarif"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert check_sarif(doc) == []
    assert doc["runs"][0]["tool"]["driver"]["name"] == "qrproto"


def test_dump_model_markdown_and_json(capsys):
    assert qrproto_main([str(PACKAGE), "--dump-model"]) == 0
    out = capsys.readouterr().out
    assert "| Verb | Flow | Fields | Feature | Handlers |" in out
    assert "`ke_resume`" in out and "`QRP2P_RESUMPTION`" in out
    assert qrproto_main([str(PACKAGE), "--dump-model", "--format", "json"]) == 0
    model = json.loads(capsys.readouterr().out)
    assert "ke_resume" in model["verbs"]
    (resume,) = [f for f in model["features"] if f["offer_key"] == "resume"]
    assert resume["env"] == "QRP2P_RESUMPTION"


# -- the CI ratchet -----------------------------------------------------------


def test_live_codebase_is_contract_clean(capsys):
    """The whole package passes qrproto: every sent verb is handled, every
    field contract holds.  New violations fail here AND in CI."""
    rc = qrproto_main([str(PACKAGE)])
    out = capsys.readouterr().out
    assert rc == 0, f"qrproto found new violations:\n{out}"


def test_live_run_is_fast_enough_for_ci():
    """Model extraction + contract checks are one pass over the qrflow
    call graph: the whole package must verify in seconds (<30s gate)."""
    contexts = {str(p): FileContext(str(p), p.read_text(encoding="utf-8"))
                for p in sorted(PACKAGE.rglob("*.py"))}
    t0 = time.perf_counter()
    analysis = ProtoAnalysis(Project(contexts))
    dt = time.perf_counter() - t0
    assert dt < 30.0, f"protocol verification took {dt:.1f}s"
    assert analysis.model.sends and analysis.model.handlers
