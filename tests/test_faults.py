"""Fault injection — what SURVEY.md §5 notes the reference lacks entirely.

Network faults (dropped handshake messages, mid-session disconnect) and
crypto faults (corrupted encapsulation) injected into the live two-node
stack; the protocol must fail closed: typed errors / timeouts, no plaintext
delivery, state reset for retry.
"""

import asyncio

import pytest

from quantum_resistant_p2p_tpu.app import messaging as messaging_mod
from quantum_resistant_p2p_tpu.app.messaging import KeyExchangeState, SecureMessaging
from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


@pytest.fixture(autouse=True)
def fast_timeout(monkeypatch):
    monkeypatch.setattr(messaging_mod, "KEY_EXCHANGE_TIMEOUT", 1.5)


async def _pair():
    a_node = P2PNode(node_id="alice", host="127.0.0.1", port=0)
    b_node = P2PNode(node_id="bob", host="127.0.0.1", port=0)
    await a_node.start()
    await b_node.start()
    a = SecureMessaging(a_node)
    b = SecureMessaging(b_node)
    assert await a_node.connect_to_peer("127.0.0.1", b_node.port) == "bob"
    for _ in range(100):
        if b_node.is_connected("alice"):
            break
        await asyncio.sleep(0.01)
    return a, b


def test_dropped_response_times_out_then_retry_succeeds(run):
    async def main():
        a, b = await _pair()
        # drop bob's ke_response exactly once
        orig = b.node.send_message
        dropped = {"n": 0}

        async def flaky(peer_id, msg_type, **kw):
            if msg_type == "ke_response" and dropped["n"] == 0:
                dropped["n"] += 1
                return True  # swallowed by the network
            return await orig(peer_id, msg_type, **kw)

        b.node.send_message = flaky
        ok = await a.initiate_key_exchange("bob")
        assert not ok
        assert a.ke_state["bob"] is KeyExchangeState.NONE  # reset for retry
        ok2 = await a.initiate_key_exchange("bob")
        assert ok2 and a.verify_key_exchange_state("bob")
        await a.node.stop()
        await b.node.stop()

    run(main())


def test_disconnect_mid_session_fails_closed(run):
    async def main():
        a, b = await _pair()
        assert await a.initiate_key_exchange("bob")
        await b.node.stop()
        for _ in range(100):
            if not a.node.is_connected("bob"):
                break
            await asyncio.sleep(0.02)
        assert not a.verify_key_exchange_state("bob")  # liveness check fails
        sent = await a.send_message("bob", b"into the void")
        assert sent is None
        await a.node.stop()

    run(main())


def test_corrupted_encapsulation_never_delivers_plaintext(run):
    """KAT-failure injection: the responder's encapsulation is corrupted in
    flight; both sides end with different keys and no message decrypts."""

    async def main():
        a, b = await _pair()
        orig = b.node.send_message

        async def corrupt(peer_id, msg_type, **kw):
            if msg_type == "ke_response":
                ct = bytearray(bytes.fromhex(kw["ke_data"]["ciphertext"]))
                ct[0] ^= 0xFF
                kw["ke_data"]["ciphertext"] = bytes(ct).hex()
                # signature now stale -> alice must reject it
            return await orig(peer_id, msg_type, **kw)

        b.node.send_message = corrupt
        ok = await a.initiate_key_exchange("bob")
        assert not ok  # invalid signature on the tampered response
        assert "bob" not in a.shared_keys or a.shared_keys.get("bob") != b.shared_keys.get("alice")
        await a.node.stop()
        await b.node.stop()

    run(main())


def test_replayed_init_rejected(run):
    """Replay window: a ke_init with an old timestamp is rejected typed."""

    async def main():
        a, b = await _pair()
        rejections = []

        async def on_reject(peer_id, msg):
            rejections.append(msg.get("reason"))

        a.node.register_message_handler("ke_reject", on_reject)
        import json
        import time
        import uuid

        pk, _ = a.kem.generate_keypair()
        stale = {
            "message_id": str(uuid.uuid4()),
            "kem": a.kem.name,
            "aead": a.symmetric.name,
            "public_key": pk.hex(),
            "sender": "alice",
            "recipient": "bob",
            "timestamp": time.time() - 3600,
        }
        sig = a.signature.sign(
            a._sig_keypair[1],
            json.dumps(stale, sort_keys=True, separators=(",", ":")).encode(),
        )
        await a.node.send_message(
            "bob", "ke_init", ke_data=stale, sig=sig,
            sig_algo=a.signature.name, sig_pk=a._sig_keypair[0],
        )
        for _ in range(100):
            if rejections:
                break
            await asyncio.sleep(0.02)
        assert rejections == ["timestamp_invalid"]
        await a.node.stop()
        await b.node.stop()

    run(main())
