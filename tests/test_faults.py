"""Deterministic fault injection — the self-healing acceptance suite.

Faults are injected through the explicit hook points (faults/ — net.send,
device.dispatch, scalar.op, warmup) from seeded :class:`FaultPlan`\\ s, never
by monkeypatching: every scenario is reproducible from its seed.

Covered here:
* protocol fail-closed under net faults (drop / corrupt / replay),
* bounded handshake retry healing one dropped/corrupted datagram,
* corrupted ciphertext mid-session -> automatic re-key, never plaintext,
* mid-session disconnect -> reconnect -> automatic re-handshake -> queued
  outbound messages delivered,
* breaker opens then heals via the half-open canary probe
  (device_served_fraction recovers to 1.0 over the post-heal window),
* the seeded chaos acceptance run: >=3 device faults + >=2 net faults over
  32 handshakes, 0 failures, final device_served_fraction >= 0.9.

The suite runs on minimal images (no ``cryptography`` wheel): the protocol
engine's HKDF is stdlib (pinned to the RFC 5869 vector below) and the AEAD
is a toy stdlib encrypt-then-MAC injected via the provider seam.
"""

import asyncio
import hashlib
import hmac
import os

import pytest

from quantum_resistant_p2p_tpu.app import messaging as messaging_mod
from quantum_resistant_p2p_tpu.app.messaging import (KeyExchangeState,
                                                     SecureMessaging,
                                                     _hkdf_sha256)
from quantum_resistant_p2p_tpu.faults import (FaultInjected, FaultPlan,
                                              FaultRule)
from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode
from quantum_resistant_p2p_tpu.provider import get_kem, get_signature
from quantum_resistant_p2p_tpu.provider.base import (KeyExchangeAlgorithm,
                                                     SignatureAlgorithm,
                                                     SymmetricAlgorithm)
from quantum_resistant_p2p_tpu.provider.registry import (register_kem,
                                                         register_signature)

# -- stdlib toy algorithms (fast, interoperable across "backends") ------------
#
# The chaos tests exercise the REAL OpQueue/Breaker/SecureMessaging stack
# over real TCP; the crypto inside is a deterministic hash-based toy so 32
# faulted handshakes cost milliseconds, and the "tpu"/"cpu" twins share the
# math so fallback results interoperate exactly like the production pairs.


def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    out = b""
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(key + nonce + ctr.to_bytes(8, "big")).digest()
        ctr += 1
    return out[:n]


class ToyAEAD(SymmetricAlgorithm):
    """Stdlib encrypt-then-MAC AEAD honouring the SymmetricAlgorithm
    contract (ValueError on auth failure) — lets the protocol suite run on
    images without the OpenSSL wheel."""

    name = "TOY-AEAD"
    display_name = "TOY-AEAD"
    key_size = 32
    nonce_size = 16

    def encrypt(self, key, plaintext, associated_data=None):
        nonce = os.urandom(self.nonce_size)
        ct = bytes(a ^ b for a, b in
                   zip(plaintext, _keystream(key, nonce, len(plaintext))))
        tag = hmac.new(key, nonce + ct + (associated_data or b""),
                       hashlib.sha256).digest()
        return nonce + ct + tag

    def decrypt(self, key, data, associated_data=None):
        if len(data) < self.nonce_size + 32:
            raise ValueError("ciphertext too short")
        nonce, ct, tag = (data[: self.nonce_size], data[self.nonce_size:-32],
                          data[-32:])
        want = hmac.new(key, nonce + ct + (associated_data or b""),
                        hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise ValueError("authentication failed")
        return bytes(a ^ b for a, b in zip(ct, _keystream(key, nonce, len(ct))))


class ToyKEM(KeyExchangeAlgorithm):
    name = "TOY-KEM"
    display_name = "TOY-KEM"
    public_key_len = 32
    secret_key_len = 32
    ciphertext_len = 32
    shared_secret_len = 32

    def __init__(self, backend="cpu"):
        self.backend = backend

    def generate_keypair(self):
        sk = os.urandom(32)
        return hashlib.sha256(b"pk" + sk).digest(), sk

    def encapsulate(self, public_key):
        ct = os.urandom(32)
        return ct, hashlib.sha256(public_key + ct).digest()

    def decapsulate(self, secret_key, ciphertext):
        pk = hashlib.sha256(b"pk" + secret_key).digest()
        return hashlib.sha256(pk + ciphertext).digest()


class ToySig(SignatureAlgorithm):
    name = "TOY-SIG"
    display_name = "TOY-SIG"
    public_key_len = 32
    secret_key_len = 32
    signature_len = 32

    def __init__(self, backend="cpu"):
        self.backend = backend

    def generate_keypair(self):
        sk = os.urandom(32)
        return hashlib.sha256(b"pk" + sk).digest(), sk

    def sign(self, secret_key, message):
        pk = hashlib.sha256(b"pk" + secret_key).digest()
        return hashlib.sha256(b"sig" + pk + message).digest()

    def verify(self, public_key, message, signature):
        return hmac.compare_digest(
            signature, hashlib.sha256(b"sig" + public_key + message).digest()
        )


# registered so SecureMessaging's cpu-fallback lookup finds the twins
register_kem("TOY-KEM", lambda backend, devices=0: ToyKEM(backend),
             ("cpu", "tpu"))
register_signature("TOY-SIG", lambda backend, devices=0: ToySig(backend),
                   ("cpu", "tpu"))


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


@pytest.fixture(autouse=True)
def fast_timeout(monkeypatch):
    monkeypatch.setattr(messaging_mod, "KEY_EXCHANGE_TIMEOUT", 1.5)
    monkeypatch.setattr(messaging_mod, "KE_RETRY_BACKOFF_S", 0.05)
    monkeypatch.setattr(messaging_mod, "HEAL_BACKOFF_S", 0.05)


async def _pair(**kwargs):
    a_node = P2PNode(node_id="alice", host="127.0.0.1", port=0)
    b_node = P2PNode(node_id="bob", host="127.0.0.1", port=0)
    await a_node.start()
    await b_node.start()
    a = SecureMessaging(a_node, symmetric=ToyAEAD(), **kwargs)
    b = SecureMessaging(b_node, symmetric=ToyAEAD(), **kwargs)
    assert await a_node.connect_to_peer("127.0.0.1", b_node.port) == "bob"
    for _ in range(100):
        if b_node.is_connected("alice"):
            break
        await asyncio.sleep(0.01)
    return a, b


# -- the stdlib HKDF is pinned to RFC 5869 ------------------------------------


def test_hkdf_sha256_rfc5869_vector():
    okm = _hkdf_sha256(
        bytes.fromhex("0b" * 22),
        salt=bytes.fromhex("000102030405060708090a0b0c"),
        info=bytes.fromhex("f0f1f2f3f4f5f6f7f8f9"),
        length=42,
    )
    assert okm.hex() == (
        "3cb25f25faacd57a90434f64d0362f2a2d2d0a90cf1a5a4c5db02d56ecc4c5bf"
        "34007208d5b887185865"
    )


# -- fault-plan engine --------------------------------------------------------


def test_fault_plan_is_deterministic_from_seed():
    """Same seed + same event stream -> identical injections, byte for
    byte (the corruption positions come from the seeded per-rule RNG)."""

    def drive(seed):
        plan = FaultPlan(seed, [
            FaultRule("net.send", "corrupt", match={"msg_type": "m"}, nth=2),
            FaultRule("device.dispatch", "raise", nth=2, times=2),
        ])
        outs = []
        with plan.activate():
            for i in range(4):
                outs.append(plan.net_send("a", "b", "m", {"ct": bytes(8)}))
            raised = []
            for i in range(4):
                try:
                    plan.device_dispatch("q.enc", 1)
                    raised.append(False)
                except FaultInjected:
                    raised.append(True)
        return outs, raised, plan.injected

    o1, r1, i1 = drive(99)
    o2, r2, i2 = drive(99)
    o3, _, _ = drive(100)
    assert o1 == o2 and r1 == r2 and i1 == i2
    assert r1 == [False, True, True, False]
    corrupted = [p for act, p in o1 if act == "send" and p["ct"] != bytes(8)]
    assert len(corrupted) == 1  # exactly the nth=2 send, deterministically
    assert o1 != o3  # a different seed corrupts differently


def test_scalar_fault_hook_reaches_real_providers():
    """provider/base.py instruments every concrete provider's scalar ops:
    an installed plan can fail the Nth call without monkeypatching."""
    kem = get_kem("ML-KEM-768", "cpu")
    plan = FaultPlan(1, [FaultRule("scalar.op", "raise",
                                   match={"algo": "ML-KEM-768",
                                          "op": "encapsulate"}, nth=1)])
    pk, sk = kem.generate_keypair()
    with plan.activate():
        with pytest.raises(FaultInjected):
            kem.encapsulate(pk)
        ct, ss = kem.encapsulate(pk)  # nth=1 consumed: next call is clean
    assert kem.decapsulate(sk, ct) == ss
    assert [e["op"] for e in plan.injected] == ["encapsulate"]


# -- protocol resilience under net faults -------------------------------------


def test_dropped_response_healed_by_bounded_retry(run):
    """One dropped ke_response datagram no longer needs a caller-driven
    retry: the initiator times out, backs off, and the bounded retry
    completes the exchange."""

    async def main():
        a, b = await _pair()
        plan = FaultPlan(7, [
            FaultRule("net.send", "drop", match={"msg_type": "ke_response"},
                      nth=1),
        ])
        with plan.activate():
            ok = await a.initiate_key_exchange("bob")
        assert ok and a.verify_key_exchange_state("bob")
        assert [e["action"] for e in plan.injected] == ["drop"]
        await a.node.stop()
        await b.node.stop()

    run(main())


def test_dropped_response_fails_closed_without_retry(run):
    """retries=0 keeps the old contract: typed timeout, state reset for a
    later caller-driven attempt."""

    async def main():
        a, b = await _pair()
        plan = FaultPlan(7, [
            FaultRule("net.send", "drop", match={"msg_type": "ke_response"},
                      nth=1),
        ])
        with plan.activate():
            ok = await a.initiate_key_exchange("bob", retries=0)
        assert not ok
        assert a.ke_state["bob"] is KeyExchangeState.NONE  # reset for retry
        ok2 = await a.initiate_key_exchange("bob", retries=0)
        assert ok2 and a.verify_key_exchange_state("bob")
        await a.node.stop()
        await b.node.stop()

    run(main())


def test_corrupted_response_never_delivers_plaintext_then_retry_heals(run):
    """A ke_response corrupted in flight fails signature verification
    (fail closed, no key adopted); the bounded retry treats it as
    transient and the second, clean attempt succeeds."""

    async def main():
        a, b = await _pair()
        plan = FaultPlan(11, [
            FaultRule("net.send", "corrupt", match={"msg_type": "ke_response"},
                      nth=1, corrupt_field="ciphertext"),
        ])
        with plan.activate():
            ok0 = await a.initiate_key_exchange("bob", retries=0)
            assert not ok0  # invalid signature on the tampered response
            assert "bob" not in a.shared_keys or (
                a.shared_keys.get("bob") != b.shared_keys.get("alice"))
            ok = await a.initiate_key_exchange("bob")  # retry path
        assert ok and a.verify_key_exchange_state("bob")
        assert a.shared_keys["bob"] == b.shared_keys["alice"]
        assert [e["action"] for e in plan.injected] == ["corrupt"]
        await a.node.stop()
        await b.node.stop()

    run(main())


def test_replayed_init_rejected(run):
    """Replay window: a ke_init with an old timestamp is rejected typed."""

    async def main():
        a, b = await _pair()
        rejections = []

        async def on_reject(peer_id, msg):
            rejections.append(msg.get("reason"))

        a.node.register_message_handler("ke_reject", on_reject)
        import json
        import time
        import uuid

        pk, _ = a.kem.generate_keypair()
        stale = {
            "message_id": str(uuid.uuid4()),
            "kem": a.kem.name,
            "aead": a.symmetric.name,
            "public_key": pk.hex(),
            "sender": "alice",
            "recipient": "bob",
            "timestamp": time.time() - 3600,
        }
        sig = a.signature.sign(
            a._sig_keypair[1],
            json.dumps(stale, sort_keys=True, separators=(",", ":")).encode(),
        )
        await a.node.send_message(
            "bob", "ke_init", ke_data=stale, sig=sig,
            sig_algo=a.signature.name, sig_pk=a._sig_keypair[0],
        )
        for _ in range(100):
            if rejections:
                break
            await asyncio.sleep(0.02)
        assert rejections == ["timestamp_invalid"]
        await a.node.stop()
        await b.node.stop()

    run(main())


def test_corrupted_ciphertext_mid_session_triggers_rekey_not_plaintext(run):
    """A corrupted secure_message fails AEAD authentication; the receiver
    drops the (possibly desynchronised) session key and re-keys
    automatically.  The corrupted content is never delivered; the next send
    arrives under the fresh key."""

    async def main():
        a, b = await _pair()
        got = []
        b.register_message_listener(
            lambda peer, m: None if m.is_system else got.append(m.content))
        assert await a.initiate_key_exchange("bob")
        old_key = b.shared_keys["alice"]
        plan = FaultPlan(23, [
            FaultRule("net.send", "corrupt",
                      match={"msg_type": "secure_message"}, nth=1,
                      corrupt_field="ct"),
        ])
        with plan.activate():
            sent = await a.send_message("bob", b"poisoned in flight")
            assert sent is not None  # sender cannot see the tampering
            # bob: AEAD failure -> rekey handshake -> fresh keys both sides
            for _ in range(200):
                if (b.shared_keys.get("alice") not in (None, old_key)
                        and b.verify_key_exchange_state("alice")):
                    break
                await asyncio.sleep(0.02)
        assert got == []  # tampered content never surfaced
        assert b.shared_keys["alice"] != old_key
        assert b.shared_keys["alice"] == a.shared_keys["bob"]
        sent2 = await a.send_message("bob", b"after rekey")
        assert sent2 is not None
        for _ in range(100):
            if got:
                break
            await asyncio.sleep(0.02)
        assert got == [b"after rekey"]
        assert [e["action"] for e in plan.injected] == ["corrupt"]
        await a.node.stop()
        await b.node.stop()

    run(main())


# -- session healing (disconnect -> reconnect -> re-handshake -> flush) -------


def test_disconnect_fails_closed_with_healing_disabled(run):
    """auto_heal=False keeps the original contract: a dead peer stays dead,
    liveness checks fail, nothing is queued or sent."""

    async def main():
        a, b = await _pair(auto_heal=False)
        assert await a.initiate_key_exchange("bob")
        await b.node.stop()
        for _ in range(100):
            if not a.node.is_connected("bob"):
                break
            await asyncio.sleep(0.02)
        assert not a.verify_key_exchange_state("bob")  # liveness check fails
        sent = await a.send_message("bob", b"into the void")
        assert sent is None
        await a.node.stop()

    run(main())


def test_disconnect_reconnect_rehandshake_delivers_queued_messages(run):
    """A mid-session transport drop heals: the dialing side reconnects with
    backoff, re-handshakes automatically, and outbound messages queued
    during the outage arrive (encrypted under the POST-heal key)."""

    async def main():
        a, b = await _pair()
        got = []
        b.register_message_listener(
            lambda peer, m: None if m.is_system else got.append(m.content))
        assert await a.initiate_key_exchange("bob")
        old_key = a.shared_keys["bob"]
        # sever the TCP session without stopping either node (a network
        # blip, not an intentional disconnect)
        b.node._peers["alice"].writer.close()
        for _ in range(200):
            if not a.node.is_connected("bob"):
                break
            await asyncio.sleep(0.01)
        # queued while the heal task redials
        q1 = await a.send_message("bob", b"queued during outage 1")
        q2 = await a.send_message("bob", b"queued during outage 2")
        assert q1 is not None and q2 is not None
        for _ in range(400):
            if len(got) >= 2:
                break
            await asyncio.sleep(0.02)
        assert got == [b"queued during outage 1", b"queued during outage 2"]
        assert a.verify_key_exchange_state("bob")
        assert a.shared_keys["bob"] != old_key  # fresh key after the heal
        assert a.shared_keys["bob"] == b.shared_keys["alice"]
        await a.node.stop()
        await b.node.stop()

    run(main())


# -- breaker heal through the batched stack (fault-plan driven) ---------------


def test_breaker_opens_then_heals_via_half_open_probe_under_plan():
    """Injected device faults open the breaker; after the cool-off a real
    queued flush probes the device, closes the breaker, and the
    device_served_fraction over the post-heal window recovers to 1.0."""
    from quantum_resistant_p2p_tpu.provider.batched import BatchedKEM, Breaker

    kem = BatchedKEM(ToyKEM("tpu"), max_batch=8, max_wait_ms=1.0,
                     fallback=ToyKEM("cpu"), breaker=Breaker(cooloff_s=0.05))
    for q in (kem._kg, kem._enc, kem._dec):
        q._warm_buckets.add(1)
    plan = FaultPlan(5, [
        FaultRule("device.dispatch", "raise", match={"op": "TOY-KEM.kg"},
                  nth=2, times=2),
    ])

    async def main():
        with plan.activate():
            await kem.generate_keypair()          # device
            assert kem.breaker.state == "closed"
            await kem.generate_keypair()          # injected fault -> open
            assert kem.breaker.state == "open"
            await kem.generate_keypair()          # open -> fallback
            await asyncio.sleep(0.08)             # cool-off expires
            await kem.generate_keypair()          # probe: injected fault #2
            assert kem.breaker.state == "open"    # reopened, backoff doubled
            assert kem.breaker.cooloff_s == pytest.approx(0.1)
            await asyncio.sleep(0.12)
            pre_fb = kem._kg.stats.fallback_ops
            for _ in range(5):                    # probe heals, then device
                await kem.generate_keypair()
            assert kem.breaker.state == "closed"
            assert kem._kg.stats.fallback_ops == pre_fb  # post-heal: 1.0
        return kem._kg.stats.as_dict()

    st = asyncio.run(main())
    assert st["breaker_trips"] == 2
    assert [e["n"] for e in plan.injected] == [2, 3]
    assert 0.0 < st["device_served_fraction"] < 1.0


# -- the seeded chaos acceptance run ------------------------------------------


def test_seeded_chaos_run_zero_failures_and_device_served(run, monkeypatch):
    """ISSUE 3 acceptance: a seeded fault plan injecting >=3 device faults
    and >=2 net faults over 32 handshakes completes with 0 handshake
    failures, and the final device_served_fraction across both engines is
    >= 0.9 — the breaker demonstrably recovered to the device path."""
    monkeypatch.setenv("QRP2P_HEALTH_GATE", "0")  # deterministic run

    async def main():
        a, b = await _pair(
            kem=get_kem("TOY-KEM", "tpu"), signature=get_signature("TOY-SIG", "tpu"),
            use_batching=True, max_batch=8, max_wait_ms=1.0,
            breaker_cooloff_s=0.05,
        )
        await a.wait_ready()
        await b.wait_ready()
        plan = FaultPlan(1234, [
            # >= 3 device faults, spread so each hits a healthy breaker
            FaultRule("device.dispatch", "raise", nth=10),
            FaultRule("device.dispatch", "raise", nth=60),
            FaultRule("device.dispatch", "raise", nth=110),
            # >= 2 net faults: one dropped handshake message (healed by the
            # bounded retry), one delayed message
            FaultRule("net.send", "drop", match={"msg_type": "ke_response"},
                      nth=2),
            FaultRule("net.send", "delay", match={"msg_type": "ke_init"},
                      nth=5, delay_s=0.05),
        ])
        failures = 0
        with plan.activate():
            for i in range(32):
                for side, peer in ((a, "bob"), (b, "alice")):
                    side.shared_keys.pop(peer, None)
                    side.raw_secrets.pop(peer, None)
                    side.ke_state[peer] = KeyExchangeState.NONE
                if not await a.initiate_key_exchange("bob"):
                    failures += 1
                # give an open breaker its cool-off so the next handshake's
                # first flush probes (and heals) it
                for eng in (a, b):
                    if eng._queue_breaker.state != "closed":
                        await asyncio.sleep(eng._queue_breaker.cooloff_s + 0.02)
        ma, mb = a.metrics(), b.metrics()
        totals = [0, 0]
        for m in (ma, mb):
            for fam in ("kem_queue", "sig_queue"):
                for q in m[fam].values():
                    totals[0] += q["ops"]
                    totals[1] += q["fallback_ops"]
        fraction = (totals[0] - totals[1]) / totals[0]
        await a.node.stop()
        await b.node.stop()
        return failures, fraction, plan, ma, mb

    failures, fraction, plan, ma, mb = run(main())
    dev_faults = [e for e in plan.injected if e["scope"] == "device.dispatch"]
    net_faults = [e for e in plan.injected if e["scope"] == "net.send"]
    assert len(dev_faults) == 3 and len(net_faults) == 2
    assert failures == 0
    assert fraction >= 0.9, f"only {fraction:.1%} device-served"
    # the gauge is surfaced per engine and the breakers healed
    for m in (ma, mb):
        assert m["device_served_fraction"] is not None
        assert m["breaker_state"] == "closed"
        assert m["breaker_closes"] >= 1 or m["breaker_opens"] == 0


def test_injection_log_lists_only_applied_faults():
    """A drop short-circuits the send: a corrupt rule firing on the same
    message must not appear in plan.injected (no phantom faults in the
    documented assertion surface), while its nth counter still advances
    deterministically."""
    plan = FaultPlan(3, [
        FaultRule("net.send", "drop", match={"msg_type": "m"}, nth=1),
        FaultRule("net.send", "corrupt", match={"msg_type": "m"}, nth=1,
                  times=2),
    ])
    with plan.activate():
        act1, _ = plan.net_send("a", "b", "m", {"ct": bytes(8)})
        assert act1 == "drop"
        assert [e["action"] for e in plan.injected] == ["drop"]
        # event 2: the drop rule is spent; the corrupt rule (times=2) still
        # fires — its counter advanced on BOTH events
        act2, payload = plan.net_send("a", "b", "m", {"ct": bytes(8)})
        assert act2 == "send" and payload["ct"] != bytes(8)
    assert [e["action"] for e in plan.injected] == ["drop", "corrupt"]
