"""qrlife self-tests: lock-discipline (order-graph cycles, await/blocking
under a threading lock, release pairing), resource lifetime on exception
edges (StreamWriters, executors, tempdirs, tasks, double release), and
secret wipe-completeness (every SECRET-taint local reaches _wipe()/
zeroize() or provably transfers ownership on every exit path) — per-rule
trigger/clean/suppressed fixtures, the seeded-mutation pin against the
live ``fleet/manager.py`` (deleting ``_peer_send``'s ``finally:
writer.close()`` flips ``life-leak-on-raise``), suppression policing,
SARIF validation, and the live-tree clean + perf gates (the fifth CI
ratchet).

Pure AST on the qrlint engine: no jax import anywhere, so this file runs
on minimal no-jax images.
"""

from __future__ import annotations

import json
import textwrap
import time
from functools import lru_cache
from pathlib import Path

from tools.analysis.engine import Engine, FileContext, Project
from tools.analysis.flow.sarif import check_sarif
from tools.analysis.life import life_rules
from tools.analysis.life.packs import LifeAnalysis
from tools.analysis.life.run import main as qrlife_main

REPO_ROOT = Path(__file__).resolve().parent.parent
PACKAGE = REPO_ROOT / "quantum_resistant_p2p_tpu"
MANAGER = PACKAGE / "fleet" / "manager.py"
BUDGET = REPO_ROOT / "tools" / "analysis" / "suppression_budget.json"


def lint(source: str, path: str = "fixture.py"):
    findings, suppressed = Engine(life_rules()).lint_source(
        textwrap.dedent(source), path)
    return findings, suppressed


def rule_ids(source: str, path: str = "fixture.py") -> list[str]:
    return sorted(f.rule for f in lint(source, path)[0])


@lru_cache(maxsize=1)
def _live_contexts() -> dict:
    return {str(p): FileContext(str(p), p.read_text(encoding="utf-8"))
            for p in sorted(PACKAGE.rglob("*.py"))}


# -- lock discipline: order-graph cycles --------------------------------------


def test_lock_cycle_cross_class_trigger():
    src = """
        import threading

        class A:
            def __init__(self):
                self._la = threading.Lock()

            def one(self, b: "B"):
                with self._la:
                    with b._lb:
                        pass

        class B:
            def __init__(self):
                self._lb = threading.Lock()

            def two(self, a: A):
                with self._lb:
                    with a._la:
                        pass
    """
    (f,) = lint(src)[0]
    assert f.rule == "life-lock-cycle"
    assert "A._la" in f.message and "B._lb" in f.message


def test_lock_cycle_consistent_order_clean():
    src = """
        import threading

        class A:
            def __init__(self):
                self._la = threading.Lock()

            def one(self, b: "B"):
                with self._la:
                    with b._lb:
                        pass

        class B:
            def __init__(self):
                self._lb = threading.Lock()

            def two(self, a: A):
                with a._la:
                    with self._lb:
                        pass
    """
    assert rule_ids(src) == []  # everyone takes A._la before B._lb


def test_self_deadlock_through_helper_call():
    """Interprocedural: holding self._lock while calling a helper that
    re-acquires the SAME non-reentrant lock is a one-node cycle."""
    src = """
        import threading

        class C:
            def __init__(self):
                self._la = threading.Lock()

            def outer(self):
                with self._la:
                    self.helper()

            def helper(self):
                with self._la:
                    pass
    """
    assert rule_ids(src) == ["life-lock-cycle"]


def test_rlock_reentry_is_clean():
    src = """
        import threading

        class C:
            def __init__(self):
                self._la = threading.RLock()

            def outer(self):
                with self._la:
                    self.helper()

            def helper(self):
                with self._la:
                    pass
    """
    assert rule_ids(src) == []  # reentrant by design


# -- lock discipline: hold hygiene --------------------------------------------


def test_await_under_threading_lock_trigger():
    src = """
        import asyncio
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            async def bad(self):
                with self._lock:
                    await asyncio.sleep(0.1)
    """
    (f,) = lint(src)[0]
    assert f.rule == "life-await-under-lock"
    assert "C._lock" in f.message


def test_blocking_sleep_under_lock_in_loop_code_trigger():
    src = """
        import threading
        import time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            async def bad(self):
                with self._lock:
                    time.sleep(0.1)
    """
    assert rule_ids(src) == ["life-await-under-lock"]


def test_asyncio_lock_await_is_clean():
    src = """
        import asyncio

        class C:
            def __init__(self):
                self._lock = asyncio.Lock()

            async def ok(self):
                async with self._lock:
                    await asyncio.sleep(0.1)
    """
    assert rule_ids(src) == []  # await-shaped by design


# -- lock discipline: release pairing -----------------------------------------


def test_unreleased_lock_trigger_and_finally_clean():
    trigger = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def grab_and_go(self, x):
                self._lock.acquire()
                do_work(x)
                self._lock.release()

        def do_work(x):
            return x + 1
    """
    (f,) = lint(trigger)[0]
    assert f.rule == "life-unreleased-lock"
    assert "exception in between skips the release" in f.message
    clean = """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def careful(self, x):
                self._lock.acquire()
                try:
                    do_work(x)
                finally:
                    self._lock.release()

        def do_work(x):
            return x + 1
    """
    assert rule_ids(clean) == []


def test_acquire_wrapper_method_is_exempt():
    src = """
        import threading

        class Slot:
            def __init__(self):
                self._lock = threading.Lock()

            def acquire_slot(self):
                self._lock.acquire()
                self.held = True
    """
    assert rule_ids(src) == []  # the function IS the lock wrapper


# -- resource lifetime: leak-on-raise -----------------------------------------


def test_stream_writer_leak_trigger_and_finally_clean():
    trigger = """
        import asyncio

        async def leaky(host, port, frame):
            reader, writer = await asyncio.open_connection(host, port)
            await send(writer, frame)
            reply = await read(reader)
            writer.close()
            return reply
    """
    (f,) = lint(trigger)[0]
    assert f.rule == "life-leak-on-raise"
    assert "writer" in f.message
    clean = """
        import asyncio

        async def careful(host, port, frame):
            reader, writer = await asyncio.open_connection(host, port)
            try:
                await send(writer, frame)
                return await read(reader)
            finally:
                writer.close()
    """
    assert rule_ids(clean) == []


def test_executor_leak_trigger_and_context_manager_clean():
    trigger = """
        from concurrent.futures import ThreadPoolExecutor

        def leaky(items):
            pool = ThreadPoolExecutor(max_workers=4)
            results = [pool.submit(work, i) for i in items]
            pool.shutdown()
            return results
    """
    assert rule_ids(trigger) == ["life-leak-on-raise"]
    clean = """
        from concurrent.futures import ThreadPoolExecutor

        def careful(items):
            with ThreadPoolExecutor(max_workers=4) as pool:
                return [pool.submit(work, i) for i in items]
    """
    assert rule_ids(clean) == []


def test_tempdir_leak_trigger_and_finally_rmtree_clean():
    trigger = """
        import shutil
        import tempfile

        def leaky():
            d = tempfile.mkdtemp()
            populate(d)
            shutil.rmtree(d)
    """
    assert rule_ids(trigger) == ["life-leak-on-raise"]
    clean = """
        import shutil
        import tempfile

        def careful():
            d = tempfile.mkdtemp()
            try:
                populate(d)
            finally:
                shutil.rmtree(d)
    """
    assert rule_ids(clean) == []


def test_task_done_callback_and_await_discharge():
    src = """
        import asyncio

        async def with_callback(coro):
            t = asyncio.create_task(coro)
            t.add_done_callback(lambda t: None if t.cancelled() else t.exception())
            await other_work()
            return t

        async def awaited(coro):
            t = asyncio.create_task(coro)
            return await t

        async def gathered(coro_a, coro_b):
            ta = asyncio.create_task(coro_a)
            tb = asyncio.create_task(coro_b)
            return await asyncio.gather(ta, tb)
    """
    assert rule_ids(src) == []


def test_ownership_escape_is_clean():
    src = """
        import asyncio

        async def handoff(registry, host, port):
            reader, writer = await asyncio.open_connection(host, port)
            registry.add(writer)
            await registry.flush()
            return reader
    """
    assert rule_ids(src) == []  # registry.add(writer): ownership moved


# -- resource lifetime: double release ----------------------------------------


def test_double_release_trigger_and_reassigned_clean():
    trigger = """
        def twice(w):
            w.close()
            flushed = True
            w.close()
            return flushed
    """
    (f,) = lint(trigger)[0]
    assert f.rule == "life-double-release"
    assert "w.close()" in f.message
    clean = """
        def rebound(w, factory):
            w.close()
            w = factory()
            w.close()
    """
    assert rule_ids(clean) == []  # a fresh receiver between the releases


# -- secret wipe-completeness -------------------------------------------------


def test_wipe_gap_trigger_and_wiped_clean():
    trigger = """
        class Node:
            def handle(self, kem, ct):
                ss = kem.decapsulate(ct)
                self.count += 1
                return self.count
    """
    (f,) = lint(trigger)[0]
    assert f.rule == "life-wipe-gap"
    assert "`ss`" in f.message and "decapsulate" in f.message
    clean = """
        class Node:
            def handle(self, kem, ct):
                ss = kem.decapsulate(ct)
                self.count += 1
                _wipe(ss)
                return self.count
    """
    assert rule_ids(clean) == []


def test_finally_wipe_covers_every_exit():
    src = """
        class Node:
            def handle(self, kem, ct):
                ss = kem.decapsulate(ct)
                try:
                    if not verify(ss):
                        return None
                    return process(ss)
                finally:
                    _wipe(ss)
    """
    assert rule_ids(src) == []


def test_secret_return_escape_is_clean():
    src = """
        class Node:
            def handle(self, kem, ct):
                ss = kem.decapsulate(ct)
                return ss
    """
    assert rule_ids(src) == []  # the caller owns it now (and is checked too)


def test_self_method_delegation_discharges():
    src = """
        class Node:
            def handle(self, kem, ct):
                ss = kem.decapsulate(ct)
                return self._respond_established(ss)
    """
    assert rule_ids(src) == []  # bare-self callee is under this rule too


def test_kdf_pass_does_not_discharge():
    """Handing the secret to another object's method is usage, not an
    ownership transfer — the exact bug class the rule exists for."""
    src = """
        class Node:
            def handle(self, kem, ct):
                ss = kem.decapsulate(ct)
                key = self.kdf.compress(ss)
                return key
    """
    (f,) = lint(src)[0]
    assert f.rule == "life-wipe-gap" and "`ss`" in f.message


def test_bytearray_twin_inherits_the_obligation():
    src = """
        class Node:
            def handle(self, kem, ct):
                ss = kem.decapsulate(ct)
                buf = bytearray(ss)
                mix(buf)
                _wipe(buf)
                return True
    """
    assert rule_ids(src) == []  # wiping the mutable twin settles the debt
    unwiped_twin = """
        class Node:
            def handle(self, kem, ct):
                ss = kem.decapsulate(ct)
                buf = bytearray(ss)
                mix(buf)
                return True
    """
    (f,) = lint(unwiped_twin)[0]
    assert f.rule == "life-wipe-gap" and "`buf`" in f.message
    assert "bytearray copy" in f.message


def test_live_rebind_of_unwiped_secret_is_flagged():
    src = """
        class Node:
            def handle(self, kem, ct):
                ss = kem.decapsulate(ct)
                ss = b""
                return True
    """
    (f,) = lint(src)[0]
    assert f.rule == "life-wipe-gap"
    assert "rebound while still holding unwiped key material" in f.message


def test_underscore_discard_of_secret_half_is_exempt():
    src = """
        class Node:
            def fingerprint(self, kem):
                pk, _ = kem.generate_keypair()
                return digest(pk)
    """
    assert rule_ids(src) == []
    tracked = """
        class Node:
            def fingerprint(self, kem):
                pk, sk = kem.generate_keypair()
                return digest(pk)
    """
    (f,) = lint(tracked)[0]
    assert f.rule == "life-wipe-gap" and "`sk`" in f.message


def test_container_append_is_an_ownership_transfer():
    src = """
        class Batch:
            def mint(self, kem, out):
                pk, sk = kem.generate_keypair()
                out.append((pk, sk))
                return len(out)
    """
    assert rule_ids(src) == []  # the container owns the tuple now


# -- suppression policing -----------------------------------------------------


def test_justified_suppression_is_honoured():
    src = """
        class Node:
            def handle(self, kem, ct):
                ss = kem.decapsulate(ct)
                return True  # qrlife: disable=life-wipe-gap — fixture: ss is wiped by the harness teardown
    """
    findings, suppressed = lint(src)
    assert findings == []
    assert [s.rule for s in suppressed] == ["life-wipe-gap"]


def test_unjustified_suppression_fires():
    src = """
        class Node:
            def handle(self, kem, ct):
                ss = kem.decapsulate(ct)
                return True  # qrlife: disable=life-wipe-gap
    """
    assert rule_ids(src) == ["life-unjustified-suppression"]


# -- seeded mutation pin (live fleet/manager.py) ------------------------------

_PEER_SEND_TAIL = (
    "        except (ConnectionError, OSError):\n"
    "            pass\n"
    "        finally:\n"
    "            writer.close()\n")


def test_manager_peer_send_is_leak_clean():
    findings, _ = Engine(life_rules()).lint_source(
        MANAGER.read_text(encoding="utf-8"),
        str(MANAGER.relative_to(REPO_ROOT)))
    assert [f for f in findings if f.rule == "life-leak-on-raise"] == []


def test_mutation_deleted_finally_close_flips_leak_on_raise():
    src = MANAGER.read_text(encoding="utf-8")
    assert src.count(_PEER_SEND_TAIL) == 1, (
        "_peer_send tail moved: update the pin")
    mutated = src.replace(
        _PEER_SEND_TAIL,
        "        except (ConnectionError, OSError):\n            pass\n", 1)
    findings, _ = Engine(life_rules()).lint_source(
        mutated, str(MANAGER.relative_to(REPO_ROOT)))
    assert any(f.rule == "life-leak-on-raise" and "writer" in f.message
               for f in findings), (
        "deleting `finally: writer.close()` from _peer_send must leak")


# -- CLI / output formats -----------------------------------------------------


def test_list_rules(capsys):
    assert qrlife_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("life-lock-cycle", "life-await-under-lock",
                "life-unreleased-lock", "life-leak-on-raise",
                "life-double-release", "life-wipe-gap",
                "life-unjustified-suppression"):
        assert rid in out


def test_cli_select_json_sarif_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent(
        """
        class Node:
            def handle(self, kem, ct):
                ss = kem.decapsulate(ct)
                return True
        """
    ))
    assert qrlife_main([str(bad)]) == 1
    capsys.readouterr()
    assert qrlife_main([str(bad), "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    (finding,) = payload["findings"]
    assert finding["rule"] == "life-wipe-gap"
    assert qrlife_main([str(bad), "--select", "life-lock-cycle"]) == 0
    assert qrlife_main([str(bad), "--select", "no-such-rule"]) == 2
    capsys.readouterr()
    assert qrlife_main([str(bad), "--format", "sarif"]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert check_sarif(doc) == []
    assert doc["runs"][0]["tool"]["driver"]["name"] == "qrlife"


def test_dump_lock_graph_names_the_live_roots(capsys):
    assert qrlife_main([str(PACKAGE), "--dump-lock-graph"]) == 0
    out = capsys.readouterr().out
    assert "DeviceProgramScheduler._lock ->" in out
    assert "SecureLogger._lock ->" in out
    for line in out.strip().splitlines():
        assert " -> " in line  # every edge renders as src -> dst  site


# -- the CI ratchet -----------------------------------------------------------


def test_live_codebase_is_lifetime_clean(capsys):
    """The whole package passes qrlife: no lock cycles, no leaks on raise,
    every secret reaches a wipe.  New violations fail here AND in CI."""
    rc = qrlife_main([str(PACKAGE)])
    out = capsys.readouterr().out
    assert rc == 0, f"qrlife found new violations:\n{out}"


def test_live_suppressions_match_the_budget():
    findings, suppressed = Engine(life_rules()).lint_paths([PACKAGE])
    assert [f for f in findings if f.severity == "error"] == []
    budget = json.loads(BUDGET.read_text(encoding="utf-8"))
    assert len(suppressed) == budget["qrlife"], (
        "qrlife suppression count drifted from the budget pin — update "
        "tools/analysis/suppression_budget.json in the same commit that "
        "adds or removes a justified suppression")


def test_live_run_is_fast_enough_for_ci():
    """Lock registry + order graph + resource scan + wipe walk are one
    pass over the qrflow call graph: the package must verify in seconds
    (<30s gate)."""
    project = Project(dict(_live_contexts()))
    t0 = time.perf_counter()
    analysis = LifeAnalysis(project)
    dt = time.perf_counter() - t0
    assert dt < 30.0, f"lifetime verification took {dt:.1f}s"
    assert analysis.locks.edges, "live lock-order graph unexpectedly empty"
    assert analysis.locks.cycles() == []
