"""Replicated router control plane (fleet/lease.py, fleet/router.py) —
the ISSUE 18 HA acceptance suite.

Covers the robustness satellite:

* seeded determinism of the lease state machine: the same injectable
  clocks + the same observed frames yield byte-identical transition
  logs, and rank-staggered claims make the failover winner a function
  of the seed, not the scheduler,
* the tied-claim race (two replicas claiming the same epoch, frames
  crossing): exactly one leader survives, broken on holder id with no
  third arbiter,
* stale-lease fencing in BOTH directions: a follower fences stale
  ``__rt_lease__``/``__rt_sync__`` frames with a typed ``__rt_reject__``
  and flight-records the event; a leader that receives such a reject
  demotes loudly instead of split-braining (live TCP, two replicas),
* STEK-rotation-during-failover: a ticket minted under the dead
  leader's current key still redeems at the new leader within the
  dual-key accept window, and the replication install guard refuses a
  pre-rotation frame that would regress the window,
* leader kill mid-storm (task-mode router fleet over real TCP): every
  established session finishes — clients fail over across the router
  ring on typed transport errors — and at least one reconnect AFTER
  the failover resumes via a ticket minted before it,
* the double-hello conn_gen supersede: a gateway reconnecting to a
  router before the old control loop saw its EOF must not double-count
  heartbeats or null the live writer (the N-router heartbeat dedupe
  bugfix).

Everything runs on minimal images: stdlib toy crypto, fake clocks for
the lease timelines, ``spawn="task"`` fleets for the live cases.
"""

import asyncio

import pytest

from quantum_resistant_p2p_tpu.app.resumption import STEKRing
from quantum_resistant_p2p_tpu.fleet import control as fleet_control
from quantum_resistant_p2p_tpu.fleet.lease import (DEMOTED, FOLLOWER, LEADER,
                                                   LeaderLease)
from quantum_resistant_p2p_tpu.fleet.manager import GatewayFleet
from quantum_resistant_p2p_tpu.obs import flight as obs_flight


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


@pytest.fixture
def recorder(monkeypatch):
    """A fresh process-wide flight recorder: the fencing assertions must
    see THIS test's events, not a prior storm's ring."""
    rec = obs_flight.FlightRecorder()
    monkeypatch.setattr(obs_flight, "RECORDER", rec)
    return rec


def _kinds(rec):
    return [ev["kind"] for ev in rec.snapshot()]


# -- lease state machine: seeded determinism ----------------------------------


def _scripted_failover():
    """One fixed failover timeline on fake clocks: rt0 claims, renews
    once, dies; rt1 takes over after its rank stagger; rt0 respawns and
    follows.  Returns both transition logs (the determinism pin)."""
    now = [0.0]
    clock = lambda: now[0]  # noqa: E731 — shared fake clock
    rt0 = LeaderLease("rt0", 0, ttl_s=1.0, claim_stagger_s=0.25, clock=clock)
    rt1 = LeaderLease("rt1", 1, ttl_s=1.0, claim_stagger_s=0.25, clock=clock)

    # birth grace: neither claims before a full TTL of silence
    assert not rt0.claim_due() and not rt1.claim_due()
    now[0] = 1.0
    # rank stagger: rt0 is due at expiry, rt1 only a stagger later
    assert rt0.claim_due() and not rt1.claim_due()
    body = rt0.claim()
    assert body["epoch"] == 1 and rt0.is_leader
    assert rt1.observe(body["holder"], body["epoch"], body["ttl_s"])

    now[0] = 1.5  # ttl/3 cadence: renew well before followers see expiry
    assert rt0.renew_due()
    body = rt0.renew()
    assert rt1.observe(body["holder"], body["epoch"], body["ttl_s"])

    # rt0 dies (no more frames).  rt1's lease view expires at 2.5; its
    # rank-1 stagger holds the claim until 2.75.
    now[0] = 2.6
    assert not rt1.claim_due()
    now[0] = 2.8
    assert rt1.claim_due()
    body = rt1.claim()
    assert body["epoch"] == 2 and rt1.is_leader

    # rt0 respawns with a FRESH lease: the birth grace keeps it quiet,
    # and the first observed renewal folds it in as a follower
    rt0b = LeaderLease("rt0", 0, ttl_s=1.0, claim_stagger_s=0.25, clock=clock)
    assert not rt0b.claim_due()
    assert rt0b.observe(body["holder"], body["epoch"], body["ttl_s"])
    assert rt0b.role == FOLLOWER and rt0b.holder == "rt1"
    return rt0.transitions + rt0b.transitions, rt1.transitions


def test_lease_failover_is_deterministic_on_injected_clocks():
    """Same clocks + same frames ⇒ byte-identical transition logs: the
    failover winner is a function of rank and timing, never of scheduler
    interleaving (the seeded-chaos replay contract, control-plane tier)."""
    a0, a1 = _scripted_failover()
    b0, b1 = _scripted_failover()
    assert repr(a0) == repr(b0)
    assert repr(a1) == repr(b1)
    assert [t[1:3] for t in a1] == [(FOLLOWER, LEADER)]
    assert a1[0][3] == 2  # rt1 took over at epoch 2 (monotonic, not reused)


def test_tied_claim_race_converges_without_arbiter():
    """Two replicas claim the same epoch before seeing each other (the
    crossed-frames race): holder-id order picks exactly one leader, and
    the loser demotes loudly — no third party, no silent dual-leader."""
    now = [10.0]
    a = LeaderLease("rt0", 0, ttl_s=1.0, clock=lambda: now[0])
    b = LeaderLease("rt1", 0, ttl_s=1.0, clock=lambda: now[0])
    assert a.claim()["epoch"] == 1
    assert b.claim()["epoch"] == 1
    # frames cross: rt0 fences rt1's tied claim (rt0 < rt1) ...
    assert a.observe("rt1", 1, 1.0) is False
    assert a.is_leader and a.stale_rejects == 1
    # ... and rt1 accepts rt0's, demoting itself out of the split brain
    assert b.observe("rt0", 1, 1.0) is True
    assert b.role == DEMOTED
    assert any(reason == "superseded_by=rt0"
               for *_ignored, reason in b.transitions)


def test_demotion_is_sticky_until_rejoin():
    """A fenced leader demotes, stops claiming entirely (sticky — a
    flapping ex-leader must not oscillate), and re-enters only via the
    explicit rejoin path."""
    now = [0.0]
    lease = LeaderLease("rt0", 0, ttl_s=1.0, clock=lambda: now[0])
    now[0] = 1.0
    lease.claim()
    assert lease.observe_reject(7) is True  # proof a fresher lease exists
    assert lease.role == DEMOTED and lease.max_seen_epoch == 7
    now[0] = 100.0  # far past any expiry: still never claims
    assert not lease.claim_due()
    assert any(reason == "fenced_by_peer"
               for *_ignored, reason in lease.transitions)
    lease.rejoin()
    assert lease.role == FOLLOWER
    assert lease.claim_due()  # a follower past expiry claims again


# -- stale-lease fencing over the control link --------------------------------


class _CaptureWriter:
    """Just enough StreamWriter for ``send_ctrl``: buffers the frames a
    handler replies with so the test can decode them."""

    def __init__(self):
        self.buf = b""
        self.closed = False

    def write(self, data):
        self.buf += data

    async def drain(self):
        pass

    def close(self):
        self.closed = True


async def _decode_frames(buf: bytes) -> list[dict]:
    reader = asyncio.StreamReader()
    reader.feed_data(buf)
    reader.feed_eof()
    frames = []
    while True:
        try:
            frames.append(await fleet_control.read_ctrl(reader))
        except asyncio.IncompleteReadError:
            return frames


def _replica(router_id: str, rank: int, peers=None) -> GatewayFleet:
    return GatewayFleet(0, attach=True, spawn="task", router_id=router_id,
                        router_rank=rank, router_peers=list(peers or []),
                        lease_ttl_s=1.0, lease_stagger_s=0.25)


def test_stale_authority_frames_are_fenced_and_flight_recorded(run, recorder):
    """A replica tracking lease epoch 5 fences an epoch-3 claim AND an
    epoch-2 sync with typed ``__rt_reject__`` replies carrying ITS epoch,
    flight-records both, and lets neither touch the STEK ring or the
    membership roster."""
    fleet = _replica("rtA", 0)
    assert fleet.lease.observe("rtB", 5, 60.0)

    w = _CaptureWriter()
    run(fleet._on_rt_lease(
        {"type": fleet_control.RT_LEASE, "holder": "rtC", "epoch": 3,
         "ttl_s": 1.0}, w))
    (reject,) = run(_decode_frames(w.buf))
    assert reject == {"type": fleet_control.RT_REJECT, "router": "rtA",
                      "epoch": 5}
    assert fleet.lease_fenced == 1
    assert "stale_lease_fenced" in _kinds(recorder)

    ring_before = fleet.ticket_keys.export()
    w2 = _CaptureWriter()
    run(fleet._on_rt_sync(
        {"type": fleet_control.RT_SYNC, "holder": "rtC", "epoch": 2,
         "keys": [["eeee", "00" * 32]], "rotations": 9,
         "members": ["gwZ"]}, w2))
    (reject2,) = run(_decode_frames(w2.buf))
    assert reject2["type"] == fleet_control.RT_REJECT
    assert reject2["epoch"] == 5
    assert fleet.ticket_keys.export() == ring_before  # authority untouched
    assert "gwZ" not in fleet.members
    assert fleet.lease_fenced == 2
    assert "stale_sync_fenced" in _kinds(recorder)


def test_stale_leader_demotes_on_reject_reply(run, recorder):
    """Split-brain, live over TCP: a replica that claims epoch 1 while a
    peer already tracks epoch 5 gets its announcement fenced — and the
    bounced ``__rt_reject__`` demotes it loudly (flight trigger), never
    leaving two writers of STEK authority."""

    async def scenario():
        peer = _replica("rtB", 1)
        await peer.start()
        try:
            assert peer.lease.observe("rtX", 5, 60.0)
            stale = _replica(
                "rtA", 0,
                peers=[{"router": "rtB", "host": "127.0.0.1",
                        "port": peer.ctrl_port}])
            body = stale.lease.claim()
            assert body["epoch"] == 1 and stale.lease.is_leader
            await stale._announce_lease(body, sync=False)
            assert stale.lease.role == DEMOTED
            assert stale.lease_rejects >= 1
            assert peer.lease_fenced >= 1
            kinds = _kinds(obs_flight.RECORDER)
            assert "router_demoted" in kinds
            assert "stale_lease_fenced" in kinds
        finally:
            await peer.stop()

    run(scenario())


# -- STEK replication: the accept window survives failover --------------------


def _import_export(ring_export):
    return [(ep, bytes.fromhex(key_hex)) for ep, key_hex in ring_export]


def test_ticket_minted_under_dead_leader_redeems_after_failover():
    """The failover currency: a ticket sealed under the leader's CURRENT
    key — then demoted to previous by one more rotation — still opens at
    the follower that replicated both frames, because the dual-key accept
    window travels with the ``__rt_sync__`` export.  The install guard
    refuses the pre-rotation frame that would regress the window."""
    leader = STEKRing()
    follower = STEKRing()
    assert follower.install(_import_export(leader.export()), guard=True)

    secret = bytes(range(32))
    ticket = leader.seal_ticket({"sid": "s1", "secret": secret.hex()})
    pre_rotation = leader.export()
    leader.rotate()
    assert follower.install(_import_export(leader.export()), guard=True)
    # leader dies here; the follower IS the accept window now
    fields, stek = follower.open_ticket(ticket)
    assert fields == {"sid": "s1"} and stek == secret
    # a new ticket mints under the replicated CURRENT key
    fields2, _stek2 = follower.open_ticket(
        follower.seal_ticket({"sid": "s2", "secret": secret.hex()}))
    assert fields2 == {"sid": "s2"}

    # structural regression guard: a delayed pre-rotation replicate frame
    # (same lease epoch, slower connection) must not roll the window back
    assert follower.install(_import_export(pre_rotation), guard=True) is False
    fields3, _stek3 = follower.open_ticket(ticket)  # window unchanged
    assert fields3 == {"sid": "s1"}


# -- leader kill mid-storm (live task-mode router fleet) ----------------------


def test_router_storm_survives_seeded_leader_kill(run):
    """The HA chaos acceptance shape in miniature (CI runs it at 1000
    sessions via ``bench.py --storm --fleet 3 --router-roll``): a seeded
    mid-storm kill of the initial leader, every established session
    finishes — clients fail over across the router ring on typed
    transport errors — 0 plaintext, and reconnects landing after the
    kill still resume via tickets minted before it."""
    from quantum_resistant_p2p_tpu.fleet.storm import (
        default_router_kill_rules, run_router_storm)

    out = run(run_router_storm(
        sessions=12, gateways=2, routers=2, spawn="task", concurrency=12,
        msgs_per_session=6, msg_interval_s=0.1, hb_interval=0.1,
        ke_timeout=30.0, session_attempts=8, seed=3,
        lease_ttl_s=0.5, lease_stagger_s=0.1, roll=False,
        fault_rules=default_router_kill_rules("rt0", 4)))
    assert out["completed_sessions"] == 12
    assert out["lost_established_sessions"] == 0
    assert out["plaintext_sends"] == 0
    assert out["router_kills"] >= 1
    assert out["chaos"]["injected"] >= 1
    # the dead leader's clients walked the router ring (typed transport
    # failure -> next replica), they did not stall out
    assert out["router_failovers"] >= 1
    # ≥1 post-failover reconnect redeemed a pre-failover ticket: the
    # accept window provably survived the leader
    assert out["post_failover_resumed"] >= 1
    roles = {row["router"]: (row["lease"] or {}).get("role")
             for row in out["router_fleet"]["routers"]}
    assert roles.get("rt1") == LEADER  # rt1 took over after the kill
    assert out["initial_leader"] == "rt0"  # ...from the seeded victim


# -- conn_gen supersede (the N-router heartbeat dedupe fix) -------------------


def test_second_hello_supersedes_stale_control_connection(run):
    """A gateway's reconnect can land before the router's old control
    loop saw its EOF (with N routers this happens constantly).  The new
    hello must supersede: the old loop's frames stop counting (no
    double-shifted reconcile windows) and its eventual EOF must NOT null
    the LIVE connection's state."""

    async def gw_conn(port, hello):
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        await fleet_control.send_ctrl(writer, hello)
        push = await fleet_control.read_ctrl(reader)  # STEK push = registered
        assert push["type"] == fleet_control.GW_TICKET_KEYS
        return reader, writer

    async def scenario():
        fleet = GatewayFleet(0, attach=True, spawn="task", hb_interval=0.5)
        await fleet.start()
        try:
            hello = {"type": fleet_control.GW_HELLO, "gateway": "gwX",
                     "p2p_port": 41001, "pid": 1}
            r1, w1 = await gw_conn(fleet.ctrl_port, hello)
            member = fleet.members["gwX"]
            assert member.conn_gen == 1 and member.port == 41001
            live_writer = member.writer

            # the reconnect: same gateway, fresh connection, new port —
            # the new hello supersedes (gen bump) and the router CLOSES
            # the stale server-side writer at once, so the dead
            # incarnation's frames can no longer count against gwX
            _r2, w2 = await gw_conn(
                fleet.ctrl_port, dict(hello, p2p_port=41002, pid=2))
            assert member.conn_gen == 2
            assert member.port == 41002
            assert member.writer is not live_writer
            data = await r1.read()  # the stale connection really is dead
            assert data == b""

            # the stale loop's EOF must NOT null the live writer or the
            # registration (pre-fix, this left a serving gateway
            # unreachable for probes and STEK pushes)
            w1.close()
            await asyncio.sleep(0.1)
            assert member.port == 41002
            assert member.writer is not None
            assert member.registered

            # the live connection heartbeats normally
            hb_count = member.hb_count
            await fleet_control.send_ctrl(w2, {
                "type": fleet_control.GW_HEARTBEAT, "gateway": "gwX",
                "stats": {"connections": 0}})
            for _ in range(40):
                if member.hb_count > hb_count:
                    break
                await asyncio.sleep(0.02)
            assert member.hb_count == hb_count + 1
            assert member.breaker.state == "closed"
            w2.close()
        finally:
            await fleet.stop()

    run(scenario())
