"""Pod-scale sharded crypto plane: the device-program scheduler's placement
axis (provider/scheduler.py) and its integration with the batching stack.

Covered here (ISSUE 6 acceptance):

* mesh-of-1 degrades to exactly the single-device behavior — same queue
  stats, same ``SecureMessaging.metrics()`` key layout;
* placement is load-aware and DETERMINISTIC under a seeded load pattern
  (least-inflight, lowest-index tie-break, probe-first);
* per-shard breaker isolation: killing ONE shard's device via ``faults/``
  quarantines that shard only — the others keep serving on their own
  breakers with ``device_served_fraction >= 0.9``, and a seeded chaos run
  over the full protocol engine completes with 0 failed handshakes;
* the opcache partitions per shard (device state never crosses chips);
* placed jitted programs are BIT-EXACT vs the single-device path,
  including the fused handshake step (the conftest pins an 8-device
  virtual CPU platform, so real per-device placement is exercised);
* obs integration: ``shard=<i>`` labeled metric children, shard attrs on
  dispatch spans, flight events for quarantine/rebalance.
"""

import asyncio
import hashlib
import hmac
import os
import time

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.app import messaging as messaging_mod
from quantum_resistant_p2p_tpu.app.messaging import SecureMessaging
from quantum_resistant_p2p_tpu.faults import FaultPlan, FaultRule
from quantum_resistant_p2p_tpu.net.p2p_node import P2PNode
from quantum_resistant_p2p_tpu.obs import flight as obs_flight
from quantum_resistant_p2p_tpu.obs import trace as obs_trace
from quantum_resistant_p2p_tpu.obs.metrics import Registry
from quantum_resistant_p2p_tpu.provider.base import (KeyExchangeAlgorithm,
                                                     SignatureAlgorithm,
                                                     SymmetricAlgorithm)
from quantum_resistant_p2p_tpu.provider.batched import Breaker, OpQueue
from quantum_resistant_p2p_tpu.provider.opcache import (DeviceOperandCache,
                                                        current_shard,
                                                        shard_scope)
from quantum_resistant_p2p_tpu.provider.registry import (register_kem,
                                                         register_signature)
from quantum_resistant_p2p_tpu.provider.scheduler import (
    DeviceProgramScheduler, Shard)

# -- stdlib toy algorithms (the faults-suite pattern: the REAL scheduler/
# queue/breaker/engine stack runs, the crypto inside is a hash toy so a
# sharded chaos run costs milliseconds) --------------------------------------


def _keystream(key: bytes, nonce: bytes, n: int) -> bytes:
    out = b""
    ctr = 0
    while len(out) < n:
        out += hashlib.sha256(key + nonce + ctr.to_bytes(8, "big")).digest()
        ctr += 1
    return out[:n]


class ToyAEAD(SymmetricAlgorithm):
    name = "TOYS-AEAD"
    display_name = "TOYS-AEAD"
    key_size = 32
    nonce_size = 16

    def encrypt(self, key, plaintext, associated_data=None):
        nonce = os.urandom(self.nonce_size)
        ct = bytes(a ^ b for a, b in
                   zip(plaintext, _keystream(key, nonce, len(plaintext))))
        tag = hmac.new(key, nonce + ct + (associated_data or b""),
                       hashlib.sha256).digest()
        return nonce + ct + tag

    def decrypt(self, key, data, associated_data=None):
        if len(data) < self.nonce_size + 32:
            raise ValueError("ciphertext too short")
        nonce, ct, tag = (data[: self.nonce_size], data[self.nonce_size:-32],
                          data[-32:])
        want = hmac.new(key, nonce + ct + (associated_data or b""),
                        hashlib.sha256).digest()
        if not hmac.compare_digest(tag, want):
            raise ValueError("authentication failed")
        return bytes(a ^ b for a, b in zip(ct, _keystream(key, nonce, len(ct))))


class ToyKEM(KeyExchangeAlgorithm):
    name = "TOYS-KEM"
    display_name = "TOYS-KEM"
    public_key_len = 32
    secret_key_len = 32
    ciphertext_len = 32
    shared_secret_len = 32

    def __init__(self, backend="cpu"):
        self.backend = backend

    def generate_keypair(self):
        sk = os.urandom(32)
        return hashlib.sha256(b"pk" + sk).digest(), sk

    def encapsulate(self, public_key):
        ct = os.urandom(32)
        return ct, hashlib.sha256(public_key + ct).digest()

    def decapsulate(self, secret_key, ciphertext):
        pk = hashlib.sha256(b"pk" + secret_key).digest()
        return hashlib.sha256(pk + ciphertext).digest()


class ToySig(SignatureAlgorithm):
    name = "TOYS-SIG"
    display_name = "TOYS-SIG"
    public_key_len = 32
    secret_key_len = 32
    signature_len = 32

    def __init__(self, backend="cpu"):
        self.backend = backend

    def generate_keypair(self):
        sk = os.urandom(32)
        return hashlib.sha256(b"pk" + sk).digest(), sk

    def sign(self, secret_key, message):
        pk = hashlib.sha256(b"pk" + secret_key).digest()
        return hashlib.sha256(b"sig" + pk + message).digest()

    def verify(self, public_key, message, signature):
        return hmac.compare_digest(
            signature, hashlib.sha256(b"sig" + public_key + message).digest()
        )


register_kem("TOYS-KEM", lambda backend, devices=0: ToyKEM(backend),
             ("cpu", "tpu"))
register_signature("TOYS-SIG", lambda backend, devices=0: ToySig(backend),
                   ("cpu", "tpu"))


def _logical(n: int, cooloff_s: float = 60.0) -> DeviceProgramScheduler:
    """An n-shard scheduler with no physical devices: placement, breakers
    and quarantine behave exactly as on hardware, minus the device pin."""
    return DeviceProgramScheduler(shards=n, cooloff_s=cooloff_s,
                                  devices=[None] * n)


# -- placement policy ---------------------------------------------------------


def test_placement_least_loaded_deterministic():
    """The policy is a pure function of the load pattern: least-inflight,
    lowest-index tie-break — the same seeded claim/release sequence yields
    the same placements, run after run."""

    def drive():
        sched = _logical(4)
        seq = []
        held = []
        for _ in range(8):  # fill: round-robin by tie-break
            sh = sched.place()
            held.append(sh)
            seq.append(sh.index)
        # release shard 2's claims: it becomes least-loaded
        for sh in list(held):
            if sh.index == 2:
                sched.done(sh)
                held.remove(sh)
        for _ in range(3):
            sh = sched.place()
            held.append(sh)
            seq.append(sh.index)
        return seq

    first, second = drive(), drive()
    assert first == second
    assert first[:8] == [0, 1, 2, 3, 0, 1, 2, 3]
    # shard 2 drained to 0 inflight: it absorbs the next two (0->1->2
    # inflight), then ties with everyone at 2 and index 0 wins
    assert first[8:] == [2, 2, 0]


def test_placement_avoids_open_shard_then_probes_it_back():
    sched = _logical(3, cooloff_s=0.05)
    sched.shards[1].breaker.trip()
    assert sched.shards[1].breaker.state == "open"
    placed = [sched.place() for _ in range(4)]
    for sh in placed:
        sched.done(sh)
    assert all(sh.index != 1 for sh in placed)
    # cool-off expired: the policy must route ONE flush back (probe-first)
    # or the shard could never heal
    time.sleep(0.06)
    probe = sched.place()
    assert probe.index == 1
    assert probe.breaker.acquire_dispatch() == "probe"
    probe.breaker.record_success("probe")
    sched.done(probe)
    assert sched.shards[1].breaker.state == "closed"


def test_placement_skips_quarantined_shard():
    sched = _logical(2)
    sched.shards[0].breaker.quarantine("bad device")
    assert all(sched.place().index == 1 for _ in range(3))


# -- mesh-of-1 degradation ----------------------------------------------------


def test_single_shard_queue_matches_legacy_behavior():
    """A 1-shard scheduler IS the old one-breaker world: same results,
    same counters, same stats layout."""

    def batch_fn(items):
        return [x * 3 for x in items]

    async def drive(queue):
        return await asyncio.gather(*(queue.submit(i) for i in range(9)))

    async def main():
        legacy = OpQueue(batch_fn, max_batch=4, max_wait_ms=1.0,
                         fallback_fn=batch_fn, breaker=Breaker(cooloff_s=60.0))
        sharded = OpQueue(batch_fn, max_batch=4, max_wait_ms=1.0,
                          fallback_fn=batch_fn, scheduler=_logical(1))
        for q in (legacy, sharded):
            q._warm_buckets.update({1, 2, 4})
        assert await drive(legacy) == await drive(sharded)
        a, b = legacy.stats.as_dict(), sharded.stats.as_dict()
        assert set(a) == set(b)
        for key in ("ops", "flushes", "fallback_ops", "device_trips",
                    "breaker_trips", "device_served_fraction"):
            assert a[key] == b[key], key

    asyncio.run(main())


def test_metrics_key_parity_across_shard_counts(monkeypatch):
    """metrics() exposes the same key layout at 0, 1 and 2 shards — the
    scheduler is additive, never a reshaping, of the legacy contract
    (which tests/test_obs.py pins against the pre-obs layout)."""
    monkeypatch.setattr(SecureMessaging, "_spawn_warmup",
                        lambda self, **kw: None)

    def engine(shards):
        from quantum_resistant_p2p_tpu.provider import get_kem, get_signature

        node = P2PNode(node_id=f"par{shards}", host="127.0.0.1", port=0)
        return SecureMessaging(
            node, symmetric=ToyAEAD(), kem=get_kem("TOYS-KEM", "tpu"),
            signature=get_signature("TOYS-SIG", "tpu"), use_batching=True,
            shard_devices=shards, sig_keypair=(b"p", b"s"),
        )

    m0, m1, m2 = (engine(n).metrics() for n in (0, 1, 2))
    assert set(m0) == set(m1) == set(m2)
    assert m0["shards"]["n_shards"] == 1
    assert m2["shards"]["n_shards"] == 2
    assert {s["shard"] for s in m2["shards"]["shards"]} == {0, 1}


# -- opcache partitioning -----------------------------------------------------


def test_opcache_partitions_per_shard_scope():
    cache = DeviceOperandCache(capacity=8)
    key = b"k" * 32
    with shard_scope(0):
        assert current_shard() == 0
        cache.put("ek", key, "state-on-chip-0")
        assert cache.lookup("ek", key) == "state-on-chip-0"
    with shard_scope(1):
        # chip 1 must never be handed chip 0's device arrays
        assert cache.lookup("ek", key) is None
        cache.put("ek", key, "state-on-chip-1")
    with shard_scope(0):
        assert cache.lookup("ek", key) == "state-on-chip-0"
    assert current_shard() == 0  # scope restored (default shard)
    assert len(cache) == 2


# -- per-shard fault isolation ------------------------------------------------


def test_killed_shard_quarantines_one_shard_others_serve(monkeypatch):
    """ISSUE 6 acceptance (facade level): kill ONE shard's device via
    faults/ — that shard's breaker opens, placement routes around it, and
    the run finishes >= 90% device-served with the other shard closed."""

    def batch_fn(items):
        time.sleep(0.005)  # overlap flushes so both shards take traffic
        return [x + 100 for x in items]

    async def main():
        sched = _logical(2)
        q = OpQueue(batch_fn, max_batch=2, max_wait_ms=0.5,
                    fallback_fn=lambda items: [x + 100 for x in items],
                    scheduler=sched, label="toy.op")
        q._warm_buckets.update({1, 2})
        plan = FaultPlan(77, [
            FaultRule("device.dispatch", "raise",
                      match={"op": "toy.op", "shard": 1}, nth=1, times=99),
        ])
        results = []
        with plan.activate():
            for _ in range(10):  # waves of concurrent flushes
                results += await asyncio.gather(
                    *(q.submit(i) for i in range(8)))
        assert results == [i + 100 for i in range(8)] * 10  # nothing failed
        assert plan.injected, "shard 1 never took a dispatch"
        assert all(e["shard"] == 1 for e in plan.injected)
        st = q.stats.as_dict()
        assert st["device_served_fraction"] >= 0.9, st
        assert sched.shards[0].breaker.state == "closed"
        assert sched.shards[1].breaker.state == "open"

    asyncio.run(main())


@pytest.fixture
def run():
    loop = asyncio.new_event_loop()
    yield loop.run_until_complete
    loop.run_until_complete(loop.shutdown_asyncgens())
    loop.close()


@pytest.fixture(autouse=True)
def fast_timeout(monkeypatch):
    monkeypatch.setattr(messaging_mod, "KEY_EXCHANGE_TIMEOUT", 1.5)
    monkeypatch.setattr(messaging_mod, "KE_RETRY_BACKOFF_S", 0.05)


async def _pair(**kwargs):
    from quantum_resistant_p2p_tpu.provider import get_kem, get_signature

    a_node = P2PNode(node_id="alice", host="127.0.0.1", port=0)
    b_node = P2PNode(node_id="bob", host="127.0.0.1", port=0)
    await a_node.start()
    await b_node.start()
    kw = dict(kem=get_kem("TOYS-KEM", "tpu"),
              signature=get_signature("TOYS-SIG", "tpu"),
              use_batching=True, max_batch=8, max_wait_ms=1.0)
    kw.update(kwargs)
    a = SecureMessaging(a_node, symmetric=ToyAEAD(), **kw)
    b = SecureMessaging(b_node, symmetric=ToyAEAD(), **kw)
    assert await a_node.connect_to_peer("127.0.0.1", b_node.port) == "bob"
    for _ in range(100):
        if b_node.is_connected("alice"):
            break
        await asyncio.sleep(0.01)
    return a, b


def test_sharded_chaos_run_zero_failed_handshakes(run, monkeypatch):
    """ISSUE 6 acceptance (engine level): a seeded chaos plan permanently
    kills shard 1's device on both sides of a 2-shard plane.  12
    handshakes complete with 0 failures; the REMAINING shard's breaker
    ends closed on both engines and the run stays >= 90% device-served
    (the sick shard's flushes degrade to the cpu fallback; its siblings
    never do)."""
    monkeypatch.setenv("QRP2P_HEALTH_GATE", "0")

    async def main():
        # max_batch=1: every op flushes immediately, so concurrent ops are
        # concurrent flushes — the load pattern that spreads placements
        a, b = await _pair(shard_devices=2, breaker_cooloff_s=300.0,
                           max_batch=1)
        await a.wait_ready()
        await b.wait_ready()
        plan = FaultPlan(4242, [
            FaultRule("device.dispatch", "raise", match={"shard": 1},
                      nth=1, times=999),
        ])
        failures = 0
        with plan.activate():
            # a concurrent burst through the plane: placement spreads the
            # flushes across both shards, so the kill rule provably lands
            # on shard 1 before the handshake window
            await asyncio.gather(
                *(a._bkem.generate_keypair() for _ in range(8)))
            for _i in range(12):
                for side, peer in ((a, "bob"), (b, "alice")):
                    side.shared_keys.pop(peer, None)
                    side.raw_secrets.pop(peer, None)
                    side.ke_state[peer] = messaging_mod.KeyExchangeState.NONE
                if not await a.initiate_key_exchange("bob"):
                    failures += 1
        ma, mb = a.metrics(), b.metrics()
        await a.node.stop()
        await b.node.stop()
        return failures, plan, ma, mb, a, b

    failures, plan, ma, mb, a, b = run(main())
    assert failures == 0
    # the kill rule fired (coalesced sibling flushes overlap, so shard 1
    # takes traffic early) and hit ONLY shard 1
    assert plan.injected and all(e["shard"] == 1 for e in plan.injected)
    total = fb = 0
    for m in (ma, mb):
        for fam in ("kem_queue", "sig_queue"):
            for q in m[fam].values():
                total += q["ops"]
                fb += q["fallback_ops"]
    assert total and (total - fb) / total >= 0.9
    for eng, m in ((a, ma), (b, mb)):
        per_shard = {s["shard"]: s for s in m["shards"]["shards"]}
        # the sick shard quarantined ALONE: shard 0 kept its device path
        assert per_shard[0]["breaker_state"] == "closed"
        if per_shard[1]["dispatches"]:
            assert per_shard[1]["breaker_state"] == "open"
            # the legacy key reports the WORST shard, so dashboards keyed
            # on it see the degradation even though shard 0 is healthy
            assert m["breaker_state"] == "open"


# -- bit-exactness of placed programs (real 8-device virtual platform) --------


def test_placed_kem_program_bit_exact_vs_default_device():
    """Placement changes WHERE a jitted program runs, never its bits: the
    same ML-KEM-512 keygen seeds yield identical keys on every shard of
    the virtual 8-device mesh."""
    from quantum_resistant_p2p_tpu.kem import mlkem

    sched = DeviceProgramScheduler(shards=4)
    assert [s.device for s in sched.shards].count(None) == 0, \
        "conftest pins an 8-device platform; shards must be physical"
    kg, enc, dec = mlkem.get("ML-KEM-512")
    rng = np.random.default_rng(20260803)
    d, z, m = (rng.integers(0, 256, (2, 32), dtype=np.uint8) for _ in range(3))
    ek_ref, dk_ref = (np.asarray(o) for o in kg(d, z))
    key_ref, ct_ref = (np.asarray(o) for o in enc(ek_ref, m))
    for shard in (sched.shards[1], sched.shards[3]):
        ek_s, dk_s = shard.run_placed(lambda _items: kg(d, z), [])
        assert np.array_equal(np.asarray(ek_s), ek_ref)
        assert np.array_equal(np.asarray(dk_s), dk_ref)
        key_s = shard.run_placed(lambda _items: dec(dk_ref, ct_ref), [])
        assert np.array_equal(np.asarray(key_s), key_ref)


def test_placed_fused_handshake_step_bit_exact():
    """The sharded handshake path vs the single-device fused path: the
    composite keygen+sign program with pinned randomness produces
    byte-identical keys and signatures when placed on another chip."""
    from quantum_resistant_p2p_tpu.provider import get_fused, get_kem, get_signature
    from quantum_resistant_p2p_tpu.provider.fused_providers import init_pk_offset

    kem = get_kem("ML-KEM-512", backend="tpu")
    sig = get_signature("ML-DSA-44", backend="tpu")
    fused = get_fused(kem, sig)
    assert fused is not None
    pk_off = init_pk_offset(kem.name, "AES-256-GCM")
    _spk, ssk = sig.generate_keypair()
    sks = np.frombuffer(ssk, np.uint8)[None]
    tmpl = [b"t" * (pk_off + 2 * kem.public_key_len + 64)]
    rnd = [b"\x07" * 32]

    # pin the host-drawn seeds so both runs dispatch identical operands
    seeds = os.urandom(64)

    def fixed_urandom(n, _s=seeds):
        return (_s * (n // len(_s) + 1))[:n]

    import quantum_resistant_p2p_tpu.provider.fused_providers as fp

    real = fp.os.urandom
    fp.os.urandom = fixed_urandom
    try:
        ek_ref, dk_ref, sig_ref = fused.keygen_sign_batch(sks, tmpl, pk_off,
                                                          rnd=rnd)
        sched = DeviceProgramScheduler(shards=2)
        ek_s, dk_s, sig_s = sched.shards[1].run_placed(
            lambda _items: fused.keygen_sign_batch(sks, tmpl, pk_off, rnd=rnd),
            [],
        )
    finally:
        fp.os.urandom = real
    assert np.array_equal(np.asarray(ek_s), np.asarray(ek_ref))
    assert np.array_equal(np.asarray(dk_s), np.asarray(dk_ref))
    assert [bytes(s) for s in sig_s] == [bytes(s) for s in sig_ref]


# -- obs integration ----------------------------------------------------------


def test_scheduler_labeled_metric_children_and_prometheus():
    reg = Registry(name="shardtest")
    sched = DeviceProgramScheduler(shards=2, devices=[None, None],
                                   registry=reg)
    sched.shards[1].run_placed(lambda items: items, [1, 2])
    snap = reg.snapshot()
    assert snap["counters"]['shard_dispatches{shard="1"}'] == 1
    assert snap["counters"]['shard_dispatches{shard="0"}'] == 0
    assert snap["histograms"]['shard_dispatch_latency{shard="1"}']["count"] == 1
    assert snap["gauges"]['shard_inflight{shard="0"}'] == 0
    prom = reg.to_prometheus()
    assert 'shard="1"' in prom


def test_dispatch_spans_carry_shard_attr():
    async def main():
        sched = _logical(2)
        q = OpQueue(lambda items: items, max_batch=2, max_wait_ms=0.5,
                    fallback_fn=lambda items: items, scheduler=sched,
                    label="toy.span")
        q._warm_buckets.update({1, 2})
        obs_trace.TRACER.reset()
        await asyncio.gather(*(q.submit(i) for i in range(4)))
        spans = obs_trace.TRACER.snapshot()
        flushes = [s for s in spans if s["name"] == "queue.flush"]
        dispatches = [s for s in spans if s["name"] == "device.dispatch"]
        assert flushes and dispatches
        assert all("shard" in s["attrs"] for s in flushes)
        assert all("shard" in s["attrs"] for s in dispatches)

    asyncio.run(main())


def test_flight_events_for_shard_quarantine_and_rebalance():
    sched = _logical(2, cooloff_s=60.0)
    for _ in range(2):
        sched.done(sched.place())  # settle the healthy-set baseline
    sched.shards[1].breaker.trip()  # shard 1 degrades
    sched.done(sched.place())  # placement notices the routing change
    sched.shards[0].breaker.quarantine("test: device computes wrong answers")
    events = obs_flight.RECORDER.snapshot()
    opens = [e for e in events if e["kind"] == "breaker_open"
             and e.get("shard") == "shard1"]
    quar = [e for e in events if e["kind"] == "breaker_quarantined"
            and e.get("shard") == "shard0"]
    rebal = [e for e in events if e["kind"] == "shard_rebalance"]
    assert opens and quar
    assert rebal and rebal[-1]["avoided"] == [1]


def test_quarantine_all_covers_every_shard():
    sched = _logical(3)
    sched.quarantine_all("health gate: wrong answers")
    assert all(s.breaker.state == "quarantined" for s in sched.shards)
    assert sched.total_trips() == 0
