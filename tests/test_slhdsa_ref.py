"""SLH-DSA pure-Python oracle: self-consistency + structural checks.

Note: with the vendored liboqs binary stripped from the reference checkout
(.MISSING_LARGE_BLOBS), no native SPHINCS+ oracle exists in this environment;
correctness rests on spec-derived structure tests here plus bit-exact
agreement between the two independent implementations (pyref vs JAX) in
test_sphincs.py.
"""

import numpy as np
import pytest

from quantum_resistant_p2p_tpu.pyref import slhdsa_ref as slh

RNG = np.random.default_rng(42)


def _seeds(p):
    s = [bytes(RNG.integers(0, 256, size=p.n, dtype=np.uint8)) for _ in range(3)]
    return s[0], s[1], s[2]


@pytest.mark.parametrize("name", ["SPHINCS+-SHA2-128f-simple"])
def test_sign_verify_roundtrip(name):
    p = slh.PARAMS[name]
    sk_seed, sk_prf, pk_seed = _seeds(p)
    pk, sk = slh.keygen(p, sk_seed, sk_prf, pk_seed)
    assert len(pk) == p.pk_len and len(sk) == p.sk_len
    msg = b"slh-dsa oracle roundtrip"
    sig = slh.sign(p, sk, msg)
    assert len(sig) == p.sig_len
    assert slh.verify(p, pk, msg, sig)
    assert not slh.verify(p, pk, msg + b"!", sig)
    # corrupt each section: randomizer, FORS, HT
    for off in (0, p.n + 5, p.sig_len - 1):
        bad = bytearray(sig)
        bad[off] ^= 0xFF
        assert not slh.verify(p, pk, msg, bytes(bad))


def test_deterministic_and_hedged():
    p = slh.PARAMS["SPHINCS+-SHA2-128f-simple"]
    sk_seed, sk_prf, pk_seed = _seeds(p)
    pk, sk = slh.keygen(p, sk_seed, sk_prf, pk_seed)
    msg = b"determinism"
    assert slh.sign(p, sk, msg) == slh.sign(p, sk, msg)
    hedged = slh.sign(p, sk, msg, addrnd=b"\x01" * p.n)
    assert hedged != slh.sign(p, sk, msg)
    assert slh.verify(p, pk, msg, hedged)


def test_wots_sign_recovers_pk():
    p = slh.PARAMS["SPHINCS+-SHA2-128f-simple"]
    sk_seed, _, pk_seed = _seeds(p)
    adrs = slh.ADRS()
    adrs.set_type_and_clear(slh.WOTS_HASH)
    adrs.w1 = 5
    pk = slh.wots_pkgen(p, sk_seed, pk_seed, adrs.copy())
    msg = bytes(RNG.integers(0, 256, size=p.n, dtype=np.uint8))
    a2 = adrs.copy()
    sig = slh.wots_sign(p, msg, sk_seed, pk_seed, a2)
    a3 = adrs.copy()
    assert slh.wots_pk_from_sig(p, sig, msg, pk_seed, a3) == pk
